#!/usr/bin/env bash
# ThreadSanitizer build + run for the C++ host network path.
#
# Equivalent of the reference's implicit `go test -race` contract
# (SURVEY.md §5 "Race detection"): builds patrol_host.cpp with
# -fsanitize=thread and runs a multi-threaded send/recv/codec driver;
# any TSan report makes the run exit non-zero (halt_on_error=1).
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=$(mktemp -d)
trap 'rm -rf "$OUT"' EXIT

g++ -std=c++17 -O1 -g -fsanitize=thread -fPIC \
    -o "$OUT/tsan_driver" \
    scripts/tsan_driver.cpp patrol_tpu/native/patrol_host.cpp \
    -DPT_NO_MAIN -lpthread

TSAN_OPTIONS="halt_on_error=1" "$OUT/tsan_driver"
echo "TSan: clean"
