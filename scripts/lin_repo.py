#!/usr/bin/env python
"""Run patrol-lin — replication-aware linearizability checking against a
sequential limiter spec (arXiv:2502.19967).

Stage 8 of the `scripts/check.sh` gate, runnable standalone. For every
kernel family registered in patrol_tpu/ops/obligations.py::LIN_SPECS it
enumerates bounded schedules through the SHARED stage-6 enumerator
(patrol_tpu/analysis/protocol.py::enumerate_schedules — takes, delivery,
dup/drop, partition, heal, refill, GC) plus a sync-delivery suite, and
checks every outcome against the sequential spec under explicit per-node
visibility relations:

  PTN001  per-node sequential soundness (each take justified by a
          linearization of the ops visible to it)
  PTN002  global visibility-respecting linearization once converged
          (partition schedules: linearizable up to visibility)
  PTN003  sync-delivery schedules grant EXACTLY what the sequential
          spec grants — full linearizability, no replication slack
  PTN004  refills/GC/cap adoption never manufacture a grant the spec
          refuses under ANY visibility extension
  PTN005  meta: every seeded lin mutation rejected with its exact code,
          every mutation knob exercised (the trust story)

Exit code 0 = every family clean AND every seeded mutation caught;
1 = findings printed one per line as `path:line: CODE message`.

Pure python model (no accelerator); deterministic — a CI failure
replays exactly, and each finding carries its witness schedule.
"""

import argparse
import os
import sys

# The model itself is pure python; obligations.py (the spec registry)
# imports jax, so pin the platform like the other static stages.
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from patrol_tpu.analysis import driver

    repo_root = driver.repo_root_for(__file__)

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--mutation",
        default=None,
        help="run ONE named mutation and print what catches it (debug aid)",
    )
    ap.add_argument(
        "--list",
        action="store_true",
        help="list registered spec families and mutations, then exit",
    )
    args = ap.parse_args()

    from patrol_tpu.analysis import linearizability as lin
    from patrol_tpu.ops.obligations import LIN_SPECS

    if args.list:
        for spec in LIN_SPECS:
            flags = f"wire={spec.wire} algebra={spec.algebra}" + (
                " lifecycle" if spec.lifecycle else ""
            )
            print(f"family   {spec.name}  [{flags}]")
        for name, mut in lin.LIN_MUTATIONS.items():
            print(f"mutation {name}  → {mut.expect} on {mut.family}")
        return 0

    if args.mutation:
        mut = lin.LIN_MUTATIONS.get(args.mutation)
        if mut is None:
            return driver.unknown_name("patrol-lin", "mutation", args.mutation)
        spec = next((s for s in LIN_SPECS if s.name == mut.family), None)
        if spec is None:
            print(f"family not registered: {mut.family}", file=sys.stderr)
            return 2
        explored, findings = lin.check_family(
            spec, mut.laws, stop_at_first=False
        )
        driver.print_findings(findings)
        hit = any(f.check == mut.expect for f in findings)
        return driver.mutation_verdict(
            "patrol-lin",
            args.mutation,
            hit,
            (
                f"REJECTED by {mut.expect} (good)"
                if hit
                else f"NOT caught by {mut.expect} (bad)"
            )
            + f" — {explored} schedules",
        )

    explored, findings = lin.check_repo(LIN_SPECS)
    findings = driver.apply_stage_suppressions(
        findings, repo_root, stale_family="PTN"
    )
    return driver.finish(
        "patrol-lin",
        findings,
        "patrol-lin: clean "
        f"(schedules explored={explored} across {len(LIN_SPECS)} kernel "
        f"families, {len(lin.LIN_MUTATIONS)} seeded mutations all "
        "rejected with their exact codes)",
    )


if __name__ == "__main__":
    sys.exit(main())
