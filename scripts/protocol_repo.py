#!/usr/bin/env python
"""Run patrol-protocol — the bounded replication-protocol model checker.

Stage 6 of the `scripts/check.sh` gate, runnable standalone. Enumerates
bounded cluster schedules (2-3 nodes, bounded takes and fault events)
against the step-for-step protocol model in
patrol_tpu/analysis/protocol.py and machine-checks:

  PTC001  convergence-after-heal (all replicas = join of all state)
  PTC002  monotonicity of replicated state at every step
  PTC003  the AP bound: admitted <= limit x partition_sides
  PTC004  dup/reorder idempotence at ingest
  PTC005  meta: every seeded protocol mutation must be rejected

Exit code 0 = clean protocol passes AND every seeded mutation is caught;
1 = findings printed one per line as `path:line: CODE message`.

Pure python (no jax, no accelerator); deterministic — no randomness, so
a CI failure replays exactly.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from patrol_tpu.analysis import driver

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--mutation",
        default=None,
        help="run ONE named mutation and print what catches it (debug aid)",
    )
    ap.add_argument(
        "--list", action="store_true", help="list registered mutations and exit"
    )
    args = ap.parse_args()

    from patrol_tpu.analysis import protocol

    if args.list:
        for name in protocol.MUTATIONS:
            print(name)
        return 0

    if args.mutation:
        sem = protocol.MUTATIONS.get(args.mutation)
        if sem is None:
            return driver.unknown_name(
                "patrol-protocol", "mutation", args.mutation
            )
        findings = protocol.check_protocol(sem)
        driver.print_findings(findings)
        return driver.mutation_verdict(
            "patrol-protocol",
            args.mutation,
            bool(findings),
            "REJECTED (good)" if findings else "NOT caught (bad)",
        )

    def clean_line() -> str:
        explored, _ = protocol.check_async_schedules()
        return (
            "patrol-protocol: clean "
            f"(async states explored={explored}, "
            f"{len(protocol.MUTATIONS)} seeded mutations all rejected)"
        )

    return driver.finish("patrol-protocol", protocol.check_repo(), clean_line)


if __name__ == "__main__":
    sys.exit(main())
