#!/usr/bin/env python
"""Run patrol-cert — the kernel-certification meta-check over the
declarative ``KernelFamily`` registry (patrol_tpu/ops/obligations.py).

Stage 9 of the `scripts/check.sh` gate, runnable standalone. Walks
every registered lattice family and checks, cross-stage:

  PTK001  every family reaches every applicable checking stage
          (prove / protocol / lin / bench) or carries a written
          exemption justification
  PTK002  every seeded mutation is rejected with its EXACT registered
          code — mutant kernels and family-law payloads are executed
          here; legacy stage-6/8 registry references are membership-
          and expect-checked
  PTK003  every obligation declared absent carries a justification
          string, and none has gone stale
  PTK004  every module-level ``*_jit`` lattice kernel under
          patrol_tpu/ops/ is registered (or PROVE_EXEMPT, with the
          reason on record)
  PTK005  registry integrity: unique names, >= 2 mutations per family,
          resolvable targets, well-formed codes

Exit code 0 = clean; 1 = findings printed one per line as
`path:line: CODE message`. Deterministic; the jax models run on CPU.
"""

import argparse
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from patrol_tpu.analysis import driver

    repo_root = driver.repo_root_for(__file__)

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--list",
        action="store_true",
        help="list registered families and their seeded mutations, then exit",
    )
    ap.add_argument(
        "--mutation",
        default=None,
        help="execute ONE named seeded mutation and print the verdict",
    )
    ap.add_argument(
        "--no-execute",
        action="store_true",
        help="registry/reachability checks only (skip mutation execution)",
    )
    args = ap.parse_args()

    from patrol_tpu.analysis import cert
    from patrol_tpu.ops.obligations import KERNEL_FAMILIES

    if args.list:
        for fam in KERNEL_FAMILIES:
            stages = [
                "prove" if fam.prove_roots else "-",
                f"protocol={fam.protocol}" if fam.protocol else "protocol:exempt",
                "lin" if fam.lin_specs else "lin:exempt",
                "bench" if fam.bench_fields else "bench:exempt",
            ]
            print(f"family   {fam.name}  [{' '.join(stages)}]")
            for mut in fam.mutations:
                kind = "stage-ref" if mut.stage == "lin" else "executed"
                print(
                    f"mutation {mut.name}  → {mut.expect} "
                    f"[{mut.stage}, {kind}]"
                )
            if fam.mutations_exempt:
                print(f"mutation (exempt: {fam.mutations_exempt})")
        return 0

    if args.mutation:
        fam = next(
            (
                f
                for f in KERNEL_FAMILIES
                if any(m.name == args.mutation for m in f.mutations)
            ),
            None,
        )
        if fam is None:
            return driver.unknown_name("patrol-cert", "mutation", args.mutation)
        findings = cert.check_mutations(families=[fam], execute=True)
        mine = [f for f in findings if f"'{args.mutation}'" in f.message]
        hit = not mine
        mut = next(m for m in fam.mutations if m.name == args.mutation)
        detail = (
            f"rejected with {mut.expect} (family {fam.name})"
            if hit
            else f"NOT rejected: {mine[0].message}"
        )
        return driver.mutation_verdict("patrol-cert", args.mutation, hit, detail)

    findings = cert.check_repo(execute_mutations=not args.no_execute)
    findings = driver.apply_stage_suppressions(findings, repo_root, "PTK")

    executed = sum(
        1
        for f in KERNEL_FAMILIES
        for m in f.mutations
        if m.stage != "lin"
    )
    referenced = sum(
        1 for f in KERNEL_FAMILIES for m in f.mutations if m.stage == "lin"
    )
    return driver.finish(
        "patrol-cert",
        findings,
        lambda: (
            f"patrol-cert: clean ({len(KERNEL_FAMILIES)} families, "
            f"{executed} seeded mutations executed + {referenced} "
            "stage-8 references pinned, all rejected with their "
            "exact codes)"
        ),
    )


if __name__ == "__main__":
    sys.exit(main())
