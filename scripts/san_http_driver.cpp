// Sanitizer exercise driver for patrol_http.cpp (the C++ epoll HTTP front).
//
// scripts/tsan_driver.cpp covers the UDP/codec/directory plane; this
// driver covers the OTHER native half — the HTTP front's concurrency
// shape and its hostile-input surface — under TSan, ASan, and UBSan
// (scripts/check.sh builds it three times):
//
//   * the epoll thread serving h1 + native-h2 requests, with in-front
//     host-store takes (hls_take_locked) contending the HostStore mutex
//     against a drain thread (≙ the engine pump's drain_locked) and a
//     probe thread (pt_hls_take_probe);
//   * a pump thread on the ring path: pt_http_poll → complete_takes /
//     complete_other, racing the epoll thread on the Server mutex (and,
//     at shutdown, the registry teardown path);
//   * the load clients pt_http_blast / pt_http_blast_h2 from multiple
//     threads (closed-loop h1 pipelining and h2 multiplexing);
//   * hostile inputs while the load runs: oversized/overflowing
//     Content-Length (the ADVICE r5 smuggling surface), truncated h2
//     frames, CONTINUATION floods, RST_STREAM races against ring
//     completions, oversized DATA bodies, absurd frame lengths, and
//     mid-request aborts.
//
// Any sanitizer report fails the run (halt_on_error / no-recover); the
// driver itself also exits non-zero when the server stops answering.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
int pt_http_start(const char* ip, uint16_t port);
int pt_http_port(int h);
int pt_http_poll(int h, int timeout_ms, uint64_t* tags, int32_t* streams,
                 uint8_t* names, int* name_lens, int64_t* freqs,
                 int64_t* pers, int64_t* counts, int cap_t, uint64_t* otags,
                 int32_t* ostreams, uint8_t* otargets, int* otarget_lens,
                 uint8_t* omethods, int cap_o, int* n_other);
int pt_http_complete_takes(int h, const uint64_t* tags,
                           const int32_t* streams, const int* statuses,
                           const int64_t* remaining, int n);
int pt_http_complete_other(int h, uint64_t tag, int32_t stream, int status,
                           const char* ctype, const uint8_t* body,
                           int body_len);
int pt_http_stats(int h, uint64_t* out8);
int pt_http_stop(int h);
int pt_http_attach_host(int http_h, int hls_h, int dir_h);
int pt_http_blast(const char* ip, uint16_t port, const char* target,
                  int conns, int pipeline, int duration_ms, uint64_t* out5);
int pt_http_blast_h2(const char* ip, uint16_t port, const char* target,
                     int conns, int pipeline, int duration_ms,
                     uint64_t* out5);
int pt_hls_create(int nodes, int64_t node_slot, int64_t promote_takes,
                  int64_t window_ns, int64_t clock_offset_ns,
                  const int64_t* cap_base, const int64_t* created,
                  int64_t* last_used);
int pt_hls_destroy(int h);
int pt_hls_lock(int h);
int pt_hls_unlock(int h);
int64_t pt_hls_host_locked(int h, int32_t row);
int pt_hls_drain_locked(int h, int32_t* dirty_out, int64_t* snap, int cap_d,
                        int32_t* promote_out, int cap_p, int* n_promote);
int pt_hls_stats(int h, uint64_t* out4);
int64_t pt_hls_events(int h);
int pt_hls_take_probe(int hls_h, int dir_h, const uint8_t* name, int len,
                      int64_t freq, int64_t per_ns, int64_t count,
                      int64_t now, int64_t* remaining);
int pt_dir_create(int64_t capacity, const uint8_t* name_bytes,
                  const int32_t* name_lens);
int pt_dir_insert(int h, uint64_t hash, int32_t row);
int pt_dir_destroy(int h);
}

namespace {

constexpr int kPacket = 256;
constexpr int kPathMax = 2048;
constexpr int kCap = 64;     // directory rows
constexpr int kNodes = 4;
constexpr int kHosted = 8;   // rows served in-front
constexpr int kBlastMs = 500;

uint64_t fnv1a(const char* b, int len) {
  uint64_t h = 0xcbf29ce484222325ULL;
  for (int i = 0; i < len; i++) {
    h ^= (uint8_t)b[i];
    h *= 0x100000001b3ULL;
  }
  return h;
}

// ---- raw hostile clients ---------------------------------------------------

int dial(uint16_t port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  inet_pton(AF_INET, "127.0.0.1", &addr.sin_addr);
  if (connect(fd, (sockaddr*)&addr, sizeof(addr)) < 0) {
    ::close(fd);
    return -1;
  }
  timeval tv{0, 200000};  // 200 ms read cap: hostile conns just probe
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

void send_all(int fd, const char* buf, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t wr = ::send(fd, buf + off, n - off, MSG_NOSIGNAL);
    if (wr <= 0) return;  // server killed the conn: expected for floods
    off += (size_t)wr;
  }
}

void drain(int fd) {
  char buf[4096];
  while (recv(fd, buf, sizeof(buf), 0) > 0) {
  }
}

void frame_hdr(std::string& out, size_t len, int type, uint8_t flags,
               int32_t stream) {
  out.push_back((char)((len >> 16) & 0xFF));
  out.push_back((char)((len >> 8) & 0xFF));
  out.push_back((char)(len & 0xFF));
  out.push_back((char)type);
  out.push_back((char)flags);
  out.push_back((char)((stream >> 24) & 0x7F));
  out.push_back((char)((stream >> 16) & 0xFF));
  out.push_back((char)((stream >> 8) & 0xFF));
  out.push_back((char)(stream & 0xFF));
}

const char kPreface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";

void hostile_h1(uint16_t port) {
  // Overflowing 23-digit Content-Length with a smuggled "request" body.
  int fd = dial(port);
  if (fd >= 0) {
    const char req[] =
        "POST /take/ovcl?rate=5:1s HTTP/1.1\r\nHost: x\r\n"
        "Content-Length: 99999999999999999999999\r\n\r\n"
        "GET /smuggled HTTP/1.1\r\nHost: x\r\n\r\n";
    send_all(fd, req, sizeof(req) - 1);
    drain(fd);
    ::close(fd);
  }
  // Oversized-but-parseable Content-Length (over the sane bound).
  fd = dial(port);
  if (fd >= 0) {
    const char req[] =
        "POST /take/big?rate=5:1s HTTP/1.1\r\nHost: x\r\n"
        "Content-Length: 2147483648\r\n\r\n";
    send_all(fd, req, sizeof(req) - 1);
    drain(fd);
    ::close(fd);
  }
  // Garbage request line, then abort mid-header on a fresh conn.
  fd = dial(port);
  if (fd >= 0) {
    send_all(fd, "NOT-HTTP\r\n\r\n", 12);
    drain(fd);
    ::close(fd);
  }
  fd = dial(port);
  if (fd >= 0) {
    send_all(fd, "POST /take/abort?rate=", 22);
    ::close(fd);  // mid-request abort: slot reap path
  }
  // Header flood past the rbuf cap (431 + close).
  fd = dial(port);
  if (fd >= 0) {
    std::string req = "GET / HTTP/1.1\r\n";
    while (req.size() < 20000) req += "X-Pad: aaaaaaaaaaaaaaaaaaaaaaaa\r\n";
    send_all(fd, req.data(), req.size());
    drain(fd);
    ::close(fd);
  }
  // Legit body drain (valid Content-Length + pipelined next request).
  fd = dial(port);
  if (fd >= 0) {
    std::string body(70000, 'z');
    std::string req = "POST /take/bd?rate=5:1s HTTP/1.1\r\nHost: x\r\n"
                      "Content-Length: " + std::to_string(body.size()) +
                      "\r\n\r\n" + body;
    req += "POST /take/bd?rate=5:1s HTTP/1.1\r\nHost: x\r\n\r\n";
    send_all(fd, req.data(), req.size());
    drain(fd);
    ::close(fd);
  }
}

void hostile_h2(uint16_t port) {
  // Truncated frame: header claims 1000 bytes, 4 arrive, then close.
  int fd = dial(port);
  if (fd >= 0) {
    std::string req(kPreface, sizeof(kPreface) - 1);
    frame_hdr(req, 0, 0x4, 0, 0);  // SETTINGS
    frame_hdr(req, 1000, 0x0, 0, 1);
    req += "xxxx";
    send_all(fd, req.data(), req.size());
    drain(fd);
    ::close(fd);
  }
  // CONTINUATION flood: header block grows past the 64 KiB bound.
  fd = dial(port);
  if (fd >= 0) {
    std::string req(kPreface, sizeof(kPreface) - 1);
    frame_hdr(req, 0, 0x4, 0, 0);
    std::string junk(16000, 'h');
    frame_hdr(req, junk.size(), 0x1, 0, 1);  // HEADERS, no END_HEADERS
    req += junk;
    for (int i = 0; i < 8; i++) {  // 128 KB of CONTINUATION
      frame_hdr(req, junk.size(), 0x9, 0, 1);
      req += junk;
    }
    send_all(fd, req.data(), req.size());
    drain(fd);
    ::close(fd);
  }
  // RST_STREAM races: reset a never-opened stream, reset after END_STREAM
  // HEADERS (ring completion must be dropped), zero-len PING, absurd frame.
  fd = dial(port);
  if (fd >= 0) {
    std::string req(kPreface, sizeof(kPreface) - 1);
    frame_hdr(req, 0, 0x4, 0, 0);
    frame_hdr(req, 4, 0x3, 0, 7);  // RST of an idle stream
    req.append("\0\0\0\x8", 4);
    frame_hdr(req, 3, 0x6, 0, 0);  // PING with wrong length (ignored)
    req += "abc";
    send_all(fd, req.data(), req.size());
    drain(fd);
    ::close(fd);
  }
  fd = dial(port);
  if (fd >= 0) {
    std::string req(kPreface, sizeof(kPreface) - 1);
    frame_hdr(req, (size_t)2 << 20, 0x0, 0, 1);  // absurd len: conn killed
    req += "zz";
    send_all(fd, req.data(), req.size());
    drain(fd);
    ::close(fd);
  }
  // Oversized DATA body on one stream (per-stream window credit path):
  // HEADERS without END_STREAM needs a real HPACK block, which the
  // driver cannot build without a deflater — send DATA on an unopened
  // stream instead (server tolerates and credits windows).
  fd = dial(port);
  if (fd >= 0) {
    std::string req(kPreface, sizeof(kPreface) - 1);
    frame_hdr(req, 0, 0x4, 0, 0);
    std::string body(16000, 'b');
    for (int i = 0; i < 6; i++) {  // ~96 KiB > both windows' hysteresis
      frame_hdr(req, body.size(), 0x0, 0, 1);
      req += body;
    }
    send_all(fd, req.data(), req.size());
    drain(fd);
    ::close(fd);
  }
}

}  // namespace

int main() {
  int hs = pt_http_start("127.0.0.1", 0);
  if (hs < 0) {
    fprintf(stderr, "pt_http_start failed: %d\n", hs);
    return 1;
  }
  uint16_t port = (uint16_t)pt_http_port(hs);

  // Directory + host store: rows 0..kHosted-1 are in-front residents.
  std::vector<uint8_t> name_bytes((size_t)kCap * kPacket, 0);
  std::vector<int32_t> name_lens(kCap, 0);
  int dir = pt_dir_create(kCap, name_bytes.data(), name_lens.data());
  std::string targets_h1, targets_h2;
  for (int r = 0; r < kHosted; r++) {
    char nm[32];
    int n = snprintf(nm, sizeof nm, "hot-%d", r);
    memcpy(&name_bytes[(size_t)r * kPacket], nm, n);
    name_lens[r] = n;
    pt_dir_insert(dir, fnv1a(nm, n), r);
    ((r % 2) ? targets_h2 : targets_h1) +=
        "/take/" + std::string(nm) + "?rate=1000:1s\n";
  }
  // Ring-path names (unknown to the directory).
  targets_h1 += "/take/ring-a?rate=100:1s\n/metrics\n";
  targets_h2 += "/take/ring-b?rate=100:1s\n";

  std::vector<int64_t> cap_base(kCap, 0), created(kCap, 0), last_used(kCap, 0);
  int hls = pt_hls_create(kNodes, 0, /*promote_takes=*/64,
                          100 * 1000 * 1000LL, 0, cap_base.data(),
                          created.data(), last_used.data());
  pt_hls_lock(hls);
  for (int r = 0; r < kHosted; r++) pt_hls_host_locked(hls, r);
  pt_hls_unlock(hls);
  pt_http_attach_host(hs, hls, dir);

  std::atomic<bool> stop{false};

  // Ring pump (≙ net/native_http.py _pump + _completer, minus Python).
  std::thread pump([&] {
    constexpr int CT = 256, CO = 64;
    std::vector<uint64_t> tags(CT), otags(CO);
    std::vector<int32_t> streams(CT), ostreams(CO);
    std::vector<uint8_t> names((size_t)CT * kPacket),
        otargets((size_t)CO * kPathMax), omethods((size_t)CO * 8);
    std::vector<int> nlens(CT), otlens(CO), statuses(CT);
    std::vector<int64_t> freqs(CT), pers(CT), counts(CT), remaining(CT);
    while (!stop.load()) {
      int n_other = 0;
      int nt = pt_http_poll(hs, 10, tags.data(), streams.data(),
                            names.data(), nlens.data(), freqs.data(),
                            pers.data(), counts.data(), CT, otags.data(),
                            ostreams.data(), otargets.data(), otlens.data(),
                            omethods.data(), CO, &n_other);
      if (nt < 0) return;
      for (int i = 0; i < nt; i++) {
        statuses[i] = (freqs[i] > 0) ? 200 : 429;
        remaining[i] = freqs[i] > 0 ? freqs[i] - 1 : 0;
      }
      if (nt > 0)
        pt_http_complete_takes(hs, tags.data(), streams.data(),
                               statuses.data(), remaining.data(), nt);
      for (int j = 0; j < n_other; j++) {
        const char body[] = "ok\n";
        pt_http_complete_other(hs, otags[j], ostreams[j], 200, "text/plain",
                               (const uint8_t*)body, 3);
      }
    }
  });

  // Drain thread (≙ engine drain_native_broadcasts under _host_mu).
  std::thread drainer([&] {
    std::vector<int32_t> dirty(256), prom(64);
    std::vector<int64_t> snap((size_t)256 * (2 * kNodes + 1));
    uint64_t out4[4];
    while (!stop.load()) {
      int np = 0;
      pt_hls_lock(hls);
      pt_hls_drain_locked(hls, dirty.data(), snap.data(), 256, prom.data(),
                          64, &np);
      pt_hls_unlock(hls);
      pt_hls_stats(hls, out4);
      pt_hls_events(hls);
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  });

  // Probe thread: the exact in-front take path from a second thread.
  std::thread prober([&] {
    int64_t rem = 0, now = 1;
    while (!stop.load()) {
      pt_hls_take_probe(hls, dir, (const uint8_t*)"hot-0", 5, 1000,
                        1000000000LL, 1, now, &rem);
      now += 1000000;
      std::this_thread::sleep_for(std::chrono::microseconds(200));
    }
  });

  // Hostile clients interleave with the load below.
  std::thread hostiles([&] {
    while (!stop.load()) {
      hostile_h1(port);
      hostile_h2(port);
    }
  });

  uint64_t out5[5];
  std::thread blast2([&] {
    uint64_t o[5];
    pt_http_blast("127.0.0.1", port, targets_h1.c_str(), 2, 4, kBlastMs, o);
  });
  int rc1 = pt_http_blast("127.0.0.1", port, targets_h1.c_str(), 2, 4,
                          kBlastMs, out5);
  blast2.join();
  uint64_t done_h1 = out5[0];
  int rc2 = pt_http_blast_h2("127.0.0.1", port, targets_h2.c_str(), 4, 4,
                             kBlastMs, out5);
  uint64_t done_h2 = out5[0];

  stop.store(true);
  hostiles.join();
  prober.join();
  drainer.join();
  pump.join();

  uint64_t stats[8];
  pt_http_stats(hs, stats);
  pt_http_attach_host(hs, -1, -1);
  pt_http_stop(hs);
  pt_hls_destroy(hls);
  pt_dir_destroy(dir);

  if (rc1 != 0 || rc2 != 0 || done_h1 == 0 || done_h2 == 0) {
    fprintf(stderr,
            "driver failed: rc1=%d rc2=%d h1=%llu h2=%llu\n", rc1, rc2,
            (unsigned long long)done_h1, (unsigned long long)done_h2);
    return 1;
  }
  printf("san http driver ok: h1=%llu h2=%llu requests=%llu accepted=%llu\n",
         (unsigned long long)done_h1, (unsigned long long)done_h2,
         (unsigned long long)stats[1], (unsigned long long)stats[0]);
  return 0;
}
