#!/usr/bin/env python
"""Run the patrol-check AST lint over the repo's Python sources.

Part of the `scripts/check.sh` gate (and runnable standalone). Exit code
0 = zero findings; 1 = findings printed one per line as

    path:line: CODE message

See patrol_tpu/analysis/lint.py for the checks and README.md for the
suppression format.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from patrol_tpu.analysis import lint  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--root",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="repo root (default: this script's parent)",
    )
    args = ap.parse_args()
    findings = lint.lint_repo(args.root)
    for f in findings:
        print(f)
    if findings:
        print(
            f"patrol-lint: {len(findings)} finding(s) across "
            f"{len({f.path for f in findings})} file(s)",
            file=sys.stderr,
        )
        return 1
    print("patrol-lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
