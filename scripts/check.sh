#!/usr/bin/env bash
# patrol-check: the repo-wide static-analysis + sanitizer + prover gate.
#
# One command, one pass/fail exit code, ten stages (plus one opt-in):
#
#   lint    — repo-specific AST checks over patrol_tpu/ (clock seams,
#             jit-reachable sync primitives, lock order, nanotoken dtype
#             discipline; patrol_tpu/analysis/lint.py) plus their
#             fixture-driven self-tests (pytest -m lint).
#   tidy    — clang-tidy with the curated native profile (.clang-tidy)
#             over patrol_tpu/native/. Skipped with a notice when
#             clang-tidy is not installed (the container images don't
#             ship LLVM).
#   san     — TSan, ASan (+LSan), and UBSan builds of BOTH multi-threaded
#             drivers: scripts/tsan_driver.cpp (UDP/codec/directory plane)
#             and scripts/san_http_driver.cpp (epoll front, h1 parser, h2
#             frame machine, hls_take_locked, HostStore mutex, hostile
#             inputs). Any sanitizer report fails the run.
#   prove   — patrol-prove: the jaxpr-level CRDT invariant prover
#             (patrol_tpu/analysis/prove.py, scripts/prove_repo.py): the
#             structural lattice check + exhaustive small-domain model
#             check over every registered kernel root, plus the
#             pytest -m prove fixture self-tests.
#   abi     — patrol-abi: the native-ABI conformance prover + cross-
#             boundary concurrency lint (patrol_tpu/analysis/abi.py,
#             scripts/abi_repo.py): pt_fold_hybrid / pt_rx_classify
#             driven through ctypes over the prove lattice domains and
#             checked bit-exact against the registered jax kernel roots
#             (incl. the merge laws on the native side), the host-lane
#             store schedule explorer, and the NATIVE_EFFECTS
#             completeness check; plus the pytest -m abi self-tests.
#             Skips LOUDLY (exit 77) when libpatrolhost cannot build.
#   protocol— patrol-protocol: the bounded replication-protocol model
#             checker (patrol_tpu/analysis/protocol.py,
#             scripts/protocol_repo.py): enumerates bounded 2-3 node
#             cluster schedules (takes × drop/dup/reorder/partition/heal)
#             against a step-for-step protocol model and machine-checks
#             convergence-after-heal, state monotonicity, the AP bound
#             admitted <= limit × partition_sides, and dup/reorder
#             idempotence (PTC001-004) — with seeded protocol mutations
#             (e.g. resync-overwrites-instead-of-joins) demonstrably
#             rejected (PTC005); plus the pytest -m protocol self-tests.
#             Pure python, never skips.
#   race    — patrol-race: the cross-seam concurrency prover + guarded-
#             state static analysis (patrol_tpu/analysis/race.py,
#             scripts/race_repo.py): exhaustive deterministic
#             interleavings of the epoll-seam protocol model
#             (pt_http_poll park/drain, completion-ring (slot, gen)
#             tags) checking lost wakeups and completion-ring token
#             conservation (PTR001-002, 3 seeded mutations rejected),
#             plus the guarded-state / lock-graph / condvar-predicate /
#             buffer-ownership AST passes over the engine/net thread
#             ensemble (PTR003-005); and the pytest -m race self-tests.
#             Pure python, never skips.
#   lin     — patrol-lin: replication-aware linearizability checking
#             against a sequential token-bucket spec
#             (patrol_tpu/analysis/linearizability.py,
#             scripts/lin_repo.py): every kernel family registered in
#             ops/obligations.py::LIN_SPECS is run through the SHARED
#             stage-6 schedule enumerator plus a sync-delivery suite,
#             with every take checked for justification under explicit
#             per-node visibility (PTN001-004: per-node soundness,
#             visibility-respecting linearization, sync-schedule
#             exactness, no manufactured grants) and seeded lin
#             mutations demonstrably rejected with their exact codes
#             (PTN005); plus the pytest -m lin self-tests.
#             Pure python, never skips.
#   cert    — patrol-cert: the kernel-certification meta-check
#             (patrol_tpu/analysis/cert.py, scripts/cert_repo.py) over
#             the declarative KernelFamily registry
#             (ops/obligations.py::KERNEL_FAMILIES): every lattice
#             family reachable by every applicable stage — prove /
#             protocol / lin / bench — or justified-exempt (PTK001),
#             every seeded mutation demonstrably rejected with its
#             exact code, mutant kernels and family-law payloads
#             executed in-process (PTK002), every declared-absent
#             obligation justified (PTK003), every module-level *_jit
#             kernel under ops/ registered (PTK004), and registry
#             integrity (PTK005); plus the pytest -m cert self-tests.
#             CPU-pinned jax models, never skips.
#   dispatch— patrol-dispatch: the dispatch-discipline prover +
#             compile-cache stability witness
#             (patrol_tpu/analysis/dispatch.py, scripts/dispatch_repo.py)
#             over the declared DispatchSpec registry
#             (ops/obligations.py::DISPATCH_SPECS): retrace-risk AST
#             dataflow at the engine jit call sites incl. shape-bucket
#             law drift (PTD001), donation discipline incl.
#             use-after-donate (PTD002), implicit host transfers on the
#             serve graph (PTD003), a deterministic witness that warms
#             every registered hot path then re-drives it at identical
#             shapes under a compile counter + the jax device-to-host
#             transfer guard (PTD004), and witness completeness over
#             every engine-dispatched jitted kernel (PTD005) — with
#             seeded mutations demonstrably rejected with their exact
#             codes; plus the pytest -m dispatch self-tests.
#             CPU-pinned jax, never skips.
#   asan-py — OPT-IN (never in the default set; select explicitly with
#             --stage): the ctypes-facing pytest subset under
#             LD_PRELOAD=libasan with an ASan-instrumented
#             libpatrolhost.so (PATROL_NATIVE_LIB), leak-checking
#             callback lifetimes and numpy buffer ownership across
#             pt_http_poll. Skips with a notice when the toolchain lacks
#             a preloadable libasan.
#
# Stage selection:   check.sh --stage lint,prove     # <10 s fast path
#                    check.sh --stage asan-py        # the opt-in seam check
# The final line is machine-readable so an outer CI can assert that no
# stage silently skipped (scripts/ci_gate.sh does exactly that):
#                    PATROL_CHECK stages=10 pass=9 skip=1 fail=0 skipped=tidy failed=-
#
# Prereqs and the lint/prove suppression format are documented in
# README.md ("patrol-check").
set -euo pipefail
cd "$(dirname "$0")/.."

DEFAULT_STAGES="lint,tidy,san,prove,abi,protocol,race,lin,cert,dispatch"
STAGES="$DEFAULT_STAGES"
while [[ $# -gt 0 ]]; do
  case "$1" in
    --stage|--stages) STAGES="$2"; shift 2 ;;
    --stage=*|--stages=*) STAGES="${1#*=}"; shift ;;
    -h|--help)
      sed -n '2,99p' "$0" | sed 's/^# \{0,1\}//'
      exit 0 ;;
    *) echo "unknown argument: $1 (try --stage lint,tidy,san,prove,abi,protocol,race,lin,cert,dispatch,asan-py)" >&2
       exit 2 ;;
  esac
done
[[ "$STAGES" == "all" ]] && STAGES="$DEFAULT_STAGES"

have_pytest() { python -c "import pytest" >/dev/null 2>&1; }

# ---------------------------------------------------------------------------
# Toolchain probe (carried hygiene item): gcc-10's libtsan cannot
# intercept pthread_cond_clockwait, which forced the
# wait_until(system_clock) workaround into pt_http_poll, and its ASan
# CHECK-fails on jaxlib's static __cxa_throw, degrading asan-py to the
# non-jit subset. gcc >= 12 (or clang >= 14 as the sanitizer compiler)
# fixes both: the san stage then builds with -DPT_STEADY_CV_WAIT, which
# reverts pt_http_poll to the steady-clock cv wait_for, and the asan-py
# jax probe comes back full. One notice line either way so the state of
# the workaround is never silent.
GXX_MAJOR=$(g++ -dumpversion 2>/dev/null | cut -d. -f1 || echo 0)
CLANG_MAJOR=$(clang --version 2>/dev/null | grep -oE 'version [0-9]+' | grep -oE '[0-9]+' | head -1 || true)
SAN_CV_FLAGS=""
if [[ "${GXX_MAJOR:-0}" -ge 12 || "${CLANG_MAJOR:-0}" -ge 14 ]]; then
  SAN_CV_FLAGS="-DPT_STEADY_CV_WAIT"
  echo "patrol-check: toolchain probe: g++ ${GXX_MAJOR:-?} / clang ${CLANG_MAJOR:--} — modern sanitizers:" \
       "reverting the wait_until(system_clock) TSan workaround (steady-clock cv wait)" \
       "and expecting the full asan-py jit subset"
else
  echo "patrol-check: toolchain probe: g++ ${GXX_MAJOR:-?} / clang ${CLANG_MAJOR:--} — pre-12/14 sanitizers:" \
       "keeping the wait_until(system_clock) TSan workaround; asan-py degrades to the" \
       "non-jit subset (see ROADMAP toolchain-blocked hygiene)"
fi

# Each stage runs in a subshell with its own `set -e`; exit 77 = skipped.

stage_lint() (
  set -euo pipefail
  echo "== patrol-check [lint] AST lint over patrol_tpu/ =="
  python scripts/lint_repo.py
  if have_pytest; then
    env JAX_PLATFORMS=cpu python -m pytest tests/test_lint.py -q -m lint \
      -p no:cacheprovider
  else
    echo "pytest unavailable: lint self-tests skipped (lint itself ran)"
  fi
)

stage_tidy() (
  set -euo pipefail
  echo "== patrol-check [tidy] clang-tidy (patrol_tpu/native/) =="
  if ! command -v clang-tidy >/dev/null 2>&1; then
    echo "clang-tidy not installed: SKIPPED (needs LLVM >= 14; see README.md)"
    exit 77
  fi
  clang-tidy --version | head -2
  clang-tidy \
    patrol_tpu/native/patrol_host.cpp \
    patrol_tpu/native/patrol_http.cpp \
    -- -std=c++17 -x c++ -DPT_NO_MAIN
  echo "clang-tidy: clean"
)

stage_san() (
  set -euo pipefail
  echo "== patrol-check [san] sanitizer drivers =="
  OUT=$(mktemp -d)
  trap 'rm -rf "$OUT"' EXIT

  build_and_run() {
    local san="$1" driver="$2" extra="" runenv=""
    case "$san" in
      thread)    extra="";                         runenv="TSAN_OPTIONS=halt_on_error=1" ;;
      address)   extra="";                         runenv="ASAN_OPTIONS=halt_on_error=1:detect_leaks=1" ;;
      undefined) extra="-fno-sanitize-recover=all" runenv="UBSAN_OPTIONS=halt_on_error=1:print_stacktrace=1" ;;
    esac
    local srcs bin="$OUT/${driver}_${san}"
    case "$driver" in
      host) srcs="scripts/tsan_driver.cpp patrol_tpu/native/patrol_host.cpp" ;;
      http) srcs="scripts/san_http_driver.cpp patrol_tpu/native/patrol_host.cpp patrol_tpu/native/patrol_http.cpp" ;;
    esac
    echo "-- $driver driver / $san --"
    # SAN_CV_FLAGS (toolchain probe above) reverts the TSan condvar
    # workaround on toolchains whose libtsan intercepts clockwait.
    # shellcheck disable=SC2086
    g++ -std=c++17 -O1 -g -fsanitize="$san" $extra $SAN_CV_FLAGS -fPIC -o "$bin" \
        $srcs -DPT_NO_MAIN -lpthread -ldl
    env "$runenv" "$bin"
  }

  for san in thread address undefined; do
    build_and_run "$san" host
    build_and_run "$san" http
  done
)

stage_prove() (
  set -euo pipefail
  echo "== patrol-check [prove] jaxpr CRDT invariant prover =="
  python scripts/prove_repo.py
  if have_pytest; then
    env JAX_PLATFORMS=cpu python -m pytest tests/test_prove.py -q -m prove \
      -p no:cacheprovider
  else
    echo "pytest unavailable: prove self-tests skipped (prover itself ran)"
  fi
)

stage_abi() (
  set -euo pipefail
  echo "== patrol-check [abi] native-ABI conformance prover =="
  # abi_repo.py exits 77 itself when libpatrolhost cannot load — the
  # stage skips LOUDLY instead of vacuously passing.
  python scripts/abi_repo.py
  if have_pytest; then
    env JAX_PLATFORMS=cpu python -m pytest tests/test_abi.py -q -m abi \
      -p no:cacheprovider
  else
    echo "pytest unavailable: abi self-tests skipped (prover itself ran)"
  fi
)

stage_protocol() (
  set -euo pipefail
  echo "== patrol-check [protocol] bounded replication-protocol model checker =="
  python scripts/protocol_repo.py
  if have_pytest; then
    env JAX_PLATFORMS=cpu python -m pytest tests/test_protocol.py -q -m protocol \
      -p no:cacheprovider
  else
    echo "pytest unavailable: protocol self-tests skipped (checker itself ran)"
  fi
)

stage_race() (
  set -euo pipefail
  echo "== patrol-check [race] cross-seam concurrency prover + guarded-state analysis =="
  python scripts/race_repo.py
  if have_pytest; then
    env JAX_PLATFORMS=cpu python -m pytest tests/test_race.py -q -m race \
      -p no:cacheprovider
  else
    echo "pytest unavailable: race self-tests skipped (prover itself ran)"
  fi
)

stage_lin() (
  set -euo pipefail
  echo "== patrol-check [lin] replication-aware linearizability checker =="
  python scripts/lin_repo.py
  if have_pytest; then
    env JAX_PLATFORMS=cpu python -m pytest tests/test_lin.py -q -m lin \
      -p no:cacheprovider
  else
    echo "pytest unavailable: lin self-tests skipped (checker itself ran)"
  fi
)

stage_cert() (
  set -euo pipefail
  echo "== patrol-check [cert] kernel-certification meta-check =="
  python scripts/cert_repo.py
  if have_pytest; then
    env JAX_PLATFORMS=cpu python -m pytest tests/test_cert.py -q -m cert \
      -p no:cacheprovider
  else
    echo "pytest unavailable: cert self-tests skipped (meta-check itself ran)"
  fi
)

stage_dispatch() (
  set -euo pipefail
  echo "== patrol-check [dispatch] dispatch-discipline prover + compile-cache witness =="
  python scripts/dispatch_repo.py
  if have_pytest; then
    env JAX_PLATFORMS=cpu python -m pytest tests/test_dispatch.py -q -m dispatch \
      -p no:cacheprovider
  else
    echo "pytest unavailable: dispatch self-tests skipped (prover itself ran)"
  fi
)

stage_asan_py() (
  set -euo pipefail
  echo "== patrol-check [asan-py] ctypes seam under LD_PRELOAD=libasan =="
  local_asan=$(gcc -print-file-name=libasan.so 2>/dev/null || true)
  if [[ "$local_asan" != /* || ! -e "$local_asan" ]]; then
    echo "no preloadable libasan.so (gcc -print-file-name): SKIPPED"
    exit 77
  fi
  if ! have_pytest; then
    echo "pytest unavailable: SKIPPED"
    exit 77
  fi
  OUT=$(mktemp -d)
  trap 'rm -rf "$OUT"' EXIT
  echo "-- building ASan-instrumented libpatrolhost --"
  g++ -std=c++17 -O1 -g -shared -fPIC -fsanitize=address -pthread \
      -o "$OUT/libpatrolhost_asan.so" \
      patrol_tpu/native/patrol_host.cpp patrol_tpu/native/patrol_http.cpp
  # malloc_context_size keeps native allocation stacks within native
  # frames, so the interpreter-side LSan suppressions cannot mask a real
  # native leak (scripts/lsan_python.supp).
  ASAN_PY_ENV=(
    LD_PRELOAD="$local_asan"
    PATROL_NATIVE_LIB="$OUT/libpatrolhost_asan.so"
    ASAN_OPTIONS="halt_on_error=1:detect_leaks=1:malloc_context_size=5:detect_odr_violation=0"
    LSAN_OPTIONS="suppressions=scripts/lsan_python.supp:print_suppressions=0"
    JAX_PLATFORMS=cpu
  )
  # gcc-10's ASan CHECK-fails on __cxa_throw from jaxlib's statically
  # linked MLIR bindings, killing any test that TRACES jax under the
  # preload. Probe once; on a broken toolchain run the non-jit ctypes
  # seam (codec/socket/directory) and say exactly what was dropped.
  SUBSET=(tests/test_native.py tests/test_native_http.py tests/test_native_hls.py)
  DESELECT=()
  if ! env "${ASAN_PY_ENV[@]}" ASAN_OPTIONS="detect_leaks=0" \
      python -c "import jax; jax.jit(lambda x: x + 1)(1)" >/dev/null 2>&1; then
    echo "NOTICE: this toolchain's ASan cannot host jax tracing" \
         "(gcc-10 __cxa_throw interceptor vs jaxlib's static libstdc++);"
    echo "NOTICE: running the non-jit ctypes seam only (tests/test_native.py" \
         "codec/socket/directory, minus the engine-driven TestRxDedup);" \
         "the pt_http_poll seam needs gcc >= 12 / llvm asan."
    SUBSET=(tests/test_native.py)
    DESELECT=(-k "not TestRxDedup")
  fi
  env "${ASAN_PY_ENV[@]}" \
      python -m pytest "${SUBSET[@]}" ${DESELECT[@]+"${DESELECT[@]}"} \
        -q -p no:cacheprovider
)

PASS=() ; SKIP=() ; FAIL=()
run_stage() {
  local name="$1" fn="$2" rc=0
  "$fn" || rc=$?
  case "$rc" in
    0)  PASS+=("$name") ;;
    77) SKIP+=("$name") ;;
    *)  FAIL+=("$name"); echo "patrol-check: stage '$name' FAILED (rc=$rc)" >&2 ;;
  esac
}

IFS=',' read -r -a SELECTED <<<"$STAGES"
for s in "${SELECTED[@]}"; do
  case "$s" in
    lint|tidy|san|prove|abi|protocol|race|lin|cert|dispatch|asan-py) ;;
    *) echo "unknown stage: '$s' (valid: lint tidy san prove abi protocol race lin cert dispatch asan-py)" >&2; exit 2 ;;
  esac
done
for s in lint tidy san prove abi protocol race lin cert dispatch asan-py; do
  for sel in "${SELECTED[@]}"; do
    if [[ "$sel" == "$s" ]]; then
      case "$s" in
        lint)    run_stage lint    stage_lint ;;
        tidy)    run_stage tidy    stage_tidy ;;
        san)     run_stage san     stage_san ;;
        prove)   run_stage prove   stage_prove ;;
        abi)     run_stage abi     stage_abi ;;
        protocol) run_stage protocol stage_protocol ;;
        race)    run_stage race    stage_race ;;
        lin)     run_stage lin     stage_lin ;;
        cert)    run_stage cert    stage_cert ;;
        dispatch) run_stage dispatch stage_dispatch ;;
        asan-py) run_stage asan-py stage_asan_py ;;
      esac
    fi
  done
done

total=$(( ${#PASS[@]} + ${#SKIP[@]} + ${#FAIL[@]} ))
join() { local IFS=','; [[ $# -gt 0 ]] && echo "$*" || echo "-"; }
echo "PATROL_CHECK stages=$total pass=${#PASS[@]} skip=${#SKIP[@]} fail=${#FAIL[@]} skipped=$(join ${SKIP[@]+"${SKIP[@]}"}) failed=$(join ${FAIL[@]+"${FAIL[@]}"})"
if [[ ${#FAIL[@]} -gt 0 ]]; then
  exit 1
fi
echo "patrol-check: ALL CLEAN"
