#!/usr/bin/env bash
# patrol-check: the repo-wide static-analysis + sanitizer gate (ISSUE 2).
#
# One command, one pass/fail exit code, three stages:
#
#   1. patrol-lint  — repo-specific AST checks over patrol_tpu/ (clock
#      seams, jit-reachable sync primitives, lock order, nanotoken dtype
#      discipline; patrol_tpu/analysis/lint.py) plus their fixture-driven
#      self-tests (pytest -m lint — the same slice tier-1 runs).
#   2. clang-tidy   — curated native profile (.clang-tidy) over
#      patrol_tpu/native/. Skipped with a notice when clang-tidy is not
#      installed (the container images don't ship LLVM); the sanitizer
#      drivers below stay the enforced native gate either way.
#   3. sanitizers   — TSan, ASan (+LSan), and UBSan builds of BOTH
#      multi-threaded drivers: scripts/tsan_driver.cpp (UDP/codec/
#      directory plane of patrol_host.cpp) and scripts/san_http_driver.cpp
#      (epoll front, h1 parser, h2 frame machine, hls_take_locked and the
#      HostStore mutex, hostile inputs). Any sanitizer report fails the
#      run (halt_on_error / -fno-sanitize-recover).
#
# Prereqs and the lint suppression format are documented in README.md
# ("patrol-check"). Total runtime is dominated by stage 3 (~6 builds +
# ~2 s of load each).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== patrol-check [1/3] AST lint over patrol_tpu/ =="
python scripts/lint_repo.py
if python -c "import pytest" >/dev/null 2>&1; then
  python -m pytest tests/test_lint.py -q -m lint -p no:cacheprovider
else
  echo "pytest unavailable: lint self-tests skipped (lint itself ran)"
fi

echo "== patrol-check [2/3] clang-tidy (patrol_tpu/native/) =="
if command -v clang-tidy >/dev/null 2>&1; then
  clang-tidy --version | head -2
  clang-tidy \
    patrol_tpu/native/patrol_host.cpp \
    patrol_tpu/native/patrol_http.cpp \
    -- -std=c++17 -x c++ -DPT_NO_MAIN
  echo "clang-tidy: clean"
else
  echo "clang-tidy not installed: SKIPPED (needs LLVM >= 14; see README.md)"
fi

echo "== patrol-check [3/3] sanitizer drivers =="
OUT=$(mktemp -d)
trap 'rm -rf "$OUT"' EXIT

build_and_run() {
  local san="$1" driver="$2" extra="" runenv=""
  case "$san" in
    thread)    extra="";                         runenv="TSAN_OPTIONS=halt_on_error=1" ;;
    address)   extra="";                         runenv="ASAN_OPTIONS=halt_on_error=1:detect_leaks=1" ;;
    undefined) extra="-fno-sanitize-recover=all" runenv="UBSAN_OPTIONS=halt_on_error=1:print_stacktrace=1" ;;
  esac
  local srcs bin="$OUT/${driver}_${san}"
  case "$driver" in
    host) srcs="scripts/tsan_driver.cpp patrol_tpu/native/patrol_host.cpp" ;;
    http) srcs="scripts/san_http_driver.cpp patrol_tpu/native/patrol_host.cpp patrol_tpu/native/patrol_http.cpp" ;;
  esac
  echo "-- $driver driver / $san --"
  # shellcheck disable=SC2086
  g++ -std=c++17 -O1 -g -fsanitize="$san" $extra -fPIC -o "$bin" \
      $srcs -DPT_NO_MAIN -lpthread -ldl
  env "$runenv" "$bin"
}

for san in thread address undefined; do
  build_and_run "$san" host
  build_and_run "$san" http
done

echo "patrol-check: ALL CLEAN"
