#!/usr/bin/env python
"""patrol-fleet perf-regression sentinel (``BENCH_TREND``).

The BENCH_r* receipts were write-only: every round pinned numbers into
the repo, and nothing ever compared the next run against them — a
regression shipped silently as a slightly different JSON line. This
gate turns the seconds-class CI smokes (``bench.py --smoke`` /
``--wire-smoke`` / ``--chaos-smoke`` / ``--churn-smoke``) into a *trend*:

* ``benchmarks/TREND_BASELINE.json`` pins the receipt fields (seeded
  from the BENCH_r05-era gates on this container class; re-pin by
  running ``bench.py --trend --pin``);
* this script compares a current run's merged fields against the
  baseline with **noise-aware thresholds** — each numeric gate carries a
  direction (higher-/lower-is-better), a relative tolerance sized to
  the field's observed run-to-run noise on shared CI, and an absolute
  floor below which a delta is never a regression;
* boolean gates (bit-exactness, convergence, cross-mode fixpoint) and
  the device-stage non-emptiness are hard: any flip is a regression;
* the verdict prints as one machine-greppable line
  (``BENCH_TREND verdict=... regressions=N checked=M``) and the exit
  code is nonzero on regression — CI pins the verdict line.

Usage::

    python scripts/bench_gate.py --baseline benchmarks/TREND_BASELINE.json \
        smoke.json wire.json chaos.json

Multiple current files merge (later files win on key collisions);
``bench.py --trend`` runs the three smokes itself and calls
:func:`check_trend` in-process.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Tuple

# Numeric gates: direction, relative tolerance (fraction of baseline the
# current value may regress by before it counts), and an absolute floor
# (deltas smaller than this are noise regardless of ratio). Tolerances
# are sized to shared-CI noise: packing ratios are highly stable
# (deterministic seeded workloads), wall-clock-adjacent fields are not.
TREND_GATES: Dict[str, dict] = {
    # wire-smoke: deterministic seeded churn — tight.
    "wire_deltas_per_packet": {"direction": "higher", "rel_tol": 0.5},
    "wire_packet_reduction_x": {"direction": "higher", "rel_tol": 0.5},
    "wire_tx_bytes_per_admitted_take": {
        "direction": "lower", "rel_tol": 1.0, "abs_floor": 10.0,
    },
    # smoke: the workload size is pinned by the script, so a shrink means
    # the gate itself was weakened.
    "ingest_commit_smoke_deltas": {"direction": "higher", "rel_tol": 0.01},
    # disabled-recorder branch cost: wall-clock-class on shared CI, so a
    # wide ratio + an absolute floor; the smoke separately hard-fails at
    # 1 µs.
    "trace_off_branch_ns": {
        "direction": "lower", "rel_tol": 4.0, "abs_floor": 500.0,
    },
    # mesh smoke (pod-scale serving): fused-step throughput on the forced
    # 4-way CPU mesh — wall-clock-class on a shared single core, so very
    # wide tolerances; the bit-exactness booleans below are the hard gate.
    "mesh_smoke_merges_per_s": {"direction": "higher", "rel_tol": 0.75},
    "mesh_smoke_take_rps": {"direction": "higher", "rel_tol": 0.75},
    # soak smoke (bucket lifecycle): blocking-take throughput under GC
    # churn and the first-vs-last-window p99 drift ratio — wall-clock-
    # class on shared CI, so wide tolerances; the exactness/nonzero
    # gates below carry the hard content.
    # Blocking single-caller takes: the most wall-clock-sensitive number
    # in the receipt set (a busy CI neighbor halves it) — widest band.
    "soak_takes_per_s": {"direction": "higher", "rel_tol": 0.9},
    "soak_p99_drift_x": {
        "direction": "lower", "rel_tol": 2.0, "abs_floor": 1.0,
    },
    # device-resident ingest (r15): the raw-plane decode+fold rate and
    # the same-box speedup over the python decode path. Both are
    # wall-clock-class on shared CI (wide bands); the smoke separately
    # hard-fails under 2x, and the fixpoint gate below carries the
    # correctness content.
    "ingest_raw_decode_per_s": {"direction": "higher", "rel_tol": 0.75},
    "ingest_raw_vs_python_speedup_x": {
        "direction": "higher", "rel_tol": 0.5, "abs_floor": 0.5,
    },
    # patrol-audit: the measured AP-overshoot factor of the chaos smoke's
    # seeded 2-side partition. Deterministic (frozen clocks, both sides
    # admit exactly one capacity: 20/10 = 2.0) — a drift means the
    # auditor's lattice arithmetic changed. The chaos leg separately
    # hard-asserts factor ∈ (1, sides].
    "audit_overshoot_factor": {
        "direction": "lower", "rel_tol": 0.05, "abs_floor": 0.01,
    },
    # patrol-dispatch: cached jit variants after the witness warm+redrive.
    # Deterministic per commit, but legitimately grows when a kernel gains
    # a shape bucket — wide band + floor so only a specialization explosion
    # (one python-size argument can mint a variant per distinct value)
    # trips it without a re-pin. Zero-entries vacuity is caught by the
    # NONZERO gate below; per-variant stability by retraces_after_warmup.
    "jit_cache_entries": {
        "direction": "lower", "rel_tol": 0.5, "abs_floor": 16.0,
    },
    # Hot-key coalescing: the coalesced leg's serving rate over the
    # seeded Zipf(1.25) crowd. Wall-clock-class on shared CI (wide
    # band); the correctness content lives in the EXACT fixpoint gate
    # and the smoke's own hard >= 5x assertion, mirrored by the floor
    # gate below.
    "hotkey_takes_per_s": {"direction": "higher", "rel_tol": 0.9},
}

# Hard boolean/exactness gates: value must equal the expectation.
EXACT_GATES: Dict[str, object] = {
    "ingest_commit_equivalence": "bit-exact",
    # Device-resident ingest: raw-plane device decode+fold must land
    # bit-exactly on the host decode path's state — THE r15 hard gate.
    "ingest_raw_vs_host_fixpoint": "bit-exact",
    "metrics_exposition": "parsed",
    "wire_fixpoint_equal": True,
    "wire_converged_delta": True,
    "wire_converged_full": True,
    "wire_default_mode": "delta",
    "chaos_converged": True,
    # mesh smoke: engine-level cross-topology fixpoint, tree-vs-flat
    # converge equality, the converge-kernel attribution, and the
    # documented-and-gated demotion constraint (ROADMAP item 4 reads it).
    "mesh_fixpoint_equal": True,
    "mesh_tree_vs_flat": "bit-exact",
    "mesh_converge_kernel": "tree",
    "mesh_demotion": "unsupported",
    # mesh lifecycle: sharded-plane demotion stays unsupported (above),
    # but the GC path must shed via host-directory reclaim.
    "mesh_gc": "host-directory",
    # soak smoke (bucket lifecycle, ROADMAP item 4): the post-GC
    # reconstructed fixpoint and per-take outcomes must match the no-GC
    # reference bit-exactly, the footprint must hold under the budget
    # for the whole soak with zero main-phase sheds, and the shed path
    # must demonstrably engage when nothing is reclaimable.
    "soak_fixpoint_equal": "bit-exact",
    "soak_admits_equal": True,
    "soak_footprint_under_budget": True,
    "soak_shed_main": 0,
    # patrol-audit: the divergence gauge MUST read zero at the chaos
    # leg's converged fixpoint (the meter's defining property), and the
    # sides estimate of the seeded 2-side partition is exactly 2.
    "audit_divergent_buckets": 0,
    "audit_sides_estimate": 2,
    # elastic membership churn (r16): the zero-downtime tentpole is an
    # EXACT claim, not a trend — every node's per-bucket digest agrees at
    # the post-churn quiesce (and the meshed node's quiesced relayout
    # cycle is bit-identical), the client load saw zero non-429 errors
    # across the whole join/leave/rejoin + 4→8 resize schedule, no
    # admitted token was lost, and the membership lattice ends clean
    # (5 members, no standing tombstones).
    "churn_digest_fixpoint": "bit-exact",
    "churn_non429_errors": 0,
    "churn_token_conservation": True,
    "churn_members_final": 5,
    "churn_tombstones_final": 0,
    # cert-kit kernel families (check.sh stage 9): the smoke drives the
    # GCRA / concurrency / hierarchical-quota device kernels against a
    # literal python replay of their registered sequential semantics on
    # frozen inputs — the admitted counts are fully deterministic, so
    # they pin exactly (a drift means the kernel algebra changed without
    # re-certification).
    "cert_kernels": "bit-exact",
    "cert_gcra_admitted": 15,
    "cert_conc_admitted": 21,
    "cert_quota_admitted": 8,
    # patrol-dispatch (check.sh stage 10): the smoke warms every
    # registered engine hot path and re-drives each at identical shapes
    # under the jax compile counter — a single post-warmup retrace means
    # a call site started feeding raw python sizes (or drifted off its
    # declared shape-bucket law) and every steady-state request is now
    # paying a recompile. EXACT zero, no tolerance. The witness-path
    # count pins the coverage half: a path silently dropped from
    # WITNESS_PATHS would otherwise weaken the retrace gate unseen.
    "retraces_after_warmup": 0,
    "dispatch_witness_paths": 16,
    # Hot-key coalescing (one-dispatch-per-tick serving): the coalesced
    # leg's per-ticket outcome stream must be BIT-EXACT equal to the
    # PATROL_TAKE_FOLD=0 replay — coalescing is visible only in the
    # dispatch count, never in results.
    "hotkey_fixpoint_equal": True,
    # The rx-fold collapse factor of the seeded Zipf crowd: 6000 tickets
    # submitted against a paused feeder fold into exactly 64 open
    # entries (one per name) = 93.75 tickets per dispatched take row.
    # Fully deterministic — a drift means the fold keying or the
    # submission discipline changed.
    "take_coalesce_ratio": 93.75,
}

# Hard lower bounds: the current value must be >= the floor regardless
# of baseline (the smoke asserts these too; gating here keeps a weakened
# smoke from shipping silently).
FLOOR_GATES: Dict[str, float] = {
    # The hot-key tentpole's acceptance bar: coalesced serving must beat
    # the per-ticket replay by >= 5x takes/s on the same box.
    "hotkey_speedup_x": 5.0,
}

# Fields that must be present AND strictly positive (no baseline needed):
# instrumentation liveness — a zero means the device-timing plane lost
# the mesh path.
NONZERO_GATES = (
    "mesh_kernel_step_samples",
    # Device-resident ingest liveness: the smoke's raw leg dispatched,
    # and the wire smoke's delta rx actually rode the raw-plane path.
    "ingest_raw_device_dispatches",
    "wire_raw_device_dispatches",
    # The lifecycle must actually CYCLE during the soak: buckets
    # reclaimed, and the frozen-clock shed probe drew explicit sheds.
    "soak_reclaimed",
    "soak_shed_probe",
    # patrol-audit instrumentation liveness: the lag gauges drew samples,
    # read-only divergence compares ran, the divergent phase was actually
    # observed (>0 before repair re-armed), and a window was evaluated.
    "audit_peer_lag_samples",
    "audit_divergence_checks",
    "audit_divergent_buckets_divergent_phase",
    "audit_windows_evaluated",
    # churn smoke liveness: takes were admitted AND shed (the exhausted
    # bucket drew 429s), and every membership arrow actually fired —
    # joins adopted fleet-wide, a lane retired, the mesh resharded.
    "churn_admitted",
    "churn_shed",
    "churn_counter_peer_joins",
    "churn_counter_peer_leaves",
    "churn_counter_lane_tombstones",
    "churn_counter_mesh_resizes",
    # patrol-dispatch: the warmed jit cache actually holds entries —
    # zero would mean the witness ran against stub kernels (the retrace
    # gate above would then pass vacuously). Not EXACT: the absolute
    # count varies with which other smoke legs warmed jits first.
    "jit_cache_entries",
    # Hot-key coalescing liveness: the smoke's Zipf crowd actually
    # exercised every coalescing seam — rows dispatched as take-n
    # (nreq > 1), tickets folded rx-side onto open queue entries, and
    # partial grants split FIFO across a row's waiting tickets. A zero
    # means the fold path silently disengaged and the fixpoint gate
    # above is comparing per-ticket against per-ticket.
    "take_rows_coalesced",
    "take_tickets_folded",
    "take_partial_grants",
)

# Device-stage columns (patrol-fleet device-dispatch timing): the smoke's
# ingest_stage_breakdown must carry samples in these — an empty column
# means the instrumentation half of the r06 capture silently died.
DEVICE_STAGE_FIELDS = ("device_commit_ns", "device_take_ns")


def merge_receipts(currents: List[dict]) -> dict:
    out: dict = {}
    for c in currents:
        out.update(c)
    return out


def check_trend(baseline: dict, current: dict) -> Tuple[List[dict], List[str]]:
    """→ (regressions, report lines). A regression dict names the field,
    the values, and why it tripped."""
    regressions: List[dict] = []
    report: List[str] = []

    for field, expect in EXACT_GATES.items():
        got = current.get(field)
        if got is None:
            regressions.append(
                {"field": field, "why": "missing", "expected": expect}
            )
            report.append(f"FAIL {field}: missing (expected {expect!r})")
        elif got != expect:
            regressions.append(
                {"field": field, "why": "exact", "got": got, "expected": expect}
            )
            report.append(f"FAIL {field}: {got!r} != {expect!r}")
        else:
            report.append(f"ok   {field} = {got!r}")

    for field, floor in FLOOR_GATES.items():
        got = current.get(field)
        if not isinstance(got, (int, float)) or isinstance(got, bool):
            regressions.append(
                {"field": field, "why": "missing", "floor": floor}
            )
            report.append(f"FAIL {field}: {got!r} (must be >= {floor})")
        elif got < floor:
            regressions.append(
                {"field": field, "why": "floor", "got": got, "floor": floor}
            )
            report.append(f"FAIL {field}: {got} < floor {floor}")
        else:
            report.append(f"ok   {field} = {got} (floor {floor})")

    for field in NONZERO_GATES:
        got = current.get(field)
        if not isinstance(got, (int, float)) or isinstance(got, bool) or got <= 0:
            regressions.append(
                {"field": field, "why": "not-positive", "got": got}
            )
            report.append(f"FAIL {field}: {got!r} (must be present and > 0)")
        else:
            report.append(f"ok   {field} = {got}")

    breakdown = current.get("ingest_stage_breakdown") or {}
    for stage in DEVICE_STAGE_FIELDS:
        cnt = (breakdown.get(stage) or {}).get("count", 0)
        if not cnt:
            regressions.append(
                {"field": f"ingest_stage_breakdown.{stage}", "why": "empty"}
            )
            report.append(f"FAIL device stage {stage}: no samples")
        else:
            report.append(f"ok   device stage {stage}: {cnt} samples")

    for field, gate in TREND_GATES.items():
        base = baseline.get(field)
        cur = current.get(field)
        if cur is None:
            regressions.append({"field": field, "why": "missing"})
            report.append(f"FAIL {field}: missing from current receipts")
            continue
        if base is None or not isinstance(base, (int, float)):
            report.append(f"new  {field} = {cur} (no baseline; pin to adopt)")
            continue
        if not isinstance(cur, (int, float)) or isinstance(cur, bool):
            regressions.append(
                {"field": field, "why": "non-numeric", "got": cur}
            )
            report.append(f"FAIL {field}: non-numeric {cur!r}")
            continue
        rel_tol = gate.get("rel_tol", 0.25)
        abs_floor = gate.get("abs_floor", 0.0)
        if gate["direction"] == "higher":
            limit = base * (1.0 - rel_tol)
            bad = cur < limit and (base - cur) > abs_floor
        else:
            limit = base * (1.0 + rel_tol)
            bad = cur > limit and (cur - base) > abs_floor
        if bad:
            regressions.append(
                {
                    "field": field,
                    "why": "trend",
                    "got": cur,
                    "baseline": base,
                    "limit": round(limit, 4),
                    "direction": gate["direction"],
                }
            )
            report.append(
                f"FAIL {field}: {cur} vs baseline {base} "
                f"({gate['direction']}-is-better, limit {limit:.4g})"
            )
        else:
            report.append(f"ok   {field}: {cur} (baseline {base})")
    return regressions, report


def verdict_line(regressions: List[dict]) -> str:
    checked = (
        len(TREND_GATES)
        + len(EXACT_GATES)
        + len(FLOOR_GATES)
        + len(DEVICE_STAGE_FIELDS)
        + len(NONZERO_GATES)
    )
    verdict = "pass" if not regressions else "fail"
    return (
        f"BENCH_TREND verdict={verdict} regressions={len(regressions)} "
        f"checked={checked}"
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--baseline",
        default="benchmarks/TREND_BASELINE.json",
        help="pinned receipts (benchmarks/TREND_BASELINE.json)",
    )
    ap.add_argument(
        "currents",
        nargs="+",
        help="current receipt JSON files (smoke/wire-smoke/chaos-smoke "
        "output lines; later files win on collisions)",
    )
    args = ap.parse_args(argv)
    try:
        with open(args.baseline) as f:
            baseline = json.load(f)
    except (OSError, ValueError) as exc:
        print(f"cannot read baseline {args.baseline}: {exc}", file=sys.stderr)
        print("BENCH_TREND verdict=error regressions=-1 checked=0")
        return 2
    currents = []
    for path in args.currents:
        try:
            with open(path) as f:
                text = f.read()
        except OSError as exc:
            print(f"cannot read {path}: {exc}", file=sys.stderr)
            print("BENCH_TREND verdict=error regressions=-1 checked=0")
            return 2
        # A smoke's stdout may carry log lines; the receipt is the last
        # JSON object line.
        doc = None
        for line in reversed(text.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    doc = json.loads(line)
                    break
                except ValueError:
                    continue
        if doc is None:
            print(f"no JSON receipt line in {path}", file=sys.stderr)
            print("BENCH_TREND verdict=error regressions=-1 checked=0")
            return 2
        currents.append(doc)
    regressions, report = check_trend(baseline, merge_receipts(currents))
    for line in report:
        print(line)
    print(verdict_line(regressions))
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
