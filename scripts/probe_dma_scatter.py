"""On-chip probe: DMA-pipeline scatter-merge (the r3 kernel exploration).

Keeps the state in HBM (memory_space=ANY) and does per-row read-modify-
write through make_async_copy with a D-deep double-buffered pipeline —
the embedding-update pattern, and the only dynamic-row-RMW shape the
current Mosaic accepts (vector dynamic slices need statically provable
tile alignment; scalar VMEM stores are rejected outright).

Measured r3 (v5e, 1M x 256-lane state, K=8192 unique rows):
  inner=bcast   (plain max, 1 op)            ~3 ns/row   -> DMA pipeline is free
  inner=pairmax (interleaved lexicographic)  ~190 ns/row -> the join dominates
The lexicographic (hi, lo) max over (lo, hi)-interleaved int32 lanes needs
lane rolls (or masked reductions, measured slower still at ~260 ns) and
that cost, not the DMA, decides the kernel: at ~190 ns/delta it cannot
beat the XLA scatter's measured ~130-215 ns/update. A de-interleaved
split-plane state layout would fix the join (~7 half-tile ops, no rolls)
but taxes every other int64 op in the framework; declined with data.

Usage: python scripts/probe_dma_scatter.py
"""
import os
import sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp
jax.config.update("jax_enable_x64", True)
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import numpy as np
from functools import partial

B, S, L = 1_000_000, 8, 128
K = 8192
D = 8

def pair_max_ilv(cur, upd, lane_par):
    # lexicographic int64 max on (lo,hi)-interleaved int32 tiles.
    # even lanes = lo, odd = hi; values non-negative (hi < 2^31).
    u_hi = jnp.roll(upd, -1, axis=-1)
    c_hi = jnp.roll(cur, -1, axis=-1)
    sign = jnp.int32(-0x80000000)
    lo_gt = (upd ^ sign) > (cur ^ sign)         # valid at even lanes
    gt = (u_hi > c_hi) | ((u_hi == c_hi) & lo_gt)
    g = gt.astype(jnp.int32) * lane_par          # keep even lanes only
    g_pair = g | jnp.roll(g, 1, axis=-1)
    return jnp.where(g_pair == 1, upd, cur)

def mk_kern(inner):
    def kern(rows_ref, w0_ref, lo_ref, hi_ref, state_ref, out_ref, rbuf, wbuf, rsem, wsem):
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, S, L), 2)
        sub = jax.lax.broadcasted_iota(jnp.int32, (1, S, L), 1)
        lane_par = (1 - (lane & 1))  # 1 at even lanes
        def start_read(j, d):
            pltpu.make_async_copy(state_ref.at[pl.ds(rows_ref[j], 1)], rbuf.at[d], rsem.at[d]).start()
        jax.lax.fori_loop(jnp.int32(0), jnp.int32(D), lambda d, _: (start_read(d, d), 0)[1], 0)
        def body(j, _):
            d = jax.lax.rem(j, jnp.int32(D))
            pltpu.make_async_copy(state_ref.at[pl.ds(rows_ref[j], 1)], rbuf.at[d], rsem.at[d]).wait()
            @pl.when(j >= D)
            def _():
                pltpu.make_async_copy(wbuf.at[d], out_ref.at[pl.ds(rows_ref[j - D], 1)], wsem.at[d]).wait()
            if inner == "bcast":
                wbuf[d] = jnp.maximum(rbuf[d], lo_ref[j])
            else:
                w0 = w0_ref[j]
                su = w0 >> 7
                l0 = w0 & 127
                m_lo = ((sub == su) & (lane == l0)).astype(jnp.int32)
                m_hi = ((sub == su) & (lane == l0 + 1)).astype(jnp.int32)
                upd = m_lo * lo_ref[j] + m_hi * hi_ref[j]
                wbuf[d] = pair_max_ilv(rbuf[d], upd, lane_par)
            pltpu.make_async_copy(wbuf.at[d], out_ref.at[pl.ds(rows_ref[j], 1)], wsem.at[d]).start()
            @pl.when(j + D < K)
            def _():
                start_read(j + D, d)
            return 0
        jax.lax.fori_loop(jnp.int32(0), jnp.int32(K), body, 0)
        def epi(d, _):
            j = jnp.int32(K) - jnp.int32(D) + d
            dd = jax.lax.rem(j, jnp.int32(D))
            pltpu.make_async_copy(wbuf.at[dd], out_ref.at[pl.ds(rows_ref[j], 1)], wsem.at[dd]).wait()
            return 0
        jax.lax.fori_loop(jnp.int32(0), jnp.int32(D), epi, 0)
    return kern

def build(inner):
    return pl.pallas_call(
        mk_kern(inner),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] * 4 + [pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((B, S, L), jnp.int32),
        scratch_shapes=[pltpu.VMEM((D, 1, S, L), jnp.int32),
                        pltpu.VMEM((D, 1, S, L), jnp.int32),
                        pltpu.SemaphoreType.DMA((D,)),
                        pltpu.SemaphoreType.DMA((D,))],
        input_output_aliases={4: 0},
    )

rng = np.random.default_rng(3)
rows = jnp.asarray(rng.choice(B - 8, K, replace=False).astype(np.int32))
w0 = jnp.asarray((rng.integers(0, 256, K) * 4).astype(np.int32))
lo = jnp.asarray(rng.integers(1, 1 << 30, K).astype(np.int32))
hi = jnp.asarray(rng.integers(0, 1 << 20, K).astype(np.int32))

probe = jax.jit(lambda s: jnp.sum(s[:64]).astype(jnp.int64))
def force(s): return int(jax.device_get(probe(s)))

for inner in ("bcast", "pairmax"):
    try:
        call = build(inner)
        @partial(jax.jit, donate_argnums=4, static_argnums=5)
        def chain(r, w, l, h, state, n):
            for i in range(n):
                state = call(r, w, l + i, h, state)
            return state
        state = jnp.zeros((B, S, L), jnp.int32)
        state = chain(rows, w0, lo, hi, state, 4); force(state)
        best = {4: 1e9, 24: 1e9}
        for _ in range(3):
            for n in (4, 24):
                t0 = time.perf_counter()
                state = chain(rows, w0, lo, hi, state, n)
                force(state)
                best[n] = min(best[n], time.perf_counter() - t0)
        per_call = (best[24] - best[4]) / 20
        print(f"{inner:8s} per-row {per_call/K*1e9:5.0f} ns  rate {K/per_call/1e6:6.2f} M-rows/s")
        del state
    except Exception as e:
        msg = str(e).replace("\n", " | ")
        import re
        mm = re.findall(r"(Mosaic failed[^|]{0,160}|Error details[^|]{0,160}|Unsupported[^|]{0,160})", msg)
        print(f"{inner}: FAILED", mm[:2] if mm else msg[:200])
