#!/usr/bin/env python
"""Run patrol-race — the cross-seam concurrency prover + guarded-state
static analysis.

Stage 7 of the `scripts/check.sh` gate, runnable standalone. Two halves
(patrol_tpu/analysis/race.py):

  dynamic  exhaustive deterministic interleavings of the C++ HTTP
           front's epoll-seam protocol model (pt_http_poll park/drain,
           completion-ring (slot, gen) tags, pt_http_complete_takes
           fan-in) across epoll-script / pump / completer actors:
    PTR001   lost wakeup / stalled completion (liveness)
    PTR002   completion-ring token conservation (safety)
           with three seeded mutations (completion-before-park,
           ring-slot reuse without fence, ack-without-holding-mutex)
           that must each be demonstrably rejected.

  static   over the engine/net thread-ensemble sources:
    PTR003   guarded attribute touched outside its declared lock
             (GUARDS registry), and retained-buffer ownership
             (owns_buffers/borrows_until) use-after-recycle
    PTR004   lock-graph cycle or declared-order inversion
             (_evict_mu -> _host_mu -> _state_mu, with
             NATIVE_EFFECTS.takes_host_mu call sites counted as
             _host_mu acquisitions)
    PTR005   condvar wait() without an enclosing predicate loop

Exit code 0 = repo proves clean AND every seeded seam mutation is
rejected; 1 = findings printed one per line as `path:line: CODE message`
(suppressible inline with `# patrol-lint: disable=PTRnnn`).

Pure python (no jax, no native build); deterministic — no randomness,
so a CI failure replays exactly.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from patrol_tpu.analysis import driver

    repo_root = driver.repo_root_for(__file__)

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--mutation",
        default=None,
        help="run ONE named seam mutation and print what catches it",
    )
    ap.add_argument(
        "--list", action="store_true",
        help="list registered seam mutations and exit",
    )
    ap.add_argument(
        "--static-only", action="store_true",
        help="run only the static half (guarded state / lock graph / "
        "condvar / ownership)",
    )
    args = ap.parse_args()

    from patrol_tpu.analysis import race

    if args.list:
        for name in race.SEAM_MUTATIONS:
            print(name)
        return 0

    if args.mutation:
        entry = race.SEAM_MUTATIONS.get(args.mutation)
        if entry is None:
            return driver.unknown_name("patrol-race", "mutation", args.mutation)
        sem, code = entry
        findings = race.check_seam(sem)
        driver.print_findings(findings)
        hit = any(f.check == code for f in findings)
        return driver.mutation_verdict(
            "patrol-race",
            args.mutation,
            hit,
            f"REJECTED by {code} (good)" if hit else "NOT caught (bad)",
        )

    if args.static_only:
        used = set()
        findings = driver.apply_stage_suppressions(
            race.race_static(race.race_sources(repo_root), used_out=used),
            repo_root,
            stale_family="PTR",
            inline_used=used,
        )
    else:
        findings = race.race_repo(repo_root)

    def clean_line() -> str:
        explored = sum(
            race.explore_seam(sc)[0] for sc in race.builtin_seam_scenarios()
        )
        n_guards = sum(
            len(attrs)
            for per_cls in race.GUARDS.values()
            for attrs in per_cls.values()
        )
        return (
            "patrol-race: clean "
            f"(seam states explored={explored} across "
            f"{len(race.builtin_seam_scenarios())} scenarios, "
            f"{len(race.SEAM_MUTATIONS)} seeded mutations all rejected; "
            f"{n_guards} guarded attrs, "
            f"{len(race.RACE_FILES)} thread-ensemble files)"
        )

    return driver.finish("patrol-race", findings, clean_line)


if __name__ == "__main__":
    sys.exit(main())
