"""Probe: dense CvRDT join formulations vs jax's s64 split-pair emulation.

VERDICT r3 item 2: the canonical dense sweep lands at 48.3M merges/s
(594.7 GB/s implied of 819) — the gap to the bandwidth bound is the s64
max emulation on a chip without native int64. All CRDT planes are
NON-NEGATIVE (lanes are monotone grow-only), so s64 max is order-preserving
on the value's (hi, lo) u32 pair — candidate reformulations:

  s64      jnp.maximum on int64 (current merge_dense)
  u64      bitcast to uint64, maximum, bitcast back (drops sign handling)
  lex32    bitcast to u32[..,2]; lexicographic (hi, lo) compare; ONE
           interleaved pair select (jnp.where on the [..,2] view)
  lex32x   same compare, arithmetic mask select (xor/and instead of where)

Timing: the proven device_loop differential from bench.py (fori carry
prevents CSE of idempotent joins; forced completion via dependent checksum
readback; min-per-window then difference). Correctness: each candidate is
checksum-compared against s64 on the same inputs before timing.

Run on the axon tunnel:  python scripts/probe_dense_u32.py
"""

import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax

import patrol_tpu  # noqa: F401  (x64)

B = int(os.environ.get("PROBE_B", 500_000))
N = int(os.environ.get("PROBE_N", 256))


# Every candidate takes (state, other, i) and joins state with (other + i):
# the +i (an s64 add, identical cost in all candidates) makes each loop
# iteration VALUE-DISTINCT — a plain idempotent max chain reaches its
# fixpoint after one step, and both the compiler and the tunnel's
# execution layer can then collapse the remaining iterations (the r4 first
# probe "measured" 73 PB/s of HBM traffic that way).


def max_s64(a, b, i):
    return jnp.maximum(a, b + i)


def max_u64(a, b, i):
    return lax.bitcast_convert_type(
        jnp.maximum(
            lax.bitcast_convert_type(a, jnp.uint64),
            lax.bitcast_convert_type(b + i, jnp.uint64),
        ),
        jnp.int64,
    )


def _lex_gt(a2, b2):
    a_lo, a_hi = a2[..., 0], a2[..., 1]
    b_lo, b_hi = b2[..., 0], b2[..., 1]
    return (a_hi > b_hi) | ((a_hi == b_hi) & (a_lo >= b_lo))


def max_lex32(a, b, i):
    a2 = lax.bitcast_convert_type(a, jnp.uint32)
    b2 = lax.bitcast_convert_type(b + i, jnp.uint32)
    out = jnp.where(_lex_gt(a2, b2)[..., None], a2, b2)
    return lax.bitcast_convert_type(out, jnp.int64)


def max_lex32x(a, b, i):
    a2 = lax.bitcast_convert_type(a, jnp.uint32)
    b2 = lax.bitcast_convert_type(b + i, jnp.uint32)
    mask = (
        _lex_gt(a2, b2)[..., None]
        .astype(jnp.uint32)
        * jnp.uint32(0xFFFFFFFF)
    )
    out = b2 ^ ((a2 ^ b2) & mask)
    return lax.bitcast_convert_type(out, jnp.int64)


CANDIDATES = {
    "s64": max_s64,
    "u64": max_u64,
    "lex32": max_lex32,
    "lex32x": max_lex32x,
}


def mk(B, N):
    @jax.jit
    def _mk():
        row = jnp.arange(B, dtype=jnp.int64)[:, None, None]
        lane = jnp.arange(N, dtype=jnp.int64)[None, :, None]
        side = jnp.arange(2, dtype=jnp.int64)[None, None, :]
        a = (row * 7 + lane * 13 + side * 3) % (10**10)
        b = (row * 11 + lane * 5 + side * 17) % (10**10)
        # Spice the high words so the hi/lo split actually matters.
        a = a + (row % 5) * (1 << 33)
        b = b + (row % 3) * (1 << 33)
        return a, b

    return _mk()


@jax.jit
def _checksum_probe(v):
    return jnp.sum(v)


def checksum(x):
    return int(jax.device_get(_checksum_probe(x)))


def bench_one(fn, a, b, iters_lo=2, iters_hi=14, repeats=3):
    @partial(jax.jit, donate_argnums=0)
    def loop_n(s, n, o):
        return lax.fori_loop(
            0, n, lambda i, st: fn(st, o, i.astype(jnp.int64)), s
        )

    s = a + 0  # private carry copy
    for _ in range(2):
        s = loop_n(s, jnp.int32(iters_lo), b)
    s = loop_n(s, jnp.int32(iters_hi), b)
    checksum(s)
    best_lo = best_hi = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        s = loop_n(s, jnp.int32(iters_lo), b)
        checksum(s)
        best_lo = min(best_lo, time.perf_counter() - t0)
        t0 = time.perf_counter()
        s = loop_n(s, jnp.int32(iters_hi), b)
        checksum(s)
        best_hi = min(best_hi, time.perf_counter() - t0)
    return max(best_hi - best_lo, 1e-9) / (iters_hi - iters_lo)


def main():
    print(f"platform={jax.default_backend()} devices={jax.devices()}", flush=True)
    a, b = mk(B, N)
    jax.block_until_ready(a)
    print(f"state {B}x{N}x2 int64 ({B * N * 2 * 8 / 1e9:.2f} GB/plane)", flush=True)

    # Correctness first: all candidates must join to the s64 answer.
    i_test = jnp.int64(3)
    want = checksum(jnp.maximum(a, b + i_test))
    bad = []
    for name, fn in CANDIDATES.items():
        got = checksum(jax.jit(fn)(a, b, i_test))
        status = "ok" if got == want else f"MISMATCH want={want} got={got}"
        print(f"correctness {name}: {status}", flush=True)
        if got != want:
            bad.append(name)
    for name in bad:
        CANDIDATES.pop(name)

    bytes_per = 3 * B * N * 2 * 8
    for name, fn in CANDIDATES.items():
        dt = bench_one(fn, a, b)
        print(
            f"{name}: {dt * 1e3:.3f} ms/sweep  "
            f"{B / dt / 1e6:.1f}M merges/s  "
            f"{bytes_per / dt / 1e9:.1f} GB/s implied",
            flush=True,
        )


if __name__ == "__main__":
    main()
