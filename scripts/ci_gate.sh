#!/usr/bin/env bash
# ci_gate: the CI wrapper around scripts/check.sh that makes stage skips
# a FAILURE instead of a notice.
#
# check.sh is tolerant by design (a laptop without LLVM still gets the
# other stages); CI must not be: the ROADMAP's standing risk is the
# clang-tidy stage silently never running. This gate
#
#   1. pins the toolchain floor: clang-tidy >= 14 must be on PATH
#      (unless explicitly waived with --allow-skip tidy);
#   2. runs check.sh (all stages, or --stage ...), capturing the
#      machine-readable `PATROL_CHECK stages=N pass=.. skip=.. fail=..
#      skipped=.. failed=..` summary line;
#   3. asserts `skipped=-` — every selected stage actually ran — modulo
#      an explicit, visible-in-CI-config --allow-skip list.
#
# Usage:
#   scripts/ci_gate.sh                       # full gate, zero skips
#   scripts/ci_gate.sh --allow-skip tidy     # container without LLVM
#   scripts/ci_gate.sh --stage lint,prove,abi
set -euo pipefail
cd "$(dirname "$0")/.."

ALLOW_SKIP=""
STAGE_ARGS=()
while [[ $# -gt 0 ]]; do
  case "$1" in
    --allow-skip) ALLOW_SKIP="$2"; shift 2 ;;
    --allow-skip=*) ALLOW_SKIP="${1#*=}"; shift ;;
    --stage|--stages) STAGE_ARGS+=(--stage "$2"); shift 2 ;;
    --stage=*|--stages=*) STAGE_ARGS+=("$1"); shift ;;
    -h|--help) sed -n '2,21p' "$0" | sed 's/^# \{0,1\}//'; exit 0 ;;
    *) echo "ci_gate: unknown argument $1" >&2; exit 2 ;;
  esac
done

allowed() {  # allowed <stage> → 0 iff stage is in the --allow-skip list
  local IFS=','
  for a in $ALLOW_SKIP; do [[ "$a" == "$1" ]] && return 0; done
  return 1
}

# Toolchain floor: clang-tidy >= 14, pinned here so the tidy stage cannot
# degrade to a permanent skip on CI hosts (ROADMAP item).
if ! allowed tidy; then
  if ! command -v clang-tidy >/dev/null 2>&1; then
    echo "ci_gate: clang-tidy not installed (need >= 14); install LLVM or" \
         "waive explicitly with --allow-skip tidy" >&2
    exit 1
  fi
  ver=$(clang-tidy --version | grep -oE 'version [0-9]+' | grep -oE '[0-9]+' | head -1)
  if [[ -z "$ver" || "$ver" -lt 14 ]]; then
    echo "ci_gate: clang-tidy version '$ver' < 14 (the curated profile" \
         "needs modern checks); upgrade or --allow-skip tidy" >&2
    exit 1
  fi
fi

LOG=$(mktemp)
trap 'rm -f "$LOG"' EXIT
rc=0
bash scripts/check.sh "${STAGE_ARGS[@]+"${STAGE_ARGS[@]}"}" 2>&1 | tee "$LOG" || rc=$?

SUMMARY=$(grep -E '^PATROL_CHECK ' "$LOG" | tail -1 || true)
if [[ -z "$SUMMARY" ]]; then
  echo "ci_gate: no PATROL_CHECK summary line emitted (check.sh died early)" >&2
  exit 1
fi
if [[ $rc -ne 0 ]]; then
  echo "ci_gate: check.sh failed (rc=$rc): $SUMMARY" >&2
  exit "$rc"
fi

skipped=$(sed -E 's/.* skipped=([^ ]+).*/\1/' <<<"$SUMMARY")
if [[ "$skipped" != "-" ]]; then
  IFS=',' read -r -a SKIPPED_LIST <<<"$skipped"
  for s in "${SKIPPED_LIST[@]}"; do
    if ! allowed "$s"; then
      echo "ci_gate: stage '$s' was SKIPPED ($SUMMARY); a skipped stage is" \
           "a silent hole in the gate — fix the toolchain or waive it" \
           "explicitly with --allow-skip $s" >&2
      exit 1
    fi
  done
  echo "ci_gate: skips [$skipped] explicitly waived (--allow-skip $ALLOW_SKIP)"
fi
echo "ci_gate: PASS — $SUMMARY"
