#!/usr/bin/env python
"""Run patrol-dispatch — the dispatch-discipline prover + compile-cache
stability witness over ``DISPATCH_SPECS`` (patrol_tpu/ops/obligations.py).

Stage 10 of the `scripts/check.sh` gate, runnable standalone:

  PTD001  retrace risk: jit dispatches fed raw python sizes /
          f-strings of shapes, and shape-bucket (_pad_size) law drift
          against the declared registry
  PTD002  donation discipline: binding/factory drift against the
          declared donate_argnums + use-after-donate dataflow at the
          engine dispatch sites
  PTD003  implicit host transfers (.item(), float()/int()/bool() on
          device values, np.asarray of device arrays, device_get) in
          functions reachable from the serve graph roots
  PTD004  compile-cache stability witness: every registered hot path
          warmed, then re-driven at identical shapes under a compile
          counter + the jax transfer guard — any post-warmup trace or
          implicit transfer is a finding carrying kernel + aval
  PTD005  completeness: every engine-dispatched jitted kernel is
          registered with a witness path or a written justified
          absence; stale/contradictory declarations flagged

Exit code 0 = clean; 1 = findings printed one per line as
`path:line: CODE message`. Deterministic; the witness runs on CPU.
"""

import argparse
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from patrol_tpu.analysis import driver

    repo_root = driver.repo_root_for(__file__)

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--list",
        action="store_true",
        help="list registered dispatch specs and seeded mutations, then exit",
    )
    ap.add_argument(
        "--mutation",
        default=None,
        help="execute ONE named seeded mutation and print the verdict",
    )
    ap.add_argument(
        "--no-witness",
        action="store_true",
        help="static checks only (skip the PTD004 dynamic witness)",
    )
    args = ap.parse_args()

    from patrol_tpu.analysis import dispatch
    from patrol_tpu.ops.obligations import DISPATCH_SPECS

    if args.list:
        for spec in DISPATCH_SPECS:
            cover = (
                f"witness={spec.witness}"
                if spec.witness
                else "witness:absent (justified)"
            )
            print(
                f"spec     {spec.name}  donate={spec.donate_argnums} "
                f"static={spec.static_argnames} buckets={spec.buckets}"
                f"({spec.bucket_lo},{spec.bucket_hi}) [{cover}]"
            )
        for name, code in dispatch.DISPATCH_MUTATIONS.items():
            kind = "dynamic" if code == "PTD004" else "static"
            print(f"mutation {name}  → {code} [{kind}]")
        return 0

    if args.mutation:
        expect = dispatch.DISPATCH_MUTATIONS.get(args.mutation)
        if expect is None:
            return driver.unknown_name(
                "patrol-dispatch", "mutation", args.mutation
            )
        findings = dispatch.mutation_findings(args.mutation)
        hit = any(f.check == expect for f in findings)
        stray = sorted(
            {f.check for f in findings if f.check != expect}
        )
        detail = (
            f"rejected with {expect}"
            + (f" (riders: {','.join(stray)})" if stray else "")
            if hit
            else f"NOT rejected (saw: {','.join(stray) or 'nothing'})"
        )
        return driver.mutation_verdict(
            "patrol-dispatch", args.mutation, hit, detail
        )

    used = set()
    findings = dispatch.check_repo(repo_root, used_out=used)
    report = None
    if not args.no_witness:
        report = dispatch.run_witness()
        findings += report.findings
    findings = driver.apply_stage_suppressions(
        findings, repo_root, "PTD", inline_used=used
    )

    witnessed = sum(1 for s in DISPATCH_SPECS if s.witness)
    absent = sum(1 for s in DISPATCH_SPECS if s.witness_absent)
    wtail = (
        "witness skipped (--no-witness)"
        if report is None
        else (
            f"{len(report.paths)} witness paths re-driven: "
            f"{report.retraces_after_warmup} post-warmup retraces, "
            f"{report.jit_cache_entries} cached variants"
        )
    )
    return driver.finish(
        "patrol-dispatch",
        findings,
        lambda: (
            f"patrol-dispatch: clean ({len(DISPATCH_SPECS)} specs: "
            f"{witnessed} witnessed + {absent} justified-absent; {wtail})"
        ),
    )


if __name__ == "__main__":
    sys.exit(main())
