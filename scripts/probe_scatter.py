"""On-chip probe: which scatter shape does TPU XLA actually vectorize?

The r3 honest capture put the element-granular scatter-merge at ~2.5M
deltas/s (~133 ns per element update, serialized). This probe measures the
alternatives before committing to a kernel redesign:

  elem3    - current merge_batch: 3 element scatters (added, taken, elapsed)
  pair     - lane-pair window: pn.at[rows, slots].max(pair[K,2]) + elapsed elem
  row      - row window: pn.at[rows].max(onehot[K,N,2]) + elapsed elem
  row_only - the row-window pn scatter alone
  el_only  - the elapsed element scatter alone
  row_flags- row_only with indices_are_sorted (rows pre-sorted host-side)
  el_flags - el_only with indices_are_sorted
  take     - current take_batch commit (2 elem adds + 1 elapsed add)
  take_row - row-window commit: pn.at[rows].add(onehot) + elapsed add

Methodology is bench.py's: unrolled chain inside one jit on a donated
input (the tunnel charges ~60-80 ms per execute), values varied with the
unroll index so CSE can't collapse the chain, forced completion via a
dependent checksum readback, differential (hi-lo)/(n_hi-n_lo) windows.

Usage: python scripts/probe_scatter.py [stage ...]
"""
from __future__ import annotations

import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

B = int(1e6)
N = 256
K = 65536

_PROBE = {}


def _force(tree):
    leaves = tuple(jax.tree_util.tree_leaves(tree))
    key = tuple((l.shape, str(l.dtype)) for l in leaves)
    p = _PROBE.get(key)
    if p is None:
        def _sum(ls):
            tot = jnp.zeros((), jnp.int64)
            for l in ls:
                tot = tot + jnp.sum(l).astype(jnp.int64)
            return tot
        p = jax.jit(_sum)
        _PROBE[key] = p
    return int(jax.device_get(p(leaves)))


def bench(fn, mk_state, *args, n_lo=2, n_hi=8, repeats=3):
    def make_run(n):
        @partial(jax.jit, donate_argnums=0)
        def run(s, *a):
            for i in range(n):
                s = fn(s, *a, i)
            return s
        return run

    run_lo, run_hi = make_run(n_lo), make_run(n_hi)
    state = mk_state()
    state = run_lo(state, *args)
    state = run_hi(state, *args)
    _force(state)
    best_lo = best_hi = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        state = run_lo(state, *args)
        _force(state)
        best_lo = min(best_lo, time.perf_counter() - t0)
        t0 = time.perf_counter()
        state = run_hi(state, *args)
        _force(state)
        best_hi = min(best_hi, time.perf_counter() - t0)
    del state
    return max(best_hi - best_lo, 1e-9) / (n_hi - n_lo)


def main():
    want = set(sys.argv[1:]) or None
    rng = np.random.default_rng(7)
    rows_np = rng.integers(0, B, K).astype(np.int32)
    rows_sorted_np = np.sort(rows_np)
    slots_np = rng.integers(0, N, K).astype(np.int32)
    rows = jnp.asarray(rows_np)
    rows_sorted = jnp.asarray(rows_sorted_np)
    slots = jnp.asarray(slots_np)
    a = jnp.asarray(rng.integers(1, 1 << 40, K).astype(np.int64))
    t = jnp.asarray(rng.integers(1, 1 << 40, K).astype(np.int64))
    e = jnp.asarray(rng.integers(1, 1 << 40, K).astype(np.int64))

    def mk_pn_el():
        return (
            jnp.zeros((B, N, 2), jnp.int64),
            jnp.zeros((B,), jnp.int64),
        )

    def mk_pn():
        return jnp.zeros((B, N, 2), jnp.int64)

    def mk_el():
        return jnp.zeros((B,), jnp.int64)

    oh = jax.jit(
        lambda slots_, a_, t_: jnp.where(
            (jnp.arange(N)[None, :, None] == slots_[:, None, None]),
            jnp.stack([a_, t_], -1)[:, None, :],
            jnp.int64(0),
        )
    )

    stages = {}

    def elem3(s, i):
        pn, el = s
        pn = pn.at[rows, slots, 0].max(a + i)
        pn = pn.at[rows, slots, 1].max(t + i)
        el = el.at[rows].max(e + i)
        return (pn, el)

    stages["elem3"] = (elem3, mk_pn_el, ())

    def pair(s, i):
        pn, el = s
        pn = pn.at[rows, slots].max(jnp.stack([a + i, t + i], -1))
        el = el.at[rows].max(e + i)
        return (pn, el)

    stages["pair"] = (pair, mk_pn_el, ())

    def row(s, i):
        pn, el = s
        pn = pn.at[rows].max(oh(slots, a + i, t + i))
        el = el.at[rows].max(e + i)
        return (pn, el)

    stages["row"] = (row, mk_pn_el, ())

    def row_only(pn, i):
        return pn.at[rows].max(oh(slots, a + i, t + i))

    stages["row_only"] = (row_only, mk_pn, ())

    def el_only(el, i):
        return el.at[rows].max(e + i)

    stages["el_only"] = (el_only, mk_el, ())

    def row_flags(pn, i):
        return pn.at[rows_sorted].max(
            oh(slots, a + i, t + i), indices_are_sorted=True
        )

    stages["row_flags"] = (row_flags, mk_pn, ())

    def el_flags(el, i):
        return el.at[rows_sorted].max(e + i, indices_are_sorted=True)

    stages["el_flags"] = (el_flags, mk_el, ())

    # --- the engine's REAL uniform tick kernel (r5): host C++ fold →
    # sorted unique sentinel-padded pairs → flagged scatter. The plain
    # elem3 above is the unfolded class the r4 bench measured; if the
    # flags buy a material win, the bench's scatter stage should measure
    # THIS (it is what the engine dispatches for uniform batches on
    # accelerator backends, PATROL_TICK_FOLD default 1).
    from patrol_tpu.ops.merge import FoldedMergeBatch, merge_batch_folded
    from patrol_tpu.runtime.engine import DeltaArrays, DeviceEngine

    deltas_np = DeltaArrays(
        rows=rows_np.astype(np.int64), slots=slots_np.astype(np.int64),
        added_nt=np.asarray(a), taken_nt=np.asarray(t),
        elapsed_ns=np.asarray(e), scalar=np.zeros(K, bool),
    )
    packed_np = DeviceEngine._fold_lane_merges(deltas_np)
    packed = jnp.asarray(packed_np)

    def folded(s, i):
        from patrol_tpu.models.limiter import LimiterState

        st = LimiterState(pn=s[0], elapsed=s[1])
        st = merge_batch_folded(
            st,
            FoldedMergeBatch(
                rows=packed[0].astype(jnp.int32),
                slots=packed[1].astype(jnp.int32),
                added_nt=packed[2] + i,
                taken_nt=packed[3] + i,
                erows=packed[4].astype(jnp.int32),
                elapsed_ns=packed[5] + i,
            ),
        )
        return (st.pn, st.elapsed)

    stages["folded"] = (folded, mk_pn_el, ())

    # --- folded + flat key: the folded pack's sorted UNIQUE (row,slot)
    # pairs re-keyed as row*N+slot — one index dim, sorted+unique flags,
    # sentinel tail dropped via OOB mode="drop" (sentinel rows are far
    # above B so their flat keys are OOB of B*N).
    Kp = packed_np.shape[1]
    flat_packed = jnp.asarray(packed_np[0] * N + packed_np[1])
    p2 = jnp.asarray(packed_np[2])
    p3 = jnp.asarray(packed_np[3])
    p4 = jnp.asarray(packed_np[4].astype(np.int32))
    p5 = jnp.asarray(packed_np[5])

    def folded_flat(s, i):
        pn, el = s
        fp = pn.reshape(B * N, 2)
        fp = fp.at[flat_packed].max(
            jnp.stack([p2 + i, p3 + i], -1),
            indices_are_sorted=True, unique_indices=True, mode="drop",
        )
        el = el.at[p4].max(
            p5 + i, indices_are_sorted=True, unique_indices=True,
            mode="drop",
        )
        return (fp.reshape(B, N, 2), el)

    stages["folded_flat"] = (folded_flat, mk_pn_el, ())

    # --- flat-key single scatter: same [B,N,2] memory viewed [B*N, 2],
    # ONE pair-window scatter at row*N+slot (one index dim instead of
    # two). A probe-only layout question: reshape is free, so a win here
    # is adoptable without moving bytes.
    flat_idx = jnp.asarray(rows_np.astype(np.int64) * N + slots_np)
    flat_sorted = jnp.asarray(
        np.sort(rows_np.astype(np.int64) * N + slots_np)
    )

    def flat(s, i):
        pn, el = s
        fp = pn.reshape(B * N, 2)
        fp = fp.at[flat_idx].max(jnp.stack([a + i, t + i], -1))
        el = el.at[rows].max(e + i)
        return (fp.reshape(B, N, 2), el)

    stages["flat"] = (flat, mk_pn_el, ())

    def flat_flags(s, i):
        pn, el = s
        fp = pn.reshape(B * N, 2)
        fp = fp.at[flat_sorted].max(
            jnp.stack([a + i, t + i], -1), indices_are_sorted=True
        )
        el = el.at[rows_sorted].max(e + i, indices_are_sorted=True)
        return (fp.reshape(B, N, 2), el)

    stages["flat_flags"] = (flat_flags, mk_pn_el, ())

    # --- take-shaped commits (K unique rows, add semantics) ---
    KT = 4096
    trows = jnp.asarray(
        rng.choice(B, KT, replace=False).astype(np.int32)
    )
    tslots = jnp.asarray(rng.integers(0, N, KT).astype(np.int32))
    da = jnp.asarray(rng.integers(1, 1 << 30, KT).astype(np.int64))
    dt = jnp.asarray(rng.integers(1, 1 << 30, KT).astype(np.int64))
    de = jnp.asarray(rng.integers(1, 1 << 30, KT).astype(np.int64))
    oh_t = jax.jit(
        lambda a_, t_: jnp.where(
            (jnp.arange(N)[None, :, None] == tslots[:, None, None]),
            jnp.stack([a_, t_], -1)[:, None, :],
            jnp.int64(0),
        )
    )

    def take_elem(s, i):
        pn, el = s
        pn = pn.at[trows, tslots, 0].add(da + i)
        pn = pn.at[trows, tslots, 1].add(dt + i)
        el = el.at[trows].add(de + i)
        return (pn, el)

    stages["take"] = (take_elem, mk_pn_el, ())

    def take_row(s, i):
        pn, el = s
        pn = pn.at[trows].add(oh_t(da + i, dt + i))
        el = el.at[trows].add(de + i)
        return (pn, el)

    stages["take_row"] = (take_row, mk_pn_el, ())

    def take_gather(s, i):
        # the full take kernel's memory shape: gather + compute + commit
        pn, el = s
        rows_g = pn[trows]
        sums = rows_g[:, :, 0].sum(-1) - rows_g[:, :, 1].sum(-1)
        pn = pn.at[trows].add(oh_t(da + i + sums * 0, dt + i))
        el = el.at[trows].add(de + i)
        return (pn, el)

    stages["take_gather"] = (take_gather, mk_pn_el, ())

    for name, (fn, mk, args) in stages.items():
        if want and name not in want:
            continue
        try:
            per = bench(fn, mk, *args)
        except Exception as ex:  # noqa: BLE001
            print(f"{name:12s} FAILED: {ex}")
            continue
        kk = KT if name.startswith("take") else K
        print(
            f"{name:12s} {per * 1e3:9.3f} ms/step  "
            f"{kk / per / 1e6:8.2f} M-deltas/s"
        )


if __name__ == "__main__":
    main()
