#!/usr/bin/env python
"""Run patrol-abi — the native-ABI conformance prover + cross-boundary
concurrency lint — over every registered obligation
(patrol_tpu/ops/obligations.py::ABI_OBLIGATIONS).

Stage 5 of the `scripts/check.sh` gate, runnable standalone. Exit codes:
0 = every obligation holds; 1 = findings printed one per line as

    path:line: CODE message

77 = the native toolchain/library is unavailable (check.sh maps this to
a LOUD stage skip — never a silent pass).

See patrol_tpu/analysis/abi.py for the passes, the PTA code table in
README.md ("patrol-check"), and `# patrol-lint: disable=PTAxxx` for the
(greppable, reviewed-like-code) suppression format.
"""

import argparse
import os
import sys

# Conformance runs on CPU: the jax twins are tiny-domain evaluations and
# the deployment env pins JAX_PLATFORMS at a TPU tunnel where every
# compile costs ~20 s.
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--root",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="repo root (default: this script's parent)",
    )
    ap.add_argument(
        "--only",
        default=None,
        help="comma-separated obligation-name substrings (default: all)",
    )
    ap.add_argument(
        "--list", action="store_true", help="list registered obligations"
    )
    args = ap.parse_args()

    from patrol_tpu.analysis import abi
    from patrol_tpu.ops.obligations import ABI_OBLIGATIONS

    if args.list:
        for ob in ABI_OBLIGATIONS:
            print(
                f"{ob.name}  [{','.join(ob.codes)}]  check={ob.check} "
                f"symbol={ob.symbol or '-'} twins={','.join(ob.twins) or '-'}"
            )
        return 0

    only = (
        [k.strip() for k in args.only.split(",") if k.strip()]
        if args.only
        else None
    )
    try:
        if only:
            findings = abi.abi_all(only=only)
        else:
            findings = abi.abi_repo(args.root)
    except abi.NativeUnavailable as exc:
        print(f"patrol-abi: SKIPPED — {exc}", file=sys.stderr)
        return 77

    for f in findings:
        print(f)
    if findings:
        print(
            f"patrol-abi: {len(findings)} finding(s) across "
            f"{len({f.path for f in findings})} file(s)",
            file=sys.stderr,
        )
        return 1
    print(
        f"patrol-abi: clean ({len(ABI_OBLIGATIONS)} obligations, all hold)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
