#!/usr/bin/env python
"""Run patrol-prove — the jaxpr-level CRDT invariant prover — over every
registered kernel root (patrol_tpu/ops/obligations.py::PROVE_ROOTS).

Stage 4 of the `scripts/check.sh` gate, runnable standalone. Exit code
0 = every declared obligation holds; 1 = findings printed one per line as

    path:line: CODE message

See patrol_tpu/analysis/prove.py for the passes, the PTP code table in
README.md ("patrol-check"), and `# patrol-lint: disable=PTPxxx` for the
(greppable, reviewed-like-code) suppression format.
"""

import argparse
import os
import sys

# Static proving always runs on CPU: tracing and the tiny-domain model
# enumerations need no accelerator, and the deployment env pins
# JAX_PLATFORMS at a TPU tunnel where every compile costs ~20 s.
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from patrol_tpu.analysis import driver

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--root",
        default=driver.repo_root_for(__file__),
        help="repo root (default: this script's parent)",
    )
    ap.add_argument(
        "--only",
        default=None,
        help="comma-separated root-name substrings to check (default: all)",
    )
    ap.add_argument(
        "--list", action="store_true", help="list registered roots and exit"
    )
    args = ap.parse_args()

    from patrol_tpu.analysis import prove
    from patrol_tpu.ops.obligations import PROVE_ROOTS

    roots = PROVE_ROOTS
    if args.only:
        keys = [k.strip() for k in args.only.split(",") if k.strip()]
        roots = tuple(r for r in roots if any(k in r.name for k in keys))

    if args.list:
        for r in roots:
            marks = ",".join(r.obligations)
            print(f"{r.name}  [{marks}]  structural={r.structural or '-'} "
                  f"model={r.model or '-'}")
        return 0

    if args.only:
        findings = []
        for r in roots:
            findings.extend(prove.prove_root(r))
        findings.sort(key=lambda f: (f.path, f.line, f.check))
    else:
        findings = prove.prove_repo(args.root)

    return driver.finish(
        "patrol-prove",
        findings,
        f"patrol-prove: clean ({len(roots)} roots, all obligations hold; "
        "engine dispatch graph fully registered)",
        findings_line=lambda fs: (
            f"patrol-prove: {len(fs)} finding(s) across "
            f"{len({f.path for f in fs})} file(s)"
        ),
    )


if __name__ == "__main__":
    sys.exit(main())
