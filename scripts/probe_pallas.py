"""On-chip probe: block-sparse Pallas merge vs XLA scatter.

Compares the vector-RMW pallas kernel against the pair-window XLA scatter
on (a) a zipf-like concentrated batch (hot working set -> few touched
512-row blocks: the realistic rate-limiter traffic, BASELINE config #2)
and (b) a uniform batch over 1M rows (every block touched: the
adversarial case where block streaming degenerates to a dense sweep).

Usage: python scripts/probe_pallas.py [K] [hot_buckets]
"""
from __future__ import annotations

import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

from patrol_tpu.models.limiter import LimiterState  # noqa: E402
from patrol_tpu.ops import pallas_merge  # noqa: E402
from patrol_tpu.ops.merge import MergeBatch, merge_batch  # noqa: E402

B = int(1e6)
N = 256
K = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
HOT = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000


def _force(state):
    s = jnp.sum(state.pn).astype(jnp.int64) + jnp.sum(state.elapsed)
    return int(jax.device_get(s))


def mk_state():
    return LimiterState(
        pn=jnp.zeros((B, N, 2), jnp.int64), elapsed=jnp.zeros((B,), jnp.int64)
    )


def time_fn(run, state, n_lo=2, n_hi=8, repeats=3):
    state = run(state, 0)
    _force(state)
    best = {n_lo: float("inf"), n_hi: float("inf")}
    for _ in range(repeats):
        for n in (n_lo, n_hi):
            t0 = time.perf_counter()
            for i in range(n):
                state = run(state, i)
            _force(state)
            best[n] = min(best[n], time.perf_counter() - t0)
    return max(best[n_hi] - best[n_lo], 1e-9) / (n_hi - n_lo)


def main():
    rng = np.random.default_rng(11)
    print(f"K={K} hot={HOT} pallas_native={pallas_merge.native_available()}")
    for label, rows_np in (
        ("zipf-hot", rng.integers(0, HOT, K).astype(np.int64)),
        ("uniform", rng.integers(0, B, K).astype(np.int64)),
    ):
        slots_np = rng.integers(0, N, K).astype(np.int64)
        a_np = rng.integers(1, 1 << 40, K).astype(np.int64)
        t_np = rng.integers(1, 1 << 40, K).astype(np.int64)
        e_np = rng.integers(1, 1 << 40, K).astype(np.int64)
        touched = len(np.unique(rows_np // pallas_merge.ROWS_PER_BLOCK))

        # XLA scatter path (device arrays prebuilt, donated chain)
        mb = MergeBatch(
            rows=jnp.asarray(rows_np, jnp.int32),
            slots=jnp.asarray(slots_np, jnp.int32),
            added_nt=jnp.asarray(a_np),
            taken_nt=jnp.asarray(t_np),
            elapsed_ns=jnp.asarray(e_np),
        )

        @partial(jax.jit, donate_argnums=0)
        def sc_step(s, i, mb=mb):
            return merge_batch(
                s,
                mb._replace(
                    added_nt=mb.added_nt + i,
                    taken_nt=mb.taken_nt + i,
                    elapsed_ns=mb.elapsed_ns + i,
                ),
            )

        per = time_fn(lambda s, i: sc_step(s, jnp.int64(i)), mk_state())
        print(
            f"{label:9s} xla-scatter {per * 1e3:9.3f} ms "
            f"{K / per / 1e6:8.2f} M-deltas/s (blocks {touched})"
        )

        if pallas_merge.native_available():
            # pallas path: host prep (sort+plan) is part of the cost in
            # production; measure device time with prep hoisted (prep is
            # ~1 ms numpy at K=65536, reported separately).
            t0 = time.perf_counter()
            order, block_ids, starts, ends, _ = pallas_merge.prepare(rows_np, B)
            prep_ms = (time.perf_counter() - t0) * 1e3

            def split_host(v):
                v = np.ascontiguousarray(v[order])
                return jnp.asarray(v.view(np.int32).reshape(len(v), 2))

            dargs = (
                jnp.asarray(block_ids),
                jnp.asarray(starts),
                jnp.asarray(ends),
                jnp.asarray(rows_np[order].astype(np.int32)),
                jnp.asarray(slots_np[order].astype(np.int32)),
                split_host(a_np),
                split_host(t_np),
                split_host(e_np),
            )

            def pal_step(s, i):
                # i is ignored: values identical each iter, but pallas_call
                # is opaque to the algebraic simplifier so the chain can't
                # collapse (verified: timing scales with n).
                return pallas_merge._merge_pallas_device(s, *dargs)

            per = time_fn(pal_step, mk_state())
            print(
                f"{label:9s} pallas      {per * 1e3:9.3f} ms "
                f"{K / per / 1e6:8.2f} M-deltas/s (prep {prep_ms:.1f} ms)"
            )


if __name__ == "__main__":
    main()
