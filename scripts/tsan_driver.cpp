// TSan exercise driver for patrol_host.cpp (the C++ host network path).
//
// The library is deliberately stateless (all state is per-fd kernel state
// or caller-owned buffers), but the production process calls it from
// multiple threads: the replication receive loop and the broadcast path
// share one socket fd, while encode/decode run on the engine feeder
// thread. This driver reproduces that concurrency shape — two senders,
// two receivers, and two codec threads hammering a loopback socket pair —
// so `-fsanitize=thread` can prove the no-shared-mutable-state claim.
//
// Reference concurrency bar: Go's `-race` on `go test ./...`
// (repo.go:13-14 documents the Repo thread-safety contract).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {
int pt_udp_open(const char* ip, uint16_t port);
int pt_udp_port(int fd);
void pt_udp_close(int fd);
int pt_recv_batch(int fd, uint8_t* buf, int max_packets, int row_stride,
                  int* sizes, uint32_t* ips, uint16_t* ports, int timeout_ms);
int pt_send_fanout(int fd, const uint8_t* payloads, const int* sizes, int n,
                   int row_stride, const uint32_t* peer_ips,
                   const uint16_t* peer_ports, int n_peers);
int pt_decode_batch(const uint8_t* packets, const int* sizes, int n,
                    int in_stride, double* added, double* taken,
                    uint64_t* elapsed,
                    uint8_t* names, int* name_lens, int* origin_slots,
                    int64_t* caps, int64_t* lane_added, int64_t* lane_taken,
                    uint64_t* name_hashes, int* multi_flags);
int pt_encode_batch(const double* added, const double* taken,
                    const uint64_t* elapsed, const uint8_t* names,
                    const int* name_lens, const int* origin_slots,
                    const int64_t* caps, const int64_t* lane_added,
                    const int64_t* lane_taken, int n,
                    uint8_t* out, int* out_sizes);
int pt_dir_create(int64_t capacity, const uint8_t* name_bytes,
                  const int32_t* name_lens);
int pt_dir_insert(int h, uint64_t hash, int32_t row);
int pt_dir_delete(int h, uint64_t hash, int32_t row);
int pt_dir_destroy(int h);
int64_t pt_rx_classify(int h, int n, const uint64_t* hashes,
                       const uint8_t* name_buf, const int32_t* lens,
                       const double* added_f, const double* taken_f,
                       const uint64_t* elapsed_u, const int64_t* slots_in,
                       int64_t max_slots, const int64_t* caps,
                       const int64_t* lane_a, const int64_t* lane_t,
                       const uint8_t* no_trailer, int64_t* cap_base,
                       int32_t* pins, int64_t* last_used, int64_t now,
                       int64_t* rows_out, int64_t* out_added,
                       int64_t* out_taken, int64_t* out_elapsed,
                       uint8_t* out_scalar);
}

static constexpr int PACKET = 256;
static constexpr int BATCH = 64;
static constexpr int ROUNDS = 200;

static uint64_t fnv1a(const uint8_t* b, int len) {
  uint64_t h = 0xcbf29ce484222325ULL;
  for (int i = 0; i < len; i++) {
    h ^= b[i];
    h *= 0x100000001b3ULL;
  }
  return h;
}

// Directory concurrency scenario: the production contract is that every
// pt_dir_* / pt_rx_classify call runs under ONE python-side mutex per
// directory (patrol_host.cpp "Thread safety" note), with the rx thread
// classifying while the engine thread binds/unbinds. Reproduce that shape
// — two threads alternating classify / insert+delete under a shared
// mutex — so TSan proves the lock is sufficient (and would catch any
// racy global the registry or the rolling classify pipeline introduced).
static void dir_scenario() {
  constexpr int CAP = 512;
  std::vector<uint8_t> name_bytes(static_cast<size_t>(CAP) * PACKET, 0);
  std::vector<int32_t> name_lens_v(CAP, 0);
  int h = pt_dir_create(CAP, name_bytes.data(), name_lens_v.data());

  std::vector<uint8_t> pkt_names(static_cast<size_t>(BATCH) * PACKET, 0);
  std::vector<int32_t> lens(BATCH);
  std::vector<uint64_t> hashes(BATCH);
  for (int i = 0; i < BATCH; i++) {
    char buf[32];
    int n = snprintf(buf, sizeof buf, "dir-%d", i);
    memcpy(&pkt_names[static_cast<size_t>(i) * PACKET], buf, n);
    memcpy(&name_bytes[static_cast<size_t>(i) * PACKET], buf, n);
    name_lens_v[i] = n;
    lens[i] = n;
    hashes[i] = fnv1a(reinterpret_cast<const uint8_t*>(buf), n);
  }
  std::mutex mu;  // ≙ BucketDirectory._mu
  {
    std::lock_guard<std::mutex> g(mu);
    for (int i = 0; i < BATCH; i++) pt_dir_insert(h, hashes[i], i);
  }

  std::atomic<bool> stop{false};
  auto classifier = [&]() {
    std::vector<double> added(BATCH, 1.5), taken(BATCH, 0.5);
    std::vector<uint64_t> elapsed(BATCH, 1000);
    std::vector<int64_t> slots(BATCH), caps(BATCH, -1), la(BATCH, -1),
        lt(BATCH, -1);
    for (int i = 0; i < BATCH; i++) slots[i] = i % 4;
    std::vector<uint8_t> no_tr(BATCH, 0);
    std::vector<int64_t> cap_base(CAP, 1000000000);
    std::vector<int32_t> pins(CAP, 0);
    std::vector<int64_t> last_used(CAP, 0);
    std::vector<int64_t> rows(BATCH), oa(BATCH), ot(BATCH), oe(BATCH);
    std::vector<uint8_t> os_(BATCH);
    for (int r = 0; r < ROUNDS; r++) {
      std::lock_guard<std::mutex> g(mu);
      pt_rx_classify(h, BATCH, hashes.data(), pkt_names.data(), lens.data(),
                     added.data(), taken.data(), elapsed.data(), slots.data(),
                     4, caps.data(), la.data(), lt.data(), no_tr.data(),
                     cap_base.data(), pins.data(), last_used.data(), r,
                     rows.data(), oa.data(), ot.data(), oe.data(), os_.data());
      for (int i = 0; i < BATCH; i++)
        if (rows[i] >= 0) pins[rows[i]]--;  // ≙ unpin after queueing
    }
    stop.store(true);
  };
  auto binder = [&]() {
    // Churn a disjoint row range: bind/unbind like eviction + re-assign.
    int row = BATCH;
    while (!stop.load()) {
      char buf[32];
      int n = snprintf(buf, sizeof buf, "churn-%d", row);
      uint64_t hv = fnv1a(reinterpret_cast<const uint8_t*>(buf), n);
      {
        std::lock_guard<std::mutex> g(mu);
        memcpy(&name_bytes[static_cast<size_t>(row) * PACKET], buf, n);
        name_lens_v[row] = n;
        pt_dir_insert(h, hv, row);
        pt_dir_delete(h, hv, row);
      }
      row = BATCH + (row - BATCH + 1) % (CAP - BATCH);
    }
  };
  std::thread t1(classifier), t2(binder);
  t1.join();
  t2.join();
  pt_dir_destroy(h);
}

int main() {
  dir_scenario();
  int tx = pt_udp_open("127.0.0.1", 0);
  int rx = pt_udp_open("127.0.0.1", 0);
  if (tx < 0 || rx < 0) {
    fprintf(stderr, "socket open failed\n");
    return 1;
  }
  uint32_t loop_ip = (127u << 24) | 1u;
  uint16_t rx_port = static_cast<uint16_t>(pt_udp_port(rx));

  std::atomic<long> received{0};
  std::atomic<bool> stop{false};

  auto sender = [&](int seed) {
    double added[BATCH], taken[BATCH];
    uint64_t elapsed[BATCH];
    uint8_t names[BATCH * PACKET];
    int name_lens[BATCH], slots[BATCH], sizes[BATCH];
    int64_t caps[BATCH], lane_a[BATCH], lane_t[BATCH];
    uint8_t out[BATCH * PACKET];
    for (int r = 0; r < ROUNDS && !stop.load(); ++r) {
      for (int i = 0; i < BATCH; ++i) {
        added[i] = seed + i + r * 0.5;
        taken[i] = i * 0.25;
        elapsed[i] = static_cast<uint64_t>(r) * 1000 + i;
        int n = snprintf(reinterpret_cast<char*>(names + i * PACKET), PACKET,
                         "bucket-%d-%d", seed, i);
        name_lens[i] = n;
        slots[i] = i & 0xFF;
        // Mix the three trailer forms across the batch.
        caps[i] = (i % 3 == 0) ? -1 : 1000000000LL * (i + 1);
        lane_a[i] = (i % 3 == 2) ? 500000000LL * i : -1;
        lane_t[i] = (i % 3 == 2) ? 250000000LL * i : -1;
      }
      pt_encode_batch(added, taken, elapsed, names, name_lens, slots, caps,
                      lane_a, lane_t, BATCH, out, sizes);
      pt_send_fanout(tx, out, sizes, BATCH, PACKET, &loop_ip, &rx_port, 1);
    }
  };

  auto receiver = [&]() {
    uint8_t buf[BATCH * PACKET];
    int sizes[BATCH];
    uint32_t ips[BATCH];
    uint16_t ports[BATCH];
    double added[BATCH], taken[BATCH];
    uint64_t elapsed[BATCH];
    uint8_t names[BATCH * PACKET];
    int name_lens[BATCH], slots[BATCH];
    int64_t caps[BATCH], lane_a[BATCH], lane_t[BATCH];
    uint64_t hashes[BATCH];
    int multi[BATCH];
    while (!stop.load()) {
      int n = pt_recv_batch(rx, buf, BATCH, PACKET, sizes, ips, ports, 50);
      if (n <= 0) continue;
      pt_decode_batch(buf, sizes, n, PACKET, added, taken, elapsed, names,
                      name_lens, slots, caps, lane_a, lane_t, hashes, multi);
      received.fetch_add(n);
    }
  };

  std::vector<std::thread> threads;
  threads.emplace_back(receiver);
  threads.emplace_back(receiver);
  threads.emplace_back(sender, 1);
  threads.emplace_back(sender, 2);
  for (int i = 2; i < 4; ++i) threads[i].join();
  // drain, then stop receivers
  std::this_thread::sleep_for(std::chrono::milliseconds(200));
  stop.store(true);
  threads[0].join();
  threads[1].join();
  pt_udp_close(tx);
  pt_udp_close(rx);
  printf("tsan driver ok: %ld packets received\n", received.load());
  return 0;
}
