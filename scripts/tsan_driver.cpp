// TSan exercise driver for patrol_host.cpp (the C++ host network path).
//
// The library is deliberately stateless (all state is per-fd kernel state
// or caller-owned buffers), but the production process calls it from
// multiple threads: the replication receive loop and the broadcast path
// share one socket fd, while encode/decode run on the engine feeder
// thread. This driver reproduces that concurrency shape — two senders,
// two receivers, and two codec threads hammering a loopback socket pair —
// so `-fsanitize=thread` can prove the no-shared-mutable-state claim.
//
// Reference concurrency bar: Go's `-race` on `go test ./...`
// (repo.go:13-14 documents the Repo thread-safety contract).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
int pt_udp_open(const char* ip, uint16_t port);
int pt_udp_port(int fd);
void pt_udp_close(int fd);
int pt_recv_batch(int fd, uint8_t* buf, int max_packets, int* sizes,
                  uint32_t* ips, uint16_t* ports, int timeout_ms);
int pt_send_fanout(int fd, const uint8_t* payloads, const int* sizes, int n,
                   const uint32_t* peer_ips, const uint16_t* peer_ports,
                   int n_peers);
int pt_decode_batch(const uint8_t* packets, const int* sizes, int n,
                    double* added, double* taken, uint64_t* elapsed,
                    uint8_t* names, int* name_lens, int* origin_slots,
                    int64_t* caps, int64_t* lane_added, int64_t* lane_taken,
                    uint64_t* name_hashes, int* multi_flags);
int pt_encode_batch(const double* added, const double* taken,
                    const uint64_t* elapsed, const uint8_t* names,
                    const int* name_lens, const int* origin_slots,
                    const int64_t* caps, const int64_t* lane_added,
                    const int64_t* lane_taken, int n,
                    uint8_t* out, int* out_sizes);
}

static constexpr int PACKET = 256;
static constexpr int BATCH = 64;
static constexpr int ROUNDS = 200;

int main() {
  int tx = pt_udp_open("127.0.0.1", 0);
  int rx = pt_udp_open("127.0.0.1", 0);
  if (tx < 0 || rx < 0) {
    fprintf(stderr, "socket open failed\n");
    return 1;
  }
  uint32_t loop_ip = (127u << 24) | 1u;
  uint16_t rx_port = static_cast<uint16_t>(pt_udp_port(rx));

  std::atomic<long> received{0};
  std::atomic<bool> stop{false};

  auto sender = [&](int seed) {
    double added[BATCH], taken[BATCH];
    uint64_t elapsed[BATCH];
    uint8_t names[BATCH * PACKET];
    int name_lens[BATCH], slots[BATCH], sizes[BATCH];
    int64_t caps[BATCH], lane_a[BATCH], lane_t[BATCH];
    uint8_t out[BATCH * PACKET];
    for (int r = 0; r < ROUNDS && !stop.load(); ++r) {
      for (int i = 0; i < BATCH; ++i) {
        added[i] = seed + i + r * 0.5;
        taken[i] = i * 0.25;
        elapsed[i] = static_cast<uint64_t>(r) * 1000 + i;
        int n = snprintf(reinterpret_cast<char*>(names + i * PACKET), PACKET,
                         "bucket-%d-%d", seed, i);
        name_lens[i] = n;
        slots[i] = i & 0xFF;
        // Mix the three trailer forms across the batch.
        caps[i] = (i % 3 == 0) ? -1 : 1000000000LL * (i + 1);
        lane_a[i] = (i % 3 == 2) ? 500000000LL * i : -1;
        lane_t[i] = (i % 3 == 2) ? 250000000LL * i : -1;
      }
      pt_encode_batch(added, taken, elapsed, names, name_lens, slots, caps,
                      lane_a, lane_t, BATCH, out, sizes);
      pt_send_fanout(tx, out, sizes, BATCH, &loop_ip, &rx_port, 1);
    }
  };

  auto receiver = [&]() {
    uint8_t buf[BATCH * PACKET];
    int sizes[BATCH];
    uint32_t ips[BATCH];
    uint16_t ports[BATCH];
    double added[BATCH], taken[BATCH];
    uint64_t elapsed[BATCH];
    uint8_t names[BATCH * PACKET];
    int name_lens[BATCH], slots[BATCH];
    int64_t caps[BATCH], lane_a[BATCH], lane_t[BATCH];
    uint64_t hashes[BATCH];
    int multi[BATCH];
    while (!stop.load()) {
      int n = pt_recv_batch(rx, buf, BATCH, sizes, ips, ports, 50);
      if (n <= 0) continue;
      pt_decode_batch(buf, sizes, n, added, taken, elapsed, names, name_lens,
                      slots, caps, lane_a, lane_t, hashes, multi);
      received.fetch_add(n);
    }
  };

  std::vector<std::thread> threads;
  threads.emplace_back(receiver);
  threads.emplace_back(receiver);
  threads.emplace_back(sender, 1);
  threads.emplace_back(sender, 2);
  for (int i = 2; i < 4; ++i) threads[i].join();
  // drain, then stop receivers
  std::this_thread::sleep_for(std::chrono::milliseconds(200));
  stop.store(true);
  threads[0].join();
  threads[1].join();
  pt_udp_close(tx);
  pt_udp_close(rx);
  printf("tsan driver ok: %ld packets received\n", received.load());
  return 0;
}
