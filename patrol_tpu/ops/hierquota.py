"""Hierarchical quotas (global -> tenant -> user) as a lattice path debit.

A hierarchical quota admits a request only if EVERY level of its path
has budget: the user's own allowance, the tenant's aggregate, and the
global pool. Each level is one ordinary ``LimiterState`` row whose own
``TAKEN`` lane counts this node's debits (a monotone G-counter; the
``ADDED`` lane stays zero — quota budgets are configuration, carried in
the request, not lattice state). Spend at level L is the sum of TAKEN
lanes of L's row, so rows join with the existing per-lane max merge
kernels and replicate over the v2 delta plane unchanged.

The kernel takes the whole path in ONE packed dispatch: gather the
three levels' rows, admit ``k = clip(min_level(headroom) // count, 0,
nreq)``, and debit all three own TAKEN lanes with a single [3K]-row
scatter-add — one device call per microbatch, not one per level (TPU
scatter cost is per update; fusing the path keeps the quota take the
same dispatch count as the flat bucket take).

The family-specific CRDT hazard is the *partial debit*: admitting
against only the leaf (or debiting only the leaf) lets a tenant's users
collectively exceed the tenant or global budget the moment the path
limits are not all equal — and with monotone lanes the overspend can
never be unwound. The protocol model's ``QuotaLaws`` checks per-level
conservation (admitted <= level-limit x partition-sides for EVERY
level); the leaf-only variants are the family's seeded cert mutations.

AP bound under partition: same shape as the bucket, per level — S sides
can each spend up to the path minimum, so any level's spend is at most
``S x its limit``.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from patrol_tpu.models.limiter import TAKEN, LimiterState

# Path depth is fixed: global -> tenant -> user. Weighted/deeper trees
# are a follow-up family, not a runtime knob — the packed layout and the
# protocol model's lane shapes are sized by this constant.
QUOTA_LEVELS = 3

# Packed-transfer layout, same staging contract as ops/take.py.
QUOTA_PACK_ROWS = 8
QUOTA_RESULT_ROWS = 5


class QuotaRequest(NamedTuple):
    """A microbatch of K path takes. Leading dim K; the three row
    vectors address the path's levels (rows of the SAME state planes);
    ``rows_user`` are unique among live rows, and distinct paths sharing
    a tenant/global row coalesce correctly under scatter-add. Padding
    rows have ``nreq == 0`` and commit nothing."""

    rows_global: jax.Array  # int32[K] global-pool row
    rows_tenant: jax.Array  # int32[K] tenant row
    rows_user: jax.Array  # int32[K] user (leaf) row
    limit_global_nt: jax.Array  # int64[K] global budget
    limit_tenant_nt: jax.Array  # int64[K] tenant budget
    limit_user_nt: jax.Array  # int64[K] user budget
    count_nt: jax.Array  # int64[K] units per request
    nreq: jax.Array  # int64[K] identical requests coalesced


class QuotaResult(NamedTuple):
    """Per-row outcome; per-level headrooms are post-commit."""

    admitted: jax.Array  # int64[K] requests granted
    headroom_global_nt: jax.Array  # int64[K]
    headroom_tenant_nt: jax.Array  # int64[K]
    headroom_user_nt: jax.Array  # int64[K]
    own_taken_user_nt: jax.Array  # int64[K] leaf own lane (wire trailer)


def quota_take_batch(
    state: LimiterState, req: QuotaRequest, node_slot: int
) -> tuple[LimiterState, QuotaResult]:
    """Pure function: admit a microbatch of hierarchical-quota takes,
    return new state + results.

    Admission is the path minimum — every level must afford ALL k
    admitted requests — and the debit is all-or-nothing across levels:
    the three own-lane deltas are identical (``k * count``) and commit
    in one packed scatter, so no interleaving (and no partial failure
    inside the kernel) can ever record a leaf debit without its
    ancestors'.
    """
    rows = jnp.concatenate([req.rows_global, req.rows_tenant, req.rows_user])
    pn_rows = state.pn[rows]  # [3K, N, 2] gather, one call for the path
    spend = pn_rows[:, :, TAKEN].sum(axis=-1)  # [3K]
    k_batch = req.rows_user.shape[0]
    spend_g = spend[:k_batch]
    spend_t = spend[k_batch : 2 * k_batch]
    spend_u = spend[2 * k_batch :]

    head_g = req.limit_global_nt - spend_g
    head_t = req.limit_tenant_nt - spend_t
    head_u = req.limit_user_nt - spend_u
    head_min = jnp.minimum(jnp.minimum(head_g, head_t), head_u)

    safe_count = jnp.where(req.count_nt <= 0, 1, req.count_nt)
    k = jnp.clip(head_min // safe_count, 0, req.nreq)
    k = jnp.where(req.count_nt > 0, k, 0)
    d = k * req.count_nt  # identical debit at every level

    # One packed scatter for the whole path: [3K] updates on the own
    # TAKEN lane. A tenant/global row shared by several live requests
    # accumulates correctly under scatter-add (each path admitted
    # against the pre-tick sums — the coalescing batcher keeps
    # same-tenant bursts in one row when exactness matters, the same
    # contract as duplicate bucket rows in ops/take.py).
    debit = jnp.concatenate([d, d, d])
    pn = state.pn.at[rows, node_slot, TAKEN].add(debit)

    result = QuotaResult(
        admitted=k,
        headroom_global_nt=head_g - d,
        headroom_tenant_nt=head_t - d,
        headroom_user_nt=head_u - d,
        own_taken_user_nt=pn_rows[2 * k_batch :, node_slot, TAKEN] + d,
    )
    return LimiterState(pn=pn, elapsed=state.elapsed), result


quota_take_batch_jit = partial(
    jax.jit, static_argnames=("node_slot",), donate_argnums=0
)(quota_take_batch)
