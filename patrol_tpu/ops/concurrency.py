"""In-flight concurrency limits as paired PN-counter lanes.

A concurrency limiter bounds how many requests are *simultaneously*
held, not how fast they arrive: ``acquire`` takes a unit while
``inflight < limit``, ``release`` returns it. On the shared
``LimiterState`` planes the two operations are the bucket algebra read
backwards: the ``TAKEN`` lane counts this node's acquires, the
``ADDED`` lane counts its releases, both monotone G-counters, and

    inflight = sum(TAKEN lanes) - sum(ADDED lanes)

so the state joins with the existing per-lane max merge kernels and
rides the v2 delta plane unchanged. (The bucket's ``node.refill()``
at-capacity refusal is exactly this family's "never release more than
was acquired" clamp under the add<->release renaming — the
linearizability reduction ``analysis/linearizability.py`` documents.)

The CRDT hazard specific to this family is the *phantom release*: a
release applied to a replica that has not yet seen the matching acquire
would drive its ADDED lane past its TAKEN lane, and after convergence
the cluster would believe capacity was returned that was never held —
``inflight`` goes negative and the limiter over-admits forever (the
lanes are monotone; the error can never be unwound). The kernel
therefore clamps releases **per own lane**: a node may only release
what it has itself acquired (``ADDED[slot] <= TAKEN[slot]`` is a kernel
invariant, checked by the protocol model's ``ConcLaws`` and seeded as a
cert mutation).

Under partition the AP bound mirrors the bucket's: each side can hold
up to ``limit`` concurrently, so S sides hold at most ``S x limit`` —
PTC003-shaped, checked by ``check_conc_protocol``.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from patrol_tpu.models.limiter import ADDED, TAKEN, LimiterState

# Packed-transfer layout, same staging contract as ops/take.py.
CONC_PACK_ROWS = 5
CONC_RESULT_ROWS = 6


class ConcRequest(NamedTuple):
    """A microbatch of K acquire/release ticks. Leading dim K; rows are
    unique among live rows; padding rows have ``nreq == releases == 0``
    and commit nothing. Releases apply BEFORE acquires (a tick that
    returns a slot and claims a new one must not self-starve)."""

    rows: jax.Array  # int32[K] bucket-slot indices
    limit_nt: jax.Array  # int64[K] max in-flight units
    count_nt: jax.Array  # int64[K] units per acquire (NANO-scaled)
    nreq: jax.Array  # int64[K] acquires coalesced into this row
    releases: jax.Array  # int64[K] releases (of count_nt units each)


class ConcResult(NamedTuple):
    """Per-row outcome; own lanes post-commit feed the wire trailer."""

    admitted: jax.Array  # int64[K] acquires granted
    released_nt: jax.Array  # int64[K] units actually released (post-clamp)
    inflight_nt: jax.Array  # int64[K] cluster-visible in-flight post-commit
    own_acquired_nt: jax.Array  # int64[K] own TAKEN lane post-commit
    own_released_nt: jax.Array  # int64[K] own ADDED lane post-commit
    clamped_nt: jax.Array  # int64[K] release units refused by the clamp


def conc_acquire_batch(
    state: LimiterState, req: ConcRequest, node_slot: int
) -> tuple[LimiterState, ConcResult]:
    """Pure function: apply a microbatch of release-then-acquire ticks,
    return new state + results.

    Releases clamp against the OWN lane pair — ``min(requested,
    own_taken - own_added)`` — never against the cluster sums: a remote
    node's acquires are not ours to return, and the clamp is what keeps
    ``ADDED[slot] <= TAKEN[slot]`` a per-lane invariant every replica
    can verify locally after any join. Acquires then admit greedily
    against the post-release in-flight sum, same coalesced-row shape as
    the bucket take (``k = clip(headroom // count, 0, nreq)``).
    """
    i64 = jnp.int64
    rows = req.rows

    pn_rows = state.pn[rows]  # [K, N, 2] gather
    own_added = pn_rows[:, node_slot, ADDED]
    own_taken = pn_rows[:, node_slot, TAKEN]
    sum_added = pn_rows[:, :, ADDED].sum(axis=-1)
    sum_taken = pn_rows[:, :, TAKEN].sum(axis=-1)

    # Release-without-acquire clamp (the phantom-release guard).
    want_rel = jnp.maximum(req.releases, i64(0)) * jnp.maximum(
        req.count_nt, i64(0)
    )
    held_own = jnp.maximum(own_taken - own_added, i64(0))
    d_rel = jnp.minimum(want_rel, held_own)

    inflight = sum_taken - (sum_added + d_rel)
    headroom = req.limit_nt - inflight
    safe_count = jnp.where(req.count_nt <= 0, 1, req.count_nt)
    k = jnp.clip(headroom // safe_count, 0, req.nreq)
    k = jnp.where(req.count_nt > 0, k, 0)
    d_acq = k * req.count_nt

    # One scatter of (ADDED, TAKEN) pairs, like the bucket take commit.
    pair = jnp.stack([d_rel, d_acq], axis=-1)
    pn = state.pn.at[rows, node_slot].add(pair)

    result = ConcResult(
        admitted=k,
        released_nt=d_rel,
        inflight_nt=inflight + d_acq,
        own_acquired_nt=own_taken + d_acq,
        own_released_nt=own_added + d_rel,
        clamped_nt=want_rel - d_rel,
    )
    return LimiterState(pn=pn, elapsed=state.elapsed), result


conc_acquire_batch_jit = partial(
    jax.jit, static_argnames=("node_slot",), donate_argnums=0
)(conc_acquire_batch)
