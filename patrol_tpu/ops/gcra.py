"""GCRA / sliding-window rate limiting as a max-lattice register kernel.

The Generic Cell Rate Algorithm keeps ONE scalar per limited flow — the
Theoretical Arrival Time (TAT). A request arriving at ``now`` conforms
iff ``TAT <= now + tol`` (``tol`` = the burst tolerance, canonically
``(burst-1) * T`` for emission interval ``T``); on admission the TAT
advances to ``max(TAT, now) + T``. Unlike the token bucket there is no
refill arithmetic at all: the whole limiter is the monotone scalar.

That scalar is a *max-register lattice*, which makes the distributed
story free: each node stores its own TAT watermark in its own
``TAKEN`` PN lane of the shared ``LimiterState`` (the ``ADDED`` lane
stays zero), the effective TAT is the max over all lanes, and the join
is the per-lane elementwise max the existing merge/delta kernels
already compute. A GCRA row therefore replicates over the v2 delta
plane, anti-entropy, and the mesh tree-converge **unchanged** —
certification reuses PTP001's scatter-max allowlist as-is.

Semantics under partition mirror the bucket's AP bound: each side
admits against the TAT it can see, so a 2-side partition admits at most
2x the conforming burst — the PTC003-shaped bound the protocol model
(``analysis/protocol.py::GcraLaws``) checks for this family.

Units: TAT and ``now`` are clock nanoseconds (the injected-clock seam),
not nanotokens; the lanes stay int64 either way.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from patrol_tpu.models.limiter import TAKEN, LimiterState

# Packed-transfer layout (engine extension dispatch): one
# int64[GCRA_PACK_ROWS, K] request matrix in, one
# int64[GCRA_RESULT_ROWS, K] result matrix out, fixed shapes per padded
# K so staging buffers recycle across ticks (same contract as
# ops/take.py's TAKE_PACK_ROWS).
GCRA_PACK_ROWS = 5
GCRA_RESULT_ROWS = 4


class GcraRequest(NamedTuple):
    """A microbatch of K GCRA conformance tests. Leading dim K; rows are
    unique among rows with ``nreq > 0`` (identical requests coalesce
    into ``nreq``); padding rows have ``nreq == 0`` and commit nothing."""

    rows: jax.Array  # int32[K] bucket-slot indices
    now_ns: jax.Array  # int64[K] request clock (injected-clock seam)
    emission_ns: jax.Array  # int64[K] T: nanoseconds per admitted request
    tol_ns: jax.Array  # int64[K] tau: burst tolerance window
    nreq: jax.Array  # int64[K] identical requests coalesced into this row


class GcraResult(NamedTuple):
    """Per-row outcome. ``allow_at_ns`` is the earliest clock at which
    the NEXT request conforms (TAT - tol) — the Retry-After seed."""

    admitted: jax.Array  # int64[K] how many of nreq conformed
    tat_ns: jax.Array  # int64[K] global TAT (max over lanes) post-commit
    own_tat_ns: jax.Array  # int64[K] this node's lane post-commit (trailer)
    allow_at_ns: jax.Array  # int64[K] earliest conforming arrival


def gcra_take_batch(
    state: LimiterState, req: GcraRequest, node_slot: int
) -> tuple[LimiterState, GcraResult]:
    """Pure function: admit a microbatch of GCRA requests, return new
    state + results.

    Sequential semantics per row (what the admitted count reproduces):
    request 0 conforms iff ``tat <= now + tol``; each admission advances
    a virtual TAT ``base = max(tat, now)`` by ``T``, and request j
    (1-based extras) conforms iff ``base + j*T <= now + tol``. So
    ``k = min(1 + (now + tol - base) // T, nreq)`` when request 0
    conforms, else 0 — the greedy coalesced-row admission, same shape as
    the bucket take's ``have // count``.

    The commit is a scatter-**max** of the own lane to ``base + k*T``:
    strictly monotone (k >= 1 implies the new watermark exceeds the old
    own-lane value is NOT guaranteed when a remote lane carries the max,
    so max-commit rather than assignment keeps the lane a G-register
    even then), idempotent for padding rows, and exactly the join the
    replication plane applies on the receive side.
    """
    i64 = jnp.int64
    rows = req.rows

    pn_rows = state.pn[rows]  # [K, N, 2] gather
    own_tat = pn_rows[:, node_slot, TAKEN]
    tat = pn_rows[:, :, TAKEN].max(axis=-1)  # global view: max over lanes

    base = jnp.maximum(tat, req.now_ns)
    deadline = req.now_ns + req.tol_ns
    conforms = tat <= deadline

    safe_t = jnp.where(req.emission_ns <= 0, 1, req.emission_ns)
    extras = jnp.maximum(deadline - base, i64(0)) // safe_t
    k = jnp.where(conforms, 1 + extras, 0)
    k = jnp.where(req.emission_ns > 0, k, 0)
    k = jnp.clip(k, 0, req.nreq)

    new_own = jnp.where(k >= 1, base + k * req.emission_ns, own_tat)
    pn = state.pn.at[rows, node_slot, TAKEN].max(new_own)

    tat_out = jnp.maximum(tat, new_own)
    result = GcraResult(
        admitted=k,
        tat_ns=tat_out,
        own_tat_ns=jnp.maximum(own_tat, new_own),
        allow_at_ns=tat_out - req.tol_ns,
    )
    return LimiterState(pn=pn, elapsed=state.elapsed), result


gcra_take_batch_jit = partial(
    jax.jit, static_argnames=("node_slot",), donate_argnums=0
)(gcra_take_batch)
