"""Pure computational ops: rate algebra, wire codec, take/merge kernels."""
