"""Rate algebra: parsing, unit conversion, formatting.

Parity target: the reference's ``Rate`` (bucket.go:96-153) — a frequency per
duration, parsed from ``"freq:duration"`` strings with bare-unit shorthand
(``"s"`` → ``"1s"``, bucket.go:116-119), converted to tokens via
``float64(d) / float64(interval)`` where ``interval`` is the *truncating*
int64 division ``per / freq`` (bucket.go:146-148).

Durations are represented as integer nanoseconds throughout (Go
``time.Duration`` is an int64 nanosecond count), so that device kernels and
the wire codec share exact semantics with this host-side algebra.
"""

from __future__ import annotations

import dataclasses

NANOS_PER_SECOND = 1_000_000_000

# Unit table of Go time.ParseDuration. Both MICRO SIGN (µ) and GREEK SMALL
# LETTER MU (μ) spell microseconds, as in Go's unitMap.
_UNITS = {
    "ns": 1,
    "us": 1_000,
    "µs": 1_000,  # µs
    "μs": 1_000,  # μs
    "ms": 1_000_000,
    "s": 1_000_000_000,
    "m": 60 * 1_000_000_000,
    "h": 3600 * 1_000_000_000,
}

# Bare units accepted as "1<unit>" shorthand by ParseRate (bucket.go:116-119).
# Note the reference's list includes µs but not μs.
_BARE_UNITS = ("ns", "us", "µs", "ms", "s", "m", "h")

_INT64_MAX = (1 << 63) - 1
_INT64_MIN = -(1 << 63)


def parse_duration(s: str) -> int:
    """Parse a Go-style duration string into integer nanoseconds.

    Mirrors Go ``time.ParseDuration`` (used at bucket.go:121): an optional
    sign, then one or more ``<decimal><unit>`` segments, e.g. ``"1.5h"``,
    ``"2h45m"``, ``"300ms"``. ``"0"`` alone is allowed; a bare number without
    a unit is not.
    """
    orig = s
    neg = False
    if s[:1] in ("+", "-"):
        neg = s[0] == "-"
        s = s[1:]
    if s == "0":
        return 0
    if not s:
        raise ValueError(f"invalid duration {orig!r}")

    total = 0
    while s:
        i = 0
        while i < len(s) and s[i].isascii() and s[i].isdigit():
            i += 1
        int_part, s = s[:i], s[i:]
        frac_part = ""
        if s[:1] == ".":
            s = s[1:]
            j = 0
            while j < len(s) and s[j].isascii() and s[j].isdigit():
                j += 1
            frac_part, s = s[:j], s[j:]
        if not int_part and not frac_part:
            raise ValueError(f"invalid duration {orig!r}")

        unit = next(
            (u for u in sorted(_UNITS, key=len, reverse=True) if s.startswith(u)),
            None,
        )
        if unit is None:
            raise ValueError(f"missing unit in duration {orig!r}")
        s = s[len(unit) :]
        scale = _UNITS[unit]

        total += int(int_part or 0) * scale
        if frac_part:
            # Exact rational scaling, truncated — matches Go's accumulation
            # of fractional digits against the unit scale.
            total += int(frac_part) * scale // 10 ** len(frac_part)
        if total > _INT64_MAX:
            raise ValueError(f"duration {orig!r} overflows int64")

    return -total if neg else total


def format_duration(ns: int) -> str:
    """Format integer nanoseconds the way Go ``time.Duration.String`` does.

    Examples: ``0 → "0s"``, ``1500 → "1.5µs"``, ``90e9 → "1m30s"``.
    """
    if ns == 0:
        return "0s"
    neg = ns < 0
    u = -ns if neg else ns
    if u < NANOS_PER_SECOND:
        if u < 1_000:
            out = f"{u}ns"
        elif u < 1_000_000:
            out = _with_frac(u, 1_000, "µs")
        else:
            out = _with_frac(u, 1_000_000, "ms")
    else:
        secs, frac = divmod(u, NANOS_PER_SECOND)
        out = _with_frac(secs % 60 * NANOS_PER_SECOND + frac, NANOS_PER_SECOND, "s")
        mins = secs // 60
        if mins > 0:
            out = f"{mins % 60}m{out}"
            hours = mins // 60
            if hours > 0:
                out = f"{hours}h{out}"
    return ("-" if neg else "") + out


def _with_frac(value: int, scale: int, unit: str) -> str:
    whole, frac = divmod(value, scale)
    if frac == 0:
        return f"{whole}{unit}"
    digits = str(frac).rjust(len(str(scale)) - 1, "0").rstrip("0")
    return f"{whole}.{digits}{unit}"


def _atoi(s: str) -> int:
    """Go ``strconv.Atoi``: optional sign, ASCII digits, int64 range."""
    body = s[1:] if s[:1] in ("+", "-") else s
    if not body or not body.isascii() or not body.isdigit():
        raise ValueError(f"parsing {s!r}: invalid syntax")
    v = int(s)
    if not _INT64_MIN <= v <= _INT64_MAX:
        raise ValueError(f"parsing {s!r}: value out of range")
    return v


@dataclasses.dataclass(frozen=True)
class Rate:
    """Maximum frequency of events: ``freq`` events per ``per_ns`` nanoseconds.

    A zero Rate (either field zero) allows no events (bucket.go:125-128).
    """

    freq: int = 0
    per_ns: int = 0

    def is_zero(self) -> bool:
        return self.freq == 0 or self.per_ns == 0

    def interval_ns(self) -> int:
        """Interval between events: truncating int64 division per/freq.

        Mirrors bucket.go:146-148 where both operands are int64 and Go's
        division truncates toward zero.
        """
        q = abs(self.per_ns) // abs(self.freq)
        return -q if (self.per_ns < 0) != (self.freq < 0) else q

    def tokens(self, d_ns: int) -> float:
        """Tokens accumulable over ``d_ns`` nanoseconds (bucket.go:130-143)."""
        if self.is_zero():
            return 0.0
        interval = self.interval_ns()
        if interval == 0:
            return 0.0
        return float(d_ns) / float(interval)

    def __str__(self) -> str:
        return f"{self.freq}:{format_duration(self.per_ns)}"


def parse_rate(v: str) -> Rate:
    """Parse ``"freq:duration"`` (e.g. ``"50:1s"``) into a Rate.

    Mirrors ``ParseRate`` (bucket.go:101-123): a missing duration defaults to
    ``"1s"``; a bare unit in the duration position is prefixed with ``"1"``.
    Raises ValueError on malformed input — callers that want the reference
    API's silently-ignored-error behavior (api.go:61) catch and use ``Rate()``.
    """
    parts = v.split(":", 1)
    if len(parts) == 1:
        parts.append("1s")
    freq = _atoi(parts[0])
    per = parts[1]
    if per in _BARE_UNITS:
        per = "1" + per
    return Rate(freq=freq, per_ns=parse_duration(per))
