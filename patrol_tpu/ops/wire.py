"""Wire codec: the reference's 25-byte-header / ≤256-byte UDP packet format.

Byte layout (bucket.go:34-91):

====  =====  =====================================================
off   size   field
====  =====  =====================================================
0     8      added, big-endian IEEE-754 float64 (tokens)
8     8      taken, big-endian IEEE-754 float64 (tokens)
16    8      elapsed, big-endian uint64 (nanoseconds, two's compl.)
24    1      name length L (≤ 231)
25    L      name bytes
====  =====  =====================================================

``created`` is deliberately NOT serialized (bucket.go:28-31): only relative
elapsed time crosses the wire, which is what makes the protocol clock-skew
independent (README.md:49-62).

This module adds a *backward-compatible* v2 extension: because the reference
decoder reads exactly ``data[25:25+L]`` and ignores any trailing bytes, we
may append a trailer carrying patrol_tpu metadata. Reference nodes
interoperate unchanged; patrol_tpu nodes use it to address the sender's
PN-counter lane. Three trailer forms (``flags`` bits select):

* base (6 B):     ``b"P2" | u8 flags=0 | u16 slot | u8 checksum``
* with-cap (14B): ``b"P2" | u8 flags=1 | u16 slot | u64 cap_nt | u8 checksum``
* lane (30 B):    ``b"P2" | u8 flags=3 | u16 slot | u64 cap_nt |``
  ``u64 lane_added_nt | u64 lane_taken_nt | u8 checksum``

(checksum = sum of the preceding trailer bytes mod 256, a guard against a
name that happens to end in "P2").

Mixed-cluster interop hinges on the **dual payload**: the float64 header
``added``/``taken`` carry the sender's *aggregate scalar view* of the bucket
(capacity-included, like the reference's ``bucket.added`` after lazy init,
bucket.go:194-196) — exactly the full-state scalars a reference node
max-merges — while the trailer carries the sender's *exact own-lane*
PN-counter values in int64 nanotokens for patrol_tpu receivers. Without the
aggregate header, a reference peer max-merging our lane-only ``taken``
against its global scalar would lose takes; without the lane trailer,
patrol_tpu peers would double-count echoed aggregates. ``cap_nt`` is the
sender's lazily-initialized capacity base, which receivers adopt for rows
whose capacity is still unknown.

The device state is int64 nanotokens; the wire is float64 tokens — this codec
is the conversion boundary. float64 represents integers exactly up to 2^53,
i.e. ~9.0e6 tokens at nanotoken resolution; beyond that the wire value is
rounded (observable semantics are preserved within float64's own precision,
which is all the reference ever had).
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Optional

NANO = 1_000_000_000

FIXED_SIZE = 25  # 8 + 8 + 8 + 1 (bucket.go:36)
PACKET_SIZE = 256  # no-fragmentation bound (bucket.go:38-41)
MAX_NAME_LENGTH = PACKET_SIZE - FIXED_SIZE - 30  # room for the lane trailer
MAX_NAME_LENGTH_V1 = PACKET_SIZE - FIXED_SIZE  # the reference's 231 (bucket.go:43-44)

_HEADER = struct.Struct(">ddQ")
_TRAILER = struct.Struct(">2sBHB")
_TRAILER_CAP = struct.Struct(">2sBHQB")
_TRAILER_LANE = struct.Struct(">2sBHQQQB")
_TRAILER_MAGIC = b"P2"
_FLAG_CAP = 0x01
_FLAG_LANE = 0x02
TRAILER_SIZE = _TRAILER.size
TRAILER_CAP_SIZE = _TRAILER_CAP.size
TRAILER_LANE_SIZE = _TRAILER_LANE.size


class NameTooLargeError(ValueError):
    """Bucket name exceeds the wire limit (bucket.go:46-48)."""

    def __init__(self, limit: int = MAX_NAME_LENGTH_V1) -> None:
        super().__init__(f"bucket name larger than {limit}")


class ShortBufferError(ValueError):
    """Packet shorter than its self-described size (bucket.go:72-74,83-85)."""


@dataclasses.dataclass(frozen=True)
class WireState:
    """One bucket state as it crosses the wire."""

    name: str
    added: float  # tokens (float64, as on the wire): the sender's AGGREGATE
    # scalar view, capacity-included — what a reference node max-merges
    taken: float
    elapsed_ns: int  # signed int64 nanoseconds
    origin_slot: Optional[int] = None  # v2 trailer; None for v1 packets
    cap_nt: Optional[int] = None  # sender's capacity base (nanotokens);
    # None on v1 / base-trailer packets — the receiver then falls back to
    # scalar (reference) merge semantics for this delta
    lane_added_nt: Optional[int] = None  # exact own-lane PN values (grants-
    lane_taken_nt: Optional[int] = None  # only, nanotokens); lane trailer

    def is_zero(self) -> bool:
        """The incast-request marker (bucket.go:163-170, repo.go:78-90)."""
        return self.added == 0 and self.taken == 0 and self.elapsed_ns == 0

    @property
    def added_nt(self) -> int:
        return _sanitize_nt(self.added)

    @property
    def taken_nt(self) -> int:
        return _sanitize_nt(self.taken)


_INT64_MAX = (1 << 63) - 1


def _sanitize_nt(tokens: float) -> int:
    """float64 wire value → int64 nanotokens, hardened against hostile
    packets: NaN → 0, ±Inf / out-of-range clamp to the int64 edge, negatives
    clamp to 0 (device state is a non-negative G-counter pair). The float64
    reference absorbs such values silently (bucket.go:78-79); the int64
    device path must not crash on them."""
    if tokens != tokens:  # NaN
        return 0
    if tokens <= 0.0:
        return 0
    nt = tokens * NANO
    if nt >= _INT64_MAX:
        return _INT64_MAX
    return round(nt)


def sanitize_nt_array(tokens) -> "np.ndarray":
    """Vectorized :func:`_sanitize_nt` for the batch rx path: float64[n]
    wire tokens → int64[n] nanotokens with identical NaN/Inf/range/negative
    hardening (round-half-even like Python's round). Bit-identical to the
    scalar form on every input — native-rx and python-rx peers MUST merge
    the same packet to the same state or replicas diverge permanently."""
    import numpy as np

    t = np.asarray(tokens, dtype=np.float64)
    with np.errstate(over="ignore", invalid="ignore"):
        nt = t * NANO
        out = np.zeros(len(t), dtype=np.int64)
        # NaN fails both comparisons → stays 0, like the scalar form.
        edge = nt >= _INT64_MAX  # +Inf and overflowing products included
        mid = (nt > 0) & ~edge
        out[mid] = np.rint(nt[mid]).astype(np.int64)
        out[edge] = _INT64_MAX
    return out


def from_nanotokens(
    name: str,
    added_nt: int,
    taken_nt: int,
    elapsed_ns: int,
    origin_slot: Optional[int] = None,
    cap_nt: Optional[int] = None,
    lane_added_nt: Optional[int] = None,
    lane_taken_nt: Optional[int] = None,
) -> WireState:
    return WireState(
        name=name,
        added=added_nt / NANO,
        taken=taken_nt / NANO,
        elapsed_ns=elapsed_ns,
        origin_slot=origin_slot,
        cap_nt=cap_nt,
        lane_added_nt=lane_added_nt,
        lane_taken_nt=lane_taken_nt,
    )


def encode(state: WireState) -> bytes:
    """Serialize to the reference wire format (bucket.go:51-68), appending the
    v2 origin-slot trailer when ``origin_slot`` is set."""
    # surrogateescape: reference names are raw bytes (bucket.go:64-88);
    # non-UTF8 bytes must round-trip exactly or distinct buckets would
    # collapse into one and fork CRDT state across the cluster.
    name_bytes = state.name.encode("utf-8", errors="surrogateescape")
    with_cap = state.origin_slot is not None and state.cap_nt is not None
    with_lane = (
        with_cap
        and state.lane_added_nt is not None
        and state.lane_taken_nt is not None
    )
    if state.origin_slot is None:
        limit = MAX_NAME_LENGTH_V1
    elif with_lane:
        limit = PACKET_SIZE - FIXED_SIZE - TRAILER_LANE_SIZE
    elif with_cap:
        limit = PACKET_SIZE - FIXED_SIZE - TRAILER_CAP_SIZE
    else:
        limit = PACKET_SIZE - FIXED_SIZE - TRAILER_SIZE
    if len(name_bytes) > limit:
        raise NameTooLargeError(limit)

    elapsed_u64 = state.elapsed_ns & 0xFFFFFFFFFFFFFFFF  # two's-complement wrap
    out = bytearray(_HEADER.pack(state.added, state.taken, elapsed_u64))
    out.append(len(name_bytes))
    out += name_bytes
    if state.origin_slot is not None:
        if with_lane:
            trailer = bytearray(
                _TRAILER_LANE.pack(
                    _TRAILER_MAGIC, _FLAG_CAP | _FLAG_LANE, state.origin_slot,
                    state.cap_nt & 0xFFFFFFFFFFFFFFFF,
                    state.lane_added_nt & 0xFFFFFFFFFFFFFFFF,
                    state.lane_taken_nt & 0xFFFFFFFFFFFFFFFF, 0,
                )
            )
        elif with_cap:
            trailer = bytearray(
                _TRAILER_CAP.pack(
                    _TRAILER_MAGIC, _FLAG_CAP, state.origin_slot,
                    state.cap_nt & 0xFFFFFFFFFFFFFFFF, 0,
                )
            )
        else:
            trailer = bytearray(_TRAILER.pack(_TRAILER_MAGIC, 0, state.origin_slot, 0))
        trailer[-1] = sum(trailer[:-1]) & 0xFF
        out += trailer
    assert len(out) <= PACKET_SIZE
    return bytes(out)


def decode(data: bytes) -> WireState:
    """Deserialize a packet (bucket.go:71-91), detecting the v2 trailer."""
    if len(data) < FIXED_SIZE:
        raise ShortBufferError("short buffer")

    added, taken, elapsed_u64 = _HEADER.unpack_from(data)
    name_len = data[24]
    if len(data) - FIXED_SIZE < name_len:
        raise ShortBufferError("short buffer")
    name = data[FIXED_SIZE : FIXED_SIZE + name_len].decode(
        "utf-8", errors="surrogateescape"
    )

    elapsed_ns = elapsed_u64 - (1 << 64) if elapsed_u64 >= 1 << 63 else elapsed_u64

    origin_slot: Optional[int] = None
    cap_nt: Optional[int] = None
    lane_added_nt: Optional[int] = None
    lane_taken_nt: Optional[int] = None
    tail = data[FIXED_SIZE + name_len :]
    if len(tail) >= TRAILER_SIZE and tail[:2] == _TRAILER_MAGIC:
        flags = tail[2]
        # Values are non-negative int64 nanotoken counts by contract; a
        # bit-63 value is a hostile packet. Validation is all-or-nothing:
        # a trailer with ANY invalid field is discarded whole (the packet
        # degrades to v1 — conservative deficit-attribution ingest), never
        # partially honored. A partially-honored lane trailer would merge
        # the header's AGGREGATE into the sender's single lane and
        # permanently inflate the PN sum (one crafted packet per bucket).
        if flags & _FLAG_LANE and flags & _FLAG_CAP and len(tail) >= TRAILER_LANE_SIZE:
            _m, _f, slot, cap_u64, la_u64, lt_u64, ck = _TRAILER_LANE.unpack_from(tail)
            if (
                ck == sum(tail[: TRAILER_LANE_SIZE - 1]) & 0xFF
                and cap_u64 < 1 << 63
                and la_u64 < 1 << 63
                and lt_u64 < 1 << 63
            ):
                origin_slot = slot
                cap_nt = cap_u64
                lane_added_nt = la_u64
                lane_taken_nt = lt_u64
        elif flags & _FLAG_CAP and not flags & _FLAG_LANE and len(tail) >= TRAILER_CAP_SIZE:
            _magic, _flags, slot, cap_u64, checksum = _TRAILER_CAP.unpack_from(tail)
            if checksum == sum(tail[: TRAILER_CAP_SIZE - 1]) & 0xFF and cap_u64 < 1 << 63:
                origin_slot = slot
                cap_nt = cap_u64
        elif not flags & (_FLAG_CAP | _FLAG_LANE):
            _magic, _flags, slot, checksum = _TRAILER.unpack_from(tail)
            if checksum == sum(tail[: TRAILER_SIZE - 1]) & 0xFF:
                origin_slot = slot

    return WireState(
        name=name,
        added=added,
        taken=taken,
        elapsed_ns=elapsed_ns,
        origin_slot=origin_slot,
        cap_nt=cap_nt,
        lane_added_nt=lane_added_nt,
        lane_taken_nt=lane_taken_nt,
    )
