"""Wire codec: the reference's 25-byte-header / ≤256-byte UDP packet format.

Byte layout (bucket.go:34-91):

====  =====  =====================================================
off   size   field
====  =====  =====================================================
0     8      added, big-endian IEEE-754 float64 (tokens)
8     8      taken, big-endian IEEE-754 float64 (tokens)
16    8      elapsed, big-endian uint64 (nanoseconds, two's compl.)
24    1      name length L (≤ 231)
25    L      name bytes
====  =====  =====================================================

``created`` is deliberately NOT serialized (bucket.go:28-31): only relative
elapsed time crosses the wire, which is what makes the protocol clock-skew
independent (README.md:49-62).

This module adds a *backward-compatible* v2 extension: because the reference
decoder reads exactly ``data[25:25+L]`` and ignores any trailing bytes, we
may append a trailer carrying patrol_tpu metadata. Reference nodes
interoperate unchanged; patrol_tpu nodes use it to address the sender's
PN-counter lane. Four trailer forms (``flags`` bits select):

* base (6 B):     ``b"P2" | u8 flags=0 | u16 slot | u8 checksum``
* with-cap (14B): ``b"P2" | u8 flags=1 | u16 slot | u64 cap_nt | u8 checksum``
* lane (30 B):    ``b"P2" | u8 flags=3 | u16 slot | u64 cap_nt |``
  ``u64 lane_added_nt | u64 lane_taken_nt | u8 checksum``
* multi (15+18K): ``b"P2" | u8 flags=5 | u16 own_slot | u64 cap_nt | u8 K |``
  ``K × (u16 slot | u64 added_nt | u64 taken_nt) | u8 checksum``

(checksum = sum of the preceding trailer bytes mod 256, a guard against a
name that happens to end in "P2").

The **multi** form carries a whole bucket's non-zero PN lanes in ONE
packet — the compact incast reply (the reference answers an incast with
one packet, repo.go:86-90; per-lane replies would storm a cold-starting
node with up to N packets per hot bucket). Flag bit ``0x04`` doubles as a
*capability advert*: an incast REQUEST whose base trailer sets it tells
the receiver the requester can parse multi replies; receivers without the
bit get per-lane replies. Decoders that predate the multi form read its
flags (0x05) as the with-cap form, whose checksum byte lands on ``K`` —
a 255/256 rejection that degrades the packet to v1 aggregate handling
(capacity-subtracted deficit attribution: conservative, never inflating).

**Rolling-upgrade gate** (``wire_mode``, ADVICE r2): senders before the
dual-payload scheme put raw own-lane values in the float64 header with a
base trailer; receivers of that era merge whatever the header holds into
the sender's single lane. Sending them today's capacity-included AGGREGATE
header with a lane trailer they cannot parse would permanently inflate
their PN state (lanes are monotone). Both replication backends therefore
take ``wire_mode``:

* ``"aggregate"`` (default) — today's dual-payload form. Requires every
  patrol_tpu node in the cluster to be lane-trailer-capable (any build
  including the lane trailer): a FLAG-DAY upgrade from pre-lane-trailer
  builds. Mixed clusters with *reference* (v1) nodes are always fine —
  v1 nodes ignore trailers and expect exactly the aggregate header.
* ``"compat"`` — raw own-lane headers + base trailers, parseable by every
  patrol_tpu build ever shipped. Run the whole cluster in this mode while
  rolling out a lane-capable build, then flip to ``aggregate``. (v1
  reference peers see own-lane scalars in this mode — they under-count
  other nodes' takes until the flip, which is within the reference's own
  lossy-scalar-merge semantics.)

Mixed-cluster interop hinges on the **dual payload**: the float64 header
``added``/``taken`` carry the sender's *aggregate scalar view* of the bucket
(capacity-included, like the reference's ``bucket.added`` after lazy init,
bucket.go:194-196) — exactly the full-state scalars a reference node
max-merges — while the trailer carries the sender's *exact own-lane*
PN-counter values in int64 nanotokens for patrol_tpu receivers. Without the
aggregate header, a reference peer max-merging our lane-only ``taken``
against its global scalar would lose takes; without the lane trailer,
patrol_tpu peers would double-count echoed aggregates. ``cap_nt`` is the
sender's lazily-initialized capacity base, which receivers adopt for rows
whose capacity is still unknown.

The device state is int64 nanotokens; the wire is float64 tokens — this codec
is the conversion boundary. float64 represents integers exactly up to 2^53,
i.e. ~9.0e6 tokens at nanotoken resolution; beyond that the wire value is
rounded (observable semantics are preserved within float64's own precision,
which is all the reference ever had).

**Wire protocol v2: delta-interval datagrams** (Almeida et al.,
arXiv:1410.2803; ROADMAP item 3). The per-take full-state packet above
ships ONE bucket per ≤256-B datagram. The delta plane instead ships
*join-decompositions*: each entry is one bucket's absolute PN-lane values
(cap base, lane added/taken, elapsed) — absolute monotone values, so an
entry IS its own join-decomposition: delivering it twice, late, or out of
order is a no-op under the lattice max. Hundreds of entries pack into one
datagram under this framing:

====  ======  ====================================================
off   size    field
====  ======  ====================================================
0     24      zeros (v1 header: added=0, taken=0, elapsed=0)
24    1       L = len(DELTA_CHANNEL_NAME) (= 7)
25    L       ``\\x00pt!dv2`` — the reserved control-channel name
25+L  1       version (= 2)
+1    2       sender_slot (u16, the sender's PN lane)
+3    4       seq (u32 interval number; 0 = bare ack, no payload)
+7    1       K = ack-vector length (≤ 32)
+8    4×K     ack vector: interval seqs received from the DESTINATION
+..   2       N = entry count
+..   ...     N × entry: u8 name_len | name | u16 slot |
              u64 cap_nt | u64 added_nt | u64 taken_nt | u64 elapsed
last  1       checksum (sum of payload bytes mod 256)
====  ======  ====================================================

The first 25+L bytes make the datagram a *v1 zero-state packet for a
reserved name*: a reference node reads it as an incast request for a
bucket that cannot exist (the API rejects NUL-led names), misses, and
stays silent; pre-delta patrol builds dispatch it to the control channel
and ignore the unknown name. Both ignore the payload because every v1
decoder reads exactly ``data[25:25+L]`` — the same invisibility argument
as the P2 trailer. Validation is all-or-nothing (version, checksum,
entry bounds, bit-63 guards): a truncated or mangled delta datagram is
rejected whole, never partially merged. Senders ship deltas only to
peers that advertised the capability (and their receive size) on the
control channel — see net/delta.py.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import List, Optional, Sequence, Tuple

NANO = 1_000_000_000

FIXED_SIZE = 25  # 8 + 8 + 8 + 1 (bucket.go:36)
PACKET_SIZE = 256  # no-fragmentation bound (bucket.go:38-41)
MAX_NAME_LENGTH = PACKET_SIZE - FIXED_SIZE - 30  # room for the lane trailer
MAX_NAME_LENGTH_V1 = PACKET_SIZE - FIXED_SIZE  # the reference's 231 (bucket.go:43-44)

_HEADER = struct.Struct(">ddQ")
# Trace-context trailer (patrol-scope cross-node take tracing): appended
# AFTER whichever P2 trailer form the packet carries. Every decoder in
# the fleet reads its trailer by self-described size and ignores trailing
# bytes (the reference reads exactly data[25:25+L]; the C++ batch decoder
# checks `tail_len >= tsz`), so the trace trailer is invisible to v1
# peers and to pre-trace patrol builds alike — compat-free by the same
# argument as the P2 trailer itself. Magic + checksum guard against a
# random tail parsing as a trace id. Best-effort: emitted only when the
# packet has room (and only for sampled takes), dropped silently
# otherwise.
_TRACE_TRAILER = struct.Struct(">2sQB")  # magic | u64 trace_id | checksum
_TRACE_MAGIC = b"PT"
TRACE_TRAILER_SIZE = _TRACE_TRAILER.size
_TRAILER = struct.Struct(">2sBHB")
_TRAILER_CAP = struct.Struct(">2sBHQB")
_TRAILER_LANE = struct.Struct(">2sBHQQQB")
_MULTI_HEAD = struct.Struct(">2sBHQB")  # magic|flags|own_slot|cap|K
_MULTI_LANE = struct.Struct(">HQQ")  # per-lane: slot|added_nt|taken_nt
_TRAILER_MAGIC = b"P2"
_FLAG_CAP = 0x01
_FLAG_LANE = 0x02
_FLAG_MULTI = 0x04
TRAILER_SIZE = _TRAILER.size
TRAILER_CAP_SIZE = _TRAILER_CAP.size
TRAILER_LANE_SIZE = _TRAILER_LANE.size


def multi_trailer_size(k: int) -> int:
    return _MULTI_HEAD.size + k * _MULTI_LANE.size + 1  # +1 checksum


def max_multi_lanes(name_len: int) -> int:
    """How many lanes fit in one multi packet for a given name length."""
    room = PACKET_SIZE - FIXED_SIZE - name_len - _MULTI_HEAD.size - 1
    return max(0, min(255, room // _MULTI_LANE.size))


class NameTooLargeError(ValueError):
    """Bucket name exceeds the wire limit (bucket.go:46-48)."""

    def __init__(self, limit: int = MAX_NAME_LENGTH_V1) -> None:
        super().__init__(f"bucket name larger than {limit}")


class ShortBufferError(ValueError):
    """Packet shorter than its self-described size (bucket.go:72-74,83-85)."""


@dataclasses.dataclass(frozen=True)
class WireState:
    """One bucket state as it crosses the wire."""

    name: str
    added: float  # tokens (float64, as on the wire): the sender's AGGREGATE
    # scalar view, capacity-included — what a reference node max-merges
    taken: float
    elapsed_ns: int  # signed int64 nanoseconds
    origin_slot: Optional[int] = None  # v2 trailer; None for v1 packets
    cap_nt: Optional[int] = None  # sender's capacity base (nanotokens);
    # None on v1 / base-trailer packets — the receiver then falls back to
    # scalar (reference) merge semantics for this delta
    lane_added_nt: Optional[int] = None  # exact own-lane PN values (grants-
    lane_taken_nt: Optional[int] = None  # only, nanotokens); lane trailer
    lanes: Optional[Tuple[Tuple[int, int, int], ...]] = None  # multi
    # trailer: ((slot, added_nt, taken_nt), …) — a whole bucket's non-zero
    # PN lanes in one packet (the compact incast reply)
    multi_ok: bool = False  # sender advertised multi-reply capability
    # (flag bit 0x04 on its trailer — set on incast requests)
    trace_id: Optional[int] = None  # patrol-scope trace context (sampled
    # takes only): propagates the sender's take span id so the receiver's
    # decode/merge spans join it (utils/trace.py)

    def is_zero(self) -> bool:
        """The incast-request marker (bucket.go:163-170, repo.go:78-90)."""
        return self.added == 0 and self.taken == 0 and self.elapsed_ns == 0

    @property
    def added_nt(self) -> int:
        return _sanitize_nt(self.added)

    @property
    def taken_nt(self) -> int:
        return _sanitize_nt(self.taken)


_INT64_MAX = (1 << 63) - 1


def _sanitize_nt(tokens: float) -> int:
    """float64 wire value → int64 nanotokens, hardened against hostile
    packets: NaN → 0, ±Inf / out-of-range clamp to the int64 edge, negatives
    clamp to 0 (device state is a non-negative G-counter pair). The float64
    reference absorbs such values silently (bucket.go:78-79); the int64
    device path must not crash on them."""
    if tokens != tokens:  # NaN
        return 0
    if tokens <= 0.0:
        return 0
    nt = tokens * NANO
    if nt >= _INT64_MAX:
        return _INT64_MAX
    return round(nt)


def sanitize_nt_array(tokens) -> "np.ndarray":
    """Vectorized :func:`_sanitize_nt` for the batch rx path: float64[n]
    wire tokens → int64[n] nanotokens with identical NaN/Inf/range/negative
    hardening (round-half-even like Python's round). Bit-identical to the
    scalar form on every input — native-rx and python-rx peers MUST merge
    the same packet to the same state or replicas diverge permanently."""
    import numpy as np

    t = np.asarray(tokens, dtype=np.float64)
    with np.errstate(over="ignore", invalid="ignore"):
        nt = t * NANO
        out = np.zeros(len(t), dtype=np.int64)
        # NaN fails both comparisons → stays 0, like the scalar form.
        edge = nt >= _INT64_MAX  # +Inf and overflowing products included
        mid = (nt > 0) & ~edge
        out[mid] = np.rint(nt[mid]).astype(np.int64)
        out[edge] = _INT64_MAX
    return out


def from_nanotokens(
    name: str,
    added_nt: int,
    taken_nt: int,
    elapsed_ns: int,
    origin_slot: Optional[int] = None,
    cap_nt: Optional[int] = None,
    lane_added_nt: Optional[int] = None,
    lane_taken_nt: Optional[int] = None,
    trace_id: Optional[int] = None,
) -> WireState:
    return WireState(
        name=name,
        added=added_nt / NANO,
        taken=taken_nt / NANO,
        elapsed_ns=elapsed_ns,
        origin_slot=origin_slot,
        cap_nt=cap_nt,
        lane_added_nt=lane_added_nt,
        lane_taken_nt=lane_taken_nt,
        trace_id=trace_id,
    )


def encode(state: WireState) -> bytes:
    """Serialize to the reference wire format (bucket.go:51-68), appending the
    v2 origin-slot trailer when ``origin_slot`` is set."""
    # surrogateescape: reference names are raw bytes (bucket.go:64-88);
    # non-UTF8 bytes must round-trip exactly or distinct buckets would
    # collapse into one and fork CRDT state across the cluster.
    name_bytes = state.name.encode("utf-8", errors="surrogateescape")
    with_multi = state.origin_slot is not None and state.cap_nt is not None and state.lanes
    with_cap = (
        not with_multi
        and state.origin_slot is not None
        and state.cap_nt is not None
    )
    with_lane = (
        with_cap
        and state.lane_added_nt is not None
        and state.lane_taken_nt is not None
    )
    if state.origin_slot is None:
        limit = MAX_NAME_LENGTH_V1
    elif with_multi:
        limit = PACKET_SIZE - FIXED_SIZE - multi_trailer_size(len(state.lanes))
    elif with_lane:
        limit = PACKET_SIZE - FIXED_SIZE - TRAILER_LANE_SIZE
    elif with_cap:
        limit = PACKET_SIZE - FIXED_SIZE - TRAILER_CAP_SIZE
    else:
        limit = PACKET_SIZE - FIXED_SIZE - TRAILER_SIZE
    if len(name_bytes) > limit:
        raise NameTooLargeError(limit)

    elapsed_u64 = state.elapsed_ns & 0xFFFFFFFFFFFFFFFF  # two's-complement wrap
    out = bytearray(_HEADER.pack(state.added, state.taken, elapsed_u64))
    out.append(len(name_bytes))
    out += name_bytes
    if state.origin_slot is not None:
        if with_multi:
            trailer = bytearray(
                _MULTI_HEAD.pack(
                    _TRAILER_MAGIC, _FLAG_CAP | _FLAG_MULTI, state.origin_slot,
                    state.cap_nt & 0xFFFFFFFFFFFFFFFF, len(state.lanes),
                )
            )
            for slot, a_nt, t_nt in state.lanes:
                trailer += _MULTI_LANE.pack(
                    slot, a_nt & 0xFFFFFFFFFFFFFFFF, t_nt & 0xFFFFFFFFFFFFFFFF
                )
            trailer.append(0)
        elif with_lane:
            trailer = bytearray(
                _TRAILER_LANE.pack(
                    _TRAILER_MAGIC, _FLAG_CAP | _FLAG_LANE, state.origin_slot,
                    state.cap_nt & 0xFFFFFFFFFFFFFFFF,
                    state.lane_added_nt & 0xFFFFFFFFFFFFFFFF,
                    state.lane_taken_nt & 0xFFFFFFFFFFFFFFFF, 0,
                )
            )
        elif with_cap:
            trailer = bytearray(
                _TRAILER_CAP.pack(
                    _TRAILER_MAGIC, _FLAG_CAP, state.origin_slot,
                    state.cap_nt & 0xFFFFFFFFFFFFFFFF, 0,
                )
            )
        else:
            # The MULTI bit on a base trailer is the capability advert
            # (incast requests): old decoders parse it as a plain base
            # trailer (their flag check masks only CAP|LANE).
            flags = _FLAG_MULTI if state.multi_ok else 0
            trailer = bytearray(
                _TRAILER.pack(_TRAILER_MAGIC, flags, state.origin_slot, 0)
            )
        trailer[-1] = sum(trailer[:-1]) & 0xFF
        out += trailer
        if (
            state.trace_id is not None
            and 0 < state.trace_id < 1 << 63
            and len(out) + TRACE_TRAILER_SIZE <= PACKET_SIZE
        ):
            tt = bytearray(
                _TRACE_TRAILER.pack(_TRACE_MAGIC, state.trace_id, 0)
            )
            tt[-1] = sum(tt[:-1]) & 0xFF
            out += tt
    assert len(out) <= PACKET_SIZE
    return bytes(out)


def decode(data: bytes) -> WireState:
    """Deserialize a packet (bucket.go:71-91), detecting the v2 trailer."""
    if len(data) < FIXED_SIZE:
        raise ShortBufferError("short buffer")

    added, taken, elapsed_u64 = _HEADER.unpack_from(data)
    name_len = data[24]
    if len(data) - FIXED_SIZE < name_len:
        raise ShortBufferError("short buffer")
    name = data[FIXED_SIZE : FIXED_SIZE + name_len].decode(
        "utf-8", errors="surrogateescape"
    )

    elapsed_ns = elapsed_u64 - (1 << 64) if elapsed_u64 >= 1 << 63 else elapsed_u64

    origin_slot: Optional[int] = None
    cap_nt: Optional[int] = None
    lane_added_nt: Optional[int] = None
    lane_taken_nt: Optional[int] = None
    lanes: Optional[Tuple[Tuple[int, int, int], ...]] = None
    multi_ok = False
    consumed = 0  # bytes of tail a VALID P2 trailer occupied (trace scan)
    tail = data[FIXED_SIZE + name_len :]
    if len(tail) >= TRAILER_SIZE and tail[:2] == _TRAILER_MAGIC:
        flags = tail[2]
        # Values are non-negative int64 nanotoken counts by contract; a
        # bit-63 value is a hostile packet. Validation is all-or-nothing:
        # a trailer with ANY invalid field is discarded whole (the packet
        # degrades to v1 — conservative deficit-attribution ingest), never
        # partially honored. A partially-honored lane trailer would merge
        # the header's AGGREGATE into the sender's single lane and
        # permanently inflate the PN sum (one crafted packet per bucket).
        if (
            flags & _FLAG_MULTI
            and flags & _FLAG_CAP
            and not flags & _FLAG_LANE
            and len(tail) >= _MULTI_HEAD.size + 1
        ):
            _m, _f, slot, cap_u64, k = _MULTI_HEAD.unpack_from(tail)
            tsz = multi_trailer_size(k)
            if len(tail) >= tsz and tail[tsz - 1] == sum(tail[: tsz - 1]) & 0xFF:
                vals = []
                good = cap_u64 < 1 << 63
                off = _MULTI_HEAD.size
                for _ in range(k):
                    ls, la, lt = _MULTI_LANE.unpack_from(tail, off)
                    off += _MULTI_LANE.size
                    good &= la < 1 << 63 and lt < 1 << 63
                    vals.append((ls, la, lt))
                if good:
                    origin_slot = slot
                    cap_nt = cap_u64
                    lanes = tuple(vals)
                    multi_ok = True
                    consumed = tsz
        elif flags & _FLAG_LANE and flags & _FLAG_CAP and len(tail) >= TRAILER_LANE_SIZE:
            _m, _f, slot, cap_u64, la_u64, lt_u64, ck = _TRAILER_LANE.unpack_from(tail)
            if (
                ck == sum(tail[: TRAILER_LANE_SIZE - 1]) & 0xFF
                and cap_u64 < 1 << 63
                and la_u64 < 1 << 63
                and lt_u64 < 1 << 63
            ):
                origin_slot = slot
                cap_nt = cap_u64
                lane_added_nt = la_u64
                lane_taken_nt = lt_u64
                consumed = TRAILER_LANE_SIZE
        elif flags & _FLAG_CAP and not flags & _FLAG_LANE and len(tail) >= TRAILER_CAP_SIZE:
            _magic, _flags, slot, cap_u64, checksum = _TRAILER_CAP.unpack_from(tail)
            if checksum == sum(tail[: TRAILER_CAP_SIZE - 1]) & 0xFF and cap_u64 < 1 << 63:
                origin_slot = slot
                cap_nt = cap_u64
                consumed = TRAILER_CAP_SIZE
        elif not flags & (_FLAG_CAP | _FLAG_LANE):
            _magic, _flags, slot, checksum = _TRAILER.unpack_from(tail)
            if checksum == sum(tail[: TRAILER_SIZE - 1]) & 0xFF:
                origin_slot = slot
                multi_ok = bool(flags & _FLAG_MULTI)  # capability advert
                consumed = TRAILER_SIZE

    trace_id: Optional[int] = None
    if consumed and len(tail) >= consumed + TRACE_TRAILER_SIZE:
        tt = tail[consumed : consumed + TRACE_TRAILER_SIZE]
        if tt[:2] == _TRACE_MAGIC and tt[-1] == sum(tt[:-1]) & 0xFF:
            tid = int.from_bytes(tt[2:10], "big")
            if 0 < tid < 1 << 63:
                trace_id = tid

    return WireState(
        name=name,
        added=added,
        taken=taken,
        elapsed_ns=elapsed_ns,
        origin_slot=origin_slot,
        cap_nt=cap_nt,
        lane_added_nt=lane_added_nt,
        lane_taken_nt=lane_taken_nt,
        lanes=lanes,
        multi_ok=multi_ok,
        trace_id=trace_id,
    )


def pack_multi(states: Sequence[WireState]) -> List[WireState]:
    """Pack per-lane snapshot states of ONE bucket into as few multi
    packets as fit (the compact incast reply, repo.go:86-90: the reference
    answers with one packet; per-lane replies would send up to N). Falls
    back to the input unchanged when the states lack lane/cap data or only
    one lane exists (the 30 B lane trailer is smaller than a 33 B 1-lane
    multi). Every packet repeats the full aggregate header — idempotent
    under the reference's scalar max-merge, like the per-lane form.

    Amplification bound: the reply to one incast request is EXACTLY
    ⌈non-zero lanes / max_multi_lanes(len(name))⌉ packets — ~12 lanes per
    packet at short names, so a flagship-shape 256-lane bucket answers in
    ~22 packets where the per-lane form would send 256 (the reference
    sends 1, but carries one scalar pair where we carry every PN lane).
    Responder-side pacing on top of this bound lives in
    net/replication.py ``ReplyGate``: one burst per (bucket, requester)
    per TTL, so a cold-start storm's reply traffic is bounded by
    distinct-requesters × ⌈lanes/per-packet⌉ per TTL window, regardless
    of request rate."""
    if len(states) <= 1:
        return list(states)
    first = states[0]
    if first.cap_nt is None or any(
        s.lane_added_nt is None or s.lane_taken_nt is None or s.origin_slot is None
        for s in states
    ):
        return list(states)
    per_packet = max_multi_lanes(
        len(first.name.encode("utf-8", errors="surrogateescape"))
    )
    if per_packet < 2:
        return list(states)
    out: List[WireState] = []
    for lo in range(0, len(states), per_packet):
        chunk = states[lo : lo + per_packet]
        out.append(
            dataclasses.replace(
                first,
                lanes=tuple(
                    (s.origin_slot, s.lane_added_nt, s.lane_taken_nt) for s in chunk
                ),
            )
        )
    return out


# ---------------------------------------------------------------------------
# Wire protocol v2: delta-interval datagrams (framing in the module docs).

# Rides the reserved control-channel namespace (net/replication.CTRL_PREFIX):
# no legal bucket name starts with NUL, so v1 peers read a delta datagram as
# an incast request for an impossible bucket and stay silent.
DELTA_CHANNEL_NAME = "\x00pt!dv2"
_DELTA_NAME_BYTES = DELTA_CHANNEL_NAME.encode()
_DELTA_BASE = FIXED_SIZE + len(_DELTA_NAME_BYTES)  # payload offset (32)
# Default delta datagram bound. Deliberately larger than the v1 PACKET_SIZE:
# the 256-B bound exists so per-take datagrams never fragment; the delta
# plane is paced and batched, and datacenter paths (and loopback) carry
# multi-KB UDP fine. Each peer advertises its own receive bound on the
# control channel (the native recvmmsg backend can only take PACKET_SIZE),
# and senders pack to min(own tx bound, peer's advertised rx bound).
DELTA_PACKET_SIZE = 8192
DELTA_VERSION = 2
DELTA_MAX_ACKS = 32  # ack-vector entries per datagram
_DELTA_HEAD = struct.Struct(">BHIB")  # version | sender_slot | seq | n_acks
_DELTA_ACK = struct.Struct(">I")
_DELTA_COUNT = struct.Struct(">H")
_DELTA_ENTRY = struct.Struct(">HQQQQ")  # slot | cap | added | taken | elapsed


@dataclasses.dataclass(frozen=True)
class DeltaEntry:
    """One bucket's join-decomposition: the ABSOLUTE values of one PN lane
    (plus cap base and the elapsed G-counter). Monotone, so shipping the
    current value subsumes every earlier interval — retransmits re-read
    state instead of replaying history."""

    name: str
    slot: int
    cap_nt: int
    added_nt: int
    taken_nt: int
    elapsed_ns: int


@dataclasses.dataclass(frozen=True)
class DeltaPacket:
    sender_slot: int
    seq: int  # 0 = bare ack (no payload interval)
    acks: Tuple[int, ...]  # interval seqs received from the destination
    entries: Tuple[DeltaEntry, ...]


def delta_entry_size(name: str) -> int:
    return 1 + len(name.encode("utf-8", errors="surrogateescape")) + _DELTA_ENTRY.size


def delta_capacity(max_size: int, name_len: int) -> int:
    """How many entries of a given name length fit one delta datagram."""
    room = max_size - _DELTA_BASE - _DELTA_HEAD.size - _DELTA_COUNT.size - 1
    return max(0, room // (1 + name_len + _DELTA_ENTRY.size))


def encode_delta_packet(
    sender_slot: int,
    seq: int,
    acks: Sequence[int],
    entries: Sequence[DeltaEntry],
    max_size: int = DELTA_PACKET_SIZE,
) -> Tuple[bytes, int]:
    """Pack ``acks`` (≤ 32 kept) and as many ``entries`` as fit under
    ``max_size`` → (datagram, number of entries packed). The caller loops
    with fresh seqs for the remainder. ``seq=0`` with no entries is a bare
    ack. Values are clamped non-negative (the bit-63 decode guard is the
    receiving side's contract)."""
    envelope = bytearray(_DELTA_BASE)
    envelope[24] = len(_DELTA_NAME_BYTES)
    envelope[FIXED_SIZE:] = _DELTA_NAME_BYTES
    acks = list(acks)[:DELTA_MAX_ACKS]
    body = bytearray(
        _DELTA_HEAD.pack(
            DELTA_VERSION, sender_slot & 0xFFFF, seq & 0xFFFFFFFF, len(acks)
        )
    )
    for a in acks:
        body += _DELTA_ACK.pack(a & 0xFFFFFFFF)
    count_off = len(body)
    body += _DELTA_COUNT.pack(0)
    budget = max_size - _DELTA_BASE - len(body) - 1  # −1 checksum
    packed = 0
    for e in entries:
        nb = e.name.encode("utf-8", errors="surrogateescape")
        if len(nb) > 255:
            raise NameTooLargeError(255)
        sz = 1 + len(nb) + _DELTA_ENTRY.size
        if sz > budget or packed >= 0xFFFF:
            break
        body.append(len(nb))
        body += nb
        body += _DELTA_ENTRY.pack(
            e.slot & 0xFFFF,
            min(max(e.cap_nt, 0), _INT64_MAX),
            min(max(e.added_nt, 0), _INT64_MAX),
            min(max(e.taken_nt, 0), _INT64_MAX),
            min(max(e.elapsed_ns, 0), _INT64_MAX),
        )
        budget -= sz
        packed += 1
    _DELTA_COUNT.pack_into(body, count_off, packed)
    body.append(sum(body) & 0xFF)
    return bytes(envelope) + bytes(body), packed


def decode_delta_packet(data: bytes) -> Optional[DeltaPacket]:
    """Strict all-or-nothing decode of a v2 delta datagram; ``None`` for
    anything malformed (wrong envelope, bad version/checksum, truncated or
    overlong body, out-of-range values) — a hostile or corrupted datagram
    must never be partially merged."""
    end = len(data) - 1
    if end < _DELTA_BASE + _DELTA_HEAD.size + _DELTA_COUNT.size:
        return None
    if (
        data[:24] != b"\x00" * 24
        or data[24] != len(_DELTA_NAME_BYTES)
        or data[FIXED_SIZE:_DELTA_BASE] != _DELTA_NAME_BYTES
    ):
        return None
    if data[end] != sum(data[_DELTA_BASE:end]) & 0xFF:
        return None
    version, sender_slot, seq, n_acks = _DELTA_HEAD.unpack_from(data, _DELTA_BASE)
    if version != DELTA_VERSION or n_acks > DELTA_MAX_ACKS:
        return None
    off = _DELTA_BASE + _DELTA_HEAD.size
    if off + n_acks * _DELTA_ACK.size + _DELTA_COUNT.size > end:
        return None
    acks = tuple(
        _DELTA_ACK.unpack_from(data, off + i * _DELTA_ACK.size)[0]
        for i in range(n_acks)
    )
    off += n_acks * _DELTA_ACK.size
    (count,) = _DELTA_COUNT.unpack_from(data, off)
    off += _DELTA_COUNT.size
    entries = []
    for _ in range(count):
        if off >= end:
            return None
        name_len = data[off]
        off += 1
        if off + name_len + _DELTA_ENTRY.size > end:
            return None
        name = data[off : off + name_len].decode("utf-8", errors="surrogateescape")
        off += name_len
        slot, cap, added, taken, elapsed = _DELTA_ENTRY.unpack_from(data, off)
        off += _DELTA_ENTRY.size
        if max(cap, added, taken, elapsed) > _INT64_MAX:
            return None
        entries.append(DeltaEntry(name, slot, cap, added, taken, elapsed))
    if off != end:
        return None  # trailing garbage ⇒ reject whole, like the P2 trailers
    return DeltaPacket(sender_slot, seq, acks, tuple(entries))


def is_delta_packet(data: bytes) -> bool:
    """Cheap envelope test — routes rx traffic to the delta decoder before
    the generic control-channel dispatch."""
    return (
        len(data) > _DELTA_BASE
        and data[24] == len(_DELTA_NAME_BYTES)
        and data[FIXED_SIZE:_DELTA_BASE] == _DELTA_NAME_BYTES
        and data[:24] == b"\x00" * 24
    )


# ---------------------------------------------------------------------------
# patrol-fleet: metrics-lattice gossip datagrams (``\x00pt!mtr``).
#
# The histograms in utils/histogram.py are G-Counter lattices (per-node
# monotone lanes, join = per-lane-per-bucket max) and the profiling
# counters are monotone scalars — so cluster-wide aggregation is exactly
# the delta-mutation move of Almeida et al. (arXiv:1410.2803): ship
# join-decompositions of the CURRENT lattice state, pairwise, on a paced
# cadence, and let receivers max-join. Dup/reorder/stale delivery are
# no-ops by construction; a dropped packet is subsumed by the next flush.
#
# Envelope: identical invisibility argument as the dv2 delta channel —
# the first 25+L bytes form a v1 zero-state packet for a reserved name a
# real bucket can never have, so reference peers read an incast request
# for an unknown bucket and stay silent, and pre-fleet patrol builds
# dispatch it to the control channel and ignore the unknown name.
#
# Payload (after the 32-byte envelope, all big-endian):
#
#   u8  version (= 1)
#   u16 sender_slot
#   u8  K  | K × (u16 slot | u8 len | name)          node-name map
#   u16 Nc | Nc × (u8 len | name | u16 slot | u64 value)   counter lanes
#   u16 Nh | Nh × (u8 len | name | u8 ulen | unit | u16 slot |
#                  u64 sum | u8 B | B × (u8 bucket | u64 count))
#   u8  checksum (sum of payload bytes mod 256)
#
# A histogram-lane entry may carry ANY SUBSET of its buckets: each
# (histogram, lane, bucket) count is itself a join-decomposition under
# the per-bucket max, so a lane too large for one datagram splits across
# several and the receiver's joins reassemble it exactly. Validation is
# all-or-nothing, like the dv2 framing.

METRICS_CHANNEL_NAME = "\x00pt!mtr"
_METRICS_NAME_BYTES = METRICS_CHANNEL_NAME.encode()
_METRICS_BASE = FIXED_SIZE + len(_METRICS_NAME_BYTES)  # payload offset (32)
METRICS_VERSION = 1
_MTR_HEAD = struct.Struct(">BH")  # version | sender_slot
_MTR_U16 = struct.Struct(">H")
_MTR_LANE_VAL = struct.Struct(">HQ")  # slot | u64 value
_MTR_BUCKET = struct.Struct(">BQ")  # bucket index | u64 count


@dataclasses.dataclass(frozen=True)
class MetricsLane:
    """One histogram lane's join-decomposition: the ABSOLUTE monotone
    bucket counts (possibly a subset) plus the lane's value sum."""

    name: str
    unit: str
    slot: int
    sum: int
    buckets: Tuple[Tuple[int, int], ...]  # ((bucket_index, count), ...)


@dataclasses.dataclass(frozen=True)
class MetricsPacket:
    sender_slot: int
    node_names: Tuple[Tuple[int, str], ...]
    counters: Tuple[Tuple[str, int, int], ...]  # (name, slot, value)
    hists: Tuple[MetricsLane, ...]


def _mtr_envelope() -> bytearray:
    env = bytearray(_METRICS_BASE)
    env[24] = len(_METRICS_NAME_BYTES)
    env[FIXED_SIZE:] = _METRICS_NAME_BYTES
    return env


def metrics_lane_size(name: str, unit: str, n_buckets: int) -> int:
    """Encoded size of one histogram-lane entry carrying n_buckets."""
    return (
        1 + len(name.encode("utf-8", "surrogateescape"))
        + 1 + len(unit.encode())
        + _MTR_LANE_VAL.size + 1 + n_buckets * _MTR_BUCKET.size
    )


def encode_metrics_packets(
    sender_slot: int,
    node_names: Sequence[Tuple[int, str]],
    counters: Sequence[Tuple[str, int, int]],
    hists: Sequence[MetricsLane],
    max_size: int = DELTA_PACKET_SIZE,
) -> List[bytes]:
    """Pack the metric lattice's join-decompositions into as many
    ``\\x00pt!mtr`` datagrams as fit under ``max_size``. Histogram lanes
    whose buckets overflow the packet split across packets (per-bucket
    counts are independent join-decompositions); an entry that cannot fit
    even in an otherwise-empty packet is dropped (never truncated into an
    undecodable tail). The node-name map rides every packet."""
    out: List[bytes] = []
    name_map = []
    for slot, nm in node_names:
        raw = nm.encode("utf-8", "surrogateescape")[:64]
        name_map.append((slot & 0xFFFF, raw))
    name_map = name_map[:255]
    map_bytes = bytearray([len(name_map)])
    for slot, raw in name_map:
        map_bytes += _MTR_U16.pack(slot)
        map_bytes.append(len(raw))
        map_bytes += raw
    head_cost = (
        _METRICS_BASE + _MTR_HEAD.size + len(map_bytes)
        + 2 * _MTR_U16.size + 1  # the two section counts + checksum
    )
    budget0 = max_size - head_cost
    if budget0 <= 0:
        raise ValueError(f"metrics packet head exceeds max_size {max_size}")

    c_todo = list(counters)
    h_todo = [
        (lane, list(lane.buckets)) for lane in hists
    ]  # (lane, remaining buckets)
    while c_todo or h_todo:
        budget = budget0
        c_now: List[Tuple[bytes, int, int]] = []
        while c_todo:
            nm, slot, val = c_todo[0]
            raw = nm.encode("utf-8", "surrogateescape")
            sz = 1 + len(raw) + _MTR_LANE_VAL.size
            if sz > budget:
                if not c_now and sz > budget0:
                    c_todo.pop(0)  # undeliverable at this MTU: drop whole
                    continue
                break
            c_todo.pop(0)
            c_now.append((raw, slot, val))
            budget -= sz
        h_now: List[Tuple[MetricsLane, bytes, bytes, List[Tuple[int, int]]]] = []
        while h_todo and len(h_now) < 0xFFFF:
            lane, rem = h_todo[0]
            raw = lane.name.encode("utf-8", "surrogateescape")
            uraw = lane.unit.encode()
            head = 1 + len(raw) + 1 + len(uraw) + _MTR_LANE_VAL.size + 1
            if head > budget0:
                h_todo.pop(0)  # name/unit can never fit: drop whole
                continue
            if head + _MTR_BUCKET.size > budget and rem:
                if head + _MTR_BUCKET.size > budget0:
                    h_todo.pop(0)  # never fits with even one bucket: drop
                    continue
                break  # not even one bucket fits this packet
            fit = min(
                len(rem),
                max(0, (budget - head) // _MTR_BUCKET.size),
                255,
            )
            if head > budget:
                break
            take_b, rest = rem[:fit], rem[fit:]
            h_now.append((lane, raw, uraw, take_b))
            budget -= head + len(take_b) * _MTR_BUCKET.size
            if rest:
                h_todo[0] = (lane, rest)
                break  # packet is full (or nearly): ship it
            h_todo.pop(0)
        if not c_now and not h_now:
            break  # nothing fit (all undeliverable): stop, never spin
        body = bytearray(
            _MTR_HEAD.pack(METRICS_VERSION, sender_slot & 0xFFFF)
        )
        body += map_bytes
        body += _MTR_U16.pack(len(c_now))
        for raw, slot, val in c_now:
            body.append(len(raw))
            body += raw
            body += _MTR_LANE_VAL.pack(
                slot & 0xFFFF, min(max(val, 0), _INT64_MAX)
            )
        body += _MTR_U16.pack(len(h_now))
        for lane, raw, uraw, buckets in h_now:
            body.append(len(raw))
            body += raw
            body.append(len(uraw))
            body += uraw
            body += _MTR_LANE_VAL.pack(
                lane.slot & 0xFFFF, min(max(lane.sum, 0), _INT64_MAX)
            )
            body.append(len(buckets))
            for b, c in buckets:
                body += _MTR_BUCKET.pack(b & 0xFF, min(max(c, 0), _INT64_MAX))
        body.append(sum(body) & 0xFF)
        out.append(bytes(_mtr_envelope()) + bytes(body))
    return out


def decode_metrics_packet(data: bytes) -> Optional[MetricsPacket]:
    """Strict all-or-nothing decode of a metrics-gossip datagram; ``None``
    for anything malformed — a corrupted lattice delta must never be
    partially joined."""
    end = len(data) - 1
    if end < _METRICS_BASE + _MTR_HEAD.size + 1 + 2 * _MTR_U16.size:
        return None
    if (
        data[:24] != b"\x00" * 24
        or data[24] != len(_METRICS_NAME_BYTES)
        or data[FIXED_SIZE:_METRICS_BASE] != _METRICS_NAME_BYTES
    ):
        return None
    if data[end] != sum(data[_METRICS_BASE:end]) & 0xFF:
        return None
    version, sender_slot = _MTR_HEAD.unpack_from(data, _METRICS_BASE)
    if version != METRICS_VERSION:
        return None
    off = _METRICS_BASE + _MTR_HEAD.size
    try:
        k = data[off]
        off += 1
        names = []
        for _ in range(k):
            (slot,) = _MTR_U16.unpack_from(data, off)
            off += _MTR_U16.size
            ln = data[off]
            off += 1
            if off + ln > end:
                return None
            names.append(
                (slot, data[off : off + ln].decode("utf-8", "surrogateescape"))
            )
            off += ln
        (nc,) = _MTR_U16.unpack_from(data, off)
        off += _MTR_U16.size
        counters = []
        for _ in range(nc):
            ln = data[off]
            off += 1
            if off + ln + _MTR_LANE_VAL.size > end:
                return None
            nm = data[off : off + ln].decode("utf-8", "surrogateescape")
            off += ln
            slot, val = _MTR_LANE_VAL.unpack_from(data, off)
            off += _MTR_LANE_VAL.size
            if val > _INT64_MAX:
                return None
            counters.append((nm, slot, val))
        (nh,) = _MTR_U16.unpack_from(data, off)
        off += _MTR_U16.size
        hists = []
        for _ in range(nh):
            ln = data[off]
            off += 1
            if off + ln + 1 > end:
                return None
            nm = data[off : off + ln].decode("utf-8", "surrogateescape")
            off += ln
            ul = data[off]
            off += 1
            if off + ul + _MTR_LANE_VAL.size + 1 > end:
                return None
            unit = data[off : off + ul].decode("utf-8", "surrogateescape")
            off += ul
            slot, total = _MTR_LANE_VAL.unpack_from(data, off)
            off += _MTR_LANE_VAL.size
            nb = data[off]
            off += 1
            if off + nb * _MTR_BUCKET.size > end or total > _INT64_MAX:
                return None
            buckets = []
            for _ in range(nb):
                b, c = _MTR_BUCKET.unpack_from(data, off)
                off += _MTR_BUCKET.size
                if c > _INT64_MAX:
                    return None
                buckets.append((b, c))
            hists.append(MetricsLane(nm, unit, slot, total, tuple(buckets)))
    except (IndexError, struct.error):
        return None
    if off != end:
        return None  # trailing garbage ⇒ reject whole
    return MetricsPacket(sender_slot, tuple(names), tuple(counters), tuple(hists))


# ---------------------------------------------------------------------------
# patrol-audit: consistency-audit datagrams (``\x00pt!adt``).
#
# The third observability plane (net/audit.py) measures how consistent the
# cluster actually IS: read-only divergence digests (no resync — that is
# anti-entropy's job) and the windowed admitted-token G-counter lanes the
# AP-overshoot auditor joins cluster-wide. Same envelope invisibility
# argument as ``dv2``/``mtr``: the first 25+L bytes form a v1 zero-state
# packet for a reserved name no real bucket can carry, so reference peers
# read an incast request for an unknown bucket and stay silent, and
# pre-audit patrol builds dispatch it to the control channel and ignore
# the unknown name.
#
# Payload (after the 32-byte envelope, all big-endian):
#
#   u8  version (= 1)
#   u16 sender_slot
#   u16 Nd | Nd × (u64 name_hash | u64 state_digest)     divergence digests
#   u8  Nw | Nw × window:
#         u64 window_id | u16 sides | u8 closed | u64 duration_ns
#         u16 Na | Na × (u8 len | name | u16 slot |
#                        u64 admitted_nt | u64 limit_nt)
#   u8  checksum (sum of payload bytes mod 256)
#
# Every admitted-lane entry is an ABSOLUTE monotone own-lane value for
# (window, bucket, lane) — its own join-decomposition, so dup/reorder/
# stale delivery max-join to a no-op, and a window's lanes may split
# across any number of datagrams (the window header repeats). Validation
# is all-or-nothing, like the dv2/mtr framings.

AUDIT_CHANNEL_NAME = "\x00pt!adt"
_AUDIT_NAME_BYTES = AUDIT_CHANNEL_NAME.encode()
_AUDIT_BASE = FIXED_SIZE + len(_AUDIT_NAME_BYTES)  # payload offset (32)
AUDIT_VERSION = 1
_ADT_HEAD = struct.Struct(">BH")  # version | sender_slot
_ADT_U16 = struct.Struct(">H")
_ADT_DIGEST = struct.Struct(">QQ")  # name_hash | state_digest
_ADT_WIN_HEAD = struct.Struct(">QHBQ")  # window_id | sides | closed | dur
_ADT_LANE_TAIL = struct.Struct(">HQQ")  # slot | admitted_nt | limit_nt


@dataclasses.dataclass(frozen=True)
class AuditLane:
    """One (bucket, node-lane) of an audit window's admitted-token
    G-counter: the ABSOLUTE cumulative nanotokens that lane admitted
    inside the window, plus the sender's view of the window limit."""

    name: str
    slot: int
    admitted_nt: int
    limit_nt: int


@dataclasses.dataclass(frozen=True)
class AuditWindow:
    window_id: int
    sides: int  # sender's partition-sides estimate for the window (max-joined)
    closed: bool  # the sender's ledger has closed this window locally
    duration_ns: int  # observed window span (refill term of the limit)
    lanes: Tuple[AuditLane, ...]


@dataclasses.dataclass(frozen=True)
class AuditPacket:
    sender_slot: int
    digests: Tuple[Tuple[int, int], ...]  # (name_hash, state_digest)
    windows: Tuple[AuditWindow, ...]


def _adt_envelope() -> bytearray:
    env = bytearray(_AUDIT_BASE)
    env[24] = len(_AUDIT_NAME_BYTES)
    env[FIXED_SIZE:] = _AUDIT_NAME_BYTES
    return env


def audit_lane_size(name: str) -> int:
    return 1 + len(name.encode("utf-8", "surrogateescape")) + _ADT_LANE_TAIL.size


def encode_audit_packets(
    sender_slot: int,
    digests: Sequence[Tuple[int, int]],
    windows: Sequence[AuditWindow],
    max_size: int = DELTA_PACKET_SIZE,
) -> List[bytes]:
    """Pack the audit exchange into as many ``\\x00pt!adt`` datagrams as
    fit under ``max_size``. Digest entries and window lanes both split
    freely across packets (each is an independent join-decomposition; the
    window header repeats per packet). A lane whose name cannot fit even
    an otherwise-empty packet is dropped whole, never truncated."""
    out: List[bytes] = []
    head_cost = _AUDIT_BASE + _ADT_HEAD.size + _ADT_U16.size + 1 + 1  # +checksum
    budget0 = max_size - head_cost
    if budget0 <= 0:
        raise ValueError(f"audit packet head exceeds max_size {max_size}")
    d_todo = list(digests)
    w_todo: List[Tuple[AuditWindow, List[AuditLane]]] = [
        (w, list(w.lanes)) for w in windows
    ]
    # Header-only windows (no lanes) still ship once: they carry the
    # sides estimate and the closed flag.
    while d_todo or w_todo:
        budget = budget0
        d_now: List[Tuple[int, int]] = []
        while d_todo and _ADT_DIGEST.size <= budget and len(d_now) < 0xFFFF:
            d_now.append(d_todo.pop(0))
            budget -= _ADT_DIGEST.size
        w_now: List[Tuple[AuditWindow, List[AuditLane]]] = []
        while w_todo and len(w_now) < 0xFF:
            win, rem = w_todo[0]
            head = _ADT_WIN_HEAD.size + _ADT_U16.size
            if head > budget:
                break
            lanes_fit: List[AuditLane] = []
            b = budget - head
            while rem:
                sz = audit_lane_size(rem[0].name)
                if sz > budget0 - head:
                    rem.pop(0)  # undeliverable at this MTU: drop whole
                    continue
                if sz > b or len(lanes_fit) >= 0xFFFF:
                    break
                lanes_fit.append(rem.pop(0))
                b -= sz
            if rem and not lanes_fit:
                break  # not even one lane fits this packet: next packet
            w_now.append(
                (dataclasses.replace(win, lanes=tuple(lanes_fit)), rem)
            )
            budget = b
            if rem:
                w_todo[0] = (win, rem)
                break  # packet is full: ship it
            w_todo.pop(0)
        if not d_now and not w_now:
            break  # nothing fit (all undeliverable): stop, never spin
        body = bytearray(_ADT_HEAD.pack(AUDIT_VERSION, sender_slot & 0xFFFF))
        body += _ADT_U16.pack(len(d_now))
        for h, d in d_now:
            body += _ADT_DIGEST.pack(
                h & 0xFFFFFFFFFFFFFFFF, d & 0xFFFFFFFFFFFFFFFF
            )
        body.append(len(w_now))
        for win, _rem in w_now:
            body += _ADT_WIN_HEAD.pack(
                win.window_id & 0xFFFFFFFFFFFFFFFF,
                min(max(win.sides, 0), 0xFFFF),
                1 if win.closed else 0,
                min(max(win.duration_ns, 0), _INT64_MAX),
            )
            body += _ADT_U16.pack(len(win.lanes))
            for lane in win.lanes:
                raw = lane.name.encode("utf-8", "surrogateescape")
                body.append(len(raw))
                body += raw
                body += _ADT_LANE_TAIL.pack(
                    lane.slot & 0xFFFF,
                    min(max(lane.admitted_nt, 0), _INT64_MAX),
                    min(max(lane.limit_nt, 0), _INT64_MAX),
                )
        body.append(sum(body) & 0xFF)
        out.append(bytes(_adt_envelope()) + bytes(body))
    return out


def decode_audit_packet(data: bytes) -> Optional[AuditPacket]:
    """Strict all-or-nothing decode of an audit datagram; ``None`` for
    anything malformed — a corrupted audit frame must never be partially
    joined (a torn admitted lane would inflate the measured overshoot)."""
    end = len(data) - 1
    if end < _AUDIT_BASE + _ADT_HEAD.size + _ADT_U16.size + 1:
        return None
    if (
        data[:24] != b"\x00" * 24
        or data[24] != len(_AUDIT_NAME_BYTES)
        or data[FIXED_SIZE:_AUDIT_BASE] != _AUDIT_NAME_BYTES
    ):
        return None
    if data[end] != sum(data[_AUDIT_BASE:end]) & 0xFF:
        return None
    version, sender_slot = _ADT_HEAD.unpack_from(data, _AUDIT_BASE)
    if version != AUDIT_VERSION:
        return None
    off = _AUDIT_BASE + _ADT_HEAD.size
    try:
        (nd,) = _ADT_U16.unpack_from(data, off)
        off += _ADT_U16.size
        if off + nd * _ADT_DIGEST.size > end:
            return None
        digests = tuple(
            _ADT_DIGEST.unpack_from(data, off + i * _ADT_DIGEST.size)
            for i in range(nd)
        )
        off += nd * _ADT_DIGEST.size
        nw = data[off]
        off += 1
        windows = []
        for _ in range(nw):
            if off + _ADT_WIN_HEAD.size + _ADT_U16.size > end:
                return None
            wid, sides, closed, dur = _ADT_WIN_HEAD.unpack_from(data, off)
            off += _ADT_WIN_HEAD.size
            if closed > 1 or dur > _INT64_MAX:
                return None
            (na,) = _ADT_U16.unpack_from(data, off)
            off += _ADT_U16.size
            lanes = []
            for _ in range(na):
                if off >= end:
                    return None
                ln = data[off]
                off += 1
                if off + ln + _ADT_LANE_TAIL.size > end:
                    return None
                nm = data[off : off + ln].decode("utf-8", "surrogateescape")
                off += ln
                slot, adm, lim = _ADT_LANE_TAIL.unpack_from(data, off)
                off += _ADT_LANE_TAIL.size
                if adm > _INT64_MAX or lim > _INT64_MAX:
                    return None
                lanes.append(AuditLane(nm, slot, adm, lim))
            windows.append(
                AuditWindow(wid, sides, bool(closed), dur, tuple(lanes))
            )
    except (IndexError, struct.error):
        return None
    if off != end:
        return None  # trailing garbage ⇒ reject whole
    return AuditPacket(sender_slot, digests, tuple(windows))


# ---------------------------------------------------------------------------
# Membership channel (``\x00pt!mbr``) — elastic-membership events
# (net/membership.py, ROADMAP 3b). Same envelope trick as dv2/mtr/adt:
# a v1 zero-state packet whose reserved name no bucket can have, with the
# real payload after the name — invisible to reference peers. One event
# per datagram, bounded well under the v1 PACKET_SIZE so the native
# recvmmsg backend (fixed 256-B slots) receives it unconditionally.
# Events are idempotent facts about the lane-lifecycle lattice (join /
# leave-tombstone / rejoin-handshake), so loss and duplication are both
# safe: a re-announce is a no-op, a lost announce is repaired the next
# time the sender emits (or at the admin's retry). Validation is
# all-or-nothing like the other framings — a torn membership event must
# never half-apply (a lane adoption without its epoch would be exactly
# the lane-reuse bug the tombstone rule forbids).

MEMBER_CHANNEL_NAME = "\x00pt!mbr"
_MEMBER_NAME_BYTES = MEMBER_CHANNEL_NAME.encode()
_MEMBER_BASE = FIXED_SIZE + len(_MEMBER_NAME_BYTES)  # payload offset (32)
MEMBER_VERSION = 1
MEMBER_JOIN = 1  # subject address admitted on a fresh lane
MEMBER_LEAVE = 2  # subject's lane tombstoned at `epoch`
MEMBER_REJOIN = 3  # subject re-attaches to `lane` by presenting `epoch`
_MBR_HEAD = struct.Struct(">BHI")  # version | sender_slot | sender_epoch
_MBR_EVENT = struct.Struct(">BHI")  # op | lane | tombstone/assign epoch
_MEMBER_MAX_ADDR = PACKET_SIZE - _MEMBER_BASE - _MBR_HEAD.size - _MBR_EVENT.size - 2


@dataclasses.dataclass(frozen=True)
class MemberEvent:
    op: int  # MEMBER_JOIN | MEMBER_LEAVE | MEMBER_REJOIN
    lane: int  # subject lane (join: assigned lane; leave/rejoin: the lane)
    epoch: int  # leave: tombstone epoch; rejoin: presented epoch; join: assign epoch
    addr: str  # subject "host:port"


@dataclasses.dataclass(frozen=True)
class MemberPacket:
    sender_slot: int
    sender_epoch: int  # sender's membership epoch AFTER the event
    event: MemberEvent


def encode_member_packet(
    sender_slot: int, sender_epoch: int, event: MemberEvent
) -> bytes:
    """One membership event as one ``\\x00pt!mbr`` datagram (≤256 B)."""
    raw = event.addr.encode("utf-8", "surrogateescape")
    if len(raw) > _MEMBER_MAX_ADDR:
        raise ValueError(f"member address too long ({len(raw)} bytes)")
    env = bytearray(_MEMBER_BASE)
    env[24] = len(_MEMBER_NAME_BYTES)
    env[FIXED_SIZE:] = _MEMBER_NAME_BYTES
    body = bytearray(
        _MBR_HEAD.pack(
            MEMBER_VERSION, sender_slot & 0xFFFF, sender_epoch & 0xFFFFFFFF
        )
    )
    body += _MBR_EVENT.pack(
        event.op & 0xFF, event.lane & 0xFFFF, event.epoch & 0xFFFFFFFF
    )
    body.append(len(raw))
    body += raw
    body.append(sum(body) & 0xFF)
    out = bytes(env) + bytes(body)
    assert len(out) <= PACKET_SIZE
    return out


def is_member_packet(data: bytes) -> bool:
    return (
        len(data) > _MEMBER_BASE
        and data[:24] == b"\x00" * 24
        and data[24] == len(_MEMBER_NAME_BYTES)
        and data[FIXED_SIZE:_MEMBER_BASE] == _MEMBER_NAME_BYTES
    )


def decode_member_packet(data: bytes) -> Optional[MemberPacket]:
    """Strict all-or-nothing decode; ``None`` for anything malformed."""
    end = len(data) - 1
    if end < _MEMBER_BASE + _MBR_HEAD.size + _MBR_EVENT.size + 1:
        return None
    if (
        data[:24] != b"\x00" * 24
        or data[24] != len(_MEMBER_NAME_BYTES)
        or data[FIXED_SIZE:_MEMBER_BASE] != _MEMBER_NAME_BYTES
    ):
        return None
    if data[end] != sum(data[_MEMBER_BASE:end]) & 0xFF:
        return None
    try:
        version, sender_slot, sender_epoch = _MBR_HEAD.unpack_from(
            data, _MEMBER_BASE
        )
        if version != MEMBER_VERSION:
            return None
        off = _MEMBER_BASE + _MBR_HEAD.size
        op, lane, epoch = _MBR_EVENT.unpack_from(data, off)
        off += _MBR_EVENT.size
        if op not in (MEMBER_JOIN, MEMBER_LEAVE, MEMBER_REJOIN):
            return None
        ln = data[off]
        off += 1
        if off + ln > end:
            return None
        addr = data[off : off + ln].decode("utf-8", "surrogateescape")
        off += ln
    except (IndexError, struct.error):
        return None
    if off != end:
        return None  # trailing garbage ⇒ reject whole
    return MemberPacket(sender_slot, sender_epoch, MemberEvent(op, lane, epoch, addr))


# ---------------------------------------------------------------------------
# patrol-cert: certified-kernel lane trailers ("PK").
#
# Each certified limiter family beyond the token bucket ships its own
# exact own-lane watermarks in a self-sized trailer appended AFTER the
# P2 (and trace) trailers, invisible to every peer that does not know
# it — the same self-described-size argument as the P2 trailer itself:
# v1 reference nodes read exactly data[25:25+L], patrol decoders read
# trailers by magic + size and skip unknown tails. Magic "PK" + a kind
# byte select the family; version + checksum make a random tail
# unparseable. Validation is all-or-nothing (PTP003: the obligations
# registry declares encode->decode bit-exact round-trip for every
# trailer below; a torn trailer must never half-apply).
#
# Payloads are the families' OWN-LANE lattice coordinates — monotone
# watermarks a receiver max-merges, never aggregates:
#   GCRA   u64 own TAT watermark (ns)
#   CONC   u64 own acquired, u64 own released (nanotokens)
#   QUOTA  u64 own taken per path level (global, tenant, user)

CERT_TRAILER_MAGIC = b"PK"
CERT_TRAILER_VERSION = 1
CERT_KIND_GCRA = 1
CERT_KIND_CONC = 2
CERT_KIND_QUOTA = 3
_CERT_GCRA = struct.Struct(">2sBBHQB")  # magic|ver|kind|own_slot|tat|ck
_CERT_CONC = struct.Struct(">2sBBHQQB")  # …|acquired|released|ck
_CERT_QUOTA = struct.Struct(">2sBBHQQQB")  # …|taken g|t|u|ck
CERT_GCRA_TRAILER_SIZE = _CERT_GCRA.size
CERT_CONC_TRAILER_SIZE = _CERT_CONC.size
CERT_QUOTA_TRAILER_SIZE = _CERT_QUOTA.size


@dataclasses.dataclass(frozen=True)
class GcraTrailer:
    own_slot: int
    tat_ns: int  # this node's TAT watermark (max-register lane)


@dataclasses.dataclass(frozen=True)
class ConcTrailer:
    own_slot: int
    acquired_nt: int  # own TAKEN lane (monotone acquires)
    released_nt: int  # own ADDED lane (monotone releases, clamp-kept <=)


@dataclasses.dataclass(frozen=True)
class QuotaTrailer:
    own_slot: int
    taken_global_nt: int  # own TAKEN lane of each path level's row
    taken_tenant_nt: int
    taken_user_nt: int


def _cert_clamp(v: int) -> int:
    """Lane watermarks are non-negative int64 on device; clamp before the
    u64 pack so a hostile in-process value cannot wrap."""
    return min(max(int(v), 0), _INT64_MAX)


def _cert_seal(packed: bytes) -> bytes:
    return packed[:-1] + bytes([sum(packed[:-1]) & 0xFF])


def _cert_open(data: bytes, st: struct.Struct, kind: int):
    """Shared all-or-nothing frame checks → unpacked payload or None."""
    if len(data) != st.size:
        return None
    if data[-1] != sum(data[:-1]) & 0xFF:
        return None
    fields = st.unpack(data)
    if fields[0] != CERT_TRAILER_MAGIC or fields[1] != CERT_TRAILER_VERSION:
        return None
    if fields[2] != kind:
        return None
    if any(v > _INT64_MAX for v in fields[4:-1]):
        return None
    return fields


def encode_gcra_trailer(t: GcraTrailer) -> bytes:
    return _cert_seal(
        _CERT_GCRA.pack(
            CERT_TRAILER_MAGIC,
            CERT_TRAILER_VERSION,
            CERT_KIND_GCRA,
            t.own_slot & 0xFFFF,
            _cert_clamp(t.tat_ns),
            0,
        )
    )


def decode_gcra_trailer(data: bytes) -> Optional[GcraTrailer]:
    f = _cert_open(data, _CERT_GCRA, CERT_KIND_GCRA)
    if f is None:
        return None
    return GcraTrailer(own_slot=f[3], tat_ns=f[4])


def encode_conc_trailer(t: ConcTrailer) -> bytes:
    return _cert_seal(
        _CERT_CONC.pack(
            CERT_TRAILER_MAGIC,
            CERT_TRAILER_VERSION,
            CERT_KIND_CONC,
            t.own_slot & 0xFFFF,
            _cert_clamp(t.acquired_nt),
            _cert_clamp(t.released_nt),
            0,
        )
    )


def decode_conc_trailer(data: bytes) -> Optional[ConcTrailer]:
    f = _cert_open(data, _CERT_CONC, CERT_KIND_CONC)
    if f is None:
        return None
    if f[5] > f[4]:
        return None  # released > acquired can never leave a clamped kernel
    return ConcTrailer(own_slot=f[3], acquired_nt=f[4], released_nt=f[5])


def encode_quota_trailer(t: QuotaTrailer) -> bytes:
    return _cert_seal(
        _CERT_QUOTA.pack(
            CERT_TRAILER_MAGIC,
            CERT_TRAILER_VERSION,
            CERT_KIND_QUOTA,
            t.own_slot & 0xFFFF,
            _cert_clamp(t.taken_global_nt),
            _cert_clamp(t.taken_tenant_nt),
            _cert_clamp(t.taken_user_nt),
            0,
        )
    )


def decode_quota_trailer(data: bytes) -> Optional[QuotaTrailer]:
    f = _cert_open(data, _CERT_QUOTA, CERT_KIND_QUOTA)
    if f is None:
        return None
    return QuotaTrailer(
        own_slot=f[3],
        taken_global_nt=f[4],
        taken_tenant_nt=f[5],
        taken_user_nt=f[6],
    )
