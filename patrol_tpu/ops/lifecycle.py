"""Bucket lifecycle kernel — the vectorized IsZero predicate that makes
idle-bucket GC safe (ROADMAP item 4; the reference's ``Bucket.IsZero``
insight, bucket.go's full-bucket reconstruction property).

A limiter bucket is *reconstructible from its rate* exactly when its
reconstructed balance at ``now`` — tokens plus the refill grant the next
take would commit — equals its capacity. Dropping such a bucket and
lazily re-creating it later is observation-equivalent: the very first
take against the fresh row sees the same ``have``/``admitted``/
``remaining`` the old row would have produced, because the old row's
entire history is subsumed by "full at capacity". Cold state can
therefore be *dropped*, not archived, and the dropped state re-enters
through the existing max-lattice join when peers still hold copies
(delta-mutation CRDTs, arXiv:1410.2803: zero lanes are the join's bottom
element, so re-entry is exact by construction).

The refill arithmetic below mirrors :func:`patrol_tpu.ops.take.take_batch`
**step for step** (float64 grant, floor quantization, capacity clamp):
the predicate must agree bit-for-bit with what the take kernel would
grant, or a "full" verdict could reclaim a bucket whose next take would
have seen less than capacity — an admitted-token loss. That conservation
law (plus time-monotonicity of the verdict and join-re-entry exactness)
is machine-checked by the ``lifecycle_iszero`` model suite declared with
this kernel's ``PROVE_ROOTS`` entry (patrol_tpu/ops/obligations.py).

What the engine keeps when it reclaims: the bucket's OWN PN lane (and
its refill clock: ``elapsed``/``created``) goes into a compact host-side
tombstone (runtime/directory.py) and re-seeds the row on re-creation —
the own lane is the one join-decomposition only this node can
regenerate, while every other lane is recoverable from its writer via
the normal join. The probe therefore returns the own-lane values next to
the verdict so the sweep reads each candidate exactly once.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from patrol_tpu.models.limiter import ADDED, TAKEN, NANO, LimiterState
from patrol_tpu.ops.take import _GRANT_CLIP


class LifecycleProbe(NamedTuple):
    """A microbatch of K reclaim-candidate probes. Padding rows carry
    ``cap_base_nt == 0`` (capacity unknown ⇒ never reclaimable), so any
    row index is safe padding — the gather is read-only."""

    rows: jax.Array  # int32[K] bucket-slot indices
    now_ns: jax.Array  # int64[K] sweep clock (the injected-clock seam)
    per_ns: jax.Array  # int64[K] rate period (0 ⇒ unknown: no projection)
    cap_base_nt: jax.Array  # int64[K] capacity base (0 ⇒ not reclaimable)
    created_ns: jax.Array  # int64[K] bucket creation time


class LifecycleView(NamedTuple):
    """Per-candidate verdict plus the tombstone payload (one gather)."""

    full: jax.Array  # bool[K]  reconstructed value == capacity
    own_added_nt: jax.Array  # int64[K] this node's PN lane …
    own_taken_nt: jax.Array  # int64[K] … the tombstone residue
    elapsed_ns: jax.Array  # int64[K] the bucket's refill clock


def lifecycle_probe(
    state: LimiterState, probe: LifecycleProbe, node_slot: int
) -> LifecycleView:
    """Pure read: evaluate the IsZero predicate over a probe batch.

    A bucket is full (reclaimable) iff the refill grant the next take
    would compute covers the distance to capacity — the exact expression
    (and operation order) of ``take_batch``'s grant path, including the
    over-capacity case (``missing <= 0``: merges pushed tokens past
    capacity; the next take forfeits down to capacity, so the row is
    reconstruction-equivalent to a fresh full bucket too). A zero or
    unknown rate projects no grant, so such rows reclaim only when the
    standing balance already covers capacity — conservative, never a
    token lost.
    """
    i64 = jnp.int64
    rows = probe.rows

    pn_rows = state.pn[rows]  # [K, N, 2] gather
    sum_added = pn_rows[:, :, ADDED].sum(axis=-1)
    sum_taken = pn_rows[:, :, TAKEN].sum(axis=-1)
    tokens_nt = probe.cap_base_nt + sum_added - sum_taken

    elapsed = state.elapsed[rows]
    last = jnp.minimum(probe.created_ns + elapsed, probe.now_ns)
    delta = probe.now_ns - last

    freq = probe.cap_base_nt // NANO
    safe_freq = jnp.where(freq == 0, 1, freq)
    interval = probe.per_ns // safe_freq
    rate_zero = (freq == 0) | (probe.per_ns == 0) | (interval == 0)
    safe_interval = jnp.where(interval == 0, 1, interval)
    grant_tokens = delta.astype(jnp.float64) / safe_interval.astype(jnp.float64)
    grant_f = jnp.where(rate_zero, 0.0, grant_tokens * float(NANO))
    grant_nt = jnp.floor(jnp.clip(grant_f, 0.0, _GRANT_CLIP)).astype(i64)

    missing_nt = probe.cap_base_nt - tokens_nt
    full = (probe.cap_base_nt > 0) & (grant_nt >= missing_nt)
    return LifecycleView(
        full=full,
        own_added_nt=pn_rows[:, node_slot, ADDED],
        own_taken_nt=pn_rows[:, node_slot, TAKEN],
        elapsed_ns=elapsed,
    )


# NOT donated: the probe is a pure read — the engine holds _state_mu for
# the call but the state buffers stay live for the next tick.
lifecycle_probe_jit = partial(jax.jit, static_argnames=("node_slot",))(
    lifecycle_probe
)


def host_lifecycle_full(
    sum_added_nt,
    sum_taken_nt,
    elapsed_ns,
    cap_base_nt,
    created_ns,
    now_ns,
    per_ns,
) -> np.ndarray:
    """Numpy reference twin of the kernel's verdict, for host-resident
    lanes (the fast-path buckets GC evaluates under ``_host_mu`` without
    a device hop) and for tests. Same expressions, same operation order —
    any divergence from the kernel is a bug the lifecycle tests pin."""
    sum_added_nt = np.asarray(sum_added_nt, np.int64)
    sum_taken_nt = np.asarray(sum_taken_nt, np.int64)
    elapsed_ns = np.asarray(elapsed_ns, np.int64)
    cap_base_nt = np.asarray(cap_base_nt, np.int64)
    created_ns = np.asarray(created_ns, np.int64)
    per_ns = np.asarray(per_ns, np.int64)

    tokens_nt = cap_base_nt + sum_added_nt - sum_taken_nt
    last = np.minimum(created_ns + elapsed_ns, now_ns)
    delta = now_ns - last

    freq = cap_base_nt // NANO
    safe_freq = np.where(freq == 0, 1, freq)
    interval = per_ns // safe_freq
    rate_zero = (freq == 0) | (per_ns == 0) | (interval == 0)
    safe_interval = np.where(interval == 0, 1, interval)
    grant_f = np.where(
        rate_zero,
        0.0,
        delta.astype(np.float64) / safe_interval.astype(np.float64) * float(NANO),
    )
    grant_nt = np.floor(np.clip(grant_f, 0.0, _GRANT_CLIP)).astype(np.int64)
    missing_nt = cap_base_nt - tokens_nt
    return (cap_base_nt > 0) & (grant_nt >= missing_nt)


def host_reconstructed_nt(
    sum_added_nt,
    sum_taken_nt,
    elapsed_ns,
    cap_base_nt,
    created_ns,
    now_ns,
    per_ns,
) -> np.ndarray:
    """The reconstructed balance at ``now`` — ``have_nt`` exactly as the
    next take would compute it (refill capped at capacity, over-capacity
    forfeited). The soak gate's per-bucket digest field: a GC'd bucket
    reconstructs to capacity by the IsZero contract, and a no-GC
    reference run's live row must reconstruct to the same value."""
    sum_added_nt = np.asarray(sum_added_nt, np.int64)
    sum_taken_nt = np.asarray(sum_taken_nt, np.int64)
    elapsed_ns = np.asarray(elapsed_ns, np.int64)
    cap_base_nt = np.asarray(cap_base_nt, np.int64)
    created_ns = np.asarray(created_ns, np.int64)
    per_ns = np.asarray(per_ns, np.int64)

    tokens_nt = cap_base_nt + sum_added_nt - sum_taken_nt
    last = np.minimum(created_ns + elapsed_ns, now_ns)
    delta = now_ns - last
    freq = cap_base_nt // NANO
    safe_freq = np.where(freq == 0, 1, freq)
    interval = per_ns // safe_freq
    rate_zero = (freq == 0) | (per_ns == 0) | (interval == 0)
    safe_interval = np.where(interval == 0, 1, interval)
    grant_f = np.where(
        rate_zero,
        0.0,
        delta.astype(np.float64) / safe_interval.astype(np.float64) * float(NANO),
    )
    grant_nt = np.floor(np.clip(grant_f, 0.0, _GRANT_CLIP)).astype(np.int64)
    grant_nt = np.minimum(grant_nt, cap_base_nt - tokens_nt)
    return tokens_nt + grant_nt
