"""Declared proof obligations for the kernel roots — the ``PROVE_ROOTS``
registry consumed by ``patrol_tpu/analysis/prove.py`` (patrol-check
stage 4, ``scripts/prove_repo.py``, ``pytest -m prove``).

The registry lives HERE, next to the kernels, for the same reason
lint.py keeps its allowlists at the top of the file: adding a kernel
without declaring its obligations — or weakening an obligation — is a
diff on this file, in code review's line of sight.

Per root:

* the **tracer** builds the abstract shapes the kernel is traced over
  (``jax.make_jaxpr`` — shapes are tiny; the IR is shape-polymorphic in
  all the ways that matter to the lattice structure);
* ``structural`` picks the PTP001 profile — ``"join"`` for CvRDT joins
  (state planes may only flow through max/scatter-max and layout ops),
  ``"callbacks"`` for delta-side kernels whose local adds are the point
  (take's greedy admission; scalar merge's deficit attribution);
* ``model`` names the exhaustive small-domain suite: every declared
  algebraic obligation (commutes / idempotent / monotone) is checked
  bit-exactly over an enumerated tiny lattice.

``merge_scalar_batch`` deliberately declares NO commutativity or
idempotence: deficit attribution against reference peers is documented
as lossy (its docstring) — declaring only PTP004 here records that
design decision machine-checkably instead of in prose.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from patrol_tpu.analysis.abi import AbiObligation
from patrol_tpu.analysis.linearizability import LinSpecFamily
from patrol_tpu.analysis.prove import JOIN_BATCH_ADAPTERS, ProveRoot, Trace
from patrol_tpu.models.limiter import LimiterState
from patrol_tpu.ops.commit import CommitBlocks
from patrol_tpu.ops.delta import DeltaBatch
from patrol_tpu.ops.merge import FoldedMergeBatch, MergeBatch, RowDenseBatch

_S = jax.ShapeDtypeStruct
_B, _N, _K = 4, 2, 3  # declared abstract shapes for tracing


def _state() -> LimiterState:
    return LimiterState(
        pn=_S((_B, _N, 2), jnp.int64), elapsed=_S((_B,), jnp.int64)
    )


def _vec(dtype, k: int = _K):
    return _S((k,), dtype)


def _mk_trace(fn, *args, n_state_in=2, n_state_out=2, shapes_match=True) -> Trace:
    closed = jax.make_jaxpr(fn)(*args)
    return Trace(
        closed,
        range(n_state_in),
        range(n_state_out),
        shapes_must_match=shapes_match,
    )


def _trace_merge_batch(fn) -> Trace:
    batch = MergeBatch(
        rows=_vec(jnp.int32),
        slots=_vec(jnp.int32),
        added_nt=_vec(jnp.int64),
        taken_nt=_vec(jnp.int64),
        elapsed_ns=_vec(jnp.int64),
    )
    return _mk_trace(fn, _state(), batch)


def _trace_merge_batch_folded(fn) -> Trace:
    batch = FoldedMergeBatch(
        rows=_vec(jnp.int32),
        slots=_vec(jnp.int32),
        added_nt=_vec(jnp.int64),
        taken_nt=_vec(jnp.int64),
        erows=_vec(jnp.int32),
        elapsed_ns=_vec(jnp.int64),
    )
    return _mk_trace(fn, _state(), batch)


def _trace_commit_blocks(fn) -> Trace:
    # Two-block ring: the commit kernel's shape class is [J, K], and a
    # J > 1 trace pins the flatten-then-scatter structure the block ring
    # relies on (a J=1 trace would also pass for a per-block loop).
    def _mat(dtype):
        return _S((2, _K), dtype)

    blocks = CommitBlocks(
        rows=_mat(jnp.int32),
        slots=_mat(jnp.int32),
        added_nt=_mat(jnp.int64),
        taken_nt=_mat(jnp.int64),
        erows=_mat(jnp.int32),
        elapsed_ns=_mat(jnp.int64),
    )
    return _mk_trace(fn, _state(), blocks)


def _trace_delta_fold(fn) -> Trace:
    batch = DeltaBatch(
        rows=_vec(jnp.int32),
        slots=_vec(jnp.int32),
        added_nt=_vec(jnp.int64),
        taken_nt=_vec(jnp.int64),
        elapsed_ns=_vec(jnp.int64),
    )
    return _mk_trace(fn, _state(), batch)


def _trace_decode_fold_raw(fn) -> Trace:
    # Tiny raw planes: 2 packets × 128-byte rows (max_entries(128) = 2).
    # State invars are the leading (pn, elapsed) pair; the state outputs
    # lead the verdict/decoded-field outputs, so indices (0, 1) hold on
    # both sides.
    from patrol_tpu.ops.ingest import max_entries

    e = max_entries(128)
    return _mk_trace(
        fn,
        _state(),
        _S((2, 128), jnp.uint8),
        _S((2,), jnp.int32),
        _S((2, e), jnp.int32),  # entry_off (the host framing proposal)
        _S((2, e), jnp.int32),  # rows (the host directory plan)
        _S((2, e), jnp.bool_),
    )


def _trace_merge_rows_dense(fn) -> Trace:
    batch = RowDenseBatch(
        rows=_vec(jnp.int32),
        updates=_S((_K, _N, 2), jnp.int64),
        elapsed_ns=_vec(jnp.int64),
    )
    return _mk_trace(fn, _state(), batch)


def _trace_merge_dense(fn) -> Trace:
    return _mk_trace(fn, _state(), _state())


_R = 2  # traced replica fan-in: power of two ⇒ the butterfly (tree) path


def _trace_tree_converge(fn) -> Trace:
    # Stacked replica planes in, one converged state out: both invars are
    # state-tainted; the leading R dim disappears, so shapes don't match.
    return _mk_trace(
        fn,
        _S((_R, _B, _N, 2), jnp.int64),
        _S((_R, _B), jnp.int64),
        shapes_match=False,
    )


def _trace_read_rows(fn) -> Trace:
    return _mk_trace(fn, _state(), _vec(jnp.int32), shapes_match=False)


def _trace_scalar_batch(fn) -> Trace:
    batch = MergeBatch(
        rows=_vec(jnp.int32),
        slots=_vec(jnp.int32),
        added_nt=_vec(jnp.int64),
        taken_nt=_vec(jnp.int64),
        elapsed_ns=_vec(jnp.int64),
    )
    return _mk_trace(fn, _state(), batch)


def _trace_take_batch(fn) -> Trace:
    from patrol_tpu.ops.take import TakeRequest

    req = TakeRequest(
        rows=_vec(jnp.int32),
        now_ns=_vec(jnp.int64),
        freq=_vec(jnp.int64),
        per_ns=_vec(jnp.int64),
        count_nt=_vec(jnp.int64),
        nreq=_vec(jnp.int64),
        cap_base_nt=_vec(jnp.int64),
        created_ns=_vec(jnp.int64),
    )
    return _mk_trace(lambda s, r: fn(s, r, 1), _state(), req)


def _trace_lifecycle_probe(fn) -> Trace:
    from patrol_tpu.ops.lifecycle import LifecycleProbe

    probe = LifecycleProbe(
        rows=_vec(jnp.int32),
        now_ns=_vec(jnp.int64),
        per_ns=_vec(jnp.int64),
        cap_base_nt=_vec(jnp.int64),
        created_ns=_vec(jnp.int64),
    )
    # Pure read: both state planes are taint sources, NO state outputs —
    # the probe structurally cannot mutate limiter state (the strongest
    # form of the PTP005 stability claim for a GC predicate).
    return _mk_trace(
        lambda s, p: fn(s, p, 1), _state(), probe,
        n_state_out=0, shapes_match=False,
    )


# --- join-batch adapters: single (row, slot, added, taken, elapsed) lattice
# deltas → each kernel's batch type, K=1 (registered for the model checker).


def _as_merge_batch(d) -> MergeBatch:
    return MergeBatch(
        rows=d[0].astype(jnp.int32)[None],
        slots=d[1].astype(jnp.int32)[None],
        added_nt=d[2][None],
        taken_nt=d[3][None],
        elapsed_ns=d[4][None],
    )


def _as_folded_batch(d) -> FoldedMergeBatch:
    # K=1: the sorted/unique flags the kernel asserts are trivially true.
    return FoldedMergeBatch(
        rows=d[0].astype(jnp.int32)[None],
        slots=d[1].astype(jnp.int32)[None],
        added_nt=d[2][None],
        taken_nt=d[3][None],
        erows=d[0].astype(jnp.int32)[None],
        elapsed_ns=d[4][None],
    )


def _as_commit_blocks(d) -> CommitBlocks:
    # J=1, K=1 ring: the asserted sorted/unique flags are trivially true,
    # and the model checker's order/duplication grids become exactly the
    # cross-block coalesce-order question (blocks are delta sets).
    return CommitBlocks(
        rows=d[0].astype(jnp.int32)[None, None],
        slots=d[1].astype(jnp.int32)[None, None],
        added_nt=d[2][None, None],
        taken_nt=d[3][None, None],
        erows=d[0].astype(jnp.int32)[None, None],
        elapsed_ns=d[4][None, None],
    )


def _as_delta_batch(d) -> DeltaBatch:
    return DeltaBatch(
        rows=d[0].astype(jnp.int32)[None],
        slots=d[1].astype(jnp.int32)[None],
        added_nt=d[2][None],
        taken_nt=d[3][None],
        elapsed_ns=d[4][None],
    )


def _as_rows_dense_batch(d) -> RowDenseBatch:
    # One-hot lane window: the delta's (added, taken) in its slot, zeros —
    # the join identity on the non-negative domain — everywhere else.
    upd = jnp.zeros((1, _N, 2), jnp.int64).at[0, d[1]].set(
        jnp.stack([d[2], d[3]])
    )
    return RowDenseBatch(
        rows=d[0].astype(jnp.int32)[None], updates=upd, elapsed_ns=d[4][None]
    )


JOIN_BATCH_ADAPTERS.update(
    merge_batch=_as_merge_batch,
    folded=_as_folded_batch,
    rows_dense=_as_rows_dense_batch,
    commit_blocks=_as_commit_blocks,
    delta_fold=_as_delta_batch,
)

_ALL = ("PTP001", "PTP002", "PTP003", "PTP004", "PTP005")

PROVE_ROOTS: Tuple[ProveRoot, ...] = (
    ProveRoot(
        "ops.merge.merge_batch", "patrol_tpu.ops.merge", "merge_batch",
        _ALL, structural="join", model="join_batch:merge_batch",
        tracer=_trace_merge_batch,
    ),
    ProveRoot(
        "ops.merge.merge_batch_folded", "patrol_tpu.ops.merge",
        "merge_batch_folded", _ALL, structural="join",
        model="join_batch:folded", tracer=_trace_merge_batch_folded,
    ),
    ProveRoot(
        "ops.merge.merge_rows_dense", "patrol_tpu.ops.merge",
        "merge_rows_dense", _ALL, structural="join",
        model="join_batch:rows_dense", tracer=_trace_merge_rows_dense,
    ),
    ProveRoot(
        "ops.commit.commit_blocks", "patrol_tpu.ops.commit",
        "commit_blocks", _ALL, structural="join",
        model="join_batch:commit_blocks", tracer=_trace_commit_blocks,
    ),
    ProveRoot(
        "ops.delta.delta_fold", "patrol_tpu.ops.delta", "delta_fold",
        _ALL, structural="join", model="join_batch:delta_fold",
        tracer=_trace_delta_fold,
    ),
    ProveRoot(
        # Device-resident ingest (r15): raw dv2 datagram byte planes →
        # framing walk + entry extraction + checksum/validation verdicts
        # + sentinel padding + scatter-max fold, ONE dispatch. The
        # ``raw_ingest`` model (analysis/prove.py) checks it against the
        # python wire decoder + reference join over real datagram bytes:
        # packet-order commutativity, duplicated-plane idempotence,
        # monotonicity, and strict all-or-nothing corruption rejection
        # (every truncation/flip verdict must match decode_delta_packet,
        # and rejected planes must merge NOTHING). PTP001 runs the join
        # allowlist on the state planes — the decode arithmetic touches
        # only untainted plane bytes, so the fold leg must stay pure
        # scatter-max; PTP005 pins the state dtypes/shapes.
        "ops.ingest.decode_fold_raw", "patrol_tpu.ops.ingest",
        "decode_fold_raw", _ALL, structural="join", model="raw_ingest",
        tracer=_trace_decode_fold_raw,
    ),
    ProveRoot(
        "ops.merge.merge_dense", "patrol_tpu.ops.merge", "merge_dense",
        _ALL, structural="join", model="dense_join",
        tracer=_trace_merge_dense,
    ),
    ProveRoot(
        # The mesh converge tree (pod-scale serving): the pure butterfly-
        # schedule twin of topology._tree_allreduce_max, model-checked for
        # flat-vs-tree equivalence, leaf-permutation/duplication freedom,
        # and monotonicity across power-of-two AND ragged fan-ins — the
        # laws that make a hierarchical reduction path (Tascade,
        # arXiv:2311.15810) bit-exact for CRDT joins (arXiv:1410.2803).
        "parallel.topology.tree_reduce_states", "patrol_tpu.parallel.topology",
        "tree_reduce_states", _ALL, structural="join",
        model="tree_converge", tracer=_trace_tree_converge,
    ),
    ProveRoot(
        "ops.merge.merge_scalar_batch", "patrol_tpu.ops.merge",
        "merge_scalar_batch", ("PTP001", "PTP004", "PTP005"),
        structural="callbacks", model="scalar_monotone",
        tracer=_trace_scalar_batch,
    ),
    ProveRoot(
        "ops.merge.read_rows", "patrol_tpu.ops.merge", "read_rows",
        ("PTP001", "PTP005"), structural="join", tracer=_trace_read_rows,
    ),
    ProveRoot(
        "ops.take.take_batch", "patrol_tpu.ops.take", "take_batch",
        ("PTP001", "PTP004", "PTP005"), structural="callbacks",
        model="take_monotone", tracer=_trace_take_batch,
    ),
    ProveRoot(
        # The bucket-lifecycle IsZero predicate (idle-bucket GC, ROADMAP
        # item 4): full obligation set, with the algebraic codes mapped
        # onto the GC conservation laws by the ``lifecycle_iszero`` model
        # (analysis/prove.py) — PTP002: a "full" verdict is *sound*
        # (reclaim-then-recreate is take-observation-equivalent to the
        # original row, bit-exact against the take kernel — the admitted-
        # token conservation law); PTP003: reclaim re-entry is exact
        # (zero lanes are the join's bottom, so join(fresh, old) == old);
        # PTP004: the verdict is monotone in time (a missed sweep window
        # can only delay a reclaim, never invalidate it). PTP001/PTP005
        # run structurally: no callbacks, and NO state outputs at all —
        # the predicate is a pure read.
        "ops.lifecycle.lifecycle_probe", "patrol_tpu.ops.lifecycle",
        "lifecycle_probe", _ALL, structural="callbacks",
        model="lifecycle_iszero", tracer=_trace_lifecycle_probe,
    ),
    ProveRoot(
        "ops.rate", "patrol_tpu.ops.rate", "parse_rate",
        ("PTP003", "PTP004"), model="rate_algebra",
    ),
    ProveRoot(
        "ops.wire.codec", "patrol_tpu.ops.wire", "encode",
        ("PTP003",), model="wire_roundtrip",
    ),
    ProveRoot(
        "ops.wire.delta_codec", "patrol_tpu.ops.wire", "encode_delta_packet",
        ("PTP003",), model="delta_roundtrip",
    ),
    ProveRoot(
        "ops.pallas_merge.merge_batch_pallas", "patrol_tpu.ops.pallas_merge",
        "merge_batch_pallas", ("PTP002", "PTP003"),
        model="pallas_interpret",
    ),
)


# --- PTP006 (registration completeness): kernels the runtime engines
# dispatch through jit that are deliberately NOT in PROVE_ROOTS, each
# with the reason on record. analysis/prove.py sweeps the engine
# dispatch graph and flags any jitted kernel found in neither registry —
# a new kernel can no longer land without declared obligations.
PROVE_EXEMPT: frozenset = frozenset(
    {
        # zero_rows writes constant zeros into selected rows — a pure
        # scatter of the lattice bottom with no algebra of its own. Its
        # lattice-facing laws are certified where they matter: the
        # lifecycle_iszero model proves reclaim-then-recreate (which IS
        # zero_rows + re-seed) take-observation-equivalent and join-
        # re-entry exact (PTP002/PTP003 on ops.lifecycle.lifecycle_probe).
        ("patrol_tpu.ops.merge", "zero_rows"),
    }
)


# --- patrol-lin (stage 8): replication-aware linearizability specs, one
# per take-capable kernel family (analysis/linearizability.py,
# scripts/lin_repo.py, PTN001-005). Registered HERE for the same reason
# PROVE_ROOTS is: a new kernel family without a sequential-spec
# registration — or a weakened one — is a diff on this file. Each entry
# names the real kernel the spec is pinned to by tests/test_lin.py's
# differentials, the wire plane its replication model rides, and whether
# lifecycle (refill + GC re-creation) events are in its alphabet.
LIN_SPECS: Tuple[LinSpecFamily, ...] = (
    LinSpecFamily(
        "ops.take.take_batch", "patrol_tpu.ops.take", "take_batch",
        wire="full",
        note="classic take: v1 full-state broadcast, admission from the "
        "full local view with the over-capacity forfeit clamp",
    ),
    LinSpecFamily(
        "ops.delta.delta_fold", "patrol_tpu.ops.delta", "delta_fold",
        wire="delta",
        note="delta-fold ingest: wire-v2 absolute own-lane intervals, "
        "visibility carried by the folded watermarks",
    ),
    LinSpecFamily(
        "ops.lifecycle.lifecycle_probe", "patrol_tpu.ops.lifecycle",
        "lifecycle_probe", wire="full", lifecycle=True,
        note="lifecycle GC re-creation: IsZero reclaim with the "
        "tombstoned own lane, refills in the schedule alphabet",
    ),
)


# --- patrol-abi (stage 5): the NATIVE re-implementations of the joins
# above, checked through the C ABI itself (analysis/abi.py). Declared
# HERE for the same reason PROVE_ROOTS is: adding a native fast path
# without declaring its conformance twin — or dropping a law — is a diff
# on this file. ``twins`` name the PROVE_ROOTS entries the symbol must
# stay bit-exact against (resolved dynamically, so a mutated kernel is
# what gets compared).

ABI_OBLIGATIONS: Tuple[AbiObligation, ...] = (
    AbiObligation(
        "native.pt_fold_hybrid", "pt_fold_hybrid",
        ("PTA001", "PTA002", "PTA003"), "fold_conformance",
        twins=(
            "ops.merge.merge_batch",
            "ops.merge.merge_batch_folded",
            "ops.merge.merge_rows_dense",
        ),
    ),
    AbiObligation(
        "native.pt_rx_classify", "pt_rx_classify",
        ("PTA001", "PTA002", "PTA003"), "classify_conformance",
        twins=("ops.wire.codec",),
    ),
    AbiObligation(
        "native.hls_schedules", "pt_hls_take_probe", ("PTA004",),
        "hls_interleavings",
    ),
    AbiObligation(
        # Zero-copy rx ring (device-resident ingest): every interleaving
        # of lease (rx thread) vs commit (engine completer — "the pump"
        # of the plane hand-off) against a lowest-free-first model, plus
        # the double-commit / stray-index refusals that guard the
        # use-after-recycle class.
        "native.rx_ring_schedules", "pt_rx_ring_lease", ("PTA004",),
        "rxring_interleavings",
    ),
    AbiObligation(
        "native.effects_table", None, ("PTA005",), "effects_table",
    ),
)
