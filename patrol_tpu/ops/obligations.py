"""The kernel-certification kit: every limiter lattice family the repo
ships is registered HERE as one declarative :class:`KernelFamily`
record — its proof obligations (``analysis/prove.py``, stage 4), its
native-ABI twins (stage 5), its protocol-model hook (stage 6), its
linearizability spec (stage 8), its wire codec, its bench smoke fields,
and the seeded mutations the stack must demonstrably reject (stage 9,
``analysis/cert.py``, PTK001-005).

The registry lives next to the kernels, for the same reason lint.py
keeps its allowlists at the top of the file: adding a kernel without
declaring its obligations — or weakening an obligation — is a diff on
this file, in code review's line of sight. The cert stage closes the
remaining gap: a family that declares itself but never reaches a
checking stage (PTK001), a seeded mutation the stack fails to reject
with the exact registered code (PTK002), an obligation declared absent
without a written justification (PTK003), or a jitted lattice kernel in
ops/ registered in no family at all (PTK004) is each a finding.

Per prove root (unchanged semantics from the flat-registry era):

* the **tracer** builds the abstract shapes the kernel is traced over
  (``jax.make_jaxpr`` — shapes are tiny; the IR is shape-polymorphic in
  all the ways that matter to the lattice structure);
* ``structural`` picks the PTP001 profile — ``"join"`` for CvRDT joins
  (state planes may only flow through max/scatter-max and layout ops),
  ``"callbacks"`` for delta-side kernels whose local adds are the point
  (take's greedy admission; scalar merge's deficit attribution);
* ``model`` names the exhaustive small-domain suite: every declared
  algebraic obligation (commutes / idempotent / monotone) is checked
  bit-exactly over an enumerated tiny lattice.

The flat ``PROVE_ROOTS`` / ``LIN_SPECS`` / ``ABI_OBLIGATIONS`` tuples
the stage drivers and tests consume are DERIVED from the family records
at the bottom of this file — same names, same entries, one source of
truth.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp

from patrol_tpu.analysis.abi import AbiObligation
from patrol_tpu.analysis.linearizability import LinSpecFamily
from patrol_tpu.analysis.protocol import ConcLaws, GcraLaws, QuotaLaws
from patrol_tpu.analysis.prove import JOIN_BATCH_ADAPTERS, ProveRoot, Trace
from patrol_tpu.models.limiter import ADDED, TAKEN, LimiterState
from patrol_tpu.ops.commit import CommitBlocks
from patrol_tpu.ops.delta import DeltaBatch
from patrol_tpu.ops.merge import FoldedMergeBatch, MergeBatch, RowDenseBatch

_S = jax.ShapeDtypeStruct
_B, _N, _K = 4, 2, 3  # declared abstract shapes for tracing


def _state() -> LimiterState:
    return LimiterState(
        pn=_S((_B, _N, 2), jnp.int64), elapsed=_S((_B,), jnp.int64)
    )


def _vec(dtype, k: int = _K):
    return _S((k,), dtype)


def _mk_trace(fn, *args, n_state_in=2, n_state_out=2, shapes_match=True) -> Trace:
    closed = jax.make_jaxpr(fn)(*args)
    return Trace(
        closed,
        range(n_state_in),
        range(n_state_out),
        shapes_must_match=shapes_match,
    )


def _trace_merge_batch(fn) -> Trace:
    batch = MergeBatch(
        rows=_vec(jnp.int32),
        slots=_vec(jnp.int32),
        added_nt=_vec(jnp.int64),
        taken_nt=_vec(jnp.int64),
        elapsed_ns=_vec(jnp.int64),
    )
    return _mk_trace(fn, _state(), batch)


def _trace_merge_batch_folded(fn) -> Trace:
    batch = FoldedMergeBatch(
        rows=_vec(jnp.int32),
        slots=_vec(jnp.int32),
        added_nt=_vec(jnp.int64),
        taken_nt=_vec(jnp.int64),
        erows=_vec(jnp.int32),
        elapsed_ns=_vec(jnp.int64),
    )
    return _mk_trace(fn, _state(), batch)


def _trace_commit_blocks(fn) -> Trace:
    # Two-block ring: the commit kernel's shape class is [J, K], and a
    # J > 1 trace pins the flatten-then-scatter structure the block ring
    # relies on (a J=1 trace would also pass for a per-block loop).
    def _mat(dtype):
        return _S((2, _K), dtype)

    blocks = CommitBlocks(
        rows=_mat(jnp.int32),
        slots=_mat(jnp.int32),
        added_nt=_mat(jnp.int64),
        taken_nt=_mat(jnp.int64),
        erows=_mat(jnp.int32),
        elapsed_ns=_mat(jnp.int64),
    )
    return _mk_trace(fn, _state(), blocks)


def _trace_delta_fold(fn) -> Trace:
    batch = DeltaBatch(
        rows=_vec(jnp.int32),
        slots=_vec(jnp.int32),
        added_nt=_vec(jnp.int64),
        taken_nt=_vec(jnp.int64),
        elapsed_ns=_vec(jnp.int64),
    )
    return _mk_trace(fn, _state(), batch)


def _trace_decode_fold_raw(fn) -> Trace:
    # Tiny raw planes: 2 packets × 128-byte rows (max_entries(128) = 2).
    # State invars are the leading (pn, elapsed) pair; the state outputs
    # lead the verdict/decoded-field outputs, so indices (0, 1) hold on
    # both sides.
    from patrol_tpu.ops.ingest import max_entries

    e = max_entries(128)
    return _mk_trace(
        fn,
        _state(),
        _S((2, 128), jnp.uint8),
        _S((2,), jnp.int32),
        _S((2, e), jnp.int32),  # entry_off (the host framing proposal)
        _S((2, e), jnp.int32),  # rows (the host directory plan)
        _S((2, e), jnp.bool_),
    )


def _trace_merge_rows_dense(fn) -> Trace:
    batch = RowDenseBatch(
        rows=_vec(jnp.int32),
        updates=_S((_K, _N, 2), jnp.int64),
        elapsed_ns=_vec(jnp.int64),
    )
    return _mk_trace(fn, _state(), batch)


def _trace_merge_dense(fn) -> Trace:
    return _mk_trace(fn, _state(), _state())


_R = 2  # traced replica fan-in: power of two ⇒ the butterfly (tree) path


def _trace_tree_converge(fn) -> Trace:
    # Stacked replica planes in, one converged state out: both invars are
    # state-tainted; the leading R dim disappears, so shapes don't match.
    return _mk_trace(
        fn,
        _S((_R, _B, _N, 2), jnp.int64),
        _S((_R, _B), jnp.int64),
        shapes_match=False,
    )


def _trace_read_rows(fn) -> Trace:
    return _mk_trace(fn, _state(), _vec(jnp.int32), shapes_match=False)


def _trace_scalar_batch(fn) -> Trace:
    batch = MergeBatch(
        rows=_vec(jnp.int32),
        slots=_vec(jnp.int32),
        added_nt=_vec(jnp.int64),
        taken_nt=_vec(jnp.int64),
        elapsed_ns=_vec(jnp.int64),
    )
    return _mk_trace(fn, _state(), batch)


def _trace_take_batch(fn) -> Trace:
    from patrol_tpu.ops.take import TakeRequest

    req = TakeRequest(
        rows=_vec(jnp.int32),
        now_ns=_vec(jnp.int64),
        freq=_vec(jnp.int64),
        per_ns=_vec(jnp.int64),
        count_nt=_vec(jnp.int64),
        nreq=_vec(jnp.int64),
        cap_base_nt=_vec(jnp.int64),
        created_ns=_vec(jnp.int64),
    )
    return _mk_trace(lambda s, r: fn(s, r, 1), _state(), req)


def _trace_take_n_batch(fn) -> Trace:
    from patrol_tpu.ops.take import TAKE_PACK_ROWS

    # The feeder's exact transfer layout: ONE int64[TAKE_PACK_ROWS, K]
    # request matrix (the coalesced nreq row included). State planes
    # lead both sides, so the default (0, 1) indices hold.
    return _mk_trace(
        lambda s, p: fn(s, p, 1),
        _state(),
        _S((TAKE_PACK_ROWS, _K), jnp.int64),
    )


def _trace_lifecycle_probe(fn) -> Trace:
    from patrol_tpu.ops.lifecycle import LifecycleProbe

    probe = LifecycleProbe(
        rows=_vec(jnp.int32),
        now_ns=_vec(jnp.int64),
        per_ns=_vec(jnp.int64),
        cap_base_nt=_vec(jnp.int64),
        created_ns=_vec(jnp.int64),
    )
    # Pure read: both state planes are taint sources, NO state outputs —
    # the probe structurally cannot mutate limiter state (the strongest
    # form of the PTP005 stability claim for a GC predicate).
    return _mk_trace(
        lambda s, p: fn(s, p, 1), _state(), probe,
        n_state_out=0, shapes_match=False,
    )


def _trace_gcra_take(fn) -> Trace:
    from patrol_tpu.ops.gcra import GcraRequest

    req = GcraRequest(
        rows=_vec(jnp.int32),
        now_ns=_vec(jnp.int64),
        emission_ns=_vec(jnp.int64),
        tol_ns=_vec(jnp.int64),
        nreq=_vec(jnp.int64),
    )
    return _mk_trace(lambda s, r: fn(s, r, 1), _state(), req)


def _trace_conc_acquire(fn) -> Trace:
    from patrol_tpu.ops.concurrency import ConcRequest

    req = ConcRequest(
        rows=_vec(jnp.int32),
        limit_nt=_vec(jnp.int64),
        count_nt=_vec(jnp.int64),
        nreq=_vec(jnp.int64),
        releases=_vec(jnp.int64),
    )
    return _mk_trace(lambda s, r: fn(s, r, 1), _state(), req)


def _trace_quota_take(fn) -> Trace:
    from patrol_tpu.ops.hierquota import QuotaRequest

    req = QuotaRequest(
        rows_global=_vec(jnp.int32),
        rows_tenant=_vec(jnp.int32),
        rows_user=_vec(jnp.int32),
        limit_global_nt=_vec(jnp.int64),
        limit_tenant_nt=_vec(jnp.int64),
        limit_user_nt=_vec(jnp.int64),
        count_nt=_vec(jnp.int64),
        nreq=_vec(jnp.int64),
    )
    return _mk_trace(lambda s, r: fn(s, r, 1), _state(), req)


# --- join-batch adapters: single (row, slot, added, taken, elapsed) lattice
# deltas → each kernel's batch type, K=1 (registered for the model checker).


def _as_merge_batch(d) -> MergeBatch:
    return MergeBatch(
        rows=d[0].astype(jnp.int32)[None],
        slots=d[1].astype(jnp.int32)[None],
        added_nt=d[2][None],
        taken_nt=d[3][None],
        elapsed_ns=d[4][None],
    )


def _as_folded_batch(d) -> FoldedMergeBatch:
    # K=1: the sorted/unique flags the kernel asserts are trivially true.
    return FoldedMergeBatch(
        rows=d[0].astype(jnp.int32)[None],
        slots=d[1].astype(jnp.int32)[None],
        added_nt=d[2][None],
        taken_nt=d[3][None],
        erows=d[0].astype(jnp.int32)[None],
        elapsed_ns=d[4][None],
    )


def _as_commit_blocks(d) -> CommitBlocks:
    # J=1, K=1 ring: the asserted sorted/unique flags are trivially true,
    # and the model checker's order/duplication grids become exactly the
    # cross-block coalesce-order question (blocks are delta sets).
    return CommitBlocks(
        rows=d[0].astype(jnp.int32)[None, None],
        slots=d[1].astype(jnp.int32)[None, None],
        added_nt=d[2][None, None],
        taken_nt=d[3][None, None],
        erows=d[0].astype(jnp.int32)[None, None],
        elapsed_ns=d[4][None, None],
    )


def _as_delta_batch(d) -> DeltaBatch:
    return DeltaBatch(
        rows=d[0].astype(jnp.int32)[None],
        slots=d[1].astype(jnp.int32)[None],
        added_nt=d[2][None],
        taken_nt=d[3][None],
        elapsed_ns=d[4][None],
    )


def _as_rows_dense_batch(d) -> RowDenseBatch:
    # One-hot lane window: the delta's (added, taken) in its slot, zeros —
    # the join identity on the non-negative domain — everywhere else.
    upd = jnp.zeros((1, _N, 2), jnp.int64).at[0, d[1]].set(
        jnp.stack([d[2], d[3]])
    )
    return RowDenseBatch(
        rows=d[0].astype(jnp.int32)[None], updates=upd, elapsed_ns=d[4][None]
    )


JOIN_BATCH_ADAPTERS.update(
    merge_batch=_as_merge_batch,
    folded=_as_folded_batch,
    rows_dense=_as_rows_dense_batch,
    commit_blocks=_as_commit_blocks,
    delta_fold=_as_delta_batch,
)

_ALL = ("PTP001", "PTP002", "PTP003", "PTP004", "PTP005")


# ---------------------------------------------------------------------------
# The certification record types.


@dataclasses.dataclass(frozen=True)
class CertMutation:
    """One seeded mutation a family registers: a deliberately broken
    variant of the family's semantics that the checking stack MUST
    reject with ``expect`` (the exact PT code, pinned — a mutation that
    trips a *different* code means the check that was supposed to own
    this hazard has gone soft).

    ``stage`` selects the executor (``analysis/cert.py``):

    * ``"prove"`` — ``mutant`` is a drop-in replacement kernel;
      executed via ``prove_root(root, fn=mutant)`` against the family
      root named by ``target``.
    * ``"protocol"`` with ``laws`` — a family-law payload; executed via
      ``protocol.FAMILY_CHECKS[target](laws=laws)``.
    * ``"protocol"`` without ``laws`` — a reference to a legacy
      ``protocol.MUTATIONS`` entry named ``target``; cert re-executes
      it through ``check_protocol`` and pins the code.
    * ``"lin"`` — a reference to a ``linearizability.LIN_MUTATIONS``
      entry named ``target``; cert checks registration + that the
      registered expect matches (stage 8 executes the schedule suite —
      re-running the full enumeration per cert pass would double the
      gate's cost for no extra signal).
    """

    name: str
    stage: str  # "prove" | "protocol" | "lin"
    target: str
    expect: str
    note: str = ""
    mutant: Optional[Callable] = None  # stage="prove" payload
    laws: Optional[object] = None  # stage="protocol" family-law payload


@dataclasses.dataclass(frozen=True)
class KernelFamily:
    """One certified lattice family: the full declarative record the
    cert meta-checker (stage 9) walks.

    ``absent`` carries the REQUIRED justification strings for every
    obligation code a prove root deliberately does not declare, keyed
    ``"<root-name>:<code>"`` — PTK003 rejects a missing code with no
    justification AND a stale justification for a code the root in fact
    declares. ``*_exempt`` fields likewise carry justifications for a
    whole stage the family doesn't reach (empty string = not exempt,
    the stage is required)."""

    name: str
    domain: str  # the lattice, in one line
    prove_roots: Tuple[ProveRoot, ...]
    absent: Mapping[str, str] = dataclasses.field(default_factory=dict)
    lin_specs: Tuple[LinSpecFamily, ...] = ()
    lin_exempt: str = ""
    protocol: Optional[str] = None  # protocol.FAMILY_CHECKS key
    protocol_exempt: str = ""
    abi: Tuple[AbiObligation, ...] = ()
    wire_codec: Optional[str] = None  # ProveRoot.name of the codec root
    bench_fields: Tuple[str, ...] = ()  # literals bench.py must emit
    bench_exempt: str = ""
    mutations: Tuple[CertMutation, ...] = ()
    mutations_exempt: str = ""
    note: str = ""


def _codec_absent(root_name: str) -> Dict[str, str]:
    """The shared absence record for host-side wire codec roots: pure
    Python byte codecs have no jaxpr to lint (PTP001/PTP005), no lattice
    algebra of their own (PTP002/PTP004) — round-trip exactness PTP003
    is the whole contract."""
    why_py = "host-side python codec: no jaxpr, nothing to trace"
    why_alg = (
        "codecs carry lattice coordinates but compute no joins; "
        "PTP003 round-trip exactness is the entire obligation"
    )
    return {
        f"{root_name}:PTP001": why_py,
        f"{root_name}:PTP002": why_alg,
        f"{root_name}:PTP004": why_alg,
        f"{root_name}:PTP005": why_py,
    }


# ---------------------------------------------------------------------------
# Seeded prove-stage mutants (PTK002 payloads). Each is a full drop-in
# copy of its kernel with exactly one seeded defect — the
# family-specific CRDT hazard its docstring names — and each must be
# rejected by the family's model suite with exactly PTP002.


def _mutant_gcra_window_off_by_one(state, req, node_slot):
    """gcra_take_batch with the conformance window widened by one
    emission interval: admits a burst of burst+1."""
    from patrol_tpu.ops.gcra import GcraResult

    i64 = jnp.int64
    rows = req.rows
    pn_rows = state.pn[rows]
    own_tat = pn_rows[:, node_slot, TAKEN]
    tat = pn_rows[:, :, TAKEN].max(axis=-1)

    base = jnp.maximum(tat, req.now_ns)
    deadline = req.now_ns + req.tol_ns + req.emission_ns  # SEEDED defect
    conforms = tat <= deadline

    safe_t = jnp.where(req.emission_ns <= 0, 1, req.emission_ns)
    extras = jnp.maximum(deadline - base, i64(0)) // safe_t
    k = jnp.where(conforms, 1 + extras, 0)
    k = jnp.where(req.emission_ns > 0, k, 0)
    k = jnp.clip(k, 0, req.nreq)

    new_own = jnp.where(k >= 1, base + k * req.emission_ns, own_tat)
    pn = state.pn.at[rows, node_slot, TAKEN].max(new_own)

    tat_out = jnp.maximum(tat, new_own)
    result = GcraResult(
        admitted=k,
        tat_ns=tat_out,
        own_tat_ns=jnp.maximum(own_tat, new_own),
        allow_at_ns=tat_out - req.tol_ns,
    )
    return LimiterState(pn=pn, elapsed=state.elapsed), result


def _mutant_conc_release_unclamped(state, req, node_slot):
    """conc_acquire_batch without the own-lane release clamp: a phantom
    release drives ADDED past TAKEN and the cluster over-admits
    forever."""
    from patrol_tpu.ops.concurrency import ConcResult

    i64 = jnp.int64
    rows = req.rows
    pn_rows = state.pn[rows]
    own_added = pn_rows[:, node_slot, ADDED]
    own_taken = pn_rows[:, node_slot, TAKEN]
    sum_added = pn_rows[:, :, ADDED].sum(axis=-1)
    sum_taken = pn_rows[:, :, TAKEN].sum(axis=-1)

    want_rel = jnp.maximum(req.releases, i64(0)) * jnp.maximum(
        req.count_nt, i64(0)
    )
    d_rel = want_rel  # SEEDED defect: clamp dropped

    inflight = sum_taken - (sum_added + d_rel)
    headroom = req.limit_nt - inflight
    safe_count = jnp.where(req.count_nt <= 0, 1, req.count_nt)
    k = jnp.clip(headroom // safe_count, 0, req.nreq)
    k = jnp.where(req.count_nt > 0, k, 0)
    d_acq = k * req.count_nt

    pair = jnp.stack([d_rel, d_acq], axis=-1)
    pn = state.pn.at[rows, node_slot].add(pair)

    result = ConcResult(
        admitted=k,
        released_nt=d_rel,
        inflight_nt=inflight + d_acq,
        own_acquired_nt=own_taken + d_acq,
        own_released_nt=own_added + d_rel,
        clamped_nt=want_rel - d_rel,
    )
    return LimiterState(pn=pn, elapsed=state.elapsed), result


def _mutant_quota_admit_leaf_only(state, req, node_slot):
    """quota_take_batch admitting against the leaf headroom only: a
    tenant's users collectively overrun the tenant/global budgets."""
    from patrol_tpu.ops.hierquota import QuotaResult

    rows = jnp.concatenate([req.rows_global, req.rows_tenant, req.rows_user])
    pn_rows = state.pn[rows]
    spend = pn_rows[:, :, TAKEN].sum(axis=-1)
    k_batch = req.rows_user.shape[0]
    spend_g = spend[:k_batch]
    spend_t = spend[k_batch : 2 * k_batch]
    spend_u = spend[2 * k_batch :]

    head_g = req.limit_global_nt - spend_g
    head_t = req.limit_tenant_nt - spend_t
    head_u = req.limit_user_nt - spend_u
    head_min = head_u  # SEEDED defect: ancestors not consulted

    safe_count = jnp.where(req.count_nt <= 0, 1, req.count_nt)
    k = jnp.clip(head_min // safe_count, 0, req.nreq)
    k = jnp.where(req.count_nt > 0, k, 0)
    d = k * req.count_nt

    debit = jnp.concatenate([d, d, d])
    pn = state.pn.at[rows, node_slot, TAKEN].add(debit)

    result = QuotaResult(
        admitted=k,
        headroom_global_nt=head_g - d,
        headroom_tenant_nt=head_t - d,
        headroom_user_nt=head_u - d,
        own_taken_user_nt=pn_rows[2 * k_batch :, node_slot, TAKEN] + d,
    )
    return LimiterState(pn=pn, elapsed=state.elapsed), result


def _mutant_take_n_uncapped(state, packed, node_slot):
    """take_n_batch with the crowd-size clip dropped: the greedy grant
    admits ``have // count`` takes even past the ``nreq`` tickets
    actually waiting (and padding rows with ``nreq == 0`` start
    committing) — the coalesced row no longer replays the sequential
    per-ticket outcomes."""
    from patrol_tpu.ops.take import take_n_batch

    lifted = packed.at[5].set(jnp.int64(1) << 40)  # SEEDED defect
    return take_n_batch(state, lifted, node_slot)


def _mutant_split_grant_lifo(have_nt, admitted, count_nt, nreq):
    """split_grant admitting the LAST k tickets instead of the first:
    late arrivals jump the crowd — the aggregate grant is unchanged,
    but the FIFO fan-out order the tickets were promised is broken."""
    from patrol_tpu.ops.take import remaining_for_request

    return [
        remaining_for_request(have_nt, admitted, count_nt, nreq - 1 - i)
        for i in range(nreq)  # SEEDED defect: arrival order reversed
    ]


def _mutant_split_deny_charges(have_nt, admitted, count_nt, nreq):
    """split_grant charging denied tickets as if they had committed: a
    deny storm walks the REPORTED balance down a ledger nobody spent
    (admission itself is untouched — only the observable remaining
    drifts, the drift a replayed hot-key flood would amplify)."""
    from patrol_tpu.models.limiter import NANO

    out = []
    for i in range(nreq):
        ok = i < admitted
        remaining_nt = have_nt - (i + 1) * count_nt  # SEEDED defect
        out.append((max(remaining_nt, 0) // NANO, ok))
    return out


# ---------------------------------------------------------------------------
# The families.


KERNEL_FAMILIES: Tuple[KernelFamily, ...] = (
    KernelFamily(
        name="merge-join",
        domain="per-lane max join over the shared PN planes (the CvRDT "
        "merge every replication path reduces to)",
        prove_roots=(
            ProveRoot(
                "ops.merge.merge_batch", "patrol_tpu.ops.merge",
                "merge_batch", _ALL, structural="join",
                model="join_batch:merge_batch", tracer=_trace_merge_batch,
            ),
            ProveRoot(
                "ops.merge.merge_batch_folded", "patrol_tpu.ops.merge",
                "merge_batch_folded", _ALL, structural="join",
                model="join_batch:folded", tracer=_trace_merge_batch_folded,
            ),
            ProveRoot(
                "ops.merge.merge_rows_dense", "patrol_tpu.ops.merge",
                "merge_rows_dense", _ALL, structural="join",
                model="join_batch:rows_dense", tracer=_trace_merge_rows_dense,
            ),
            ProveRoot(
                "ops.commit.commit_blocks", "patrol_tpu.ops.commit",
                "commit_blocks", _ALL, structural="join",
                model="join_batch:commit_blocks", tracer=_trace_commit_blocks,
            ),
            ProveRoot(
                "ops.merge.merge_dense", "patrol_tpu.ops.merge",
                "merge_dense", _ALL, structural="join", model="dense_join",
                tracer=_trace_merge_dense,
            ),
            ProveRoot(
                # The mesh converge tree (pod-scale serving): the pure
                # butterfly-schedule twin of topology._tree_allreduce_max,
                # model-checked for flat-vs-tree equivalence, leaf-
                # permutation/duplication freedom, and monotonicity across
                # power-of-two AND ragged fan-ins — the laws that make a
                # hierarchical reduction path (Tascade, arXiv:2311.15810)
                # bit-exact for CRDT joins (arXiv:1410.2803).
                "parallel.topology.tree_reduce_states",
                "patrol_tpu.parallel.topology", "tree_reduce_states", _ALL,
                structural="join", model="tree_converge",
                tracer=_trace_tree_converge,
            ),
            ProveRoot(
                "ops.merge.read_rows", "patrol_tpu.ops.merge", "read_rows",
                ("PTP001", "PTP005"), structural="join",
                tracer=_trace_read_rows,
            ),
            ProveRoot(
                "ops.pallas_merge.merge_batch_pallas",
                "patrol_tpu.ops.pallas_merge", "merge_batch_pallas",
                ("PTP002", "PTP003"), model="pallas_interpret",
            ),
        ),
        absent={
            "ops.merge.read_rows:PTP002": (
                "pure gather: no algebra to replay — bit-exactness is "
                "covered by the engines' own read-back differentials"
            ),
            "ops.merge.read_rows:PTP003": (
                "a read commits nothing; there is no inverse to be exact "
                "against"
            ),
            "ops.merge.read_rows:PTP004": (
                "reads don't move the lattice; monotonicity is vacuous"
            ),
            "ops.pallas_merge.merge_batch_pallas:PTP001": (
                "pallas kernels lower to mosaic, not a lintable jaxpr; "
                "the interpret-mode model checks it bit-exact against "
                "merge_batch, which IS PTP001-linted"
            ),
            "ops.pallas_merge.merge_batch_pallas:PTP004": (
                "monotonicity is inherited from the bit-exact twin "
                "merge_batch via the pallas_interpret differential"
            ),
            "ops.pallas_merge.merge_batch_pallas:PTP005": (
                "no traceable jaxpr in interpret-free mode; shape/dtype "
                "stability rides the twin differential"
            ),
        },
        lin_exempt=(
            "joins are the replication substrate the lin model itself "
            "applies between events; ops.take.take_batch's spec covers "
            "the admission-facing surface"
        ),
        protocol="bucket-full",
        abi=(
            AbiObligation(
                "native.pt_fold_hybrid", "pt_fold_hybrid",
                ("PTA001", "PTA002", "PTA003"), "fold_conformance",
                twins=(
                    "ops.merge.merge_batch",
                    "ops.merge.merge_batch_folded",
                    "ops.merge.merge_rows_dense",
                ),
            ),
        ),
        bench_fields=("ingest_commit_equivalence",),
        mutations=(
            CertMutation(
                "merge-sums-instead-of-maxes", "protocol",
                "merge-sums-instead-of-maxes", "PTC001",
                note="join degenerates to a counter sum; replayed "
                "deliveries double-count",
            ),
            CertMutation(
                "merge-assigns-lww", "protocol", "merge-assigns-lww",
                "PTC002",
                note="last-writer-wins assignment loses concurrent lanes",
            ),
            CertMutation(
                "resync-overwrites-instead-of-joins", "protocol",
                "resync-overwrites-instead-of-joins", "PTC002",
                note="anti-entropy that overwrites forks the replicas it "
                "was meant to heal",
            ),
        ),
    ),
    KernelFamily(
        name="scalar-merge",
        domain="lossy scalar deficit attribution against reference peers "
        "(documented non-CRDT: PTP002/PTP003 deliberately absent)",
        prove_roots=(
            ProveRoot(
                "ops.merge.merge_scalar_batch", "patrol_tpu.ops.merge",
                "merge_scalar_batch", ("PTP001", "PTP004", "PTP005"),
                structural="callbacks", model="scalar_monotone",
                tracer=_trace_scalar_batch,
            ),
        ),
        absent={
            "ops.merge.merge_scalar_batch:PTP002": (
                "deficit attribution against reference peers is documented "
                "as lossy (kernel docstring): declaring only PTP004 "
                "records that design decision machine-checkably"
            ),
            "ops.merge.merge_scalar_batch:PTP003": (
                "no inverse exists for a lossy attribution; exactness is "
                "not claimed anywhere it could be relied on"
            ),
        },
        lin_exempt=(
            "the scalar plane is advisory (observability), never an "
            "admission input; no grants to linearize"
        ),
        protocol_exempt=(
            "not a replicated lattice: scalar deficits ride inside v1 "
            "datagrams and are re-derived, not joined"
        ),
        bench_exempt=(
            "no standalone device leg: the scalar fold runs fused inside "
            "the merge paths the merge-join family benches"
        ),
        mutations_exempt=(
            "documented-lossy family with a single monotone law; the "
            "scalar_monotone model's internal self-test already flips it"
        ),
    ),
    KernelFamily(
        name="bucket",
        domain="token bucket: greedy admission against the summed PN "
        "view, refill arithmetic in nanotokens",
        prove_roots=(
            ProveRoot(
                "ops.take.take_batch", "patrol_tpu.ops.take", "take_batch",
                ("PTP001", "PTP004", "PTP005"), structural="callbacks",
                model="take_monotone", tracer=_trace_take_batch,
            ),
            ProveRoot(
                # The hot-key take-n serving kernel (one dispatch per
                # coalesced crowd): the full obligation set, with the
                # algebraic codes mapped onto the coalescing laws by
                # the ``take_n_laws`` model — PTP002: one row carrying
                # nreq=n commits and admits EXACTLY what n sequential
                # unit takes at the same timestamp do (the replay leg
                # runs the certified per-ticket kernel, so a defect
                # cannot vouch for itself); PTP003: a fully denied row
                # is a state fixpoint (deny storms never drift the
                # bucket); PTP004: monotone lanes + own-lane locality.
                "ops.take.take_n_batch", "patrol_tpu.ops.take",
                "take_n_batch", _ALL, structural="callbacks",
                model="take_n_laws", tracer=_trace_take_n_batch,
            ),
            ProveRoot(
                # The host-side grant split behind take-n coalescing:
                # pure-Python fan-out of one coalesced row's grant to
                # its FIFO ticket queue. Registered as its own root so
                # the split ORDER is a certified law, not a convention:
                # PTP002 pins first-k-of-m against the sequential
                # ledger (LIFO / round-robin splits are rejected),
                # PTP003 pins the deny-storm balance.
                "ops.take.split_grant", "patrol_tpu.ops.take",
                "split_grant", ("PTP002", "PTP003"),
                model="take_split_fifo",
            ),
            ProveRoot(
                "ops.rate", "patrol_tpu.ops.rate", "parse_rate",
                ("PTP003", "PTP004"), model="rate_algebra",
            ),
            ProveRoot(
                "ops.wire.codec", "patrol_tpu.ops.wire", "encode",
                ("PTP003",), model="wire_roundtrip",
            ),
        ),
        absent={
            "ops.take.take_batch:PTP002": (
                "admission is order-sensitive by design (greedy grants); "
                "the commutative core is the join it scatters through, "
                "certified in merge-join"
            ),
            "ops.take.take_batch:PTP003": (
                "grants are not invertible — the forfeit clamp "
                "deliberately discards over-capacity remainder"
            ),
            "ops.take.split_grant:PTP001": (
                "host-side python fan-out: no jaxpr, nothing to trace"
            ),
            "ops.take.split_grant:PTP004": (
                "the split moves no lattice state — it fans one already-"
                "committed row's grant out to tickets; monotonicity "
                "lives in the take-n kernel root it serves"
            ),
            "ops.take.split_grant:PTP005": (
                "host-side python fan-out: no jaxpr, nothing to trace"
            ),
            "ops.rate:PTP001": (
                "host-side python parser: no jaxpr, nothing to trace"
            ),
            "ops.rate:PTP002": (
                "rate parsing has no join; PTP003 canonical-form "
                "round-trip plus PTP004 ordering are the whole algebra"
            ),
            "ops.rate:PTP005": (
                "host-side python parser: no jaxpr, nothing to trace"
            ),
            **_codec_absent("ops.wire.codec"),
        },
        lin_specs=(
            LinSpecFamily(
                "ops.take.take_batch", "patrol_tpu.ops.take", "take_batch",
                wire="full",
                note="classic take: v1 full-state broadcast, admission "
                "from the full local view with the over-capacity forfeit "
                "clamp",
            ),
            LinSpecFamily(
                "ops.take.take_n_batch", "patrol_tpu.ops.take",
                "take_n_batch", wire="full",
                note="hot-key coalesced take-n: the SAME sequential "
                "bucket spec — one row carrying nreq=n must hand out "
                "exactly the outcomes of n serialized takes, so "
                "coalescing is invisible to linearizability",
            ),
        ),
        protocol="bucket-full",
        abi=(
            AbiObligation(
                "native.pt_rx_classify", "pt_rx_classify",
                ("PTA001", "PTA002", "PTA003"), "classify_conformance",
                twins=("ops.wire.codec",),
            ),
            AbiObligation(
                "native.hls_schedules", "pt_hls_take_probe", ("PTA004",),
                "hls_interleavings",
            ),
        ),
        wire_codec="ops.wire.codec",
        bench_fields=(
            "device_kernel_breakdown",
            "take_coalesce_ratio",
            "hotkey_takes_per_s",
        ),
        mutations=(
            CertMutation(
                "take-n-uncapped-crowd", "prove",
                "ops.take.take_n_batch", "PTP002",
                note="crowd-size clip dropped: one coalesced row "
                "admits past its waiting tickets, diverging from the "
                "sequential per-ticket replay",
                mutant=_mutant_take_n_uncapped,
            ),
            CertMutation(
                "take-split-lifo", "prove",
                "ops.take.split_grant", "PTP002",
                note="grant split admits the LAST k tickets: late "
                "arrivals jump the FIFO crowd",
                mutant=_mutant_split_grant_lifo,
            ),
            CertMutation(
                "take-split-deny-drift", "prove",
                "ops.take.split_grant", "PTP003",
                note="denied tickets charged as if committed: the "
                "reported balance drifts under a deny storm",
                mutant=_mutant_split_deny_charges,
            ),
            CertMutation(
                "take-ignores-remote-lanes", "protocol",
                "take-ignores-remote-lanes", "PTC003",
                note="own-lane-only admission view breaks the AP "
                "overspend bound",
            ),
            CertMutation(
                "incast-gate-bypass", "protocol", "incast-gate-bypass",
                "PTC003",
                note="the incast admission gate is part of the bucket's "
                "bound; bypassing it over-admits under fan-in",
            ),
            CertMutation(
                "take-ignores-visible-remote-spend", "lin",
                "take-ignores-visible-remote-spend", "PTN001",
                note="delivered remote lanes excluded from the admission "
                "view",
            ),
            CertMutation(
                "grant-exceeds-spec-on-sync-schedule", "lin",
                "grant-exceeds-spec-on-sync-schedule", "PTN003",
                note="over-grant on a fully synchronous schedule",
            ),
            CertMutation(
                "visibility-violating-linearization-accepted", "lin",
                "visibility-violating-linearization-accepted", "PTN002",
                note="checker soundness: an illegal witness order must "
                "not be accepted",
            ),
        ),
    ),
    KernelFamily(
        name="delta",
        domain="wire-v2 absolute own-lane intervals: delta-fold ingest, "
        "device-resident raw decode, watermark visibility",
        prove_roots=(
            ProveRoot(
                "ops.delta.delta_fold", "patrol_tpu.ops.delta",
                "delta_fold", _ALL, structural="join",
                model="join_batch:delta_fold", tracer=_trace_delta_fold,
            ),
            ProveRoot(
                # Device-resident ingest (r15): raw dv2 datagram byte
                # planes → framing walk + entry extraction + checksum/
                # validation verdicts + sentinel padding + scatter-max
                # fold, ONE dispatch. The ``raw_ingest`` model checks it
                # against the python wire decoder + reference join over
                # real datagram bytes: packet-order commutativity,
                # duplicated-plane idempotence, monotonicity, and strict
                # all-or-nothing corruption rejection (every truncation/
                # flip verdict must match decode_delta_packet, and
                # rejected planes must merge NOTHING). PTP001 runs the
                # join allowlist on the state planes — the decode
                # arithmetic touches only untainted plane bytes, so the
                # fold leg must stay pure scatter-max; PTP005 pins the
                # state dtypes/shapes.
                "ops.ingest.decode_fold_raw", "patrol_tpu.ops.ingest",
                "decode_fold_raw", _ALL, structural="join",
                model="raw_ingest", tracer=_trace_decode_fold_raw,
            ),
            ProveRoot(
                "ops.wire.delta_codec", "patrol_tpu.ops.wire",
                "encode_delta_packet", ("PTP003",), model="delta_roundtrip",
            ),
        ),
        absent=_codec_absent("ops.wire.delta_codec"),
        lin_specs=(
            LinSpecFamily(
                "ops.delta.delta_fold", "patrol_tpu.ops.delta",
                "delta_fold", wire="delta",
                note="delta-fold ingest: wire-v2 absolute own-lane "
                "intervals, visibility carried by the folded watermarks",
            ),
        ),
        protocol="bucket-delta",
        abi=(
            AbiObligation(
                # Zero-copy rx ring (device-resident ingest): every
                # interleaving of lease (rx thread) vs commit (engine
                # completer — "the pump" of the plane hand-off) against a
                # lowest-free-first model, plus the double-commit / stray-
                # index refusals that guard the use-after-recycle class.
                "native.rx_ring_schedules", "pt_rx_ring_lease", ("PTA004",),
                "rxring_interleavings",
            ),
        ),
        wire_codec="ops.wire.delta_codec",
        bench_fields=("ingest_raw_smoke_deltas",),
        mutations=(
            CertMutation(
                "delta-ships-increments-not-absolutes", "protocol",
                "delta-ships-increments-not-absolutes", "PTC001",
                note="increments on the wire double-apply under redelivery",
            ),
            CertMutation(
                "delta-gc-before-ack", "protocol", "delta-gc-before-ack",
                "PTC001",
                note="eager delta GC drops intervals a slow peer never saw",
            ),
        ),
    ),
    KernelFamily(
        name="lifecycle",
        domain="idle-bucket GC: the IsZero reclaim predicate and "
        "tombstoned own-lane re-creation",
        prove_roots=(
            ProveRoot(
                # The bucket-lifecycle IsZero predicate (idle-bucket GC,
                # ROADMAP item 4): full obligation set, with the algebraic
                # codes mapped onto the GC conservation laws by the
                # ``lifecycle_iszero`` model (analysis/prove.py) —
                # PTP002: a "full" verdict is *sound* (reclaim-then-
                # recreate is take-observation-equivalent to the original
                # row, bit-exact against the take kernel — the admitted-
                # token conservation law); PTP003: reclaim re-entry is
                # exact (zero lanes are the join's bottom, so
                # join(fresh, old) == old); PTP004: the verdict is
                # monotone in time (a missed sweep window can only delay
                # a reclaim, never invalidate it). PTP001/PTP005 run
                # structurally: no callbacks, and NO state outputs at all
                # — the predicate is a pure read.
                "ops.lifecycle.lifecycle_probe", "patrol_tpu.ops.lifecycle",
                "lifecycle_probe", _ALL, structural="callbacks",
                model="lifecycle_iszero", tracer=_trace_lifecycle_probe,
            ),
        ),
        lin_specs=(
            LinSpecFamily(
                "ops.lifecycle.lifecycle_probe", "patrol_tpu.ops.lifecycle",
                "lifecycle_probe", wire="full", lifecycle=True,
                note="lifecycle GC re-creation: IsZero reclaim with the "
                "tombstoned own lane, refills in the schedule alphabet",
            ),
        ),
        protocol="lifecycle-gc",
        bench_fields=("mesh_gc_reclaimed_probe",),
        mutations=(
            CertMutation(
                "gc-drops-admitted-tokens", "protocol",
                "gc-drops-admitted-tokens", "PTC006",
                note="reclaiming a non-zero row un-spends admitted tokens",
            ),
            CertMutation(
                "gc-treats-collected-as-unknown", "protocol",
                "gc-treats-collected-as-unknown", "PTC001",
                note="a tombstone read back as bottom resurrects "
                "collected spend",
            ),
            CertMutation(
                "gc-forgets-visible-admits", "lin",
                "gc-forgets-visible-admits", "PTN004",
                note="reclaim erases grants the visibility ledger still "
                "carries",
            ),
        ),
    ),
    KernelFamily(
        name="gcra",
        domain="GCRA / sliding window: the Theoretical Arrival Time as a "
        "per-lane max register, conformance iff TAT <= now + tol",
        prove_roots=(
            ProveRoot(
                "ops.gcra.gcra_take_batch", "patrol_tpu.ops.gcra",
                "gcra_take_batch", ("PTP001", "PTP002", "PTP004", "PTP005"),
                structural="callbacks", model="gcra_laws",
                tracer=_trace_gcra_take,
            ),
            ProveRoot(
                "ops.wire.gcra_trailer", "patrol_tpu.ops.wire",
                "encode_gcra_trailer", ("PTP003",),
                model="cert_trailer_roundtrip",
            ),
        ),
        absent={
            "ops.gcra.gcra_take_batch:PTP003": (
                "admission is not invertible (a conforming grant advances "
                "the TAT permanently); exactness lives in the trailer "
                "codec root's PTP003"
            ),
            **_codec_absent("ops.wire.gcra_trailer"),
        },
        lin_specs=(
            LinSpecFamily(
                "ops.gcra.gcra_take_batch", "patrol_tpu.ops.gcra",
                "gcra_take_batch", wire="delta", algebra="gcra",
                note="TAT max register: per-partition-side sequential "
                "GCRA replay (SequentialGcra) over the protocol-model "
                "cluster, shared injected clock in the alphabet",
            ),
        ),
        protocol="gcra",
        wire_codec="ops.wire.gcra_trailer",
        bench_fields=("cert_gcra_admitted",),
        mutations=(
            CertMutation(
                "gcra-window-off-by-one", "prove",
                "ops.gcra.gcra_take_batch", "PTP002",
                note="conformance window widened by one emission "
                "interval: burst+1 admitted",
                mutant=_mutant_gcra_window_off_by_one,
            ),
            CertMutation(
                "gcra-conformance-own-lane-only", "protocol", "gcra",
                "PTC006",
                note="judging conformance from the own TAT lane ignores "
                "merged remote watermarks: overspend past the AP bound",
                laws=GcraLaws(view="own"),
            ),
        ),
    ),
    KernelFamily(
        name="concurrency",
        domain="in-flight concurrency limit: paired PN lanes (TAKEN = "
        "acquires, ADDED = releases), inflight = sum difference",
        prove_roots=(
            ProveRoot(
                "ops.concurrency.conc_acquire_batch",
                "patrol_tpu.ops.concurrency", "conc_acquire_batch",
                ("PTP001", "PTP002", "PTP004", "PTP005"),
                structural="callbacks", model="conc_laws",
                tracer=_trace_conc_acquire,
            ),
            ProveRoot(
                "ops.wire.conc_trailer", "patrol_tpu.ops.wire",
                "encode_conc_trailer", ("PTP003",),
                model="cert_trailer_roundtrip",
            ),
        ),
        absent={
            "ops.concurrency.conc_acquire_batch:PTP003": (
                "acquire/release ticks are not invertible on monotone "
                "lanes (that is the point of the clamp); exactness lives "
                "in the trailer codec root's PTP003"
            ),
            **_codec_absent("ops.wire.conc_trailer"),
        },
        lin_specs=(
            LinSpecFamily(
                "ops.concurrency.conc_acquire_batch",
                "patrol_tpu.ops.concurrency", "conc_acquire_batch",
                wire="delta", algebra="conc",
                note="client-owned leases: per-side sequential replay "
                "(SequentialConc) — the own-lane release clamp IS lease "
                "ownership in the sequential limit",
            ),
        ),
        protocol="concurrency",
        wire_codec="ops.wire.conc_trailer",
        bench_fields=("cert_conc_admitted",),
        mutations=(
            CertMutation(
                "conc-release-unclamped", "prove",
                "ops.concurrency.conc_acquire_batch", "PTP002",
                note="phantom release: ADDED lane driven past TAKEN, "
                "capacity returned that was never held",
                mutant=_mutant_conc_release_unclamped,
            ),
            CertMutation(
                "conc-phantom-release-model", "protocol", "concurrency",
                "PTC006",
                note="the model twin of the clamp: uncapped releases "
                "break held <= limit x sides",
                laws=ConcLaws(release="uncapped"),
            ),
        ),
    ),
    KernelFamily(
        name="hierquota",
        domain="hierarchical quotas global→tenant→user: path-minimum "
        "admission, all-or-nothing three-level debit in one scatter",
        prove_roots=(
            ProveRoot(
                "ops.hierquota.quota_take_batch",
                "patrol_tpu.ops.hierquota", "quota_take_batch",
                ("PTP001", "PTP002", "PTP004", "PTP005"),
                structural="callbacks", model="quota_laws",
                tracer=_trace_quota_take,
            ),
            ProveRoot(
                "ops.wire.quota_trailer", "patrol_tpu.ops.wire",
                "encode_quota_trailer", ("PTP003",),
                model="cert_trailer_roundtrip",
            ),
        ),
        absent={
            "ops.hierquota.quota_take_batch:PTP003": (
                "debits are permanent on monotone G-counter lanes; "
                "exactness lives in the trailer codec root's PTP003"
            ),
            **_codec_absent("ops.wire.quota_trailer"),
        },
        lin_specs=(
            LinSpecFamily(
                "ops.hierquota.quota_take_batch",
                "patrol_tpu.ops.hierquota", "quota_take_batch",
                wire="delta", algebra="quota",
                note="path-minimum admission: per-side sequential replay "
                "(SequentialQuota) against the three-level model cluster",
            ),
        ),
        protocol="hierquota",
        wire_codec="ops.wire.quota_trailer",
        bench_fields=("cert_quota_admitted",),
        mutations=(
            CertMutation(
                "quota-admit-leaf-only", "prove",
                "ops.hierquota.quota_take_batch", "PTP002",
                note="leaf-only headroom: users collectively overrun the "
                "tenant/global pools",
                mutant=_mutant_quota_admit_leaf_only,
            ),
            CertMutation(
                "quota-debit-leaf-only", "protocol", "hierquota", "PTC006",
                note="the model twin: leaf-only debits break per-level "
                "conservation whenever an ancestor limit is tighter",
                laws=QuotaLaws(debit="leaf-only"),
            ),
        ),
    ),
)


# ---------------------------------------------------------------------------
# Toolchain-wide ABI obligations that belong to no single lattice family
# (the effects-table sweep covers every exported native symbol).
TOOLCHAIN_ABI: Tuple[AbiObligation, ...] = (
    AbiObligation(
        "native.effects_table", None, ("PTA005",), "effects_table",
    ),
)


# ---------------------------------------------------------------------------
# Derived flat registries — the historical exports; every stage driver
# and test keeps consuming these names unchanged. Order follows the
# family declaration order above.

PROVE_ROOTS: Tuple[ProveRoot, ...] = tuple(
    root for fam in KERNEL_FAMILIES for root in fam.prove_roots
)

LIN_SPECS: Tuple[LinSpecFamily, ...] = tuple(
    spec for fam in KERNEL_FAMILIES for spec in fam.lin_specs
)

ABI_OBLIGATIONS: Tuple[AbiObligation, ...] = (
    tuple(ob for fam in KERNEL_FAMILIES for ob in fam.abi) + TOOLCHAIN_ABI
)


# --- PTP006 (registration completeness): kernels the runtime engines
# dispatch through jit that are deliberately NOT in PROVE_ROOTS, each
# with the reason on record. analysis/prove.py sweeps the engine
# dispatch graph — and stage 9's PTK004 sweeps ops/ module-level
# ``*_jit`` bindings — and flags any jitted kernel found in neither
# registry: a new kernel can no longer land without declared
# obligations.
PROVE_EXEMPT: frozenset = frozenset(
    {
        # zero_rows writes constant zeros into selected rows — a pure
        # scatter of the lattice bottom with no algebra of its own. Its
        # lattice-facing laws are certified where they matter: the
        # lifecycle_iszero model proves reclaim-then-recreate (which IS
        # zero_rows + re-seed) take-observation-equivalent and join-
        # re-entry exact (PTP002/PTP003 on ops.lifecycle.lifecycle_probe).
        ("patrol_tpu.ops.merge", "zero_rows"),
    }
)


# ---------------------------------------------------------------------------
# Dispatch-discipline registry (check.sh stage 10, patrol-dispatch).
# Every kernel the runtime engines push through jax.jit declares HERE the
# dispatch contract stage 10 proves: which buffers are donated, which
# argnames are static, what shape-bucket law its call sites must pad to
# (StagingPool's power-of-two buckets, machine-readable at last), and
# which witness path re-drives it post-warmup under the compile counter
# and transfer guard (analysis/dispatch.py::WITNESS_PATHS). A kernel
# with no witness carries a written justification instead — PTD005
# rejects both a dispatched kernel missing from this registry and a
# registered kernel with neither witness nor justification.


@dataclasses.dataclass(frozen=True)
class DispatchSpec:
    """One jit-dispatched kernel's dispatch-discipline contract.

    ``buckets`` names the shape-bucket law of the kernel's call sites:

    * ``"pow2"`` — batches are padded through ``engine._pad_size`` with
      the declared ``(bucket_lo, bucket_hi)`` clamp; PTD001 requires a
      textually matching ``_pad_size`` site in the engine files (lo/hi
      compared by ``ast.unparse``, defaults ``"8"`` /
      ``"MAX_MERGE_ROWS"``), so silently dropping the padding — or
      drifting the clamp away from the declared ceiling — is a finding.
    * ``"fixed"`` — every dispatch ships one pinned shape
      (``bucket_hi`` names the constant: the commit ring's
      ``MAX_MERGE_ROWS`` block width, the rx ring's plane geometry).
    * ``"caller"`` — shapes are the caller's contract (the cert-kit
      microbatches: bench/tests drive fixed shapes); the witness still
      pins post-warmup stability for the shapes it drives.

    ``witness`` names the ``analysis/dispatch.py::WITNESS_PATHS`` entry
    that warms and re-drives this kernel (PTD004); ``witness_absent``
    is the REQUIRED justification when no witness path can reach it.
    """

    name: str
    module: str  # owning ops module, e.g. "patrol_tpu.ops.take"
    attr: str  # kernel function name in that module
    donate_argnums: Tuple[int, ...] = (0,)
    static_argnames: Tuple[str, ...] = ()
    buckets: str = "pow2"  # "pow2" | "fixed" | "caller"
    bucket_lo: str = "8"
    bucket_hi: str = "MAX_MERGE_ROWS"
    witness: str = ""
    witness_absent: str = ""
    note: str = ""


DISPATCH_SPECS: Tuple[DispatchSpec, ...] = (
    DispatchSpec(
        "take_batch", "patrol_tpu.ops.take", "take_batch",
        static_argnames=("node_slot",),
        bucket_hi="MAX_TAKE_ROWS", witness="take",
        note="packed [8,K] request / [7,K] result; feeder tick path",
    ),
    DispatchSpec(
        "take_n_batch", "patrol_tpu.ops.take", "take_n_batch",
        static_argnames=("node_slot",),
        bucket_hi="MAX_TAKE_ROWS", witness="take_n",
        note="the coalesced serving wrapper the feeder tick actually "
        "dispatches: packed [8,K] in / [7,K] out with hot-key crowds "
        "folded into the nreq row",
    ),
    DispatchSpec(
        "merge_batch", "patrol_tpu.ops.merge", "merge_batch",
        witness="merge_packed",
        note="packed [5,K] scatter-max join; promotion + CPU commit path",
    ),
    DispatchSpec(
        "merge_batch_folded", "patrol_tpu.ops.merge", "merge_batch_folded",
        witness="merge_folded",
        note="unique/sorted-asserted fold; accelerator tick commit",
    ),
    DispatchSpec(
        "commit_blocks", "patrol_tpu.ops.commit", "commit_blocks",
        buckets="fixed", witness="commit_blocks",
        note="[6,J,MAX_MERGE_ROWS] coalesced block ring, J a pow2 "
        "block count warmed per variant",
    ),
    DispatchSpec(
        "merge_rows_dense", "patrol_tpu.ops.merge", "merge_rows_dense",
        bucket_hi="MAX_ROW_DENSE", witness="merge_rows_dense",
        note="row-window dense half of the fold-to-dense hybrid",
    ),
    DispatchSpec(
        "merge_scalar_batch", "patrol_tpu.ops.merge", "merge_scalar_batch",
        witness="merge_scalar",
        note="deficit-attribution interop merge",
    ),
    DispatchSpec(
        "zero_rows", "patrol_tpu.ops.merge", "zero_rows",
        bucket_hi="1 << 20", witness="zero_rows",
        note="lifecycle reclaim / checkpoint-restore scatter of bottom",
    ),
    DispatchSpec(
        "lifecycle_probe", "patrol_tpu.ops.lifecycle", "lifecycle_probe",
        donate_argnums=(), static_argnames=("node_slot",),
        bucket_hi="1 << 20", witness="lifecycle_probe",
        note="pure read (no donation): GC sweep idle/full probe",
    ),
    DispatchSpec(
        "gcra_take_batch", "patrol_tpu.ops.gcra", "gcra_take_batch",
        static_argnames=("node_slot",), buckets="caller", witness="gcra",
    ),
    DispatchSpec(
        "conc_acquire_batch", "patrol_tpu.ops.concurrency",
        "conc_acquire_batch",
        static_argnames=("node_slot",), buckets="caller", witness="conc",
    ),
    DispatchSpec(
        "quota_take_batch", "patrol_tpu.ops.hierquota", "quota_take_batch",
        static_argnames=("node_slot",), buckets="caller", witness="quota",
    ),
    DispatchSpec(
        "delta_fold", "patrol_tpu.ops.delta", "delta_fold",
        witness="delta_fold",
        note="interval-encoded replication deltas, host decode fold",
    ),
    DispatchSpec(
        "decode_fold_raw", "patrol_tpu.ops.ingest", "decode_fold_raw",
        buckets="fixed", bucket_hi="rx-ring planes",
        witness="raw_ingest",
        note="whole rx ring ships as-is: [P,row_w] planes + [P]/[P,E] "
        "framing, geometry pinned by the ring allocation",
    ),
    DispatchSpec(
        "read_rows", "patrol_tpu.ops.merge", "read_rows",
        donate_argnums=(), bucket_lo="1", bucket_hi="1 << 20",
        witness="read_rows",
        note="eager (un-jitted) padded gather behind every "
        "snapshot/introspection read; donation-free by construction",
    ),
    DispatchSpec(
        "merge_batch_pallas", "patrol_tpu.ops.pallas_merge",
        "merge_batch_pallas",
        static_argnames=("interpret",), buckets="fixed",
        witness_absent="accelerator-only Pallas scatter-max, lazily "
        "imported behind PATROL_PALLAS and unreachable on the CPU "
        "witness host; interpret-mode tracing is minutes-class. Covered "
        "by tests/test_pallas_merge.py interpret-mode equivalence.",
    ),
)

DISPATCH_KERNELS: frozenset = frozenset(
    (s.module, s.attr) for s in DISPATCH_SPECS
)
