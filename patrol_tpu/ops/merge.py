"""CvRDT merge kernels — the reference's ``Bucket.Merge`` (bucket.go:240-263)
as batched scatter-max / elementwise-max over dense state.

Three shapes of merge, replacing the reference's one-packet-at-a-time
single-threaded receive loop (repo.go:54-92):

* :func:`merge_batch` — a microbatch of K replication deltas scatter-maxed
  into state. Duplicate (row, slot) pairs in one batch are fine: max is
  commutative/associative/idempotent, which is the whole point of the CRDT.
* :func:`merge_dense` — full-state join of two limiter states (elementwise
  max). This is the partition-heal / anti-entropy path (BASELINE.json
  config #5: millions of stale deltas replayed in one call) and the inner
  op of cross-replica convergence.
* :func:`read_rows` — gather of per-bucket state for incast replies
  (repo.go:86-90) and introspection.

All merges are elementwise int64 max: bit-deterministic, so every replica
converges to an identical state regardless of delivery order, duplication,
or loss — the property the reference proves empirically with its 10k-
permutation test (bucket_test.go:68-114) and these kernels re-prove over
batches.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from patrol_tpu.models.limiter import ADDED, TAKEN, LimiterState

# Sentinel row for fold/commit padding, shared by the engine's tick fold
# (FoldedMergeBatch / RowDenseBatch) and the coalesced commit ring
# (ops/commit.py): far above any bucket row (pools are ≤ ~2^24 rows) yet
# int32-safe after a +arange uniquifier; every scatter that sees it runs
# with ``mode="drop"``.
FOLD_PAD_ROW = 1 << 30


class MergeBatch(NamedTuple):
    """K replication deltas. Padding rows use (row=0, slot=0, zeros): state
    is non-negative, so a zero max is a no-op even on a live bucket.

    Invariant maintained at ingest: values are non-negative (negative wire
    values are clamped before reaching the device).
    """

    rows: jax.Array  # int32[K]
    slots: jax.Array  # int32[K] origin node lane
    added_nt: jax.Array  # int64[K]
    taken_nt: jax.Array  # int64[K]
    elapsed_ns: jax.Array  # int64[K]


def merge_batch(state: LimiterState, batch: MergeBatch) -> LimiterState:
    """Scatter-max K deltas into state (≙ bucket.go:240-263 per delta).

    The (added, taken) pair commits as ONE scatter of K two-element
    windows: XLA's TPU scatter serializes per *update*, not per element
    (~130-215 ns/update measured on v5e regardless of window size,
    scripts/probe_scatter.py), so pairing the planes halves the pn cost
    versus two element-granular scatters."""
    pair = jnp.stack([batch.added_nt, batch.taken_nt], axis=-1)
    pn = state.pn.at[batch.rows, batch.slots].max(pair)
    elapsed = state.elapsed.at[batch.rows].max(batch.elapsed_ns)
    return LimiterState(pn=pn, elapsed=elapsed)


merge_batch_jit = partial(jax.jit, donate_argnums=0)(merge_batch)


class FoldedMergeBatch(NamedTuple):
    """A tick-level folded merge batch (engine._fold_lane_merges): the
    (row, slot) pairs are lexicographically sorted and duplicate keys are
    pre-joined by elementwise max on the host, so the scatters may assert
    ``unique_indices`` + ``indices_are_sorted`` — measured +28% on v5e
    (scripts/probe_scatter.py), where the plain scatter serializes per
    update. ``erows``/``elapsed_nt`` are the per-ROW fold of the elapsed
    updates (a row appears once even when several lanes updated it).

    Padding entries carry genuinely-unique OUT-OF-BOUNDS keys (sentinel
    row above every live row, distinct slot per entry, appended after the
    live span so sortedness holds) which ``mode="drop"`` discards — the
    asserted flags are literally true for every index the kernel sees, so
    no behavior is borrowed from XLA's unspecified duplicate-index
    handling (see engine._fold_lane_merges)."""

    rows: jax.Array  # int32[K] sorted
    slots: jax.Array  # int32[K]
    added_nt: jax.Array  # int64[K]
    taken_nt: jax.Array  # int64[K]
    erows: jax.Array  # int32[K] sorted, unique-per-live-row
    elapsed_ns: jax.Array  # int64[K]


def merge_batch_folded(state: LimiterState, batch: FoldedMergeBatch) -> LimiterState:
    """Scatter-max of a host-folded batch with both scatter flags asserted
    (see :class:`FoldedMergeBatch` for why that is sound)."""
    pair = jnp.stack([batch.added_nt, batch.taken_nt], axis=-1)
    pn = state.pn.at[batch.rows, batch.slots].max(
        pair, unique_indices=True, indices_are_sorted=True, mode="drop"
    )
    elapsed = state.elapsed.at[batch.erows].max(
        batch.elapsed_ns, unique_indices=True, indices_are_sorted=True, mode="drop"
    )
    return LimiterState(pn=pn, elapsed=elapsed)


class RowDenseBatch(NamedTuple):
    """R bucket rows committing their FULL lane plane in one scatter
    update each — the dense half of the fold-to-dense hybrid (VERDICT r3
    item 3). TPU scatter cost is per *update* with the window size
    irrelevant (scripts/probe_scatter.py), so a row whose tick touches
    many lanes (hot-key storms, config #4; heal replays fanning a row
    across its peers' slots) commits N lanes for the price of one update
    instead of one per touched lane. Untouched lanes carry zeros — a
    zero max-join is a no-op on non-negative state. Rows are unique and
    sorted; padding uses out-of-bounds sentinel rows dropped by
    ``mode="drop"`` (same discipline as FoldedMergeBatch)."""

    rows: jax.Array  # int32[R] unique, sorted
    updates: jax.Array  # int64[R, N, 2] full lane windows (zeros = no-op)
    elapsed_ns: jax.Array  # int64[R]


def merge_rows_dense(state: LimiterState, batch: RowDenseBatch) -> LimiterState:
    """Scatter-max R full-row lane windows into state: R updates total."""
    pn = state.pn.at[batch.rows].max(
        batch.updates, unique_indices=True, indices_are_sorted=True,
        mode="drop",
    )
    elapsed = state.elapsed.at[batch.rows].max(
        batch.elapsed_ns, unique_indices=True, indices_are_sorted=True,
        mode="drop",
    )
    return LimiterState(pn=pn, elapsed=elapsed)


def merge_scalar_batch(state: LimiterState, batch: MergeBatch) -> LimiterState:
    """Deficit-attribution merge for deltas from *scalar-semantics* peers
    (reference nodes, bucket.go:240-263): interop's echo-cancellation kernel.

    A reference node's wire ``added``/``taken`` are scalar maxima over
    EVERYONE's state — including grants this cluster already holds in other
    PN lanes (our own broadcasts, max-merged into the reference node's
    scalars and echoed back). Ingesting the raw value into the sender's lane
    would double-count those echoes under the PN sum. Instead, attribute to
    the sender's lane only the part of its counter NOT explained by the
    other lanes:

        attributed = max(delta − Σ_{l ≠ slot} lane_l, 0)
        lane_slot  = max(lane_slot, attributed)

    ``batch.added_nt`` must arrive capacity-subtracted (the host ingest
    path subtracts the row's cap_base, since the reference folds its lazy
    capacity init into ``added``). Exact for one scalar peer; for multiple
    scalar peers it degrades toward the reference's own lossy-max behavior
    (over-attribution only when a reference node relays grants we have not
    yet heard first-hand — the same AP best-effort class as the reference).

    Duplicate rows in one batch all read the pre-batch state: scatter-max
    keeps the result order-free, at worst under-attributing until the next
    full-state rebroadcast (every take rebroadcasts, README.md:41-43)."""
    k = batch.rows.shape[0]
    pn_rows = state.pn[batch.rows]  # [K, N, 2] gather
    ar = jnp.arange(k, dtype=jnp.int32)
    lane_a = pn_rows[ar, batch.slots, ADDED]
    lane_t = pn_rows[ar, batch.slots, TAKEN]
    other_a = pn_rows[:, :, ADDED].sum(axis=-1) - lane_a
    other_t = pn_rows[:, :, TAKEN].sum(axis=-1) - lane_t
    zero = jnp.int64(0)
    attr_a = jnp.maximum(batch.added_nt - other_a, zero)
    attr_t = jnp.maximum(batch.taken_nt - other_t, zero)
    pair = jnp.stack([attr_a, attr_t], axis=-1)
    pn = state.pn.at[batch.rows, batch.slots].max(pair)
    elapsed = state.elapsed.at[batch.rows].max(batch.elapsed_ns)
    return LimiterState(pn=pn, elapsed=elapsed)


merge_scalar_batch_jit = partial(jax.jit, donate_argnums=0)(merge_scalar_batch)


def merge_dense(state: LimiterState, other: LimiterState) -> LimiterState:
    """Full-state join: elementwise max of both CRDT planes.

    The HBM-bandwidth-bound fast path: XLA fuses this into a single
    streaming pass, merging every bucket per sweep.

    The max runs on the planes BITCAST TO uint64 (r5): every CRDT plane
    is non-negative by construction (lanes are monotone grow-only
    counters; every wire ingress sanitizes to ≥0, ops/wire.py), and for
    non-negative int64 the bit patterns order identically under unsigned
    compare — so u64 max ≡ s64 max on the domain. v5e has no native
    64-bit vector compare either way; XLA's u32-pair emulation of the
    UNSIGNED max is materially cheaper than the signed one (probe
    matrix, scripts/probe_dense_u32.py on-chip: 8.76 vs 11.94 ms per
    500k×256×2 sweep — 701 vs 514 GB/s implied; benchmarks/PROBES.md).
    A negative value (impossible absent a corruption bug upstream) would
    win every unsigned max; the property/differential suites pin the
    equivalence on the real domain."""
    pn = lax.bitcast_convert_type(
        jnp.maximum(
            lax.bitcast_convert_type(state.pn, jnp.uint64),
            lax.bitcast_convert_type(other.pn, jnp.uint64),
        ),
        jnp.int64,
    )
    elapsed = lax.bitcast_convert_type(
        jnp.maximum(
            lax.bitcast_convert_type(state.elapsed, jnp.uint64),
            lax.bitcast_convert_type(other.elapsed, jnp.uint64),
        ),
        jnp.int64,
    )
    return LimiterState(pn=pn, elapsed=elapsed)


merge_dense_jit = partial(jax.jit, donate_argnums=0)(merge_dense)
# Benchmarking note (r4): timing merge_dense inside a fori carry loop
# UNDERSTATES it by ~15% (20.7 vs 17.9 ms per 1M×256×2 sweep) unless each
# iteration is made value-distinct with the induction var — the idempotent
# max chain reaches its fixpoint after one step and the plain-carry loop
# compiles/executes pessimally. A loop-invariant zero bias is NOT a guard
# (LICM hoists it). Bit-reinterpreting the s64 stream to u32 pairs with a
# lexicographic compare is 4-5× WORSE (stride-2 lane access defeats
# vectorization); bitcast-to-u64 max (adopted above, r5) is the one
# reformulation that wins. Measured via the forced-completion
# differential harness; scripts/probe_dense_u32.py is the repro.


def zero_rows(state: LimiterState, rows: jax.Array) -> LimiterState:
    """Clear bucket rows (slot recycling / eviction). Semantically this is
    a node restart for those buckets: state is soft and re-hydrates from
    peers via incast (repo.go:96-106). Duplicate indices are fine."""
    n = state.pn.shape[1]
    pn = state.pn.at[rows].set(jnp.zeros((rows.shape[0], n, 2), state.pn.dtype))
    elapsed = state.elapsed.at[rows].set(0)
    return LimiterState(pn=pn, elapsed=elapsed)


zero_rows_jit = partial(jax.jit, donate_argnums=0)(zero_rows)


class RowState(NamedTuple):
    pn: jax.Array  # int64[K, N, 2]
    elapsed: jax.Array  # int64[K]


@jax.jit
def read_rows(state: LimiterState, rows: jax.Array) -> RowState:
    """Gather full per-bucket state for the given rows (incast replies,
    repo.go:86-90, and debugging)."""
    return RowState(pn=state.pn[rows], elapsed=state.elapsed[rows])
