"""CvRDT merge kernels — the reference's ``Bucket.Merge`` (bucket.go:240-263)
as batched scatter-max / elementwise-max over dense state.

Three shapes of merge, replacing the reference's one-packet-at-a-time
single-threaded receive loop (repo.go:54-92):

* :func:`merge_batch` — a microbatch of K replication deltas scatter-maxed
  into state. Duplicate (row, slot) pairs in one batch are fine: max is
  commutative/associative/idempotent, which is the whole point of the CRDT.
* :func:`merge_dense` — full-state join of two limiter states (elementwise
  max). This is the partition-heal / anti-entropy path (BASELINE.json
  config #5: millions of stale deltas replayed in one call) and the inner
  op of cross-replica convergence.
* :func:`read_rows` — gather of per-bucket state for incast replies
  (repo.go:86-90) and introspection.

All merges are elementwise int64 max: bit-deterministic, so every replica
converges to an identical state regardless of delivery order, duplication,
or loss — the property the reference proves empirically with its 10k-
permutation test (bucket_test.go:68-114) and these kernels re-prove over
batches.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from patrol_tpu.models.limiter import ADDED, TAKEN, LimiterState


class MergeBatch(NamedTuple):
    """K replication deltas. Padding rows use (row=0, slot=0, zeros): state
    is non-negative, so a zero max is a no-op even on a live bucket.

    Invariant maintained at ingest: values are non-negative (negative wire
    values are clamped before reaching the device).
    """

    rows: jax.Array  # int32[K]
    slots: jax.Array  # int32[K] origin node lane
    added_nt: jax.Array  # int64[K]
    taken_nt: jax.Array  # int64[K]
    elapsed_ns: jax.Array  # int64[K]


def merge_batch(state: LimiterState, batch: MergeBatch) -> LimiterState:
    """Scatter-max K deltas into state (≙ bucket.go:240-263 per delta)."""
    pn = state.pn.at[batch.rows, batch.slots, ADDED].max(batch.added_nt)
    pn = pn.at[batch.rows, batch.slots, TAKEN].max(batch.taken_nt)
    elapsed = state.elapsed.at[batch.rows].max(batch.elapsed_ns)
    return LimiterState(pn=pn, elapsed=elapsed)


merge_batch_jit = partial(jax.jit, donate_argnums=0)(merge_batch)


def merge_dense(state: LimiterState, other: LimiterState) -> LimiterState:
    """Full-state join: elementwise max of both CRDT planes.

    The HBM-bandwidth-bound fast path: XLA fuses this into a single
    streaming pass, merging every bucket per sweep."""
    return LimiterState(
        pn=jnp.maximum(state.pn, other.pn),
        elapsed=jnp.maximum(state.elapsed, other.elapsed),
    )


merge_dense_jit = partial(jax.jit, donate_argnums=0)(merge_dense)


def zero_rows(state: LimiterState, rows: jax.Array) -> LimiterState:
    """Clear bucket rows (slot recycling / eviction). Semantically this is
    a node restart for those buckets: state is soft and re-hydrates from
    peers via incast (repo.go:96-106). Duplicate indices are fine."""
    n = state.pn.shape[1]
    pn = state.pn.at[rows].set(jnp.zeros((rows.shape[0], n, 2), state.pn.dtype))
    elapsed = state.elapsed.at[rows].set(0)
    return LimiterState(pn=pn, elapsed=elapsed)


zero_rows_jit = partial(jax.jit, donate_argnums=0)(zero_rows)


class RowState(NamedTuple):
    pn: jax.Array  # int64[K, N, 2]
    elapsed: jax.Array  # int64[K]


@jax.jit
def read_rows(state: LimiterState, rows: jax.Array) -> RowState:
    """Gather full per-bucket state for the given rows (incast replies,
    repo.go:86-90, and debugging)."""
    return RowState(pn=state.pn[rows], elapsed=state.elapsed[rows])
