"""Delta-interval fold kernel — the device half of the wire-v2 data plane.

One decoded delta datagram (ops/wire.py ``DeltaPacket``) carries hundreds
of bucket join-decompositions: absolute PN-lane values, monotone by
construction. :func:`delta_fold` joins a whole interval into state in ONE
scatter-max dispatch — the rx path the device-commit pipeline wants:
wire bytes become a single batched plane commit instead of hundreds of
queued per-delta objects (engine.ingest_interval).

Algebra: identical lattice join as ops/merge.py (elementwise int64 max),
so every PTP obligation holds bit-exactly; registered in
``ops/obligations.py::PROVE_ROOTS`` with the full PTP001-005 set. The only
structural difference from ``merge_batch`` is ``mode="drop"`` with the
shared ``FOLD_PAD_ROW`` sentinel: intervals arrive in arbitrary sizes, and
padding to the power-of-two shape class with out-of-bounds sentinel rows
(dropped by XLA, never merged) bounds the compiled-variant count without a
host-side compaction pass.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from patrol_tpu.models.limiter import LimiterState
from patrol_tpu.ops.merge import FOLD_PAD_ROW  # noqa: F401  (re-export: the
# sentinel contract is shared with the tick fold and the commit ring)


class DeltaBatch(NamedTuple):
    """K decoded delta-interval entries. Padding entries carry
    ``FOLD_PAD_ROW`` (out of bounds ⇒ dropped by ``mode="drop"``); live
    entries are non-negative absolute lane values (the decode guard
    rejects bit-63 wire values, ingest clamps the rest)."""

    rows: jax.Array  # int32[K]; FOLD_PAD_ROW marks padding
    slots: jax.Array  # int32[K] origin node lane
    added_nt: jax.Array  # int64[K] absolute own-lane PN values
    taken_nt: jax.Array  # int64[K]
    elapsed_ns: jax.Array  # int64[K]


def delta_fold(state: LimiterState, batch: DeltaBatch) -> LimiterState:
    """Join one delta interval into state: scatter-max of K (row, slot)
    lane pairs plus the per-row elapsed max. Duplicate keys in one
    interval are fine (max is commutative/associative/idempotent — the
    same argument as ``merge_batch``); sentinel rows are dropped."""
    pair = jnp.stack([batch.added_nt, batch.taken_nt], axis=-1)
    pn = state.pn.at[batch.rows, batch.slots].max(pair, mode="drop")
    elapsed = state.elapsed.at[batch.rows].max(batch.elapsed_ns, mode="drop")
    return LimiterState(pn=pn, elapsed=elapsed)


delta_fold_jit = partial(jax.jit, donate_argnums=0)(delta_fold)
