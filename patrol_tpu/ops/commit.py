"""Coalesced device-commit kernel — the device half of the commit
pipeline between the engine's drain threads and device state.

The r05 bench showed the host fold pipeline sustaining 6.6M deltas/s
while end-to-end ingest collapsed to 375k/s: the CRDT join was never the
wall, the host→device commit path was — one blocking transfer plus one
dispatch per drained block (~5 MB/s effective on a remote-execute
transport). Delta-state CRDTs exist precisely so joins can be batched
and shipped lazily (Almeida et al., arXiv:1410.2803); this module is the
kernel that cashes that in: K pending delta blocks fold into ONE
donated, fixed-shape dispatch instead of K, exploiting the join
commutativity/idempotence patrol-prove certifies (PTP002/PTP003 on
``ops.commit.commit_blocks`` in ``ops/obligations.py::PROVE_ROOTS``).

Shape discipline: a commit is an int64[6, J, K] **block ring** — J
blocks of K = ``MAX_MERGE_ROWS`` folded pairs each, the flattened view
lexicographically sorted and unique with out-of-bounds sentinel padding
(the exact :class:`patrol_tpu.ops.merge.FoldedMergeBatch` contract,
extended across blocks). J is padded to a power of two so the jit
variant count stays logarithmic, and the host side packs into reusable
staging buffers (engine.StagingPool) shipped with ``jax.device_put``
*before* the state lock, so transfer overlaps the previous tick's
compute instead of serializing inside the jit call.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from patrol_tpu.models.limiter import LimiterState
from patrol_tpu.ops.merge import FOLD_PAD_ROW


class CommitBlocks(NamedTuple):
    """J fixed-shape blocks of host-folded merge pairs, committed in one
    dispatch. Invariants maintained by :func:`pack_commit_blocks`:

    * the FLATTENED (row, slot) keys are lexicographically sorted and
      strictly unique (live pairs are one cross-block fold's output;
      padding keys are out-of-bounds sentinels appended after the live
      span), so the scatter asserts ``unique_indices`` +
      ``indices_are_sorted`` truthfully — same contract as
      :class:`patrol_tpu.ops.merge.FoldedMergeBatch`, per block ring;
    * ``erows``/``elapsed_ns`` carry the per-unique-row elapsed fold
      under the same discipline;
    * padding rows are ≥ ``FOLD_PAD_ROW`` and dropped by ``mode="drop"``.
    """

    rows: jax.Array  # int32[J, K] flattened-sorted
    slots: jax.Array  # int32[J, K]
    added_nt: jax.Array  # int64[J, K]
    taken_nt: jax.Array  # int64[J, K]
    erows: jax.Array  # int32[J, K] flattened-sorted, unique-per-live-row
    elapsed_ns: jax.Array  # int64[J, K]


def commit_blocks(state: LimiterState, blocks: CommitBlocks) -> LimiterState:
    """Fold a whole block ring into state as ONE pair of flagged
    scatter-max updates — the padded-superbatch form of K sequential
    ``merge_batch`` dispatches, exact because the join is commutative
    and idempotent (delivery order across blocks cannot matter)."""
    rows = blocks.rows.reshape(-1)
    slots = blocks.slots.reshape(-1)
    pair = jnp.stack(
        [blocks.added_nt.reshape(-1), blocks.taken_nt.reshape(-1)], axis=-1
    )
    pn = state.pn.at[rows, slots].max(
        pair, unique_indices=True, indices_are_sorted=True, mode="drop"
    )
    elapsed = state.elapsed.at[blocks.erows.reshape(-1)].max(
        blocks.elapsed_ns.reshape(-1),
        unique_indices=True,
        indices_are_sorted=True,
        mode="drop",
    )
    return LimiterState(pn=pn, elapsed=elapsed)


commit_blocks_jit = partial(jax.jit, donate_argnums=0)(commit_blocks)


def commit_shape(n_pairs: int, block_rows: int) -> Tuple[int, int, int]:
    """The staging-buffer shape for a fold of ``n_pairs`` pairs: (6, J,
    block_rows) with J the smallest power of two whose ring holds the
    fold — the shape key the engine's StagingPool recycles on."""
    j = 1
    while j * block_rows < n_pairs:
        j <<= 1
    return (6, j, block_rows)


def pack_commit_blocks(
    ur: np.ndarray,
    us: np.ndarray,
    ua: np.ndarray,
    ut: np.ndarray,
    er: np.ndarray,
    e: np.ndarray,
    block_rows: int,
    out: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Pack one cross-block fold (sorted unique pairs + per-row elapsed,
    engine._fold_core's output) into the int64[6, J, K] commit matrix.
    ``out``, when given, is a staging buffer of exactly
    :func:`commit_shape`'s shape (leased from the engine pool and
    refilled in place). Sentinel tail mirrors engine._pack_folded: rows
    above every live row keep the flattened keys sorted, distinct
    slots/rows keep them unique, ``mode="drop"`` discards them."""
    n, ne = len(ur), len(er)
    if out is None:
        out = np.empty(commit_shape(n, block_rows), dtype=np.int64)
    elif out.shape[0] != 6 or out.shape[1] * out.shape[2] < n:
        raise ValueError(
            f"staging buffer shape {tuple(out.shape)} cannot hold {n} pairs"
        )
    k = out.shape[1] * out.shape[2]
    flat = out.reshape(6, k)
    flat[0, :n] = ur
    flat[1, :n] = us
    flat[2, :n] = ua
    flat[3, :n] = ut
    flat[0, n:] = FOLD_PAD_ROW
    flat[1, n:] = np.arange(k - n)
    flat[2, n:] = 0
    flat[3, n:] = 0
    flat[4, :ne] = er
    flat[5, :ne] = e
    flat[4, ne:] = FOLD_PAD_ROW + np.arange(k - ne)
    flat[5, ne:] = 0
    return out
