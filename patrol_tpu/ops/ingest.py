"""Device-resident ingest: decode + fold raw wire-v2 delta datagrams on
device (ROADMAP item 1's "make the device the bulk plane" lever).

The r05 wall in one sentence: the host pipeline folds 6.6M deltas/s in
isolation but end-to-end ingest lands at 375k/s, because the wire→state
path ships *folded matrices*, not bytes — every dv2 datagram pays a
Python ``decode_delta_packet`` (per-entry object churn), a host fold,
and a staging copy before the device sees work. This module inverts
that: the rx path ships the **raw datagram byte planes** (uint8[P, 8192]
rows straight out of the recvmmsg ring) and ONE dispatch performs the
framing walk, entry extraction, checksum/validation verdicts,
sentinel-padding of invalid packets, and the scatter-max fold into
state (:func:`decode_fold_raw`).

Division of labor with the host (the part a device kernel cannot do):

* **row resolution** — bucket names live in the host directory's hash
  table, so the host runs a *vectorized structure walk*
  (:func:`host_walk`, numpy: one python-level iteration per entry
  ordinal, vectorized across all packets) that extracts per-entry name
  offsets/hashes and the header/ack fields, resolves rows through the
  existing directory pass, and hands the kernel a ``rows[P, E]`` plan
  (``FOLD_PAD_ROW`` marks entries the fold must skip: directory-miss
  drops, control-channel names, out-of-range slots);
* **host-lane split** — rows currently host-resident are flagged in the
  ``hosted[P, E]`` input; the kernel masks them OUT of the fold and
  returns a ``hosted_mask`` output (valid ∩ hosted) plus the decoded
  entry values, which the engine absorbs through the existing
  host-lane join (engine.ingest_raw_planes).

Validation is **bit-identical to ops/wire.py::decode_delta_packet** —
all-or-nothing per packet: envelope (24 zero bytes, reserved name),
checksum, version, ack-vector bounds, per-entry framing bounds, bit-63
value guards, exact end-of-payload. The differential sweep in
tests/test_ingest.py pins verdicts AND folded state against the Python
decoder over the hostile corpus (truncations, flips, trailing garbage,
mixed valid/invalid planes), for the XLA path and the Pallas twin.

Kernel forms, same pattern as ops/pallas_merge.py:

* :func:`decode_fold_raw` — the XLA implementation (gathers + one
  ``lax.scan`` over entry ordinals + one scatter-max). The production
  path on every backend today.
* :func:`decode_fold_raw_pallas` — the Pallas twin sharing the same
  decode core inside a ``pallas_call`` (interpret-capable on CPU; a
  compile probe gates the native path, which current Mosaic rejects —
  byte-granular gathers and scalar VMEM stores are not lowerable, the
  same verdict BENCH_r02 pinned for the scatter-merge kernel).

Algebra: the fold leg is the identical lattice join as
``ops/delta.delta_fold`` (elementwise int64 max, ``mode="drop"``
sentinels), so the full PTP001-005 obligation set holds; registered in
``ops/obligations.py::PROVE_ROOTS`` under the ``raw_ingest`` model
(analysis/prove.py): packet-order commutativity, duplicated-plane
idempotence, join monotonicity, and strict corruption rejection are
machine-checked through the REAL kernel, and the seeded
accept-bad-checksum / add-instead-of-max mutations are demonstrably
rejected (tests/test_prove.py).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from patrol_tpu.models.limiter import LimiterState
from patrol_tpu.ops import wire
from patrol_tpu.ops.merge import FOLD_PAD_ROW

# Framing constants, mirrored from ops/wire.py (the codec is the spec;
# these are the offsets its struct layout implies).
RAW_PLANE_BYTES = wire.DELTA_PACKET_SIZE  # 8192: the rx ring row width
_BASE = 32  # envelope: 25-byte v1 header + 7-byte reserved name
_HEAD = 8  # version u8 | sender_slot u16 | seq u32 | n_acks u8
_ACK = 4
_COUNT = 2
_ENTRY_TAIL = 34  # slot u16 | cap u64 | added u64 | taken u64 | elapsed u64
_MIN_LEN = _BASE + _HEAD + _COUNT + 1  # 43: header + count + checksum
_NAME = np.frombuffer(wire.DELTA_CHANNEL_NAME.encode(), np.uint8)
_FNV_OFFSET = np.uint64(0xCBF29CE484222325)
_FNV_PRIME = np.uint64(0x100000001B3)


def max_entries(row_bytes: int) -> int:
    """Entry-ordinal bound for one plane row: the most entries a legal
    packet of ``row_bytes`` can carry (minimum entry = empty name)."""
    return max(1, (row_bytes - _MIN_LEN) // (1 + _ENTRY_TAIL))


MAX_RAW_ENTRIES = max_entries(RAW_PLANE_BYTES)  # 232 at the 8-KiB row


def dv2_mask(planes: np.ndarray, sizes: np.ndarray) -> np.ndarray:
    """Vectorized envelope test over a recv batch: which rows are dv2
    delta datagrams (the numpy twin of wire.is_delta_packet) — routes
    the raw batch path before the generic per-packet dispatch."""
    n = len(sizes)
    if n == 0:
        return np.zeros(0, dtype=bool)
    head = planes[:n, :_BASE]
    return (
        (np.asarray(sizes[:n]) > _BASE)
        & (head[:, :24] == 0).all(axis=1)
        & (head[:, 24] == len(_NAME))
        & (head[:, 25:_BASE] == _NAME).all(axis=1)
    )


class RawWalk(NamedTuple):
    """The host structure walk's view of one plane batch: packet
    verdicts + header/ack fields (the delta plane's bookkeeping) and the
    per-entry name structure the directory pass consumes. Shapes:
    scalars ``[P]``, entry fields ``[P, E]``; entries past a packet's
    count (or of an invalid packet) are zero-filled."""

    ok: np.ndarray  # bool[P] — the all-or-nothing packet verdict
    sender_slot: np.ndarray  # int32[P]
    seq: np.ndarray  # int64[P] (u32 on the wire)
    n_acks: np.ndarray  # int32[P]
    acks: np.ndarray  # int64[P, 32]
    count: np.ndarray  # int32[P] live entries (0 when not ok)
    name_off: np.ndarray  # int32[P, E] offset of the name bytes
    name_len: np.ndarray  # int32[P, E]
    name_hash: np.ndarray  # uint64[P, E] FNV-1a (directory routing)
    slot: np.ndarray  # int64[P, E]
    cap: np.ndarray  # int64[P, E]
    added: np.ndarray  # int64[P, E]
    taken: np.ndarray  # int64[P, E]
    elapsed: np.ndarray  # int64[P, E]


def _np_be(planes: np.ndarray, pi: np.ndarray, off: np.ndarray, nbytes: int):
    """Big-endian uint read at per-row offsets → uint64[P] (vectorized
    gather; callers guarantee off+nbytes stays inside the plane row)."""
    acc = np.zeros(len(pi), np.uint64)
    for k in range(nbytes):
        acc = (acc << np.uint64(8)) | planes[pi, off + k].astype(np.uint64)
    return acc


def host_walk(planes: np.ndarray, lengths: np.ndarray) -> "RawWalk":
    """The vectorized host structure walk: verdicts bit-identical to
    ``wire.decode_delta_packet`` plus the name structure (offset, length,
    FNV hash) the directory pass needs and the numeric fields the
    host-lane absorb and cap-adoption tails use. One python-level loop
    iteration per entry ORDINAL (≤ :data:`MAX_RAW_ENTRIES`), each
    vectorized across every packet still walking — not per entry."""
    planes = np.asarray(planes)
    P, row = planes.shape
    E = max_entries(row)
    lengths = np.asarray(lengths, np.int64)
    pidx = np.arange(P)
    end = lengths - 1  # checksum byte offset
    safe_end = np.clip(end, 0, row - 1)

    ok = (lengths >= _MIN_LEN) & (lengths <= row)
    ok &= (planes[:, :24] == 0).all(axis=1)
    ok &= planes[:, 24] == len(_NAME)
    ok &= (planes[:, 25:_BASE] == _NAME).all(axis=1)
    # Checksum: sum(data[32:end]) & 0xFF == data[end]. Bytes past the
    # datagram length are stale ring contents and MUST NOT contribute.
    col = np.arange(row)
    body = np.where(
        (col[None, :] >= _BASE) & (col[None, :] < end[:, None]), planes, 0
    )
    ok &= (body.sum(axis=1) & 0xFF) == planes[pidx, safe_end]
    ok &= planes[:, _BASE] == wire.DELTA_VERSION
    sender_slot = (
        planes[:, _BASE + 1].astype(np.int32) << 8
    ) | planes[:, _BASE + 2]
    seq = _np_be(planes, pidx, np.full(P, _BASE + 3), 4).astype(np.int64)
    n_acks = planes[:, _BASE + 7].astype(np.int32)
    ok &= n_acks <= wire.DELTA_MAX_ACKS
    off0 = _BASE + _HEAD + _ACK * n_acks.astype(np.int64)
    ok &= off0 + _COUNT <= end
    # The STRUCTURE walk below is gated only on walkability (safe cursor
    # bounds), NOT on the envelope/checksum/version verdicts: the offsets
    # are a framing PROPOSAL for the device kernel, which re-validates
    # everything itself and must stay the verdict authority — a host
    # walk that withheld offsets from checksum-failed packets would mask
    # an in-kernel validation bug from the prover's mutation sweep.
    walkable = (
        (lengths >= _MIN_LEN)
        & (lengths <= row)
        & (n_acks <= wire.DELTA_MAX_ACKS)
        & (off0 + _COUNT <= end)
    )
    acks = np.zeros((P, wire.DELTA_MAX_ACKS), np.int64)
    for k in range(wire.DELTA_MAX_ACKS):
        sel = ok & (n_acks > k)
        if sel.any():
            si = np.flatnonzero(sel)
            acks[si, k] = _np_be(
                planes, si, (_BASE + _HEAD + _ACK * k) * np.ones(len(si), np.int64), 4
            ).astype(np.int64)
    count_off = np.clip(off0, 0, row - 2)
    count = (
        (planes[pidx, count_off].astype(np.int64) << 8)
        | planes[pidx, count_off + 1]
    ).astype(np.int64)
    count = np.where(walkable, count, 0)

    name_off = np.zeros((P, E), np.int32)
    name_len = np.zeros((P, E), np.int32)
    entry_seen = np.zeros((P, E), bool)

    # Structure walk: ONLY the cursor advance and framing bounds run
    # per-ordinal; field extraction happens once, flat, below (34 gathers
    # total instead of 34 per ordinal — the walk is the host hot path).
    off = np.where(walkable, off0 + _COUNT, 0).astype(np.int64)
    walking = walkable.copy()
    for i in range(E):
        active = walking & (count > i)
        if not active.any():
            break
        if active.all():
            # Flood fast path (every packet still walking — the common
            # recvmmsg-sweep shape): full-array ops, no index sets.
            in_bounds = off < end
            nl = planes[pidx, np.minimum(off, row - 1)].astype(np.int64)
            fits = in_bounds & (off + 1 + nl + _ENTRY_TAIL <= end)
            if fits.all():
                name_off[:, i] = off + 1
                name_len[:, i] = nl
                entry_seen[:, i] = True
                off = off + 1 + nl + _ENTRY_TAIL
                continue
        ai = np.flatnonzero(active)
        o = off[ai]
        # Python: ``if off >= end: return None`` then name_len = data[off];
        # off += 1; ``if off + nl + 34 > end: return None``.
        in_bounds = o < end[ai]
        nl = planes[ai, np.clip(o, 0, row - 1)].astype(np.int64)
        fits = in_bounds & (o + 1 + nl + _ENTRY_TAIL <= end[ai])
        bad = ai[~fits]
        walking[bad] = False
        ok[bad] = False
        gi = ai[fits]
        if gi.size:
            nlg = nl[fits]
            name_off[gi, i] = off[gi] + 1
            name_len[gi, i] = nlg
            entry_seen[gi, i] = True
            off[gi] = off[gi] + 1 + nlg + _ENTRY_TAIL
    # A count the walk could not finish (count > E physically cannot fit)
    # and a payload that does not end exactly at the checksum both reject.
    ok &= count <= E
    ok &= off == end

    # Flat field extraction over every structurally-walked entry. The
    # bit-63 guard applies here: any value ≥ 2^63 rejects the WHOLE
    # packet (decode_delta_packet's max(...) > _INT64_MAX check) — field
    # values never change the cursor walk, so deferring the check out of
    # the loop is exact.
    slot = np.zeros((P, E), np.int64)
    cap = np.zeros((P, E), np.int64)
    added = np.zeros((P, E), np.int64)
    taken = np.zeros((P, E), np.int64)
    elapsed = np.zeros((P, E), np.int64)
    spi, sei = np.nonzero(entry_seen)
    if spi.size:
        # One [n, 34] tail gather instead of 34 per-byte gathers (the
        # walked entries guarantee tail+34 ≤ end, so no clipping).
        tails = (name_off[spi, sei] + name_len[spi, sei]).astype(np.int64)
        b34 = planes[spi[:, None], tails[:, None] + np.arange(_ENTRY_TAIL)]
        b34 = b34.astype(np.uint64)

        def _be64(o: int) -> np.ndarray:
            acc = b34[:, o]
            for k in range(1, 8):
                acc = (acc << np.uint64(8)) | b34[:, o + k]
            return acc

        slot[spi, sei] = ((b34[:, 0] << np.uint64(8)) | b34[:, 1]).astype(
            np.int64
        )
        c = _be64(2)
        a = _be64(10)
        t = _be64(18)
        e = _be64(26)
        hi = np.uint64(1) << np.uint64(63)
        bit63 = ((c | a | t | e) & hi) != 0
        if bit63.any():
            ok[spi[bit63]] = False
        cap[spi, sei] = c.astype(np.int64)
        added[spi, sei] = a.astype(np.int64)
        taken[spi, sei] = t.astype(np.int64)
        elapsed[spi, sei] = e.astype(np.int64)
    count = np.where(ok, count, 0).astype(np.int32)

    # Zero the VALUE fields of rejected packets: a RawWalk never leaks
    # values from a packet its verdict refused (the engine masks on ok
    # anyway). The STRUCTURE fields (name_off/name_len) stay — they are
    # the kernel's framing proposal, and the kernel must judge even
    # packets the host verdict refused (see the walkable note above).
    dead = ~ok
    if dead.any():
        for arr in (slot, cap, added, taken, elapsed):
            arr[dead] = 0

    # FNV-1a over the live entry names, flattened: one vectorized loop
    # over byte POSITIONS (bounded by the longest live name, ≤255).
    name_hash = np.zeros((P, E), np.uint64)
    live = ok[:, None] & (np.arange(E)[None, :] < count[:, None])
    pi, ei = np.nonzero(live)
    if pi.size:
        offs = name_off[pi, ei].astype(np.int64)
        lens = name_len[pi, ei].astype(np.int64)
        h = np.full(pi.size, _FNV_OFFSET)
        maxlen = int(lens.max()) if lens.size else 0
        with np.errstate(over="ignore"):
            for k in range(maxlen):
                m = lens > k
                if not m.any():
                    break
                b = planes[pi[m], offs[m] + k].astype(np.uint64)
                h[m] = (h[m] ^ b) * _FNV_PRIME
        name_hash[pi, ei] = h

    return RawWalk(
        ok=ok,
        sender_slot=sender_slot.astype(np.int32),
        seq=seq,
        n_acks=np.where(ok, n_acks, 0).astype(np.int32),
        acks=acks,
        count=count,
        name_off=name_off,
        name_len=name_len,
        name_hash=name_hash,
        slot=slot,
        cap=cap,
        added=added,
        taken=taken,
        elapsed=elapsed,
    )


def gather_name_rows(
    planes: np.ndarray,
    pkt_idx: np.ndarray,
    name_off: np.ndarray,
    name_len: np.ndarray,
) -> np.ndarray:
    """Zero-padded uint8[n, 256] name rows for flat entries addressed by
    (packet index, byte offset) — the layout the directory's vectorized
    hash-table lookup verifies, built with one 2-D gather."""
    n = len(pkt_idx)
    out = np.zeros((n, 256), np.uint8)
    if n == 0:
        return out
    row = planes.shape[1]
    lens = np.minimum(name_len.astype(np.int64), 255)
    w = int(lens.max())
    if w == 0:
        return out
    # Gather only the longest live name's width (typical names are a few
    # bytes — a fixed 256-wide gather was the raw path's top host cost).
    cols = np.arange(w)[None, :]
    idx = np.clip(name_off.astype(np.int64)[:, None] + cols, 0, row - 1)
    vals = planes[pkt_idx.astype(np.int64)[:, None], idx]
    out[:, :w] = np.where(cols < lens[:, None], vals, 0)
    return out


# ---------------------------------------------------------------------------
# Device decode core — shared by the XLA path and the Pallas twin. Pure
# jnp on values; the framing walk is a lax.scan over entry ordinals.


def _device_decode(planes: jax.Array, lengths: jax.Array, entry_off: jax.Array):
    """→ (ok[P], count[P], slot, cap, added, taken, elapsed — all
    int64[P, E]). The in-dispatch framing walk + checksum/validation
    verdicts, bit-identical to wire.decode_delta_packet.

    ``entry_off`` is the host walk's per-entry offset PROPOSAL (the
    length-byte position of each entry; the host computed it anyway for
    the directory pass). The kernel never trusts it: it re-derives each
    entry's name length from the plane bytes and verifies the WHOLE
    framing chain — first offset at header+count, each successor exactly
    ``off + 1 + name_len + 34``, every entry inside the payload, the
    last one ending exactly at the checksum byte — plus envelope,
    checksum, version, ack bounds and the bit-63 value guards. Because
    the chain is fully determined by the bytes, a packet passes iff the
    proposal IS the true chain and that chain satisfies every check the
    python decoder applies: a lying host plan can only reject, never
    smuggle. This trades the r15-draft ``lax.scan`` framing walk (one
    sequential step per entry ordinal — measured ~50 ms/dispatch of pure
    small-op overhead on XLA:CPU) for ~30 wide vectorized ops over
    [P, E] lanes."""
    P, row = planes.shape
    E = entry_off.shape[1]
    pl32 = planes.astype(jnp.int32)
    pidx = jnp.arange(P)
    lengths = lengths.astype(jnp.int64)
    end = lengths - 1
    safe_end = jnp.clip(end, 0, row - 1)

    ok = (lengths >= _MIN_LEN) & (lengths <= row)
    ok &= (pl32[:, :24] == 0).all(axis=1)
    ok &= pl32[:, 24] == len(_NAME)
    # Scalar per-byte compares (not an array constant): pallas kernels
    # may not capture closed-over arrays, and this core is shared.
    for k, b in enumerate(_NAME.tolist()):
        ok &= pl32[:, 25 + k] == b
    col = jnp.arange(row)
    body = jnp.where(
        (col[None, :] >= _BASE) & (col[None, :] < end[:, None]), pl32, 0
    )
    ok &= (body.sum(axis=1) & 0xFF) == pl32[pidx, safe_end]
    ok &= pl32[:, _BASE] == wire.DELTA_VERSION
    n_acks = pl32[:, _BASE + 7].astype(jnp.int64)
    ok &= n_acks <= wire.DELTA_MAX_ACKS
    off0 = _BASE + _HEAD + _ACK * n_acks
    ok &= off0 + _COUNT <= end
    count_off = jnp.clip(off0, 0, row - 2)
    count = (
        pl32[pidx, count_off].astype(jnp.int64) << 8
    ) | pl32[pidx, count_off + 1].astype(jnp.int64)
    count = jnp.where(ok, count, 0)
    ok &= count <= E

    # Framing-chain re-validation of the proposal, vectorized.
    eo = entry_off.astype(jnp.int64)
    cols = jnp.arange(E)[None, :]
    cmask = cols < jnp.minimum(count, E)[:, None]
    nl = pl32[pidx[:, None], jnp.clip(eo, 0, row - 1)].astype(jnp.int64)
    tail = eo + 1 + nl
    nxt = tail + _ENTRY_TAIL
    in_bounds = (eo < end[:, None]) & (nxt <= end[:, None])
    ok &= jnp.where(cmask, in_bounds, True).all(axis=1)
    first_ok = jnp.where(count > 0, eo[:, 0] == off0 + _COUNT, True)
    succ_ok = jnp.where(
        cmask[:, 1:], eo[:, 1:] == nxt[:, :-1], True
    ).all(axis=1)
    last_idx = jnp.clip(count - 1, 0, E - 1)
    last_end = jnp.take_along_axis(nxt, last_idx[:, None], axis=1)[:, 0]
    end_ok = jnp.where(count > 0, last_end == end, off0 + _COUNT == end)
    ok &= first_ok & succ_ok & end_ok

    # Entry extraction: one [P, E, 34] byte gather, big-endian folds.
    idx34 = jnp.clip(tail[:, :, None] + jnp.arange(_ENTRY_TAIL), 0, row - 1)
    b34 = pl32[pidx[:, None, None], idx34].astype(jnp.int64)
    slot = (b34[..., 0] << 8) | b34[..., 1]

    def be64(o: int) -> jax.Array:
        acc = b34[..., o]
        for k in range(1, 8):
            acc = (acc << 8) | b34[..., o + k]
        return acc

    cap = be64(2)
    added = be64(10)
    taken = be64(18)
    elapsed = be64(26)
    # Negative int64 == u64 bit 63 set: reject the whole packet (the
    # python decoder's max(...) > _INT64_MAX check).
    bit63 = (cap < 0) | (added < 0) | (taken < 0) | (elapsed < 0)
    ok &= ~jnp.where(cmask, bit63, False).any(axis=1)
    count = jnp.where(ok, count, 0)
    return ok, count, slot, cap, added, taken, elapsed


def _decode_fold_core(
    state: LimiterState,
    planes: jax.Array,
    lengths: jax.Array,
    entry_off: jax.Array,
    rows: jax.Array,
    hosted: jax.Array,
):
    """Decode + fold, pure: → (state', ok[P], entry_ok[P,E],
    hosted_mask[P,E], slot, cap, added, taken, elapsed). ``entry_off``
    is the host walk's framing proposal the kernel re-validates (see
    _device_decode); ``rows`` is the host directory plan (FOLD_PAD_ROW
    sentinels mark entries the fold must skip); ``hosted`` flags
    host-resident rows, masked OUT of the fold and surfaced in
    ``hosted_mask`` for the engine's host-lane absorb tail."""
    E = rows.shape[1]
    ok, count, slot, cap, added, taken, elapsed = _device_decode(
        planes, lengths, entry_off
    )
    live = ok[:, None] & (jnp.arange(E)[None, :] < count[:, None])
    nodes = state.pn.shape[1]
    entry_ok = live & (slot >= 0) & (slot < nodes)
    hosted_mask = entry_ok & hosted
    fold = entry_ok & ~hosted
    frows = jnp.where(fold, rows, FOLD_PAD_ROW)
    fslots = jnp.where(fold, slot, 0).astype(jnp.int32)
    a = jnp.where(fold, added, 0)
    t = jnp.where(fold, taken, 0)
    e = jnp.where(fold, jnp.maximum(elapsed, 0), 0)
    pair = jnp.stack([a, t], axis=-1)
    pn = state.pn.at[frows, fslots].max(pair, mode="drop")
    el = state.elapsed.at[frows].max(e, mode="drop")
    return (
        LimiterState(pn=pn, elapsed=el),
        ok,
        entry_ok,
        hosted_mask,
        slot,
        cap,
        added,
        taken,
        elapsed,
    )


def decode_fold_raw(
    state: LimiterState,
    planes: jax.Array,
    lengths: jax.Array,
    entry_off: jax.Array,
    rows: jax.Array,
    hosted: jax.Array,
):
    """The registered kernel root (PROVE_ROOTS ``ops.ingest.
    decode_fold_raw``): raw dv2 byte planes → joined state + verdicts in
    one dispatch. See module docs for the contract."""
    return _decode_fold_core(state, planes, lengths, entry_off, rows, hosted)


decode_fold_raw_jit = partial(jax.jit, donate_argnums=0)(decode_fold_raw)


# ---------------------------------------------------------------------------
# Pallas twin — same decode core inside a pallas_call (interpret-capable
# on CPU; the native probe gates compiled use, and current Mosaic rejects
# byte-granular gathers the same way it rejected the scatter-merge
# kernel's scalar VMEM stores, BENCH_r02/pallas_merge.py notes).

try:
    from jax.experimental import pallas as pl  # noqa: F401

    _PALLAS_OK = True
except Exception:  # pragma: no cover - env without pallas
    _PALLAS_OK = False


def available() -> bool:
    return _PALLAS_OK


def decode_fold_raw_pallas(
    state: LimiterState,
    planes: jax.Array,
    lengths: jax.Array,
    entry_off: jax.Array,
    rows: jax.Array,
    hosted: jax.Array,
    interpret: bool = True,
):
    """Pallas form of :func:`decode_fold_raw`: one program, every operand
    resident, outputs aliased onto the state planes — the shape a future
    Mosaic byte-gather lowering would fill in. Shares
    :func:`_decode_fold_core` verbatim so the differential sweep pinning
    it against the XLA path is a check on the pallas_call plumbing, not
    a second decoder implementation to drift."""
    if not _PALLAS_OK:  # pragma: no cover - env without pallas
        raise RuntimeError("pallas unavailable")
    P, E = rows.shape

    def kernel(
        planes_ref, lengths_ref, eoff_ref, rows_ref, hosted_ref, pn_in,
        el_in, pn_out, el_out, ok_out, eok_out, hm_out, slot_out,
        cap_out, a_out, t_out, e_out,
    ):
        st = LimiterState(pn=pn_in[...], elapsed=el_in[...])
        out = _decode_fold_core(
            st, planes_ref[...], lengths_ref[...], eoff_ref[...],
            rows_ref[...], hosted_ref[...],
        )
        pn_out[...] = out[0].pn
        el_out[...] = out[0].elapsed
        ok_out[...] = out[1]
        eok_out[...] = out[2]
        hm_out[...] = out[3]
        slot_out[...] = out[4]
        cap_out[...] = out[5]
        a_out[...] = out[6]
        t_out[...] = out[7]
        e_out[...] = out[8]

    pe_i64 = jax.ShapeDtypeStruct((P, E), jnp.int64)
    pe_b = jax.ShapeDtypeStruct((P, E), jnp.bool_)
    outs = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct(state.pn.shape, state.pn.dtype),
            jax.ShapeDtypeStruct(state.elapsed.shape, state.elapsed.dtype),
            jax.ShapeDtypeStruct((P,), jnp.bool_),
            pe_b, pe_b, pe_i64, pe_i64, pe_i64, pe_i64, pe_i64,
        ],
        # Flat inputs: planes, lengths, entry_off, rows, hosted, pn, el.
        input_output_aliases={5: 0, 6: 1},
        interpret=interpret,
    )(planes, lengths, entry_off, rows, hosted, state.pn, state.elapsed)
    return (LimiterState(pn=outs[0], elapsed=outs[1]), *outs[2:])


_native_probe: "bool | None" = None


def native_available() -> bool:
    """Compiled (non-interpret) Pallas path usable on this backend,
    proven by a one-time tiny probe — same honesty contract as
    pallas_merge.native_available: interpret mode exists everywhere but
    is slower than the XLA path, so only a real accelerator lowering
    counts, and only if Mosaic actually accepts the kernel."""
    global _native_probe
    if not _PALLAS_OK:
        return False
    try:
        if jax.default_backend() in ("cpu",):
            return False
    except Exception:  # pragma: no cover - backend init failure
        return False
    if _native_probe is None:
        try:
            from patrol_tpu.models.limiter import LimiterConfig, init_state

            st = init_state(LimiterConfig(buckets=8, nodes=2))
            planes = jnp.zeros((1, 128), jnp.uint8)
            e = max_entries(128)
            decode_fold_raw_pallas(
                st, planes, jnp.zeros(1, jnp.int32),
                jnp.zeros((1, e), jnp.int32),
                jnp.zeros((1, e), jnp.int32),
                jnp.zeros((1, e), jnp.bool_),
                interpret=False,
            )[0].pn.block_until_ready()
            _native_probe = True
        except Exception as exc:  # pragma: no cover - backend-specific
            import logging

            logging.getLogger("patrol.ingest").warning(
                "pallas decode_fold_raw rejected by backend, using XLA: %s",
                str(exc).splitlines()[0] if str(exc) else type(exc).__name__,
            )
            _native_probe = False
    return _native_probe
