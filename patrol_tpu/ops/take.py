"""The batched take kernel — the reference's hot inner computation
(``Bucket.Take``, bucket.go:186-225) re-expressed as one branch-free JAX
kernel over a microbatch of requests.

Where the reference serializes takes under a per-bucket mutex
(bucket.go:21,187), this kernel admits a whole microbatch in one device
call. Contention on a hot bucket is handled *algebraically* instead of with
locks: the host batcher coalesces same-(bucket, rate, count) requests into a
single kernel row carrying ``nreq`` (how many identical requests queued) and
the kernel computes how many of them fit greedily — exactly the result of
running the reference's sequential takes at the same timestamp, where only
the first take refills (delta becomes 0 for the rest).

Fixed-point arithmetic notes: state is int64 nanotokens; the refill grant is
computed in float64 exactly as the reference does (``float64(d) /
float64(interval)``, bucket.go:130-143) then floor-quantized to nanotokens,
so host oracle and device kernel agree bit-for-bit on CPU and to float64
precision on TPU.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from patrol_tpu.models.limiter import ADDED, TAKEN, NANO, LimiterState

# Refill grants are clipped here before the float64→int64 cast to keep the
# cast defined; any realistic grant is far below (and the capacity cap is
# applied after, in exact int64).
_GRANT_CLIP = float(2**62)

# Packed-transfer layout of one take tick (engine._apply_takes ↔
# engine._jit_take_packed): the host ships ONE int64[TAKE_PACK_ROWS, K]
# request matrix (rows, now_ns, freq, per_ns, count_nt, nreq,
# cap_base_nt, created_ns) and receives ONE int64[TAKE_RESULT_ROWS, K]
# result matrix (have, admitted, own_added, own_taken, elapsed,
# sum_added, sum_taken). Fixed shapes per padded K, so the engine's
# StagingPool recycles the exact request buffers across ticks.
TAKE_PACK_ROWS = 8
TAKE_RESULT_ROWS = 7


class TakeRequest(NamedTuple):
    """A microbatch of K take requests. All arrays have leading dim K.

    Invariants maintained by the host batcher:
      * ``rows`` are unique among rows with ``nreq > 0`` (duplicates are
        coalesced into ``nreq``); padding rows have ``nreq == 0`` and commit
        nothing.
      * ``cap_base_nt`` is the lazily-initialized capacity base for the row
        (host-owned mirror of the reference's ``added = capacity`` init,
        bucket.go:194-196).
      * ``created_ns`` is the host-owned creation timestamp (repo.go:205).
    """

    rows: jax.Array  # int32[K] bucket-slot indices
    now_ns: jax.Array  # int64[K] request clock (the injected-clock seam)
    freq: jax.Array  # int64[K] rate frequency (capacity in tokens)
    per_ns: jax.Array  # int64[K] rate period
    count_nt: jax.Array  # int64[K] tokens per request, in nanotokens
    nreq: jax.Array  # int64[K] identical requests coalesced into this row
    cap_base_nt: jax.Array  # int64[K] capacity base (0 ⇒ fresh bucket)
    created_ns: jax.Array  # int64[K] bucket creation time


class TakeResult(NamedTuple):
    """Per-row outcome. The host fans per-request responses out of this:
    request i (0-based arrival order) of a row with admitted count k gets
    ``ok = i < k`` and ``remaining = have − min(i+1, k)·count`` (the
    reference returns post-commit remaining on success, pre-reject remaining
    on failure, bucket.go:215-224)."""

    have_nt: jax.Array  # int64[K] tokens after refill, before the batch's takes
    admitted: jax.Array  # int64[K] how many of nreq were admitted
    own_added_nt: jax.Array  # int64[K] this node's PN lane after commit …
    own_taken_nt: jax.Array  # int64[K] … the exact lane values for the v2 trailer
    elapsed_ns: jax.Array  # int64[K] bucket elapsed after commit
    sum_added_nt: jax.Array  # int64[K] Σ lanes added post-commit … the aggregate
    sum_taken_nt: jax.Array  # int64[K] … scalars reference peers expect in the header


def take_batch(
    state: LimiterState, req: TakeRequest, node_slot: int
) -> tuple[LimiterState, TakeResult]:
    """Pure function: apply a microbatch of takes, return new state + results.

    Mirrors bucket.go:186-225 step-for-step on each row:
    capacity base (lazy init is host-side), monotonic-time guard
    (bucket.go:198-201), refill capped at capacity — cap may be negative,
    forfeiting excess tokens from merges (bucket.go:211-213) — and a
    conditional commit of (grant, taken, elapsed) only when at least one
    request is admitted (bucket.go:217-223).
    """
    i64 = jnp.int64
    rows = req.rows

    pn_rows = state.pn[rows]  # [K, N, 2] gather
    sum_added = pn_rows[:, :, ADDED].sum(axis=-1)
    sum_taken = pn_rows[:, :, TAKEN].sum(axis=-1)

    cap_now_nt = req.freq * NANO  # capacity of *this* request (bucket.go:192)
    tokens_nt = req.cap_base_nt + sum_added - sum_taken

    last = jnp.minimum(req.created_ns + state.elapsed[rows], req.now_ns)
    delta = req.now_ns - last

    # Refill: float64(delta)/float64(interval) tokens (bucket.go:130-148),
    # interval being the truncating integer division per/freq.
    safe_freq = jnp.where(req.freq == 0, 1, req.freq)
    interval = req.per_ns // safe_freq
    rate_zero = (req.freq == 0) | (req.per_ns == 0) | (interval == 0)
    safe_interval = jnp.where(interval == 0, 1, interval)
    grant_tokens = delta.astype(jnp.float64) / safe_interval.astype(jnp.float64)
    grant_f = jnp.where(rate_zero, 0.0, grant_tokens * float(NANO))
    grant_nt = jnp.floor(jnp.clip(grant_f, 0.0, _GRANT_CLIP)).astype(i64)
    missing_nt = cap_now_nt - tokens_nt
    grant_nt = jnp.minimum(grant_nt, missing_nt)

    have_nt = tokens_nt + grant_nt

    # Greedy admission of nreq identical requests of count_nt each: the first
    # take sees the refilled balance; takes 2..n run at the same now (delta 0,
    # no further refill), so k = clip(have // count, 0, nreq).
    safe_count = jnp.where(req.count_nt <= 0, 1, req.count_nt)
    k = jnp.clip(have_nt // safe_count, 0, req.nreq)
    k = jnp.where(req.count_nt > 0, k, 0)
    success = k >= 1

    # Over-capacity forfeit, monotone form: the reference commits a NEGATIVE
    # grant when merges pushed tokens above capacity (bucket.go:211-213),
    # which would make the added-lane non-monotone — and any max-based join
    # (UDP merge or mesh max-convergence) would resurrect forfeited tokens
    # (the reference's own protocol has exactly that quirk). Booking the
    # forfeit as extra TAKEN keeps both lanes monotone G-counters with the
    # same observable balance: a − t is unchanged.
    forfeit = jnp.maximum(-grant_nt, i64(0))
    d_added = jnp.where(success, jnp.maximum(grant_nt, i64(0)), i64(0))
    d_taken = jnp.where(success, k * req.count_nt + forfeit, i64(0))
    d_elapsed = jnp.where(success, delta, i64(0))

    # Padding rows (nreq == 0) contribute zero deltas, so duplicate indices
    # from padding are harmless under scatter-add. The (added, taken) pair
    # commits as one scatter of two-element windows: TPU scatter cost is
    # per update, not per element (scripts/probe_scatter.py), so this
    # halves the pn commit versus two element-granular scatters.
    pair = jnp.stack([d_added, d_taken], axis=-1)
    pn = state.pn.at[rows, node_slot].add(pair)
    elapsed = state.elapsed.at[rows].add(d_elapsed)

    result = TakeResult(
        have_nt=have_nt,
        admitted=k,
        own_added_nt=pn_rows[:, node_slot, ADDED] + d_added,
        own_taken_nt=pn_rows[:, node_slot, TAKEN] + d_taken,
        elapsed_ns=state.elapsed[rows] + d_elapsed,
        sum_added_nt=sum_added + d_added,
        sum_taken_nt=sum_taken + d_taken,
    )
    return LimiterState(pn=pn, elapsed=elapsed), result


take_batch_jit = partial(jax.jit, static_argnames=("node_slot",), donate_argnums=0)(
    take_batch
)


def take_n_batch(
    state: LimiterState, packed: jax.Array, node_slot: int
) -> tuple[LimiterState, jax.Array]:
    """The take-n serving kernel: ONE packed ``int64[TAKE_PACK_ROWS, K]``
    request matrix in, ONE packed ``int64[TAKE_RESULT_ROWS, K]`` result
    matrix out — the exact transfer layout the feeder tick ships
    (engine._apply_takes), promoted to a certified kernel root of its
    own. Hot-key coalescing rides the ``nreq`` row: n same-(bucket,
    rate, count) takes collapse into one kernel row granting
    ``min(n, available)`` in a single dispatch, and the host splits the
    grant FIFO across the waiting tickets (:func:`split_grant`).

    The admission algebra is :func:`take_batch`'s — this wrapper only
    fixes the wire layout — but it is registered as its own prove root
    so the n>1 greedy grant is checked DIRECTLY against the sequential
    one-at-a-time replay (PTP002), the deny fixpoint is pinned (PTP003),
    and the packed layout's dtypes can't drift (PTP005)."""
    req = TakeRequest(
        rows=packed[0].astype(jnp.int32),
        now_ns=packed[1],
        freq=packed[2],
        per_ns=packed[3],
        count_nt=packed[4],
        nreq=packed[5],
        cap_base_nt=packed[6],
        created_ns=packed[7],
    )
    state, res = take_batch(state, req, node_slot)
    out = jnp.stack(
        [
            res.have_nt,
            res.admitted,
            res.own_added_nt,
            res.own_taken_nt,
            res.elapsed_ns,
            res.sum_added_nt,
            res.sum_taken_nt,
        ]
    )
    return state, out


take_n_batch_jit = partial(
    jax.jit, static_argnames=("node_slot",), donate_argnums=0
)(take_n_batch)


def split_grant(
    have_nt: int, admitted: int, count_nt: int, nreq: int
) -> list[tuple[int, bool]]:
    """Deterministic FIFO split of one coalesced row's grant across its
    ``nreq`` waiting tickets, in arrival order: the first ``admitted``
    tickets succeed (each seeing the balance after its own commit), the
    rest get clean denies (each seeing the balance after ALL admitted
    commits). This is host policy — the kernel only reports ``admitted``
    — so it is registered as its own prove root: the small-domain model
    checks the split against the first-k-of-m sequential outcome
    bit-exactly (a LIFO or round-robin split is rejected as PTP002)."""
    return [
        remaining_for_request(have_nt, admitted, count_nt, i)
        for i in range(nreq)
    ]


def remaining_for_request(
    have_nt: int, admitted: int, count_nt: int, index: int
) -> tuple[int, bool]:
    """Host-side fan-out of one coalesced row to per-request responses.

    ``index`` is the request's 0-based arrival position in the coalesced
    queue. Matches the reference's sequential semantics: admitted requests
    see the balance after their own commit; rejected ones see the balance
    left after all admitted requests (bucket.go:215-224). The uint64 cast of
    the reference is clamped at zero (PN merges can drive the balance
    negative; Go's negative-float→uint64 cast is UB we do not reproduce).
    """
    ok = index < admitted
    consumed = (index + 1 if ok else admitted) * count_nt
    remaining_nt = have_nt - consumed
    return max(remaining_nt, 0) // NANO, ok
