"""Pallas TPU kernel for the scatter-merge hot path.

XLA lowers ``state.at[rows, slots].max(values)`` (ops/merge.py) to a scatter
that serializes on TPU. This kernel restructures the op around the memory
system instead:

1. The host sorts the delta batch by bucket row (cheap numpy argsort) and
   computes which 512-row *blocks* of the state are touched.
2. The grid iterates only the touched blocks — block indices arrive via
   scalar prefetch (``PrefetchScalarGridSpec``), so the BlockSpec index_map
   DMAs exactly the needed 512×N×2 state tiles into VMEM and nothing else.
   A merge of K deltas therefore streams O(touched blocks) of state, not
   O(B) and not K serialized HBM round-trips.
3. Inside a block, a scalar loop applies that block's slice of the sorted
   deltas as VMEM read-modify-writes.

Because TPU vector lanes are 32-bit, the int64 CRDT planes are bitcast to
int32 (lo, hi) pairs and merged with a lexicographic max — exact for the
non-negative int64 domain the state invariants guarantee (lanes are
G-counters; ingest clamps negatives, ops/merge.py).

Safety notes baked into the host-side preparation (:func:`prepare`):
* touched-block ids are deduplicated — revisiting a block within one grid
  would race the pipeline's write-back (read-before-write hazard);
* padding of the block-id list uses *untouched* block ids for the same
  reason (processing an untouched block is a no-op copy);
* when every block is touched, a dense ``merge_dense`` sweep is cheaper —
  the engine picks per batch.

Verified against the XLA scatter path in interpret mode (tests) and usable
on CPU the same way; selected on TPU via PATROL_MERGE_KERNEL=auto|pallas
— behind a compile probe (:func:`native_available`). On the current
jax 0.9.0 / v5e Mosaic the probe fails and the engine stays on the XLA
scatter, which r3 measured honestly at ~130-215 ns per scatter *update*
regardless of window size (scripts/probe_scatter.py). The full r3 kernel
exploration, so the next Mosaic bump can be retried with data:

* This kernel's per-delta VMEM read-modify-writes lower only as vector
  dynamic slices, and Mosaic requires a dynamic dim-0 slice index it can
  statically prove tile-aligned ("cannot statically prove that index in
  dimension 0 is a multiple of 128") — arbitrary per-row RMW inside one
  VMEM block is not expressible today.
* A DMA-based variant (state in HBM via ``memory_space=ANY``, per-row
  ``make_async_copy`` RMW, D=8 double-buffered pipeline) DOES compile and
  run (scripts/probe_dma_scatter.py): raw row traffic streams at ~3 ns/row
  pipelined. But the CRDT join itself — a lexicographic (hi, lo) int64 max
  on (lo, hi)-interleaved int32 lanes — costs ~190-260 ns/delta in-kernel
  (lane rolls or masked reductions), landing the total at or above the
  XLA scatter's per-update cost. The kernel only wins if state moves to a
  de-interleaved (split lo/hi plane) layout, which would put the whole
  int64 emulation burden on every other op; measured and declined.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from patrol_tpu.models.limiter import LimiterState

ROWS_PER_BLOCK = 512


def _split64(v: jax.Array) -> jax.Array:
    """int64[...] → int32[..., 2] as (lo, hi) words (XLA bitcast order:
    index 0 = least-significant 32 bits)."""
    return jax.lax.bitcast_convert_type(v, jnp.int32)


def _join64(v32: jax.Array) -> jax.Array:
    return jax.lax.bitcast_convert_type(v32, jnp.int64)


def _pair_max(a_lo, a_hi, b_lo, b_hi):
    """Lexicographic (hi, lo-unsigned) max — int64 max for non-negative
    values split into 32-bit words."""
    sign = jnp.int32(-0x80000000)
    a_gt = (a_hi > b_hi) | ((a_hi == b_hi) & ((a_lo ^ sign) > (b_lo ^ sign)))
    return jnp.where(a_gt, a_lo, b_lo), jnp.where(a_gt, a_hi, b_hi)


def _kernel(
    block_ids_ref,  # int32[G]       (scalar prefetch)
    starts_ref,  # int32[G]          (scalar prefetch)
    ends_ref,  # int32[G]            (scalar prefetch)
    rows_ref,  # int32[K]            sorted, global row ids
    slots_ref,  # int32[K]
    added_ref,  # int32[K, 2]
    taken_ref,  # int32[K, 2]
    elapsed_ref,  # int32[K, 2]
    pn_in_ref,  # int32[R, N, 2, 2]  (aliased with pn_out)
    el_in_ref,  # int32[R, 2]        (aliased with el_out)
    pn_out_ref,
    el_out_ref,
):
    """Per-block body, VECTOR read-modify-writes only.

    The r2 kernel did per-delta scalar VMEM stores and Mosaic (v5e)
    rejects those ("Cannot store scalars to VMEM"). This version touches
    VMEM exclusively through shapes Mosaic vectorizes:

    * pn: per delta, one dynamic-slice row load [1, N, 2, 2], a one-hot
      lane/plane join built from broadcast scalars, one dynamic-slice row
      store. Non-target lanes join with (0, 0), a no-op under max on the
      non-negative domain.
    * elapsed: per delta, a full-tile [R, 2] one-hot max — no dynamic
      store at all.

    Consecutive deltas hitting the same row are safe: fori_loop is
    sequential, each iteration reads the previous one's store.

    Lowering-hazard rules obeyed throughout (each bisected to a concrete
    failure on jax 0.9.0 / v5e Mosaic, scripts/probe_pallas.py notes):

    * no ``jnp.where`` whose condition compares an iota against a TRACED
      scalar — select lowering recurses in ``_convert_helper``; use the
      ``(cmp).astype(int32) * value`` mask-multiply form instead;
    * no ``//`` or ``%`` on traced scalars (same recursion) — shift/mask;
    * no bare python literals where promotion would insert a scalar
      convert (same recursion) — spell ``jnp.int32(0)``;
    * int32 ``fori_loop`` bounds, or the induction variable arrives as
      int64 under x64 and every mixed index add fails MLIR verification
      ("'arith.addi' op requires the same type for all operands").
    """
    g = pl.program_id(0)
    base = block_ids_ref[g] * ROWS_PER_BLOCK
    n = pn_out_ref.shape[1]

    pn_out_ref[...] = pn_in_ref[...]
    el_out_ref[...] = el_in_ref[...]

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, n, 2), 1)
    plane_is_added = (
        jax.lax.broadcasted_iota(jnp.int32, (1, n, 2), 2) == 0
    ).astype(jnp.int32)
    plane_is_taken = jnp.int32(1) - plane_is_added
    rowvec = jax.lax.broadcasted_iota(jnp.int32, (ROWS_PER_BLOCK, 1), 0)

    def body(j, _):
        r = rows_ref[j] - base
        s = slots_ref[j]

        cur = pn_out_ref[pl.dslice(r, 1)]  # [1, N, 2, 2]
        # Mask-multiply select (see hazard rules above): the target lane
        # carries (added, taken) pairs, every other lane carries (0, 0) —
        # the identity of max on the non-negative CRDT domain.
        onehot = (lane == s).astype(jnp.int32)
        val_lo = plane_is_added * added_ref[j, 0] + plane_is_taken * taken_ref[j, 0]
        val_hi = plane_is_added * added_ref[j, 1] + plane_is_taken * taken_ref[j, 1]
        upd_lo = onehot * val_lo
        upd_hi = onehot * val_hi
        new_lo, new_hi = _pair_max(upd_lo, upd_hi, cur[..., 0], cur[..., 1])
        pn_out_ref[pl.dslice(r, 1)] = jnp.stack([new_lo, new_hi], axis=-1)

        el = el_out_ref[...]  # [R, 2]
        hit = (rowvec == r).astype(jnp.int32)
        eu_lo = hit * elapsed_ref[j, 0]
        eu_hi = hit * elapsed_ref[j, 1]
        ne_lo, ne_hi = _pair_max(eu_lo[:, 0], eu_hi[:, 0], el[:, 0], el[:, 1])
        el_out_ref[...] = jnp.stack([ne_lo, ne_hi], axis=-1)
        return 0

    jax.lax.fori_loop(
        starts_ref[g].astype(jnp.int32), ends_ref[g].astype(jnp.int32), body, 0
    )


try:  # pallas is TPU/CPU-interpret capable; degrade gracefully elsewhere
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False


def prepare(
    rows: np.ndarray, num_buckets: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, int]:
    """Host-side sort + block planning.

    → (order, block_ids[G], starts[G], ends[G], n_touched). ``order``
    sorts the batch by row; ``block_ids`` are the touched 512-row blocks,
    padded with *untouched* ids up to a power-of-two length (≤ total
    blocks); ``starts[g]:ends[g]`` is block g's slice of the sorted batch
    (empty for padding blocks).
    """
    order = np.argsort(rows, kind="stable")
    sorted_rows = rows[order]
    touched = np.unique(sorted_rows // ROWS_PER_BLOCK).astype(np.int32)
    total_blocks = (num_buckets + ROWS_PER_BLOCK - 1) // ROWS_PER_BLOCK

    g = max(1, len(touched))
    G = 1
    while G < g:
        G <<= 1
    G = min(G, total_blocks)
    if G < len(touched):
        raise ValueError("more touched blocks than padded grid")  # pragma: no cover

    block_ids = np.zeros(G, np.int32)
    block_ids[: len(touched)] = touched
    if len(touched) < G:
        touched_set = set(touched.tolist())
        fill = [b for b in range(total_blocks) if b not in touched_set]
        block_ids[len(touched) :] = np.array(fill[: G - len(touched)], np.int32)

    starts = np.searchsorted(sorted_rows, block_ids * ROWS_PER_BLOCK).astype(np.int32)
    ends = np.searchsorted(sorted_rows, (block_ids + 1) * ROWS_PER_BLOCK).astype(np.int32)
    # Padding blocks have start == end (their searchsorted range is empty).
    return order, block_ids, starts, ends, len(touched)


@functools.partial(jax.jit, static_argnames=("interpret",), donate_argnums=0)
def _merge_pallas_device(
    state: LimiterState,
    block_ids,
    starts,
    ends,
    rows,
    slots,
    added,
    taken,
    elapsed,
    interpret: bool = False,
) -> LimiterState:
    B, N = state.pn.shape[0], state.pn.shape[1]
    pn32 = _split64(state.pn)  # [B, N, 2, 2]
    el32 = _split64(state.elapsed)  # [B, 2]
    G = block_ids.shape[0]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(G,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),  # rows
            pl.BlockSpec(memory_space=pltpu.VMEM),  # slots
            pl.BlockSpec(memory_space=pltpu.VMEM),  # added
            pl.BlockSpec(memory_space=pltpu.VMEM),  # taken
            pl.BlockSpec(memory_space=pltpu.VMEM),  # elapsed
            pl.BlockSpec(
                (ROWS_PER_BLOCK, N, 2, 2),
                lambda g, blk, st, en: (blk[g], 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (ROWS_PER_BLOCK, 2),
                lambda g, blk, st, en: (blk[g], 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (ROWS_PER_BLOCK, N, 2, 2),
                lambda g, blk, st, en: (blk[g], 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (ROWS_PER_BLOCK, 2),
                lambda g, blk, st, en: (blk[g], 0),
                memory_space=pltpu.VMEM,
            ),
        ],
    )

    pn32, el32 = pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(pn32.shape, jnp.int32),
            jax.ShapeDtypeStruct(el32.shape, jnp.int32),
        ],
        # Inputs in flattened order: 0=block_ids, 1=starts, 2=ends, 3=rows,
        # 4=slots, 5=added, 6=taken, 7=elapsed, 8=pn32, 9=el32.
        input_output_aliases={8: 0, 9: 1},
        interpret=interpret,
    )(block_ids, starts, ends, rows, slots, added, taken, elapsed, pn32, el32)

    return LimiterState(pn=_join64(pn32), elapsed=_join64(el32))


def merge_batch_pallas(
    state: LimiterState,
    rows: np.ndarray,
    slots: np.ndarray,
    added_nt: np.ndarray,
    taken_nt: np.ndarray,
    elapsed_ns: np.ndarray,
    interpret: bool = False,
) -> LimiterState:
    """Host entry: sort, plan blocks, launch. Arrays are host numpy; values
    must already be non-negative (ingest clamp)."""
    B = state.pn.shape[0]
    order, block_ids, starts, ends, _ = prepare(np.asarray(rows, np.int64), B)

    def split_host(v) -> np.ndarray:
        v = np.ascontiguousarray(np.asarray(v, np.int64)[order])
        return v.view(np.int32).reshape(len(v), 2)

    return _merge_pallas_device(
        state,
        jnp.asarray(block_ids),
        jnp.asarray(starts),
        jnp.asarray(ends),
        jnp.asarray(np.asarray(rows, np.int32)[order]),
        jnp.asarray(np.asarray(slots, np.int32)[order]),
        jnp.asarray(split_host(added_nt)),
        jnp.asarray(split_host(taken_nt)),
        jnp.asarray(split_host(elapsed_ns)),
        interpret=interpret,
    )


def available() -> bool:
    """Pallas importable (interpret-mode capable on CPU — tests use this)."""
    return _PALLAS_OK


_native_probe: "bool | None" = None


def native_available() -> bool:
    """Pallas compiled path usable on the current backend, proven by a
    one-time tiny compile probe (cached).

    Interpret mode exists on CPU but is orders of magnitude slower than
    the XLA scatter, so only an accelerator backend counts — and an
    accelerator only counts if Mosaic actually accepts the kernel: real
    v5e rejects the per-delta scalar VMEM read-modify-writes ("Cannot
    store scalars to VMEM", BENCH_r02), so without the probe an explicit
    PATROL_MERGE_KERNEL=pallas would crash the engine tick. Measured
    verdict on hardware (bench.py pallas-compare, r2): the XLA scatter
    merges K=131072 in ~20-40µs — at or under one engine tick — so the
    scatter path stays the TPU default and this kernel is selected only
    where a future Mosaic accepts it AND the batch is block-sparse."""
    global _native_probe
    if not _PALLAS_OK:
        return False
    try:
        if jax.default_backend() in ("cpu",):
            return False
    except Exception:  # pragma: no cover - backend init failure
        return False
    if _native_probe is None:
        try:
            probe = LimiterState(
                pn=jnp.zeros((ROWS_PER_BLOCK, 8, 2), jnp.int64),
                elapsed=jnp.zeros((ROWS_PER_BLOCK,), jnp.int64),
            )
            merge_batch_pallas(
                probe,
                np.zeros(1, np.int64),
                np.zeros(1, np.int64),
                np.ones(1, np.int64),
                np.zeros(1, np.int64),
                np.zeros(1, np.int64),
            ).pn.block_until_ready()
            _native_probe = True
        except Exception as exc:
            import logging

            logging.getLogger("patrol.pallas").warning(
                "pallas merge kernel rejected by backend, using XLA scatter: %s",
                str(exc).splitlines()[0] if str(exc) else type(exc).__name__,
            )
            _native_probe = False
    return _native_probe


# auto-mode knobs (PATROL_MERGE_KERNEL=auto): pallas wins when the batch is
# block-sparse — it streams only touched 512-row tiles where the XLA scatter
# serializes per delta. Tiny batches lose to kernel-launch overhead; near-
# dense batches should take the vectorized dense path instead. Thresholds
# are overridable so bench.py's measured crossover can be pinned via env.
import os as _os

AUTO_MIN_BATCH = int(_os.environ.get("PATROL_PALLAS_MIN_BATCH", "1024"))
AUTO_BLOCK_FRAC = float(_os.environ.get("PATROL_PALLAS_BLOCK_FRAC", "0.25"))


def auto_pick(rows: np.ndarray, num_buckets: int) -> bool:
    """The PATROL_MERGE_KERNEL=auto heuristic (docstring contract): use the
    pallas block-sparse kernel iff it can run natively, the batch is big
    enough to amortize launch, and it touches a small fraction of the
    state's 512-row blocks."""
    if len(rows) < AUTO_MIN_BATCH or not native_available():
        return False
    touched = len(np.unique(np.asarray(rows) // ROWS_PER_BLOCK))
    total = max(1, (num_buckets + ROWS_PER_BLOCK - 1) // ROWS_PER_BLOCK)
    return touched <= total * AUTO_BLOCK_FRAC
