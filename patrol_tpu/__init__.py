"""patrol_tpu — a TPU-native distributed rate-limiting framework.

Re-imagines calavera/patrol (a Go distributed rate-limiting HTTP sidecar whose
token buckets are CRDT PN-counters replicated eventually-consistently over
≤256-byte UDP full-state packets; reference at /root/reference) as a TPU-first
system:

* Bucket state is a dense ``(buckets × nodes × 2)`` int64 array of fixed-point
  "nanotokens" on device, plus an int64 elapsed G-counter per bucket. Instead
  of the reference's lock-per-bucket concurrency (bucket.go:21, repo.go:173),
  takes and CvRDT max-merges are batched, branch-free JAX kernels.
* The reference's lossy scalar max-merge (bucket.go:240-263) becomes a true
  PN-counter: one (added, taken) slot per node, elementwise max on merge,
  bucket value = capacity + Σadded − Σtaken.
* Replication within a TPU slice rides ICI (a max all-reduce across a mesh axis);
  replication between hosts keeps the reference's 25-byte-header / 256-byte
  UDP wire format (bucket.go:34-91) for interop.
* A host runtime microbatches HTTP takes and incoming UDP deltas into single
  device calls; the keystone `Repo` seam (repo.go:13-18) is preserved.

Reference parity map (file:line cites refer to the Go reference):

====================  ==================================================
bucket.go:186-225     Bucket.Take        -> patrol_tpu.ops.take.take_batch
bucket.go:240-263     Bucket.Merge       -> patrol_tpu.ops.merge.merge_batch
bucket.go:96-153      Rate / ParseRate   -> patrol_tpu.ops.rate
bucket.go:34-91       wire codec         -> patrol_tpu.ops.wire
repo.go:171-235       LocalRepo          -> patrol_tpu.runtime.bucket (host)
repo.go:13-18         Repo seam          -> patrol_tpu.runtime.repo
repo.go:20-169        ReplicatedRepo/UDP -> patrol_tpu.net.replication
api.go:14-86          HTTP /take API     -> patrol_tpu.net.api
command.go:17-83      supervisor         -> patrol_tpu.command
cmd/patrol/main.go    CLI                -> patrol_tpu.cli
====================  ==================================================
"""

import jax

# int64 bucket state is the core invariant: fixed-point "nanotokens" make the
# CvRDT max-merge bit-deterministic across replicas (float64 max on mixed
# hardware is not). This must run before any tracing happens.
jax.config.update("jax_enable_x64", True)

from patrol_tpu.ops.rate import (  # noqa: E402
    Rate,
    parse_rate,
    parse_duration,
    format_duration,
)
from patrol_tpu.runtime.bucket import Bucket, LocalRepo  # noqa: E402

__version__ = "0.1.0"

__all__ = [
    "Rate",
    "parse_rate",
    "parse_duration",
    "format_duration",
    "Bucket",
    "LocalRepo",
    "__version__",
]
