"""Mesh scale-out: the TPU-native replacement for scaling by adding UDP
peers (SURVEY §2 "Parallelism & distribution strategies").

The reference has exactly two scaling axes (SURVEY §5): bucket cardinality
and node count. They map onto a 2-D ``jax.sharding.Mesh``:

* axis ``"b"`` — **bucket sharding**: the bucket dimension of
  ``pn[B, N, 2]`` / ``elapsed[B]`` is partitioned across devices; takes and
  merges for a bucket run only on the shard that owns its rows (host
  routing, no cross-device traffic on the hot path).
* axis ``"r"`` — **replication**: full state replicas that each ingest a
  partition of the incoming take/merge stream and converge with one
  max all-reduce per step. This is Patrol's UDP broadcast re-expressed as an
  ICI collective — the 256-byte-datagram protocol (repo.go:123-158) becomes
  an elementwise int64 max across the mesh, five orders of magnitude more
  bandwidth.

Correctness of max-convergence relies on two invariants:

1. All CRDT planes are monotone (PN lanes and the elapsed G-counter only
   grow), so elementwise max is a join and convergence is exact.
2. Each bucket row has one *home replica* (``row % R``) that applies its
   takes; other replicas receive the result via the max all-reduce. Two
   replicas incrementing the same lane concurrently would race like the
   reference's lossy scalar merge (SURVEY §2, known bug) — home routing
   makes the write single-writer per lane while reads/merges stay
   everywhere.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from patrol_tpu.models.limiter import LimiterConfig, LimiterState
from patrol_tpu.ops.merge import MergeBatch, merge_batch
from patrol_tpu.ops.take import TakeRequest, TakeResult, take_batch

REPLICA_AXIS = "r"
BUCKET_AXIS = "b"

# jax.shard_map graduated from jax.experimental in newer releases (which
# also renamed check_rep → check_vma); the pinned toolchain (0.4.x) still
# ships the experimental name and the old kwarg.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:  # pragma: no cover - exercised only on older jax
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect as _inspect

_SM_CHECK_KW = (
    "check_vma"
    if "check_vma" in _inspect.signature(_shard_map).parameters
    else "check_rep"
)


def make_mesh(replicas: int = 1, devices=None) -> Mesh:
    """A (replicas × shards) mesh over the available devices. ``replicas``
    must divide the device count; the remainder becomes the bucket axis."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % replicas:
        raise ValueError(f"{replicas} replicas do not divide {n} devices")
    grid = np.array(devices).reshape(replicas, n // replicas)
    return Mesh(grid, (REPLICA_AXIS, BUCKET_AXIS))


# State: bucket axis sharded over "b", replicated over "r".
STATE_SPEC = LimiterState(pn=P(BUCKET_AXIS, None, None), elapsed=P(BUCKET_AXIS))
# Request/delta batches: leading dim laid out as (replica-major, shard-minor)
# blocks, partitioned over both axes.
BATCH_SPEC = P((REPLICA_AXIS, BUCKET_AXIS))


def state_sharding(mesh: Mesh) -> LimiterState:
    return LimiterState(
        pn=NamedSharding(mesh, STATE_SPEC.pn),
        elapsed=NamedSharding(mesh, STATE_SPEC.elapsed),
    )


def place_state(state: LimiterState, mesh: Mesh) -> LimiterState:
    """Shard an existing state onto the mesh (bucket rows split across
    ``"b"``, replicated across ``"r"``)."""
    sh = state_sharding(mesh)
    return LimiterState(
        pn=jax.device_put(state.pn, sh.pn),
        elapsed=jax.device_put(state.elapsed, sh.elapsed),
    )


def init_sharded_state(config: LimiterConfig, mesh: Mesh) -> LimiterState:
    sh = state_sharding(mesh)
    return LimiterState(
        pn=jnp.zeros((config.buckets, config.nodes, 2), jnp.int64, device=sh.pn),
        elapsed=jnp.zeros((config.buckets,), jnp.int64, device=sh.elapsed),
    )


def _allreduce_max(x: jax.Array) -> jax.Array:
    """FLAT max all-reduce over the replica axis, expressed as all_gather +
    local max: real TPU compile paths (v5e AOT, BENCH r2) reject non-Sum
    s64 all-reduces ("Supported lowering only of Sum all reduce") while
    all-gather lowers everywhere. One replica step's extra HBM is
    replicas × block, transient, and XLA fuses the reduction. Kept as the
    fallback converge (non-power-of-two replica counts) and as the
    reference the tree path is checked bit-exact against."""
    g = jax.lax.all_gather(x, REPLICA_AXIS)
    return jnp.max(g, axis=0)


def _tree_allreduce_max(x: jax.Array, replicas: int) -> jax.Array:
    """Hierarchical tree max-reduce over the replica axis (Tascade's
    coalescing-reduction shape, arXiv:2311.15810): log2(R) rounds of
    recursive doubling — each round every replica exchanges its partial
    join with the partner at XOR distance 2^k (``ppermute``, point-to-
    point over ICI) and max-joins it locally, so interior "nodes" RE-FOLD
    before forwarding. Total traffic is R·log2(R) blocks versus the flat
    all_gather's R·(R−1), and each round moves one block per link instead
    of gathering the whole replica set — at R=8 that is 24 vs 56 blocks,
    and the gap widens superlinearly with R. Exactness is free: max is
    associative/commutative/idempotent, so ANY reduction tree computes
    the same join bit-for-bit (the delta-CRDT composition result,
    arXiv:1410.2803) — machine-checked by the registered
    :func:`tree_reduce_states` prove root and pinned on-device by
    tests/test_topology.py's tree-vs-flat equality.

    Requires a power-of-two ``replicas`` (the butterfly pairing);
    :func:`converge` falls back to the flat path otherwise. ``ppermute``
    is pure data movement, so the v5e "Sum all reduce only" s64 lowering
    restriction (BENCH r2) does not apply."""
    step = 1
    while step < replicas:
        perm = [(i, i ^ step) for i in range(replicas)]
        peer = jax.lax.ppermute(x, REPLICA_AXIS, perm=perm)
        x = jnp.maximum(x, peer)
        step <<= 1
    return x


def tree_join_states(a: LimiterState, b: LimiterState) -> LimiterState:
    """The tree's interior-node join: elementwise max of both CRDT
    planes — one node of the converge tree, host-callable for tests."""
    return LimiterState(
        pn=jnp.maximum(a.pn, b.pn),
        elapsed=jnp.maximum(a.elapsed, b.elapsed),
    )


def tree_reduce_states(pn: jax.Array, elapsed: jax.Array) -> LimiterState:
    """Pure (collective-free) twin of the converge tree, THE registered
    prove root (``parallel.topology.tree_reduce_states``): reduce R
    stacked replica states (``pn[R, B, N, 2]``, ``elapsed[R, B]``) with
    exactly the butterfly schedule :func:`_tree_allreduce_max` runs over
    ICI — level k joins index i with index i XOR 2^k. patrol-prove
    traces it (PTP001/PTP005) and model-checks flat-vs-tree equivalence,
    permutation independence, duplicate-leaf idempotence, and
    monotonicity over enumerated lattice domains (PTP002-004) — the
    distributed path inherits the argument because the schedule is the
    same join tree. Non-power-of-two R folds flat (the fallback
    :func:`converge` takes on hardware)."""
    r = pn.shape[0]
    if r > 1 and r & (r - 1) == 0:
        step = 1
        while step < r:
            idx = jnp.arange(r, dtype=jnp.int32) ^ step
            pn = jnp.maximum(pn, pn[idx])
            elapsed = jnp.maximum(elapsed, elapsed[idx])
            step <<= 1
        return LimiterState(pn=pn[0], elapsed=elapsed[0])
    return LimiterState(pn=jnp.max(pn, axis=0), elapsed=jnp.max(elapsed, axis=0))


def converge(state: LimiterState, replicas: Optional[int] = None) -> LimiterState:
    """Cross-replica CvRDT join over ICI — the collective that replaces the
    reference's per-take UDP fan-out (repo.go:129-158). With a static
    power-of-two ``replicas`` (the builders thread it from the mesh), the
    join runs as a hierarchical tree reduce; otherwise the flat
    all_gather+max fallback (bit-identical by the join laws)."""
    if replicas is not None and replicas > 1 and replicas & (replicas - 1) == 0:
        return LimiterState(
            pn=_tree_allreduce_max(state.pn, replicas),
            elapsed=_tree_allreduce_max(state.elapsed, replicas),
        )
    return LimiterState(
        pn=_allreduce_max(state.pn),
        elapsed=_allreduce_max(state.elapsed),
    )


def cluster_step(
    state: LimiterState,
    deltas: MergeBatch,
    reqs: TakeRequest,
    node_slot: int,
    replicas: Optional[int] = None,
) -> Tuple[LimiterState, TakeResult]:
    """One SPMD update step, per (replica, shard) block: merge this block's
    replication deltas, apply this block's takes, converge replicas.

    Rows in ``reqs``/``deltas`` are SHARD-LOCAL indices; the host router
    (:func:`route_requests`) guarantees each take sits in its home
    (replica, shard) block and every other block carries padding."""
    state = merge_batch(state, deltas)
    state, res = take_batch(state, reqs, node_slot)
    state = converge(state, replicas)
    return state, res


def build_cluster_step(mesh: Mesh, node_slot: int):
    """jit(shard_map(cluster_step)) over the mesh, with donated state."""
    fn = _shard_map(
        partial(
            cluster_step,
            node_slot=node_slot,
            replicas=mesh.shape[REPLICA_AXIS],
        ),
        mesh=mesh,
        in_specs=(
            STATE_SPEC,
            MergeBatch(*(BATCH_SPEC,) * 5),
            TakeRequest(*(BATCH_SPEC,) * 8),
        ),
        out_specs=(STATE_SPEC, TakeResult(*(BATCH_SPEC,) * 7)),
        # converge() replicates its outputs by VALUE (tree reduce or
        # all_gather over the replica axis — every replica computes the
        # identical join), but the static varying-axes checker can only
        # prove replication for collectives like pmax, which the v5e AOT
        # compile path rejects for s64 ("Supported lowering only of Sum
        # all reduce", BENCH r2). Replication is instead asserted by
        # tests/test_topology.py's cross-replica equality checks.
        **{_SM_CHECK_KW: False},
    )
    return jax.jit(fn, donate_argnums=0)


# Packed-matrix layouts for the staged mesh step (the device-commit
# pipeline's transfer shape, PR 5): ONE int64[8, B·k] take matrix and ONE
# int64[5, B·k] merge matrix per dispatch instead of 13 little arrays —
# per-array transfer setup dominates host→device latency on this stack,
# and a single matrix can ride a reusable StagingPool buffer.
TAKE_MAT_ROWS = 8  # rows, now_ns, freq, per_ns, count_nt, nreq, cap, created
MERGE_MAT_ROWS = 5  # rows, slots, added_nt, taken_nt, elapsed_ns


def batch_sharding(mesh: Mesh):
    """NamedSharding for the packed matrices: field dim replicated, the
    block dim split (replica-major, shard-minor) over both mesh axes."""
    return NamedSharding(mesh, P(None, (REPLICA_AXIS, BUCKET_AXIS)))


def build_cluster_step_packed(mesh: Mesh, node_slot: int):
    """jit(shard_map(...)) over the mesh taking the PACKED matrices:
    ``(state, take_mat[8, B·k_t], merge_mat[5, B·k_m])`` →
    ``(state, out[7, B·k_t])`` with donated state — merge + take +
    tree-converge fused in one dispatch, unpacking on-device so the host
    ships exactly two staged transfers per tick (no host round-trips
    between the three phases). ``out`` rows mirror the single-device
    ``_jit_take_packed`` result stack: have_nt, admitted, own_added_nt,
    own_taken_nt, elapsed_ns, sum_added_nt, sum_taken_nt."""
    replicas = mesh.shape[REPLICA_AXIS]
    mat_spec = P(None, (REPLICA_AXIS, BUCKET_AXIS))

    def step(state, take_mat, merge_mat):
        mb = MergeBatch(
            rows=merge_mat[0].astype(jnp.int32),
            slots=merge_mat[1].astype(jnp.int32),
            added_nt=merge_mat[2],
            taken_nt=merge_mat[3],
            elapsed_ns=merge_mat[4],
        )
        req = TakeRequest(
            rows=take_mat[0].astype(jnp.int32),
            now_ns=take_mat[1],
            freq=take_mat[2],
            per_ns=take_mat[3],
            count_nt=take_mat[4],
            nreq=take_mat[5],
            cap_base_nt=take_mat[6],
            created_ns=take_mat[7],
        )
        state, res = cluster_step(
            state, mb, req, node_slot=node_slot, replicas=replicas
        )
        out = jnp.stack(
            [
                res.have_nt,
                res.admitted,
                res.own_added_nt,
                res.own_taken_nt,
                res.elapsed_ns,
                res.sum_added_nt,
                res.sum_taken_nt,
            ]
        )
        return state, out

    fn = _shard_map(
        step,
        mesh=mesh,
        in_specs=(STATE_SPEC, mat_spec, mat_spec),
        out_specs=(STATE_SPEC, mat_spec),
        # See build_cluster_step: converge() replicates by value.
        **{_SM_CHECK_KW: False},
    )
    return jax.jit(fn, donate_argnums=0)


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """Host-side routing geometry for a mesh deployment."""

    replicas: int
    shards: int
    rows_per_shard: int

    @property
    def blocks(self) -> int:
        return self.replicas * self.shards

    def locate(self, global_row: int) -> Tuple[int, int, int]:
        """→ (home_replica, shard, local_row) for a bucket row."""
        shard, local_row = divmod(global_row, self.rows_per_shard)
        return global_row % self.replicas, shard, local_row

    def block_index(self, replica: int, shard: int) -> int:
        return replica * self.shards + shard


def plan_for(mesh: Mesh, config: LimiterConfig) -> MeshPlan:
    shards = mesh.shape[BUCKET_AXIS]
    if config.buckets % shards:
        raise ValueError(f"{shards} shards do not divide {config.buckets} buckets")
    return MeshPlan(
        replicas=mesh.shape[REPLICA_AXIS],
        shards=shards,
        rows_per_shard=config.buckets // shards,
    )


def route_requests(
    plan: MeshPlan,
    takes,  # sequence of (global_row, now_ns, freq, per_ns, count_nt, nreq, cap_base_nt, created_ns)
    deltas,  # sequence of (global_row, slot, added_nt, taken_nt, elapsed_ns)
    k_take: int,
    k_merge: int,
    deltas_to_home: bool = False,
) -> Tuple[TakeRequest, MergeBatch]:
    """Pack host requests into the (replica-major, shard-minor) block layout
    consumed by :func:`build_cluster_step`. Each take lands in its home
    block; deltas spread round-robin over replicas (merges are idempotent,
    any replica may ingest them) unless ``deltas_to_home`` — then a delta
    lands on its row's home replica, making it visible to same-step takes
    (useful for deterministic tests and lowest staleness). Overflowing a
    block raises — the caller batches accordingly."""
    take_mat, merge_mat, _placed = route_packed(
        plan, takes, deltas, k_take, k_merge, deltas_to_home=deltas_to_home
    )
    return (
        TakeRequest(
            rows=jnp.asarray(take_mat[0], jnp.int32),
            now_ns=jnp.asarray(take_mat[1]),
            freq=jnp.asarray(take_mat[2]),
            per_ns=jnp.asarray(take_mat[3]),
            count_nt=jnp.asarray(take_mat[4]),
            nreq=jnp.asarray(take_mat[5]),
            cap_base_nt=jnp.asarray(take_mat[6]),
            created_ns=jnp.asarray(take_mat[7]),
        ),
        MergeBatch(
            rows=jnp.asarray(merge_mat[0], jnp.int32),
            slots=jnp.asarray(merge_mat[1], jnp.int32),
            added_nt=jnp.asarray(merge_mat[2]),
            taken_nt=jnp.asarray(merge_mat[3]),
            elapsed_ns=jnp.asarray(merge_mat[4]),
        ),
    )


def delta_block_assignment(
    plan: MeshPlan, rows_a: np.ndarray, deltas_to_home: bool = False
) -> np.ndarray:
    """The delta→block routing rule, exposed so callers that sub-tick a
    drain (MeshEngine) can compute per-block fills BEFORE packing:
    shard from the row, replica round-robin by arrival index (merges are
    idempotent joins — any replica may ingest, converge spreads them) or
    the row's home replica with ``deltas_to_home``."""
    K = len(rows_a)
    shard = rows_a // plan.rows_per_shard
    replica = (
        rows_a % plan.replicas
        if deltas_to_home
        else np.arange(K, dtype=np.int64) % plan.replicas
    )
    return replica * plan.shards + shard


def route_packed(
    plan: MeshPlan,
    takes,
    deltas,
    k_take: int,
    k_merge: int,
    take_out: Optional[np.ndarray] = None,
    merge_out: Optional[np.ndarray] = None,
    deltas_to_home: bool = False,
    delta_blocks: Optional[np.ndarray] = None,
):
    """Packing core shared by :func:`route_requests` and the MeshEngine's
    staged tick: fills (or allocates) the int64 ``[TAKE_MAT_ROWS, B·k_take]``
    / ``[MERGE_MAT_ROWS, B·k_merge]`` matrices in block layout and returns
    ``(take_mat, merge_mat, placed)`` where ``placed`` is the
    ``(block, slot-in-block)`` of each take in input order (the completion
    path's result indices). Caller-leased ``*_out`` buffers (StagingPool)
    are zeroed first — padding entries MUST read as no-ops."""
    B = plan.blocks
    if take_out is None:
        take_mat = np.zeros((TAKE_MAT_ROWS, B * k_take), dtype=np.int64)
    else:
        take_mat = take_out
        take_mat[:] = 0
    if merge_out is None:
        merge_mat = np.zeros((MERGE_MAT_ROWS, B * k_merge), dtype=np.int64)
    else:
        merge_mat = merge_out
        merge_mat[:] = 0

    placed: list = []
    fill_t = [0] * B
    for row, now_ns, freq, per_ns, count_nt, nreq, cap_base_nt, created_ns in takes:
        replica, shard, local = plan.locate(row)
        blk = plan.block_index(replica, shard)
        i = fill_t[blk]
        if i >= k_take:
            raise ValueError(f"take block {blk} overflow (k_take={k_take})")
        at = blk * k_take + i
        take_mat[0, at] = local
        take_mat[1, at] = now_ns
        take_mat[2, at] = freq
        take_mat[3, at] = per_ns
        take_mat[4, at] = count_nt
        take_mat[5, at] = nreq
        take_mat[6, at] = cap_base_nt
        take_mat[7, at] = created_ns
        fill_t[blk] += 1
        placed.append((blk, i))

    # Deltas pack vectorized — thousands per tick ride this path (takes
    # are pre-coalesced to a few keys, so their loop stays Python).
    # ``deltas`` is a 5-tuple of int64 arrays (rows, slots, added_nt,
    # taken_nt, elapsed_ns) or a sequence of 5-tuples (tests).
    if deltas is not None and len(deltas):
        if isinstance(deltas, tuple) and isinstance(deltas[0], np.ndarray):
            rows_a, slots_a, added_a, taken_a, elapsed_a = (
                np.asarray(x, dtype=np.int64) for x in deltas
            )
        else:
            arr = np.asarray(list(deltas), dtype=np.int64).T
            rows_a, slots_a, added_a, taken_a, elapsed_a = arr
        K = len(rows_a)
        local = rows_a % plan.rows_per_shard
        blk = (
            delta_blocks
            if delta_blocks is not None
            else delta_block_assignment(plan, rows_a, deltas_to_home)
        )
        counts = np.bincount(blk, minlength=B)
        if counts.max(initial=0) > k_merge:
            raise ValueError(
                f"merge block {int(counts.argmax())} overflow (k_merge={k_merge})"
            )
        order = np.argsort(blk, kind="stable")
        sblk = blk[order]
        run_start = np.concatenate(([0], np.cumsum(counts)))[sblk]
        at = sblk * k_merge + (np.arange(K, dtype=np.int64) - run_start)
        merge_mat[0, at] = local[order]
        merge_mat[1, at] = slots_a[order]
        merge_mat[2, at] = np.maximum(added_a[order], 0)
        merge_mat[3, at] = np.maximum(taken_a[order], 0)
        merge_mat[4, at] = np.maximum(elapsed_a[order], 0)

    return take_mat, merge_mat, placed
