"""Mesh scale-out: the TPU-native replacement for scaling by adding UDP
peers (SURVEY §2 "Parallelism & distribution strategies").

The reference has exactly two scaling axes (SURVEY §5): bucket cardinality
and node count. They map onto a 2-D ``jax.sharding.Mesh``:

* axis ``"b"`` — **bucket sharding**: the bucket dimension of
  ``pn[B, N, 2]`` / ``elapsed[B]`` is partitioned across devices; takes and
  merges for a bucket run only on the shard that owns its rows (host
  routing, no cross-device traffic on the hot path).
* axis ``"r"`` — **replication**: full state replicas that each ingest a
  partition of the incoming take/merge stream and converge with one
  max all-reduce per step. This is Patrol's UDP broadcast re-expressed as an
  ICI collective — the 256-byte-datagram protocol (repo.go:123-158) becomes
  an elementwise int64 max across the mesh, five orders of magnitude more
  bandwidth.

Correctness of max-convergence relies on two invariants:

1. All CRDT planes are monotone (PN lanes and the elapsed G-counter only
   grow), so elementwise max is a join and convergence is exact.
2. Each bucket row has one *home replica* (``row % R``) that applies its
   takes; other replicas receive the result via the max all-reduce. Two
   replicas incrementing the same lane concurrently would race like the
   reference's lossy scalar merge (SURVEY §2, known bug) — home routing
   makes the write single-writer per lane while reads/merges stay
   everywhere.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from patrol_tpu.models.limiter import LimiterConfig, LimiterState
from patrol_tpu.ops.merge import MergeBatch, merge_batch
from patrol_tpu.ops.take import TakeRequest, TakeResult, take_batch

REPLICA_AXIS = "r"
BUCKET_AXIS = "b"

# jax.shard_map graduated from jax.experimental in newer releases (which
# also renamed check_rep → check_vma); the pinned toolchain (0.4.x) still
# ships the experimental name and the old kwarg.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:  # pragma: no cover - exercised only on older jax
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect as _inspect

_SM_CHECK_KW = (
    "check_vma"
    if "check_vma" in _inspect.signature(_shard_map).parameters
    else "check_rep"
)


def make_mesh(replicas: int = 1, devices=None) -> Mesh:
    """A (replicas × shards) mesh over the available devices. ``replicas``
    must divide the device count; the remainder becomes the bucket axis."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % replicas:
        raise ValueError(f"{replicas} replicas do not divide {n} devices")
    grid = np.array(devices).reshape(replicas, n // replicas)
    return Mesh(grid, (REPLICA_AXIS, BUCKET_AXIS))


# State: bucket axis sharded over "b", replicated over "r".
STATE_SPEC = LimiterState(pn=P(BUCKET_AXIS, None, None), elapsed=P(BUCKET_AXIS))
# Request/delta batches: leading dim laid out as (replica-major, shard-minor)
# blocks, partitioned over both axes.
BATCH_SPEC = P((REPLICA_AXIS, BUCKET_AXIS))


def state_sharding(mesh: Mesh) -> LimiterState:
    return LimiterState(
        pn=NamedSharding(mesh, STATE_SPEC.pn),
        elapsed=NamedSharding(mesh, STATE_SPEC.elapsed),
    )


def place_state(state: LimiterState, mesh: Mesh) -> LimiterState:
    """Shard an existing state onto the mesh (bucket rows split across
    ``"b"``, replicated across ``"r"``)."""
    sh = state_sharding(mesh)
    return LimiterState(
        pn=jax.device_put(state.pn, sh.pn),
        elapsed=jax.device_put(state.elapsed, sh.elapsed),
    )


def init_sharded_state(config: LimiterConfig, mesh: Mesh) -> LimiterState:
    sh = state_sharding(mesh)
    return LimiterState(
        pn=jnp.zeros((config.buckets, config.nodes, 2), jnp.int64, device=sh.pn),
        elapsed=jnp.zeros((config.buckets,), jnp.int64, device=sh.elapsed),
    )


def _allreduce_max(x: jax.Array) -> jax.Array:
    """Max all-reduce over the replica axis, expressed as all_gather +
    local max: real TPU compile paths (v5e AOT, BENCH r2) reject non-Sum
    s64 all-reduces ("Supported lowering only of Sum all reduce") while
    all-gather lowers everywhere. One replica step's extra HBM is
    replicas × block, transient, and XLA fuses the reduction."""
    g = jax.lax.all_gather(x, REPLICA_AXIS)
    return jnp.max(g, axis=0)


def converge(state: LimiterState) -> LimiterState:
    """Cross-replica CvRDT join over ICI — the collective that replaces the
    reference's per-take UDP fan-out (repo.go:129-158)."""
    return LimiterState(
        pn=_allreduce_max(state.pn),
        elapsed=_allreduce_max(state.elapsed),
    )


def cluster_step(
    state: LimiterState,
    deltas: MergeBatch,
    reqs: TakeRequest,
    node_slot: int,
) -> Tuple[LimiterState, TakeResult]:
    """One SPMD update step, per (replica, shard) block: merge this block's
    replication deltas, apply this block's takes, converge replicas.

    Rows in ``reqs``/``deltas`` are SHARD-LOCAL indices; the host router
    (:func:`route_requests`) guarantees each take sits in its home
    (replica, shard) block and every other block carries padding."""
    state = merge_batch(state, deltas)
    state, res = take_batch(state, reqs, node_slot)
    state = converge(state)
    return state, res


def build_cluster_step(mesh: Mesh, node_slot: int):
    """jit(shard_map(cluster_step)) over the mesh, with donated state."""
    fn = _shard_map(
        partial(cluster_step, node_slot=node_slot),
        mesh=mesh,
        in_specs=(
            STATE_SPEC,
            MergeBatch(*(BATCH_SPEC,) * 5),
            TakeRequest(*(BATCH_SPEC,) * 8),
        ),
        out_specs=(STATE_SPEC, TakeResult(*(BATCH_SPEC,) * 7)),
        # converge() replicates its outputs by VALUE (all_gather over the
        # replica axis, then a local reduce — every replica computes the
        # identical join), but the static varying-axes checker can only
        # prove replication for collectives like pmax, which the v5e AOT
        # compile path rejects for s64 ("Supported lowering only of Sum
        # all reduce", BENCH r2). Replication is instead asserted by
        # tests/test_topology.py's cross-replica equality checks.
        **{_SM_CHECK_KW: False},
    )
    return jax.jit(fn, donate_argnums=0)


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """Host-side routing geometry for a mesh deployment."""

    replicas: int
    shards: int
    rows_per_shard: int

    @property
    def blocks(self) -> int:
        return self.replicas * self.shards

    def locate(self, global_row: int) -> Tuple[int, int, int]:
        """→ (home_replica, shard, local_row) for a bucket row."""
        shard, local_row = divmod(global_row, self.rows_per_shard)
        return global_row % self.replicas, shard, local_row

    def block_index(self, replica: int, shard: int) -> int:
        return replica * self.shards + shard


def plan_for(mesh: Mesh, config: LimiterConfig) -> MeshPlan:
    shards = mesh.shape[BUCKET_AXIS]
    if config.buckets % shards:
        raise ValueError(f"{shards} shards do not divide {config.buckets} buckets")
    return MeshPlan(
        replicas=mesh.shape[REPLICA_AXIS],
        shards=shards,
        rows_per_shard=config.buckets // shards,
    )


def route_requests(
    plan: MeshPlan,
    takes,  # sequence of (global_row, now_ns, freq, per_ns, count_nt, nreq, cap_base_nt, created_ns)
    deltas,  # sequence of (global_row, slot, added_nt, taken_nt, elapsed_ns)
    k_take: int,
    k_merge: int,
    deltas_to_home: bool = False,
) -> Tuple[TakeRequest, MergeBatch]:
    """Pack host requests into the (replica-major, shard-minor) block layout
    consumed by :func:`build_cluster_step`. Each take lands in its home
    block; deltas spread round-robin over replicas (merges are idempotent,
    any replica may ingest them) unless ``deltas_to_home`` — then a delta
    lands on its row's home replica, making it visible to same-step takes
    (useful for deterministic tests and lowest staleness). Overflowing a
    block raises — the caller batches accordingly."""
    B = plan.blocks
    t = {name: np.zeros((B * k_take,), dtype=np.int64) for name in TakeRequest._fields}
    t["rows"] = np.zeros((B * k_take,), dtype=np.int32)
    d = {name: np.zeros((B * k_merge,), dtype=np.int64) for name in MergeBatch._fields}
    d["rows"] = np.zeros((B * k_merge,), dtype=np.int32)
    d["slots"] = np.zeros((B * k_merge,), dtype=np.int32)

    fill_t = [0] * B
    for row, now_ns, freq, per_ns, count_nt, nreq, cap_base_nt, created_ns in takes:
        replica, shard, local = plan.locate(row)
        blk = plan.block_index(replica, shard)
        i = fill_t[blk]
        if i >= k_take:
            raise ValueError(f"take block {blk} overflow (k_take={k_take})")
        at = blk * k_take + i
        t["rows"][at] = local
        t["now_ns"][at] = now_ns
        t["freq"][at] = freq
        t["per_ns"][at] = per_ns
        t["count_nt"][at] = count_nt
        t["nreq"][at] = nreq
        t["cap_base_nt"][at] = cap_base_nt
        t["created_ns"][at] = created_ns
        fill_t[blk] += 1

    # Deltas pack vectorized — thousands per tick ride this path (takes
    # are pre-coalesced to a few keys, so their loop stays Python).
    # ``deltas`` is a 5-tuple of int64 arrays (rows, slots, added_nt,
    # taken_nt, elapsed_ns) or a sequence of 5-tuples (tests).
    if deltas is not None and len(deltas):
        if isinstance(deltas, tuple) and isinstance(deltas[0], np.ndarray):
            rows_a, slots_a, added_a, taken_a, elapsed_a = (
                np.asarray(x, dtype=np.int64) for x in deltas
            )
        else:
            arr = np.asarray(list(deltas), dtype=np.int64).T
            rows_a, slots_a, added_a, taken_a, elapsed_a = arr
        K = len(rows_a)
        shard = rows_a // plan.rows_per_shard
        local = rows_a % plan.rows_per_shard
        replica = (
            rows_a % plan.replicas
            if deltas_to_home
            else np.arange(K, dtype=np.int64) % plan.replicas
        )
        blk = replica * plan.shards + shard
        counts = np.bincount(blk, minlength=B)
        if counts.max(initial=0) > k_merge:
            raise ValueError(
                f"merge block {int(counts.argmax())} overflow (k_merge={k_merge})"
            )
        order = np.argsort(blk, kind="stable")
        sblk = blk[order]
        run_start = np.concatenate(([0], np.cumsum(counts)))[sblk]
        at = sblk * k_merge + (np.arange(K, dtype=np.int64) - run_start)
        d["rows"][at] = local[order]
        d["slots"][at] = slots_a[order]
        d["added_nt"][at] = np.maximum(added_a[order], 0)
        d["taken_nt"][at] = np.maximum(taken_a[order], 0)
        d["elapsed_ns"][at] = np.maximum(elapsed_a[order], 0)

    return (
        TakeRequest(**{k: jnp.asarray(v) for k, v in t.items()}),
        MergeBatch(**{k: jnp.asarray(v) for k, v in d.items()}),
    )
