"""Multi-device scale-out: mesh topologies, sharded state, ICI convergence."""
