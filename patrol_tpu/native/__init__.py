"""ctypes bindings for the native host network path (patrol_host.cpp).

Builds ``libpatrolhost.so`` with g++ on first use (cached beside the
source; no pybind11 in this environment — plain C ABI + ctypes + numpy).
Falls back gracefully: :func:`load` returns None when no compiler is
available, and callers use the pure-Python asyncio path instead.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

log = logging.getLogger("patrol.native")


class NativeEffect(NamedTuple):
    """Declared cross-boundary effects of one C ABI symbol.

    The Python lint passes cannot see into the .so: a ctypes call that
    parks the caller on a condition variable (``pt_http_poll``) or takes
    the host-lane store mutex (``pt_hls_lock`` — the engine's
    ``_host_mu`` IS that mutex) is invisible to PTL002's sync-in-jit walk
    and PTL003's lock-order analysis. This table is the boundary
    contract those passes consume; PTA005 (``analysis/abi.py``) asserts
    every registered ``lib.pt_*`` symbol has an entry, so the table
    cannot silently rot as the ABI grows.

    * ``blocks`` — may block the calling thread for scheduling-relevant
      time: poll/condvar waits, thread create/join, or acquiring a mutex
      the epoll thread contends (PTL002 treats such a call inside a
      jit-reachable function exactly like ``.item()``).
    * ``takes_host_mu`` — acquires the host-lane store mutex internally
      (or IS the acquisition). PTL003 treats the call site as an
      acquisition of ``_host_mu``, so the reverse-order nesting under
      ``_state_mu`` — and a re-acquire while already holding it, which
      deadlocks against itself — is now a lexical finding.
    * ``requires_host_mu`` — caller must already hold ``_host_mu`` (the
      ``*_locked`` family and ``pt_hls_unlock``). The PTA004 schedule
      explorer uses this to judge lock-protocol legality.
    * ``callback_safe`` — pure compute on caller-owned buffers: no
      locks, no syscalls that block, safe from a jax host callback.
    * ``owns_buffers`` / ``borrows_until`` — buffer-ownership contract
      (patrol-race, ``analysis/race.py``). Most symbols *borrow* their
      numpy arguments for the duration of the call only
      (``borrows_until="call"``); a symbol that RETAINS the pointers
      past its return (``owns_buffers=True``) names the releasing
      symbol in ``borrows_until`` — until that release runs, the Python
      side must never rebind or resize those arrays (the .so would keep
      reading freed storage: use-after-recycle). The static ownership
      pass checks both directions against its declared retained-buffer
      registry, PTA005-style.
    """

    blocks: bool
    takes_host_mu: bool
    requires_host_mu: bool
    callback_safe: bool
    owns_buffers: bool = False
    borrows_until: str = "call"


_E = NativeEffect

# One entry per ctypes symbol registered in load() below. PTA005
# (scripts/abi_repo.py, check.sh --stage abi) diffs this table against
# the argtypes registrations, both ways.
NATIVE_EFFECTS: Dict[str, NativeEffect] = {
    # -- UDP replication plane (patrol_host.cpp) --
    "pt_udp_open": _E(False, False, False, False),
    "pt_udp_port": _E(False, False, False, False),
    "pt_udp_close": _E(False, False, False, False),
    "pt_recv_batch": _E(True, False, False, False),   # poll(timeout_ms)
    "pt_send_fanout": _E(True, False, False, False),  # POLLOUT stall wait
    "pt_decode_batch": _E(False, False, False, True),
    "pt_encode_batch": _E(False, False, False, True),
    # -- zero-copy rx ring (device-resident ingest) --
    # pt_rx_ring_create allocates C++-OWNED page-aligned planes that
    # Python views zero-copy via pt_rx_ring_plane until destroy: the
    # inverse of the usual borrow, declared owns_buffers so the
    # ownership pass tracks the retained-memory lifetime — rebinding or
    # freeing while the engine's H2D still reads a leased plane is the
    # use-after-recycle class (destroy therefore DEFERS while any plane
    # is leased; the last commit frees).
    "pt_rx_ring_create": _E(
        False, False, False, False,
        owns_buffers=True, borrows_until="pt_rx_ring_destroy",
    ),
    "pt_rx_ring_plane": _E(False, False, False, False),
    "pt_rx_ring_lease": _E(False, False, False, False),   # leaf mutex
    "pt_rx_ring_commit": _E(False, False, False, False),  # leaf mutex
    "pt_rx_ring_stats": _E(False, False, False, False),
    "pt_rx_ring_destroy": _E(False, False, False, False),
    # -- directory / rx fast path --
    # pt_dir_create RETAINS name_bytes/name_len: the C++ directory
    # verifies hash hits against those rows through the stored pointers
    # until pt_dir_destroy. Rebinding either array use-after-frees.
    "pt_dir_create": _E(
        False, False, False, False,
        owns_buffers=True, borrows_until="pt_dir_destroy",
    ),
    "pt_dir_insert": _E(False, False, False, False),
    "pt_dir_insert_batch": _E(False, False, False, False),
    "pt_dir_delete": _E(False, False, False, False),
    "pt_dir_resolve": _E(False, False, False, False),   # needs py dir lock
    "pt_dir_resolve_rt": _E(False, False, False, False),
    "pt_rx_classify": _E(False, False, False, False),   # needs py dir lock
    "pt_dir_destroy": _E(False, False, False, False),
    "pt_fold_hybrid": _E(True, False, False, False),    # thread fan-out/join
    # -- HTTP front (patrol_http.cpp) --
    "pt_http_start": _E(True, False, False, False),     # spawns epoll thread
    "pt_http_port": _E(False, False, False, False),
    "pt_http_poll": _E(True, False, False, False),      # condvar wait
    "pt_http_complete_takes": _E(False, False, False, False),
    "pt_http_complete_other": _E(False, False, False, False),
    "pt_http_stats": _E(False, False, False, False),
    "pt_http_set_h2_backend": _E(False, False, False, False),
    "pt_http_stop": _E(True, False, False, False),      # joins epoll thread
    "pt_http_attach_host": _E(True, False, False, False),  # server mu
    "pt_http_blast": _E(True, False, False, False),
    "pt_http_blast_h2": _E(True, False, False, False),
    # -- host-lane store (the engine's _host_mu lives here) --
    # pt_hls_create RETAINS cap_base/created/last_used (the directory's
    # side arrays): the in-front take path reads refill baselines through
    # the stored pointers until pt_hls_destroy.
    "pt_hls_create": _E(
        False, False, False, False,
        owns_buffers=True, borrows_until="pt_hls_destroy",
    ),
    "pt_hls_destroy": _E(False, False, False, False),
    "pt_hls_lock": _E(True, True, False, False),
    "pt_hls_unlock": _E(False, False, True, False),
    "pt_hls_host_locked": _E(False, False, True, False),
    "pt_hls_unhost_locked": _E(False, False, True, False),
    "pt_hls_drain_locked": _E(False, False, True, False),
    "pt_hls_stats": _E(True, True, False, False),       # lock_guard st->mu
    "pt_hls_events": _E(False, False, False, True),     # relaxed atomic read
    "pt_hls_take_probe": _E(True, True, False, False),  # lock_guard st->mu
    # -- pure parsing helpers --
    "pt_parse_rate": _E(False, False, False, True),
    "pt_parse_duration": _E(False, False, False, True),
}

PACKET = 256
# recvmmsg rx-ring row width (and the unicast tx bound): sized to the
# delta-interval datagram bound (ops/wire.py DELTA_PACKET_SIZE) so the
# compiled path accepts full 8-KiB intervals — the 256-B rows it had
# before ROADMAP 3b silently truncated them and forced the backend to
# advertise a v1-sized rx bound.
RX_RING_ROW = 8192
PATH_MAX = 2048  # kPathMax in patrol_http.cpp
_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "patrol_host.cpp")
_SRC_HTTP = os.path.join(_HERE, "patrol_http.cpp")
# PATROL_NATIVE_LIB points the ctypes seam at a prebuilt library instead of
# the cached in-tree build — the check.sh asan-py stage uses it to load an
# ASan-instrumented build under LD_PRELOAD=libasan without dirtying the
# packaged .so.
_LIB_OVERRIDE = os.environ.get("PATROL_NATIVE_LIB")
_LIB = _LIB_OVERRIDE or os.path.join(_HERE, "libpatrolhost.so")

_mu = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False

_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
_u16p = np.ctypeslib.ndpointer(np.uint16, flags="C_CONTIGUOUS")
_f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
_u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")


def _build() -> bool:
    if _LIB_OVERRIDE:
        # Caller supplied the binary (possibly sanitizer-instrumented);
        # never overwrite it with a plain build.
        return os.path.exists(_LIB)
    srcs = [_SRC, _SRC_HTTP]
    if os.path.exists(_LIB) and all(
        os.path.getmtime(_LIB) >= os.path.getmtime(s) for s in srcs
    ):
        return True
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
             "-o", _LIB, *srcs],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (subprocess.SubprocessError, FileNotFoundError) as exc:
        log.warning("native build failed, using pure-python path: %s", exc)
        return False


def load() -> Optional[ctypes.CDLL]:
    """Build-if-needed and load the native library; None on failure."""
    global _lib, _load_failed
    with _mu:
        if _lib is not None:
            return _lib
        if _load_failed:
            return None
        if not _build():
            _load_failed = True
            return None
        lib = ctypes.CDLL(_LIB)
        lib.pt_udp_open.argtypes = [ctypes.c_char_p, ctypes.c_uint16]
        lib.pt_udp_open.restype = ctypes.c_int
        lib.pt_udp_port.argtypes = [ctypes.c_int]
        lib.pt_udp_port.restype = ctypes.c_int
        lib.pt_udp_close.argtypes = [ctypes.c_int]
        lib.pt_recv_batch.argtypes = [
            ctypes.c_int, _u8p, ctypes.c_int, ctypes.c_int, _i32p, _u32p,
            _u16p, ctypes.c_int,
        ]
        lib.pt_recv_batch.restype = ctypes.c_int
        lib.pt_send_fanout.argtypes = [
            ctypes.c_int, _u8p, _i32p, ctypes.c_int, ctypes.c_int, _u32p,
            _u16p, ctypes.c_int,
        ]
        lib.pt_send_fanout.restype = ctypes.c_int
        lib.pt_rx_ring_create.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.pt_rx_ring_create.restype = ctypes.c_int
        lib.pt_rx_ring_plane.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.pt_rx_ring_plane.restype = ctypes.c_int64
        lib.pt_rx_ring_lease.argtypes = [ctypes.c_int]
        lib.pt_rx_ring_lease.restype = ctypes.c_int
        lib.pt_rx_ring_commit.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.pt_rx_ring_commit.restype = ctypes.c_int
        lib.pt_rx_ring_stats.argtypes = [ctypes.c_int, _u64p]
        lib.pt_rx_ring_stats.restype = ctypes.c_int
        lib.pt_rx_ring_destroy.argtypes = [ctypes.c_int]
        lib.pt_rx_ring_destroy.restype = ctypes.c_int
        lib.pt_decode_batch.argtypes = [
            _u8p, _i32p, ctypes.c_int, ctypes.c_int, _f64p, _f64p, _u64p,
            _u8p, _i32p, _i32p, _i64p, _i64p, _i64p, _u64p, _i32p,
        ]
        lib.pt_decode_batch.restype = ctypes.c_int
        lib.pt_encode_batch.argtypes = [
            _f64p, _f64p, _u64p, _u8p, _i32p, _i32p, _i64p, _i64p, _i64p,
            ctypes.c_int, _u8p, _i32p,
        ]
        lib.pt_encode_batch.restype = ctypes.c_int
        # -- HTTP front (patrol_http.cpp) --
        lib.pt_http_start.argtypes = [ctypes.c_char_p, ctypes.c_uint16]
        lib.pt_http_start.restype = ctypes.c_int
        lib.pt_http_port.argtypes = [ctypes.c_int]
        lib.pt_http_port.restype = ctypes.c_int
        lib.pt_http_poll.argtypes = [
            ctypes.c_int, ctypes.c_int,
            _u64p, _i32p, _u8p, _i32p, _i64p, _i64p, _i64p, ctypes.c_int,
            _u64p, _i32p, _u8p, _i32p, _u8p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.pt_http_poll.restype = ctypes.c_int
        lib.pt_http_complete_takes.argtypes = [
            ctypes.c_int, _u64p, _i32p, _i32p, _i64p, ctypes.c_int,
        ]
        lib.pt_http_complete_takes.restype = ctypes.c_int
        lib.pt_http_complete_other.argtypes = [
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int32, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.pt_http_complete_other.restype = ctypes.c_int
        lib.pt_http_stats.argtypes = [ctypes.c_int, _u64p]
        lib.pt_http_stats.restype = ctypes.c_int
        lib.pt_http_set_h2_backend.argtypes = [ctypes.c_int, ctypes.c_uint16]
        lib.pt_http_set_h2_backend.restype = ctypes.c_int
        lib.pt_http_stop.argtypes = [ctypes.c_int]
        lib.pt_http_stop.restype = ctypes.c_int
        lib.pt_dir_create.argtypes = [ctypes.c_int64, _u8p, _i32p]
        lib.pt_dir_create.restype = ctypes.c_int
        lib.pt_dir_insert.argtypes = [ctypes.c_int, ctypes.c_uint64, ctypes.c_int32]
        lib.pt_dir_insert.restype = ctypes.c_int
        lib.pt_dir_insert_batch.argtypes = [ctypes.c_int, _u64p, _i32p, ctypes.c_int]
        lib.pt_dir_insert_batch.restype = ctypes.c_int
        lib.pt_dir_delete.argtypes = [ctypes.c_int, ctypes.c_uint64, ctypes.c_int32]
        lib.pt_dir_delete.restype = ctypes.c_int
        lib.pt_dir_resolve.argtypes = [
            ctypes.c_int, ctypes.c_int, _u64p, _u8p, _i32p, _i64p, _i32p,
            _i64p, ctypes.c_int64,
        ]
        lib.pt_dir_resolve.restype = ctypes.c_int64
        lib.pt_rx_classify.argtypes = [
            ctypes.c_int, ctypes.c_int, _u64p, _u8p, _i32p,
            _f64p, _f64p, _u64p, _i64p, ctypes.c_int64,
            _i64p, _i64p, _i64p, _u8p,
            _i64p, _i32p, _i64p, ctypes.c_int64,
            _i64p, _i64p, _i64p, _i64p, _u8p,
        ]
        lib.pt_rx_classify.restype = ctypes.c_int64
        lib.pt_dir_destroy.argtypes = [ctypes.c_int]
        lib.pt_dir_destroy.restype = ctypes.c_int
        # -- host-lane store (in-front /take serving) --
        lib.pt_hls_create.argtypes = [
            ctypes.c_int, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, _i64p, _i64p, _i64p,
        ]
        lib.pt_hls_create.restype = ctypes.c_int
        lib.pt_hls_destroy.argtypes = [ctypes.c_int]
        lib.pt_hls_destroy.restype = ctypes.c_int
        lib.pt_hls_lock.argtypes = [ctypes.c_int]
        lib.pt_hls_lock.restype = ctypes.c_int
        lib.pt_hls_unlock.argtypes = [ctypes.c_int]
        lib.pt_hls_unlock.restype = ctypes.c_int
        lib.pt_hls_host_locked.argtypes = [ctypes.c_int, ctypes.c_int32]
        lib.pt_hls_host_locked.restype = ctypes.c_int64
        lib.pt_hls_unhost_locked.argtypes = [ctypes.c_int, ctypes.c_int32]
        lib.pt_hls_unhost_locked.restype = ctypes.c_int
        lib.pt_hls_drain_locked.argtypes = [
            ctypes.c_int, _i32p, _i64p, ctypes.c_int, _i32p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.pt_hls_drain_locked.restype = ctypes.c_int
        lib.pt_hls_stats.argtypes = [ctypes.c_int, _u64p]
        lib.pt_hls_stats.restype = ctypes.c_int
        lib.pt_hls_events.argtypes = [ctypes.c_int]
        lib.pt_hls_events.restype = ctypes.c_int64
        lib.pt_http_attach_host.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.pt_http_attach_host.restype = ctypes.c_int
        lib.pt_hls_take_probe.argtypes = [
            ctypes.c_int, ctypes.c_int, _u8p, ctypes.c_int, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.pt_hls_take_probe.restype = ctypes.c_int
        lib.pt_dir_resolve_rt.argtypes = [
            ctypes.c_int, _u8p, ctypes.c_int32, _i64p, ctypes.c_int64,
        ]
        lib.pt_dir_resolve_rt.restype = ctypes.c_int32
        lib.pt_fold_hybrid.argtypes = [
            _i64p, _i64p, _i64p, _i64p, _i64p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            _i64p, _i64p, _i64p, ctypes.c_int64,
            _i64p, _i64p, _i64p, _i64p, _i64p, _i64p, _i64p,
        ]
        lib.pt_fold_hybrid.restype = ctypes.c_int
        lib.pt_http_blast.argtypes = [
            ctypes.c_char_p, ctypes.c_uint16, ctypes.c_char_p,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, _u64p,
        ]
        lib.pt_http_blast.restype = ctypes.c_int
        lib.pt_http_blast_h2.argtypes = [
            ctypes.c_char_p, ctypes.c_uint16, ctypes.c_char_p,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, _u64p,
        ]
        lib.pt_http_blast_h2.restype = ctypes.c_int
        lib.pt_parse_rate.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.pt_parse_rate.restype = ctypes.c_int
        lib.pt_parse_duration.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ]
        lib.pt_parse_duration.restype = ctypes.c_int
        _lib = lib
        return lib


class NativeSocket:
    """One UDP socket, native recv/send batch ops, numpy in/out. The rx
    ring rows are ``RX_RING_ROW`` (8 KiB) wide so full delta-interval
    datagrams arrive untruncated on the compiled path."""

    def __init__(self, ip: str, port: int, max_batch: int = 512,
                 row: int = RX_RING_ROW):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self.lib = lib
        self.fd = lib.pt_udp_open(ip.encode(), port)
        if self.fd < 0:
            raise OSError(-self.fd, os.strerror(-self.fd))
        self.max_batch = max_batch
        self.row = max(row, PACKET)
        self._rx_buf = np.zeros((max_batch, self.row), np.uint8)
        self._rx_sizes = np.zeros(max_batch, np.int32)
        self._rx_ips = np.zeros(max_batch, np.uint32)
        self._rx_ports = np.zeros(max_batch, np.uint16)

    @property
    def port(self) -> int:
        return self.lib.pt_udp_port(self.fd)

    def recv_batch(self, timeout_ms: int = 100):
        """→ (packets[n,row] uint8 view, sizes[n], src_ips[n], src_ports[n])."""
        return self.recv_batch_into(self._rx_buf, timeout_ms)

    def recv_batch_into(self, buf: np.ndarray, timeout_ms: int = 100):
        """recvmmsg directly into ``buf`` (uint8[max_batch, row] — an rx
        ring plane for the zero-copy ingest path, or the socket's own
        staging buffer). Same return shape as :meth:`recv_batch`."""
        n = self.lib.pt_recv_batch(
            self.fd, buf, min(self.max_batch, len(buf)), buf.shape[1],
            self._rx_sizes, self._rx_ips, self._rx_ports, timeout_ms,
        )
        if n < 0:
            raise OSError(-n, os.strerror(-n))
        return (
            buf[:n],
            self._rx_sizes[:n],
            self._rx_ips[:n],
            self._rx_ports[:n],
        )

    def send_fanout(self, payloads: np.ndarray, sizes: np.ndarray,
                    peer_ips: np.ndarray, peer_ports: np.ndarray) -> int:
        if len(payloads) == 0 or len(peer_ips) == 0:
            return 0
        payloads = np.ascontiguousarray(payloads, np.uint8)
        n = self.lib.pt_send_fanout(
            self.fd,
            payloads,
            np.ascontiguousarray(sizes, np.int32),
            len(payloads),
            payloads.shape[1],  # row stride: (n,256) matrices or wide rows
            np.ascontiguousarray(peer_ips, np.uint32),
            np.ascontiguousarray(peer_ports, np.uint16),
            len(peer_ips),
        )
        if n < 0:
            raise OSError(-n, os.strerror(-n))
        return n

    def close(self) -> None:
        self.lib.pt_udp_close(self.fd)


class RxRing:
    """Zero-copy rx ring (device-resident ingest): C++-owned page-aligned
    byte planes the recvmmsg loop fills directly and Python views without
    copying (``plane()``), shipped to the device with ``jax.device_put``
    and recycled via lease/commit. The rx thread LEASES before receiving;
    the engine's completion pipeline COMMITS once the shipped operand is
    ready — until then the plane bytes are pinned by contract (the C side
    refuses to free them: destroy defers while leased). Python-side
    bookkeeping (``_leased``) mirrors the native free-list under ``_mu``
    for observability and teardown sanity, registered in
    analysis/race.py::GUARDS like every other shared-state discipline."""

    def __init__(self, n_planes: int = 4, max_batch: int = 512,
                 row: int = RX_RING_ROW):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self.lib = lib
        self.n_planes = n_planes
        self.max_batch = max_batch
        self.row = row
        h = lib.pt_rx_ring_create(n_planes, max_batch, row)
        if h < 0:
            raise OSError(-h, os.strerror(-h))
        self.h = h
        self._mu = threading.Lock()
        self._leased: set = set()
        self._closed = False
        self._views = []
        size = max_batch * row
        for i in range(n_planes):
            ptr = lib.pt_rx_ring_plane(h, i)
            buf = (ctypes.c_uint8 * size).from_address(ptr)
            self._views.append(
                np.ctypeslib.as_array(buf).reshape(max_batch, row)
            )

    def lease(self) -> Optional[int]:
        """→ plane index, or None when every plane is in flight (the
        caller falls back to its copying path for this batch)."""
        idx = self.lib.pt_rx_ring_lease(self.h)
        if idx < 0:
            return None
        with self._mu:
            self._leased.add(idx)
        return idx

    def plane(self, idx: int) -> np.ndarray:
        """Zero-copy numpy view of one plane (valid until close)."""
        return self._views[idx]

    def commit(self, idx: int) -> None:
        """Return a leased plane (engine completion callback — may run
        on any thread)."""
        with self._mu:
            self._leased.discard(idx)
        self.lib.pt_rx_ring_commit(self.h, idx)

    def stats(self) -> dict:
        out = np.zeros(4, np.uint64)
        if self.lib.pt_rx_ring_stats(self.h, out) < 0:
            return {}
        return {
            "rx_ring_leases": int(out[0]),
            "rx_ring_commits": int(out[1]),
            "rx_ring_lease_reuse": int(out[2]),
            "rx_ring_exhausted": int(out[3]),
        }

    def close(self) -> None:
        """Destroy (deferred natively while planes are leased — an
        in-flight H2D can never read freed memory). The numpy views are
        invalid once the last lease commits; callers stop reading them
        before close."""
        with self._mu:
            if self._closed:
                return
            self._closed = True
        self.lib.pt_rx_ring_destroy(self.h)


class DecodeBuffers:
    """Reusable output buffers for :func:`decode_batch_raw` — the rx loop
    allocates once instead of zeroing ~2 MB of numpy arrays per batch
    (pt_decode_batch re-zeroes each valid name row itself)."""

    def __init__(self, max_batch: int):
        n = max_batch
        self.added = np.zeros(n, np.float64)
        self.taken = np.zeros(n, np.float64)
        self.elapsed = np.zeros(n, np.uint64)
        self.names = np.zeros((n, PACKET), np.uint8)
        self.name_lens = np.zeros(n, np.int32)
        self.slots = np.zeros(n, np.int32)
        self.caps = np.zeros(n, np.int64)
        self.lane_a = np.zeros(n, np.int64)
        self.lane_t = np.zeros(n, np.int64)
        self.hashes = np.zeros(n, np.uint64)
        # 0 = plain, 1 = capability advert (base trailer, MULTI bit),
        # 2 = valid multi-lane trailer (re-decode through ops.wire).
        self.multi = np.zeros(n, np.int32)


def decode_batch_raw(
    packets: np.ndarray, sizes: np.ndarray, buf: Optional[DecodeBuffers] = None
) -> Tuple[DecodeBuffers, int]:
    """Zero-materialization wire decode: fills ``buf`` (allocating one when
    None) and returns ``(buf, n)``. Names stay raw zero-padded byte rows
    (``buf.names[i, :name_lens[i]]``) with their FNV-1a hash in
    ``buf.hashes`` — the directory's vectorized lookup consumes these
    directly; Python strings are only materialized for directory misses and
    incast requests. ``name_lens[i] < 0`` marks a malformed packet."""
    lib = load()
    n = len(packets)
    if buf is None or len(buf.added) < n:
        buf = DecodeBuffers(n)
    packets = np.ascontiguousarray(packets, np.uint8)
    in_stride = packets.shape[1] if packets.ndim == 2 and n else PACKET
    lib.pt_decode_batch(
        packets,
        np.ascontiguousarray(sizes, np.int32),
        n, in_stride, buf.added, buf.taken, buf.elapsed, buf.names,
        buf.name_lens, buf.slots, buf.caps, buf.lane_a, buf.lane_t,
        buf.hashes, buf.multi,
    )
    return buf, n


def decode_batch(packets: np.ndarray, sizes: np.ndarray):
    """Vectorized wire decode → (added[f64], taken[f64], elapsed[i64],
    names[list[str]], origin_slots[i32], valid[bool], caps[i64], lane_added
    [i64], lane_taken[i64]) — caps/lane values in nanotokens, -1 = absent.
    Materializes every name as a Python string; the hot rx loop uses
    :func:`decode_batch_raw` instead."""
    buf, n = decode_batch_raw(packets, sizes)
    valid = buf.name_lens[:n] >= 0
    out_names: List[str] = [
        bytes(buf.names[i, : buf.name_lens[i]]).decode("utf-8", "surrogateescape")
        if valid[i]
        else ""
        for i in range(n)
    ]
    return (
        buf.added[:n].copy(), buf.taken[:n].copy(),
        buf.elapsed[:n].astype(np.int64), out_names, buf.slots[:n].copy(),
        valid, buf.caps[:n].copy(), buf.lane_a[:n].copy(), buf.lane_t[:n].copy(),
    )


def encode_batch(
    added: Sequence[float],
    taken: Sequence[float],
    elapsed_ns: Sequence[int],
    names: Sequence[str],
    origin_slots: Sequence[int],
    caps: Optional[Sequence[int]] = None,
    lane_added: Optional[Sequence[int]] = None,
    lane_taken: Optional[Sequence[int]] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized wire encode → (packets[n,256], sizes[n]); size -1 marks a
    state whose name was too large (caller decides; see replication).
    ``caps``/``lane_added``/``lane_taken`` are per-state nanotoken values
    (-1 = omit from the trailer); omitted entirely ⇒ base-form trailers."""
    lib = load()
    n = len(names)
    name_buf = np.zeros((n, PACKET), np.uint8)
    name_lens = np.zeros(n, np.int32)
    for i, name in enumerate(names):
        raw = name.encode("utf-8", "surrogateescape")
        name_lens[i] = len(raw)
        if len(raw) <= PACKET:
            name_buf[i, : len(raw)] = np.frombuffer(raw, np.uint8)
    out = np.zeros((n, PACKET), np.uint8)
    out_sizes = np.zeros(n, np.int32)

    def _i64(vals):
        if vals is None:
            return np.full(n, -1, np.int64)
        return np.ascontiguousarray(np.asarray(vals, np.int64))

    lib.pt_encode_batch(
        np.ascontiguousarray(np.asarray(added, np.float64)),
        np.ascontiguousarray(np.asarray(taken, np.float64)),
        np.ascontiguousarray(np.asarray(elapsed_ns, np.int64).view(np.uint64)),
        name_buf, name_lens,
        np.ascontiguousarray(np.asarray(origin_slots, np.int32)),
        _i64(caps), _i64(lane_added), _i64(lane_taken),
        n, out, out_sizes,
    )
    return out, out_sizes
