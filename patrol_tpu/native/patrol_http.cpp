// patrol_http: native HTTP/1.1 front for the /take hot path.
//
// The reference serves /take from compiled Go net/http (command.go:41-44,
// api.go:51-86) — a performance class a Python asyncio server cannot
// reach. This is the C++ equivalent, shaped for the microbatching TPU
// runtime the same way patrol_host.cpp shapes the UDP plane:
//
//   * one epoll thread owns accept/read/parse/write — zero Python on the
//     socket path;
//   * /take requests are FULLY parsed in C++ (percent-decoding, Go
//     ParseRate/ParseDuration semantics ported below) into fixed records
//     on a ring; the Python pump drains the ring in BATCHES (one ctypes
//     call), submits them to the device engine, and completes them in
//     batches — so Python cost amortizes over the batch exactly like the
//     engine's take microbatching;
//   * responses are formatted and written back in C++;
//   * non-/take routes (debug, metrics) ride a slow-path ring to Python.
//
// Concurrency: the epoll thread and the Python pump share one mutex per
// server (batch-level contention only) plus an eventfd to kick the epoll
// loop when completions arrive. Connection slots carry a generation tag
// so a completion for a closed/reused connection is dropped, never
// misdelivered.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this environment).

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cctype>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <deque>
#include <dlfcn.h>
#include <mutex>
#include <thread>
#include <string_view>
#include <unordered_map>
#include <vector>

// From patrol_host.cpp (same shared library): epoll-thread-safe single
// name resolve against the C++ directory probe table.
extern "C" int32_t pt_dir_resolve_rt(int h, const uint8_t* name_padded,
                                     int32_t len, int64_t* last_used,
                                     int64_t now);

namespace {

constexpr int kNameMax = 256;     // matches wire NAME_BYTES_MAX
constexpr int kNameLimit = 231;   // MAX_NAME_LENGTH_V1 (bucket.go:43-44)
constexpr int kPathMax = 2048;    // slow-path target cap
constexpr int kRbufMax = 16384;   // per-connection read buffer cap
constexpr int kRingCap = 8192;    // parsed-take ring capacity
// Sane request-body bound. The API carries take input in the URL; a
// Content-Length beyond this is hostile (or a config error) and gets a
// 400 + close instead of a body drain — and the digit parse saturates
// HERE rather than wrapping size_t, which under-skipped the body and
// re-parsed its bytes as pipelined requests (request-smuggling surface
// behind a connection-reusing proxy; ADVICE r5).
constexpr size_t kMaxContentLen = (size_t)1 << 30;
constexpr int64_t kInt64Max = 0x7FFFFFFFFFFFFFFFLL;

// ---- Go time.ParseDuration / ParseRate port (ops/rate.py parity) ----------

// Unit table incl. both µ (U+00B5, "\xc2\xb5") and μ (U+03BC, "\xce\xbc").
struct Unit { const char* s; int len; int64_t scale; };
const Unit kUnits[] = {
    {"ns", 2, 1LL},
    {"us", 2, 1000LL},
    {"\xc2\xb5s", 3, 1000LL},
    {"\xce\xbcs", 3, 1000LL},
    {"ms", 2, 1000000LL},
    {"s", 1, 1000000000LL},
    {"m", 1, 60LL * 1000000000LL},
    {"h", 1, 3600LL * 1000000000LL},
};
// Bare units accepted as "1<unit>" shorthand (bucket.go:116-119): the
// reference's list has µs but NOT μs.
const char* kBareUnits[] = {"ns", "us", "\xc2\xb5s", "ms", "s", "m", "h"};

// Longest-match unit lookup at s[i:]; returns scale or 0.
int64_t match_unit(const std::string& s, size_t i, size_t* adv) {
  const Unit* best = nullptr;
  for (const auto& u : kUnits) {
    if (s.compare(i, u.len, u.s) == 0 && (!best || u.len > best->len)) best = &u;
  }
  if (!best) return 0;
  *adv = best->len;
  return best->scale;
}

// parse_duration (ops/rate.py:41-92). Returns false on malformed input.
bool parse_duration(const std::string& orig, int64_t* out) {
  std::string s = orig;
  bool neg = false;
  if (!s.empty() && (s[0] == '+' || s[0] == '-')) {
    neg = s[0] == '-';
    s.erase(0, 1);
  }
  if (s == "0") {
    *out = 0;
    return true;
  }
  if (s.empty()) return false;
  __int128 total = 0;
  size_t i = 0;
  while (i < s.size()) {
    size_t d0 = i;
    while (i < s.size() && s[i] >= '0' && s[i] <= '9') i++;
    size_t int_len = i - d0;
    __int128 int_part = 0;
    for (size_t k = d0; k < i; k++) {
      int_part = int_part * 10 + (s[k] - '0');
      if (int_part > (__int128)kInt64Max * 10) return false;  // overflow guard
    }
    size_t f0 = i, frac_len = 0;
    __int128 frac_part = 0;
    if (i < s.size() && s[i] == '.') {
      i++;
      f0 = i;
      while (i < s.size() && s[i] >= '0' && s[i] <= '9') i++;
      frac_len = i - f0;
      // Cap fraction digits the way Python's exact-int math behaves for
      // practical inputs: accumulate into int128 (19+ digits saturate).
      for (size_t k = f0; k < i && k < f0 + 18; k++)
        frac_part = frac_part * 10 + (s[k] - '0');
      for (size_t k = f0 + 18; k < i; k++) frac_len--;  // drop beyond 18
    }
    if (int_len == 0 && frac_len == 0 && (i == f0)) return false;
    if (int_len == 0 && f0 == d0) return false;  // no digits at all
    size_t adv = 0;
    int64_t scale = match_unit(s, i, &adv);
    if (scale == 0) return false;
    i += adv;
    total += int_part * scale;
    if (frac_len > 0) {
      __int128 p10 = 1;
      for (size_t k = 0; k < frac_len; k++) p10 *= 10;
      total += frac_part * scale / p10;
    }
    if (total > (__int128)kInt64Max) return false;
  }
  int64_t v = (int64_t)total;
  *out = neg ? -v : v;
  return true;
}

// strconv.Atoi semantics (ops/rate.py:_atoi): optional sign, ASCII digits.
bool parse_atoi(const std::string& s, int64_t* out) {
  size_t i = 0;
  bool neg = false;
  if (i < s.size() && (s[i] == '+' || s[i] == '-')) {
    neg = s[i] == '-';
    i++;
  }
  if (i >= s.size()) return false;
  __int128 v = 0;
  for (; i < s.size(); i++) {
    if (s[i] < '0' || s[i] > '9') return false;
    v = v * 10 + (s[i] - '0');
    if (v > (__int128)kInt64Max + 1) return false;
  }
  if (!neg && v > (__int128)kInt64Max) return false;
  if (neg && v > (__int128)kInt64Max + 1) return false;
  *out = neg ? (int64_t)(-v) : (int64_t)v;
  return true;
}

// parse_rate "freq:duration" (ops/rate.py:177-192). false ⇒ malformed
// (callers use the zero Rate: unconditional 429, api.go:61).
bool parse_rate(const std::string& v, int64_t* freq, int64_t* per_ns) {
  std::string fpart = v, dpart = "1s";
  size_t colon = v.find(':');
  if (colon != std::string::npos) {
    fpart = v.substr(0, colon);
    dpart = v.substr(colon + 1);
  }
  if (!parse_atoi(fpart, freq)) return false;
  for (const char* u : kBareUnits) {
    if (dpart == u) {
      dpart = std::string("1") + u;
      break;
    }
  }
  return parse_duration(dpart, per_ns);
}

// ---- HTTP plumbing --------------------------------------------------------

int hexval(char c) {
  if (c >= '0' && c <= '9') return c - '0';
  if (c >= 'a' && c <= 'f') return c - 'a' + 10;
  if (c >= 'A' && c <= 'F') return c - 'A' + 10;
  return -1;
}

// Percent-decode. plus_to_space mirrors urllib parse_qs for query values;
// path segments keep '+' literal (urllib.unquote semantics).
std::string pct_decode(std::string_view s, bool plus_to_space) {
  std::string out;
  out.reserve(s.size());
  for (size_t i = 0; i < s.size(); i++) {
    if (s[i] == '%' && i + 2 < s.size()) {
      int hi = hexval(s[i + 1]), lo = hexval(s[i + 2]);
      if (hi >= 0 && lo >= 0) {
        out.push_back((char)((hi << 4) | lo));
        i += 2;
        continue;
      }
    }
    if (plus_to_space && s[i] == '+') {
      out.push_back(' ');
      continue;
    }
    out.push_back(s[i]);
  }
  return out;
}

// ---- Host-lane store (the C++ twin of runtime/engine.py HostLanes) --------
//
// The reference serves the whole /take decision natively in-process
// (api.go:51-86 → bucket.go:186-225). This store lets the epoll thread do
// the same for host-resident buckets: per-row PN lane blocks in plain
// int64 memory, shared with Python — the engine maps each block as numpy
// views (runtime/hoststore.py), so every Python-side operation (rx
// absorb, snapshot, checkpoint, promotion join) runs the EXISTING
// HostLanes code on the same bytes. One native mutex replaces the
// engine's _host_mu: Python takes it via pt_hls_lock/unlock (ctypes
// releases the GIL), the epoll thread takes it inline per take.
//
// Block layout (int64 words): added[nodes] | taken[nodes] | elapsed_ns |
// win_start_ns | win_takes | win_rx | resident | dirty.
constexpr int64_t kNano = 1000000000LL;

struct HostStore {
  std::mutex mu;
  int nodes = 0;
  int words = 0;          // per-block int64 words = 2*nodes + 6
  int64_t node_slot = 0;
  int64_t promote_takes = 0;  // <=0: native take pressure never promotes
  int64_t window_ns = 0;
  int64_t clock_offset_ns = 0;  // realtime → injected-clock domain
  const int64_t* cap_base = nullptr;  // Python directory arrays (stable
  const int64_t* created = nullptr;   // fixed-size allocations)
  int64_t* last_used = nullptr;       // LRU stamps (eviction input)
  // row → block. Blocks are immortal until store destroy: a popped
  // (promoted/evicted) row's Python views stay valid, and a re-host of
  // the same row reuses its block (bounded by rows ever hosted).
  std::unordered_map<int32_t, int64_t*> blocks;
  std::vector<int32_t> dirty_rows;    // coalesced-broadcast queue
  std::vector<int32_t> promote_rows;  // take-pressure threshold crossings
  // Event sequence for the pump's poll predicate (read without mu).
  std::atomic<uint64_t> events{0};
  uint64_t native_takes = 0;  // takes served by the epoll thread
};

HostStore* g_hls[16] = {nullptr};
std::mutex g_hls_mu;

inline int64_t sat_mul_nano(int64_t v) {
  if (v > kInt64Max / kNano) return kInt64Max;
  if (v < -(kInt64Max / kNano)) return -kInt64Max;
  return v * kNano;
}

// One take against a resident block. MUST mirror HostLanes.take
// (runtime/engine.py) step-for-step — the same lazy capacity base,
// monotonic-time guard, float64 refill grant, capacity cap (possibly
// negative ⇒ monotone forfeit booked as taken), conditional commit, and
// remaining_for_request(have, k, count_nt, 0) fan-out — so a bucket's
// observable behavior is identical whichever side serves it and the
// promotion join stays exact. Caller holds st->mu.
void hls_take_locked(HostStore* st, int64_t* blk, int32_t row, int64_t freq,
                     int64_t per_ns, int64_t count, int64_t now,
                     int64_t* remaining, int* ok, bool* events_bumped) {
  const int n = st->nodes;
  int64_t* added = blk;
  int64_t* taken = blk + n;
  int64_t* sc = blk + 2 * n;  // scalars (layout above)
  if (now - sc[1] > st->window_ns) {
    sc[1] = now;
    sc[2] = 0;
    sc[3] = 0;
  }
  sc[2]++;
  if (st->promote_takes > 0 && sc[2] == st->promote_takes + 1) {
    st->promote_rows.push_back(row);
    // Promotions wake the pump promptly (poll predicate); dirty marks
    // below deliberately don't — broadcasts coalesce on the pump's short
    // poll tick, so a take never pays a pump wakeup on its latency path.
    st->events.fetch_add(1, std::memory_order_relaxed);
    *events_bumped = true;
  }
  const int64_t cap = st->cap_base[row];
  const int64_t cap_now = sat_mul_nano(freq);
  int64_t sum_a = 0, sum_t = 0;
  for (int i = 0; i < n; i++) {
    sum_a += added[i];
    sum_t += taken[i];
  }
  const int64_t tokens = cap + sum_a - sum_t;
  int64_t last = st->created[row] + sc[0];
  if (now < last) last = now;
  const int64_t delta = now - last;  // >= 0 by the min above
  const int64_t interval = freq ? per_ns / freq : 0;
  int64_t grant = 0;
  if (freq != 0 && per_ns != 0 && interval != 0) {
    // float64(delta)/float64(interval) tokens then ·1e9, floored — the
    // exact expression (and operation order) of the kernel and of
    // HostLanes.take.
    double gf = ((double)delta / (double)interval) * 1e9;
    if (gf < 0.0) gf = 0.0;
    const double hi = 4611686018427387904.0;  // float(2**62), exact
    if (gf > hi) gf = hi;
    grant = (int64_t)std::floor(gf);
  }
  if (grant > cap_now - tokens) grant = cap_now - tokens;
  const int64_t have = tokens + grant;
  const int64_t count_nt = sat_mul_nano(count);
  const int k = (count_nt > 0 && have >= count_nt) ? 1 : 0;
  if (k) {
    const int64_t forfeit = grant < 0 ? -grant : 0;
    added[st->node_slot] += grant > 0 ? grant : 0;
    taken[st->node_slot] += count_nt + forfeit;
    sc[0] += delta;
  }
  int64_t rem = have - (k ? count_nt : 0);
  if (rem < 0) rem = 0;
  *remaining = rem / kNano;
  *ok = k;
  st->native_takes++;
  if (!sc[5]) {
    sc[5] = 1;
    st->dirty_rows.push_back(row);
  }
}

int64_t realtime_ns() {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (int64_t)ts.tv_sec * kNano + ts.tv_nsec;
}

struct TakeRec {
  uint64_t tag;
  int32_t stream;  // h2 stream id; 0 = HTTP/1.1
  int64_t freq, per_ns, count;
  uint8_t name[kNameMax];
  int name_len;
};

struct OtherRec {
  uint64_t tag;
  int32_t stream;  // h2 stream id; 0 = HTTP/1.1
  char method[8];
  char target[kPathMax];  // path?query
  int target_len;
};

// ---- native h2c (VERDICT r4 item 9) ---------------------------------------
//
// The reference serves h2c from its single front (command.go:41-44); r4's
// splice satisfied protocol parity at python-front speed. This serves the
// h2 request/response framing DIRECTLY for the API's bodyless shapes:
// SETTINGS/PING/WINDOW_UPDATE handling, HEADERS (+CONTINUATION, padding,
// priority) with HPACK decoding delegated to the system libnghttp2
// inflater (the same battle-tested one net/h2.py and curl use; response
// headers use only HPACK literals-without-indexing, so no deflater), and
// flow-controlled DATA out. net/h2.py is the porting spec. When
// libnghttp2 is unavailable the old splice (python h2 backend) remains
// the fallback; the h1→h2c Upgrade dance stays a python-front feature.

struct Nghttp2 {
  void* handle = nullptr;
  int (*inflate_new)(void**) = nullptr;
  void (*inflate_del)(void*) = nullptr;
  ssize_t (*inflate_hd2)(void*, void* nv, int* flags, const uint8_t* in,
                         size_t inlen, int in_final) = nullptr;
  int (*inflate_end_headers)(void*) = nullptr;
  bool ok() const { return inflate_hd2 != nullptr; }
};

struct NgNV {  // nghttp2_nv layout (name/value pointers + lengths + flags)
  uint8_t* name;
  uint8_t* value;
  size_t namelen;
  size_t valuelen;
  uint8_t flags;
};

Nghttp2* load_nghttp2() {
  static Nghttp2 g;
  static std::once_flag once;
  std::call_once(once, [] {
    void* h = dlopen("libnghttp2.so.14", RTLD_NOW | RTLD_GLOBAL);
    if (!h) h = dlopen("libnghttp2.so", RTLD_NOW | RTLD_GLOBAL);
    if (!h) return;
    g.handle = h;
    g.inflate_new = (int (*)(void**))dlsym(h, "nghttp2_hd_inflate_new");
    g.inflate_del = (void (*)(void*))dlsym(h, "nghttp2_hd_inflate_del");
    g.inflate_hd2 = (ssize_t (*)(void*, void*, int*, const uint8_t*, size_t,
                                 int))dlsym(h, "nghttp2_hd_inflate_hd2");
    g.inflate_end_headers =
        (int (*)(void*))dlsym(h, "nghttp2_hd_inflate_end_headers");
    if (!g.inflate_new || !g.inflate_del || !g.inflate_end_headers)
      g.inflate_hd2 = nullptr;
  });
  return g.ok() ? &g : nullptr;
}

constexpr int kH2HeadersFrame = 0x1;
constexpr int kH2Priority = 0x2;
constexpr int kH2RstStream = 0x3;
constexpr int kH2Settings = 0x4;
constexpr int kH2Ping = 0x6;
constexpr int kH2Goaway = 0x7;
constexpr int kH2WindowUpdate = 0x8;
constexpr int kH2Continuation = 0x9;
constexpr int kH2Data = 0x0;
constexpr uint8_t kH2FlagEndStream = 0x1;
constexpr uint8_t kH2FlagAck = 0x1;
constexpr uint8_t kH2FlagEndHeaders = 0x4;
constexpr uint8_t kH2FlagPadded = 0x8;
constexpr uint8_t kH2FlagPriority = 0x20;

// Peers must accept frames up to the h2 default; we never send larger
// (RFC 7540 §4.2: SETTINGS_MAX_FRAME_SIZE is never below this).
constexpr size_t kH2MaxSend = 16384;
// Hostile-input bounds: one header block, and the conn's total write
// backlog (an unread socket must backpressure, not buffer unboundedly).
constexpr size_t kH2MaxHeaderBlock = 64 * 1024;
constexpr size_t kH2MaxWbuf = 1 << 20;

// Client-reset stream ids remembered per conn (bounded; oldest pruned on
// overflow, each id pruned when a completion for it is dropped): ring-
// completed takes must not answer on a closed stream — HEADERS there is
// a STREAM_CLOSED/PROTOCOL_ERROR that can GOAWAY every other in-flight
// stream on the connection (ADVICE r5).
constexpr size_t kH2MaxResetTracked = 128;

struct H2State {
  void* inflater = nullptr;
  int64_t conn_send_window = 65535;
  int64_t peer_initial_window = 65535;
  // CONTINUATION accumulation for one in-flight header block.
  int32_t hdr_stream = 0;
  std::string hdr_block;
  // DATA parked behind a spent connection OR stream window:
  // (stream, body, stream_window_remaining).
  std::deque<std::tuple<int32_t, std::string, int64_t>> pending;
  uint64_t rx_data_unacked = 0;
  std::deque<int32_t> reset_streams;
};

void h2_append_frame(std::string& out, int type, uint8_t flags,
                     int32_t stream, const char* payload, size_t n) {
  out.push_back((char)((n >> 16) & 0xFF));
  out.push_back((char)((n >> 8) & 0xFF));
  out.push_back((char)(n & 0xFF));
  out.push_back((char)type);
  out.push_back((char)flags);
  out.push_back((char)((stream >> 24) & 0x7F));
  out.push_back((char)((stream >> 16) & 0xFF));
  out.push_back((char)((stream >> 8) & 0xFF));
  out.push_back((char)(stream & 0xFF));
  out.append(payload, n);
}

// HPACK literal-without-indexing, new name, no Huffman (RFC 7541 §6.2.2)
// — the always-valid canonical form net/h2.py uses for responses.
void hpack_literal(std::string& out, const char* name, size_t nlen,
                   const char* value, size_t vlen) {
  out.push_back('\0');
  auto prefix_int = [&](size_t n) {
    if (n < 127) {
      out.push_back((char)n);
      return;
    }
    out.push_back(127);
    n -= 127;
    while (n >= 128) {
      out.push_back((char)((n & 0x7F) | 0x80));
      n >>= 7;
    }
    out.push_back((char)n);
  };
  prefix_int(nlen);
  out.append(name, nlen);
  prefix_int(vlen);
  out.append(value, vlen);
}

struct Conn {
  int fd = -1;
  uint32_t gen = 0;
  std::string rbuf;
  std::string wbuf;
  size_t woff = 0;
  bool in_flight = false;   // one request at a time; pipelined bytes wait
  bool close_after = false;
  bool want_close = false;  // fully close once wbuf drains
  size_t body_skip = 0;     // request body bytes still to drain
  // h2c splice mode: this conn forwards raw bytes to/from its peer slot
  // (an h2 client conn and its backend conn form a pair) — the h2
  // protocol itself is served by the python front on the backend port.
  bool proxy = false;
  int peer_slot = -1;
  // Native h2c mode (preferred over the splice when libnghttp2 loads):
  // the connection speaks h2 frames directly; h2 != nullptr is the flag.
  H2State* h2 = nullptr;
  std::chrono::steady_clock::time_point req_start{};  // latency stamp
};

struct Server {
  int listen_fd = -1;
  int epoll_fd = -1;
  int event_fd = -1;
  uint16_t port = 0;
  std::thread thread;
  // Read by the epoll thread each loop, written by pt_http_stop from the
  // caller's thread: atomic, or the stop handshake is a data race.
  std::atomic<bool> running{false};

  std::mutex mu;
  std::condition_variable cv;  // signals the Python pump: work available
  std::vector<Conn> conns;     // slot-indexed
  std::vector<int> free_slots;
  uint16_t h2_backend_port = 0;  // 0 = h2c preface rejected with 400
  // In-front host serving (pt_http_attach_host): resolve via this C++
  // directory handle, serve host-resident rows from this store without
  // ever crossing into Python. -1/null = every take rides the ring.
  int dir_h = -1;
  HostStore* hls = nullptr;
  uint64_t hls_events_seen = 0;  // poll predicate cursor
  uint64_t hls_takes = 0;        // served in-front (this server)
  std::deque<TakeRec> take_q;
  std::deque<OtherRec> other_q;
  // Completions flow: pump → (mu) wbuf append → eventfd kick.

  // stats
  uint64_t accepted = 0, requests = 0, dropped = 0;
  // Server-side request latency (parse → response queued): a fixed-size
  // sample ring; percentiles computed on read. ~32 KB, overwrites oldest.
  static constexpr int kLatRing = 4096;
  uint64_t lat_ns[kLatRing] = {0};
  uint64_t lat_count = 0;
};

Server* g_servers[8] = {nullptr};
// Guards registry lookup+use in the completion entry points vs teardown:
// pt_http_stop nulls the slot under this mutex BEFORE deleting, and the
// completion calls hold it across their whole body, so a late completion
// can never touch a freed Server. (pt_http_poll is exempt: the Python
// front joins its pump thread before calling pt_http_stop.)
std::mutex g_reg_mu;

uint64_t make_tag(int slot, uint32_t gen) {
  return ((uint64_t)(uint32_t)slot << 32) | gen;
}

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

const char* status_text(int code) {
  switch (code) {
    case 200: return "OK";
    case 400: return "Bad Request";
    case 404: return "Not Found";
    case 405: return "Method Not Allowed";
    case 429: return "Too Many Requests";
    case 431: return "Request Header Fields Too Large";
    case 500: return "Internal Server Error";
    case 503: return "Service Unavailable";
    default: return "OK";
  }
}

// Append a full response to the conn's write buffer (mu held).
void queue_response(Server* s, Conn* c, int code, const char* ctype,
                    const char* body, size_t body_len) {
  if (c->req_start.time_since_epoch().count() != 0) {
    uint64_t ns = std::chrono::duration_cast<std::chrono::nanoseconds>(
                      std::chrono::steady_clock::now() - c->req_start)
                      .count();
    s->lat_ns[s->lat_count++ % Server::kLatRing] = ns;
    c->req_start = {};
  }
  char head[256];
  int hl = snprintf(head, sizeof(head),
                    "HTTP/1.1 %d %s\r\nContent-Type: %s\r\n"
                    "Content-Length: %zu\r\n%s\r\n",
                    code, status_text(code), ctype, body_len,
                    c->close_after ? "Connection: close\r\n" : "");
  // snprintf returns the would-be length on truncation; clamping keeps a
  // hostile/long Content-Type from overreading the stack buffer.
  if (hl > (int)sizeof(head) - 1) hl = (int)sizeof(head) - 1;
  c->wbuf.append(head, hl);
  c->wbuf.append(body, body_len);
  c->in_flight = false;
  if (c->close_after) c->want_close = true;
}

void epoll_mod(Server* s, int slot) {
  Conn& c = s->conns[slot];
  epoll_event ev{};
  ev.events = EPOLLIN | (c.wbuf.size() > c.woff ? EPOLLOUT : 0);
  ev.data.u64 = make_tag(slot, c.gen);
  epoll_ctl(s->epoll_fd, EPOLL_CTL_MOD, c.fd, &ev);
}

void close_conn(Server* s, int slot) {
  Conn& c = s->conns[slot];
  if (c.fd < 0) return;  // already closed (e.g. via a splice pair-close):
  // a second close must not re-push the slot into free_slots — two
  // accepts would then alias one Conn.
  epoll_ctl(s->epoll_fd, EPOLL_CTL_DEL, c.fd, nullptr);
  ::close(c.fd);
  c.fd = -1;
  c.gen++;  // invalidate outstanding tags
  if (c.h2) {
    if (c.h2->inflater) {
      Nghttp2* ng = load_nghttp2();
      if (ng) ng->inflate_del(c.h2->inflater);
    }
    delete c.h2;
    c.h2 = nullptr;
  }
  c.rbuf.clear();
  c.rbuf.shrink_to_fit();
  c.wbuf.clear();
  c.wbuf.shrink_to_fit();
  c.woff = 0;
  c.in_flight = c.close_after = c.want_close = false;
  c.body_skip = 0;
  int peer = c.peer_slot;
  c.proxy = false;
  c.peer_slot = -1;
  s->free_slots.push_back(slot);
  if (peer >= 0 && peer < (int)s->conns.size() &&
      s->conns[peer].peer_slot == slot) {
    // Unlink FIRST so the recursive close can't bounce back.
    s->conns[peer].peer_slot = -1;
    close_conn(s, peer);
  }
}

// Emit one stream's DATA, split to the always-valid frame size, debiting
// the connection window (the caller already cleared the stream window).
void h2_emit_data(Conn* c, int32_t stream, const char* body, size_t n) {
  c->h2->conn_send_window -= (int64_t)n;
  size_t off = 0;
  do {
    size_t chunk = std::min(n - off, kH2MaxSend);
    bool last = off + chunk == n;
    h2_append_frame(c->wbuf, kH2Data, last ? kH2FlagEndStream : 0, stream,
                    body + off, chunk);
    off += chunk;
  } while (off < n);
}

// Queue one h2 response (HEADERS + DATA/END_STREAM) onto the conn,
// respecting BOTH flow-control windows (HEADERS frames are exempt; DATA
// debits the connection window and must fit the stream's initial window
// — we send exactly one response per stream, so its window at send time
// is the peer's INITIAL_WINDOW_SIZE plus any stream WINDOW_UPDATEs,
// tracked only for parked responses). mu held.
void queue_h2_response(Server* s, Conn* c, int32_t stream, int code,
                       const char* ctype, const char* body,
                       size_t body_len) {
  // Client already reset the stream: drop the completion (and prune the
  // tracked id — one response per stream, so it cannot recur).
  auto& resets = c->h2->reset_streams;
  auto rit = std::find(resets.begin(), resets.end(), stream);
  if (rit != resets.end()) {
    resets.erase(rit);
    return;
  }
  std::string block;
  char st[8], cl[8];
  int stl = snprintf(st, sizeof(st), "%d", code);
  int cll = snprintf(cl, sizeof(cl), "%zu", body_len);
  hpack_literal(block, ":status", 7, st, stl);
  hpack_literal(block, "content-type", 12, ctype, strlen(ctype));
  hpack_literal(block, "content-length", 14, cl, cll);
  // Header blocks above the frame bound continue in CONTINUATION frames.
  size_t off = 0;
  bool first = true;
  do {
    size_t chunk = std::min(block.size() - off, kH2MaxSend);
    bool last = off + chunk == block.size();
    uint8_t fl = (last ? kH2FlagEndHeaders : 0) |
                 (first && body_len == 0 ? kH2FlagEndStream : 0);
    h2_append_frame(c->wbuf, first ? kH2HeadersFrame : kH2Continuation, fl,
                    stream, block.data() + off, chunk);
    first = false;
    off += chunk;
  } while (off < block.size());
  if (body_len == 0) return;
  H2State* h = c->h2;
  if ((int64_t)body_len <= h->conn_send_window &&
      (int64_t)body_len <= h->peer_initial_window) {
    h2_emit_data(c, stream, body, body_len);
  } else {
    // Spent window (connection, or a client that paused reads with a
    // tiny INITIAL_WINDOW_SIZE): park until WINDOW_UPDATEs arrive.
    h->pending.emplace_back(stream, std::string(body, body_len),
                            h->peer_initial_window);
  }
}

void h2_flush_pending(Server* s, Conn* c) {
  H2State* h = c->h2;
  while (!h->pending.empty()) {
    auto& [stream, body, swin] = h->pending.front();
    if ((int64_t)body.size() > h->conn_send_window ||
        (int64_t)body.size() > swin)
      break;
    h2_emit_data(c, stream, body.data(), body.size());
    h->pending.pop_front();
  }
}

bool try_parse_one(Server* s, int slot);  // fwd (h1 parser)
void serve_h2_request(Server* s, int slot, int32_t stream,
                      const std::string& method, const std::string& target);

// Decode one accumulated header block and dispatch the request. Returns
// false on a connection-fatal HPACK error.
bool h2_dispatch_headers(Server* s, int slot) {
  Conn& c = s->conns[slot];
  H2State* h = c.h2;
  Nghttp2* ng = load_nghttp2();
  std::string method, path;
  void* inf = h->inflater;
  const uint8_t* in = (const uint8_t*)h->hdr_block.data();
  size_t left = h->hdr_block.size();
  while (true) {
    NgNV nv{};
    int flags = 0;
    ssize_t used = ng->inflate_hd2(inf, &nv, &flags, in, left, 1);
    if (used < 0) return false;
    in += used;
    left -= (size_t)used;
    if (flags & 0x02 /*EMIT*/) {
      if (nv.namelen == 7 && memcmp(nv.name, ":method", 7) == 0)
        method.assign((const char*)nv.value, nv.valuelen);
      else if (nv.namelen == 5 && memcmp(nv.name, ":path", 5) == 0)
        path.assign((const char*)nv.value, nv.valuelen);
    }
    if (flags & 0x01 /*FINAL*/) break;
    if (used == 0 && !(flags & 0x02)) return false;  // stalled: malformed
  }
  ng->inflate_end_headers(inf);
  int32_t stream = h->hdr_stream;
  h->hdr_stream = 0;
  h->hdr_block.clear();
  serve_h2_request(s, slot, stream, method, path);
  return true;
}

// Process buffered h2 frames on an h2-mode conn (mu held). Returns false
// when the connection must close (protocol error / GOAWAY). Frames are
// walked by offset and the buffer compacted ONCE per call — a per-frame
// erase is quadratic over a pipelined client's event batch.
bool h2_process(Server* s, int slot) {
  Conn& c = s->conns[slot];
  H2State* h = c.h2;
  size_t pos = 0;
  bool ok = true;
  while (ok && c.rbuf.size() - pos >= 9) {
    const uint8_t* p = (const uint8_t*)c.rbuf.data() + pos;
    size_t len = ((size_t)p[0] << 16) | ((size_t)p[1] << 8) | p[2];
    int type = p[3];
    uint8_t flags = p[4];
    int32_t stream =
        (int32_t)((((uint32_t)p[5] & 0x7F) << 24) | ((uint32_t)p[6] << 16) |
                  ((uint32_t)p[7] << 8) | p[8]);
    if (len > (size_t)1 << 20) {  // absurd frame: kill conn
      ok = false;
      break;
    }
    if (c.rbuf.size() - pos < 9 + len) break;
    const uint8_t* pl = p + 9;
    // A CONTINUATION for an open header block must be exactly next.
    if (h->hdr_stream != 0 &&
        (type != kH2Continuation || stream != h->hdr_stream)) {
      ok = false;
      break;
    }
    switch (type) {
      case kH2Settings: {
        if (!(flags & kH2FlagAck)) {
          for (size_t i = 0; i + 6 <= len; i += 6) {
            uint16_t id = ((uint16_t)pl[i] << 8) | pl[i + 1];
            uint32_t v = ((uint32_t)pl[i + 2] << 24) |
                         ((uint32_t)pl[i + 3] << 16) |
                         ((uint32_t)pl[i + 4] << 8) | pl[i + 5];
            if (id == 0x4) {
              // RFC 7540 §6.9.2: the delta applies to every open
              // stream's window — ours are only the parked responses.
              int64_t delta = (int64_t)v - h->peer_initial_window;
              h->peer_initial_window = v;
              for (auto& [st_, body_, swin] : h->pending) swin += delta;
            }
          }
          h2_append_frame(c.wbuf, kH2Settings, kH2FlagAck, 0, "", 0);
          h2_flush_pending(s, &c);
        }
        break;
      }
      case kH2Ping:
        if (!(flags & kH2FlagAck) && len == 8)
          h2_append_frame(c.wbuf, kH2Ping, kH2FlagAck, 0, (const char*)pl, 8);
        break;
      case kH2WindowUpdate:
        if (len == 4) {
          uint32_t incr = (((uint32_t)pl[0] & 0x7F) << 24) |
                          ((uint32_t)pl[1] << 16) | ((uint32_t)pl[2] << 8) |
                          pl[3];
          if (stream == 0) {
            h->conn_send_window += incr;
          } else {
            for (auto& [st_, body_, swin] : h->pending)
              if (st_ == stream) swin += incr;
          }
          h2_flush_pending(s, &c);
        }
        break;
      case kH2HeadersFrame: {
        if (stream <= 0 || (stream & 1) == 0) {  // RFC 7540 §5.1.1
          ok = false;
          break;
        }
        size_t off = 0, tail = 0;
        if (flags & kH2FlagPadded) {
          if (len < 1) {
            ok = false;
            break;
          }
          tail = pl[0];
          off = 1;
        }
        if (flags & kH2FlagPriority) off += 5;
        if (off + tail > len || len - off - tail > kH2MaxHeaderBlock) {
          ok = false;
          break;
        }
        h->hdr_stream = stream;
        h->hdr_block.assign((const char*)pl + off, len - off - tail);
        if (flags & kH2FlagEndHeaders) ok = h2_dispatch_headers(s, slot);
        break;
      }
      case kH2Continuation:
        if (h->hdr_block.size() + len > kH2MaxHeaderBlock) {
          ok = false;  // unbounded-CONTINUATION flood
          break;
        }
        h->hdr_block.append((const char*)pl, len);
        if (flags & kH2FlagEndHeaders) ok = h2_dispatch_headers(s, slot);
        break;
      case kH2Data: {
        // API requests are bodyless; tolerate and drain bodies, crediting
        // BOTH flow-control windows back so clients never stall. The
        // connection window batches (32 KiB hysteresis); the per-stream
        // window is credited per frame — without it the comment's
        // "never stall" only held for bodies under the 64 KiB initial
        // stream window, and a larger upload wedged its stream
        // mid-body (ADVICE r5).
        h->rx_data_unacked += len;
        if (h->rx_data_unacked >= 32768) {
          uint8_t w[4] = {
              (uint8_t)((h->rx_data_unacked >> 24) & 0x7F),
              (uint8_t)(h->rx_data_unacked >> 16),
              (uint8_t)(h->rx_data_unacked >> 8),
              (uint8_t)h->rx_data_unacked,
          };
          h2_append_frame(c.wbuf, kH2WindowUpdate, 0, 0, (const char*)w, 4);
          h->rx_data_unacked = 0;
        }
        if (len > 0 && !(flags & kH2FlagEndStream)) {
          uint8_t w[4] = {
              (uint8_t)((len >> 24) & 0x7F),
              (uint8_t)(len >> 16),
              (uint8_t)(len >> 8),
              (uint8_t)len,
          };
          h2_append_frame(c.wbuf, kH2WindowUpdate, 0, stream,
                          (const char*)w, 4);
        }
        break;
      }
      case kH2Goaway:
        ok = false;
        break;
      case kH2RstStream: {
        if (len == 4 && stream > 0) {
          // Drop any parked response body for the stream, then remember
          // the id so a late ring completion is dropped too.
          for (auto it = h->pending.begin(); it != h->pending.end();)
            it = std::get<0>(*it) == stream ? h->pending.erase(it)
                                            : std::next(it);
          if (std::find(h->reset_streams.begin(), h->reset_streams.end(),
                        stream) == h->reset_streams.end()) {
            h->reset_streams.push_back(stream);
            if (h->reset_streams.size() > kH2MaxResetTracked)
              h->reset_streams.pop_front();
          }
        }
        break;
      }
      case kH2Priority:
      default:
        break;  // ignore (incl. unknown extension frames, RFC 7540 §4.1)
    }
    pos += 9 + len;
  }
  if (pos > 0) c.rbuf.erase(0, pos);
  // Write-backlog bound: an unread client socket must not buffer replies
  // without limit (PING floods, pipelined takes against a stalled
  // reader) — the h1 path's bound is its one-in-flight gate; this is
  // the h2 equivalent.
  if (c.wbuf.size() - c.woff > kH2MaxWbuf) ok = false;
  return ok;
}

// Turn an h2c client conn into a splice pair with a fresh backend conn
// to the python front (which speaks the actual h2 protocol). The client
// conn's buffered bytes (the preface and anything after it) are queued
// verbatim to the backend. Returns false when the backend is not
// configured or the connect fails — the caller falls back to the 400.
bool start_h2_proxy(Server* s, int slot) {
  if (s->h2_backend_port == 0) return false;
  int bfd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (bfd < 0) return false;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(s->h2_backend_port);
  inet_pton(AF_INET, "127.0.0.1", &addr.sin_addr);
  if (connect(bfd, (sockaddr*)&addr, sizeof(addr)) < 0 &&
      errno != EINPROGRESS) {
    ::close(bfd);
    return false;
  }
  int one = 1;
  setsockopt(bfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  int bslot;
  if (!s->free_slots.empty()) {
    bslot = s->free_slots.back();
    s->free_slots.pop_back();
  } else {
    bslot = (int)s->conns.size();
    s->conns.emplace_back();
  }
  // emplace_back may reallocate: re-take the client ref after.
  Conn& b = s->conns[bslot];
  Conn& c = s->conns[slot];
  b.fd = bfd;
  b.proxy = true;
  b.peer_slot = slot;
  b.wbuf.swap(c.rbuf);  // forward everything read so far (incl. preface)
  c.rbuf.clear();
  c.proxy = true;
  c.peer_slot = bslot;
  c.in_flight = false;
  c.req_start = {};
  epoll_event ev{};
  ev.events = EPOLLIN | (b.wbuf.size() ? EPOLLOUT : 0);
  ev.data.u64 = make_tag(bslot, b.gen);
  epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, bfd, &ev);
  return true;
}

// Shared /take query parsing (h1 + h2): first rate= and count= win
// (parse_qs[0] semantics); malformed rate ⇒ zero Rate (429, api.go:61).
void parse_take_query(std::string_view query, int64_t* freq,
                      int64_t* per_ns, int64_t* count) {
  *freq = *per_ns = *count = 0;
  bool have_rate = false, have_count = false;
  size_t qp = 0;
  while (qp <= query.size() && query.size()) {
    size_t amp = query.find('&', qp);
    if (amp == std::string::npos) amp = query.size();
    std::string_view kv = query.substr(qp, amp - qp);
    qp = amp + 1;
    size_t eq = kv.find('=');
    std::string_view k =
        kv.substr(0, eq == std::string_view::npos ? kv.size() : eq);
    std::string v = eq == std::string_view::npos
                        ? std::string()
                        : pct_decode(kv.substr(eq + 1), true);
    if (k == "rate" && !have_rate) {
      have_rate = true;
      if (!parse_rate(v, freq, per_ns)) *freq = *per_ns = 0;
    } else if (k == "count" && !have_count) {
      have_count = true;
      size_t b = 0, e2 = v.size();
      while (b < e2 && isspace((unsigned char)v[b])) b++;
      while (e2 > b && isspace((unsigned char)v[e2 - 1])) e2--;
      int64_t cv = 0;
      if (parse_atoi(v.substr(b, e2 - b), &cv) && cv >= 0) *count = cv;
    }
    if (amp == query.size()) break;
  }
  if (*count == 0) *count = 1;  // api.go:63-65 (incl. bad/negative count)
}

// In-front host-store take attempt (h1 + h2). Returns true when served,
// filling remaining/ok; false ⇒ the caller rides the Python ring.
bool try_inline_take(Server* s, const std::string& name, int64_t freq,
                     int64_t per_ns, int64_t count, int64_t* remaining,
                     int* ok, bool* events_bumped) {
  if (s->hls == nullptr || s->dir_h < 0) return false;
  alignas(8) uint8_t padded[kNameMax] = {0};
  memcpy(padded, name.data(), name.size());
  const int64_t now = realtime_ns() + s->hls->clock_offset_ns;
  std::lock_guard<std::mutex> hlk(s->hls->mu);
  int32_t row = pt_dir_resolve_rt(s->dir_h, padded, (int32_t)name.size(),
                                  s->hls->last_used, now);
  if (row < 0) return false;
  auto it = s->hls->blocks.find(row);
  if (it == s->hls->blocks.end() ||
      it->second[2 * s->hls->nodes + 4] == 0)
    return false;
  hls_take_locked(s->hls, it->second, row, freq, per_ns, count, now,
                  remaining, ok, events_bumped);
  return true;
}

// Dispatch one decoded h2 request (mu held): the same routing as the h1
// parser — in-front take, else the Python rings — answered as h2 frames
// on `stream`. No in_flight gate: h2 multiplexes streams per conn.
void serve_h2_request(Server* s, int slot, int32_t stream,
                      const std::string& method, const std::string& target) {
  Conn& c = s->conns[slot];
  s->requests++;
  // No per-conn req_start stamp here: h2 multiplexes streams, so a
  // single stamp would be overwritten by concurrent requests and
  // corrupt the latency ring. In-front takes are timed inline below;
  // ring-completed h2 requests go unsampled (h1 keeps sampling both).
  auto t0 = std::chrono::steady_clock::now();
  std::string path = target, query;
  size_t qm = target.find('?');
  if (qm != std::string::npos) {
    path = target.substr(0, qm);
    query = target.substr(qm + 1);
  }
  if (path.compare(0, 6, "/take/") == 0) {
    if (method != "POST") {
      queue_h2_response(s, &c, stream, 405, "text/plain",
                        "method not allowed\n", 19);
      return;
    }
    std::string name = pct_decode(path.substr(6), false);
    if (name.size() > kNameLimit) {
      char body[64];
      int bl = snprintf(body, sizeof(body), "bucket name larger than %d",
                        kNameLimit);
      queue_h2_response(s, &c, stream, 400, "text/plain", body, bl);
      return;
    }
    int64_t freq, per_ns, count;
    parse_take_query(query, &freq, &per_ns, &count);
    bool bumped = false;
    int64_t remaining = 0;
    int ok = 0;
    if (try_inline_take(s, name, freq, per_ns, count, &remaining, &ok,
                        &bumped)) {
      s->hls_takes++;
      char body[24];
      int bl = snprintf(body, sizeof(body), "%lld", (long long)remaining);
      queue_h2_response(s, &c, stream, ok ? 200 : 429, "text/plain", body,
                        bl);
      s->lat_ns[s->lat_count++ % Server::kLatRing] =
          std::chrono::duration_cast<std::chrono::nanoseconds>(
              std::chrono::steady_clock::now() - t0)
              .count();
      if (bumped) s->cv.notify_one();
      return;
    }
    if ((int)s->take_q.size() >= kRingCap) {
      s->dropped++;
      queue_h2_response(s, &c, stream, 503, "text/plain", "overloaded\n",
                        11);
      return;
    }
    TakeRec r{};
    r.tag = make_tag(slot, c.gen);
    r.stream = stream;
    r.freq = freq;
    r.per_ns = per_ns;
    r.count = count;
    r.name_len = (int)name.size();
    memcpy(r.name, name.data(), name.size());
    s->take_q.push_back(r);
    s->cv.notify_one();
    return;
  }
  if (target.size() >= kPathMax || (int)s->other_q.size() >= 1024) {
    queue_h2_response(s, &c, stream,
                      target.size() >= kPathMax ? 431 : 503, "text/plain",
                      "unavailable\n", 12);
    return;
  }
  OtherRec o{};
  o.tag = make_tag(slot, c.gen);
  o.stream = stream;
  snprintf(o.method, sizeof(o.method), "%.7s", method.c_str());
  memcpy(o.target, target.data(), target.size());
  o.target_len = (int)target.size();
  s->other_q.push_back(o);
  s->cv.notify_one();
}

// Activate native h2 on a preface-bearing conn: per-conn HPACK inflater
// + the server's (empty) SETTINGS preface. mu held.
bool start_h2_native(Server* s, int slot) {
  Nghttp2* ng = load_nghttp2();
  if (!ng) return false;
  Conn& c = s->conns[slot];
  H2State* h = new H2State();
  if (ng->inflate_new(&h->inflater) != 0) {
    delete h;
    return false;
  }
  c.h2 = h;
  c.in_flight = false;
  c.req_start = {};
  h2_append_frame(c.wbuf, kH2Settings, 0, 0, "", 0);
  return true;
}

// Parse one request out of c->rbuf (mu held). Returns false when more
// bytes are needed. May queue an immediate response or push ring records.
bool try_parse_one(Server* s, int slot) {
  Conn& c = s->conns[slot];
  if (c.in_flight || c.want_close || c.h2 != nullptr || c.proxy) return false;
  if (c.body_skip > 0) {
    size_t n = c.rbuf.size() < c.body_skip ? c.rbuf.size() : c.body_skip;
    c.rbuf.erase(0, n);
    c.body_skip -= n;
    if (c.body_skip > 0) return false;
  }
  size_t hdr_end = c.rbuf.find("\r\n\r\n");
  if (hdr_end == std::string::npos) {
    // h2c preface detection: reject cleanly (use the python front for h2).
    // Only the full 16-byte connection-preface request line ("PRI * ...")
    // triggers it — a request whose method merely starts with "PRI"
    // (e.g. "PRINT") must keep accumulating; and the 431 branch below is
    // exclusive so an oversized PRI-prefixed buffer queues ONE response.
    static const char kPreface[] = "PRI * HTTP/2.0\r\n";
    constexpr size_t kPrefaceLen = sizeof(kPreface) - 1;
    if (c.rbuf.size() >= kPrefaceLen &&
        c.rbuf.compare(0, kPrefaceLen, kPreface) == 0) {
      // h2c prior-knowledge client. Preference order: serve h2 natively
      // (libnghttp2 inflater available — wait for the full 24-byte
      // preface, which contains \r\n\r\n and so reaches the PRI method
      // branch below once ≥18 bytes arrive); else splice to the python
      // h2 backend; else reject cleanly.
      if (load_nghttp2() != nullptr) return false;  // accumulate
      if (start_h2_proxy(s, slot)) return false;
      c.close_after = true;
      queue_response(s, &c, 400, "text/plain", "h2c not supported here\n", 23);
    } else if (c.rbuf.size() > kRbufMax) {
      c.close_after = true;
      queue_response(s, &c, 431, "text/plain", "header too large\n", 17);
    }
    return false;
  }
  // Zero-copy parse: views over c.rbuf (valid until the single erase
  // below — everything that outlives it is materialized first). The
  // prior shape copied the whole header block plus ~6 substrings per
  // request; at 300k+ rps on one core that allocator churn was a
  // measurable slice of the budget.
  std::string_view head(c.rbuf.data(), hdr_end);
  size_t consumed = hdr_end + 4;

  // Request line.
  size_t eol = head.find("\r\n");
  std::string_view reqline =
      head.substr(0, eol == std::string_view::npos ? head.size() : eol);
  size_t sp1 = reqline.find(' ');
  size_t sp2 = reqline.rfind(' ');
  if (sp1 == std::string_view::npos || sp2 == sp1) {
    c.close_after = true;
    queue_response(s, &c, 400, "text/plain", "bad request\n", 12);
    c.rbuf.erase(0, consumed);
    return true;
  }
  std::string_view method = reqline.substr(0, sp1);
  std::string_view target = reqline.substr(sp1 + 1, sp2 - sp1 - 1);
  if (method == "PRI") {
    // A complete h2 preface ("PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n") contains
    // \r\n\r\n, so it reaches the normal parse path rather than the
    // incomplete-header preface check above. NOTHING was consumed yet, so
    // both handoffs see the raw buffer verbatim.
    static const char kFullPreface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
    if (load_nghttp2() != nullptr) {
      if (c.rbuf.size() < 24) return false;  // wait for the whole preface
      if (c.rbuf.compare(0, 24, kFullPreface, 24) == 0 &&
          start_h2_native(s, slot)) {
        c.rbuf.erase(0, 24);
        // Frames may already be buffered behind the preface.
        if (!h2_process(s, slot)) {
          close_conn(s, slot);
          return false;
        }
        return false;  // h2 conns never re-enter the h1 parser
      }
      // Malformed preface tail: fall through to the h1 400 below.
    }
    if (c.h2 == nullptr && start_h2_proxy(s, slot)) return false;
    c.close_after = true;
    queue_response(s, &c, 400, "text/plain", "h2c not supported here\n", 23);
    c.rbuf.erase(0, consumed);
    return true;
  }

  // Headers we care about: Content-Length, Connection — matched
  // case-insensitively in place, no per-line copies.
  auto ieq = [](std::string_view a, const char* b, size_t bn) {
    if (a.size() != bn) return false;
    for (size_t i = 0; i < bn; i++)
      if (tolower((unsigned char)a[i]) != b[i]) return false;
    return true;
  };
  size_t content_len = 0;
  bool conn_close = false;
  size_t pos = (eol == std::string_view::npos) ? head.size() : eol + 2;
  while (pos < head.size()) {
    size_t e = head.find("\r\n", pos);
    if (e == std::string_view::npos) e = head.size();
    std::string_view line = head.substr(pos, e - pos);
    pos = e + 2;
    size_t colon = line.find(':');
    if (colon == std::string_view::npos) continue;
    std::string_view key = line.substr(0, colon);
    size_t v0 = colon + 1;
    while (v0 < line.size() && line[v0] == ' ') v0++;
    std::string_view val = line.substr(v0);
    if (ieq(key, "content-length", 14)) {
      content_len = 0;
      for (char ch : val) {
        if (ch < '0' || ch > '9') break;
        if (content_len > kMaxContentLen / 10) {
          // Saturate past the sane bound (a 20+-digit value used to wrap
          // size_t to a small count — under-skipped body bytes then
          // reparsed as pipelined requests); the bound check after the
          // header loop turns this into a 400 + close.
          content_len = kMaxContentLen + 1;
          break;
        }
        content_len = content_len * 10 + (size_t)(ch - '0');
      }
    } else if (ieq(key, "connection", 10)) {
      for (size_t i = 0; i + 5 <= val.size(); i++) {
        if (tolower((unsigned char)val[i]) == 'c' &&
            tolower((unsigned char)val[i + 1]) == 'l' &&
            tolower((unsigned char)val[i + 2]) == 'o' &&
            tolower((unsigned char)val[i + 3]) == 's' &&
            tolower((unsigned char)val[i + 4]) == 'e') {
          conn_close = true;
          break;
        }
      }
    }
  }
  if (content_len > kMaxContentLen) {
    // Oversized (or saturated-overflow) Content-Length: reject and close.
    // The whole buffer is dropped — body bytes must never be re-parsed
    // as pipelined requests (the desync/request-smuggling surface).
    c.close_after = true;
    queue_response(s, &c, 400, "text/plain", "content length too large\n", 25);
    c.rbuf.clear();
    return true;
  }
  std::string_view path = target, query;
  size_t qm = target.find('?');
  if (qm != std::string_view::npos) {
    path = target.substr(0, qm);
    query = target.substr(qm + 1);
  }
  // Materialize everything that outlives the erase BEFORE it runs: the
  // views above point into c.rbuf.
  const bool is_take = path.compare(0, 6, "/take/") == 0;
  const bool is_post = method == "POST";
  std::string name;
  int64_t freq = 0, per_ns = 0, count = 1;
  OtherRec o{};
  if (is_take) {
    if (is_post) {
      name = pct_decode(path.substr(6), false);
      parse_take_query(query, &freq, &per_ns, &count);
    }
  } else if (target.size() < kPathMax) {
    o.tag = make_tag(slot, c.gen);
    snprintf(o.method, sizeof(o.method), "%.*s",
             (int)std::min(method.size(), (size_t)7), method.data());
    memcpy(o.target, target.data(), target.size());
    o.target_len = (int)target.size();
  }
  const bool target_oversize = target.size() >= kPathMax;

  c.rbuf.erase(0, consumed);
  // Drain any request body (take input rides the URL, api.py contract).
  if (content_len > 0) {
    size_t n = c.rbuf.size() < content_len ? c.rbuf.size() : content_len;
    c.rbuf.erase(0, n);
    c.body_skip = content_len - n;
  }
  c.close_after = conn_close;
  s->requests++;
  c.req_start = std::chrono::steady_clock::now();

  if (is_take) {
    if (!is_post) {
      queue_response(s, &c, 405, "text/plain", "method not allowed\n", 19);
      return true;
    }
    if (name.size() > kNameLimit) {
      // api.go:55-58 → 400 with the error text.
      char body[64];
      int bl = snprintf(body, sizeof(body), "bucket name larger than %d", kNameLimit);
      queue_response(s, &c, 400, "text/plain", body, bl);
      return true;
    }

    // In-front fast path: a host-resident bucket's whole take decision —
    // resolve, lane arithmetic, response — runs here on the epoll thread,
    // the reference's in-process shape (api.go:51-86). The resolve runs
    // INSIDE the store's critical section: re-hosting a recycled row
    // requires the same mutex (_host_mu IS this lock), so the pair can
    // never be interleaved by evict→rebind→rehost and charge the wrong
    // bucket; the nested tab_mu(shared) is cycle-free. Misses (unknown
    // names, device-resident rows) fall through to the Python ring,
    // which binds/hosts/promotes exactly as before.
    {
      bool bumped = false;
      int64_t remaining = 0;
      int ok = 0;
      if (try_inline_take(s, name, freq, per_ns, count, &remaining, &ok,
                          &bumped)) {
        s->hls_takes++;
        char body[24];
        int bl = snprintf(body, sizeof(body), "%lld", (long long)remaining);
        queue_response(s, &c, ok ? 200 : 429, "text/plain", body, bl);
        // Promotions wake the pump promptly (poll predicate); broadcast
        // dirty marks ride the pump's short poll tick instead.
        if (bumped) s->cv.notify_one();
        return true;
      }
    }

    if ((int)s->take_q.size() >= kRingCap) {
      s->dropped++;
      queue_response(s, &c, 503, "text/plain", "overloaded\n", 11);
      return true;
    }
    TakeRec r{};
    r.tag = make_tag(slot, c.gen);
    r.freq = freq;
    r.per_ns = per_ns;
    r.count = count;
    r.name_len = (int)name.size();
    memcpy(r.name, name.data(), name.size());
    c.in_flight = true;
    s->take_q.push_back(r);
    s->cv.notify_one();
    return true;
  }

  // Slow path: hand method+target to Python (debug routes, 404s). The
  // record was filled BEFORE the erase (the views are dead by now).
  if (target_oversize || (int)s->other_q.size() >= 1024) {
    queue_response(s, &c, target_oversize ? 431 : 503, "text/plain",
                   "unavailable\n", 12);
    return true;
  }
  c.in_flight = true;
  s->other_q.push_back(o);
  s->cv.notify_one();
  return true;
}

void flush_writes(Server* s, int slot) {
  while (true) {
    Conn& c = s->conns[slot];  // re-take: try_parse_one may grow conns
    while (c.woff < c.wbuf.size()) {
      ssize_t n = ::send(c.fd, c.wbuf.data() + c.woff, c.wbuf.size() - c.woff,
                         MSG_NOSIGNAL);
      if (n > 0) {
        c.woff += (size_t)n;
        continue;
      }
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        epoll_mod(s, slot);  // arm EPOLLOUT
        return;
      }
      close_conn(s, slot);
      return;
    }
    c.wbuf.clear();
    c.woff = 0;
    if (c.want_close) {
      close_conn(s, slot);
      return;
    }
    if (c.proxy) break;  // splice conns carry no h1 requests to parse
    // Response done: a pipelined next request may already be buffered —
    // and may queue an immediate response (405/400), so loop until the
    // write buffer stays empty.
    bool parsed = false;
    while (try_parse_one(s, slot)) parsed = true;
    if (!parsed || s->conns[slot].wbuf.empty()) break;
  }
  if (s->conns[slot].fd >= 0) epoll_mod(s, slot);
}

void serve_loop(Server* s) {
  epoll_event evs[256];
  while (s->running.load(std::memory_order_relaxed)) {
    int n = epoll_wait(s->epoll_fd, evs, 256, 100);
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    std::unique_lock<std::mutex> lk(s->mu);
    for (int i = 0; i < n; i++) {
      uint64_t tag = evs[i].data.u64;
      if (tag == (uint64_t)-1) {  // listen socket
        while (true) {
          int fd = accept4(s->listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
          if (fd < 0) break;
          int one = 1;
          setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          int slot;
          if (!s->free_slots.empty()) {
            slot = s->free_slots.back();
            s->free_slots.pop_back();
          } else {
            slot = (int)s->conns.size();
            s->conns.emplace_back();
          }
          Conn& c = s->conns[slot];
          c.fd = fd;
          s->accepted++;
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.u64 = make_tag(slot, c.gen);
          epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, fd, &ev);
        }
        continue;
      }
      if (tag == (uint64_t)-2) {  // eventfd kick: completions queued
        uint64_t v;
        ssize_t rd = read(s->event_fd, &v, 8);
        (void)rd;
        // Flush every conn with pending writes.
        for (int slot = 0; slot < (int)s->conns.size(); slot++) {
          if (s->conns[slot].fd >= 0 &&
              s->conns[slot].wbuf.size() > s->conns[slot].woff)
            flush_writes(s, slot);
        }
        continue;
      }
      int slot = (int)(tag >> 32);
      uint32_t gen = (uint32_t)tag;
      if (slot >= (int)s->conns.size() || s->conns[slot].gen != gen ||
          s->conns[slot].fd < 0)
        continue;
      Conn& c = s->conns[slot];
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        close_conn(s, slot);
        continue;
      }
      if (evs[i].events & EPOLLIN) {
        char buf[8192];
        bool closed = false;
        while (true) {
          ssize_t rd = recv(c.fd, buf, sizeof(buf), 0);
          if (rd > 0) {
            c.rbuf.append(buf, rd);
            // Hostile-flood cap: h1 conns only. A splice conn's rbuf is
            // a transit buffer cleared every event (large h2 bodies are
            // legitimate); its backpressure is the peer-wbuf cap below.
            // Native-h2 conns drain frame-by-frame per event with a 1 MB
            // frame sanity bound of their own.
            if (!c.proxy && c.h2 == nullptr &&
                c.rbuf.size() > (size_t)kRbufMax * 4) {
              closed = true;
              break;
            }
            continue;
          }
          if (rd == 0) closed = true;
          break;  // EAGAIN or close
        }
        if (c.proxy && c.peer_slot < 0) {
          // Orphaned splice (peer closed; we survive only to drain
          // want_close writes): incoming bytes have no destination —
          // discard them (unbounded rbuf otherwise, the flood cap is
          // proxy-exempt), and EOF closes NOW (the h1 tail below skips
          // proxy conns, which would leave a level-triggered EPOLLIN
          // refiring on the dead socket forever).
          c.rbuf.clear();
          if (closed) close_conn(s, slot);
          continue;
        }
        if (c.proxy && c.peer_slot >= 0) {
          // Splice: everything read forwards verbatim to the peer.
          Conn& p = s->conns[c.peer_slot];
          if (!c.rbuf.empty()) {
            p.wbuf.append(c.rbuf);
            c.rbuf.clear();
          }
          if (p.wbuf.size() - p.woff > (size_t)kRbufMax * 16) {
            close_conn(s, slot);  // runaway peer backlog: drop the pair
            continue;
          }
          if (p.fd >= 0 && p.wbuf.size() > p.woff)
            flush_writes(s, c.peer_slot);
          if (closed) {
            // Half-close: let the peer DRAIN its pending bytes (the tail
            // of an h2 response/GOAWAY) before closing — an immediate
            // pair-close would clear its wbuf mid-flight.
            int peer = c.peer_slot;
            c.peer_slot = -1;
            if (peer >= 0 && s->conns[peer].fd >= 0 &&
                s->conns[peer].peer_slot == slot) {
              Conn& pc = s->conns[peer];
              pc.peer_slot = -1;  // unlink: no recursive close
              if (pc.wbuf.size() > pc.woff) {
                pc.want_close = true;  // close once drained
              } else {
                close_conn(s, peer);
              }
            }
            close_conn(s, slot);
            continue;
          }
          continue;
        }
        if (c.h2 != nullptr) {
          // Native h2: frame processing replaces the h1 parser entirely.
          if (!h2_process(s, slot)) {
            close_conn(s, slot);
            continue;
          }
          Conn& ch = s->conns[slot];
          if (ch.fd >= 0 && ch.wbuf.size() > ch.woff) flush_writes(s, slot);
          if (closed && s->conns[slot].fd >= 0) close_conn(s, slot);
          continue;
        }
        if (closed && c.rbuf.empty()) {
          close_conn(s, slot);
          continue;
        }
        while (try_parse_one(s, slot)) {
        }
        // Re-take the ref: an h2 handoff inside try_parse_one may have
        // grown the conn table (reference invalidation) and turned this
        // conn into a splice.
        Conn& c2 = s->conns[slot];
        if (c2.fd >= 0 && c2.wbuf.size() > c2.woff) flush_writes(s, slot);
        if (closed && s->conns[slot].fd >= 0 && !s->conns[slot].in_flight &&
            !s->conns[slot].proxy)
          close_conn(s, slot);
      }
      if (s->conns[slot].fd >= 0 && (evs[i].events & EPOLLOUT))
        flush_writes(s, slot);
    }
  }
}

}  // namespace

extern "C" {

// Start a server; returns handle ≥0 or -errno.
int pt_http_start(const char* ip, uint16_t port) {
  int h = -1;
  for (int i = 0; i < 8; i++)
    if (!g_servers[i]) {
      h = i;
      break;
    }
  if (h < 0) return -EMFILE;

  int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -errno;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, ip, &addr.sin_addr) != 1) {
    ::close(fd);
    return -EINVAL;
  }
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) < 0 || listen(fd, 1024) < 0) {
    int e = errno;
    ::close(fd);
    return -e;
  }

  Server* s = new Server();
  s->listen_fd = fd;
  socklen_t alen = sizeof(addr);
  getsockname(fd, (sockaddr*)&addr, &alen);
  s->port = ntohs(addr.sin_port);
  s->epoll_fd = epoll_create1(0);
  s->event_fd = eventfd(0, EFD_NONBLOCK);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = (uint64_t)-1;
  epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, fd, &ev);
  ev.events = EPOLLIN;
  ev.data.u64 = (uint64_t)-2;
  epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, s->event_fd, &ev);
  s->running = true;
  s->thread = std::thread(serve_loop, s);
  g_servers[h] = s;
  return h;
}

int pt_http_port(int h) {
  Server* s = g_servers[h];
  return s ? s->port : -1;
}

// Configure the h2c splice backend (the python front's loopback h2
// server). 0 disables (preface → 400, the pre-r4 behavior).
int pt_http_set_h2_backend(int h, uint16_t port) {
  std::lock_guard<std::mutex> reg(g_reg_mu);
  Server* s = g_servers[h];
  if (!s) return -EBADF;
  std::lock_guard<std::mutex> lk(s->mu);
  s->h2_backend_port = port;
  return 0;
}

// Drain parsed requests. Blocks up to timeout_ms when both queues are
// empty (GIL released by ctypes). Fills up to cap_t takes and cap_o
// others; *n_other receives the other-count; returns the take-count.
int pt_http_poll(int h, int timeout_ms,
                 uint64_t* tags, int32_t* streams, uint8_t* names,
                 int* name_lens,
                 int64_t* freqs, int64_t* pers, int64_t* counts, int cap_t,
                 uint64_t* otags, int32_t* ostreams, uint8_t* otargets,
                 int* otarget_lens,
                 uint8_t* omethods, int cap_o, int* n_other) {
  Server* s = g_servers[h];
  if (!s) return -EBADF;
  std::unique_lock<std::mutex> lk(s->mu);
  if (s->take_q.empty() && s->other_q.empty() && timeout_ms > 0) {
    auto pred = [&] {
      return !s->take_q.empty() || !s->other_q.empty() || !s->running ||
             (s->hls != nullptr &&
              s->hls->events.load(std::memory_order_relaxed) !=
                  s->hls_events_seen);
    };
#if defined(PT_STEADY_CV_WAIT)
    // Modern toolchain (gcc >= 12 / llvm >= 14, probed by check.sh):
    // the steady-clock wait_for is the correct form — immune to
    // realtime clock jumps — and its pthread_cond_clockwait lowering is
    // intercepted by these sanitizer runtimes.
    s->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred);
#else
    // wait_until(system_clock) rather than wait_for: wait_for's
    // steady_clock lowers to pthread_cond_clockwait, which the gcc-10
    // libtsan doesn't intercept — TSan then never sees the mutex release
    // inside the wait and reports every later acquisition as a double
    // lock (the checker must stay usable; scripts/check.sh runs it). A
    // realtime-clock jump can only shorten/stretch one poll timeout.
    s->cv.wait_until(
        lk,
        std::chrono::system_clock::now() +
            std::chrono::milliseconds(timeout_ms),
        pred);
#endif
  }
  if (s->hls != nullptr)
    s->hls_events_seen = s->hls->events.load(std::memory_order_relaxed);
  int nt = 0;
  while (nt < cap_t && !s->take_q.empty()) {
    TakeRec& r = s->take_q.front();
    tags[nt] = r.tag;
    streams[nt] = r.stream;
    memset(names + nt * kNameMax, 0, kNameMax);
    memcpy(names + nt * kNameMax, r.name, r.name_len);
    name_lens[nt] = r.name_len;
    freqs[nt] = r.freq;
    pers[nt] = r.per_ns;
    counts[nt] = r.count;
    s->take_q.pop_front();
    nt++;
  }
  int no = 0;
  while (no < cap_o && !s->other_q.empty()) {
    OtherRec& o = s->other_q.front();
    otags[no] = o.tag;
    ostreams[no] = o.stream;
    memcpy(otargets + no * kPathMax, o.target, o.target_len);
    otarget_lens[no] = o.target_len;
    memset(omethods + no * 8, 0, 8);
    memcpy(omethods + no * 8, o.method, strnlen(o.method, 7));
    s->other_q.pop_front();
    no++;
  }
  *n_other = no;
  return nt;
}

// Complete a batch of takes: status 200/429 + remaining-tokens body.
// streams[i] > 0 answers on that h2 stream; 0 = HTTP/1.1.
int pt_http_complete_takes(int h, const uint64_t* tags,
                           const int32_t* streams, const int* statuses,
                           const int64_t* remaining, int n) {
  std::lock_guard<std::mutex> reg(g_reg_mu);
  Server* s = g_servers[h];
  if (!s) return -EBADF;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    for (int i = 0; i < n; i++) {
      int slot = (int)(tags[i] >> 32);
      uint32_t gen = (uint32_t)tags[i];
      if (slot >= (int)s->conns.size()) continue;
      Conn& c = s->conns[slot];
      if (c.fd < 0 || c.gen != gen) continue;  // conn died mid-flight
      char body[24];
      int bl = snprintf(body, sizeof(body), "%lld", (long long)remaining[i]);
      if (streams[i] > 0 && c.h2 != nullptr)
        queue_h2_response(s, &c, streams[i], statuses[i], "text/plain",
                          body, bl);
      else
        queue_response(s, &c, statuses[i], "text/plain", body, bl);
    }
  }
  uint64_t one = 1;
  ssize_t wr = write(s->event_fd, &one, 8);
  (void)wr;
  return 0;
}

// Complete one slow-path request with an arbitrary body.
int pt_http_complete_other(int h, uint64_t tag, int32_t stream, int status,
                           const char* ctype, const uint8_t* body,
                           int body_len) {
  std::lock_guard<std::mutex> reg(g_reg_mu);
  Server* s = g_servers[h];
  if (!s) return -EBADF;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    int slot = (int)(tag >> 32);
    uint32_t gen = (uint32_t)tag;
    if (slot < (int)s->conns.size()) {
      Conn& c = s->conns[slot];
      if (c.fd >= 0 && c.gen == gen) {
        if (stream > 0 && c.h2 != nullptr)
          queue_h2_response(s, &c, stream, status, ctype,
                            (const char*)body, body_len);
        else
          queue_response(s, &c, status, ctype, (const char*)body, body_len);
      }
    }
  }
  uint64_t one = 1;
  ssize_t wr = write(s->event_fd, &one, 8);
  (void)wr;
  return 0;
}

// out8 = {accepted, requests, active_conns, dropped, lat_p50_ns,
// lat_p99_ns, lat_max_ns, lat_samples} — latency is server-side
// (request parsed → response queued) over a 4096-sample ring.
int pt_http_stats(int h, uint64_t* out8) {
  std::lock_guard<std::mutex> reg(g_reg_mu);
  Server* s = g_servers[h];
  if (!s) return -EBADF;
  std::lock_guard<std::mutex> lk(s->mu);
  out8[0] = s->accepted;
  out8[1] = s->requests;
  out8[2] = 0;
  for (const auto& c : s->conns)
    if (c.fd >= 0) out8[2]++;
  out8[3] = s->dropped;
  uint64_t n = s->lat_count < Server::kLatRing ? s->lat_count : Server::kLatRing;
  out8[4] = out8[5] = out8[6] = 0;
  out8[7] = n;
  if (n > 0) {
    std::vector<uint64_t> lat(s->lat_ns, s->lat_ns + n);
    std::sort(lat.begin(), lat.end());
    out8[4] = lat[n / 2];
    out8[5] = lat[(size_t)(n * 0.99) < n ? (size_t)(n * 0.99) : n - 1];
    out8[6] = lat[n - 1];
  }
  return 0;
}

int pt_http_stop(int h) {
  Server* s;
  {
    // Unregister FIRST (under the registry lock) so any completion that
    // races with shutdown either sees the slot and finishes before we
    // proceed, or sees nullptr and returns EBADF — never a freed Server.
    std::lock_guard<std::mutex> reg(g_reg_mu);
    s = g_servers[h];
    if (!s) return -EBADF;
    g_servers[h] = nullptr;
  }
  s->running = false;
  s->cv.notify_all();
  uint64_t one = 1;
  ssize_t wr = write(s->event_fd, &one, 8);
  (void)wr;
  if (s->thread.joinable()) s->thread.join();
  {
    std::lock_guard<std::mutex> lk(s->mu);
    for (int i = 0; i < (int)s->conns.size(); i++)
      if (s->conns[i].fd >= 0) close_conn(s, i);
  }
  ::close(s->listen_fd);
  ::close(s->epoll_fd);
  ::close(s->event_fd);
  delete s;
  return 0;
}

// Closed-loop load client: `conns` keep-alive connections, each keeping
// `pipeline` requests in flight, for `duration_ms`. A C++ client is the
// only way to measure the server on a 1-core box — a Python client costs
// more per request than the C++ front does and dominates the machine.
// `target` may be a single path or many paths joined by '\n'; requests
// cycle through them round-robin (how the zipf multi-bucket workloads
// are driven: the caller pre-samples the key distribution into paths).
// out5 = {requests_completed, p50_ns, p99_ns, ok_200, limited_429}
// (latency per response at pipeline depth, i.e. includes queueing behind
// the pipeline window; the status split feeds admitted-vs-limit checks).
int pt_http_blast(const char* ip, uint16_t port, const char* target,
                  int conns, int pipeline, int duration_ms, uint64_t* out5) {
  std::vector<std::string> reqs;
  {
    const char* t = target;
    while (*t) {
      const char* e = strchr(t, '\n');
      size_t len = e ? (size_t)(e - t) : strlen(t);
      if (len)
        reqs.push_back("POST " + std::string(t, len) +
                       " HTTP/1.1\r\nHost: x\r\n\r\n");
      t += len + (e ? 1 : 0);
    }
  }
  if (reqs.empty()) return -EINVAL;
  size_t req_rr = 0;
  struct CC {
    int fd = -1;
    std::string rbuf;
    std::string wpend;  // partially-sent bytes (non-blocking send)
    size_t woff = 0;
    int inflight = 0;
    std::deque<std::chrono::steady_clock::time_point> sent;
  };
  std::vector<CC> cs(conns);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, ip, &addr.sin_addr) != 1) return -EINVAL;
  int ep = epoll_create1(0);
  for (int i = 0; i < conns; i++) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (connect(fd, (sockaddr*)&addr, sizeof(addr)) < 0) {
      ::close(fd);
      ::close(ep);
      return -errno;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    set_nonblock(fd);
    cs[i].fd = fd;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u32 = i;
    epoll_ctl(ep, EPOLL_CTL_ADD, fd, &ev);
  }
  auto now = [] { return std::chrono::steady_clock::now(); };
  auto t_end = now() + std::chrono::milliseconds(duration_ms);
  std::vector<uint64_t> lats;
  lats.reserve(1 << 20);
  uint64_t done = 0, ok200 = 0, lim429 = 0;

  auto pump_conn = [&](CC& c) {  // fill the pipeline window
    // Queue whole requests, then flush as far as the socket allows: a
    // partial non-blocking send must never splice the NEXT request into
    // the middle of a half-written one.
    while (c.inflight < pipeline) {
      c.wpend += reqs[req_rr++ % reqs.size()];
      c.inflight++;
      c.sent.push_back(now());
    }
    while (c.woff < c.wpend.size()) {
      ssize_t wr = ::send(c.fd, c.wpend.data() + c.woff,
                          c.wpend.size() - c.woff, MSG_NOSIGNAL);
      if (wr <= 0) break;  // EAGAIN: socket buffer full
      c.woff += (size_t)wr;
    }
    if (c.woff >= c.wpend.size()) {
      c.wpend.clear();
      c.woff = 0;
    }
  };
  for (auto& c : cs) pump_conn(c);

  epoll_event evs[64];
  char buf[65536];
  while (now() < t_end) {
    int n = epoll_wait(ep, evs, 64, 50);
    for (int i = 0; i < n; i++) {
      CC& c = cs[evs[i].data.u32];
      while (true) {
        ssize_t rd = recv(c.fd, buf, sizeof(buf), 0);
        if (rd <= 0) break;
        c.rbuf.append(buf, rd);
      }
      // Count complete responses (Content-Length framing).
      while (true) {
        size_t he = c.rbuf.find("\r\n\r\n");
        if (he == std::string::npos) break;
        size_t clen = 0;
        size_t p = c.rbuf.find("Content-Length:");
        if (p != std::string::npos && p < he)
          clen = strtoul(c.rbuf.c_str() + p + 15, nullptr, 10);
        if (c.rbuf.size() < he + 4 + clen) break;
        if (c.rbuf.size() >= 12 && c.rbuf.compare(9, 3, "200") == 0) ok200++;
        else if (c.rbuf.size() >= 12 && c.rbuf.compare(9, 3, "429") == 0) lim429++;
        c.rbuf.erase(0, he + 4 + clen);
        c.inflight--;
        done++;
        if (!c.sent.empty()) {
          lats.push_back(std::chrono::duration_cast<std::chrono::nanoseconds>(
                             now() - c.sent.front())
                             .count());
          c.sent.pop_front();
        }
      }
      pump_conn(c);
    }
  }
  for (auto& c : cs) ::close(c.fd);
  ::close(ep);
  out5[0] = done;
  if (!lats.empty()) {
    std::sort(lats.begin(), lats.end());
    out5[1] = lats[lats.size() / 2];
    out5[2] = lats[(size_t)(lats.size() * 0.99)];
  } else {
    out5[1] = out5[2] = 0;
  }
  out5[3] = ok200;
  out5[4] = lim429;
  return 0;
}

// ---- Host-lane store ABI --------------------------------------------------

// Create a store. cap_base/created/last_used are the Python directory's
// fixed-size int64 arrays (stable allocations; the C++ side reads the
// first two and stamps the third). promote_takes <= 0 disables native
// take-pressure promotion: an in-front take costs ~0.2 µs, so unlike the
// Python host path there is no QPS past which the device tick serves ONE
// row's takes faster — promotion stays rx-pressure/scalar-driven.
int pt_hls_create(int nodes, int64_t node_slot, int64_t promote_takes,
                  int64_t window_ns, int64_t clock_offset_ns,
                  const int64_t* cap_base, const int64_t* created,
                  int64_t* last_used) {
  std::lock_guard<std::mutex> reg(g_hls_mu);
  int h = -1;
  for (int i = 0; i < 16; i++)
    if (!g_hls[i]) {
      h = i;
      break;
    }
  if (h < 0) return -EMFILE;
  HostStore* st = new HostStore();
  st->nodes = nodes;
  st->words = 2 * nodes + 6;
  st->node_slot = node_slot;
  st->promote_takes = promote_takes;
  st->window_ns = window_ns;
  st->clock_offset_ns = clock_offset_ns;
  st->cap_base = cap_base;
  st->created = created;
  st->last_used = last_used;
  g_hls[h] = st;
  return h;
}

// Destroy: caller (engine.stop) must guarantee the HTTP front is detached
// and no Python proxy views the blocks afterwards.
int pt_hls_destroy(int h) {
  HostStore* st;
  {
    std::lock_guard<std::mutex> reg(g_hls_mu);
    st = g_hls[h];
    if (!st) return -EBADF;
    g_hls[h] = nullptr;
  }
  for (auto& kv : st->blocks) delete[] kv.second;
  delete st;
  return 0;
}

// Python's _host_mu: ctypes releases the GIL for the blocking acquire, so
// the epoll thread (which never takes the GIL) cannot deadlock it.
int pt_hls_lock(int h) {
  HostStore* st = g_hls[h];
  if (!st) return -EBADF;
  st->mu.lock();
  return 0;
}

int pt_hls_unlock(int h) {
  HostStore* st = g_hls[h];
  if (!st) return -EBADF;
  st->mu.unlock();
  return 0;
}

// Get-or-create the row's block, zeroed, resident. Returns the block
// address for numpy views (0 on failure). Caller holds the store lock.
int64_t pt_hls_host_locked(int h, int32_t row) {
  HostStore* st = g_hls[h];
  if (!st) return 0;
  int64_t*& blk = st->blocks[row];
  if (blk == nullptr) blk = new int64_t[st->words];
  std::memset(blk, 0, sizeof(int64_t) * st->words);
  blk[2 * st->nodes + 4] = 1;  // resident
  return (int64_t)(intptr_t)blk;
}

// Stop serving the row in-front (promotion pop / eviction / release).
// The block and its Python views stay valid. Caller holds the store lock.
int pt_hls_unhost_locked(int h, int32_t row) {
  HostStore* st = g_hls[h];
  if (!st) return -EBADF;
  auto it = st->blocks.find(row);
  if (it != st->blocks.end()) it->second[2 * st->nodes + 4] = 0;
  return 0;
}

// Drain pending events: dirty rows (coalesced-broadcast queue; flags
// cleared) and promote rows. For each dirty row, `snap` receives a
// consistent lane snapshot — added[nodes] | taken[nodes] | elapsed, one
// stride of 2*nodes+1 int64 per row — taken HERE, in C++, under the
// lock, so the caller's per-row Python work (which previously held the
// store mutex for ~ms per drain at 1000 dirty rows and showed up as the
// front's p99 tail) happens outside it. Caller holds the store lock.
int pt_hls_drain_locked(int h, int32_t* dirty_out, int64_t* snap, int cap_d,
                        int32_t* promote_out, int cap_p, int* n_promote) {
  HostStore* st = g_hls[h];
  if (!st) return -EBADF;
  // Pop at most cap rows; the remainder KEEPS its queue entries and dirty
  // flags, so overflow rows are re-delivered on the caller's next drain
  // (a silent truncation here would permanently lose a bucket's final
  // broadcast — the caller loops until both queues come back empty).
  const int stride = 2 * st->nodes + 1;
  int nd = 0;
  for (; nd < cap_d && nd < (int)st->dirty_rows.size(); nd++) {
    int32_t row = st->dirty_rows[nd];
    auto it = st->blocks.find(row);
    if (it != st->blocks.end()) {
      it->second[2 * st->nodes + 5] = 0;
      std::memcpy(snap + (size_t)nd * stride, it->second,
                  sizeof(int64_t) * (2 * st->nodes));
      snap[(size_t)nd * stride + 2 * st->nodes] = it->second[2 * st->nodes];
    } else {
      std::memset(snap + (size_t)nd * stride, 0, sizeof(int64_t) * stride);
    }
    dirty_out[nd] = row;
  }
  st->dirty_rows.erase(st->dirty_rows.begin(), st->dirty_rows.begin() + nd);
  int np = 0;
  for (; np < cap_p && np < (int)st->promote_rows.size(); np++)
    promote_out[np] = st->promote_rows[np];
  st->promote_rows.erase(st->promote_rows.begin(),
                         st->promote_rows.begin() + np);
  *n_promote = np;
  return nd;
}

// Promotion-event counter: bumped by the epoll thread's takes ONLY on a
// take-pressure promotion threshold crossing (hls_take_locked). Lock-free
// read — the pump compares it against its cursor after a poll wake and
// runs a promotions-only drain when it moved, bypassing the broadcast
// cadence gate so a newly-hot bucket leaves the slow path promptly.
int64_t pt_hls_events(int h) {
  HostStore* st = g_hls[h];
  if (!st) return -EBADF;
  return (int64_t)st->events.load(std::memory_order_relaxed);
}

// out4 = {native_takes, resident_rows, blocks_allocated, pending_events}.
int pt_hls_stats(int h, uint64_t* out4) {
  HostStore* st = g_hls[h];
  if (!st) return -EBADF;
  std::lock_guard<std::mutex> lk(st->mu);
  out4[0] = st->native_takes;
  uint64_t res = 0;
  for (auto& kv : st->blocks)
    if (kv.second[2 * st->nodes + 4]) res++;
  out4[1] = res;
  out4[2] = st->blocks.size();
  out4[3] = st->dirty_rows.size() + st->promote_rows.size();
  return 0;
}

// Wire the HTTP front to a store + C++ directory; -1/-1 detaches.
int pt_http_attach_host(int http_h, int hls_h, int dir_h) {
  std::lock_guard<std::mutex> reg(g_reg_mu);
  Server* s = g_servers[http_h];
  if (!s) return -EBADF;
  std::lock_guard<std::mutex> lk(s->mu);
  if (hls_h < 0) {
    s->hls = nullptr;
    s->dir_h = -1;
    return 0;
  }
  HostStore* st = g_hls[hls_h];
  if (!st) return -EBADF;
  s->hls = st;
  s->dir_h = dir_h;
  return 0;
}

// Test hook: run the EXACT in-front take path (resolve + residency +
// hls_take_locked) with a caller-controlled clock. Returns 1 (admitted),
// 0 (limited), -1 (not servable in front: miss or device-resident).
int pt_hls_take_probe(int hls_h, int dir_h, const uint8_t* name, int len,
                      int64_t freq, int64_t per_ns, int64_t count,
                      int64_t now, int64_t* remaining) {
  HostStore* st = g_hls[hls_h];
  if (!st) return -EBADF;
  alignas(8) uint8_t padded[kNameMax] = {0};
  if (len < 0 || len > kNameMax) return -EINVAL;
  std::memcpy(padded, name, (size_t)len);
  // Same shape as the front's inline path: resolve inside the store's
  // critical section (see try_parse_one).
  std::lock_guard<std::mutex> lk(st->mu);
  int32_t row = pt_dir_resolve_rt(dir_h, padded, len, st->last_used, now);
  if (row < 0) return -1;
  auto it = st->blocks.find(row);
  if (it == st->blocks.end() || it->second[2 * st->nodes + 4] == 0) return -1;
  bool bumped = false;
  int ok = 0;
  hls_take_locked(st, it->second, row, freq, per_ns, count, now, remaining,
                  &ok, &bumped);
  return ok;
}

// h2 prior-knowledge closed-loop load client: `conns` connections, each
// keeping `pipeline` streams in flight. The request HEADERS block uses
// HPACK literals-without-indexing only (stateless, always valid), so no
// deflater is needed; responses are counted by END_STREAM DATA frames
// and the :status literal is peeked from our server's known block shape.
// out5 = {requests_completed, p50_ns, p99_ns, ok_200, limited_429}.
int pt_http_blast_h2(const char* ip, uint16_t port, const char* target,
                     int conns, int pipeline, int duration_ms,
                     uint64_t* out5) {
  std::vector<std::string> head_frames;  // per-target HEADERS payloads
  {
    const char* t = target;
    while (*t) {
      const char* e = strchr(t, '\n');
      size_t len = e ? (size_t)(e - t) : strlen(t);
      if (len) {
        std::string block;
        hpack_literal(block, ":method", 7, "POST", 4);
        hpack_literal(block, ":scheme", 7, "http", 4);
        hpack_literal(block, ":authority", 10, "x", 1);
        hpack_literal(block, ":path", 5, t, len);
        head_frames.push_back(block);
      }
      t += len + (e ? 1 : 0);
    }
  }
  if (head_frames.empty()) return -EINVAL;
  size_t rr = 0;
  struct HC {
    int fd = -1;
    std::string rbuf, wpend;
    size_t woff = 0;
    int inflight = 0;
    int32_t next_stream = 1;
    uint64_t rx_data = 0;
    std::deque<std::chrono::steady_clock::time_point> sent;
  };
  std::vector<HC> cs(conns);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, ip, &addr.sin_addr) != 1) return -EINVAL;
  int ep = epoll_create1(0);
  for (int i = 0; i < conns; i++) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (connect(fd, (sockaddr*)&addr, sizeof(addr)) < 0) {
      ::close(fd);
      ::close(ep);
      return -errno;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    set_nonblock(fd);
    cs[i].fd = fd;
    cs[i].wpend.assign("PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n");
    h2_append_frame(cs[i].wpend, kH2Settings, 0, 0, "", 0);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u32 = i;
    epoll_ctl(ep, EPOLL_CTL_ADD, fd, &ev);
  }
  auto now = [] { return std::chrono::steady_clock::now(); };
  auto t_end = now() + std::chrono::milliseconds(duration_ms);
  std::vector<uint64_t> lats;
  lats.reserve(1 << 20);
  uint64_t done = 0, ok200 = 0, lim429 = 0;

  auto pump_conn = [&](HC& c) {
    while (c.inflight < pipeline) {
      const std::string& block = head_frames[rr++ % head_frames.size()];
      h2_append_frame(c.wpend, kH2HeadersFrame,
                      kH2FlagEndHeaders | kH2FlagEndStream, c.next_stream,
                      block.data(), block.size());
      c.next_stream += 2;
      c.inflight++;
      c.sent.push_back(now());
    }
    while (c.woff < c.wpend.size()) {
      ssize_t wr = ::send(c.fd, c.wpend.data() + c.woff,
                          c.wpend.size() - c.woff, MSG_NOSIGNAL);
      if (wr <= 0) break;
      c.woff += (size_t)wr;
    }
    if (c.woff >= c.wpend.size()) {
      c.wpend.clear();
      c.woff = 0;
    }
  };
  for (auto& c : cs) pump_conn(c);

  epoll_event evs[64];
  char buf[65536];
  while (now() < t_end) {
    int n = epoll_wait(ep, evs, 64, 50);
    for (int i = 0; i < n; i++) {
      HC& c = cs[evs[i].data.u32];
      while (true) {
        ssize_t rd = recv(c.fd, buf, sizeof(buf), 0);
        if (rd <= 0) break;
        c.rbuf.append(buf, rd);
      }
      size_t rpos = 0;
      while (c.rbuf.size() - rpos >= 9) {
        const uint8_t* p = (const uint8_t*)c.rbuf.data() + rpos;
        size_t len = ((size_t)p[0] << 16) | ((size_t)p[1] << 8) | p[2];
        if (c.rbuf.size() - rpos < 9 + len) break;
        int type = p[3];
        uint8_t flags = p[4];
        const uint8_t* pl = p + 9;
        if (type == kH2Settings && !(flags & kH2FlagAck)) {
          h2_append_frame(c.wpend, kH2Settings, kH2FlagAck, 0, "", 0);
        } else if (type == kH2HeadersFrame && len > 10 && pl[0] == 0 &&
                   pl[1] == 7) {
          // Our server's block: literal :status first; peek the value.
          const uint8_t* v = pl + 2 + 7 + 1;  // 0x00, len, ":status", vlen
          if (pl[9] >= 3 && v[0] == '2') ok200++;
          else if (pl[9] >= 3 && v[0] == '4') lim429++;
        } else if (type == kH2Data) {
          c.rx_data += len;
          if (flags & kH2FlagEndStream) {
            c.inflight--;
            done++;
            if (!c.sent.empty()) {
              lats.push_back(
                  std::chrono::duration_cast<std::chrono::nanoseconds>(
                      now() - c.sent.front())
                      .count());
              c.sent.pop_front();
            }
          }
          if (c.rx_data >= 16384) {
            uint8_t w[4] = {(uint8_t)((c.rx_data >> 24) & 0x7F),
                            (uint8_t)(c.rx_data >> 16),
                            (uint8_t)(c.rx_data >> 8), (uint8_t)c.rx_data};
            h2_append_frame(c.wpend, kH2WindowUpdate, 0, 0, (const char*)w,
                            4);
            c.rx_data = 0;
          }
        } else if (type == kH2Goaway) {
          rpos = c.rbuf.size();
          break;
        }
        rpos += 9 + len;
      }
      if (rpos > 0) c.rbuf.erase(0, rpos);
      pump_conn(c);
    }
  }
  for (auto& c : cs) ::close(c.fd);
  ::close(ep);
  out5[0] = done;
  if (!lats.empty()) {
    std::sort(lats.begin(), lats.end());
    out5[1] = lats[lats.size() / 2];
    out5[2] = lats[(size_t)(lats.size() * 0.99)];
  } else {
    out5[1] = out5[2] = 0;
  }
  out5[3] = ok200;
  out5[4] = lim429;
  return 0;
}

// Exposed for differential tests against ops/rate.py.
int pt_parse_rate(const char* v, int64_t* freq, int64_t* per_ns) {
  return parse_rate(std::string(v), freq, per_ns) ? 0 : -1;
}

int pt_parse_duration(const char* v, int64_t* out) {
  return parse_duration(std::string(v), out) ? 0 : -1;
}

}  // extern "C"
