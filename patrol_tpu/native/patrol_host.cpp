// patrol_host: native host network path for patrol_tpu.
//
// The reference's replication plane is compiled Go: goroutine-per-peer UDP
// fan-out (repo.go:129-158) and a single-packet-per-syscall receive loop
// (repo.go:108-120). This library is the C++ equivalent, shaped for the
// microbatching TPU runtime instead of goroutines:
//
//   * pt_recv_batch  — recvmmsg(): up to N datagrams per syscall, with a
//                      poll() timeout so the loop stays cancellable (the
//                      3s read-deadline idea of repo.go:109).
//   * pt_send_fanout — sendmmsg(): one syscall flushes a whole broadcast
//                      matrix (payloads × peers).
//   * pt_decode_batch / pt_encode_batch — the 25-byte-header wire codec
//                      (bucket.go:34-91) + the v2 origin-slot trailer,
//                      vectorized over packet batches into flat arrays that
//                      map 1:1 onto numpy buffers.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this environment).
// Build: g++ -O2 -shared -fPIC -o libpatrolhost.so patrol_host.cpp

#include <arpa/inet.h>
#include <cerrno>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <netinet/in.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <unistd.h>

#include <algorithm>
#include <memory>
#include <mutex>
#include <shared_mutex>
#include <thread>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

constexpr int kPacketSize = 256;
constexpr int kFixedSize = 25;
constexpr int kTrailerSize = 6;       // base form: P2 | flags=0 | slot u16 | ck
constexpr int kTrailerCapSize = 14;   // with-cap:  P2 | flags=1 | slot u16 | cap u64 | ck
constexpr int kTrailerLaneSize = 30;  // lane: P2 | flags=3 | slot | cap | lane_a | lane_t | ck
constexpr int kTrailerMultiHead = 14;  // multi: P2 | flags=5 | own_slot | cap | K (then K×18 + ck)
constexpr int kMaxBatch = 1024;

inline uint64_t load_be64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
#if __BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__
  v = __builtin_bswap64(v);
#endif
  return v;
}

inline void store_be64(uint8_t* p, uint64_t v) {
#if __BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__
  v = __builtin_bswap64(v);
#endif
  std::memcpy(p, &v, 8);
}

// FNV-1a 64-bit over the raw name bytes. MUST stay bit-identical to
// patrol_tpu.runtime.directory._fnv1a64 — the directory's vectorized
// hash-table lookup routes on this value (bytes are then verified, so a
// mismatch only costs the slow path, never correctness).
inline uint64_t fnv1a64(const uint8_t* p, int n) {
  uint64_t h = 0xcbf29ce484222325ULL;
  for (int i = 0; i < n; i++) {
    h ^= p[i];
    h *= 0x100000001b3ULL;
  }
  return h;
}

inline double bits_to_double(uint64_t b) {
  double d;
  std::memcpy(&d, &b, 8);
  return d;
}

inline uint64_t double_to_bits(double d) {
  uint64_t b;
  std::memcpy(&b, &d, 8);
  return b;
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------- sockets

// Open a nonblocking UDP socket bound to ip:port. Returns fd or -errno.
int pt_udp_open(const char* ip, uint16_t port) {
  int fd = socket(AF_INET, SOCK_DGRAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -errno;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  int buf = 4 << 20;  // fat socket buffers: bursty broadcast matrices
  setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &buf, sizeof(buf));
  setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &buf, sizeof(buf));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, ip, &addr.sin_addr) != 1) {
    close(fd);
    return -EINVAL;
  }
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  return fd;
}

// Local bound port (for port-0 binds in tests).
int pt_udp_port(int fd) {
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  if (getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len) < 0) return -errno;
  return ntohs(addr.sin_port);
}

void pt_udp_close(int fd) { close(fd); }

// Receive up to max_packets datagrams (≤row_stride bytes each) in one
// recvmmsg sweep. buf: max_packets*row_stride bytes; sizes/src_ips/
// src_ports: per-packet outputs. row_stride was fixed at 256 (the v1
// packet bound) until ROADMAP 3b: delta-interval datagrams are up to
// 8 KiB, and a 256-B ring row silently truncated them — the backend had
// to advertise a v1-sized rx bound. Callers now size the ring rows to
// the delta bound. Waits up to timeout_ms for the first datagram.
// Returns n ≥ 0 or -errno.
int pt_recv_batch(int fd, uint8_t* buf, int max_packets, int row_stride,
                  int* sizes, uint32_t* src_ips, uint16_t* src_ports,
                  int timeout_ms) {
  if (max_packets > kMaxBatch) max_packets = kMaxBatch;
  if (row_stride < kPacketSize) return -EINVAL;
  pollfd pfd{fd, POLLIN, 0};
  int pr = poll(&pfd, 1, timeout_ms);
  if (pr < 0) return -errno;
  if (pr == 0) return 0;

  mmsghdr msgs[kMaxBatch];
  iovec iovs[kMaxBatch];
  sockaddr_in addrs[kMaxBatch];
  std::memset(msgs, 0, sizeof(mmsghdr) * max_packets);
  for (int i = 0; i < max_packets; i++) {
    iovs[i] = {buf + static_cast<size_t>(i) * row_stride,
               static_cast<size_t>(row_stride)};
    msgs[i].msg_hdr.msg_iov = &iovs[i];
    msgs[i].msg_hdr.msg_iovlen = 1;
    msgs[i].msg_hdr.msg_name = &addrs[i];
    msgs[i].msg_hdr.msg_namelen = sizeof(sockaddr_in);
  }
  int n = recvmmsg(fd, msgs, max_packets, MSG_DONTWAIT, nullptr);
  if (n < 0) return (errno == EAGAIN || errno == EWOULDBLOCK) ? 0 : -errno;
  for (int i = 0; i < n; i++) {
    sizes[i] = static_cast<int>(msgs[i].msg_len);
    src_ips[i] = ntohl(addrs[i].sin_addr.s_addr);
    src_ports[i] = ntohs(addrs[i].sin_port);
  }
  return n;
}

// Send every payload to every peer: n_payloads × n_peers datagrams, flushed
// through sendmmsg in chunks. payloads: n_payloads rows of row_stride bytes
// (sizes per payload; a delta-interval unicast is one 8-KiB row, the v1
// broadcast matrix stays 256-B rows). Returns datagrams handed to the
// kernel, or -errno on hard failure.
int pt_send_fanout(int fd, const uint8_t* payloads, const int* sizes,
                   int n_payloads, int row_stride, const uint32_t* peer_ips,
                   const uint16_t* peer_ports, int n_peers) {
  if (row_stride <= 0) return -EINVAL;
  mmsghdr msgs[kMaxBatch];
  iovec iovs[kMaxBatch];
  sockaddr_in addrs[kMaxBatch];
  int queued = 0, sent_total = 0;

  auto flush = [&]() -> int {
    int off = 0;
    while (off < queued) {
      int n = sendmmsg(fd, msgs + off, queued - off, 0);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          pollfd pfd{fd, POLLOUT, 0};
          if (poll(&pfd, 1, 50) <= 0) break;  // give up after 50ms stall
          continue;
        }
        return -errno;
      }
      off += n;
      sent_total += n;
    }
    queued = 0;
    return 0;
  };

  for (int p = 0; p < n_payloads; p++) {
    for (int j = 0; j < n_peers; j++) {
      if (queued == kMaxBatch) {
        int rc = flush();
        if (rc < 0) return rc;
      }
      int i = queued++;
      std::memset(&msgs[i], 0, sizeof(mmsghdr));
      iovs[i] = {const_cast<uint8_t*>(payloads) +
                     static_cast<size_t>(p) * row_stride,
                 static_cast<size_t>(sizes[p])};
      addrs[i] = sockaddr_in{};
      addrs[i].sin_family = AF_INET;
      addrs[i].sin_port = htons(peer_ports[j]);
      addrs[i].sin_addr.s_addr = htonl(peer_ips[j]);
      msgs[i].msg_hdr.msg_iov = &iovs[i];
      msgs[i].msg_hdr.msg_iovlen = 1;
      msgs[i].msg_hdr.msg_name = &addrs[i];
      msgs[i].msg_hdr.msg_namelen = sizeof(sockaddr_in);
    }
  }
  int rc = flush();
  if (rc < 0) return rc;
  return sent_total;
}

// ---------------------------------------------------------------- rx ring
//
// Device-resident ingest (ops/ingest.py): the recvmmsg loop writes
// datagrams DIRECTLY into reusable page-aligned byte planes that Python
// views zero-copy (pt_rx_ring_plane) and ships with jax.device_put —
// no intermediate numpy copy between the wire and the H2D transfer.
// Lease/commit is the ownership protocol: the rx thread LEASES a plane
// before receiving into it, hands the filled plane to the engine, and
// the engine's completion pipeline COMMITS it back once the shipped
// operand is ready (the StagingPool contract). The mutex serializes
// lease/commit across those two threads; planes are C++-owned
// (posix_memalign, page boundaries — the pinned-allocation seam a real
// accelerator transport would mlock/host-register) and freed only at
// destroy, which defers while any plane is still leased so an in-flight
// transfer can never read freed memory.

namespace {

struct PtRxRing {
  std::mutex mu;
  int n_planes = 0;
  int max_batch = 0;
  int row = 0;
  std::vector<uint8_t*> planes;
  std::vector<uint8_t> leased;
  std::vector<uint8_t> used;  // plane saw a prior lease (reuse counter)
  uint64_t leases = 0, commits = 0, reuse = 0, exhausted = 0;
  bool closing = false;
};

PtRxRing* g_rings[16] = {nullptr};
std::mutex g_ring_mu;

void ptring_free(PtRxRing* r) {
  for (uint8_t* p : r->planes) std::free(p);
  delete r;
}

}  // namespace

// Allocate a ring of n_planes page-aligned planes, each max_batch rows
// of row_stride bytes. Returns handle or -errno.
int pt_rx_ring_create(int n_planes, int max_batch, int row_stride) {
  if (n_planes <= 0 || n_planes > 64 || max_batch <= 0 ||
      max_batch > kMaxBatch || row_stride < kPacketSize)
    return -EINVAL;
  std::lock_guard<std::mutex> reg(g_ring_mu);
  int h = -1;
  for (int i = 0; i < 16; i++)
    if (!g_rings[i]) {
      h = i;
      break;
    }
  if (h < 0) return -EMFILE;
  PtRxRing* r = new PtRxRing();
  r->n_planes = n_planes;
  r->max_batch = max_batch;
  r->row = row_stride;
  size_t bytes = static_cast<size_t>(max_batch) * row_stride;
  for (int i = 0; i < n_planes; i++) {
    void* p = nullptr;
    if (posix_memalign(&p, 4096, bytes) != 0) {
      ptring_free(r);
      return -ENOMEM;
    }
    std::memset(p, 0, bytes);
    r->planes.push_back(static_cast<uint8_t*>(p));
  }
  r->leased.assign(n_planes, 0);
  r->used.assign(n_planes, 0);
  g_rings[h] = r;
  return h;
}

// Base address of one plane (Python builds a zero-copy numpy view).
int64_t pt_rx_ring_plane(int h, int plane) {
  PtRxRing* r = (h >= 0 && h < 16) ? g_rings[h] : nullptr;
  if (!r || plane < 0 || plane >= r->n_planes) return 0;
  return reinterpret_cast<int64_t>(r->planes[plane]);
}

// Lease the lowest free plane (deterministic — the abi schedule
// explorer's model relies on it). Returns plane index, or -EAGAIN when
// every plane is in flight (caller falls back / retries next batch).
int pt_rx_ring_lease(int h) {
  PtRxRing* r = (h >= 0 && h < 16) ? g_rings[h] : nullptr;
  if (!r) return -EBADF;
  std::lock_guard<std::mutex> lk(r->mu);
  if (r->closing) return -EBADF;
  for (int i = 0; i < r->n_planes; i++) {
    if (!r->leased[i]) {
      r->leased[i] = 1;
      r->leases++;
      if (r->used[i]) r->reuse++;
      r->used[i] = 1;
      return i;
    }
  }
  r->exhausted++;
  return -EAGAIN;
}

// Return a leased plane to the free set. -EINVAL on a plane that was
// never leased (double-commit / stray index — the ownership bug class
// the PTA004 schedule scenario drives). Frees the ring when a deferred
// destroy is pending and this was the last outstanding lease.
int pt_rx_ring_commit(int h, int plane) {
  std::lock_guard<std::mutex> reg(g_ring_mu);
  PtRxRing* r = (h >= 0 && h < 16) ? g_rings[h] : nullptr;
  if (!r) return -EBADF;
  bool free_now = false;
  {
    std::lock_guard<std::mutex> lk(r->mu);
    if (plane < 0 || plane >= r->n_planes || !r->leased[plane])
      return -EINVAL;
    r->leased[plane] = 0;
    r->commits++;
    if (r->closing) {
      free_now = true;
      for (int i = 0; i < r->n_planes; i++)
        if (r->leased[i]) free_now = false;
    }
  }
  if (free_now) {
    g_rings[h] = nullptr;
    ptring_free(r);
  }
  return 0;
}

// leases, commits, reuse, exhausted — observability (rx_ring_* counters).
int pt_rx_ring_stats(int h, uint64_t* out4) {
  PtRxRing* r = (h >= 0 && h < 16) ? g_rings[h] : nullptr;
  if (!r) return -EBADF;
  std::lock_guard<std::mutex> lk(r->mu);
  out4[0] = r->leases;
  out4[1] = r->commits;
  out4[2] = r->reuse;
  out4[3] = r->exhausted;
  return 0;
}

// Destroy: immediate when no plane is leased; otherwise DEFERRED — the
// ring is marked closing (no new leases) and the last commit frees it,
// so an in-flight H2D transfer can never read freed plane memory.
int pt_rx_ring_destroy(int h) {
  std::lock_guard<std::mutex> reg(g_ring_mu);
  PtRxRing* r = (h >= 0 && h < 16) ? g_rings[h] : nullptr;
  if (!r) return -EBADF;
  bool free_now = true;
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->closing = true;
    for (int i = 0; i < r->n_planes; i++)
      if (r->leased[i]) free_now = false;
  }
  if (free_now) {
    g_rings[h] = nullptr;
    ptring_free(r);
  }
  return 0;
}

// ------------------------------------------------------------------ codec

// Decode n packets (each at in_stride bytes per row; rows may be the
// 8-KiB rx ring's — a row's decodable prefix is sizes[i] bytes, and
// oversized control-channel payloads like delta intervals simply decode
// as zero-state packets for their reserved name). Outputs per packet:
//   added/taken (float64 tokens), elapsed (uint64 ns, two's complement),
//   name bytes copied into names at 256B stride with name_lens set,
//   origin_slots (-1 when no valid v2 trailer), caps (sender capacity base
//   in int64 nanotokens; -1 when absent — v1 or base-form trailer),
//   lane_added/lane_taken (exact own-lane PN values; -1 when absent),
//   multi_flags: 0 = none, 1 = base trailer with the capability-advert
//   bit (incast requests from multi-capable peers), 2 = a valid
//   multi-lane trailer — the batch path does NOT expand its lanes; the
//   caller re-decodes those few packets (incast replies, cold-start only)
//   through the Python codec.
// Malformed packets get name_lens[i] = -1. Returns count of valid packets.
int pt_decode_batch(const uint8_t* packets, const int* sizes, int n,
                    int in_stride, double* added, double* taken,
                    uint64_t* elapsed, uint8_t* names, int* name_lens,
                    int* origin_slots, int64_t* caps, int64_t* lane_added,
                    int64_t* lane_taken, uint64_t* name_hashes,
                    int* multi_flags) {
  if (in_stride < kPacketSize) return 0;
  int ok = 0;
  for (int i = 0; i < n; i++) {
    const uint8_t* p = packets + static_cast<size_t>(i) * in_stride;
    int sz = sizes[i];
    if (sz > in_stride) sz = in_stride;
    origin_slots[i] = -1;
    caps[i] = -1;
    lane_added[i] = -1;
    lane_taken[i] = -1;
    if (multi_flags) multi_flags[i] = 0;
    if (name_hashes) name_hashes[i] = 0;
    if (sz < kFixedSize) {
      name_lens[i] = -1;
      continue;
    }
    int nlen = p[24];
    if (sz - kFixedSize < nlen) {
      name_lens[i] = -1;
      continue;
    }
    added[i] = bits_to_double(load_be64(p));
    taken[i] = bits_to_double(load_be64(p + 8));
    elapsed[i] = load_be64(p + 16);
    // Zero the full name row so callers can REUSE the output buffer across
    // batches: the directory's vectorized byte-verify compares whole
    // zero-padded rows, which a stale longer name would corrupt.
    uint8_t* nrow = names + i * kPacketSize;
    std::memset(nrow, 0, kPacketSize);
    std::memcpy(nrow, p + kFixedSize, nlen);
    name_lens[i] = nlen;
    if (name_hashes) name_hashes[i] = fnv1a64(nrow, nlen);
    const uint8_t* tail = p + kFixedSize + nlen;
    int tail_len = sz - kFixedSize - nlen;
    if (tail_len >= kTrailerSize && tail[0] == 'P' && tail[1] == '2') {
      bool with_cap = (tail[2] & 0x01) != 0;
      bool with_lane = (tail[2] & 0x02) != 0;
      bool with_multi = (tail[2] & 0x04) != 0;
      if (with_multi && with_cap && !with_lane) {
        // Multi-lane trailer: magic|flags|own_slot u16|cap u64|K u8|
        // K×(slot u16, added u64, taken u64)|ck. Validate whole, flag for
        // Python re-decode; only slot+cap surface through the flat outputs.
        if (tail_len >= kTrailerMultiHead + 1) {
          int K = tail[13];
          int tsz = kTrailerMultiHead + K * 18 + 1;
          if (tail_len >= tsz) {
            uint8_t sum = 0;
            for (int t = 0; t < tsz - 1; t++) sum += tail[t];
            uint64_t cap = load_be64(tail + 5);
            if (sum == tail[tsz - 1] && cap < (1ULL << 63)) {
              origin_slots[i] = (tail[3] << 8) | tail[4];
              caps[i] = static_cast<int64_t>(cap);
              if (multi_flags) multi_flags[i] = 2;
            }
          }
        }
        ok++;
        continue;
      }
      int tsz = with_lane ? kTrailerLaneSize
                          : (with_cap ? kTrailerCapSize : kTrailerSize);
      if (tail_len >= tsz && (!with_lane || with_cap)) {
        uint8_t sum = 0;
        for (int t = 0; t < tsz - 1; t++) sum += tail[t];
        if (sum == tail[tsz - 1]) {
          // Bit-63 values are hostile (non-negative int64 counts by
          // contract). All-or-nothing: any invalid field discards the WHOLE
          // trailer (packet degrades to v1 / deficit-attribution ingest) —
          // a partially-honored lane trailer would merge the header's
          // aggregate into one lane and permanently inflate the PN sum.
          uint64_t cap = with_cap ? load_be64(tail + 5) : 0;
          uint64_t la = with_lane ? load_be64(tail + 13) : 0;
          uint64_t lt = with_lane ? load_be64(tail + 21) : 0;
          if (cap < (1ULL << 63) && la < (1ULL << 63) && lt < (1ULL << 63)) {
            origin_slots[i] = (tail[3] << 8) | tail[4];
            if (with_cap) caps[i] = static_cast<int64_t>(cap);
            if (with_lane) {
              lane_added[i] = static_cast<int64_t>(la);
              lane_taken[i] = static_cast<int64_t>(lt);
            }
            // Base trailer carrying the advert bit: multi-capable sender.
            if (multi_flags && with_multi && !with_cap) multi_flags[i] = 1;
          }
        }
      }
    }
    ok++;
  }
  return ok;
}

// Encode n states into packets at 256B stride. names at 256B stride with
// name_lens; origin_slots ≥ 0 appends the v2 trailer — the 30-byte lane
// form when caps[i] ≥ 0 and lane_added[i]/lane_taken[i] ≥ 0 (names ≤ 201),
// the 14-byte with-cap form when only caps[i] ≥ 0 (names ≤ 217), the 6-byte
// base form otherwise (names ≤ 225; ≤ 231 with no trailer — oversize gets
// out_sizes[i] = -1). Returns count encoded.
int pt_encode_batch(const double* added, const double* taken,
                    const uint64_t* elapsed, const uint8_t* names,
                    const int* name_lens, const int* origin_slots,
                    const int64_t* caps, const int64_t* lane_added,
                    const int64_t* lane_taken, int n,
                    uint8_t* out, int* out_sizes) {
  int ok = 0;
  for (int i = 0; i < n; i++) {
    uint8_t* p = out + i * kPacketSize;
    int nlen = name_lens[i];
    bool with_trailer = origin_slots[i] >= 0;
    bool with_cap = with_trailer && caps[i] >= 0;
    bool with_lane = with_cap && lane_added[i] >= 0 && lane_taken[i] >= 0;
    int tsz = with_trailer
                  ? (with_lane ? kTrailerLaneSize
                               : (with_cap ? kTrailerCapSize : kTrailerSize))
                  : 0;
    int limit = kPacketSize - kFixedSize - tsz;
    if (nlen < 0 || nlen > limit) {
      out_sizes[i] = -1;
      continue;
    }
    store_be64(p, double_to_bits(added[i]));
    store_be64(p + 8, double_to_bits(taken[i]));
    store_be64(p + 16, elapsed[i]);
    p[24] = static_cast<uint8_t>(nlen);
    std::memcpy(p + kFixedSize, names + i * kPacketSize, nlen);
    int sz = kFixedSize + nlen;
    if (with_trailer) {
      uint8_t* t = p + sz;
      t[0] = 'P';
      t[1] = '2';
      t[2] = static_cast<uint8_t>((with_cap ? 1 : 0) | (with_lane ? 2 : 0));
      t[3] = static_cast<uint8_t>((origin_slots[i] >> 8) & 0xFF);
      t[4] = static_cast<uint8_t>(origin_slots[i] & 0xFF);
      if (with_cap) {
        store_be64(t + 5, static_cast<uint64_t>(caps[i]));
      }
      if (with_lane) {
        store_be64(t + 13, static_cast<uint64_t>(lane_added[i]));
        store_be64(t + 21, static_cast<uint64_t>(lane_taken[i]));
      }
      uint8_t sum = 0;
      for (int b = 0; b < tsz - 1; b++) sum += t[b];
      t[tsz - 1] = sum;
      sz += tsz;
    }
    out_sizes[i] = sz;
    ok++;
  }
  return ok;
}

// ---- pt_dir: native bucket-name resolve table ------------------------------
//
// The C++ half of BucketDirectory's hash-routing fast path. Python owns
// binding policy (allocation, eviction, pin lifecycle) and keeps the name
// bytes in numpy arrays; this table holds only (hash → row) and READS the
// numpy buffers (shared pointers, zero copy) to verify bytes. One call
// resolves a whole decoded batch: probe + memcmp + pin + LRU stamp per
// packet — the work the vectorized numpy path pays ~0.5 µs/packet of
// gather overhead for at 1M rows, done here in one cache-aware pass.
//
// Thread safety: every entry point MUST be called under the Python
// directory lock (the Python side guarantees this); no internal locking.

namespace {

// One probe-table entry, 16 bytes — hash, row, and the bound name's
// length packed into ONE cache line (4 entries/line). The r2 layout kept
// hash/row/len in three arrays, so every probe at 1M rows paid two-three
// DRAM lines; this layout pays one (the dominant classify cost is DRAM
// latency on a single host core — see pt_rx_classify).
struct PtSlot {
  uint64_t h;
  int32_t row;  // -1 empty, -2 tombstone, ≥0 bound row
  int32_t len;  // name length of `row` (valid when row ≥ 0)
};
static_assert(sizeof(PtSlot) == 16, "slot must pack to 16 bytes");

struct PtDir {
  int64_t capacity = 0;
  uint64_t mask = 0;
  std::vector<PtSlot> tab;      // open-addressing probe table
  std::vector<uint64_t> row_h;  // row → its hash (for delete/rebuild)
  std::vector<uint8_t> live;    // row → bound?
  const uint8_t* name_bytes = nullptr;  // [capacity, 256], Python-owned
  const int32_t* name_lens = nullptr;   // [capacity], Python-owned
  int64_t tombs = 0;
  int maxprobe = 1;
  // Table writers (insert/delete/rebuild, all Python-lock-serialized
  // already) vs the HTTP front's epoll-thread resolve (pt_dir_resolve_rt,
  // NOT under the Python lock): writers take unique, the runtime resolve
  // takes shared. The Python-side batch resolvers stay lock-free readers
  // — the Python directory lock already serializes them against every
  // writer; only the epoll thread needs this.
  std::shared_mutex tab_mu;
};

PtDir* g_dirs[16] = {nullptr};
// Serializes slot allocation/release: create runs from Python __init__
// (no directory lock exists yet) and destroy can run from GC on any
// thread. Per-table operations are NOT guarded here — the per-directory
// Python lock covers them, and close() nulls its handle under that lock
// before destroying, so no operation can race its own table's teardown.
std::mutex g_dir_mu;

void ptdir_insert(PtDir* d, uint64_t h, int32_t row) {
  uint64_t pos = h & d->mask;
  int probes = 1;
  int64_t tomb = -1;
  while (true) {
    int32_t r = d->tab[pos].row;
    if (r == -1) break;
    if (r == -2 && tomb < 0) tomb = (int64_t)pos;
    pos = (pos + 1) & d->mask;
    probes++;
  }
  if (tomb >= 0) {
    pos = (uint64_t)tomb;
    d->tombs--;
  }
  d->tab[pos].h = h;
  d->tab[pos].row = row;
  // The name bytes/len are already written by the Python bind path when
  // the insert lands (directory._bind_locked order), so the length can be
  // denormalized into the probe entry — resolve then never touches the
  // separate name_lens array.
  d->tab[pos].len = d->name_lens ? d->name_lens[row] : 0;
  if (probes > d->maxprobe) d->maxprobe = probes;
  d->row_h[row] = h;
  d->live[row] = 1;
}

void ptdir_rebuild(PtDir* d) {
  std::fill(d->tab.begin(), d->tab.end(), PtSlot{0, -1, 0});
  d->tombs = 0;
  d->maxprobe = 1;
  for (int64_t r = 0; r < d->capacity; r++)
    if (d->live[r]) ptdir_insert(d, d->row_h[r], (int32_t)r);
}

}  // namespace

int pt_dir_create(int64_t capacity, const uint8_t* name_bytes,
                  const int32_t* name_lens) {
  std::lock_guard<std::mutex> reg(g_dir_mu);
  int h = -1;
  for (int i = 0; i < 16; i++)
    if (!g_dirs[i]) {
      h = i;
      break;
    }
  if (h < 0) return -EMFILE;
  PtDir* d = new PtDir();
  d->capacity = capacity;
  uint64_t m = 64;
  while ((int64_t)m < capacity * 4) m <<= 1;
  d->mask = m - 1;
  d->tab.assign(m, PtSlot{0, -1, 0});
  d->row_h.assign(capacity, 0);
  d->live.assign(capacity, 0);
  d->name_bytes = name_bytes;
  d->name_lens = name_lens;
  g_dirs[h] = d;
  return h;
}

int pt_dir_insert(int h, uint64_t hash, int32_t row) {
  PtDir* d = g_dirs[h];
  if (!d) return -EBADF;
  std::unique_lock<std::shared_mutex> wl(d->tab_mu);
  ptdir_insert(d, hash, row);
  return 0;
}

// Batch insert for the bulk bind path (assign_many): one ctypes call per
// delta chunk instead of one per new bucket.
int pt_dir_insert_batch(int h, const uint64_t* hashes, const int32_t* rows,
                        int n) {
  PtDir* d = g_dirs[h];
  if (!d) return -EBADF;
  std::unique_lock<std::shared_mutex> wl(d->tab_mu);
  for (int i = 0; i < n; i++) ptdir_insert(d, hashes[i], rows[i]);
  return 0;
}

int pt_dir_delete(int h, uint64_t hash, int32_t row) {
  PtDir* d = g_dirs[h];
  if (!d) return -EBADF;
  std::unique_lock<std::shared_mutex> wl(d->tab_mu);
  uint64_t pos = hash & d->mask;
  for (int p = 0; p < d->maxprobe; p++) {
    int32_t r = d->tab[pos].row;
    if (r == row) {
      d->tab[pos] = PtSlot{0, -2, 0};
      d->tombs++;
      break;
    }
    if (r == -1) break;
    pos = (pos + 1) & d->mask;
  }
  d->live[row] = 0;
  if (d->tombs > (int64_t)(d->mask + 1) / 8) ptdir_rebuild(d);
  return 0;
}

namespace {

// One name resolve: probe + verify. Zero-padded 256B rows on both sides,
// so comparing ceil(len/8) u64-words is exact name equality while touching
// ≤1 cache line for typical short names (a full 256B memcmp pulls 4 lines
// of the 1M-row name table per packet — the dominant resolve cost). The
// length check rides the probe entry itself (PtSlot.len), so a resolve
// touches exactly one probe line + one name line.
inline int32_t ptdir_resolve_one(const PtDir* d, uint64_t hv,
                                 const uint8_t* name_row, int32_t len) {
  // Collision discipline (shared with pt_rx_classify pass-1 so both
  // resolvers answer identically for the same name): keep probing past an
  // entry whose hash matches but length differs — distinct same-hash
  // names coexist in the table, so a len mismatch is not this name — and
  // stop at the first (hash, len) match, where a byte-verify failure is
  // reported as a miss (the python slow path re-resolves).
  uint64_t pos = hv & d->mask;
  for (int p = 0; p < d->maxprobe; p++) {
    const PtSlot& s = d->tab[pos];
    if (s.row == -1) return -1;  // definite miss
    if (s.row >= 0 && s.h == hv && s.len == len) {
      if (std::memcmp(d->name_bytes + (size_t)s.row * kPacketSize, name_row,
                      ((size_t)len + 7) & ~(size_t)7) == 0) {
        return s.row;
      }
      return -1;  // byte-verify fail ⇒ miss (slow path re-resolves)
    }
    pos = (pos + 1) & d->mask;
  }
  return -1;
}

}  // namespace

// Single-name resolve for the HTTP front's epoll thread (the only caller
// NOT serialized by the Python directory lock): computes the FNV hash,
// probes under the table's shared lock, and stamps the LRU clock on a hit
// (plain aligned int64 store — tear-free on x86-64; eviction reading a
// stale stamp is the same benignity the Python batch resolve accepts).
// No pin is taken: the inline host take completes before returning to the
// event loop, so there is no in-flight window for eviction to violate —
// a take racing the eviction itself answers from the dying bucket's last
// state, the same bounded anomaly the Python fast path documents.
int32_t pt_dir_resolve_rt(int h, const uint8_t* name_padded, int32_t len,
                          int64_t* last_used, int64_t now) {
  PtDir* d = g_dirs[h];
  if (!d || len < 0) return -1;
  uint64_t hv = fnv1a64(name_padded, len);
  std::shared_lock<std::shared_mutex> rl(d->tab_mu);
  int32_t row = ptdir_resolve_one(d, hv, name_padded, len);
  if (row >= 0 && last_used) last_used[row] = now;
  return row;
}

// Batch resolve: rows_out[i] = row or -1 (miss/malformed). On a hit, pins
// and last_used (Python-owned numpy buffers) are updated in place.
// Returns the hit count.
int64_t pt_dir_resolve(int h, int n, const uint64_t* hashes,
                       const uint8_t* name_buf, const int32_t* lens,
                       int64_t* rows_out, int32_t* pins, int64_t* last_used,
                       int64_t now) {
  PtDir* d = g_dirs[h];
  if (!d) return -EBADF;
  int64_t hits = 0;
  for (int i = 0; i < n; i++) {
    rows_out[i] = -1;
    if (lens[i] < 0) continue;
    int32_t r =
        ptdir_resolve_one(d, hashes[i], name_buf + (size_t)i * kPacketSize,
                          lens[i]);
    if (r >= 0) {
      rows_out[i] = r;
      pins[r]++;
      last_used[r] = now;
      hits++;
    }
  }
  return hits;
}

namespace {

// float64 wire tokens → int64 nanotokens; MUST stay bit-identical to
// ops/wire.py sanitize_nt_array (NaN → 0, ≤0 → 0, ≥2^63 clamps to the
// int64 edge, round-half-even like np.rint — nearbyint under the default
// FE_TONEAREST mode). Native-rx and python-rx peers must merge the same
// packet to the same state or replicas diverge permanently.
inline int64_t sanitize_nt(double tokens) {
  if (!(tokens > 0.0)) return 0;  // NaN fails the comparison, like numpy
  double nt = tokens * 1e9;
  if (nt >= 9223372036854775808.0) return INT64_MAX;  // +Inf / overflow
  return (int64_t)std::nearbyint(nt);
}

}  // namespace

// Fused rx fast path: resolve + sanitize + wire-semantics classification
// in one pass over a decoded batch — the python side of this
// (engine._classify_queue_chunk's ~20 numpy array passes) was the feed
// bottleneck at ~500 ns/delta (BENCH r2: feed 6.76 s of a ~10 s replay).
//
// Two passes: (1) resolve rows (pinning hits) and adopt wire capacities,
// so a v1 delta EARLIER in the batch than a cap-carrying delta for the
// same row still sees the base (order parity with the batch-wide numpy
// adopt); (2) sanitize + classify.
//
// rows_out[i]: ≥0 = resolved row (PINNED — ownership passes to the queued
// chunk); -1 = miss (python binds + classifies the leftover subset);
// -2 = invalid (negative len / slot out of range), not pinned.
// out_scalar[i]: 0 = exact lane merge; 1 = scalar (deficit-attribution)
// merge; 2 = v1 delta whose row capacity was 0 at classify time — python
// re-checks after binding misses (which may adopt caps) and drops the
// still-unknown ones. Must be called under the directory lock.
int64_t pt_rx_classify(int h, int n, const uint64_t* hashes,
                       const uint8_t* name_buf, const int32_t* lens,
                       const double* added_f, const double* taken_f,
                       const uint64_t* elapsed_u, const int64_t* slots_in,
                       int64_t max_slots, const int64_t* caps,
                       const int64_t* lane_a, const int64_t* lane_t,
                       const uint8_t* no_trailer, int64_t* cap_base,
                       int32_t* pins, int64_t* last_used, int64_t now,
                       int64_t* rows_out, int64_t* out_added,
                       int64_t* out_taken, int64_t* out_elapsed,
                       uint8_t* out_scalar) {
  PtDir* d = g_dirs[h];
  if (!d) return -EBADF;
  int64_t hits = 0;
  // Pass 1 is a ROLLING 3-stage pipeline: every loop iteration i runs
  //   A(i):      validate, compute probe position, prefetch the probe line
  //   B(i-GAP):  probe (hash+row+len live in ONE PtSlot line), prefetch
  //              the candidate's name line + pins/cap_base/last_used
  //   C(i-2*GAP): byte-verify, pin, LRU stamp, adopt wire capacities
  // GAP is sized to the core's memory-level parallelism, not to a cache
  // block: this host sustains ~13 overlapped misses at ~200 ns DRAM
  // latency (scripts: /tmp-style pointer-chase probe, r3), so a prefetch
  // needs only ~10-15 iterations of other work to land. The r2 shape
  // (three separate loops over 256-delta blocks) issued hundreds of
  // prefetches ahead — beyond the prefetch queue, most were dropped and
  // the pass ran at near-serial DRAM latency (~440-600 ns/delta at 1M
  // rows). Rolling keeps ≤ ~5·GAP prefetches in flight.
  constexpr int kGap = 12;
  constexpr int kRing = 32;  // ≥ 2*kGap, power of two
  static_assert(kRing >= 2 * kGap, "ring must cover the pipeline depth");
  uint64_t pos[kRing];
  int32_t cand[kRing];
  for (int i = 0; i < n + 2 * kGap; i++) {
    if (i < n) {  // -- A
      out_scalar[i] = 0;
      // rows_out arrives as uninitialized np.empty storage — write every
      // entry here (the later passes branch on it).
      if (lens[i] < 0 || slots_in[i] < 0 || slots_in[i] >= max_slots) {
        rows_out[i] = -2;
      } else {
        rows_out[i] = -1;
        uint64_t p = hashes[i] & d->mask;
        pos[i & (kRing - 1)] = p;
        __builtin_prefetch(&d->tab[p]);
      }
    }
    int j = i - kGap;  // -- B
    if (j >= 0 && j < n && rows_out[j] != -2) {
      uint64_t hv = hashes[j];
      uint64_t p = pos[j & (kRing - 1)];
      int32_t c = -1;
      for (int pr = 0; pr < d->maxprobe; pr++) {
        const PtSlot& s = d->tab[p];
        if (s.row == -1) break;
        if (s.row >= 0 && s.h == hv && s.len == lens[j]) {
          c = s.row;
          break;
        }
        p = (p + 1) & d->mask;
      }
      cand[j & (kRing - 1)] = c;
      if (c >= 0) {
        __builtin_prefetch(d->name_bytes + (size_t)c * kPacketSize);
        __builtin_prefetch(&pins[c], 1);
        __builtin_prefetch(&cap_base[c], 1);
        __builtin_prefetch(&last_used[c], 1);
      }
    }
    int k = i - 2 * kGap;  // -- C
    if (k >= 0 && rows_out[k] != -2) {
      int32_t r = cand[k & (kRing - 1)];
      if (r >= 0 &&
          std::memcmp(d->name_bytes + (size_t)r * kPacketSize,
                      name_buf + (size_t)k * kPacketSize,
                      ((size_t)lens[k] + 7) & ~(size_t)7) == 0) {
        rows_out[k] = r;
        pins[r]++;
        last_used[r] = now;
        hits++;
        if (caps[k] > 0 && cap_base[r] == 0) cap_base[r] = caps[k];
      } else {
        rows_out[k] = -1;  // miss or collision: python slow path
      }
    }
  }
  // Pass 2: classify + per-batch (row, slot) CRDT dedup. Duplicate
  // (row, slot) entries in one batch fold into the FIRST occurrence by
  // elementwise max — exactly the join the device would compute, minus
  // the per-element-update scatter cost (~150 ns each on v5e, the merge
  // throughput ceiling). A hot-key storm collapses to one update per
  // lane per batch; uniform traffic pays one hash probe per delta.
  // Folding is valid across ALL classify codes: lane values join by max,
  // and scalar (deficit-attribution) deltas are monotone in their
  // aggregates, so the max aggregate subsumes the smaller one. Folded
  // entries get rows_out = -4 and their pin is RELEASED here (their
  // state rides the survivor's entry).
  constexpr uint32_t kDedupCap = 16384;  // ≥2× max batch, power of two
  static_assert((kDedupCap & (kDedupCap - 1)) == 0, "power of two");
  uint64_t dkeys[kDedupCap];
  int32_t didx[kDedupCap];
  // Table sized to the batch (next pow2 ≥ 2n): a small rx batch clears a
  // small prefix, not the whole 64 KB — the fixed clear would cost more
  // than the dedup saves under low/steady load.
  uint32_t dcap = 64;
  while (dcap < (uint32_t)(2 * n)) dcap <<= 1;
  // The key packs (row << 22 | slot << 2 | code): needs slot < 2^20 —
  // true for any sane lane count, but guard rather than alias buckets.
  bool dedup = dcap <= kDedupCap && max_slots <= (1 << 20);
  uint32_t dmask = dcap - 1;
  if (dedup)
    for (uint32_t i2 = 0; i2 < dcap; i2++) didx[i2] = -1;
  for (int i = 0; i < n; i++) {
    int64_t r = rows_out[i];
    if (r < 0) continue;
    int64_t a = sanitize_nt(added_f[i]);
    int64_t t = sanitize_nt(taken_f[i]);
    int64_t e = (int64_t)elapsed_u[i];
    out_elapsed[i] = e < 0 ? 0 : e;
    if (caps[i] >= 0) {
      if (lane_a[i] >= 0 && lane_t[i] >= 0) {
        out_added[i] = lane_a[i];  // exact PN lane values (lane trailer)
        out_taken[i] = lane_t[i];
      } else {
        a -= caps[i];  // aggregate header minus wire cap
        out_added[i] = a < 0 ? 0 : a;
        out_taken[i] = t;
        out_scalar[i] = 1;
      }
    } else if (no_trailer[i]) {
      int64_t base = cap_base[r];
      if (base == 0) {
        out_added[i] = a;  // python re-checks after miss binds adopt caps
        out_taken[i] = t;
        out_scalar[i] = 2;
      } else {
        a -= base;
        out_added[i] = a < 0 ? 0 : a;
        out_taken[i] = t;
        out_scalar[i] = 1;
      }
    } else {
      out_added[i] = a;  // base-trailer peer: raw own-lane header
      out_taken[i] = t;
    }
    if (!dedup) continue;
    // The classify code is part of the key: entries fold only with the
    // same code (mixed joins are left to the kernel), and a lone
    // different-code entry must not block a same-code storm behind it.
    uint64_t key = ((uint64_t)r << 22) | ((uint64_t)slots_in[i] << 2) |
                   (uint64_t)out_scalar[i];
    // Fibonacci hashing: the product's entropy lives in its HIGH bits,
    // so fold them down before masking. Masking the raw product (the r2
    // code) kept only bits the key's low 14 bits determine — i.e. only
    // (slot, code) — so any batch with few distinct slots collapsed into
    // a handful of probe chains and the dedup pass went O(n^2) (~390
    // ns/delta measured at n=8192 with 4 slots; ~15 ns/delta fixed).
    uint64_t prod = key * 0x9E3779B97F4A7C15ULL;
    uint64_t pos = (prod ^ (prod >> 32)) & dmask;
    while (true) {
      int32_t j = didx[pos];
      if (j < 0) {
        dkeys[pos] = key;
        didx[pos] = i;
        break;
      }
      if (dkeys[pos] == key) {
        if (out_added[i] > out_added[j]) out_added[j] = out_added[i];
        if (out_taken[i] > out_taken[j]) out_taken[j] = out_taken[i];
        if (out_elapsed[i] > out_elapsed[j]) out_elapsed[j] = out_elapsed[i];
        rows_out[i] = -4;
        pins[r]--;  // the survivor keeps the row pinned
        break;
      }
      pos = (pos + 1) & dmask;
    }
  }
  return hits;
}

int pt_dir_destroy(int h) {
  std::lock_guard<std::mutex> reg(g_dir_mu);
  PtDir* d = g_dirs[h];
  if (!d) return -EBADF;
  g_dirs[h] = nullptr;
  delete d;
  return 0;
}

}  // extern "C"

// ---- Native fold-to-dense hybrid (VERDICT r4 item 6) ----------------------
//
// The engine's hot-key path was fold-dominated: 131k deltas for one row
// cost ~6.1 ms of single-threaded numpy (lexsort + reduceat) against a
// ~0.2 ms device commit. This is the C++ fold: one pass over the batch
// into per-row lane blocks (dense accumulate + touched bitmap), threaded
// across cores for large batches — grouping work the clustered/hot-key
// shapes need WITHOUT a sort. The uniform shape (distinct rows ≈ batch)
// intentionally bails to the numpy path: per-row blocks would allocate
// rows×nodes, and that shape is scatter-bound anyway.

namespace {

struct FoldRowAcc {
  int64_t* lanes = nullptr;   // [nodes, 2] max-joined values
  uint64_t* bits = nullptr;   // touched-slot bitmap
  int64_t elapsed = 0;
  int64_t touched = 0;
};

struct FoldShard {
  std::unordered_map<int64_t, FoldRowAcc> map;
  std::vector<std::unique_ptr<int64_t[]>> lane_arena;
  std::vector<std::unique_ptr<uint64_t[]>> bit_arena;
  bool aborted = false;
};

void fold_shard(const int64_t* rows, const int64_t* slots,
                const int64_t* added, const int64_t* taken,
                const int64_t* elapsed, int64_t lo, int64_t hi,
                int64_t nodes, int64_t max_distinct, int64_t bit_words,
                FoldShard* sh) {
  auto& map = sh->map;
  for (int64_t i = lo; i < hi; i++) {
    int64_t slot = slots[i];
    if (slot < 0 || slot >= nodes) {
      sh->aborted = true;  // malformed: let the python path handle it
      return;
    }
    auto it = map.find(rows[i]);
    if (it == map.end()) {
      if ((int64_t)map.size() >= max_distinct) {
        sh->aborted = true;  // uniform shape: numpy path is the right tool
        return;
      }
      sh->lane_arena.emplace_back(new int64_t[nodes * 2]());
      sh->bit_arena.emplace_back(new uint64_t[bit_words]());
      FoldRowAcc acc;
      acc.lanes = sh->lane_arena.back().get();
      acc.bits = sh->bit_arena.back().get();
      it = map.emplace(rows[i], acc).first;
    }
    FoldRowAcc& a = it->second;
    uint64_t w = (uint64_t)slot >> 6, b = 1ULL << (slot & 63);
    if (!(a.bits[w] & b)) {
      a.bits[w] |= b;
      a.touched++;
    }
    int64_t* lane = a.lanes + slot * 2;
    if (added[i] > lane[0]) lane[0] = added[i];
    if (taken[i] > lane[1]) lane[1] = taken[i];
    if (elapsed[i] > a.elapsed) a.elapsed = elapsed[i];
  }
}

}  // namespace

extern "C" {

// → 0 ok, -1 fall back to the numpy fold (too many distinct rows or a
// malformed slot). out_counts = {n_sparse_pairs, n_sparse_rows, n_dense}.
// Dense rows beyond cap_dense spill to the sparse outputs in ascending
// row order — the same first-cap selection as the numpy hybrid.
int pt_fold_hybrid(const int64_t* rows, const int64_t* slots,
                   const int64_t* added, const int64_t* taken,
                   const int64_t* elapsed, int64_t n, int64_t nodes,
                   int64_t row_dense_min, int64_t max_distinct,
                   int64_t* d_rows, int64_t* d_upd, int64_t* d_el,
                   int64_t cap_dense, int64_t* sp_rows, int64_t* sp_slots,
                   int64_t* sp_a, int64_t* sp_t, int64_t* sp_er,
                   int64_t* sp_e, int64_t* out_counts) {
  if (n <= 0 || nodes <= 0) return -1;
  const int64_t bit_words = (nodes + 63) / 64;
  unsigned hw = std::thread::hardware_concurrency();
  int T = (n >= 65536 && hw > 1) ? (int)std::min<unsigned>(hw, 8) : 1;
  // Test/tuning override: force the shard count (exercises the shard
  // merge on single-core boxes; 0/unset = auto).
  if (const char* tf = getenv("PATROL_FOLD_THREADS")) {
    int v = atoi(tf);
    if (v > 0) T = std::min(v, 8);
  }
  std::vector<FoldShard> shards((size_t)T);
  if (T == 1) {
    fold_shard(rows, slots, added, taken, elapsed, 0, n, nodes,
               max_distinct, bit_words, &shards[0]);
  } else {
    std::vector<std::thread> ts;
    int64_t step = (n + T - 1) / T;
    for (int t = 0; t < T; t++) {
      int64_t lo = t * step, hi = std::min<int64_t>(n, lo + step);
      if (lo >= hi) break;
      ts.emplace_back(fold_shard, rows, slots, added, taken, elapsed, lo,
                      hi, nodes, max_distinct, bit_words, &shards[t]);
    }
    for (auto& t : ts) t.join();
  }
  for (auto& sh : shards)
    if (sh.aborted) return -1;
  // Merge shards 1..T-1 into shard 0 (small maps: ≤ max_distinct rows).
  FoldShard& m = shards[0];
  for (int t = 1; t < T; t++) {
    for (auto& kv : shards[t].map) {
      auto it = m.map.find(kv.first);
      if (it == m.map.end()) {
        if ((int64_t)m.map.size() >= max_distinct) return -1;
        m.lane_arena.emplace_back(new int64_t[nodes * 2]());
        m.bit_arena.emplace_back(new uint64_t[bit_words]());
        FoldRowAcc acc;
        acc.lanes = m.lane_arena.back().get();
        acc.bits = m.bit_arena.back().get();
        it = m.map.emplace(kv.first, acc).first;
      }
      FoldRowAcc& a = it->second;
      const FoldRowAcc& b = kv.second;
      for (int64_t w = 0; w < bit_words; w++) a.bits[w] |= b.bits[w];
      for (int64_t j = 0; j < nodes * 2; j++)
        if (b.lanes[j] > a.lanes[j]) a.lanes[j] = b.lanes[j];
      if (b.elapsed > a.elapsed) a.elapsed = b.elapsed;
      a.touched = 0;
      for (int64_t w = 0; w < bit_words; w++)
        a.touched += __builtin_popcountll(a.bits[w]);
    }
  }
  // Emit in ascending row order (the numpy fold's sorted invariant).
  std::vector<std::pair<int64_t, const FoldRowAcc*>> ordered;
  ordered.reserve(m.map.size());
  for (auto& kv : m.map) ordered.emplace_back(kv.first, &kv.second);
  std::sort(ordered.begin(), ordered.end(),
            [](const auto& x, const auto& y) { return x.first < y.first; });
  int64_t np = 0, nr = 0, nd = 0;
  for (auto& [row, acc] : ordered) {
    if (acc->touched >= row_dense_min && nd < cap_dense) {
      d_rows[nd] = row;
      d_el[nd] = acc->elapsed;
      std::memcpy(d_upd + nd * nodes * 2, acc->lanes,
                  sizeof(int64_t) * nodes * 2);
      nd++;
      continue;
    }
    for (int64_t w = 0; w < bit_words; w++) {
      uint64_t bits = acc->bits[w];
      while (bits) {
        int64_t slot = w * 64 + __builtin_ctzll(bits);
        bits &= bits - 1;
        sp_rows[np] = row;
        sp_slots[np] = slot;
        sp_a[np] = acc->lanes[slot * 2];
        sp_t[np] = acc->lanes[slot * 2 + 1];
        np++;
      }
    }
    sp_er[nr] = row;
    sp_e[nr] = acc->elapsed;
    nr++;
  }
  out_counts[0] = np;
  out_counts[1] = nr;
  out_counts[2] = nd;
  return 0;
}

}  // extern "C"
