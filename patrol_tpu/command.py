"""Process supervisor (reference: ``Command``, command.go:17-83).

Wires storage (device engine) + replication (UDP) + API (HTTP) into one
process and supervises them — the reference's ``oklog/run`` actor group
becomes an asyncio task group with signal handling and a graceful-shutdown
timeout. Used by both the CLI (cmd/patrol/main.go) and the in-process
multi-node cluster tests (≙ command_test.go:13-77).
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import logging
import signal
from typing import List, Optional

from patrol_tpu.models.limiter import LimiterConfig, SMALL
from patrol_tpu.net.api import API, serve
from patrol_tpu.net.replication import Replicator, SlotTable
from patrol_tpu.runtime.bucket import ClockFn, system_clock
from patrol_tpu.runtime.engine import DeviceEngine
from patrol_tpu.runtime.repo import TPURepo


@dataclasses.dataclass
class Command:
    """All runtime config funnels into this struct (≙ command.go:18-25),
    which doubles as the test-harness entry point."""

    api_addr: str = "127.0.0.1:8080"
    node_addr: str = "127.0.0.1:16000"
    # Human-meaningful node identity for fleet views (patrol-fleet lane
    # attribution: /debug/vars histogram summaries, /cluster/* labels).
    # Defaults to node_addr.
    node_name: str = ""
    peer_addrs: List[str] = dataclasses.field(default_factory=list)
    clock: ClockFn = system_clock  # the injected-clock seam (command.go:23)
    shutdown_timeout_s: float = 30.0
    config: LimiterConfig = SMALL
    log: Optional[logging.Logger] = None
    handle_signals: bool = True
    # "native" = C++ recvmmsg/sendmmsg path, "asyncio" = pure python,
    # "auto" = native when the toolchain built it, else asyncio.
    udp_backend: str = "auto"
    # Outgoing wire form: "delta" (the DEFAULT since the wire-v2 bake:
    # batched delta-interval datagrams to capability-advertising peers,
    # aggregate full-state to the rest — the handshake keeps mixed
    # v1/v2 clusters safe), "full"/"aggregate" (the per-take full-state
    # opt-out; dual-payload headers, flag-day upgrade from
    # pre-lane-trailer patrol_tpu builds), or "compat" (raw own-lane
    # headers for rolling upgrades). See ops/wire.py and net/delta.py.
    wire_mode: str = "delta"
    # HTTP front: "native" = C++ epoll front (net/native_http.py) — the
    # /take decision runs entirely in-process for host-resident buckets
    # (the reference's performance class, api.go:51-86) and h2c clients
    # splice to a loopback python h2 server, so protocol parity holds;
    # "python" = asyncio server, the protocol-reference implementation;
    # "auto" (default) = native when the toolchain built it, else python.
    # r4 kept python as default for h2c; the r5 in-front take path plus
    # the h2c splice makes native strictly better when available.
    http_front: str = "auto"
    # Checkpoint/resume (the reference has none, SURVEY §5): restore at
    # boot when the directory holds a snapshot; save every interval (0 ⇒
    # only at shutdown) and at graceful shutdown.
    checkpoint_dir: Optional[str] = None
    checkpoint_interval_s: float = 0.0
    # Pre-compile all kernel batch variants at boot (kills JIT p99 spikes;
    # adds seconds to startup — off for tests, on for production/bench).
    warmup: bool = False
    # Multi-device: >0 runs the MeshEngine over all local devices with this
    # many full replicas (the rest of the devices become bucket shards);
    # 0 = single-device engine.
    mesh_replicas: int = 0

    # Populated by run() for tests/introspection.
    engine: Optional[DeviceEngine] = None
    repo: Optional[TPURepo] = None
    replicator: Optional[Replicator] = None
    # Set by run() once every socket is bound and the API is accepting —
    # the deterministic "serving" signal for supervisors and tests
    # (awaitable immediately after construction; cleared when run() begins).
    started: asyncio.Event = dataclasses.field(default_factory=asyncio.Event)

    async def run(self, stop: Optional[asyncio.Event] = None) -> None:
        """Run until ``stop`` is set or SIGINT/SIGTERM arrives; then shut
        down gracefully (drain HTTP, stop engine) within the timeout
        (command.go:46-82)."""
        if self.shutdown_timeout_s <= 0:
            raise ValueError("shutdown_timeout_s must be set")
        log = self.log or logging.getLogger("patrol")
        stop = stop or asyncio.Event()
        self.started.clear()

        from patrol_tpu.runtime import checkpoint as ckpt

        # Rejoin pinning (patrol-membership): a restarting node must come
        # back on its ORIGINAL lane — its checkpointed PN spend lives
        # there — even when its peer list changed (rolling restart under a
        # new address). The checkpoint's membership meta carries the lane;
        # without it, rank-order assignment could hand self a different
        # lane and strand the restored spend where stale echoes absorb it.
        self_slot = None
        mem = None
        if self.checkpoint_dir and ckpt.exists(self.checkpoint_dir):
            mem = ckpt.load_membership(self.checkpoint_dir)
            if mem is not None and isinstance(mem.get("self_slot"), int):
                self_slot = mem["self_slot"]
        slots = SlotTable(
            self.node_addr,
            self.peer_addrs,
            max_slots=self.config.nodes,
            self_slot=self_slot,
        )
        if mem is not None:
            # The epoch counter survives restarts (monotone; a reborn
            # admin must never re-issue historical epochs).
            slots.restore_epoch(mem.get("epoch"))
        from patrol_tpu.utils import histogram as hist_mod

        # Node identity rides every histogram summary and gossip packet,
        # so merged fleet views attribute lanes without guessing.
        node_name = self.node_name or self.node_addr
        hist_mod.set_node_identity(slots.self_slot, node_name)
        http_front = self.http_front
        if http_front == "auto":
            from patrol_tpu.net import native_http as _nh

            http_front = "native" if _nh.available() else "python"
        if self.mesh_replicas > 0:
            from patrol_tpu.runtime.mesh_engine import MeshEngine

            engine = MeshEngine(
                self.config,
                replicas=self.mesh_replicas,
                node_slot=slots.self_slot,
                clock=self.clock,
            )
        else:
            engine = DeviceEngine(
                self.config,
                node_slot=slots.self_slot,
                clock=self.clock,
                # Native front ⇒ host-resident lanes live in the C++ store
                # and /take is served on the epoll thread (api.go:51-86's
                # in-process shape); python front keeps the pure-Python
                # host tier.
                native_host=(http_front == "native"),
            )

        from patrol_tpu.net import native_replication

        use_native = self.udp_backend == "native" or (
            self.udp_backend == "auto" and native_replication.available()
        )
        if use_native:
            replicator = native_replication.NativeReplicator(
                self.node_addr, self.peer_addrs, slots, log_=log,
                wire_mode=self.wire_mode,
            )
        else:
            replicator = await Replicator.create(
                self.node_addr, self.peer_addrs, slots, log=log,
                wire_mode=self.wire_mode,
            )
        repo = TPURepo(engine, send_incast=replicator.send_incast_request)
        replicator.repo = repo
        engine.on_broadcast = replicator.broadcast_states
        if getattr(replicator, "fleet", None) is not None:
            replicator.fleet.set_identity(node_name)

        if self.checkpoint_dir and ckpt.exists(self.checkpoint_dir):
            n = ckpt.restore(self.checkpoint_dir, engine)
            log.info("checkpoint restored", extra={"buckets": n, "dir": self.checkpoint_dir})

        if self.warmup:
            t0 = asyncio.get_running_loop().time()
            await asyncio.get_running_loop().run_in_executor(None, engine.warmup)
            log.info(
                "kernels warmed",
                extra={"seconds": round(asyncio.get_running_loop().time() - t0, 2)},
            )
        log.debug(
            "peers",
            extra={
                "self": self.node_addr,
                "slot": slots.self_slot,
                "others": [f"{h}:{p}" for h, p in replicator.peers],
            },
        )

        def stats() -> dict:
            from patrol_tpu.utils import histogram as hist_mod
            from patrol_tpu.utils import profiling

            return {
                "engine_ticks": engine.ticks,
                "engine_evictions": engine.evictions,
                "engine_scalar_dropped": engine.scalar_dropped,
                "engine_pending_completions": engine.pending_completions,
                "engine_hosted_buckets": engine.hosted_buckets,
                "engine_host_takes": engine.host_takes,
                "engine_promotions": engine.promotions,
                "engine_demotions": engine.demotions,
                "buckets": len(engine.directory),
                "node_slot": slots.self_slot,
                # Bucket lifecycle (idle-bucket GC + memory budget):
                # live gauges — reclaim/shed/compaction counts, bytes in
                # use vs budget, tombstones, pressure level.
                **engine.lifecycle_stats(),
                # Mesh serving (MeshEngine only): replica/shard geometry,
                # fused-dispatch accounting, and the machine-readable
                # `mesh_demotion: unsupported` residency constraint.
                **(engine.stats() if hasattr(engine, "stats") else {}),
                # Device-commit pipeline counters (staging reuse, commit
                # coalescing, dispatch-ahead depth, rx staging).
                **profiling.COUNTERS.snapshot(),
                **replicator.stats(),
                # patrol-scope latency histograms (count/p50/p99/max per
                # stage) — the /debug/vars view; /metrics exposes the
                # full cumulative-bucket form of the same histograms.
                "histograms": hist_mod.HISTOGRAMS.snapshot(),
            }

        api = API(repo, log=log, stats=stats)
        # /cluster/* (patrol-fleet): served from the replicator's gossip
        # store — any node answers for the fleet.
        api.fleet = getattr(replicator, "fleet", None)
        # /debug/audit (patrol-audit): the consistency plane's gauges.
        api.audit = getattr(replicator, "audit", None)
        # /admin/peers (patrol-membership): runtime join/leave/rejoin.
        api.membership = getattr(replicator, "membership", None)
        host, _, port = self.api_addr.rpartition(":")
        native_front = None
        server = None
        if http_front == "native":
            from patrol_tpu.net import native_http

            native_front = native_http.NativeHTTPFront(
                api, host or "127.0.0.1", int(port)
            )
            # h2c parity (command.go:41-44): a loopback python h2 server
            # receives preface-bearing connections spliced through the
            # C++ front, so `--http-front native` speaks BOTH protocols
            # (h1 on the fast path, h2 at the python front's throughput).
            server = await serve(api, "127.0.0.1", 0)
            h2_port = server.sockets[0].getsockname()[1]
            native_front.set_h2_backend(h2_port)
            base_stats = stats

            def stats_with_http() -> dict:  # /debug/vars includes the front
                return {
                    **base_stats(),
                    **native_front.stats(),
                    "h2_backend_port": h2_port,
                }

            api.stats = stats_with_http
        else:
            server = await serve(api, host or "127.0.0.1", int(port))

        self.engine, self.repo, self.replicator = engine, repo, replicator

        if self.handle_signals:
            loop = asyncio.get_running_loop()
            for sig in (signal.SIGINT, signal.SIGTERM):
                with contextlib.suppress(NotImplementedError, RuntimeError):
                    loop.add_signal_handler(sig, stop.set)

        log.info("API serving", extra={"addr": self.api_addr})
        self.started.set()

        # Membership meta rides every checkpoint so a restart (possibly
        # under a new address) can pin itself back onto its original lane.
        def _membership_meta():
            mem = getattr(replicator, "membership", None)
            return mem.view() if mem is not None else None

        ckpt_task = None
        if self.checkpoint_dir and self.checkpoint_interval_s > 0:
            loop = asyncio.get_running_loop()

            async def _periodic_checkpoint():
                while True:
                    await asyncio.sleep(self.checkpoint_interval_s)
                    try:
                        await loop.run_in_executor(
                            None,
                            ckpt.save,
                            self.checkpoint_dir,
                            engine,
                            _membership_meta(),
                        )
                    except Exception:  # pragma: no cover
                        log.exception("periodic checkpoint failed")

            ckpt_task = asyncio.ensure_future(_periodic_checkpoint())

        try:
            await stop.wait()
        finally:
            if ckpt_task is not None:
                ckpt_task.cancel()
            if self.checkpoint_dir:
                try:
                    ckpt.save(self.checkpoint_dir, engine, _membership_meta())
                    log.info("checkpoint saved", extra={"dir": self.checkpoint_dir})
                except Exception:  # pragma: no cover
                    log.exception("final checkpoint failed")
            log.info("shutting down")
            # Graceful-shutdown flush: re-broadcast the final state of
            # recently-active buckets (bounded, paced) BEFORE the transport
            # closes, so a clean restart doesn't silently shed recent takes
            # whose last organic broadcast was lost. Best-effort: any
            # failure degrades to the old behavior (peers re-learn the
            # state via incast on next contact).
            try:
                states = (
                    engine.drain_dirty_states(limit=1024)
                    if replicator.peers
                    else []
                )
                for lo in range(0, len(states), 64):
                    replicator.broadcast_states(states[lo : lo + 64])
                    await asyncio.sleep(0.002)  # pace; lets the loop send
                if states:
                    from patrol_tpu.utils import profiling

                    profiling.COUNTERS.inc("shutdown_flush_states", len(states))
                    log.info(
                        "shutdown flush", extra={"states": len(states)}
                    )
            except Exception:  # pragma: no cover
                log.exception("shutdown flush failed")
            if server is not None:
                server.close()
                with contextlib.suppress(asyncio.TimeoutError):
                    await asyncio.wait_for(
                        server.wait_closed(), timeout=self.shutdown_timeout_s
                    )
            if native_front is not None:
                await asyncio.get_running_loop().run_in_executor(
                    None, native_front.close
                )
            replicator.close()
            engine.stop()
            for handler in (self.log.handlers if self.log else []):
                with contextlib.suppress(Exception):
                    handler.flush()  # ≙ Log.Sync() (command.go:38)
            self.started.clear()  # no stale "serving" signal after shutdown
