"""Minimal pprof-protobuf writer — makes ``/debug/pprof/profile`` emit the
same artifact class as the reference's ``net/http/pprof`` (api.go:29-39):
a gzipped ``perftools.profiles.Profile`` message that ``go tool pprof``
and speedscope open directly.

Only the writer half of profile.proto is needed, and only five message
types (Profile, ValueType, Sample, Location+Line, Function), so this is a
hand-rolled protobuf encoder rather than a generated binding — protoc
output would be 50× the code for the same bytes. Wire format reference:
protobuf encoding docs; message schema: github.com/google/pprof
proto/profile.proto (stable since 2016).

Input model: a Counter over *stack tuples*, each stack a tuple of frames
leaf-first, each frame ``(function_name, filename, line)`` — exactly what
:class:`patrol_tpu.utils.profiling.SamplingProfiler` collects.
"""

from __future__ import annotations

import gzip
import time
from collections import Counter
from typing import Dict, Tuple

Frame = Tuple[str, str, int]  # (function qualname, filename, line)


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_varint(num: int, val: int) -> bytes:
    if not val:
        return b""  # proto3 default elision
    return _varint(num << 3) + _varint(val)


def _field_bytes(num: int, data: bytes) -> bytes:
    return _varint((num << 3) | 2) + _varint(len(data)) + data


def _value_type(type_idx: int, unit_idx: int) -> bytes:
    return _field_varint(1, type_idx) + _field_varint(2, unit_idx)


def build_profile_values(
    samples: Dict[tuple, Tuple[int, ...]],
    period_ns: int,
    duration_ns: int,
    sample_type: Tuple[Tuple[str, str], ...],
    period_type: Tuple[str, str] = ("cpu", "nanoseconds"),
) -> bytes:
    """Encode stack → value-tuple samples as a gzipped pprof Profile —
    the general writer behind the CPU, mutex, and block profiles. Each
    value tuple must be parallel to ``sample_type``."""
    strings: Dict[str, int] = {"": 0}

    def s(v: str) -> int:
        idx = strings.get(v)
        if idx is None:
            idx = strings[v] = len(strings)
        return idx

    functions: Dict[Tuple[str, str], int] = {}  # (name, file) -> id
    locations: Dict[Frame, int] = {}
    func_msgs = []
    loc_msgs = []

    def location_id(frame: Frame) -> int:
        lid = locations.get(frame)
        if lid is not None:
            return lid
        name, filename, line = frame
        fkey = (name, filename)
        fid = functions.get(fkey)
        if fid is None:
            fid = functions[fkey] = len(functions) + 1
            func_msgs.append(
                _field_varint(1, fid)
                + _field_varint(2, s(name))
                + _field_varint(3, s(name))
                + _field_varint(4, s(filename))
            )
        lid = locations[frame] = len(locations) + 1
        line_msg = _field_varint(1, fid) + _field_varint(2, line)
        loc_msgs.append(_field_varint(1, lid) + _field_bytes(4, line_msg))
        return lid

    sample_msgs = []
    for stack, values in samples.items():
        loc_ids = b"".join(_varint(location_id(f)) for f in stack)
        packed = b"".join(_varint(v) for v in values)
        # location_id (field 1) and value (field 2) are packed repeated.
        sample_msgs.append(_field_bytes(1, loc_ids) + _field_bytes(2, packed))

    out = bytearray()
    for t, u in sample_type:
        out += _field_bytes(1, _value_type(s(t), s(u)))
    for m in sample_msgs:
        out += _field_bytes(2, m)
    for m in loc_msgs:
        out += _field_bytes(4, m)
    for m in func_msgs:
        out += _field_bytes(5, m)
    # string_table: every index in insertion order (dict preserves it).
    for v in strings:
        out += _field_bytes(6, v.encode("utf-8", errors="replace"))
    out += _field_varint(9, time.time_ns())  # patrol-lint: clock-seam (pprof)
    out += _field_varint(10, duration_ns)
    out += _field_bytes(11, _value_type(s(period_type[0]), s(period_type[1])))
    out += _field_varint(12, period_ns)
    return gzip.compress(bytes(out))


def build_profile(
    stacks: Counter,
    period_ns: int,
    duration_ns: int,
    sample_type: Tuple[Tuple[str, str], ...] = (
        ("samples", "count"),
        ("cpu", "nanoseconds"),
    ),
) -> bytes:
    """Encode sampled stacks as a gzipped pprof Profile.

    Each stack's values are ``[count, count * period_ns]`` matching the
    default ``(samples/count, cpu/nanoseconds)`` sample types — the shape
    Go's sampled CPU profile uses, so pprof's top/graph/flame views all
    aggregate correctly.
    """
    return build_profile_values(
        {
            stack: (count, count * period_ns)
            for stack, count in stacks.most_common()
        },
        period_ns=period_ns,
        duration_ns=duration_ns,
        sample_type=sample_type,
    )
