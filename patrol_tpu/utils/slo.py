"""SLO sentinel: burn-rate / stage-budget watchdogs over the patrol-scope
latency histograms, auto-firing the flight recorder's anomaly snapshots.

patrol-scope records *what happened*; this module decides *when it is
bad enough to freeze evidence*. Two breach classes, both computed from
cumulative histogram deltas between checks (so a check is O(histograms ×
buckets) integer work — no sampling, no timers):

* **take-latency burn rate** — the fraction of takes in the window since
  the last check that exceeded the take budget. A window burning past
  ``max_burn`` fires ``anomaly("slo.take_burn")``, which snapshots every
  thread's flight-recorder ring (damped to 1/reason/s by the recorder).
* **stage-budget overrun** — any commit-pipeline or device stage whose
  window p99 exceeds its budget fires ``anomaly("slo.stage_budget")``.
* **AP-overshoot** (patrol-audit, net/audit.py) — when the measured
  admitted-token overshoot factor of the last evaluated audit window
  exceeds ``PATROL_SLO_OVERSHOOT × partition-sides-estimate``, the
  sentinel fires ``anomaly("slo.overshoot")``: admission multiplied
  beyond what the observed partition explains is evidence worth
  freezing. Enabled by setting ``PATROL_SLO_OVERSHOOT`` > 0 (1.0 = the
  paper's AP bound exactly: overshoot must not exceed the sides
  estimate).

Budgets default OFF (0 = disabled) so an unconfigured process never
snapshots itself; set them via environment (``PATROL_SLO_TAKE_P99_NS``,
``PATROL_SLO_STAGE_P99_NS``, ``PATROL_SLO_OVERSHOOT``) or
programmatically (tests, operators). The check is driven by the fleet
gossip flusher (net/fleet.py) — the same paced observability tick that
ships the histograms — by the audit plane's own tick
(:meth:`SloSentinel.check_audit`), and by ``bench.py --trend``.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from patrol_tpu.utils import config
from patrol_tpu.utils import histogram as hist
from patrol_tpu.utils import profiling


# Observations in buckets strictly ABOVE this index are guaranteed over
# the budget (bucket b holds [2^(b-1), 2^b)); the budget's own bucket may
# contain under-budget values, so it is not counted — conservative, never
# a false breach from bucketing.
def _over_bucket(budget_ns: int) -> int:
    return hist.bucket_of(max(budget_ns, 0))


class SloSentinel:
    """Windowed breach detector. ``check()`` compares each watched
    histogram's cumulative bucket counts against the last check's
    snapshot; the difference is the window. Thread-safe; one instance
    per process (``SENTINEL``)."""

    def __init__(
        self,
        take_budget_ns: Optional[int] = None,
        stage_budget_ns: Optional[int] = None,
        max_burn: float = 0.10,
        min_samples: int = 16,
        overshoot_budget: Optional[float] = None,
    ):
        self.take_budget_ns = (
            config.env_int("PATROL_SLO_TAKE_P99_NS")
            if take_budget_ns is None
            else take_budget_ns
        )
        self.stage_budget_ns = (
            config.env_int("PATROL_SLO_STAGE_P99_NS")
            if stage_budget_ns is None
            else stage_budget_ns
        )
        self.overshoot_budget = (
            config.env_float("PATROL_SLO_OVERSHOOT")
            if overshoot_budget is None
            else overshoot_budget
        )
        self.max_burn = max_burn
        self.min_samples = min_samples
        self._mu = threading.Lock()
        self._last: Dict[str, List[int]] = {}
        self.breaches = 0
        # Bucket-lifecycle budget provider (engine._budget_snapshot):
        # registered when a memory budget is configured, polled on every
        # check — a hard-watermark breach freezes evidence exactly like a
        # latency burn.
        self._budget_src: Optional[Callable[[], dict]] = None
        # patrol-audit overshoot provider (AuditPlane._slo_snapshot):
        # last evaluated window's measured factor + sides estimate.
        self._audit_src: Optional[Callable[[], dict]] = None
        # The last (window, factor) breach fired, so one bad window does
        # not re-fire on every subsequent check.
        self._audit_fired: Optional[tuple] = None

    def watch_budget(self, provider: Callable[[], dict]) -> None:
        """Register the engine's memory-budget snapshot provider (dict
        with ``over`` plus the accounting gauges). Latest engine wins —
        one process serves one engine."""
        with self._mu:
            self._budget_src = provider

    def unwatch_budget(self, provider: Callable[[], dict]) -> None:
        """Engine shutdown: drop the provider IF it is still ours (a
        replacement engine's registration must survive). Equality, not
        identity: bound methods are fresh objects per attribute access —
        ``==`` compares (instance, function)."""
        with self._mu:
            if self._budget_src == provider:
                self._budget_src = None

    def watch_audit(self, provider: Callable[[], dict]) -> None:
        """Register the audit plane's overshoot provider (dict with
        ``overshoot``, ``sides``, ``window``). Latest plane wins."""
        with self._mu:
            self._audit_src = provider

    def unwatch_audit(self, provider: Callable[[], dict]) -> None:
        """Audit plane shutdown: drop the provider IF still ours (same
        equality contract as :meth:`unwatch_budget`)."""
        with self._mu:
            if self._audit_src == provider:
                self._audit_src = None

    def configure(
        self,
        take_budget_ns: Optional[int] = None,
        stage_budget_ns: Optional[int] = None,
        max_burn: Optional[float] = None,
        min_samples: Optional[int] = None,
        overshoot_budget: Optional[float] = None,
    ) -> None:
        with self._mu:
            if take_budget_ns is not None:
                self.take_budget_ns = take_budget_ns
            if stage_budget_ns is not None:
                self.stage_budget_ns = stage_budget_ns
            if max_burn is not None:
                self.max_burn = max_burn
            if min_samples is not None:
                self.min_samples = min_samples
            if overshoot_budget is not None:
                self.overshoot_budget = overshoot_budget

    def _window(self, name: str, counts: List[int]) -> List[int]:
        """Per-bucket deltas since the last check (counts are cumulative
        monotone, so the delta is exact). First sight seeds the baseline
        and reports an empty window — budgets judge fresh traffic only."""
        last = self._last.get(name)
        self._last[name] = list(counts)
        if last is None:
            return [0] * len(counts)
        return [max(0, c - l) for c, l in zip(counts, last)]

    def _burn(self, window: List[int], budget_ns: int) -> tuple:
        total = sum(window)
        over = sum(window[_over_bucket(budget_ns) + 1 :])
        return total, (over / total if total else 0.0)

    def check(
        self, registry: Optional[hist.HistogramRegistry] = None
    ) -> List[dict]:
        """One sentinel pass; returns the breaches found (and fires an
        anomaly snapshot per breach class)."""
        from patrol_tpu.utils import trace as trace_mod

        reg = registry if registry is not None else hist.HISTOGRAMS
        breaches: List[dict] = []
        with self._mu:
            if self.take_budget_ns > 0:
                h = reg.get("take_service_ns")
                total, burn = self._burn(
                    self._window("take_service_ns", h._merged_counts()),
                    self.take_budget_ns,
                )
                if total >= self.min_samples and burn > self.max_burn:
                    breaches.append(
                        {
                            "kind": "take_burn",
                            "stage": "take_service_ns",
                            "window": total,
                            "burn": round(burn, 4),
                            "budget_ns": self.take_budget_ns,
                        }
                    )
            if self.stage_budget_ns > 0:
                for name in hist.INGEST_STAGES + hist.DEVICE_STAGES:
                    h = reg.get(name)
                    window = self._window(name, h._merged_counts())
                    total, burn = self._burn(window, self.stage_budget_ns)
                    if total >= self.min_samples and burn > 0.01:
                        # p99 over budget ⇔ >1% of the window's samples
                        # landed in buckets strictly above it.
                        breaches.append(
                            {
                                "kind": "stage_budget",
                                "stage": name,
                                "window": total,
                                "burn": round(burn, 4),
                                "budget_ns": self.stage_budget_ns,
                            }
                        )
            breaches.extend(self._audit_breach_locked())
            budget_src = self._budget_src
            if budget_src is not None:
                try:
                    snap = budget_src()
                except Exception:  # pragma: no cover - provider must not kill checks
                    snap = None
                if snap and snap.get("over"):
                    breaches.append(
                        {
                            "kind": "budget",
                            "stage": "state_bytes",
                            "window": 1,
                            "burn": 1.0,
                            "budget_ns": 0,
                            **{
                                k: snap.get(k, 0)
                                for k in (
                                    "state_bytes_in_use",
                                    "state_bytes_budget",
                                    "buckets_bound",
                                    "max_buckets",
                                )
                            },
                        }
                    )
            if breaches:
                self.breaches += len(breaches)
        for kind in sorted({b["kind"] for b in breaches}):
            profiling.COUNTERS.inc("slo_breaches")
            trace_mod.anomaly(f"slo.{kind}")
        return breaches

    def _audit_breach_locked(self) -> List[dict]:
        """The AP-overshoot budget (patrol-audit): breach when the last
        evaluated window's measured factor exceeds ``overshoot_budget ×
        sides-estimate``. Caller holds ``_mu``. Fires once per (window,
        factor) — a standing bad window must not re-snapshot every tick."""
        if self.overshoot_budget <= 0 or self._audit_src is None:
            return []
        try:
            snap = self._audit_src()
        except Exception:  # pragma: no cover - provider must not kill checks
            return []
        factor = float(snap.get("overshoot", 0.0))
        sides = max(int(snap.get("sides", 1)), 1)
        window = snap.get("window", -1)
        bound = self.overshoot_budget * sides
        key = (window, round(factor, 6))
        if factor <= bound or window < 0 or self._audit_fired == key:
            return []
        self._audit_fired = key
        profiling.COUNTERS.inc("audit_overshoot_breaches")
        return [
            {
                "kind": "overshoot",
                "stage": "audit_overshoot_factor",
                "window": window,
                "burn": round(factor, 4),
                "budget_ns": 0,
                "overshoot": round(factor, 4),
                "sides": sides,
                "bound": round(bound, 4),
            }
        ]

    def check_audit(self) -> List[dict]:
        """The audit plane's own tick: evaluate ONLY the overshoot budget
        (the latency/stage windows stay on the fleet-gossip cadence, so
        an extra audit tick never shrinks their burn windows)."""
        from patrol_tpu.utils import trace as trace_mod

        with self._mu:
            breaches = self._audit_breach_locked()
            if breaches:
                self.breaches += len(breaches)
        for _ in breaches:
            profiling.COUNTERS.inc("slo_breaches")
            trace_mod.anomaly("slo.overshoot")
        return breaches


SENTINEL = SloSentinel()
