"""SLO sentinel: burn-rate / stage-budget watchdogs over the patrol-scope
latency histograms, auto-firing the flight recorder's anomaly snapshots.

patrol-scope records *what happened*; this module decides *when it is
bad enough to freeze evidence*. Two breach classes, both computed from
cumulative histogram deltas between checks (so a check is O(histograms ×
buckets) integer work — no sampling, no timers):

* **take-latency burn rate** — the fraction of takes in the window since
  the last check that exceeded the take budget. A window burning past
  ``max_burn`` fires ``anomaly("slo.take_burn")``, which snapshots every
  thread's flight-recorder ring (damped to 1/reason/s by the recorder).
* **stage-budget overrun** — any commit-pipeline or device stage whose
  window p99 exceeds its budget fires ``anomaly("slo.stage_budget")``.

Budgets default OFF (0 = disabled) so an unconfigured process never
snapshots itself; set them via environment (``PATROL_SLO_TAKE_P99_NS``,
``PATROL_SLO_STAGE_P99_NS``) or programmatically (tests, operators).
The check is driven by the fleet gossip flusher (net/fleet.py) — the
same paced observability tick that ships the histograms — and by
``bench.py --trend``.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional

from patrol_tpu.utils import histogram as hist
from patrol_tpu.utils import profiling


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


# Observations in buckets strictly ABOVE this index are guaranteed over
# the budget (bucket b holds [2^(b-1), 2^b)); the budget's own bucket may
# contain under-budget values, so it is not counted — conservative, never
# a false breach from bucketing.
def _over_bucket(budget_ns: int) -> int:
    return hist.bucket_of(max(budget_ns, 0))


class SloSentinel:
    """Windowed breach detector. ``check()`` compares each watched
    histogram's cumulative bucket counts against the last check's
    snapshot; the difference is the window. Thread-safe; one instance
    per process (``SENTINEL``)."""

    def __init__(
        self,
        take_budget_ns: Optional[int] = None,
        stage_budget_ns: Optional[int] = None,
        max_burn: float = 0.10,
        min_samples: int = 16,
    ):
        self.take_budget_ns = (
            _env_int("PATROL_SLO_TAKE_P99_NS", 0)
            if take_budget_ns is None
            else take_budget_ns
        )
        self.stage_budget_ns = (
            _env_int("PATROL_SLO_STAGE_P99_NS", 0)
            if stage_budget_ns is None
            else stage_budget_ns
        )
        self.max_burn = max_burn
        self.min_samples = min_samples
        self._mu = threading.Lock()
        self._last: Dict[str, List[int]] = {}
        self.breaches = 0
        # Bucket-lifecycle budget provider (engine._budget_snapshot):
        # registered when a memory budget is configured, polled on every
        # check — a hard-watermark breach freezes evidence exactly like a
        # latency burn.
        self._budget_src: Optional[Callable[[], dict]] = None

    def watch_budget(self, provider: Callable[[], dict]) -> None:
        """Register the engine's memory-budget snapshot provider (dict
        with ``over`` plus the accounting gauges). Latest engine wins —
        one process serves one engine."""
        with self._mu:
            self._budget_src = provider

    def unwatch_budget(self, provider: Callable[[], dict]) -> None:
        """Engine shutdown: drop the provider IF it is still ours (a
        replacement engine's registration must survive). Equality, not
        identity: bound methods are fresh objects per attribute access —
        ``==`` compares (instance, function)."""
        with self._mu:
            if self._budget_src == provider:
                self._budget_src = None

    def configure(
        self,
        take_budget_ns: Optional[int] = None,
        stage_budget_ns: Optional[int] = None,
        max_burn: Optional[float] = None,
        min_samples: Optional[int] = None,
    ) -> None:
        with self._mu:
            if take_budget_ns is not None:
                self.take_budget_ns = take_budget_ns
            if stage_budget_ns is not None:
                self.stage_budget_ns = stage_budget_ns
            if max_burn is not None:
                self.max_burn = max_burn
            if min_samples is not None:
                self.min_samples = min_samples

    def _window(self, name: str, counts: List[int]) -> List[int]:
        """Per-bucket deltas since the last check (counts are cumulative
        monotone, so the delta is exact). First sight seeds the baseline
        and reports an empty window — budgets judge fresh traffic only."""
        last = self._last.get(name)
        self._last[name] = list(counts)
        if last is None:
            return [0] * len(counts)
        return [max(0, c - l) for c, l in zip(counts, last)]

    def _burn(self, window: List[int], budget_ns: int) -> tuple:
        total = sum(window)
        over = sum(window[_over_bucket(budget_ns) + 1 :])
        return total, (over / total if total else 0.0)

    def check(
        self, registry: Optional[hist.HistogramRegistry] = None
    ) -> List[dict]:
        """One sentinel pass; returns the breaches found (and fires an
        anomaly snapshot per breach class)."""
        from patrol_tpu.utils import trace as trace_mod

        reg = registry if registry is not None else hist.HISTOGRAMS
        breaches: List[dict] = []
        with self._mu:
            if self.take_budget_ns > 0:
                h = reg.get("take_service_ns")
                total, burn = self._burn(
                    self._window("take_service_ns", h._merged_counts()),
                    self.take_budget_ns,
                )
                if total >= self.min_samples and burn > self.max_burn:
                    breaches.append(
                        {
                            "kind": "take_burn",
                            "stage": "take_service_ns",
                            "window": total,
                            "burn": round(burn, 4),
                            "budget_ns": self.take_budget_ns,
                        }
                    )
            if self.stage_budget_ns > 0:
                for name in hist.INGEST_STAGES + hist.DEVICE_STAGES:
                    h = reg.get(name)
                    window = self._window(name, h._merged_counts())
                    total, burn = self._burn(window, self.stage_budget_ns)
                    if total >= self.min_samples and burn > 0.01:
                        # p99 over budget ⇔ >1% of the window's samples
                        # landed in buckets strictly above it.
                        breaches.append(
                            {
                                "kind": "stage_budget",
                                "stage": name,
                                "window": total,
                                "burn": round(burn, 4),
                                "budget_ns": self.stage_budget_ns,
                            }
                        )
            budget_src = self._budget_src
            if budget_src is not None:
                try:
                    snap = budget_src()
                except Exception:  # pragma: no cover - provider must not kill checks
                    snap = None
                if snap and snap.get("over"):
                    breaches.append(
                        {
                            "kind": "budget",
                            "stage": "state_bytes",
                            "window": 1,
                            "burn": 1.0,
                            "budget_ns": 0,
                            **{
                                k: snap.get(k, 0)
                                for k in (
                                    "state_bytes_in_use",
                                    "state_bytes_budget",
                                    "buckets_bound",
                                    "max_buckets",
                                )
                            },
                        }
                    )
            if breaches:
                self.breaches += len(breaches)
        for kind in sorted({b["kind"] for b in breaches}):
            profiling.COUNTERS.inc("slo_breaches")
            trace_mod.anomaly(f"slo.{kind}")
        return breaches


SENTINEL = SloSentinel()
