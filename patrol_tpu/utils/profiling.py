"""Observability primitives behind the ``/debug`` routes — the equivalent of
the reference's full ``net/http/pprof`` suite (api.go:29-39) plus mutex-
profile-style engine stats (cmd/patrol/main.go:24), re-imagined for a
Python-host + JAX-device process:

* :class:`SamplingProfiler` — a wall-clock sampling CPU profiler over all
  threads (``sys._current_frames`` at a fixed interval), the analogue of
  ``pprof.Profile``'s sampled CPU profile.
* :func:`thread_dump` — all-thread stack dump (≙ ``/debug/pprof/goroutine``).
* :func:`heap_summary` — allocation summary via ``tracemalloc`` when
  enabled, else GC stats (≙ ``/debug/pprof/heap`` / ``allocs``).
* :func:`jax_trace` — captures a JAX profiler trace (XPlane/perfetto dump),
  the device-side story pprof never had.
"""

from __future__ import annotations

import gc
import sys
import tempfile
import threading
import time
import traceback
from collections import Counter
from typing import Dict, Optional


class SamplingProfiler:
    """Sample every thread's stack at ``interval_s`` for ``duration_s``;
    report as pprof protobuf (:meth:`run_pprof`, ≙ ``pprof.Profile``'s
    sampled CPU profile — opens in ``go tool pprof`` / speedscope) or as
    human-readable text (:meth:`run`)."""

    def __init__(self, duration_s: float = 5.0, interval_s: float = 0.005):
        self.duration_s = min(duration_s, 120.0)
        self.interval_s = interval_s

    def _collect(self) -> Counter:
        """Counter over stack tuples, each a tuple of
        ``(qualname, filename, line)`` frames leaf-first."""
        stacks: Counter = Counter()
        deadline = time.monotonic() + self.duration_s
        me = threading.get_ident()
        while time.monotonic() < deadline:
            for tid, frame in sys._current_frames().items():
                if tid == me:
                    continue
                stack = []
                f: Optional[object] = frame
                while f is not None:
                    code = f.f_code  # type: ignore[attr-defined]
                    stack.append(
                        (code.co_qualname, code.co_filename, f.f_lineno)  # type: ignore[attr-defined]
                    )
                    f = f.f_back  # type: ignore[attr-defined]
                stacks[tuple(stack)] += 1
            time.sleep(self.interval_s)
        return stacks

    def run_pprof(self) -> bytes:
        """Gzipped pprof protobuf (profile.proto), the reference's
        ``/debug/pprof/profile`` artifact class (api.go:29-39)."""
        from patrol_tpu.utils.pprof import build_profile

        stacks = self._collect()
        return build_profile(
            stacks,
            period_ns=int(self.interval_s * 1e9),
            duration_ns=int(self.duration_s * 1e9),
        )

    def run(self) -> str:
        stacks = self._collect()
        samples = sum(stacks.values())
        leaf: Counter = Counter()
        flat: Counter = Counter()
        for stack, n in stacks.items():
            name, filename, line = stack[0]
            leaf[f"{name} ({filename}:{line})"] += n
            flat[";".join(f[0] for f in reversed(stack))] += n

        lines = [
            f"sampling cpu profile: {self.duration_s:.1f}s at "
            f"{1 / self.interval_s:.0f}Hz, {samples} samples",
            "",
            "-- hottest frames --",
        ]
        for name, n in leaf.most_common(30):
            lines.append(f"{n:8d}  {name}")
        lines += ["", "-- hottest stacks --"]
        for stack, n in flat.most_common(10):
            lines.append(f"{n:8d}  {stack}")
        return "\n".join(lines) + "\n"


def thread_dump() -> str:
    """Stack dump of all live threads (≙ /debug/pprof/goroutine?debug=2)."""
    names: Dict[int, str] = {t.ident: t.name for t in threading.enumerate() if t.ident}
    out = [f"threads: {threading.active_count()}", ""]
    for tid, frame in sys._current_frames().items():
        out.append(f"thread {tid} [{names.get(tid, '?')}]:")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out) + "\n"


def heap_summary(limit: int = 30) -> str:
    """Allocation summary (≙ /debug/pprof/heap). Detailed when tracemalloc
    is active (start the server with PYTHONTRACEMALLOC=1 or POST
    /debug/pprof/heap/start), GC table otherwise."""
    import tracemalloc

    lines = []
    if tracemalloc.is_tracing():
        snap = tracemalloc.take_snapshot()
        stats = snap.statistics("lineno")
        total = sum(s.size for s in stats)
        lines.append(f"tracemalloc: {total / 1e6:.2f} MB in {len(stats)} sites")
        for s in stats[:limit]:
            lines.append(f"{s.size / 1e3:10.1f} kB  {s.count:8d} blocks  {s.traceback}")
    else:
        lines.append("tracemalloc not active; gc stats:")
        for i, gen in enumerate(gc.get_stats()):
            lines.append(f"gen{i}: {gen}")
        lines.append(f"objects: {len(gc.get_objects())}")
    return "\n".join(lines) + "\n"


def jax_trace(duration_s: float = 2.0, out_dir: Optional[str] = None) -> str:
    """Capture a JAX profiler trace (XPlane; viewable in perfetto /
    tensorboard). Returns the dump directory."""
    import jax

    out = out_dir or tempfile.mkdtemp(prefix="patrol-jax-trace-")
    jax.profiler.start_trace(out)
    time.sleep(min(duration_s, 30.0))
    jax.profiler.stop_trace()
    return out
