"""Observability primitives behind the ``/debug`` routes — the equivalent of
the reference's full ``net/http/pprof`` suite (api.go:29-39) plus mutex-
profile-style engine stats (cmd/patrol/main.go:24), re-imagined for a
Python-host + JAX-device process:

* :class:`SamplingProfiler` — a wall-clock sampling CPU profiler over all
  threads (``sys._current_frames`` at a fixed interval), the analogue of
  ``pprof.Profile``'s sampled CPU profile.
* :func:`thread_dump` — all-thread stack dump (≙ ``/debug/pprof/goroutine``).
* :func:`heap_summary` — allocation summary via ``tracemalloc`` when
  enabled, else GC stats (≙ ``/debug/pprof/heap`` / ``allocs``).
* :func:`jax_trace` — captures a JAX profiler trace (XPlane/perfetto dump),
  the device-side story pprof never had.
"""

from __future__ import annotations

import gc
import sys
import tempfile
import threading
import time
import traceback
from collections import Counter
from typing import Dict, Optional


def _qualname(code) -> str:
    """``co_qualname`` is 3.11+; on 3.10 fall back to the bare name. An
    AttributeError here used to kill whichever engine thread recorded the
    first contended wait — feeder death presented as takes hanging."""
    return getattr(code, "co_qualname", None) or code.co_name


class SamplingProfiler:
    """Sample every thread's stack at ``interval_s`` for ``duration_s``;
    report as pprof protobuf (:meth:`run_pprof`, ≙ ``pprof.Profile``'s
    sampled CPU profile — opens in ``go tool pprof`` / speedscope) or as
    human-readable text (:meth:`run`)."""

    def __init__(self, duration_s: float = 5.0, interval_s: float = 0.005):
        self.duration_s = min(duration_s, 120.0)
        self.interval_s = interval_s

    def _collect(self) -> Counter:
        """Counter over stack tuples, each a tuple of
        ``(qualname, filename, line)`` frames leaf-first."""
        stacks: Counter = Counter()
        deadline = time.monotonic() + self.duration_s
        me = threading.get_ident()
        while time.monotonic() < deadline:
            for tid, frame in sys._current_frames().items():
                if tid == me:
                    continue
                stack = []
                f: Optional[object] = frame
                while f is not None:
                    code = f.f_code  # type: ignore[attr-defined]
                    stack.append(
                        (_qualname(code), code.co_filename, f.f_lineno)  # type: ignore[attr-defined]
                    )
                    f = f.f_back  # type: ignore[attr-defined]
                stacks[tuple(stack)] += 1
            time.sleep(self.interval_s)
        return stacks

    def run_pprof(self) -> bytes:
        """Gzipped pprof protobuf (profile.proto), the reference's
        ``/debug/pprof/profile`` artifact class (api.go:29-39)."""
        from patrol_tpu.utils.pprof import build_profile

        stacks = self._collect()
        return build_profile(
            stacks,
            period_ns=int(self.interval_s * 1e9),
            duration_ns=int(self.duration_s * 1e9),
        )

    def run(self) -> str:
        stacks = self._collect()
        samples = sum(stacks.values())
        leaf: Counter = Counter()
        flat: Counter = Counter()
        for stack, n in stacks.items():
            name, filename, line = stack[0]
            leaf[f"{name} ({filename}:{line})"] += n
            flat[";".join(f[0] for f in reversed(stack))] += n

        lines = [
            f"sampling cpu profile: {self.duration_s:.1f}s at "
            f"{1 / self.interval_s:.0f}Hz, {samples} samples",
            "",
            "-- hottest frames --",
        ]
        for name, n in leaf.most_common(30):
            lines.append(f"{n:8d}  {name}")
        lines += ["", "-- hottest stacks --"]
        for stack, n in flat.most_common(10):
            lines.append(f"{n:8d}  {stack}")
        return "\n".join(lines) + "\n"


class ContentionRegistry:
    """Process-wide lock/block contention accounting — the real
    ``/debug/pprof/mutex`` and ``/block`` (VERDICT r2 item 5; reference:
    ``runtime.SetMutexProfileFraction(50)`` at main.go:24, routes at
    api.go:29-39). Two event classes, matching Go's split:

    * **mutex** — time a thread spent WAITING to acquire a lock another
      thread held (recorded by :class:`ProfiledLock`);
    * **block** — time a thread spent parked in a condition wait
      (:class:`ProfiledCondition`), Go's block-profile class.

    ``fraction`` subsamples events Go-style (stack walks are the
    expensive part); the default records every event — a contended
    acquire already paid a wait that dwarfs the ~µs stack walk, and at
    rate-limiter tick rates (kHz, not MHz) full recording is noise-level
    overhead. Raise it for pathologically contended deployments."""

    def __init__(self, fraction: int = 1):
        self.fraction = max(1, fraction)
        self._mu = threading.Lock()
        # stack tuple -> [contentions, delay_ns]
        self._mutex: Dict[tuple, list] = {}
        self._block: Dict[tuple, list] = {}
        self._mutex_events = 0
        self._block_events = 0

    @staticmethod
    def _caller_stack(skip: int) -> tuple:
        stack = []
        f = sys._getframe(skip)
        while f is not None and len(stack) < 24:
            code = f.f_code
            stack.append((_qualname(code), code.co_filename, f.f_lineno))
            f = f.f_back
        return tuple(stack)

    def _record(self, table: Dict[tuple, list], nth: int, name: str, wait_ns: int) -> None:
        if nth % self.fraction:
            return
        # The lock name leads the stack so pprof's top view groups by
        # which lock contended, then by waiter call site.
        stack = ((name, "<lock>", 0),) + self._caller_stack(3)
        with self._mu:
            entry = table.get(stack)
            if entry is None:
                table[stack] = [1, wait_ns]
            else:
                entry[0] += 1
                entry[1] += wait_ns

    def record_mutex(self, name: str, wait_ns: int) -> None:
        self._mutex_events += 1  # benign race: stat, not invariant
        self._record(self._mutex, self._mutex_events, name, wait_ns)

    def record_block(self, name: str, wait_ns: int) -> None:
        self._block_events += 1
        self._record(self._block, self._block_events, name, wait_ns)

    def _pprof(self, table: Dict[tuple, list], kind: str) -> bytes:
        from patrol_tpu.utils.pprof import build_profile_values

        with self._mu:
            samples = {
                stack: (c * self.fraction, d * self.fraction)
                for stack, (c, d) in table.items()
            }
        return build_profile_values(
            samples,
            period_ns=self.fraction,
            duration_ns=0,
            sample_type=(("contentions", "count"), ("delay", "nanoseconds")),
            period_type=(kind, "count"),
        )

    def mutex_pprof(self) -> bytes:
        return self._pprof(self._mutex, "contentions")

    def block_pprof(self) -> bytes:
        return self._pprof(self._block, "contentions")

    def _text(self, table: Dict[tuple, list], title: str) -> str:
        with self._mu:
            rows = sorted(table.items(), key=lambda kv: -kv[1][1])
        lines = [f"{title}: {len(rows)} contended sites (1/{self.fraction} sampled)"]
        for stack, (c, d) in rows[:30]:
            where = " <- ".join(f"{f[0]}" for f in stack[:4])
            lines.append(
                f"{c * self.fraction:8d} waits  {d * self.fraction / 1e6:10.2f} ms  {where}"
            )
        return "\n".join(lines) + "\n"

    def mutex_text(self) -> str:
        return self._text(self._mutex, "mutex contention")

    def block_text(self) -> str:
        return self._text(self._block, "block (condition-wait)")


REGISTRY = ContentionRegistry()


class CounterRegistry:
    """Process-wide transfer/dispatch counters for the device-commit
    pipeline, surfaced verbatim in ``/debug/vars`` (pt-stats) next to the
    engine stats and snapshotted by bench.py's ingest stages:

    * ``staging_reuse_hits`` / ``staging_leases_fresh`` — how often a
      packed commit matrix refilled a recycled pinned staging buffer
      instead of allocating (engine.StagingPool);
    * ``commit_blocks_coalesced`` / ``commit_dispatches`` — drained delta
      blocks folded into single donated commit dispatches (ops/commit.py)
      and how many such dispatches ran;
    * ``dispatch_ahead_depth`` — high-water count of device ticks in
      flight ahead of the completer (the pipeline's achieved depth);
    * ``rx_staging_reuse_hits`` — native rx batches served from the
      replicator's reused slot/flag staging planes;
    * ``peer_probes_tx`` / ``peer_reresolves`` — replication peer-health
      probe pings sent and DNS re-resolution attempts (net/replication.py
      ``PeerHealth``);
    * ``ae_resync_buckets`` / ``ae_packets_tx`` — buckets re-synced and
      packets sent by heal-time anti-entropy (net/antientropy.py);
    * ``shutdown_flush_states`` — final dirty bucket states broadcast by
      the graceful-shutdown flush (command.py);
    * ``trace_anomaly_snapshots`` / ``trace_take_samples`` — patrol-scope
      flight-recorder anomaly snapshots taken and takes tagged with a
      cross-node trace id (utils/trace.py);
    * ``replication_tx_packets`` / ``replication_tx_bytes`` — datagrams
      and bytes the replication send paths put on the wire (both
      backends' broadcast fan-outs);
    * ``wire_deltas_batched`` / ``wire_interval_retransmits`` /
      ``wire_fullstate_fallbacks`` — wire-v2 delta plane (net/delta.py):
      bucket join-decompositions packed into delta-interval datagrams,
      expired intervals re-shipped, and peers dropped back to full-state
      repair (anti-entropy) after ack loss or heal;
    * ``fleet_packets_tx`` / ``fleet_packets_rx`` — patrol-fleet metrics
      gossip datagrams shipped and joined (net/fleet.py);
    * ``slo_breaches`` — SLO sentinel breach classes fired (take-latency
      burn rate / stage-budget overrun / memory-budget watermark,
      utils/slo.py — each also freezes a flight-recorder anomaly
      snapshot);
    * ``gc_sweeps`` / ``gc_buckets_reclaimed`` — bucket-lifecycle sweeps
      run and full idle buckets reclaimed from the device plane + host
      directory (runtime/engine.py gc_sweep, the IsZero predicate of
      ops/lifecycle.py);
    * ``gc_pressure_shed`` — NEW bucket names shed with the explicit
      429/overloaded signal at the memory budget's hard watermark;
    * ``directory_compactions`` — free-list compactions after a reclaim
      (lane-reuse locality: lowest rows hand out first);
    * ``state_bytes_in_use`` — high-water bytes of live limiter state
      (device rows + directory metadata + host lanes + GC tombstones);
      the live gauge rides ``engine_state_bytes`` in ``/debug/vars``.

    Monotonic counts + high-water gauges only; all call sites are
    per-tick/per-batch (kHz), so one mutex is noise-level overhead.

    Every ``inc``/``set_max`` call site in the tree must name a counter
    declared here — enforced by the PTL005 lint (analysis/lint.py), so a
    new counter cannot silently miss the zero-filled ``/debug/vars``
    field set below."""

    _KNOWN = (
        "staging_reuse_hits",
        "staging_leases_fresh",
        "commit_blocks_coalesced",
        "commit_dispatches",
        "dispatch_ahead_depth",
        "rx_staging_reuse_hits",
        "peer_probes_tx",
        "peer_reresolves",
        "ae_resync_buckets",
        "ae_packets_tx",
        "shutdown_flush_states",
        "trace_anomaly_snapshots",
        "trace_take_samples",
        "replication_tx_packets",
        "replication_tx_bytes",
        "wire_deltas_batched",
        "wire_interval_retransmits",
        "wire_fullstate_fallbacks",
        "fleet_packets_tx",
        "fleet_packets_rx",
        "slo_breaches",
        "gc_sweeps",
        "gc_buckets_reclaimed",
        "gc_pressure_shed",
        "directory_compactions",
        "state_bytes_in_use",
        # patrol-audit (net/audit.py): lag samples recorded, read-only
        # divergence compares completed, admitted-token windows evaluated,
        # the high-water measured overshoot (milli-factor, set_max so the
        # gauge is monotone and fleet-gossip-safe), audit frames shipped /
        # joined, and SLO overshoot breaches fired.
        # Device-resident ingest (ops/ingest.py, r15): raw-plane
        # decode+fold dispatches issued, raw dv2 bytes shipped to the
        # device (the wire→state path's "bytes, not matrices" proof),
        # rx-ring/pool plane reuse hits, and adaptive commit-block
        # resizes (PATROL_COMMIT_BLOCKS=auto governor actuations).
        "ingest_raw_device_dispatches",
        "ingest_raw_bytes_on_device",
        "rx_ring_lease_reuse",
        "commit_blocks_auto_resized",
        "audit_lag_samples",
        "audit_divergence_checks",
        "audit_windows_evaluated",
        "audit_overshoot_millis",
        "audit_packets_tx",
        "audit_packets_rx",
        "audit_overshoot_breaches",
        # patrol-membership (net/membership.py + runtime/mesh_engine.py):
        # members admitted (join + successful rejoin handshakes), members
        # retired, lanes tombstoned behind a retirement epoch, and live
        # device-mesh reshardings (MeshEngine.resize quiesce-swap-resume
        # cycles). Churn observability: /debug/vars + Prometheus carry
        # them zero-filled, and bench --churn-smoke gates on them.
        "peer_joins",
        "peer_leaves",
        "lane_tombstones",
        "mesh_resizes",
        # patrol-dispatch (runtime/engine.py scrape mirror): stats/debug
        # reads served from the epoch-validated host mirror vs. reads
        # that had to gather device rows, and mirror refreshes run (the
        # regression test pins gathers at zero per steady-state scrape).
        "scrape_mirror_hits",
        "scrape_device_gathers",
        "scrape_mirror_refreshes",
        # Hot-key take coalescing (runtime/engine.py): packed rows
        # dispatched as take-n (nreq > 1), tickets absorbed into an
        # already-open queue fold at submit time (the rx-side collapse),
        # and coalesced rows whose grant covered only a FIFO prefix of
        # their tickets (partial grant → clean denies for the rest).
        # bench --smoke's hot-key leg gates all three nonzero.
        "take_rows_coalesced",
        "take_tickets_folded",
        "take_partial_grants",
    )

    def __init__(self):
        self._mu = threading.Lock()
        self._vals: Dict[str, int] = {}

    def inc(self, name: str, n: int = 1) -> None:
        with self._mu:
            self._vals[name] = self._vals.get(name, 0) + n

    def set_max(self, name: str, value: int) -> None:
        """High-water gauge: keep the largest value ever observed."""
        with self._mu:
            if value > self._vals.get(name, 0):
                self._vals[name] = value

    def get(self, name: str) -> int:
        with self._mu:
            return self._vals.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        """Every known counter (zero-filled) plus any ad-hoc ones — the
        stable field set /debug/vars readers can rely on."""
        with self._mu:
            out = {k: self._vals.get(k, 0) for k in self._KNOWN}
            for k, v in self._vals.items():
                out.setdefault(k, v)
            return out


COUNTERS = CounterRegistry()


class ProfiledLock:
    """``threading.Lock`` wrapper recording contended-acquire wait time
    into :data:`REGISTRY`. The uncontended fast path is one extra
    non-blocking try — no timing, no stack walk."""

    __slots__ = ("_lock", "_name")

    def __init__(self, name: str):
        self._lock = threading.Lock()
        self._name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._lock.acquire(False):
            return True
        if not blocking:
            return False
        t0 = time.perf_counter_ns()
        ok = self._lock.acquire(True, timeout)
        REGISTRY.record_mutex(self._name, time.perf_counter_ns() - t0)
        return ok

    def release(self) -> None:
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self._lock.release()


class ProfiledCondition:
    """``threading.Condition`` over a :class:`ProfiledLock`, recording
    ``wait``/``wait_for`` park time as block events (Go's block-profile
    class) and lock contention as mutex events."""

    def __init__(self, name: str):
        self._name = name
        self._plock = ProfiledLock(name)
        self._cond = threading.Condition(self._plock)  # type: ignore[arg-type]

    def wait(self, timeout: Optional[float] = None) -> bool:
        t0 = time.perf_counter_ns()
        ok = self._cond.wait(timeout)
        REGISTRY.record_block(self._name, time.perf_counter_ns() - t0)
        return ok

    def wait_for(self, predicate, timeout: Optional[float] = None):
        t0 = time.perf_counter_ns()
        ok = self._cond.wait_for(predicate, timeout)
        REGISTRY.record_block(self._name, time.perf_counter_ns() - t0)
        return ok

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()

    def acquire(self, *a, **kw):
        return self._plock.acquire(*a, **kw)

    def release(self) -> None:
        self._plock.release()

    def __enter__(self):
        return self._cond.__enter__()

    def __exit__(self, *exc):
        return self._cond.__exit__(*exc)


def thread_dump() -> str:
    """Stack dump of all live threads (≙ /debug/pprof/goroutine?debug=2)."""
    names: Dict[int, str] = {t.ident: t.name for t in threading.enumerate() if t.ident}
    out = [f"threads: {threading.active_count()}", ""]
    for tid, frame in sys._current_frames().items():
        out.append(f"thread {tid} [{names.get(tid, '?')}]:")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out) + "\n"


def heap_summary(limit: int = 30) -> str:
    """Allocation summary (≙ /debug/pprof/heap). Detailed when tracemalloc
    is active (start the server with PYTHONTRACEMALLOC=1 or POST
    /debug/pprof/heap/start), GC table otherwise."""
    import tracemalloc

    lines = []
    if tracemalloc.is_tracing():
        snap = tracemalloc.take_snapshot()
        stats = snap.statistics("lineno")
        total = sum(s.size for s in stats)
        lines.append(f"tracemalloc: {total / 1e6:.2f} MB in {len(stats)} sites")
        for s in stats[:limit]:
            lines.append(f"{s.size / 1e3:10.1f} kB  {s.count:8d} blocks  {s.traceback}")
    else:
        lines.append("tracemalloc not active; gc stats:")
        for i, gen in enumerate(gc.get_stats()):
            lines.append(f"gen{i}: {gen}")
        lines.append(f"objects: {len(gc.get_objects())}")
    return "\n".join(lines) + "\n"


class ProfilerBusyError(RuntimeError):
    """A JAX trace capture is already running (the route answers 409)."""


# One capture at a time: jax.profiler.start_trace is process-global state,
# and two overlapping /debug/jax/trace requests used to call it twice —
# the second start_trace raises inside the handler's executor and the
# route 500s (or worse, the stop_trace of one request tears down the
# other's live capture). Serialized here rather than in the HTTP layer so
# BOTH fronts (and direct callers) get the same busy contract.
_jax_trace_mu = threading.Lock()


def jax_trace(duration_s: float = 2.0, out_dir: Optional[str] = None) -> str:
    """Capture a JAX profiler trace (XPlane; viewable in perfetto /
    tensorboard). Returns the dump directory. Raises
    :class:`ProfilerBusyError` when a capture is already in flight."""
    if not _jax_trace_mu.acquire(blocking=False):
        raise ProfilerBusyError("a jax trace capture is already running")
    try:
        import jax

        out = out_dir or tempfile.mkdtemp(prefix="patrol-jax-trace-")
        jax.profiler.start_trace(out)
        try:
            time.sleep(min(duration_s, 30.0))
        finally:
            jax.profiler.stop_trace()
        return out
    finally:
        _jax_trace_mu.release()
