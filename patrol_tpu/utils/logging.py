"""Structured logging — the zap-equivalent (reference: zap throughout,
SURVEY §5 Metrics/logging).

Two environments, mirroring ``-log-env`` (cmd/patrol/main.go:31,40-47):

* ``production`` — one JSON object per line (zap.NewProduction style);
* ``development`` — human-readable console lines (zap.NewDevelopment style).

Loggers accept structured fields as ``extra={...}`` kwargs via the helpers
below; buckets render as structured objects (≙ ``MarshalLogObject``,
bucket.go:173-182) through their ``log_fields()`` method.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Any, Dict

_RESERVED = set(
    logging.LogRecord("", 0, "", 0, "", (), None).__dict__
) | {"message", "asctime", "taskName"}


class JSONFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out: Dict[str, Any] = {
            "level": record.levelname.lower(),
            "ts": round(time.time(), 6),  # patrol-lint: clock-seam (log stamp)
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        for key, val in record.__dict__.items():
            if key not in _RESERVED and not key.startswith("_"):
                try:
                    json.dumps(val)
                    out[key] = val
                except (TypeError, ValueError):
                    out[key] = repr(val)
        return json.dumps(out, separators=(",", ":"))


class ConsoleFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime("%H:%M:%S", time.localtime(record.created))
        fields = " ".join(
            f"{k}={v!r}"
            for k, v in record.__dict__.items()
            if k not in _RESERVED and not k.startswith("_")
        )
        base = f"{ts}\t{record.levelname}\t{record.name}\t{record.getMessage()}"
        if fields:
            base += "\t" + fields
        if record.exc_info:
            base += "\n" + self.formatException(record.exc_info)
        return base


def configure(env: str = "production", level: int | None = None) -> logging.Logger:
    """Configure and return the root ``patrol`` logger.

    ``env``: ``production`` (JSON, INFO) or ``development`` (console, DEBUG)
    — unknown values raise, like main.go:46's fatal on bad ``-log-env``.
    """
    if env == "production":
        formatter: logging.Formatter = JSONFormatter()
        default_level = logging.INFO
    elif env == "development":
        formatter = ConsoleFormatter()
        default_level = logging.DEBUG
    else:
        raise ValueError(f"unsupported log env {env!r}")

    logger = logging.getLogger("patrol")
    logger.setLevel(level if level is not None else default_level)
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(formatter)
    logger.handlers[:] = [handler]
    logger.propagate = False
    return logger
