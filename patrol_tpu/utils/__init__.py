"""Utilities: structured logging, profiling endpoints, clocks."""
