"""patrol-scope metrics plane: mergeable log-bucketed latency histograms
and the Prometheus text exposition behind ``/metrics``.

Aggregate counters (utils/profiling.py ``COUNTERS``) say *how much*; the
ingest-wall question (ROADMAP item 1) is *where time goes* — so the
pipeline's stages each feed a latency histogram: staging wait, H2D put,
kernel dispatch, completion, replication rx decode, and the tick fold,
plus take service time end-to-end. ``bench.py --smoke`` publishes their
per-stage breakdown as ``ingest_stage_breakdown``.

**The lattice.** Buckets are powers of two (bucket *b* holds values with
``bit_length == b``, i.e. ``[2^(b-1), 2^b)``; bucket 0 holds 0), and each
bucket is a **G-Counter**: one monotone count lane per node, observed
value = lane sum, join = per-lane max. That is exactly the limiter
state's merge discipline (PN lanes under max/sum), so per-node histograms
combine associatively/commutatively/idempotently — node histograms can be
shipped and joined by an aggregator with the same convergence guarantees
as the bucket state itself (pinned by ``tests/test_trace.py``'s lattice
law tests). A process records into its own lane only; the in-process
fast path is one lock + two integer adds (the CounterRegistry's own
cost argument: call sites are per-take/per-tick, kHz-class).

**Exposition.** :func:`render_exposition` produces real Prometheus text
format (``# TYPE`` lines, cumulative ``_bucket{le=...}`` /``_sum``/
``_count`` series) for ``/metrics`` on both HTTP fronts, replacing the
gauge-only dump; :func:`parse_exposition` is the minimal strict parser
the roundtrip test and the CI smoke gate validate against.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, Iterable, List, Optional, Tuple

# 64 log2 buckets cover the full non-negative int64 ns range.
NBUCKETS = 64


def bucket_of(value: int) -> int:
    """Log2 bucket index: bit_length, clamped. Bucket 0 holds value 0."""
    if value < 0:
        value = 0
    b = value.bit_length()
    return b if b < NBUCKETS else NBUCKETS - 1


class LatticeHistogram:
    """One named histogram: ``nodes`` G-Counter lanes per bucket plus a
    per-lane monotone value sum. ``record`` writes this process's lane;
    ``join`` max-merges another histogram's lanes in (idempotent,
    commutative, associative — the CRDT laws the tests pin)."""

    __slots__ = ("name", "unit", "nodes", "node_slot", "_mu", "_counts", "_sums")

    def __init__(self, name: str, nodes: int = 1, node_slot: int = 0, unit: str = "ns"):
        if not 0 <= node_slot < nodes:
            raise ValueError(f"node_slot {node_slot} outside {nodes} lanes")
        self.name = name
        self.unit = unit
        self.nodes = nodes
        self.node_slot = node_slot
        self._mu = threading.Lock()
        self._counts = [[0] * NBUCKETS for _ in range(nodes)]
        self._sums = [0] * nodes

    # -- hot path ------------------------------------------------------------

    def record(self, value: int) -> None:
        v = int(value)
        if v < 0:
            v = 0
        b = bucket_of(v)
        with self._mu:
            self._counts[self.node_slot][b] += 1
            self._sums[self.node_slot] += v

    # -- lattice -------------------------------------------------------------

    def _grow(self, nodes: int) -> None:
        while len(self._counts) < nodes:
            self._counts.append([0] * NBUCKETS)
            self._sums.append(0)
        self.nodes = len(self._counts)

    def join(self, other: "LatticeHistogram") -> None:
        """Max-join ``other``'s lanes into this histogram (both sides may
        have recorded concurrently; lanes are monotone, so the join is
        exact for disjoint writers — the same single-writer-per-lane rule
        as the PN state)."""
        with other._mu:
            o_counts = [list(lane) for lane in other._counts]
            o_sums = list(other._sums)
        with self._mu:
            self._grow(len(o_counts))
            for lane, (mine, theirs) in enumerate(zip(self._counts, o_counts)):
                for b in range(NBUCKETS):
                    if mine[b] < theirs[b]:
                        mine[b] = theirs[b]
                if self._sums[lane] < o_sums[lane]:
                    self._sums[lane] = o_sums[lane]

    def to_lattice(self) -> dict:
        """Serializable lattice state (what a node would ship to an
        aggregator); :meth:`join_lattice` is its receiving half."""
        with self._mu:
            return {
                "name": self.name,
                "unit": self.unit,
                "counts": [list(lane) for lane in self._counts],
                "sums": list(self._sums),
            }

    def join_lattice(self, lattice: dict) -> None:
        o_counts = lattice["counts"]
        o_sums = lattice["sums"]
        with self._mu:
            self._grow(len(o_counts))
            for lane, theirs in enumerate(o_counts):
                mine = self._counts[lane]
                for b in range(min(NBUCKETS, len(theirs))):
                    if mine[b] < theirs[b]:
                        mine[b] = theirs[b]
                if self._sums[lane] < o_sums[lane]:
                    self._sums[lane] = o_sums[lane]

    # -- reading -------------------------------------------------------------

    def _merged_counts(self) -> List[int]:
        with self._mu:
            out = [0] * NBUCKETS
            for lane in self._counts:
                for b, c in enumerate(lane):
                    out[b] += c
            return out

    @property
    def count(self) -> int:
        return sum(self._merged_counts())

    @property
    def total(self) -> int:
        with self._mu:
            return sum(self._sums)

    def quantile(self, q: float) -> int:
        """Upper edge (2^b - 1) of the bucket holding quantile ``q``;
        0 for an empty histogram."""
        counts = self._merged_counts()
        n = sum(counts)
        if n == 0:
            return 0
        target = max(1, int(q * n + 0.999999))
        acc = 0
        for b, c in enumerate(counts):
            acc += c
            if acc >= target:
                return (1 << b) - 1
        return (1 << NBUCKETS) - 1

    def max_edge(self) -> int:
        """Upper edge of the highest non-empty bucket (≥ true max)."""
        counts = self._merged_counts()
        for b in range(NBUCKETS - 1, -1, -1):
            if counts[b]:
                return (1 << b) - 1
        return 0

    def summary(self) -> dict:
        n = self.count
        return {
            "count": n,
            "sum": self.total,
            "p50": self.quantile(0.50),
            "p99": self.quantile(0.99),
            "max": self.max_edge(),
            "unit": self.unit,
        }


class HistogramRegistry:
    """Process-wide named histograms (the /metrics + /debug/vars field
    set). ``get`` is idempotent; hot paths hold the returned object so
    recording never re-enters the registry lock."""

    def __init__(self):
        self._mu = threading.Lock()
        self._h: Dict[str, LatticeHistogram] = {}

    def get(self, name: str, unit: str = "ns") -> LatticeHistogram:
        with self._mu:
            h = self._h.get(name)
            if h is None:
                h = LatticeHistogram(name, unit=unit)
                self._h[name] = h
            return h

    def observe(self, name: str, value: int) -> None:
        self.get(name).record(value)

    def items(self) -> List[Tuple[str, LatticeHistogram]]:
        with self._mu:
            return sorted(self._h.items())

    def snapshot(self) -> Dict[str, dict]:
        """name → summary for every registered histogram (the
        /debug/vars ``histograms`` field), plus a reserved ``node`` key
        carrying this process's cluster identity (slot + configured
        name; :func:`set_node_identity`) so merged fleet views can
        attribute the lanes without guessing — no histogram can collide
        with it (stage/kernel names never equal ``node``)."""
        out: Dict[str, dict] = {"node": node_identity()}
        out.update({name: h.summary() for name, h in self.items()})
        return out


HISTOGRAMS = HistogramRegistry()

# Pre-created stage histograms: the hot paths record through these module
# attributes, never through a registry lookup.
STAGE_STAGING_WAIT = HISTOGRAMS.get("ingest_staging_wait_ns")
STAGE_H2D = HISTOGRAMS.get("ingest_h2d_ns")
STAGE_DISPATCH = HISTOGRAMS.get("ingest_dispatch_ns")
STAGE_COMPLETION = HISTOGRAMS.get("ingest_completion_ns")
STAGE_RX_DECODE = HISTOGRAMS.get("ingest_rx_decode_ns")
STAGE_FOLD = HISTOGRAMS.get("ingest_fold_ns")
TAKE_SERVICE = HISTOGRAMS.get("take_service_ns")
RX_APPLY = HISTOGRAMS.get("replication_rx_apply_ns")
AE_JOB = HISTOGRAMS.get("ae_job_ns")
FRONT_WAIT = HISTOGRAMS.get("http_front_wait_ns")
# Device-side stage histograms (patrol-fleet, ROADMAP item 1's r06
# capture): dispatch→ready wall time of the engine's commit and take
# kernels, measured on the completion pipeline (block_until_ready /
# result-readback deltas in runtime/engine.py).
STAGE_DEVICE_COMMIT = HISTOGRAMS.get("device_commit_ns")
STAGE_DEVICE_TAKE = HISTOGRAMS.get("device_take_ns")
# Bucket-lifecycle sweep duration (idle-bucket GC, runtime/engine.py
# gc_sweep): candidate selection + IsZero probe + reclaim, end to end.
# Not an ingest/device stage column — the sweep is a maintenance path,
# so it must not gate the smoke's every-stage-has-samples assertion.
GC_SWEEP = HISTOGRAMS.get("gc_sweep_ns")
# patrol-audit (net/audit.py): per-peer replication lag (oldest unacked
# delta interval's age, one sample per delta-exchanging peer per audit
# tick) and per-bucket staleness (ns the last local emission ran ahead
# of the last remote absorb). Both are G-Counter lattices like every
# histogram here, so the fleet gossip merges them cluster-wide for free.
AUDIT_PEER_LAG = HISTOGRAMS.get("audit_peer_lag_ns")
AUDIT_STALENESS = HISTOGRAMS.get("audit_bucket_staleness_ns")

# The bench's per-stage attribution set (benchmarks/PROBES.md).
INGEST_STAGES = (
    "ingest_staging_wait_ns",
    "ingest_h2d_ns",
    "ingest_dispatch_ns",
    "ingest_completion_ns",
    "ingest_rx_decode_ns",
    "ingest_fold_ns",
)

# Device-side columns of the same breakdown (the r06 capture evidence:
# what the DEVICE spent, not what the host waited).
DEVICE_STAGES = (
    "device_commit_ns",
    "device_take_ns",
)

# Per-kernel device-duration histograms (``device_kernel_<name>_ns``):
# one per dispatched kernel family, created on first dispatch and cached
# here so hot paths never re-enter the registry lock per tick.
_kernel_mu = threading.Lock()
_kernel_hists: Dict[str, LatticeHistogram] = {}


def kernel_histogram(kernel: str) -> LatticeHistogram:
    h = _kernel_hists.get(kernel)
    if h is None:
        with _kernel_mu:
            h = _kernel_hists.get(kernel)
            if h is None:
                h = HISTOGRAMS.get(f"device_kernel_{kernel}_ns")
                _kernel_hists[kernel] = h
    return h


def stage_breakdown(registry: HistogramRegistry = HISTOGRAMS) -> Dict[str, dict]:
    """The ``ingest_stage_breakdown`` bench section: every ingest stage's
    count/p50/p99 from the live histograms, plus the device-side commit/
    take columns (``device_*``, runtime/engine.py's completion-pipeline
    block_until_ready deltas)."""
    out = {}
    for name in INGEST_STAGES + DEVICE_STAGES:
        h = registry.get(name)
        out[name] = {
            "count": h.count,
            "p50_ns": h.quantile(0.50),
            "p99_ns": h.quantile(0.99),
        }
    return out


def kernel_breakdown(registry: HistogramRegistry = HISTOGRAMS) -> Dict[str, dict]:
    """Per-kernel device-duration summaries (``device_kernel_*_ns``)."""
    return {
        name: h.summary()
        for name, h in registry.items()
        if name.startswith("device_kernel_")
    }


# -- node identity (patrol-fleet lane attribution) ---------------------------

_node_identity = {"slot": 0, "name": ""}


def set_node_identity(slot: int, name: str) -> None:
    """Declare this process's cluster identity (node slot + configured
    name). Carried by the ``/debug/vars`` histogram summaries and the
    metrics gossip so merged fleet views attribute lanes without
    guessing. Settable once at startup (command.py)."""
    _node_identity["slot"] = int(slot)
    _node_identity["name"] = str(name)


def node_identity() -> dict:
    return dict(_node_identity)


# -- Prometheus text exposition ----------------------------------------------

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _metric_name(key: str) -> Optional[str]:
    name = "patrol_" + key
    return name if _NAME_OK.match(name) else None


def render_exposition(
    stats: dict,
    registry: HistogramRegistry = HISTOGRAMS,
    uptime_s: Optional[float] = None,
) -> str:
    """Prometheus text exposition (format 0.0.4): every numeric stat as a
    gauge, every registered histogram as a real cumulative histogram
    (only non-empty buckets below the top occupied edge are emitted —
    64 log2 buckets would otherwise dominate the scrape)."""
    lines: List[str] = []
    for key in sorted(stats):
        val = stats[key]
        if isinstance(val, bool) or not isinstance(val, (int, float)):
            continue
        name = _metric_name(key)
        if name is None:
            continue
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {val}")
    for hname, h in registry.items():
        name = _metric_name(hname)
        if name is None:
            continue
        counts = h._merged_counts()
        total = h.total
        n = sum(counts)
        lines.append(f"# TYPE {name} histogram")
        acc = 0
        top = max((b for b, c in enumerate(counts) if c), default=-1)
        for b in range(top + 1):
            acc += counts[b]
            lines.append(f'{name}_bucket{{le="{(1 << b) - 1}"}} {acc}')
        lines.append(f'{name}_bucket{{le="+Inf"}} {n}')
        lines.append(f"{name}_sum {total}")
        lines.append(f"{name}_count {n}")
    if uptime_s is not None:
        lines.append("# TYPE patrol_uptime_seconds gauge")
        lines.append(f"patrol_uptime_seconds {uptime_s:.3f}")
    return "\n".join(lines) + "\n"


_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"  # metric name
    r"(?:\{([^}]*)\})?"  # optional labels
    r" ([0-9eE.+-]+|\+Inf|-Inf|NaN)$"  # value
)
_LABEL_RE = re.compile(r'^([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"$')
_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) (\w+)$")


def parse_exposition(text: str) -> dict:
    """Minimal strict exposition-format parser — the roundtrip fixture
    for the /metrics exporter (tests + the CI smoke gate). Returns
    ``{"types": {name: type}, "samples": {(name, label_items): value}}``
    and raises ``ValueError`` on any malformed line, non-cumulative
    histogram buckets, or a histogram whose ``_count`` disagrees with its
    ``+Inf`` bucket."""
    types: Dict[str, str] = {}
    samples: Dict[tuple, float] = {}
    for lineno, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("#"):
            m = _TYPE_RE.match(line)
            if m:
                types[m.group(1)] = m.group(2)
            elif not line.startswith("# HELP"):
                raise ValueError(f"line {lineno}: unrecognized comment {line!r}")
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: malformed sample {line!r}")
        name, raw_labels, raw_val = m.groups()
        labels: List[Tuple[str, str]] = []
        if raw_labels:
            for part in raw_labels.rstrip(",").split(","):
                lm = _LABEL_RE.match(part.strip())
                if not lm:
                    raise ValueError(f"line {lineno}: malformed label {part!r}")
                labels.append((lm.group(1), lm.group(2)))
        val = float("inf") if raw_val == "+Inf" else float(raw_val)
        samples[(name, tuple(labels))] = val
    _validate_histograms(types, samples)
    return {"types": types, "samples": samples}


def _validate_histograms(types: Dict[str, str], samples: Dict[tuple, float]) -> None:
    """Validate every histogram series-group. Buckets are grouped by
    their non-``le`` label set (the fleet exposition labels each node's
    lane with ``node="<slot>"``); each group must be cumulative with a
    matching ``_count``/``_sum`` carrying the SAME label set — the
    unlabeled single-group case is exactly the old behavior."""
    for name, typ in types.items():
        if typ != "histogram":
            continue
        groups: Dict[tuple, dict] = {}
        for (sname, labels), val in samples.items():
            if sname == f"{name}_bucket":
                rest = tuple(l for l in labels if l[0] != "le")
                le = dict(labels).get("le")
                if le is None:
                    raise ValueError(f"{name}: bucket without le label")
                g = groups.setdefault(rest, {"buckets": [], "inf": None})
                if le == "+Inf":
                    g["inf"] = val
                else:
                    g["buckets"].append((float(le), val))
        if not groups:
            raise ValueError(f"{name}: histogram without +Inf bucket")
        for rest, g in groups.items():
            tag = f"{name}{dict(rest) if rest else ''}"
            if g["inf"] is None:
                raise ValueError(f"{tag}: histogram without +Inf bucket")
            g["buckets"].sort()
            prev = 0.0
            for le, val in g["buckets"]:
                if val < prev:
                    raise ValueError(f"{tag}: non-cumulative bucket at le={le}")
                prev = val
            if g["buckets"] and g["inf"] < g["buckets"][-1][1]:
                raise ValueError(f"{tag}: +Inf below last bucket")
            count = samples.get((f"{name}_count", rest))
            if count is None or count != g["inf"]:
                raise ValueError(f"{tag}: _count missing or != +Inf bucket")
            if (f"{name}_sum", rest) not in samples:
                raise ValueError(f"{tag}: _sum missing")


# -- fleet exposition (GET /cluster/metrics) ---------------------------------

_LABEL_SAFE = re.compile(r"[^0-9A-Za-z_.:\-]")


def _label_value(raw: str) -> str:
    """Sanitized label value: the strict parser's label grammar has no
    escape sequences, so identity labels are reduced to a safe subset."""
    return _LABEL_SAFE.sub("_", raw)[:64]


def render_fleet_exposition(store) -> str:
    """Prometheus text exposition of a :class:`patrol_tpu.net.fleet.
    FleetStore`: every gossiped counter lane as a ``node``-labeled gauge
    and every histogram lane as a ``node``-labeled cumulative histogram —
    strictly parseable by :func:`parse_exposition` (per-label-set
    validation). Only non-empty lanes are emitted."""
    lines: List[str] = []
    snap = store.lattice_snapshot()
    node_names = snap["node_names"]

    def node_label(slot: int) -> str:
        nm = node_names.get(slot)
        if nm:
            return f'node="{slot}",node_name="{_label_value(nm)}"'
        return f'node="{slot}"'

    if node_names:
        lines.append("# TYPE patrol_cluster_node_info gauge")
        for slot in sorted(node_names):
            lines.append(f"patrol_cluster_node_info{{{node_label(slot)}}} 1")
    for cname in sorted(snap["counters"]):
        name = _metric_name("cluster_" + cname)
        if name is None:
            continue
        lines.append(f"# TYPE {name} gauge")
        for slot in sorted(snap["counters"][cname]):
            val = snap["counters"][cname][slot]
            lines.append(f"{name}{{{node_label(slot)}}} {val}")
    for hname in sorted(snap["hists"]):
        name = _metric_name("cluster_" + hname)
        if name is None:
            continue
        lanes = snap["hists"][hname]
        emitted_type = False
        for slot in sorted(lanes):
            counts, total = lanes[slot]
            n = sum(counts)
            if n == 0:
                continue
            if not emitted_type:
                lines.append(f"# TYPE {name} histogram")
                emitted_type = True
            lbl = node_label(slot)
            acc = 0
            top = max((b for b, c in enumerate(counts) if c), default=-1)
            for b in range(top + 1):
                acc += counts[b]
                lines.append(
                    f'{name}_bucket{{{lbl},le="{(1 << b) - 1}"}} {acc}'
                )
            lines.append(f'{name}_bucket{{{lbl},le="+Inf"}} {n}')
            lines.append(f"{name}_sum{{{lbl}}} {total}")
            lines.append(f"{name}_count{{{lbl}}} {n}")
    return "\n".join(lines) + "\n"
