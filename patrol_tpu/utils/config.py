"""The ``PATROL_*`` environment-knob registry (PTL007).

Every environment knob the codebase reads is declared HERE, once, with
its default and a one-line operator doc. The patrol-lint PTL007 pass
enforces the contract statically: any ``os.environ`` / ``os.getenv``
read of a ``PATROL_*`` name anywhere in the tree must use a string
literal that appears in :data:`KNOBS` (so the README knob table — which
``tests/test_config.py`` checks is generated from this registry — can
never silently drift from the code), and reads through a *computed*
name are allowed only in this module, the one declared seam.

Import-light on purpose: no jax, no heavy deps — the lint stage loads
this file standalone (``importlib``) the same way it loads the native
effects table, and pure-python consumers (net/, utils/) must not pull
an accelerator runtime just to read a flush interval.

Call-site idiom: modules may keep reading literally —
``os.environ.get("PATROL_GC_WINDOW_MS", 500)`` — as long as the name is
registered, or use the typed accessors below (``env_int`` /
``env_float`` / ``env_str`` / ``env_flag``) which fall back to the
registry default and swallow malformed values the way the old scattered
``_env_int``/``_env_float`` helpers did.
"""

# NOTE: no `from __future__ import annotations` here — the lint stage
# execs this file standalone (spec_from_file_location without a
# sys.modules entry), where dataclass field resolution under deferred
# annotations breaks on py3.10.

import dataclasses
import os
from typing import Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Knob:
    """One declared environment knob: the default in its environment
    string form (empty string = unset), and a one-line operator doc."""

    name: str
    default: str
    doc: str


_DECLARED: Tuple[Knob, ...] = (
    # --- runtime/engine.py: device-commit pipeline ---------------------
    Knob("PATROL_MAX_MERGE_ROWS", "8192",
         "Per-dispatch row budget for the padded merge kernels."),
    Knob("PATROL_COMMIT_BLOCKS", "auto",
         "Commit pipeline block count, or 'auto' for the adaptive governor."),
    Knob("PATROL_COMMIT_BLOCKS_MAX", "8",
         "Upper bound the 'auto' commit-block governor may resize to."),
    Knob("PATROL_COMMIT_BUDGET_MS", "50",
         "Per-tick commit latency budget steering the block governor."),
    Knob("PATROL_DISPATCH_AHEAD", "8",
         "Max in-flight device dispatches before the engine awaits."),
    Knob("PATROL_DEVICE_TIMING", "1",
         "Record per-kernel device timings into patrol-scope (0 = off)."),
    Knob("PATROL_DEVICE_ANNOTATIONS", "0",
         "Emit jax named_scope annotations for profiler traces (1 = on)."),
    Knob("PATROL_MERGE_KERNEL", "scatter",
         "Merge kernel select: scatter | auto | pallas (compile-probed)."),
    Knob("PATROL_TICK_FOLD", "1",
         "Fold deltas before the merge tick (default: 0 on cpu, 1 on "
         "accelerators)."),
    Knob("PATROL_TAKE_FOLD", "1",
         "Hot-key take coalescing (0 = per-ticket replay; differential/"
         "debug)."),
    Knob("PATROL_ROW_DENSE_MIN", "0",
         "Min distinct rows before the row-dense merge path engages."),
    Knob("PATROL_FOLD_NATIVE_MAX_DISTINCT", "4096",
         "Native-fold cutover: max distinct buckets per fold batch."),
    # --- runtime/engine.py + hoststore.py: host fastpath ---------------
    Knob("PATROL_HOST_FASTPATH", "1",
         "Serve hot buckets from the host store between ticks (0 = off)."),
    Knob("PATROL_HOST_PROMOTE_TAKES", "4096",
         "Takes per window that promote a bucket to the host fastpath."),
    Knob("PATROL_HOST_PROMOTE_WINDOW_MS", "100",
         "Window for the host-promotion take counter."),
    Knob("PATROL_HOST_DEMOTE_TAKES", "1024",
         "Takes per window below which a host bucket demotes (default: "
         "PROMOTE_TAKES/4)."),
    Knob("PATROL_HOST_DEMOTE_WINDOW_MS", "200",
         "Window for the host-demotion take counter."),
    Knob("PATROL_NATIVE_PROMOTE_TAKES", "0",
         "Promotion threshold for the native (C++) host store (0 = off)."),
    # --- runtime/engine.py: stats/debug scrape mirror ------------------
    Knob("PATROL_SCRAPE_MIRROR", "1",
         "Serve stats/debug reads (snapshot/tokens//debug/vars) from an "
         "epoch-validated host mirror instead of a device gather per "
         "scrape (0 = gather every time)."),
    Knob("PATROL_SCRAPE_MIRROR_ROWS", "4096",
         "Max device rows the scrape mirror caches per refresh; rows "
         "beyond the window fall back to a targeted gather."),
    # --- runtime/engine.py: bucket lifecycle / GC ----------------------
    Knob("PATROL_GC_WINDOW_MS", "500",
         "Idle-bucket GC sweep cadence."),
    Knob("PATROL_GC_IDLE_MS", "1000",
         "Idle age after which a zero-balance bucket is reclaimable."),
    Knob("PATROL_GC_SWEEP_MAX", "8192",
         "Max buckets examined per GC sweep."),
    Knob("PATROL_MAX_BUCKETS", "0",
         "Hard bucket-count budget (0 = unbounded)."),
    Knob("PATROL_STATE_BYTES_BUDGET", "0",
         "Hard device-state byte budget (0 = unbounded)."),
    Knob("PATROL_GC_SOFT_FRAC", "0.85",
         "Budget fraction at which GC turns eager before shedding."),
    Knob("PATROL_AUDIT_WINDOW_MS", "5000",
         "patrol-audit consistency-window length on the engine side."),
    # --- ops/pallas_merge.py -------------------------------------------
    Knob("PATROL_PALLAS_MIN_BATCH", "1024",
         "Min batch before the pallas merge is preferred under 'auto'."),
    Knob("PATROL_PALLAS_BLOCK_FRAC", "0.25",
         "VMEM fraction the pallas merge may claim per block."),
    # --- net/: replication planes --------------------------------------
    Knob("PATROL_RAW_INGEST", "1",
         "Device-resident decode+fold of raw delta datagrams (0 = host)."),
    Knob("PATROL_DELTA_FLUSH_MS", "20",
         "Delta-plane flush pacing."),
    Knob("PATROL_DELTA_RETX_TICKS", "8",
         "Flush ticks before an unacked delta interval retransmits."),
    Knob("PATROL_PYFRONT_BATCH", "1",
         "Batch python HTTP-front takes per engine tick (0 = per-call)."),
    Knob("PATROL_AUDIT_MS", "1000",
         "patrol-audit plane pacing (0 = manual flush; tests/bench)."),
    Knob("PATROL_FLEET_GOSSIP_MS", "1000",
         "Metrics-lattice gossip pacing (0 = manual flush)."),
    # --- native/ --------------------------------------------------------
    Knob("PATROL_NATIVE_LIB", "",
         "Override path for the native host library (asan-py stage)."),
    Knob("PATROL_FOLD_THREADS", "",
         "Native fold worker threads (unset = library picks)."),
    # --- utils/: observability ------------------------------------------
    Knob("PATROL_TRACE", "1",
         "Flight-recorder master switch (0 = rings off)."),
    Knob("PATROL_TRACE_RING", "4096",
         "Flight-recorder ring capacity, events per thread."),
    Knob("PATROL_TRACE_SAMPLE", "0",
         "Cross-node span sampling: 1 in N takes traced (0 = off)."),
    Knob("PATROL_SLO_TAKE_P99_NS", "0",
         "Take-latency burn-rate budget for the SLO sentinel (0 = off)."),
    Knob("PATROL_SLO_STAGE_P99_NS", "0",
         "Commit-stage p99 budget for the SLO sentinel (0 = off)."),
    Knob("PATROL_SLO_OVERSHOOT", "0",
         "AP-overshoot budget factor for patrol-audit (0 = off)."),
)

KNOBS: Dict[str, Knob] = {k.name: k for k in _DECLARED}
assert len(KNOBS) == len(_DECLARED), "duplicate knob declaration"


def _raw(name: str, default: Optional[str]) -> str:
    knob = KNOBS[name]  # KeyError = unregistered knob; declare it above
    fallback = knob.default if default is None else default
    # The one sanctioned computed-name environment read (PTL007 seam).
    return os.environ.get(name, fallback)


def env_str(name: str, default: Optional[str] = None) -> str:
    """Registered knob as a string (registry default when unset)."""
    return _raw(name, default)


def env_int(name: str, default: Optional[int] = None) -> int:
    """Registered knob as an int; malformed values fall back to the
    default (the old scattered ``_env_int`` helpers' contract)."""
    fb = None if default is None else str(default)
    try:
        return int(_raw(name, fb))
    except ValueError:
        return int(KNOBS[name].default if default is None else default)


def env_float(name: str, default: Optional[float] = None) -> float:
    """Registered knob as a float; malformed values fall back."""
    fb = None if default is None else str(default)
    try:
        return float(_raw(name, fb))
    except ValueError:
        return float(KNOBS[name].default if default is None else default)


def env_flag(name: str, default: Optional[bool] = None) -> bool:
    """Registered knob as the repo's boolean idiom: set-and-not-"0"."""
    fb = None if default is None else ("1" if default else "0")
    return _raw(name, fb) != "0"


def render_knob_table() -> str:
    """The README/PROBES markdown table, generated from the registry so
    docs and code cannot drift (checked by ``tests/test_config.py``)."""
    lines = [
        "| knob | default | what it does |",
        "|------|---------|--------------|",
    ]
    for k in _DECLARED:
        default = f"`{k.default}`" if k.default else "*(unset)*"
        lines.append(f"| `{k.name}` | {default} | {k.doc} |")
    return "\n".join(lines)
