"""patrol-scope flight recorder: per-thread ring buffers of ns-stamped
typed events, plus the cross-node take-span collector.

The reference's whole debug story is the pprof route set (api.go:29-39):
aggregate profiles, no *timeline*. The ingest wall (ROADMAP item 1) is
exactly the question aggregates cannot answer — where a delta spends its
time between the wire and the donated dispatch — so this module records
the pipeline's typed events (tick, staging lease/recycle, H2D put,
dispatch, completion, rx decode, fold, broadcast tx, anti-entropy
phases) into fixed-size per-thread rings:

* **Lock-free on the hot path.** Each ring has exactly one writer (its
  owning thread); recording is a handful of list stores behind a single
  ``if TRACE.enabled:`` branch at the call site — the disabled cost is
  one attribute load + branch, pinned by ``bench.py --smoke``'s
  ``trace_off_branch_ns`` micro-test and ``tests/test_trace.py``.
* **Bounded by construction.** ``PATROL_TRACE_RING`` events per thread
  (default 4096), oldest overwritten; a wedged consumer can never make
  the recorder grow.
* **Dumpable on demand** as Chrome-trace/Perfetto JSON via
  ``/debug/trace/ring`` (open in ``chrome://tracing`` or ui.perfetto.dev)
  and **auto-snapshotted on anomalies** — take stalls
  (``TakeTicket.wait`` timeout) and anti-entropy convergence-budget
  breaches call :func:`anomaly`, which freezes the rings into a bounded
  snapshot list served by ``/debug/trace/ring?snapshot=N``. Snapshots are
  damped to one per reason per second so a stall storm cannot turn the
  recorder into the bottleneck it is observing.

Cross-node take tracing (the span collector): a sampled take (1 in
``PATROL_TRACE_SAMPLE``; 0 disables) gets a process-unique trace id that
rides the replication datagram in a reserved trace trailer
(ops/wire.py) — invisible to v1 peers and to pre-trace patrol builds,
both of which ignore bytes past the trailers they know. The receiving
node stamps its decode and merge spans with the propagated id, so
``/debug/trace/spans?trace_id=N`` shows one take's full cross-node
story: local take span (node A) joined to the rx-decode and device-merge
spans (node B). Spans carry node slot + bucket name. The id rides the
python wire codec; the C++ batch encoder does not emit trace trailers
(native-backend broadcasts drop the id — tracing degrades, never
breaks).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from patrol_tpu.utils import profiling

# Event types (values are stable: they appear in dumps and snapshots).
EV_TICK = 1  # one engine tick's device work (arg = work rows)
EV_STAGING_LEASE = 2  # StagingPool.lease (arg = buffer elements)
EV_STAGING_RECYCLE = 3  # StagingPool.release
EV_H2D_PUT = 4  # host->device staging transfer shipped
EV_COMMIT_DISPATCH = 5  # donated kernel dispatch under _state_mu
EV_COMMIT_COMPLETE = 6  # completer-side result readback + fanout
EV_RX_DECODE = 7  # replication rx decode (arg = packets)
EV_FOLD = 8  # host-side tick fold (arg = deltas folded)
EV_BROADCAST_TX = 9  # replication broadcast fan-out (arg = datagrams)
EV_AE_PHASE = 10  # anti-entropy job (arg = phase code, see AE_PHASES)
EV_TAKE = 11  # one served take (sampled)
EV_ANOMALY = 12  # anomaly marker (snapshot trigger)
EV_DELTA_PACK = 13  # delta-plane flush: intervals packed (arg = datagrams)
EV_DELTA_ACK = 14  # delta ack vector sent/processed (arg = acks)
EV_DELTA_RETRANSMIT = 15  # expired intervals re-shipped (arg = intervals)
EV_DEVICE_READY = 16  # device dispatch→ready observed (arg = work rows)
EV_AUDIT_TICK = 17  # patrol-audit flush tick (arg = datagrams shipped)
EV_AUDIT_COMPARE = 18  # read-only divergence compare (arg = divergent buckets)
EV_TAKE_COALESCE = 19  # hot-key take-n rows in a tick (arg = tickets folded)

EVENT_NAMES = {
    EV_TICK: "engine.tick",
    EV_STAGING_LEASE: "staging.lease",
    EV_STAGING_RECYCLE: "staging.recycle",
    EV_H2D_PUT: "h2d.put",
    EV_COMMIT_DISPATCH: "commit.dispatch",
    EV_COMMIT_COMPLETE: "commit.complete",
    EV_RX_DECODE: "rx.decode",
    EV_FOLD: "fold",
    EV_BROADCAST_TX: "broadcast.tx",
    EV_AE_PHASE: "ae.phase",
    EV_TAKE: "take",
    EV_ANOMALY: "anomaly",
    EV_DELTA_PACK: "delta.pack",
    EV_DELTA_ACK: "delta.ack",
    EV_DELTA_RETRANSMIT: "delta.retransmit",
    EV_DEVICE_READY: "device.ready",
    EV_AUDIT_TICK: "audit.tick",
    EV_AUDIT_COMPARE: "audit.compare",
    EV_TAKE_COALESCE: "take.coalesce",
}

AE_PHASES = {"trigger": 1, "digest": 2, "fetch": 3}

RING_SIZE = max(64, int(os.environ.get("PATROL_TRACE_RING", 4096)))


class _Ring:
    """One thread's fixed-size event ring. Parallel plain lists, single
    writer (the owning thread); readers copy — a torn read corrupts at
    most the event being written, never the reader."""

    __slots__ = ("tid", "name", "size", "etype", "t_ns", "dur_ns", "arg", "pos", "count")

    def __init__(self, tid: int, name: str, size: int):
        self.tid = tid
        self.name = name
        self.size = size
        self.etype = [0] * size
        self.t_ns = [0] * size
        self.dur_ns = [0] * size
        self.arg = [0] * size
        self.pos = 0
        self.count = 0

    def events(self) -> List[tuple]:
        """Oldest-first copy of the live events (reader-side)."""
        et = list(self.etype)
        ts = list(self.t_ns)
        du = list(self.dur_ns)
        ar = list(self.arg)
        n = min(self.count, self.size)
        pos = self.pos
        out = []
        for k in range(n):
            i = (pos - n + k) % self.size
            if et[i]:
                out.append((et[i], ts[i], du[i], ar[i]))
        return out


class FlightRecorder:
    """The process-wide recorder. ``enabled`` is the single hot-path
    gate: call sites read it once and skip the record call entirely when
    off (``if TRACE.enabled: TRACE.record(...)``)."""

    def __init__(self, size: int = RING_SIZE):
        self.enabled = os.environ.get("PATROL_TRACE", "1") != "0"
        self.size = size
        self._tls = threading.local()
        self._reg_mu = threading.Lock()
        self._rings: List[_Ring] = []
        self._snap_mu = threading.Lock()
        self._snapshots: deque = deque(maxlen=4)
        self._last_anomaly: Dict[str, float] = {}

    def _ring(self) -> _Ring:
        ring = getattr(self._tls, "ring", None)
        if ring is None:
            t = threading.current_thread()
            ring = _Ring(t.ident or 0, t.name, self.size)
            self._tls.ring = ring
            with self._reg_mu:
                self._rings.append(ring)
        return ring

    def record(self, etype: int, dur_ns: int = 0, arg: int = 0) -> None:
        """Record one completed event on the calling thread's ring.
        Lock-free: this thread is the ring's only writer."""
        if not self.enabled:
            return
        ring = self._ring()
        i = ring.pos
        ring.etype[i] = etype
        ring.t_ns[i] = time.perf_counter_ns()
        ring.dur_ns[i] = dur_ns
        ring.arg[i] = arg
        ring.pos = (i + 1) % ring.size
        ring.count += 1

    # -- dump / snapshot -----------------------------------------------------

    def dump(self) -> List[dict]:
        """All rings' live events as plain dicts (oldest-first per ring)."""
        with self._reg_mu:
            rings = list(self._rings)
        out = []
        for ring in rings:
            for etype, t_ns, dur_ns, arg in ring.events():
                out.append(
                    {
                        "type": EVENT_NAMES.get(etype, str(etype)),
                        "t_ns": t_ns,
                        "dur_ns": dur_ns,
                        "arg": arg,
                        "tid": ring.tid,
                        "thread": ring.name,
                    }
                )
        return out

    def chrome_trace(self, events: Optional[List[dict]] = None) -> bytes:
        """Chrome-trace/Perfetto JSON ('X' complete events, µs scale)."""
        evs = self.dump() if events is None else events
        trace_events = [
            {
                "name": e["type"],
                "ph": "X",
                "ts": e["t_ns"] / 1000.0,
                "dur": e["dur_ns"] / 1000.0,
                "pid": os.getpid(),
                "tid": e["tid"],
                "args": {"arg": e["arg"], "thread": e["thread"]},
            }
            for e in evs
        ]
        return json.dumps(
            {"traceEvents": trace_events, "displayTimeUnit": "ms"}
        ).encode()

    def snapshot(self, reason: str) -> Optional[dict]:
        """Freeze the current rings under a reason tag (bounded, newest
        kept). Damped to one per reason per second — an anomaly storm
        must not turn the recorder into its own hot path."""
        now = time.monotonic()
        with self._snap_mu:
            if now - self._last_anomaly.get(reason, -1e9) < 1.0:
                return None
            self._last_anomaly[reason] = now
        snap = {
            "reason": reason,
            "at_ns": time.perf_counter_ns(),
            "events": self.dump(),
        }
        with self._snap_mu:
            self._snapshots.append(snap)
        profiling.COUNTERS.inc("trace_anomaly_snapshots")
        return snap

    def snapshots(self) -> List[dict]:
        with self._snap_mu:
            return list(self._snapshots)


TRACE = FlightRecorder()


def anomaly(reason: str) -> None:
    """Anomaly hook: mark the ring and auto-snapshot it (take stall,
    convergence-budget breach, engine tick failure)."""
    if TRACE.enabled:
        TRACE.record(EV_ANOMALY, 0, 0)
    TRACE.snapshot(reason)


# -- cross-node take spans ---------------------------------------------------


class SpanCollector:
    """Bounded collector of completed spans (local takes + remote
    decode/merge joined by the propagated trace id). One per process —
    in-process multi-node tests see both nodes' spans here, disambiguated
    by the ``node`` field."""

    def __init__(self, cap: int = 4096):
        self._mu = threading.Lock()
        self._spans: deque = deque(maxlen=cap)

    def add(
        self,
        trace_id: int,
        node: int,
        kind: str,
        bucket: str,
        t_ns: int,
        dur_ns: int,
    ) -> None:
        with self._mu:
            self._spans.append(
                {
                    "trace_id": trace_id,
                    "node": node,
                    "kind": kind,
                    "bucket": bucket,
                    "t_ns": t_ns,
                    "dur_ns": dur_ns,
                }
            )

    def export(self, trace_id: Optional[int] = None) -> List[dict]:
        with self._mu:
            spans = list(self._spans)
        if trace_id is not None:
            spans = [s for s in spans if s["trace_id"] == trace_id]
        return spans

    def clear(self) -> None:
        with self._mu:
            self._spans.clear()


SPANS = SpanCollector()

# Take sampling: 0 = off (default), N = every Nth take gets a trace id.
_take_sample = int(os.environ.get("PATROL_TRACE_SAMPLE", "0"))
_take_counter = itertools.count(1)
# Process tag keeps ids from colliding across real multi-process nodes;
# the monotone counter keeps them unique within one process (shared by
# every in-process node).
_ID_TAG = (os.getpid() & 0x7FFF) << 48


def set_take_sampling(n: int) -> None:
    """1-in-``n`` take sampling; 0 disables. Runtime-settable (tests,
    operator resync debugging)."""
    global _take_sample
    _take_sample = max(0, int(n))


def take_sampling() -> int:
    return _take_sample


def sample_take() -> Optional[int]:
    """Next take's trace id, or None when unsampled/off. Called once per
    ticket creation; the off path is one global read + branch."""
    n = _take_sample
    if not n:
        return None
    c = next(_take_counter)
    if c % n:
        return None
    profiling.COUNTERS.inc("trace_take_samples")
    return _ID_TAG | (c & 0xFFFFFFFFFFFF)
