"""TPURepo — the device-backed implementation of the reference's keystone
``Repo`` seam (repo.go:13-18), plus the incast request logic of
``ReplicatedRepo.GetBucket`` (repo.go:96-106).

The hot path is the *fused* :meth:`take` (get-or-create + take + upsert +
broadcast in one engine tick), because splitting it into the reference's
three calls would cost three device round-trips. The classic
``get_bucket`` / ``upsert_bucket`` pair is still provided for parity,
introspection and tests — ``get_bucket`` returns a scalar *view* of the
PN state (value = capacity base + Σadded − Σtaken).
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Callable, List, Optional, Tuple



from patrol_tpu.ops.rate import Rate
from patrol_tpu.ops import wire
from patrol_tpu.runtime.bucket import Bucket
from patrol_tpu.runtime.engine import DeviceEngine, TakeTicket

IncastFn = Callable[[str], None]


class TPURepo:
    """Facade over the device engine: fused takes, incast-on-miss with
    singleflight-style dedup (≙ golang.org/x/sync/singleflight at
    repo.go:26,99-103), delta ingest, and Repo-seam compatibility."""

    def __init__(
        self,
        engine: DeviceEngine,
        send_incast: Optional[IncastFn] = None,
        incast_ttl_s: float = 1.0,
    ):
        self.engine = engine
        self.send_incast = send_incast
        self._incast_ttl_s = incast_ttl_s
        self._incast_mu = threading.Lock()
        self._incast_inflight: dict = {}

    # -- hot path -----------------------------------------------------------

    def submit_take(
        self, name: str, rate: Rate, count: int, now_ns: Optional[int] = None
    ) -> TakeTicket:
        ticket, created = self.engine.submit_take(name, rate, count, now_ns)
        if created:
            # First sight of this bucket: ask the cluster for its state
            # asynchronously (repo.go:96-106). The local request proceeds
            # against the fresh bucket; convergence is eventual.
            self._maybe_incast(name)
        return ticket

    def submit_takes_batch(self, names, rates, counts):
        """Batched :meth:`submit_take` (native HTTP pump): one engine
        directory pass, then the per-created incast solicitations.
        → [(ticket, created), ...] or None on a fully-pinned pool."""
        res = self.engine.submit_takes_batch(names, rates, counts)
        if res is None:
            return None
        for (ticket, created), name in zip(res, names):
            if created:
                self._maybe_incast(name)
        return res

    def take(
        self, name: str, rate: Rate, count: int, now_ns: Optional[int] = None
    ) -> Tuple[int, bool]:
        ticket = self.submit_take(name, rate, count, now_ns)
        ticket.wait()
        return ticket.remaining, ticket.ok

    async def take_async(
        self, name: str, rate: Rate, count: int, now_ns: Optional[int] = None
    ) -> Tuple[int, bool]:
        ticket = self.submit_take(name, rate, count, now_ns)
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def _done() -> None:
            loop.call_soon_threadsafe(
                lambda: fut.done() or fut.set_result((ticket.remaining, ticket.ok))
            )

        ticket.add_done_callback(_done)
        return await fut

    def _maybe_incast(self, name: str) -> None:
        if self.send_incast is None:
            return
        now = time.monotonic()
        with self._incast_mu:
            deadline = self._incast_inflight.get(name, 0.0)
            if deadline > now:
                return  # already in flight — dedup
            self._incast_inflight[name] = now + self._incast_ttl_s
            if len(self._incast_inflight) > 4096:
                self._incast_inflight = {
                    k: v for k, v in self._incast_inflight.items() if v > now
                }
        self.send_incast(name)

    # -- replication ingest -------------------------------------------------

    def apply_delta(self, state: wire.WireState, slot: int, scalar: bool = False) -> None:
        self.engine.ingest_delta(state, slot, scalar=scalar)

    def snapshot(self, name: str) -> List[wire.WireState]:
        return self.engine.snapshot(name)

    # -- Repo-seam compatibility (repo.go:13-18) ----------------------------

    def get_bucket(self, name: str) -> Tuple[Bucket, bool]:
        """Scalar view of a bucket. Creates the row if absent (stamping
        ``created`` from the engine clock, repo.go:205). Mutating the
        returned view does not write back to device state."""
        row = self.engine.directory.lookup(name)
        existed = row is not None
        if row is None:
            # assign_row (not directory.assign): evicts idle rows when the
            # pool is spent, so keyspace > pool stays a supported state on
            # the introspection surface too.
            row, _ = self.engine.assign_row(name, self.engine.clock())
            self._maybe_incast(name)
        pn, elapsed = self.engine.row_view(row)  # host- or device-resident
        base = int(self.engine.directory.cap_base_nt[row])
        return (
            Bucket(
                name=name,
                added_nt=base + int(pn[:, 0].sum()),
                taken_nt=int(pn[:, 1].sum()),
                elapsed_ns=int(elapsed),
                created_ns=int(self.engine.directory.created_ns[row]),
            ),
            existed,
        )

    def upsert_bucket(self, b: Bucket) -> Tuple[Bucket, bool]:
        """Merge a host bucket's scalar state into this node's lane (a join
        is always safe: lanes only grow). Returns the refreshed view."""
        existed = self.engine.directory.lookup(b.name) is not None
        self.engine.ingest_delta(
            wire.from_nanotokens(b.name, b.added_nt, b.taken_nt, b.elapsed_ns),
            slot=self.engine.node_slot,
        )
        self.engine.flush()
        view, _ = self.get_bucket(b.name)
        return view, existed

    def tokens(self, name: str) -> int:
        return self.engine.tokens(name)

    def tokens_if_known(self, name: str) -> Optional[int]:
        """Balance introspection with existence: ``None`` for a bucket this
        node has never seen (the HTTP /tokens route's 404), else the whole-
        token balance. Keeps API handlers on the repo facade rather than
        reaching into engine internals; the engine closes the
        eviction/rebind race with a post-read re-lookup."""
        return self.engine.tokens_if_known(name)
