"""Host-side token bucket with CRDT PN-counter semantics, and LocalRepo.

This is the *exact-semantics host model* of the reference's core
(bucket.go:17-263, repo.go:171-235). It exists for three reasons:

1. It is the differential-testing oracle for the batched device kernels in
   :mod:`patrol_tpu.ops.take` / :mod:`patrol_tpu.ops.merge` — every kernel
   behavior is cross-checked against this model.
2. Its arithmetic is the semantic model for the LIVE host fast path
   (``runtime/engine.py HostLanes`` — per-lane state, same take math):
   cold/low-QPS buckets are served in-process, µs-class, and promoted to
   the device path when hot (VERDICT r3 item 1; see tests/test_fastpath.py
   for the host/device equivalence laws).
3. It preserves the reference's ``Repo`` seam (repo.go:13-18) so the API and
   replication layers are backend-agnostic.

Unlike the reference's float64 scalars, counters here are integer
*nanotokens* (1 token = 1e9 nanotokens) so that host and device state merge
bit-identically. The arithmetic inside :meth:`Bucket.take` mirrors the
reference's float64 math (bucket.go:186-225) before quantizing the committed
grant to nanotokens.
"""

from __future__ import annotations

import dataclasses
import threading
import time as _time
from typing import Callable, Dict, Iterable, Tuple

from patrol_tpu.ops.rate import Rate, format_duration

NANO = 1_000_000_000

ClockFn = Callable[[], int]  # returns epoch nanoseconds


def system_clock() -> int:
    """Default clock: wall time in epoch nanoseconds (UTC)."""
    return _time.time_ns()


def offset_clock(offset_ns: int, base: ClockFn = system_clock) -> ClockFn:
    """Clock skewed by a fixed offset — the reference's ``-clock-offset``
    fault-injection seam (cmd/patrol/main.go:30,35-37)."""
    return lambda: base() + offset_ns


@dataclasses.dataclass
class Bucket:
    """A token bucket whose counters form a state-based CRDT.

    ``added_nt`` / ``taken_nt`` are this *bucket's scalar view* in nanotokens
    (like the reference's ``added`` / ``taken`` floats, bucket.go:24-27);
    ``elapsed_ns`` is the G-counter of time consumed by successful takes;
    ``created_ns`` is the node-local creation timestamp that is deliberately
    never serialized (bucket.go:28-31, README.md:49-62) — clock-skew
    independence comes from replicating only the relative ``elapsed``.
    """

    name: str = ""
    added_nt: int = 0
    taken_nt: int = 0
    elapsed_ns: int = 0
    created_ns: int = 0

    def __post_init__(self) -> None:
        self._mu = threading.RLock()

    # -- introspection (bucket.go:156-182,228-236) --------------------------

    def tokens(self) -> int:
        """Whole tokens in the bucket: ``uint64(added - taken)`` truncation
        (bucket.go:156-161), clamped at zero (the Go float→uint64 cast of a
        negative value is undefined behavior we do not reproduce)."""
        with self._mu:
            nt = self.added_nt - self.taken_nt
        return max(nt, 0) // NANO

    def is_zero(self) -> bool:
        """True when all replicated state is zero (bucket.go:163-170).

        On the wire this doubles as the incast request marker (repo.go:78-90).
        """
        with self._mu:
            return self.added_nt == 0 and self.taken_nt == 0 and self.elapsed_ns == 0

    def __str__(self) -> str:
        with self._mu:
            return (
                f"Bucket{{name: {self.name!r}, "
                f"tokens: {(self.added_nt - self.taken_nt) / NANO:f}, "
                f"elapsed: {format_duration(self.elapsed_ns)}, "
                f"created: {self.created_ns}}}"
            )

    def log_fields(self) -> dict:
        """Structured-log rendering (bucket.go:173-182)."""
        with self._mu:
            return {
                "name": self.name,
                "added": self.added_nt / NANO,
                "taken": self.taken_nt / NANO,
                "elapsed": format_duration(self.elapsed_ns),
                "created": self.created_ns,
            }

    # -- the hot arithmetic (bucket.go:186-225) -----------------------------

    def take(self, now_ns: int, rate: Rate, n: int) -> Tuple[int, bool]:
        """Attempt to take ``n`` tokens at time ``now_ns`` with fill ``rate``.

        Returns ``(remaining_tokens, ok)``. Mirrors bucket.go:186-225
        step-for-step: lazy capacity init, monotonic-time guard, refill from
        elapsed time capped at capacity (the cap can be *negative*, forfeiting
        excess tokens — reference behavior), conditional commit.
        """
        with self._mu:
            # Burst capacity in nanotokens (bucket.go:192).
            capacity_nt = rate.freq * NANO

            if self.added_nt == 0:
                # Lazy init commits even when the take below fails
                # (bucket.go:194-196).
                self.added_nt = capacity_nt

            last = self.created_ns + self.elapsed_ns
            if now_ns < last:
                last = now_ns

            tokens_nt = self.added_nt - self.taken_nt
            elapsed = now_ns - last

            # Refill due to elapsed time, in nanotokens, quantized by floor.
            added_nt = int(rate.tokens(elapsed) * NANO)
            missing_nt = capacity_nt - tokens_nt
            if added_nt > missing_nt:
                added_nt = missing_nt

            take_nt = n * NANO
            have_nt = tokens_nt + added_nt
            if take_nt > have_nt:
                return max(have_nt, 0) // NANO, False

            self.elapsed_ns += elapsed
            self.added_nt += added_nt
            self.taken_nt += take_nt
            return max(self.added_nt - self.taken_nt, 0) // NANO, True

    # -- the CRDT join (bucket.go:240-263) ----------------------------------

    def merge(self, *others: "Bucket") -> None:
        """Join: field-wise max of added, taken, elapsed.

        Commutative, associative, idempotent — the CvRDT laws the property
        tests pin down (bucket_test.go:68-114). Locks are taken in id() order
        to avoid the ABBA deadlock the reference's self-then-other ordering
        permits under concurrent cross-merges (bucket.go:240-263).
        """
        for other in others:
            if other is self:
                continue
            first, second = (
                (self, other) if id(self) < id(other) else (other, self)
            )
            with first._mu, second._mu:
                if self.added_nt < other.added_nt:
                    self.added_nt = other.added_nt
                if self.taken_nt < other.taken_nt:
                    self.taken_nt = other.taken_nt
                if self.elapsed_ns < other.elapsed_ns:
                    self.elapsed_ns = other.elapsed_ns


class Repo:
    """The keystone storage seam (repo.go:13-18).

    Implementations must be safe for concurrent use. The API layer is written
    against this interface; replication decorates it; the TPU runtime
    implements it with device-resident state.
    """

    def get_bucket(self, name: str) -> Tuple[Bucket, bool]:
        raise NotImplementedError

    def upsert_bucket(self, b: Bucket) -> Tuple[Bucket, bool]:
        raise NotImplementedError


class LocalRepo(Repo):
    """In-memory bucket store (repo.go:171-235).

    Get-or-create stamps ``created`` from the injected clock (repo.go:205);
    upsert keeps the identity fast path (repo.go:220) and otherwise merges
    (repo.go:233).
    """

    def __init__(self, clock: ClockFn, buckets: Iterable[Bucket] = ()) -> None:
        self._clock = clock
        self._mu = threading.Lock()
        self._buckets: Dict[str, Bucket] = {b.name: b for b in buckets}

    def get_bucket(self, name: str) -> Tuple[Bucket, bool]:
        # Python dict reads are atomic under the GIL; the lock only guards
        # the create path (the reference uses an RWMutex + double-checked
        # locking, repo.go:189-211).
        b = self._buckets.get(name)
        if b is not None:
            return b, True
        with self._mu:
            b = self._buckets.get(name)
            if b is None:
                b = Bucket(name=name, created_ns=self._clock())
                self._buckets[name] = b
                return b, False
        return b, True

    def upsert_bucket(self, b: Bucket) -> Tuple[Bucket, bool]:
        prev = self._buckets.get(b.name)
        if prev is b:  # Identity fast path (repo.go:220).
            return prev, True
        with self._mu:
            prev = self._buckets.get(b.name)
            if prev is None:
                b.created_ns = self._clock()
                self._buckets[b.name] = b
                return b, False
        prev.merge(b)
        return prev, True

    def __len__(self) -> int:
        return len(self._buckets)
