"""Checkpoint / resume — an aux subsystem the reference lacks entirely
(SURVEY §5: state is purely in-memory, repo.go:172-176; durability is
replication itself, with incast as the only recovery path).

The dense-tensor layout makes checkpointing trivial and exact: the whole
replicated CRDT is two int64 arrays, and the host metadata is one JSON
object. A restored node resumes with its full PN state instead of
rebuilding lazily bucket-by-bucket via incast — and because state is a
join-semilattice, restoring a *stale* checkpoint is always safe: the next
merges simply catch it up (the same property that makes UDP loss safe).

Format: ``<dir>/state.npz`` (pn, elapsed) + ``<dir>/directory.json``
(name→row, created_ns, cap_base_nt, node_slot, shape), written atomically
via rename.
"""

from __future__ import annotations

import json
import os
import tempfile

import numpy as np

FORMAT_VERSION = 1


def save(directory: str, engine, membership: dict | None = None) -> str:
    """Snapshot an engine's device state + host directory. Returns the dir.

    Safe to call while the engine is live: drains queued work first, then
    reads under the state lock.

    ``membership`` (patrol-membership, ROADMAP 3b): the node's
    ``SlotTable.view()`` at snapshot time. Rides as an extra meta key —
    older builds restoring this checkpoint ignore it — and a restarting
    node reads it back via :func:`load_membership` to pin itself onto its
    ORIGINAL lane (``SlotTable(self_slot=...)``) before the rejoin
    handshake, so its checkpointed PN spend and its live lane line up.
    """
    os.makedirs(directory, exist_ok=True)
    engine.flush()
    # Atomic copy-and-join view: host-resident lanes are max-joined into
    # the snapshot under the host lock (no promotion can slip between the
    # device copy and the join), and residency is untouched — a periodic
    # checkpoint must not erode the host fast path bucket by bucket.
    pn, elapsed = engine.snapshot_planes()

    d = engine.directory
    rows = dict(d._rows)  # name -> row
    meta = {
        "version": FORMAT_VERSION,
        "node_slot": engine.node_slot,
        "buckets": engine.config.buckets,
        "nodes": engine.config.nodes,
        "rows": rows,
        "created_ns": {str(r): int(d.created_ns[r]) for r in rows.values()},
        "cap_base_nt": {str(r): int(d.cap_base_nt[r]) for r in rows.values()},
        # GC tombstones (ROADMAP 4c): a reclaimed bucket's own-lane
        # residue must survive a restart, or the stale-echo window the
        # tombstone closes re-opens — a peer echoing pre-reclaim lane
        # values into the restarted node would absorb (erase) the
        # reclaimed spend. Written as an extra key, so older builds
        # restoring this checkpoint simply ignore it (format-compatible
        # both ways).
        "tombstones": {
            name: list(tomb) for name, tomb in d.export_tombstones().items()
        },
    }
    if membership is not None:
        meta["membership"] = membership

    # Atomic write: temp files + rename.
    fd, tmp_npz = tempfile.mkstemp(dir=directory, suffix=".npz.tmp")
    os.close(fd)
    with open(tmp_npz, "wb") as f:
        np.savez(f, pn=pn, elapsed=elapsed)
    os.replace(tmp_npz, os.path.join(directory, "state.npz"))

    fd, tmp_json = tempfile.mkstemp(dir=directory, suffix=".json.tmp")
    os.close(fd)
    with open(tmp_json, "w") as f:
        json.dump(meta, f)
    os.replace(tmp_json, os.path.join(directory, "directory.json"))
    return directory


def load_membership(directory: str) -> dict | None:
    """The membership view saved with the checkpoint, or ``None`` (absent
    file, pre-membership checkpoint). Read at boot BEFORE the engine is
    built: the ``self_slot`` inside pins the restarting node to its
    original lane."""
    path = os.path.join(directory, "directory.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            meta = json.load(f)
    except (OSError, ValueError):
        return None
    mem = meta.get("membership")
    return mem if isinstance(mem, dict) else None


def exists(directory: str) -> bool:
    return os.path.exists(os.path.join(directory, "state.npz")) and os.path.exists(
        os.path.join(directory, "directory.json")
    )


def restore(directory: str, engine) -> int:
    """Load a checkpoint into a fresh engine (same shape config). Restores
    device planes via a dense max-join — so restoring onto a non-empty
    engine is also safe (CRDT join, never a rollback). Returns the number
    of buckets restored."""
    with open(os.path.join(directory, "directory.json")) as f:
        meta = json.load(f)
    if meta.get("version") != FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint version {meta.get('version')}")
    if meta["buckets"] != engine.config.buckets or meta["nodes"] != engine.config.nodes:
        raise ValueError(
            "checkpoint shape mismatch: "
            f"ckpt ({meta['buckets']}×{meta['nodes']}) vs "
            f"engine ({engine.config.buckets}×{engine.config.nodes})"
        )

    # Any live host-resident rows move device-side before the join: a
    # restored name could collide with a hosted row, and the max-join
    # below only sees device planes. flush_hosted raises on timeout —
    # proceeding would silently restore into still-hosted rows. Idle
    # demotion is paused across the whole flush→load→join sequence: a
    # demotion in the gap would zero the very device rows the join is
    # about to land on (the restored spend would be stranded where the
    # host path never reads it — or erased outright by the demotion's
    # zero racing the join).
    engine._demotion_paused = True
    try:
        engine.flush_hosted()
        engine.flush()

        data = np.load(os.path.join(directory, "state.npz"))
        import jax.numpy as jnp

        from patrol_tpu.models.limiter import LimiterState

        restored = LimiterState(
            pn=jnp.asarray(data["pn"]), elapsed=jnp.asarray(data["elapsed"])
        )
        with engine._state_mu:
            engine.state = LimiterState(
                pn=jnp.maximum(engine.state.pn, restored.pn),
                elapsed=jnp.maximum(engine.state.elapsed, restored.elapsed),
            )

        d = engine.directory
        with d._mu:
            for name, row in meta["rows"].items():
                row = int(row)
                # Full bind (not just the dict): sets _bound (eviction
                # eligibility), name bytes + hash, and the resolve-table
                # entry so restored buckets are hash-resolvable by the
                # wire rx path.
                d._bind_locked(name, row, int(meta["created_ns"][str(row)]))
                d.cap_base_nt[row] = int(meta["cap_base_nt"][str(row)])
                d._next_fresh = max(d._next_fresh, row + 1)
        # Tombstones restore AFTER the binds: restore_tombstones skips
        # names the checkpoint re-bound (their lanes carry the spend).
        # Absent on pre-tombstone checkpoints — restoring those keeps the
        # old (stale-echo-exposed) behavior rather than failing.
        d.restore_tombstones(meta.get("tombstones", {}))
        return len(meta["rows"])
    finally:
        engine._demotion_paused = False
