"""Native host-lane store: the C++ twin of the engine's HostLanes tier.

VERDICT r4 item 1: the reference serves the ENTIRE /take decision natively
in-process (api.go:51-86 → bucket.go:186-225), while every patrol take
still crossed into Python — the C++ front parsed the request, the C++
directory resolved the name, and then the interpreter ran ~40 lines of
integer arithmetic per request (saturated config #1: 18.6k rps vs the
482k compiled baseline). This module moves the host-resident lane state
into plain int64 blocks owned by the C++ library (patrol_http.cpp
HostStore), so:

* the epoll thread serves host-resident takes entirely in C++ — resolve
  (pt_dir_resolve_rt), lane arithmetic (hls_take_locked, a step-for-step
  mirror of HostLanes.take), response formatting — with zero Python;
* the engine keeps running its EXISTING HostLanes code paths (rx absorb,
  snapshot, checkpoint join, promotion drain) unchanged: each block is
  exposed to Python as numpy views (:class:`NativeHostLanes`, same
  attribute surface as HostLanes), and the engine's ``_host_mu`` becomes
  :class:`NativeHostMutex` — the SAME native mutex the epoll thread
  takes, so both sides serialize on one lock;
* broadcasts coalesce: the C++ take path marks rows dirty; the pump
  drains the dirty set and emits each row's LATEST full state once per
  drain — semantically lossless for a state-based CvRDT (a later state
  subsumes every earlier one), and it bounds replication traffic at
  rows×drain-rate instead of the reference's takes×peers packets
  (repo.go:123-158).

Block layout (int64 words): added[nodes] | taken[nodes] | elapsed_ns |
win_start_ns | win_takes | win_rx | resident | dirty. Blocks are immortal
until store destroy, so Python views stay valid across unhost/re-host.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Tuple

import numpy as np

from patrol_tpu import native

# Native take-pressure promotion threshold (takes per window). Default 0 =
# disabled: an in-front take costs ~0.2 µs, so unlike the Python host path
# there is no per-bucket QPS past which the device tick answers ONE row's
# takes faster — promotion stays rx-pressure/scalar-driven (those signals
# ride the Python paths, whose thresholds are unchanged).
NATIVE_PROMOTE_TAKES = int(os.environ.get("PATROL_NATIVE_PROMOTE_TAKES", 0))


class NativeHostLanes:
    """numpy-view proxy over one C++ host-lane block, presenting the exact
    HostLanes attribute surface (``added``/``taken`` int64 lane views,
    scalar properties, ``roll_window``/``take``) so every engine code path
    that touches host lanes runs unchanged on the shared memory. All
    mutation happens under the engine's ``_host_mu`` — which IS the C++
    store mutex (:class:`NativeHostMutex`), so the epoll thread's inline
    takes serialize with it."""

    __slots__ = ("added", "taken", "_sc")

    def __init__(self, ptr: int, nodes: int):
        words = 2 * nodes + 6
        buf = (ctypes.c_int64 * words).from_address(ptr)
        blk = np.ctypeslib.as_array(buf)
        self.added = blk[:nodes]
        self.taken = blk[nodes : 2 * nodes]
        self._sc = blk[2 * nodes :]

    @property
    def elapsed_ns(self) -> int:
        return int(self._sc[0])

    @elapsed_ns.setter
    def elapsed_ns(self, v: int) -> None:
        self._sc[0] = v

    @property
    def win_start_ns(self) -> int:
        return int(self._sc[1])

    @win_start_ns.setter
    def win_start_ns(self, v: int) -> None:
        self._sc[1] = v

    @property
    def win_takes(self) -> int:
        return int(self._sc[2])

    @win_takes.setter
    def win_takes(self, v: int) -> None:
        self._sc[2] = v

    @property
    def win_rx(self) -> int:
        return int(self._sc[3])

    @win_rx.setter
    def win_rx(self, v: int) -> None:
        self._sc[3] = v

    # Exact semantic reuse: these are the HostLanes methods themselves,
    # bound to this proxy — one implementation, two backings.
    # (Assigned in _bind_methods below to dodge a circular import.)


def _bind_methods() -> None:
    from patrol_tpu.runtime.engine import HostLanes

    NativeHostLanes.roll_window = HostLanes.roll_window
    NativeHostLanes.take = HostLanes.take


class NativeHostMutex:
    """Context-manager wrapper over the store's native mutex — drop-in for
    the engine's ``threading.Lock`` ``_host_mu``. ctypes releases the GIL
    for the blocking acquire; the epoll thread never takes the GIL, so
    the lock order is cycle-free."""

    __slots__ = ("_lib", "_h")

    def __init__(self, lib, h: int):
        self._lib = lib
        self._h = h

    def __enter__(self):
        self._lib.pt_hls_lock(self._h)
        return self

    def __exit__(self, *exc):
        self._lib.pt_hls_unlock(self._h)
        return False


class NativeHostStore:
    """Engine-side handle for the C++ host-lane store."""

    def __init__(self, lib, h: int, nodes: int, directory):
        self.lib = lib
        self.h = h
        self.nodes = nodes
        self.directory = directory
        self._dirty = np.zeros(4096, np.int32)
        # Per-dirty-row C++ lane snapshot: added[nodes]|taken[nodes]|elapsed.
        self._snap = np.zeros((4096, 2 * nodes + 1), np.int64)
        self._promote = np.zeros(1024, np.int32)
        self._np = ctypes.c_int(0)
        self._closed = False
        _bind_methods()

    @classmethod
    def create(
        cls,
        nodes: int,
        node_slot: int,
        directory,
        clock_offset_ns: int,
        window_ns: int,
        promote_takes: Optional[int] = None,
    ) -> Optional["NativeHostStore"]:
        if promote_takes is None:
            promote_takes = NATIVE_PROMOTE_TAKES
        lib = native.load()
        if lib is None or directory._ptdir < 0:
            return None
        h = lib.pt_hls_create(
            nodes, node_slot, promote_takes, window_ns, clock_offset_ns,
            directory.cap_base_nt, directory.created_ns,
            directory.last_used_ns,
        )
        if h < 0:
            return None
        return cls(lib, h, nodes, directory)

    def mutex(self) -> NativeHostMutex:
        return NativeHostMutex(self.lib, self.h)

    # -- callers hold the store mutex (the engine's _host_mu) ---------------

    def host_locked(self, row: int) -> NativeHostLanes:
        ptr = self.lib.pt_hls_host_locked(self.h, row)
        if ptr == 0:
            raise MemoryError("pt_hls_host_locked failed")
        return NativeHostLanes(ptr, self.nodes)

    def unhost_locked(self, row: int) -> None:
        self.lib.pt_hls_unhost_locked(self.h, row)

    def drain_locked(self) -> Tuple[List[int], np.ndarray, List[int]]:
        """→ (dirty_rows, lane_snapshots[nd, 2*nodes+1], promote_rows);
        clears both queues. The snapshots are taken in C++ under the held
        lock — the caller does its per-row work (wire building) OUTSIDE
        the lock against the copies."""
        nd = self.lib.pt_hls_drain_locked(
            self.h, self._dirty, self._snap, len(self._dirty),
            self._promote, len(self._promote), ctypes.byref(self._np),
        )
        if nd <= 0 and self._np.value <= 0:
            return [], self._snap[:0], []
        nd = max(nd, 0)
        return (
            self._dirty[:nd].tolist(),
            self._snap[:nd],
            self._promote[: self._np.value].tolist(),
        )

    def drain_promotes_locked(self) -> List[int]:
        """Pop ONLY the promote queue (zero dirty-row capacity leaves the
        broadcast queue and its dirty flags in place for the cadence-gated
        drain). Used by the pump's promotions-only fast path."""
        out: List[int] = []
        while True:
            self.lib.pt_hls_drain_locked(
                self.h, self._dirty, self._snap, 0,
                self._promote, len(self._promote), ctypes.byref(self._np),
            )
            n = self._np.value
            if n <= 0:
                return out
            out.extend(self._promote[:n].tolist())

    # -- lock-free ----------------------------------------------------------

    @property
    def events(self) -> int:
        """Promotion-event counter: bumped by the C++ take path only on
        take-pressure threshold crossings. Lock-free read."""
        return int(self.lib.pt_hls_events(self.h))

    def stats(self) -> dict:
        out = np.zeros(4, np.uint64)
        self.lib.pt_hls_stats(self.h, out)
        return {
            "native_host_takes": int(out[0]),
            "native_host_resident": int(out[1]),
            "native_host_blocks": int(out[2]),
        }

    @property
    def native_takes(self) -> int:
        out = np.zeros(4, np.uint64)
        self.lib.pt_hls_stats(self.h, out)
        return int(out[0])

    def destroy(self) -> None:
        """Free the store. The HTTP front must be detached and no proxy
        views may be touched afterwards (engine.stop ordering)."""
        if not self._closed:
            self._closed = True
            self.lib.pt_hls_destroy(self.h)
