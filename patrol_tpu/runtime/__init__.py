"""Host runtime: bucket directory, microbatcher, repos (host and TPU)."""
