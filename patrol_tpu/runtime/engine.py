"""Device engine: the microbatching feeder between concurrent host callers
and single-device kernel calls.

The reference's concurrency story is goroutine-per-request + a mutex per
bucket + one single-threaded UDP merge loop (bucket.go:21, repo.go:54-92).
The TPU-native inversion: *batching replaces locking*. All mutation of
limiter state happens on one engine thread that drains two queues — take
tickets and replication deltas — into padded, fixed-shape kernel calls:

    submit_take()/ingest_delta()  →  queues  →  engine tick (feeder):
        merge_batch(deltas)   one scatter-max call (async dispatch)
        take_batch(groups)    one fused take call (async dispatch)
    completion pipeline (completer thread):
        read results, complete tickets, emit broadcast states

Natural batching: the feeder dispatches immediately when work exists;
requests that arrive during a device call form the next batch, so batch size
adapts to load and idle latency stays at one device round-trip. Completion
(the host-side fanout) runs on its own thread and overlaps the next tick's
device compute — see _enqueue_completion.

Hot buckets are coalesced algebraically (see ops/take.py): identical
(bucket, rate, count) tickets become one kernel row with ``nreq``; a bucket
appearing with *different* rate/count in the same tick is deferred one tick
to preserve the unique-rows kernel invariant (sequential semantics).
"""

from __future__ import annotations

import contextlib
import itertools
import logging
import os
import threading
import time
from collections import deque
from functools import lru_cache
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from patrol_tpu.models.limiter import NANO, LimiterConfig, LimiterState, init_state
from patrol_tpu.utils import histogram as hist
from patrol_tpu.utils import profiling
from patrol_tpu.utils import trace as trace_mod
from patrol_tpu.ops import commit as commit_mod
from patrol_tpu.ops import delta as delta_ops
from patrol_tpu.ops import merge as merge_mod
from patrol_tpu.ops import wire
from patrol_tpu.ops.merge import (
    MergeBatch,
    merge_batch,
    merge_scalar_batch,
    read_rows,
    zero_rows_jit,
)
from patrol_tpu.ops.rate import Rate
from patrol_tpu.ops.take import (
    TAKE_PACK_ROWS,
    remaining_for_request,
    split_grant,
    take_n_batch,
)
from patrol_tpu.ops import lifecycle as lifecycle_ops
from patrol_tpu.ops.gcra import GcraRequest, gcra_take_batch_jit
from patrol_tpu.ops.concurrency import ConcRequest, conc_acquire_batch_jit
from patrol_tpu.ops.hierquota import QuotaRequest, quota_take_batch_jit
from patrol_tpu.runtime.bucket import ClockFn, system_clock
from patrol_tpu.runtime.directory import (
    BucketDirectory,
    DirectoryFullError,
    OverloadedError,
)

log = logging.getLogger("patrol.engine")

# Per-tick caps: at most this many take rows / merge rows per device call;
# the rest stays queued for the next tick (the loop runs back-to-back).
MAX_TAKE_ROWS = 4096


def _take_fold_enabled() -> bool:
    """Hot-key take coalescing (rx-side fold): same-(row, rate, count)
    takes fold into ONE queue entry at submit time, so a Zipf crowd on a
    few buckets drains as a handful of take-n rows instead of eating the
    whole per-tick row budget ticket-by-ticket. Read at call time (like
    PATROL_TICK_FOLD) so the bench's per-ticket replay leg can flip it
    without forking the engine; "0" also makes _group_tickets serve one
    ticket per row per tick — the true pre-coalescing reference path."""
    return os.environ.get("PATROL_TAKE_FOLD", "1") != "0"
# Merge rows per engine tick. Bigger ticks amortize per-dispatch cost
# (decisive on remote-execute transports: the axon tunnel charges ~60 ms
# per execute regardless of kernel size) at the price of one compiled
# variant per power-of-two up to the cap; the env knob lets the replay
# bench trade warmup variants for tick size without forking the engine.
MAX_MERGE_ROWS = int(os.environ.get("PATROL_MAX_MERGE_ROWS", 8192))
# Device-commit pipeline (r6): how many MAX_MERGE_ROWS blocks one engine
# tick may drain and fold into a SINGLE donated commit dispatch
# (ops/commit.py). The r05 drain paid one transfer + one dispatch per
# block (~5 MB/s effective on the remote-execute transport, 18.5 s of
# ingest_device_drain_ms for 10M deltas); coalescing K blocks into one
# dispatch divides the per-dispatch constant by K and lets the staged
# transfer overlap the previous tick's compute.
#
# Default ``auto`` (device-resident ingest, r15): the feeder SIZES the
# drain per tick from the queue backlog and the completion pipeline's
# measured per-row device-commit cost — light load drains one block
# (lowest latency), floods coalesce toward the budget cap so the
# 8-KiB-interval blocks wire v2 delivers commit in as few dispatches as
# the latency budget allows. A numeric value pins the static r6
# behavior; MeshEngine pins its own static copy (fused-step drains).
_COMMIT_BLOCKS_ENV = os.environ.get("PATROL_COMMIT_BLOCKS", "auto")
COMMIT_BLOCKS_AUTO = _COMMIT_BLOCKS_ENV.strip().lower() == "auto"
COMMIT_BLOCKS = (
    4 if COMMIT_BLOCKS_AUTO else max(1, int(_COMMIT_BLOCKS_ENV))
)
# Auto-mode bounds: the widest drain auto may size, and the latency
# budget one coalesced commit dispatch may spend (the measured
# device_commit_ns EWMA caps block count so a flood can't build a
# dispatch whose completion stalls the pipeline past the budget).
COMMIT_BLOCKS_MAX = max(1, int(os.environ.get("PATROL_COMMIT_BLOCKS_MAX", 8)))
COMMIT_BUDGET_NS = int(
    float(os.environ.get("PATROL_COMMIT_BUDGET_MS", 50)) * 1e6
)
# In-flight device ticks the feeder may dispatch ahead of the completer
# (the completion-queue bound). > 1 keeps a tick queued on the device
# while the completer blocks reading the previous tick's results; the
# bound back-pressures the feeder so a slow completer can't buffer
# device results without limit.
DISPATCH_AHEAD = max(2, int(os.environ.get("PATROL_DISPATCH_AHEAD", 8)))

# patrol-fleet device-dispatch timing (ROADMAP item 1's r06 capture,
# instrumentation half): every commit/take dispatch gets a device-side
# dispatch→ready duration measured on the completion pipeline
# (block_until_ready / result-readback deltas) into the
# ``device_commit_ns``/``device_take_ns`` stage histograms plus a
# per-kernel ``device_kernel_<name>_ns`` histogram. Default on (the
# observation rides the completer thread, which blocks on device results
# anyway); opt out for overhead experiments.
DEVICE_TIMING = os.environ.get("PATROL_DEVICE_TIMING", "1") != "0"
# Optional jax.profiler dispatch annotations: names the engine's kernel
# dispatches inside an XPlane capture (/debug/jax/trace) so the r06
# device trace attributes time to commit/take/fold kernels directly.
DEVICE_ANNOTATIONS = os.environ.get("PATROL_DEVICE_ANNOTATIONS", "0") != "0"


def _annotate(kernel: str):
    """Context for one dispatch: a jax.profiler TraceAnnotation when
    enabled, else a free nullcontext (no per-dispatch cost)."""
    if DEVICE_ANNOTATIONS:
        return jax.profiler.TraceAnnotation(f"patrol.{kernel}")
    return contextlib.nullcontext()


BroadcastFn = Callable[[List[wire.WireState]], None]


class StagingPool:
    """Shape-bucketed reusable host staging buffers for packed device
    commits (the pinned-buffer half of the device-commit pipeline).

    ``lease()`` pops a recycled int64 buffer for a shape (or allocates on
    miss); ``release()`` returns it. The release contract is the caller's:
    a buffer may only come back once its shipped transfer is READY —
    ``jax.block_until_ready`` on the ``device_put`` result for merge
    commits (device_put copies, it never aliases the host source, so
    operand readiness means the host bytes are refillable), or the
    result readback for take ticks (compute done ⇒ operand consumed on
    any backend). Bounded per shape so a burst can't pin unbounded host
    memory."""

    __slots__ = ("_free", "_mu", "_max_per_shape")

    def __init__(self, max_per_shape: int = 8):
        self._free: Dict[tuple, list] = {}
        self._mu = threading.Lock()
        self._max_per_shape = max_per_shape

    def lease(self, shape) -> np.ndarray:
        t0 = time.perf_counter_ns()
        key = tuple(shape)
        buf = None
        with self._mu:
            stack = self._free.get(key)
            if stack:
                buf = stack.pop()
        if buf is not None:
            profiling.COUNTERS.inc("staging_reuse_hits")
        else:
            profiling.COUNTERS.inc("staging_leases_fresh")
            buf = np.empty(key, dtype=np.int64)
        dur = time.perf_counter_ns() - t0
        hist.STAGE_STAGING_WAIT.record(dur)
        tr = trace_mod.TRACE
        if tr.enabled:
            tr.record(trace_mod.EV_STAGING_LEASE, dur, buf.size)
        return buf

    def release(self, buf: np.ndarray) -> None:
        with self._mu:
            stack = self._free.setdefault(buf.shape, [])
            if len(stack) < self._max_per_shape:
                stack.append(buf)
        tr = trace_mod.TRACE
        if tr.enabled:
            tr.record(trace_mod.EV_STAGING_RECYCLE, 0, buf.size)

# Host fast path (SURVEY §7 hard-part #1; VERDICT r3 item 1): serve
# cold/low-QPS buckets from an in-process scalar-lane model — µs-class, no
# device hop — and promote a bucket to the device path when it gets hot.
# The reference answers /take in-process in ~µs (api.go:51-86); a device
# round-trip floors a cold bucket's p99 well above that on any hardware.
HOST_FASTPATH = os.environ.get("PATROL_HOST_FASTPATH", "1") != "0"
# Promote when a bucket sees more than this many host takes (or absorbed
# rx deltas) inside one window. The default approximates the crossover
# where device batching beats per-request host python: a host take costs
# ~10-20 µs single-threaded (≈50-100k/s ceiling), so below ~40k/s per
# bucket the in-process path is strictly faster than ANY device
# round-trip; above it, coalescing thousands of requests into one kernel
# row wins. Measured on this box (BASELINE_MEASURED r3): the device path
# capped config #1 at 16.6k rps / 7.3 ms p99 while the host path holds
# it sub-ms — a low threshold demoted exactly the buckets the fast path
# exists for. Env-tunable for hosts with different single-core budgets.
HOST_PROMOTE_TAKES = int(os.environ.get("PATROL_HOST_PROMOTE_TAKES", 4096))
HOST_PROMOTE_WINDOW_NS = int(
    float(os.environ.get("PATROL_HOST_PROMOTE_WINDOW_MS", 100)) * 1e6
)
# Idle demotion (VERDICT r4 item 3): a promoted bucket whose device-path
# take rate falls below this count per demote window moves BACK to host
# residency (exact: gather the row, seed host lanes, zero the device row)
# — below the crossover the host path is strictly faster than ANY device
# round trip, and promotion was one-way in r4, so a bucket hot for one
# burst paid the device hop forever after. Hysteresis: the demote rate
# threshold sits ~8× below the promote rate (quarter the takes over twice
# the window), so residency can't flap on a steady workload.
HOST_DEMOTE_TAKES = int(
    os.environ.get("PATROL_HOST_DEMOTE_TAKES", max(HOST_PROMOTE_TAKES // 4, 1))
)
HOST_DEMOTE_WINDOW_NS = int(
    float(os.environ.get("PATROL_HOST_DEMOTE_WINDOW_MS", 200)) * 1e6
)

# Scrape mirror (patrol-dispatch stage 10, PTD003): the stats/debug
# surfaces (snapshot/snapshot_many/tokens_if_known/row_view → /metrics,
# /debug/vars, audit + anti-entropy fan-ins) used to pay one device
# gather PER CALL. The mirror keeps a host copy of the low row window,
# stamped with the (_ticks, _state_gen) epoch it reflects: while the
# epoch is unchanged the mirror is EXACT (not merely fresh-ish), so a
# steady-state scrape costs zero device transfers. Refreshes ride the
# completion pipeline when scrapes are active; a stale scrape pays one
# batched window gather instead of a targeted one.
SCRAPE_MIRROR = os.environ.get("PATROL_SCRAPE_MIRROR", "1") != "0"
SCRAPE_MIRROR_ROWS = int(os.environ.get("PATROL_SCRAPE_MIRROR_ROWS", 4096))

# Bucket lifecycle (ROADMAP item 4): idle-bucket GC on the feeder tick.
# A bound bucket whose reconstructed value equals its rate-derived refill
# (the IsZero predicate, ops/lifecycle.py) is reclaimed from the device
# plane AND the host directory — under a power-law keyspace the cold tail
# stops living forever in dense state. 0 disables the feeder cadence
# (sweeps still run via gc_sweep(): tests, bench, operators).
GC_WINDOW_NS = int(float(os.environ.get("PATROL_GC_WINDOW_MS", 500)) * 1e6)
# Only buckets untouched for this long are sweep candidates at zero
# budget pressure; pressure (and force) drops the idleness requirement —
# the predicate alone already guarantees reclaim safety, idleness just
# keeps the steady-state sweep off warm buckets.
GC_IDLE_NS = int(float(os.environ.get("PATROL_GC_IDLE_MS", 1000)) * 1e6)
# Candidate rows probed per sweep (one padded device gather).
GC_SWEEP_MAX = int(os.environ.get("PATROL_GC_SWEEP_MAX", 8192))
# Memory-budget watermarks: bound-bucket count and/or byte budget
# (0 = unenforced). Crossing soft (GC_SOFT_FRAC × budget) ramps GC
# pressure — sweeps ignore idleness and run at window/8 cadence; at the
# hard watermark admission of NEW names sheds load with an explicit
# OverloadedError (HTTP 429 "overloaded") instead of growing toward OOM.
MAX_BUCKETS = int(os.environ.get("PATROL_MAX_BUCKETS", 0))
STATE_BYTES_BUDGET = int(os.environ.get("PATROL_STATE_BYTES_BUDGET", 0))
GC_SOFT_FRAC = float(os.environ.get("PATROL_GC_SOFT_FRAC", 0.85))

# Host-side directory metadata attributable to one bound row (name bytes
# + the per-row int64/int32 columns) — the budget accounting's row class.
_ROW_HOST_BYTES = 256 + 64

# patrol-audit (net/audit.py): the admitted-token audit window. Every
# admitted take books its nanotokens into the engine's AuditLedger under
# the current window id; the audit plane gossips the closed windows'
# own-lane G-counters cluster-wide and reports the measured AP-overshoot
# factor (global admitted vs limit×1) as a live SLI. 0 = manual windows
# (tests/bench close them explicitly via roll(force=True)).
AUDIT_WINDOW_NS = int(float(os.environ.get("PATROL_AUDIT_WINDOW_MS", 5000)) * 1e6)


class AuditLedger:
    """Own-lane half of the AP-overshoot auditor: a windowed per-bucket
    admitted-token G-counter. Each admitted take books its nanotokens
    under the CURRENT window id; a window's per-bucket totals are monotone
    within the window, so they gossip as join-decompositions exactly like
    the metrics lattices (net/fleet.py) — receivers max-join per (window,
    bucket, lane). Window ids are engine-clock derived (``clock //
    window_ns``), so clock-synced nodes agree on attribution; with
    ``window_ns == 0`` windows only close via ``roll(force=True)`` and the
    id is a lockstep epoch counter (the deterministic test/bench mode).

    Alongside the admitted count the ledger keeps each bucket's limit
    view: capacity base plus the rate-derived refill over the window's
    observed span — the ``limit × 1`` denominator of the overshoot
    factor. Thread-safe; one leaf lock, never held across other locks
    (declared in analysis/race.py::GUARDS)."""

    def __init__(self, window_ns: int = 0):
        self._mu = threading.Lock()
        self.window_ns = window_ns
        self._window = 0
        self._start_ns: Optional[int] = None
        # name -> [admitted_nt, cap_nt(max), per_ns(max)] for the open window.
        self._cur: Dict[str, list] = {}
        self._closed: deque = deque(maxlen=4)
        self.windows_closed = 0

    def _clock_window(self, now: int) -> int:
        return now // self.window_ns if self.window_ns > 0 else self._window

    def _close_locked(self, now: int, next_window: int) -> None:
        start = self._start_ns if self._start_ns is not None else now
        dur = max(0, now - start)
        if self._cur:
            lanes = {
                name: (
                    v[0],
                    # limit×1: capacity base + refill over the window span.
                    v[1] + (v[1] * dur // v[2] if v[2] > 0 else 0),
                )
                for name, v in self._cur.items()
            }
            self._closed.append((self._window, dur, lanes))
            self.windows_closed += 1
        self._cur = {}
        self._window = next_window
        self._start_ns = now

    def note(
        self, name: str, admitted_nt: int, cap_nt: int, per_ns: int, now: int
    ) -> None:
        """Book one admitted take into the open window (self-rolling on
        clock-derived window ids)."""
        if admitted_nt <= 0:
            return
        with self._mu:
            if self._start_ns is None:
                self._start_ns = now
                self._window = self._clock_window(now)
            elif self.window_ns > 0:
                w = self._clock_window(now)
                if w > self._window:
                    self._close_locked(now, w)
            ent = self._cur.get(name)
            if ent is None:
                self._cur[name] = [admitted_nt, max(cap_nt, 0), max(per_ns, 0)]
            else:
                ent[0] += admitted_nt
                ent[1] = max(ent[1], cap_nt)
                ent[2] = max(ent[2], per_ns)

    def roll(self, now: int, force: bool = False) -> None:
        """Close the open window when its span lapsed (or ``force``)."""
        with self._mu:
            if self._start_ns is None:
                self._start_ns = now
                self._window = self._clock_window(now)
                return
            if force:
                self._close_locked(now, self._window + 1)
            elif self.window_ns > 0:
                w = self._clock_window(now)
                if w > self._window:
                    self._close_locked(now, w)

    def export(self):
        """→ (current window id, closed windows) where each closed window
        is ``(window_id, duration_ns, {name: (admitted_nt, limit_nt)})``
        and the OPEN window rides along too (monotone — shipping partial
        progress is join-safe). The open window's limit uses the span so
        far."""
        with self._mu:
            out = list(self._closed)
            if self._cur and self._start_ns is not None:
                # The open window's partial view (duration so far unknown
                # to a frozen clock ⇒ 0 refill, conservative).
                out.append(
                    (
                        self._window,
                        0,
                        {
                            name: (v[0], v[1])
                            for name, v in self._cur.items()
                        },
                    )
                )
            return self._window, out


class HostLanes:
    """Host-resident PN-lane state for one bucket row: the fast-path twin
    of one row of ``LimiterState`` (int64 nanotoken lanes + the elapsed
    G-counter), plus the promotion QPS window. All mutation happens under
    the engine's ``_host_mu``.

    The take arithmetic mirrors ops/take.py's ``take_batch`` step-for-step
    (itself ≙ bucket.go:186-225) for a single row with ``nreq=1`` — same
    lazy capacity base, monotonic-time guard, float64 refill grant,
    capacity cap (possibly negative ⇒ monotone forfeit booked as taken),
    conditional commit — so a bucket's observable behavior is IDENTICAL
    whether it is served here or on the device, and a later promotion join
    (lanes are monotone, max-merge) is exact, not approximate."""

    __slots__ = (
        "added", "taken", "elapsed_ns", "win_start_ns", "win_takes", "win_rx"
    )

    def __init__(self, nodes: int):
        self.added = np.zeros(nodes, np.int64)
        self.taken = np.zeros(nodes, np.int64)
        self.elapsed_ns = 0
        self.win_start_ns = 0
        self.win_takes = 0
        self.win_rx = 0  # rx deltas absorbed this window (promotion signal)

    def roll_window(self, now_ns: int) -> None:
        """Reset the promotion window when it lapsed. Both counters roll
        TOGETHER: an rx count that survived take-window rolls would accrue
        one peer echo per take and promote every clustered bucket after
        ~HOST_PROMOTE_TAKES takes total, at any QPS."""
        if now_ns - self.win_start_ns > HOST_PROMOTE_WINDOW_NS:
            self.win_start_ns = now_ns
            self.win_takes = 0
            self.win_rx = 0

    def take(
        self,
        cap_base_nt: int,
        created_ns: int,
        now_ns: int,
        rate: Rate,
        count: int,
        node_slot: int,
    ) -> Tuple[int, bool]:
        """One take; returns (remaining_tokens, ok). ≙ take_batch nreq=1."""
        cap_now_nt = rate.freq * NANO
        sum_a = int(self.added.sum())
        sum_t = int(self.taken.sum())
        tokens_nt = cap_base_nt + sum_a - sum_t

        last = min(created_ns + self.elapsed_ns, now_ns)
        delta = now_ns - last

        interval = rate.per_ns // rate.freq if rate.freq else 0
        if rate.freq == 0 or rate.per_ns == 0 or interval == 0:
            grant_nt = 0
        else:
            # float64(delta)/float64(interval) tokens then ·1e9, floored —
            # the exact expression (and operation order) of the kernel.
            grant_f = (float(delta) / float(interval)) * float(NANO)
            grant_nt = int(np.floor(np.clip(grant_f, 0.0, float(2**62))))
        grant_nt = min(grant_nt, cap_now_nt - tokens_nt)

        have_nt = tokens_nt + grant_nt
        count_nt = count * NANO
        if count_nt > 0:
            k = min(max(have_nt // count_nt, 0), 1)
        else:
            k = 0
        if k >= 1:
            forfeit = max(-grant_nt, 0)
            self.added[node_slot] += max(grant_nt, 0)
            self.taken[node_slot] += count_nt + forfeit
            self.elapsed_ns += delta
        return remaining_for_request(have_nt, k, count_nt, 0)


class TakeTicket:
    """One pending take request. Completion is observable both from threads
    (:meth:`wait`) and event loops (:meth:`add_done_callback`), so the
    asyncio HTTP front never blocks on the engine thread."""

    __slots__ = (
        "name",
        "row",
        "rate",
        "count",
        "now_ns",
        "_event",
        "_mu",
        "_callbacks",
        "remaining",
        "ok",
        "deferred",
        "shed",
        "t0_ns",
        "trace_id",
    )

    def __init__(self, name: str, row: int, rate: Rate, count: int, now_ns: int):
        self.name = name
        self.row = row
        self.rate = rate
        self.count = count
        self.now_ns = now_ns
        self._event = threading.Event()
        self._mu = threading.Lock()
        self._callbacks: List[Callable[[], None]] = []
        self.remaining: int = 0
        self.ok: bool = False
        # True while re-queued by _group_tickets (rate-key conflict): such a
        # ticket is still live in the queue — failure paths must not
        # complete/unpin it (engine thread only; no lock needed).
        self.deferred = False
        # True when completed by the memory watermark's overload shed
        # (never pinned, never queued): lets the multi-take HTTP front
        # answer 429 "overloaded" for exactly the shed entries of a batch
        # while live names in the same request keep their real outcomes.
        self.shed = False
        # patrol-scope: service-latency stamp (take_service_ns histogram)
        # and the sampled cross-node trace id (None when unsampled).
        self.t0_ns = time.perf_counter_ns()
        self.trace_id = trace_mod.sample_take()

    def complete(self, remaining: int, ok: bool) -> bool:
        """Returns True on the first completion (False if already done) —
        the engine unpins the ticket's directory row exactly on that
        transition."""
        with self._mu:
            if self._event.is_set():
                return False
            self.remaining = remaining
            self.ok = ok
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:
            cb()
        return True

    def add_done_callback(self, cb: Callable[[], None]) -> None:
        """Invoke ``cb`` once completed (immediately if already done).
        ``cb`` must be thread-safe — it runs on the engine thread."""
        with self._mu:
            if not self._event.is_set():
                self._callbacks.append(cb)
                return
        cb()

    def wait(self, timeout: Optional[float] = None) -> bool:
        ok = self._event.wait(timeout)
        if not ok:
            # A caller-visible take stall: freeze the flight recorder so
            # the tick/dispatch/completion timeline that led here is
            # inspectable after the fact (damped inside anomaly()).
            trace_mod.anomaly("take-stall")
        return ok


class _TakeFold:
    """One coalesced take-queue entry: every ticket with the same
    (row, freq, per_ns, count) key that arrived while the entry waited
    for a tick, in arrival order. The feeder's drain counts ENTRIES
    (future packed rows), so a hot-key flood of thousands of tickets
    costs one row of the per-tick budget instead of drowning it; the
    grant still splits FIFO per ticket (ops/take.py split_grant).
    Created and appended-to only under the work condvar's lock, like
    the queue it lives in (analysis/race.py GUARDS)."""

    __slots__ = ("key", "tickets")

    def __init__(self, key: tuple, first: TakeTicket):
        self.key = key
        self.tickets = [first]


class _Delta:
    __slots__ = (
        "row", "slot", "added_nt", "taken_nt", "elapsed_ns", "scalar",
        "trace_id", "trace_name",
    )

    def __init__(
        self,
        row: int,
        slot: int,
        added_nt: int,
        taken_nt: int,
        elapsed_ns: int,
        scalar: bool = False,
    ):
        # Cross-node tracing: a sampled remote take's propagated id (and
        # the bucket name for the span label); None on the common path.
        self.trace_id = None
        self.trace_name = None
        self.row = row
        self.slot = slot
        # Ingest clamp: device state is non-negative by invariant; hostile or
        # corrupt packets must not be able to poison the max-merge.
        self.added_nt = max(added_nt, 0)
        self.taken_nt = max(taken_nt, 0)
        self.elapsed_ns = max(elapsed_ns, 0)
        # True ⇒ the delta came from a scalar-semantics (reference) peer and
        # must go through the deficit-attribution kernel (merge_scalar_batch).
        self.scalar = scalar


class _DeltaChunk:
    """A pre-vectorized batch of deltas (bulk ingest path): parallel int64
    numpy arrays, already clamped non-negative and slot-validated, plus a
    per-delta scalar-semantics flag."""

    __slots__ = ("rows", "slots", "added_nt", "taken_nt", "elapsed_ns", "scalar", "n")

    def __init__(self, rows, slots, added_nt, taken_nt, elapsed_ns, scalar=None):
        self.rows = rows
        self.slots = slots
        self.added_nt = added_nt
        self.taken_nt = taken_nt
        self.elapsed_ns = elapsed_ns
        self.scalar = (
            scalar if scalar is not None else np.zeros(len(rows), dtype=bool)
        )
        self.n = len(rows)


class DeltaArrays(NamedTuple):
    """One tick's drained replication deltas, in arrival order, as flat
    numpy arrays — the canonical form both engines consume."""

    rows: np.ndarray
    slots: np.ndarray
    added_nt: np.ndarray
    taken_nt: np.ndarray
    elapsed_ns: np.ndarray
    scalar: np.ndarray  # bool[K]: deficit-attribution (reference peer) deltas

    def __len__(self) -> int:
        return len(self.rows)


# Sentinel row for fold-padding — canonical definition lives with the
# kernels (ops/merge.py FOLD_PAD_ROW, shared with ops/commit.py); the
# underscore alias keeps this module's historical name importable.
_FOLD_PAD_ROW = merge_mod.FOLD_PAD_ROW

# Fold-to-dense hybrid: a tick row touching at least this many lanes
# commits its full lane plane as ONE row-window scatter update instead of
# one update per lane (0 = auto: max(4, nodes // 3) — the point where the
# row window's extra transfer bytes beat the per-update scatter cost on a
# transfer-walled link; on a PCIe-attached chip 4 is already a win).
ROW_DENSE_MIN = int(os.environ.get("PATROL_ROW_DENSE_MIN", 0))
MAX_ROW_DENSE = 512  # padded-shape ceiling of the row-dense batch


def _pad_size(n: int, lo: int = 8, hi: int = MAX_MERGE_ROWS) -> int:
    """Next power of two ≥ n, bounded — keeps the jit-variant count ~log."""
    size = lo
    while size < n and size < hi:
        size <<= 1
    return size


def _obs_stage(h, t0_ns: int, ev: int, arg: int = 0) -> int:
    """patrol-scope stage probe: close a stage opened at ``t0_ns`` into
    its latency histogram and (when enabled) the flight recorder. The
    cost is one perf_counter read + a histogram lane increment — the
    same noise-level class as the COUNTERS mutex."""
    dur = time.perf_counter_ns() - t0_ns
    h.record(dur)
    tr = trace_mod.TRACE
    if tr.enabled:
        tr.record(ev, dur, arg)
    return dur


# Distinct-row bound for the native fold: past this the per-row lane
# blocks stop paying for themselves (the uniform shape is scatter-bound
# anyway) and the numpy fold takes over.
FOLD_NATIVE_MAX_DISTINCT = int(
    os.environ.get("PATROL_FOLD_NATIVE_MAX_DISTINCT", 4096)
)

# Per-thread reusable output buffers for the native fold (the feeder is
# the caller in production; the bench drives it from the main thread; two
# engines in one process each fold on their own feeder — thread-local
# keeps them from sharing).
_fold_tls = threading.local()


def _fold_buffers(nodes: int, cap_pairs: int):
    cached = getattr(_fold_tls, "bufs", None)
    if (
        cached is not None
        and cached[0][0] == nodes
        and cached[0][1] >= cap_pairs
    ):
        return cached[1]
    cap_pairs = 1 << max(cap_pairs - 1, 1).bit_length()  # grow-once sizes
    cap_rows = min(cap_pairs, FOLD_NATIVE_MAX_DISTINCT)
    bufs = (
        np.empty(MAX_ROW_DENSE, np.int64),
        np.empty((MAX_ROW_DENSE, nodes, 2), np.int64),
        np.empty(MAX_ROW_DENSE, np.int64),
        np.empty(cap_pairs, np.int64),
        np.empty(cap_pairs, np.int64),
        np.empty(cap_pairs, np.int64),
        np.empty(cap_pairs, np.int64),
        np.empty(cap_rows, np.int64),
        np.empty(cap_rows, np.int64),
        np.zeros(3, np.int64),
    )
    _fold_tls.bufs = ((nodes, cap_pairs), bufs)
    return bufs


def _fold_hybrid_native(deltas: DeltaArrays, nodes: int, row_dense_min: int):
    """C++ fold (pt_fold_hybrid): one hash pass into per-row lane blocks,
    threaded across cores for large batches — replaces the numpy
    lexsort+reduceat fold that dominated the hot-key tick (~6.1 ms for
    131k deltas vs ~0.2 ms of device commit, VERDICT r4 item 6). Returns
    the exact numpy-fold result shape, or None to fall back (library
    unavailable, tiny batch, or a distinct-row set past the bound)."""
    n = len(deltas.rows)
    if n < 1024:
        return None  # per-call buffers beat numpy only at batch scale
    # Cheap shape probe BEFORE any allocation or native work: a mostly-
    # distinct sample means the uniform shape (the native fold would only
    # burn a partial hash pass to discover it must bail, and the numpy
    # fold would then redo the batch from scratch). The sample is sized
    # so a clustered batch can't trip it: its unique count is bounded by
    # the true distinct-row count, so only shapes near/past the native
    # bound (where numpy is the right path anyway) read as uniform.
    sample = deltas.rows[:: max(1, n // 2048)][:2048]
    if len(np.unique(sample)) >= 0.85 * len(sample):
        return None
    from patrol_tpu import native as native_mod

    lib = native_mod.load()
    if lib is None:
        return None
    rows = np.ascontiguousarray(deltas.rows, np.int64)
    slots = np.ascontiguousarray(deltas.slots, np.int64)
    added = np.ascontiguousarray(deltas.added_nt, np.int64)
    taken = np.ascontiguousarray(deltas.taken_nt, np.int64)
    elapsed = np.ascontiguousarray(deltas.elapsed_ns, np.int64)
    bufs = _fold_buffers(nodes, min(n, FOLD_NATIVE_MAX_DISTINCT * nodes))
    (d_rows, d_upd, d_el, sp_rows, sp_slots, sp_a, sp_t, sp_er, sp_e,
     counts) = bufs
    counts[:] = 0
    rc = lib.pt_fold_hybrid(
        rows, slots, added, taken, elapsed, n, nodes, row_dense_min,
        FOLD_NATIVE_MAX_DISTINCT, d_rows, d_upd, d_el, MAX_ROW_DENSE,
        sp_rows, sp_slots, sp_a, sp_t, sp_er, sp_e, counts,
    )
    if rc != 0:
        return None
    n_pairs, n_rows, n_dense = int(counts[0]), int(counts[1]), int(counts[2])
    packed = DeviceEngine._pack_folded(
        sp_rows[:n_pairs], sp_slots[:n_pairs], sp_a[:n_pairs],
        sp_t[:n_pairs], sp_er[:n_rows], sp_e[:n_rows],
    )
    if n_dense == 0:
        return packed, None
    rp = _pad_size(n_dense, lo=8, hi=MAX_ROW_DENSE)
    rows_p = np.empty(rp, dtype=np.int64)
    rows_p[:n_dense] = d_rows[:n_dense]
    rows_p[n_dense:] = _FOLD_PAD_ROW + np.arange(rp - n_dense)
    upd_p = np.zeros((rp, nodes, 2), dtype=np.int64)
    upd_p[:n_dense] = d_upd[:n_dense]
    el_p = np.zeros(rp, dtype=np.int64)
    el_p[:n_dense] = d_el[:n_dense]
    return packed, (rows_p, upd_p, el_p)


def fold_hybrid(deltas: DeltaArrays, nodes: int, row_dense_min: int):
    """Fold-to-dense hybrid split (VERDICT r3 item 3): rows whose tick
    touches ≥ ``row_dense_min`` lanes commit their FULL lane plane as ONE
    row-window scatter update (TPU scatter is per update, window size
    free — a hot-key tick collapses from ~N updates to 1); the sparse
    remainder rides the flagged pair scatter. Returns
    (packed|None, (rows, updates, elapsed)|None); module-level so the
    bench measures the exact engine-tick computation. Large clustered
    batches fold in C++ (:func:`_fold_hybrid_native`); the numpy fold
    below is the reference implementation and the uniform-shape path."""
    native_res = _fold_hybrid_native(deltas, nodes, row_dense_min)
    if native_res is not None:
        return native_res
    ur, us, ua, ut, er, e = DeviceEngine._fold_core(deltas)
    nrow = np.empty(len(ur), bool)
    nrow[0] = True
    np.not_equal(ur[1:], ur[:-1], out=nrow[1:])
    rstart = np.flatnonzero(nrow)
    counts = np.diff(np.append(rstart, len(ur)))
    dense_sel = counts >= row_dense_min
    if not dense_sel.any():
        return DeviceEngine._pack_folded(ur, us, ua, ut, er, e), None
    di = np.flatnonzero(dense_sel)
    if len(di) > MAX_ROW_DENSE:
        # Cap the dense batch at its padded-shape ceiling; the
        # overflow rides the sparse scatter (correct, just slower).
        dense_sel = np.zeros_like(dense_sel)
        dense_sel[di[:MAX_ROW_DENSE]] = True
    pair_dense = np.repeat(dense_sel, counts)
    d_rows = er[dense_sel]  # unique + sorted (er follows ur's order)
    R = len(d_rows)
    upd = np.zeros((R, nodes, 2), dtype=np.int64)
    pr_idx = np.repeat(np.arange(R), counts[dense_sel])
    upd[pr_idx, us[pair_dense], 0] = ua[pair_dense]
    upd[pr_idx, us[pair_dense], 1] = ut[pair_dense]
    sparse = ~pair_dense
    packed = DeviceEngine._pack_folded(
        ur[sparse], us[sparse], ua[sparse], ut[sparse],
        er[~dense_sel], e[~dense_sel],
    )
    rp = _pad_size(R, lo=8, hi=MAX_ROW_DENSE)
    rows_p = np.empty(rp, dtype=np.int64)
    rows_p[:R] = d_rows
    rows_p[R:] = _FOLD_PAD_ROW + np.arange(rp - R)  # OOB, unique, sorted
    upd_p = np.zeros((rp, nodes, 2), dtype=np.int64)
    upd_p[:R] = upd
    el_p = np.zeros(rp, dtype=np.int64)
    el_p[:R] = e[dense_sel]
    return packed, (rows_p, upd_p, el_p)


# Packed-transfer variants: host↔device latency is dominated by per-array
# transfer setup (~50µs each on this stack), so the engine ships ONE
# int64[8,K] request matrix and receives ONE int64[5,K] result matrix per
# tick instead of 8 + 5 little arrays (measured: single-take p50 578µs →
# the kernel's own 38µs + one transfer each way).
@lru_cache(maxsize=8)
def _jit_take_packed(node_slot: int):
    def step(state, packed):
        # The packed↔result layout lives with the kernel now
        # (ops/take.py take_n_batch — its own certified prove root);
        # this factory only binds the static node slot and donation.
        return take_n_batch(state, packed, node_slot)

    return jax.jit(step, donate_argnums=0)


@lru_cache(maxsize=8)
def _jit_merge_packed():
    def step(state, packed):
        batch = MergeBatch(
            rows=packed[0].astype(jnp.int32),
            slots=packed[1].astype(jnp.int32),
            added_nt=packed[2],
            taken_nt=packed[3],
            elapsed_ns=packed[4],
        )
        return merge_batch(state, batch)

    return jax.jit(step, donate_argnums=0)


@lru_cache(maxsize=8)
def _jit_merge_packed_folded():
    """Scatter-max with unique/sorted flags asserted — only valid for
    batches prepared by :meth:`DeviceEngine._fold_lane_merges`."""

    def step(state, packed):
        batch = merge_mod.FoldedMergeBatch(
            rows=packed[0].astype(jnp.int32),
            slots=packed[1].astype(jnp.int32),
            added_nt=packed[2],
            taken_nt=packed[3],
            erows=packed[4].astype(jnp.int32),
            elapsed_ns=packed[5],
        )
        return merge_mod.merge_batch_folded(state, batch)

    return jax.jit(step, donate_argnums=0)


@lru_cache(maxsize=8)
def _jit_commit_packed():
    """Coalesced block-ring commit (ops/commit.py): one int64[6, J, K]
    staged matrix → one donated dispatch folding every block. Only valid
    for matrices prepared by :func:`patrol_tpu.ops.commit.pack_commit_blocks`
    (flattened-sorted unique keys, sentinel padding)."""

    def step(state, packed):
        blocks = commit_mod.CommitBlocks(
            rows=packed[0].astype(jnp.int32),
            slots=packed[1].astype(jnp.int32),
            added_nt=packed[2],
            taken_nt=packed[3],
            erows=packed[4].astype(jnp.int32),
            elapsed_ns=packed[5],
        )
        return commit_mod.commit_blocks(state, blocks)

    return jax.jit(step, donate_argnums=0)


@lru_cache(maxsize=8)
def _jit_merge_rows_dense():
    """Row-window scatter-max — the dense half of the fold-to-dense
    hybrid (one update per row, full lane plane per window)."""

    def step(state, rows, updates, elapsed):
        batch = merge_mod.RowDenseBatch(
            rows=rows.astype(jnp.int32),
            updates=updates,
            elapsed_ns=elapsed,
        )
        return merge_mod.merge_rows_dense(state, batch)

    return jax.jit(step, donate_argnums=0)


@lru_cache(maxsize=8)
def _jit_merge_scalar_packed():
    """Deficit-attribution merge for scalar-semantics (reference-peer)
    deltas — interop path, typically a small batch."""

    def step(state, packed):
        batch = MergeBatch(
            rows=packed[0].astype(jnp.int32),
            slots=packed[1].astype(jnp.int32),
            added_nt=packed[2],
            taken_nt=packed[3],
            elapsed_ns=packed[4],
        )
        return merge_scalar_batch(state, batch)

    return jax.jit(step, donate_argnums=0)


class DeviceEngine:
    """Owns device state and the feeder thread. Thread-safe entry points:
    :meth:`submit_take` / :meth:`take`, :meth:`ingest_delta`,
    :meth:`snapshot`, :meth:`stop`."""

    def __init__(
        self,
        config: LimiterConfig,
        node_slot: int = 0,
        clock: ClockFn = system_clock,
        on_broadcast: Optional[BroadcastFn] = None,
        device=None,
        native_host: bool = False,
    ):
        self.config = config
        self.node_slot = node_slot
        self.clock = clock
        self.on_broadcast = on_broadcast
        self._row_dense_min = ROW_DENSE_MIN or max(4, config.nodes // 3)
        self.directory = BucketDirectory(config.buckets)
        self.state: LimiterState = init_state(config, device=device)

        # Profiled sync primitives: contended-acquire wait time and
        # condition park time feed the REAL /debug/pprof/mutex and /block
        # profiles (≙ runtime.SetMutexProfileFraction(50), main.go:24).
        self._cond = profiling.ProfiledCondition("engine.work")
        # Kernel calls donate the state buffers (zero-copy update); this lock
        # keeps introspection readers off a donated-and-deleted array.
        self._state_mu = profiling.ProfiledLock("engine.state")
        # Serializes evictions (pick victims → zero device rows → recycle);
        # concurrent assigners that hit a spent pool queue up behind it.
        self._evict_mu = threading.Lock()
        self._takes: deque = deque()
        self._deltas: deque = deque()
        # Hot-key coalescer index: take-fold key → its OPEN _TakeFold
        # entry in _takes (removed when the feeder drains the entry).
        # Rides the work condvar like the queue it indexes.
        self._open_folds: Dict[tuple, _TakeFold] = {}
        # Host fast path: row → HostLanes for buckets currently served
        # in-process (µs-class) instead of on-device. The bool flag array
        # gives the rx hot path an O(1)/vectorized residency probe; dict
        # and flags only ever change together, under _host_mu. This and
        # the other shared-state disciplines in this class are no longer
        # comment-level only: analysis/race.py::GUARDS registers each
        # attribute→lock pair and check.sh stage 7 (patrol-race PTR003)
        # flags any access outside the declared lock.
        self._hosted: Dict[int, HostLanes] = {}
        self._hosted_flag = np.zeros(config.buckets, dtype=bool)
        self._promote_pending: set = set()
        # Lanes popped from _hosted by a promotion drain but whose device
        # join hasn't landed yet. snapshot_planes joins this dict too, so
        # a checkpoint save in the pop→merge window still sees the lanes
        # (they'd otherwise be in NEITHER _hosted nor the device planes —
        # a restored checkpoint would drop the spend and over-admit).
        # Entries are cleared under _host_mu only AFTER the _state_mu
        # merge lands; the join is a max (idempotent), so a snapshot that
        # reads both the merged planes and a not-yet-cleared entry is
        # still exact.
        self._promoting: Dict[int, HostLanes] = {}
        self._host_mu = threading.Lock()
        # Native host-lane store (VERDICT r4 item 1): when requested and
        # the native library is available, host-resident lanes live in C++
        # blocks the HTTP front serves takes from WITHOUT crossing into
        # Python; the engine sees the same bytes through numpy-view
        # proxies, and _host_mu becomes the store's native mutex so both
        # sides serialize on one lock. Python code paths are unchanged —
        # they just operate on shared memory.
        self._native_store = None
        if native_host and HOST_FASTPATH:
            from patrol_tpu.runtime import hoststore

            # Map the injected clock onto CLOCK_REALTIME for the epoll
            # thread's takes: offset = clock() - realtime at init. Exact
            # for the CLI's offset clocks (main.go:35-37 semantics); a
            # test FakeClock driving the C++ path uses the probe's
            # explicit now instead.
            self._native_store = hoststore.NativeHostStore.create(
                nodes=config.nodes,
                node_slot=node_slot,
                directory=self.directory,
                clock_offset_ns=int(self.clock()) - time.time_ns(),
                window_ns=HOST_PROMOTE_WINDOW_NS,
            )
            if self._native_store is not None:
                self._host_mu = self._native_store.mutex()
        self._host_takes = 0  # takes served by the fast path
        self._promotions = 0  # host→device residency transitions
        self._demotions = 0  # device→host residency transitions (idle)
        # Recently-broadcast bucket names (insertion-ordered, bounded):
        # the graceful-shutdown flush re-broadcasts these buckets' FINAL
        # state so a lost last-broadcast datagram doesn't silently shed a
        # stopping node's most recent takes (tests/test_cluster.py
        # TestShutdownFlush). Names, not rows — a row may be recycled
        # between the broadcast and the flush.
        self._dirty_mu = threading.Lock()
        self._dirty_names: Dict[str, None] = {}
        self._dirty_cap = 4096
        # Idle-demotion bookkeeping (feeder-driven): rows promoted to the
        # device path and still bound, their device-take counts in the
        # current demote window, and the window's start. Set mutations run
        # under _host_mu (drain/drop) or on the feeder (_maybe_demote).
        self._promoted_rows: set = set()
        self._promoted_at: Dict[int, int] = {}  # row → promotion clock time
        self._dev_window: Dict[int, int] = {}
        self._demote_win_start: Optional[int] = None
        # Checkpoint restore pauses demotion: its flush→load→join sequence
        # must not interleave with a gather/zero that would strand the
        # restored spend in zeroed device rows (see _maybe_demote).
        self._demotion_paused = False
        # Bucket lifecycle: knobs are instance copies (tests and the soak
        # bench tune them per engine via configure_lifecycle); the sweep
        # bookkeeping below mutates under _evict_mu only (the same lock
        # that already serializes every unbind/zero/recycle path) —
        # declared in analysis/race.py::GUARDS like the rest.
        self._gc_window_ns = GC_WINDOW_NS
        self._gc_idle_ns = GC_IDLE_NS
        self._gc_sweep_max = GC_SWEEP_MAX
        self._max_buckets = MAX_BUCKETS
        self._bytes_budget = STATE_BYTES_BUDGET
        self._gc_soft_frac = GC_SOFT_FRAC
        self._gc_win_start: Optional[int] = None
        self._gc_reclaimed = 0
        self._gc_shed = 0
        self._gc_sweeps = 0
        self._gc_compactions = 0
        # Host-fastpath GC kick: takes served in-process never queue
        # work, so a pure fast-path workload would starve the feeder's
        # sweep cadence. The host-serve seams set this flag (two int
        # reads per take) at window rollover and wake the feeder, which
        # runs the sweep. Guarded by _cond like the work queues.
        self._gc_due = False
        # patrol-audit: the admitted-token window ledger (net/audit.py
        # reads it on the audit plane's pace). Known attribution gap: the
        # C++ native-front in-process takes never cross into Python, so
        # they are invisible to the ledger — audit coverage degrades to
        # the python-served paths there (documented in README).
        self._audit = AuditLedger(AUDIT_WINDOW_NS)
        if self._max_buckets or self._bytes_budget:
            from patrol_tpu.utils import slo as slo_mod

            slo_mod.SENTINEL.watch_budget(self._budget_snapshot)
        self._stopped = False
        self._busy = False
        # Tick pause (MeshEngine.resize quiesce): while True the feeder
        # parks between ticks — work queues keep absorbing submissions,
        # nothing dispatches — so device geometry (mesh/plan/step/
        # sharding) can swap atomically with NO tick in flight. Guarded
        # by _cond like the work queues; _stopped overrides it so a
        # shutdown never deadlocks behind a forgotten pause.
        self._tick_paused = False
        self._ticks = 0  # device calls issued (observability)
        # Device-state mutations that do NOT ride a _ticks bump (row
        # zeroing on evict/demote/reclaim, the gcra/conc/quota
        # microbatches). (_ticks, _state_gen) together form the scrape
        # epoch: any device-state change moves it, so an epoch-matched
        # mirror read is exactly the gather it replaces.
        self._state_gen = 0
        # (epoch, pn[K,N,2], elapsed[K]) or None — swapped atomically as
        # one tuple so readers never see torn pn/elapsed/epoch combos.
        self._scrape_mirror: Optional[Tuple[Tuple[int, int], np.ndarray, np.ndarray]] = None
        self._mirror_window = (
            min(int(config.buckets), SCRAPE_MIRROR_ROWS)
            if SCRAPE_MIRROR
            else 0
        )
        self._mirror_want = False  # a scrape went stale; completer refreshes
        # Cross-node tracing: (trace_id, bucket) pairs drained into the
        # current tick; the feeder records their merge spans after _apply.
        self._tick_traced: List[Tuple[int, str]] = []
        self._evictions = 0  # rows recycled under pool pressure
        self._scalar_dropped = 0  # v1 deltas dropped for unknown capacity
        # Completion pipeline: the feeder DISPATCHES device ticks and hands
        # (thunk, tickets) to this queue; the completer thread blocks on
        # the device result (np.asarray) and fans results out to tickets.
        # Host-side completion work (result read, per-ticket fanout, wire
        # encode for broadcasts) therefore overlaps the NEXT tick's device
        # compute instead of serializing with it — on TPU the device step
        # is ~28 µs while completion is comparable-or-larger Python time,
        # so the overlap roughly doubles sustained tick rate. Bounded so a
        # slow completer back-pressures the feeder instead of buffering
        # unboundedly.
        self._pcond = profiling.ProfiledCondition("engine.completion")
        self._pending: deque = deque()
        self._completing = False
        self._feeder_done = False
        # Device-commit pipeline: reusable staging buffers for the packed
        # commit/take matrices (shipped with jax.device_put BEFORE the
        # state lock so transfer overlaps the previous tick's compute),
        # and the dispatch-ahead bound on in-flight device ticks.
        self._staging = StagingPool()
        self._dispatch_ahead = DISPATCH_AHEAD
        # Adaptive commit-block sizing (PATROL_COMMIT_BLOCKS=auto):
        # measured per-row device-commit cost (completer-written racy
        # float gauge) and the feeder's current drain width. Starts at
        # the static default so warmup compiles the same shape ladder;
        # the first ticks then track the backlog.
        self._commit_row_ns_ewma = 0.0
        # Materialize the class default as an instance attr: auto mode
        # mutates it per tick, and the class constant must stay pristine
        # for the next engine.
        self._commit_blocks = type(self)._commit_blocks
        self._completer = threading.Thread(
            target=self._complete_loop, name="patrol-engine-complete", daemon=True
        )
        self._completer.start()
        self._thread = threading.Thread(target=self._run, name="patrol-engine", daemon=True)
        self._thread.start()

    # -- eviction (the dynamic-keyspace story; VERDICT r1 item 3) -----------

    def _evict(self, need: int) -> int:
        """Reclaim at least ``need`` rows: unbind the LRU unpinned rows,
        zero their device state in one batch, recycle the slots. Evicts a
        swath per trip so pool-exhaustion doesn't thrash. Caller must hold
        ``_evict_mu``. Returns rows reclaimed (0 ⇒ everything is pinned)."""
        # A fraction of the pool per trip: big enough to amortize the device
        # zeroing call, small enough that recently-used buckets survive.
        swath = min(4096, max(1, self.config.buckets // 8))
        victims = self.directory.pick_victims(max(need, swath))
        if victims.size == 0:
            return 0
        # Unbound now; forget any host-resident lanes BEFORE the rows
        # recycle, or a re-bind would inherit a dead bucket's state.
        self._drop_hosted_rows(victims)
        k = _pad_size(int(victims.size), lo=8, hi=1 << 20)
        rows = np.full(k, victims[0], np.int32)  # pad dupes: zeroing twice is fine
        rows[: victims.size] = victims
        with self._state_mu:
            self.state = zero_rows_jit(self.state, jnp.asarray(rows))
            self._state_gen += 1
        self.directory.recycle(victims)
        self._evictions += int(victims.size)
        log.info("evicted %d idle buckets (pool pressure)", victims.size)
        return int(victims.size)

    def _with_evict_retry(self, call, need: int):
        """Second-chance eviction scaffolding shared by every assign
        variant: fast path, then evict-and-retry under ``_evict_mu``.
        Loops because concurrent fast-path assigners may consume freed
        rows before the re-try; each iteration that evicts makes global
        progress. Returns None when every row is mid-flight (nothing
        evictable)."""
        try:
            return call()
        except DirectoryFullError:
            pass
        with self._evict_mu:
            while True:
                try:
                    return call()
                except DirectoryFullError:
                    if self._evict(need) == 0:
                        return None

    def assign_row(self, name: str, now: int, pin: bool = False) -> Tuple[int, bool]:
        """Directory assign with second-chance eviction on a spent pool.
        Raises DirectoryFullError only when every row is mid-flight."""
        res = self._with_evict_retry(
            lambda: self.directory.assign(name, now, pin=pin), 1
        )
        if res is None:
            raise DirectoryFullError("every bucket row is mid-flight")
        row, fresh = res
        # Unpinned (introspection) creations re-seed here; the take path
        # (pin=True, submit_take) pops the tombstone itself so it can
        # write the seed into fresh HOST lanes before the first commit.
        if fresh and not pin and self.directory.has_tombstones():
            seed = self._pop_tombstone_seed(name, row)
            if seed is not None:
                with self._cond:
                    self._deltas.append(_Delta(row, self.node_slot, *seed))
                    self._cond.notify()
        return res

    def _assign_pinned(self, name: str, now: int) -> Tuple[int, bool]:
        return self.assign_row(name, now, pin=True)

    def _assign_many_pinned(
        self, names: Sequence[str], now: int, hashes=None, with_fresh=False
    ):
        """Batch form of :meth:`_assign_pinned`; returns rows (or
        ``(rows, bind_fresh_mask)`` with ``with_fresh``), or None when
        the pool is spent with every row pinned (callers drop the batch —
        replication is loss-tolerant)."""
        return self._with_evict_retry(
            lambda: self.directory.assign_many(
                names, now, pin=True, hashes=hashes, with_fresh=with_fresh
            ),
            len(names),
        )

    def _assign_many_pinned_wire(self, names, name_rows, name_lens, hashes, now):
        """Wire-decoded variant of :meth:`_assign_many_pinned` — fresh
        binds copy the already-decoded name bytes vectorized
        (directory.assign_many_wire); same eviction-retry contract."""
        return self._with_evict_retry(
            lambda: self.directory.assign_many_wire(
                names, name_rows, name_lens, hashes, now, pin=True
            ),
            len(names),
        )

    # -- bucket lifecycle: idle-bucket GC + memory budget (ROADMAP item 4)

    def configure_lifecycle(
        self,
        window_ms: Optional[float] = None,
        idle_ms: Optional[float] = None,
        sweep_max: Optional[int] = None,
        max_buckets: Optional[int] = None,
        bytes_budget: Optional[int] = None,
        soft_frac: Optional[float] = None,
    ) -> None:
        """Tune the lifecycle knobs on a live engine (tests, the soak
        bench, operators). Setting a budget registers this engine with
        the SLO sentinel so watermark breaches auto-fire flight-recorder
        anomaly snapshots."""
        if window_ms is not None:
            self._gc_window_ns = int(window_ms * 1e6)
        if idle_ms is not None:
            self._gc_idle_ns = int(idle_ms * 1e6)
        if sweep_max is not None:
            self._gc_sweep_max = sweep_max
        if max_buckets is not None:
            self._max_buckets = max_buckets
        if bytes_budget is not None:
            self._bytes_budget = bytes_budget
        if soft_frac is not None:
            self._gc_soft_frac = soft_frac
        if self._max_buckets or self._bytes_budget:
            from patrol_tpu.utils import slo as slo_mod

            slo_mod.SENTINEL.watch_budget(self._budget_snapshot)

    def state_bytes_in_use(self) -> int:
        """Bytes of limiter state attributable to live buckets: device
        row planes (pn + elapsed), host directory metadata, host-resident
        lanes, and GC tombstones — the ``/debug/vars`` accounting the
        byte budget enforces against."""
        n = self.config.nodes
        row_bytes = n * 16 + 8 + _ROW_HOST_BYTES
        _t_n, t_bytes = self.directory.tombstone_stats()
        return (
            len(self.directory) * row_bytes
            + len(self._hosted) * (n * 16 + 64)
            + t_bytes
        )

    def _budget_pressure(self) -> int:
        """0 = under budget, 1 = soft watermark (GC pressure ramp),
        2 = hard watermark (new-name admission sheds)."""
        hard = soft = False
        if self._max_buckets:
            bound = len(self.directory)
            hard |= bound >= self._max_buckets
            soft |= bound >= int(self._max_buckets * self._gc_soft_frac)
        if self._bytes_budget:
            in_use = self.state_bytes_in_use()
            hard |= in_use >= self._bytes_budget
            soft |= in_use >= int(self._bytes_budget * self._gc_soft_frac)
        return 2 if hard else (1 if soft else 0)

    def _budget_snapshot(self) -> dict:
        """The SLO sentinel's budget provider: breach ⇒ anomaly snapshot
        (utils/slo.py watch_budget)."""
        return {
            "state_bytes_in_use": self.state_bytes_in_use(),
            "state_bytes_budget": self._bytes_budget,
            "buckets_bound": len(self.directory),
            "max_buckets": self._max_buckets,
            "over": self._budget_pressure() >= 2,
        }

    def _shed_new_names(self, now: int, n: int = 1) -> bool:
        """Hard-watermark admission check for NEW bucket names: one
        emergency sweep (damped to window/8 cadence) gets a chance to
        free budget; if pressure holds, the caller sheds the admission
        with an explicit signal instead of growing state. Existing names
        are never shed — their state is already paid for."""
        if self._budget_pressure() < 2:
            return False
        start = self._gc_win_start
        if start is None or now - start > self._gc_window_ns // 8:
            self.gc_sweep(now, force=True)
            if self._budget_pressure() < 2:
                return False
        with self._evict_mu:
            self._gc_shed += n
        profiling.COUNTERS.inc("gc_pressure_shed", n)
        trace_mod.anomaly("budget-shed")
        return True

    def _kick_gc_if_due(self, now: int) -> None:
        """Host-fastpath seam: wake the feeder for a sweep when the GC
        window rolled over (in-process takes never queue feeder work, so
        without this a pure fast-path workload never collects). Cost on
        the serve path: two int reads; the sweep itself runs on the
        feeder."""
        if not self._gc_window_ns:
            return
        start = self._gc_win_start
        if start is not None and now - start <= self._gc_window_ns:
            return
        with self._cond:
            self._gc_due = True
            self._cond.notify()

    def _maybe_gc(self) -> None:
        """Feeder-tick lifecycle cadence: sweep at window rollover, or at
        window/8 under budget pressure (the graceful-degradation ramp —
        GC ramps first, only then does admission shed)."""
        if not self._gc_window_ns:
            return
        now = self.clock()
        start = self._gc_win_start
        if start is None:
            with self._evict_mu:
                self._gc_win_start = now
            return
        window = self._gc_window_ns
        if (self._max_buckets or self._bytes_budget) and self._budget_pressure():
            window //= 8
        if now - start > window:
            self.gc_sweep(now)

    def gc_sweep(self, now_ns: Optional[int] = None, force: bool = False) -> int:
        """One lifecycle sweep: probe up to ``_gc_sweep_max`` idle
        candidates through the IsZero kernel (ops/lifecycle.py — host
        lanes answer via the numpy twin without a device hop), reclaim
        the full ones from the device plane and the host directory, and
        compact the free list. Returns buckets reclaimed. Callable from
        any thread: every candidate's verdict is re-verified under
        ``_evict_mu`` by :meth:`BucketDirectory.reclaim_rows` (pins and
        an untouched ``last_used_ns`` stamp), so in-flight takes/deltas —
        and rows that saw traffic after the probe — void their reclaim.

        Conservation (the part the provers pin): the reclaimed bucket's
        own PN lane + refill clock go into a directory tombstone and
        re-seed the row on re-creation, so the own-lane G-counters stay
        monotone across reclaim epochs — a peer's stale echo of the old
        lane values can never absorb (erase) post-reclaim spend. The
        protocol model's ``gc-drops-admitted-tokens`` mutation is exactly
        this design with the tombstone dropped, and it is rejected."""
        now = self.clock() if now_ns is None else now_ns
        pressure = self._budget_pressure()
        idle_ns = 0 if (force or pressure) else self._gc_idle_ns
        t0 = time.perf_counter_ns()
        cands, stamps = self.directory.gc_candidates(
            now, idle_ns, self._gc_sweep_max
        )
        reclaimed = 0
        if cands.size:
            reclaimed = self._gc_reclaim(cands, stamps, now)
        with self._evict_mu:
            self._gc_sweeps += 1
            self._gc_win_start = now
        profiling.COUNTERS.inc("gc_sweeps")
        profiling.COUNTERS.set_max(
            "state_bytes_in_use", self.state_bytes_in_use()
        )
        hist.GC_SWEEP.record(time.perf_counter_ns() - t0)
        return reclaimed

    def _gc_reclaim(self, cands: np.ndarray, stamps: np.ndarray, now: int) -> int:
        """Probe + reclaim body of :meth:`gc_sweep`."""
        n = len(cands)
        cap = self.directory.cap_base_nt[cands]
        per = self.directory.rate_per_ns[cands]
        created = self.directory.created_ns[cands]
        full = np.zeros(n, bool)
        own_a = np.zeros(n, np.int64)
        own_t = np.zeros(n, np.int64)
        el = np.zeros(n, np.int64)
        # Rows mid-promotion live in NEITHER plane completely (lanes
        # popped, device join not landed): never probe or reclaim them.
        # A promotion requested after this snapshot is caught by the
        # reclaim's last_used stamp — the takes that triggered it
        # refreshed the row at assign.
        with self._host_mu:
            promo = set(self._promote_pending) | set(self._promoting)
            hosted_sel = self._hosted_flag[cands].copy()
        if promo:
            keep = np.array([int(r) not in promo for r in cands], bool)
        else:
            keep = np.ones(n, bool)
        host_idx = np.flatnonzero(hosted_sel & keep)
        if host_idx.size:
            with self._host_mu:
                for i in host_idx:
                    lanes = self._hosted.get(int(cands[i]))
                    if lanes is None:
                        continue
                    sa = int(lanes.added.sum())
                    st = int(lanes.taken.sum())
                    full[i] = bool(
                        lifecycle_ops.host_lifecycle_full(
                            sa, st, lanes.elapsed_ns, cap[i], created[i],
                            now, per[i],
                        )
                    )
                    own_a[i] = int(lanes.added[self.node_slot])
                    own_t[i] = int(lanes.taken[self.node_slot])
                    el[i] = lanes.elapsed_ns
        dev_idx = np.flatnonzero(~hosted_sel & keep)
        if dev_idx.size:
            m = len(dev_idx)
            k = _pad_size(m, lo=8, hi=1 << 20)
            rows_p = np.zeros(k, np.int32)
            rows_p[:m] = cands[dev_idx]
            pad = np.zeros(k, np.int64)

            def col(vals):
                out = pad.copy()
                out[:m] = vals
                return jnp.asarray(out)

            probe = lifecycle_ops.LifecycleProbe(
                rows=jnp.asarray(rows_p),
                now_ns=col(np.full(m, now, np.int64)),
                per_ns=col(per[dev_idx]),
                cap_base_nt=col(cap[dev_idx]),  # padding keeps cap 0 ⇒ never full
                created_ns=col(created[dev_idx]),
            )
            with self._state_mu:
                view = lifecycle_ops.lifecycle_probe_jit(
                    self.state, probe, self.node_slot
                )
            # One batched, padded probe readback per GC sweep: the host
            # must learn which rows are reclaimable — cadenced by the
            # sweep interval, never per-request.
            full[dev_idx] = np.asarray(view.full)[:m]  # patrol-lint: disable=PTD003
            own_a[dev_idx] = np.asarray(view.own_added_nt)[:m]  # patrol-lint: disable=PTD003
            own_t[dev_idx] = np.asarray(view.own_taken_nt)[:m]  # patrol-lint: disable=PTD003
            el[dev_idx] = np.asarray(view.elapsed_ns)[:m]  # patrol-lint: disable=PTD003
        vict = np.flatnonzero(full)
        if not vict.size:
            return 0
        with self._evict_mu:
            kept = self.directory.reclaim_rows(
                cands[vict],
                stamps[vict],
                [(own_a[i], own_t[i], el[i]) for i in vict],
            )
            if not kept.size:
                return 0
            self._drop_hosted_rows(kept)
            k = _pad_size(int(kept.size), lo=8, hi=1 << 20)
            rows_z = np.full(k, kept[0], np.int32)
            rows_z[: kept.size] = kept
            with self._state_mu:
                self.state = zero_rows_jit(self.state, jnp.asarray(rows_z))
                self._state_gen += 1
            if self.directory.recycle_compact(kept):
                self._gc_compactions += 1
                profiling.COUNTERS.inc("directory_compactions")
            self._gc_reclaimed += int(kept.size)
        profiling.COUNTERS.inc("gc_buckets_reclaimed", int(kept.size))
        log.debug("lifecycle GC reclaimed %d full idle buckets", kept.size)
        return int(kept.size)

    def _pop_tombstone_seed(self, name: str, row: int):
        """Consume a reclaimed bucket's tombstone at re-creation:
        → (own_added_nt, own_taken_nt, elapsed_ns) or None. Restores the
        row's original creation stamp so the refill clock reconstructs
        exactly. The seed MUST land before the row's first take commit
        (callers order it into the same tick's merge phase, or write it
        straight into fresh host lanes) — a later join would let the
        tombstone values absorb the first takes' debits."""
        tomb = self.directory.pop_tombstone(name, row)
        if tomb is None:
            return None
        return tomb[0], tomb[1], tomb[2]

    def _reseed_fresh_rows(self, names, rows, fresh_mask) -> None:
        """Bulk-ingest tail: queue tombstone seeds for freshly-bound rows
        (merge order against the triggering deltas is free — joins
        commute)."""
        if not self.directory.has_tombstones():
            return
        seeds = []
        seen = set()
        for i in np.flatnonzero(fresh_mask):
            row = int(rows[i])
            if row in seen:
                continue
            seen.add(row)
            seed = self._pop_tombstone_seed(names[i], row)
            if seed is not None:
                seeds.append(_Delta(row, self.node_slot, *seed))
        if seeds:
            with self._cond:
                self._deltas.extend(seeds)
                self._cond.notify()

    def lifecycle_stats(self) -> Dict[str, object]:
        """The bucket-lifecycle accounting block for ``/debug/vars`` and
        the soak receipts (live gauges; the CounterRegistry carries the
        cluster-mergeable monotone counters next to these)."""
        t_n, _t_bytes = self.directory.tombstone_stats()
        return {
            "engine_gc_reclaimed": self._gc_reclaimed,
            "engine_gc_shed": self._gc_shed,
            "engine_gc_sweeps": self._gc_sweeps,
            "engine_gc_compactions": self._gc_compactions,
            "engine_gc_tombstones": t_n,
            "engine_state_bytes": self.state_bytes_in_use(),
            "engine_state_bytes_budget": self._bytes_budget,
            "engine_max_buckets": self._max_buckets,
            "engine_buckets_bound": len(self.directory),
            "engine_budget_pressure": self._budget_pressure(),
        }

    # -- entry points -------------------------------------------------------

    def _enqueue_take_locked(self, ticket: TakeTicket) -> None:
        """Queue one take (caller holds ``_cond``). With the hot-key fold
        on, a ticket whose (row, rate, count) key already has an OPEN
        queue entry rides that entry instead of appending its own — the
        rx-side collapse that keeps a single-name flood at one row of
        the per-tick budget."""
        if _take_fold_enabled():
            key = (
                ticket.row,
                ticket.rate.freq,
                ticket.rate.per_ns,
                ticket.count,
            )
            fold = self._open_folds.get(key)
            if fold is not None:
                fold.tickets.append(ticket)
                profiling.COUNTERS.inc("take_tickets_folded")
                return
            fold = _TakeFold(key, ticket)
            self._open_folds[key] = fold
            self._takes.append(fold)
            return
        self._takes.append(ticket)

    def submit_take(
        self, name: str, rate: Rate, count: int, now_ns: Optional[int] = None
    ) -> Tuple[TakeTicket, bool]:
        """Queue a take; returns (ticket, created). ``created`` mirrors the
        get-or-create miss signal that triggers incast (repo.go:96-106).
        Raises :class:`OverloadedError` for a NEW name when the memory
        budget's hard watermark holds after an emergency GC sweep — the
        explicit 429-class shed signal of the lifecycle layer."""
        now = self.clock() if now_ns is None else now_ns
        if (
            (self._max_buckets or self._bytes_budget)
            and self.directory.lookup(name) is None
            and self._shed_new_names(now)
        ):
            raise OverloadedError(
                "memory budget spent and nothing reclaimable; "
                f"new bucket {name!r} shed"
            )
        row, fresh = self._assign_pinned(name, now)
        seed = self._pop_tombstone_seed(name, row) if fresh else None
        # First *local* take on the row (capacity still unset) counts as a
        # miss for incast purposes even when replication created the row
        # first: scalar (v1-peer) deltas are dropped while the capacity is
        # unknown, so peer state must be re-solicited now that it is.
        created = fresh or int(self.directory.cap_base_nt[row]) == 0
        self.directory.init_cap_base(row, rate.freq * NANO)
        self.directory.note_rate(row, rate.per_ns)
        if HOST_FASTPATH and (fresh or self._hosted_flag[row]):
            ticket = self._try_host_take(
                name, row, rate, count, now, fresh, seed=seed
            )
            if ticket is not None:
                self._kick_gc_if_due(now)
                return ticket, created
        ticket = TakeTicket(name, row, rate, count, now)
        with self._cond:
            if seed is not None:
                # Tombstone re-seed rides the SAME tick's merge phase —
                # merges apply before takes, so the first take commits on
                # top of the restored own lane, never below it.
                self._deltas.append(_Delta(row, self.node_slot, *seed))
            self._enqueue_take_locked(ticket)
            self._cond.notify()
        return ticket, created

    # -- host fast path (cold/low-QPS buckets; VERDICT r3 item 1) -----------

    def _try_host_take(
        self,
        name: str,
        row: int,
        rate: Rate,
        count: int,
        now: int,
        fresh: bool,
        out_broadcasts: Optional[List[wire.WireState]] = None,
        seed: Optional[Tuple[int, int, int]] = None,
    ) -> Optional[TakeTicket]:
        """Serve one take from the host-resident lane model, in-process.
        Returns the already-completed ticket, or None when the row is (or
        just became) device-resident — the caller falls through to the
        device queue."""
        ticket = TakeTicket(name, row, rate, count, now)
        served = self._host_serve_ticket(ticket, fresh, out_broadcasts, seed)
        return ticket if served else None

    def _host_serve_ticket(
        self,
        ticket: TakeTicket,
        fresh: bool,
        out_broadcasts: Optional[List[wire.WireState]] = None,
        seed: Optional[Tuple[int, int, int]] = None,
    ) -> bool:
        """Complete an existing ticket from the host lane model; False ⇒
        the row is device-resident and the caller keeps the device path.
        Promotion to the device path happens here when the bucket's QPS
        window crosses HOST_PROMOTE_TAKES. ``out_broadcasts``: batch
        callers pass an accumulator so a whole batch fans out through ONE
        on_broadcast call, like the device completion path.

        Known creation race, accepted by design: between the directory
        bind and the hosted-flag flip (sub-µs of straight-line python), a
        concurrent rx delta or a concurrent take on the SAME brand-new
        name can route to the device plane, which the host model doesn't
        read. Consequences, both bounded to one bucket creation: (a) that
        spend is invisible to host admission until promotion joins the
        planes — at most one bucket burst of over-admission; (b) for a
        leaked concurrent TAKE, the promotion max-join keeps the larger
        of the two own-lane debits instead of their sum, i.e. the smaller
        take can be uncounted. Class precedent: the reference's merge
        loses concurrent takes across nodes the same way by design
        (scalar max, SURVEY §2 known-bugs) and accepts seconds-scale
        multiplied admission under partition (README.md:64-76); this
        window is ~6 orders of magnitude narrower. Closing it fully needs
        bind+host atomicity across the directory and host locks, whose
        ordering would deadlock against eviction (_evict holds _evict_mu
        then takes _host_mu via _drop_hosted_rows)."""
        row, rate, now = ticket.row, ticket.rate, ticket.now_ns
        with self._host_mu:
            lanes = self._hosted.get(row)
            if lanes is None:
                if not fresh:
                    return False  # promoted by a concurrent rx/take
                if self._native_store is not None:
                    # C++-backed block (we hold _host_mu == store mutex):
                    # from here the epoll thread serves this row in-front.
                    lanes = self._native_store.host_locked(row)
                else:
                    lanes = HostLanes(self.config.nodes)
                if seed is not None:
                    # Tombstone re-seed (lifecycle GC): the fresh lanes
                    # resume at the reclaimed bucket's own-lane values
                    # BEFORE the first take commits, so stale peer echoes
                    # can never absorb post-reclaim spend.
                    lanes.added[self.node_slot] = seed[0]
                    lanes.taken[self.node_slot] = seed[1]
                    lanes.elapsed_ns = seed[2]
                self._hosted[row] = lanes
                self._hosted_flag[row] = True
            lanes.roll_window(now)
            lanes.win_takes += 1
            # cap is read HERE, while the caller's pin still protects the
            # row — after the unpin below an eviction could re-bind the
            # row and a late read would broadcast another bucket's
            # capacity into peers' monotone lanes (permanently).
            cap = int(self.directory.cap_base_nt[row])
            remaining, ok = lanes.take(
                cap,
                int(self.directory.created_ns[row]),
                now,
                rate,
                ticket.count,
                self.node_slot,
            )
            self._host_takes += 1
            own_a = int(lanes.added[self.node_slot])
            own_t = int(lanes.taken[self.node_slot])
            sum_a = int(lanes.added.sum())
            sum_t = int(lanes.taken.sum())
            elapsed = lanes.elapsed_ns
            if lanes.win_takes > HOST_PROMOTE_TAKES:
                self._promote_locked(row)
        if ticket.complete(remaining, ok):
            self.directory.unpin_rows([row])
        done_ns = time.perf_counter_ns()
        hist.TAKE_SERVICE.record(done_ns - ticket.t0_ns)
        if ok:
            # patrol-audit: book the admitted tokens into the open audit
            # window (the AP-overshoot auditor's own lane). Leaf lock,
            # taken strictly after _host_mu released.
            self._audit.note(
                ticket.name, ticket.count * NANO, cap, rate.per_ns, now
            )
        if ticket.trace_id:
            trace_mod.SPANS.add(
                ticket.trace_id, self.node_slot, "take", ticket.name,
                ticket.t0_ns, done_ns - ticket.t0_ns,
            )
            tr = trace_mod.TRACE
            if tr.enabled:
                tr.record(trace_mod.EV_TAKE, done_ns - ticket.t0_ns, 1)
        # Replicate exactly as the device completion does (zero state is
        # the incast request marker and must never broadcast).
        if (own_a or own_t or elapsed or cap) and self.on_broadcast is not None:
            ws = wire.from_nanotokens(
                ticket.name, cap + sum_a, sum_t, elapsed,
                origin_slot=self.node_slot, cap_nt=cap,
                lane_added_nt=own_a, lane_taken_nt=own_t,
                trace_id=ticket.trace_id,
            )
            if out_broadcasts is not None:
                out_broadcasts.append(ws)
            else:
                self._emit_broadcasts([ws])
        return True

    def _emit_broadcasts(self, broadcasts: List[wire.WireState]) -> None:
        if not broadcasts:
            return
        self._note_dirty(broadcasts)
        if self.on_broadcast is not None:
            try:
                self.on_broadcast(broadcasts)
            except Exception:  # pragma: no cover
                log.exception("broadcast hook failed")

    def _note_dirty(self, broadcasts: List[wire.WireState]) -> None:
        """Remember which buckets this node broadcast state for (bounded,
        newest kept) — the shutdown-flush working set. Also stamps the
        patrol-audit per-bucket emission clock (staleness sampler)."""
        now = self.clock()
        with self._dirty_mu:
            d = self._dirty_names
            for st in broadcasts:
                d.pop(st.name, None)  # move-to-back keeps recency order
                d[st.name] = None
            while len(d) > self._dirty_cap:
                d.pop(next(iter(d)))
        for st in broadcasts:
            row = self.directory.lookup(st.name)
            if row is not None:
                self.directory.last_emit_ns[row] = now

    def drain_dirty_states(self, limit: int = 1024) -> List[wire.WireState]:
        """Snapshot the most recently broadcast buckets' CURRENT full lane
        state and clear the dirty set — the graceful-shutdown flush
        payload. Bounded by ``limit`` buckets (newest first); per-lane
        states, so both replication backends ship them on the normal
        broadcast path."""
        with self._dirty_mu:
            names = list(self._dirty_names)[-limit:]
            self._dirty_names.clear()
        out: List[wire.WireState] = []
        for lo in range(0, len(names), 64):
            for states in self.snapshot_many(names[lo : lo + 64]).values():
                out.extend(states)
        return out

    def _promote_locked(self, row: int) -> None:
        """Mark a bucket for promotion to device residency. The row KEEPS
        serving host-side (flag stays set, lanes stay live) until the
        feeder's next :meth:`_drain_promotions` joins every pending row's
        lanes in ONE batched device merge — deferral means no device
        round trip ever runs under ``_host_mu`` (a synchronous join here
        stalled every hosted bucket for the call; on a remote-compile
        transport that was an ~80 ms cliff on unrelated buckets), and the
        tick-ordered drain (pop+flip, then join, then _apply) preserves
        the atomicity argument: a take can only route device-ward AFTER
        the flag flips, and by then the join for its tick has landed.
        Caller holds ``_host_mu`` (a declared HOLDER contract in
        analysis/race.py — patrol-race checks this body as if the lock
        were taken at entry)."""
        if row in self._hosted:
            self._promote_pending.add(row)
            with self._cond:
                self._cond.notify()

    def _drain_promotions(self) -> None:
        """Complete pending host→device promotions: pop lanes + flip flags
        under ``_host_mu`` (brief), then apply ONE padded merge per
        MAX_MERGE_ROWS chunk under ``_state_mu``. Callers: the FEEDER at
        tick start (before _apply, so same-tick device work sees the
        joined planes — the ordering the promotion design relies on) and
        :meth:`flush_hosted` only on a STOPPED engine; a live off-feeder
        drain could flip flags and lose the join/apply ordering race.

        The whole pop→merge window runs under ``_evict_mu``: an eviction
        (or release) landing between the pop and the merge would zero and
        recycle the device row, and the already-packed merge would then
        resurrect the dead bucket's lanes into whatever bucket is bound
        to the recycled row next. ``_evict_mu`` is taken strictly outside
        ``_host_mu``/``_state_mu`` everywhere (same order as _evict and
        release_bucket), so this adds no ordering cycle."""
        with self._host_mu:
            if not self._promote_pending:
                return
        with self._evict_mu:
            self._drain_promotions_locked()

    def _drain_promotions_locked(self) -> None:
        """Body of :meth:`_drain_promotions`; caller holds ``_evict_mu``."""
        with self._host_mu:
            if not self._promote_pending:
                return
            popped: List[Tuple[int, HostLanes]] = []
            for row in self._promote_pending:
                lanes = self._hosted.pop(row, None)
                self._hosted_flag[row] = False
                if self._native_store is not None:
                    # Stop in-front serving NOW, inside the same critical
                    # section that flips the Python flag (the block's data
                    # stays valid for the join below).
                    self._native_store.unhost_locked(row)
                if lanes is not None:
                    self._promotions += 1
                    popped.append((row, lanes))
                    self._promoted_rows.add(row)  # idle-demotion candidate
                    self._promoted_at[row] = self.clock()
                    # Keep the lanes snapshot-visible until the device
                    # join lands (see _promoting's init comment).
                    self._promoting[row] = lanes
            self._promote_pending.clear()
        if not popped:
            return
        rows_l: List[int] = []
        slots_l: List[int] = []
        added_l: List[int] = []
        taken_l: List[int] = []
        elapsed_l: List[int] = []
        for row, lanes in popped:
            slots = np.flatnonzero(lanes.added | lanes.taken)
            if slots.size == 0 and not lanes.elapsed_ns:
                continue
            if slots.size == 0:
                slots = np.array([self.node_slot])
            for slot in slots:
                rows_l.append(row)
                slots_l.append(int(slot))
                added_l.append(int(lanes.added[slot]))
                taken_l.append(int(lanes.taken[slot]))
                elapsed_l.append(lanes.elapsed_ns)
        for lo in range(0, len(rows_l), MAX_MERGE_ROWS):
            hi = lo + MAX_MERGE_ROWS
            n = len(rows_l[lo:hi])
            k = _pad_size(n)
            packed = np.zeros((5, k), dtype=np.int64)
            packed[0, :n] = rows_l[lo:hi]
            packed[1, :n] = slots_l[lo:hi]
            packed[2, :n] = added_l[lo:hi]
            packed[3, :n] = taken_l[lo:hi]
            packed[4, :n] = elapsed_l[lo:hi]
            with self._state_mu:
                self.state = _jit_merge_packed()(
                    self.state, jnp.asarray(packed)
                )
            self._ticks += 1
        # All chunk joins have landed: the staged lanes are now fully
        # represented in the device planes, so drop the snapshot aliases.
        # (pop, not clear — an eviction may have already dropped some.)
        with self._host_mu:
            for row, _lanes in popped:
                self._promoting.pop(row, None)

    def _host_absorb_ingest(
        self,
        rows: np.ndarray,
        slots: np.ndarray,
        added: np.ndarray,
        taken: np.ndarray,
        elapsed: np.ndarray,
        scalar,
    ) -> Optional[np.ndarray]:
        """Fold rx deltas addressed to host-resident rows into their host
        lanes (the same elementwise max-join the device computes — exact,
        and the own-lane single-writer rule holds because rx deltas only
        RAISE lanes, never take). Returns a keep-mask for the caller's
        chunk (False ⇒ absorbed here; unpin those rows), or None when
        nothing in the chunk is hosted.

        Why absorb instead of promote: a cold bucket in a cluster gets its
        own state echoed back within one RTT (state broadcast + incast
        reply, repo.go:86-90) — promoting on any rx would end every hosted
        bucket after its first take. Promotion still happens for (a)
        scalar-semantics (v1 reference peer) deltas, whose
        deficit-attribution kernel needs the device path, and (b) rx
        pressure past HOST_PROMOTE_TAKES per window — a remotely-hot
        bucket belongs on the device."""
        if not self._hosted:
            return None
        keep = np.ones(len(rows), dtype=bool)
        now = self.clock()
        with self._host_mu:
            # The residency mask is read UNDER the lock: an idle demotion
            # flips flags inside its _host_mu commit, so reading here means
            # either we see the flip (absorb host-side) or the demotion's
            # pin re-check sees our caller's pin (taken at assign, before
            # this call) and skips the row — no delta can slip device-ward
            # into a row that is about to be zeroed.
            mask = self._hosted_flag[rows]
            if not mask.any():
                return None
            for i in np.flatnonzero(mask):
                row = int(rows[i])
                lanes = self._hosted.get(row)
                if lanes is None:
                    continue  # promoted since the mask was taken: keep
                if scalar is not None and scalar[i]:
                    self._promote_locked(row)
                    continue  # delta rides the tick; the feeder joins the
                    # lanes (_drain_promotions) before applying it
                slot = int(slots[i])
                if lanes.added[slot] < added[i]:
                    lanes.added[slot] = added[i]
                if lanes.taken[slot] < taken[i]:
                    lanes.taken[slot] = taken[i]
                if lanes.elapsed_ns < elapsed[i]:
                    lanes.elapsed_ns = int(elapsed[i])
                keep[i] = False
                lanes.roll_window(now)
                lanes.win_rx += 1
                if lanes.win_rx > HOST_PROMOTE_TAKES:
                    self._promote_locked(row)
        return keep

    def _drop_hosted_rows(self, rows) -> None:
        """Forget host-resident state for rows leaving service (eviction /
        release): must run after unbind and before recycle, or a future
        re-bind of the row would inherit a dead bucket's lanes."""
        if not self._hosted and not self._promoted_rows:
            return
        with self._host_mu:
            for row in rows:
                # A recycled row must not stay an idle-demotion candidate.
                self._promoted_rows.discard(int(row))
                self._promoted_at.pop(int(row), None)
                if self._hosted_flag[row]:
                    self._hosted.pop(int(row), None)
                    self._hosted_flag[row] = False
                    if self._native_store is not None:
                        self._native_store.unhost_locked(int(row))
                # A stale pending entry would promote (and de-host) the
                # NEXT bucket bound to this recycled row after one take.
                self._promote_pending.discard(int(row))
                # A staged mid-promotion entry would resurrect the dead
                # bucket's lanes into a snapshot of the recycled row.
                self._promoting.pop(int(row), None)

    # True on the single-device engine; MeshEngine opts out (its state is
    # sharded — the per-row gather/zero pair is unmeasured there).
    _demotion_capable = True

    # Delta blocks one tick may drain and coalesce into a single commit
    # dispatch; MeshEngine opts down to 1 (its fused shard_map step has
    # its own per-block routing and no commit-ring kernel).
    _commit_blocks = COMMIT_BLOCKS
    # Adaptive commit-block sizing (PATROL_COMMIT_BLOCKS=auto): the
    # feeder re-sizes _commit_blocks per tick from backlog + measured
    # device-commit cost. MeshEngine pins it off (fused-step drains have
    # their own routing economics, unmeasured under auto).
    _commit_blocks_auto = COMMIT_BLOCKS_AUTO
    # Raw-plane device ingest (ops/ingest.py): MeshEngine opts out — a
    # decode_fold_raw dispatch against its sharded planes is unmeasured,
    # and the delta plane falls back to the python decode there.
    _raw_ingest_capable = True
    # Inline interval fold (ingest_interval's delta_fold dispatch on the
    # rx thread): MeshEngine opts out — against SHARDED planes the fold
    # is a collective program, and launching one from the rx context
    # both holds the state mutex across a mesh rendezvous and (on the
    # forced-host-device platform) can wedge the shared event loop.
    # Opt-outs route the interval through the queued classify path so
    # the lanes merge inside the feeder's own fused step.
    _interval_fold_capable = True

    def _maybe_demote(self, tickets, deltas) -> None:
        """Feeder-only: at demote-window rollover, return quiet promoted
        rows to host residency. Exact by construction — the row's device
        planes are gathered into fresh host lanes, THEN the device row is
        zeroed (flag→zero order, so the state is never in neither place;
        a snapshot in between max-joins identical values, which is
        idempotent).

        Safety against concurrent work, in order:
        * in-hand deltas (this tick's drain) would merge into the zeroed
          row — rows with deltas in hand are skipped;
        * any OTHER queued/in-flight work holds a directory pin, so a row
          is only eligible when its pin count exactly equals the pins of
          this tick's own drained tickets (which the re-route then serves
          host-side). The pin re-check runs under _host_mu: an ingest that
          classified the row device-ward before our flag flip necessarily
          pinned it first (assign→classify order), so it is visible here;
          one that classifies after sees the flag and absorbs host-side.
        * the whole gather→flag→zero runs under _evict_mu, so eviction /
          release can't unbind or recycle a row mid-demotion (same
          exclusion the promotion drain uses)."""
        if not (HOST_FASTPATH and self._demotion_capable):
            return
        if self._demotion_paused:
            return
        now = self.clock()
        if self._demote_win_start is None:
            self._demote_win_start = now
            return
        if now - self._demote_win_start <= HOST_DEMOTE_WINDOW_NS:
            return
        counts, self._dev_window = self._dev_window, {}
        self._demote_win_start = now
        with self._host_mu:
            cands = [
                r for r in self._promoted_rows
                if counts.get(r, 0) < HOST_DEMOTE_TAKES
                # Anchor eligibility to the ROW's promotion time, not the
                # global window: a row promoted mid-window (or right after
                # a long idle gap left the window stale) has only a
                # truncated count — demoting it one tick after a hot-burst
                # promotion would flap. It must have been device-resident
                # for at least one full window first.
                and now - self._promoted_at.get(r, now)
                >= HOST_DEMOTE_WINDOW_NS
            ]
        if not cands:
            return
        own_pins: Dict[int, int] = {}
        for t in tickets:
            own_pins[t.row] = own_pins.get(t.row, 0) + 1
        delta_rows = (
            set(int(r) for r in deltas.rows) if deltas is not None else set()
        )
        with self._evict_mu:
            elig = []
            for row in cands:
                if row in delta_rows:
                    continue
                if not self.directory._bound[row]:
                    self._promoted_rows.discard(row)
                    self._promoted_at.pop(row, None)
                    continue
                if int(self.directory.pins[row]) != own_pins.get(row, 0):
                    continue  # queued work beyond this tick pins the row
                elig.append(row)
            if not elig:
                return
            pn, el = self.read_rows(elig)  # ONE padded gather
            demoted: List[int] = []
            with self._host_mu:
                # Re-check the pause under the lock: checkpoint restore
                # sets it, then snapshots _hosted under this same lock —
                # so no demotion can commit after restore's snapshot.
                if self._demotion_paused:
                    return
                for i, row in enumerate(elig):
                    if int(self.directory.pins[row]) != own_pins.get(row, 0):
                        continue  # pinned since the outer check
                    if self._hosted_flag[row]:
                        continue
                    if self._native_store is not None:
                        lanes = self._native_store.host_locked(row)
                    else:
                        lanes = HostLanes(self.config.nodes)
                    lanes.added[:] = pn[i][:, 0]
                    lanes.taken[:] = pn[i][:, 1]
                    lanes.elapsed_ns = int(el[i])
                    lanes.win_start_ns = now
                    self._hosted[row] = lanes
                    self._hosted_flag[row] = True
                    self._promoted_rows.discard(row)
                    self._promoted_at.pop(row, None)
                    demoted.append(row)
            if demoted:
                k = _pad_size(len(demoted), lo=8, hi=1 << 20)
                rows_arr = np.full(k, demoted[0], np.int32)
                rows_arr[: len(demoted)] = demoted
                with self._state_mu:
                    self.state = zero_rows_jit(self.state, jnp.asarray(rows_arr))
                    self._state_gen += 1
                self._demotions += len(demoted)
                log.debug("demoted %d idle buckets to host residency", len(demoted))

    def flush_hosted(self, timeout: float = 10.0) -> int:
        """Promote every host-resident bucket to the device path (exact
        batched join). Used by checkpoint RESTORE, whose dense max-join
        only sees device planes. Returns rows promoted; raises
        ``TimeoutError`` if the feeder's join hasn't landed within
        ``timeout`` — a silent partial flush would let the caller proceed
        against planes that never received the host-lane join (restore
        would then max-join into still-hosted rows and drop spend).

        The drain itself runs on the FEEDER (we only mark + wait): a
        drain on this thread would flip residency flags, release the
        host lock, and only then take the state lock for the join — a
        racing take could route device-ward and be applied by the feeder
        against pre-join planes (over-admission, and the later max-join
        would erase the smaller own-lane debit). Feeder-driven drains
        flip and join strictly before the same tick's _apply, which is
        the ordering the promotion design relies on."""
        with self._host_mu:
            rows = list(self._hosted.keys())
            self._promote_pending.update(rows)
        if not rows:
            return 0
        if self._stopped:
            # Feeder is gone and no traffic can race a stopped engine:
            # drain inline.
            self._drain_promotions()
            return len(rows)
        with self._cond:
            self._cond.notify()
        deadline = time.monotonic() + timeout
        ours = set(rows)
        while time.monotonic() < deadline:
            with self._host_mu:
                # A row leaves _promote_pending at the drain's pop and
                # leaves _promoting only after the device join lands —
                # absence from both is the exact "flush visible in device
                # planes" signal. Scoped to OUR rows: on a live engine,
                # ongoing traffic keeps feeding new promotions, and a
                # global-emptiness wait could spin past the deadline (and
                # spuriously raise) with our join long landed.
                if not (ours & self._promote_pending) and not (
                    ours & self._promoting.keys()
                ):
                    return len(rows)
            time.sleep(0.0005)
        raise TimeoutError(
            f"flush_hosted: promotion join for {len(rows)} rows did not "
            f"land within {timeout}s"
        )

    # -- cert-kit kernel families (ops/gcra.py, ops/concurrency.py, ----
    # ops/hierquota.py): synchronous microbatch entry points, one device
    # dispatch per call against the SHARED planes — these families ride
    # the same replication/merge path as the bucket take, so they share
    # its state lock and donate-and-replace discipline. Certified by
    # check.sh stage 9 (patrol-cert); registered in PROVE_ROOTS.

    def gcra_take(
        self, rows, now_ns, emission_ns, tol_ns, nreq
    ):
        """GCRA conformance microbatch → GcraResult (device arrays)."""
        req = GcraRequest(
            rows=jnp.asarray(np.asarray(rows, np.int32)),
            now_ns=jnp.asarray(np.asarray(now_ns, np.int64)),
            emission_ns=jnp.asarray(np.asarray(emission_ns, np.int64)),
            tol_ns=jnp.asarray(np.asarray(tol_ns, np.int64)),
            nreq=jnp.asarray(np.asarray(nreq, np.int64)),
        )
        with self._state_mu:
            self.state, res = gcra_take_batch_jit(
                self.state, req, self.node_slot
            )
            self._state_gen += 1
        return res

    def conc_acquire(
        self, rows, limit_nt, count_nt, nreq, releases
    ):
        """Concurrency acquire/release microbatch → ConcResult."""
        req = ConcRequest(
            rows=jnp.asarray(np.asarray(rows, np.int32)),
            limit_nt=jnp.asarray(np.asarray(limit_nt, np.int64)),
            count_nt=jnp.asarray(np.asarray(count_nt, np.int64)),
            nreq=jnp.asarray(np.asarray(nreq, np.int64)),
            releases=jnp.asarray(np.asarray(releases, np.int64)),
        )
        with self._state_mu:
            self.state, res = conc_acquire_batch_jit(
                self.state, req, self.node_slot
            )
            self._state_gen += 1
        return res

    def quota_take(
        self,
        rows_global,
        rows_tenant,
        rows_user,
        limit_global_nt,
        limit_tenant_nt,
        limit_user_nt,
        count_nt,
        nreq,
    ):
        """Hierarchical-quota path-take microbatch → QuotaResult."""
        req = QuotaRequest(
            rows_global=jnp.asarray(np.asarray(rows_global, np.int32)),
            rows_tenant=jnp.asarray(np.asarray(rows_tenant, np.int32)),
            rows_user=jnp.asarray(np.asarray(rows_user, np.int32)),
            limit_global_nt=jnp.asarray(np.asarray(limit_global_nt, np.int64)),
            limit_tenant_nt=jnp.asarray(np.asarray(limit_tenant_nt, np.int64)),
            limit_user_nt=jnp.asarray(np.asarray(limit_user_nt, np.int64)),
            count_nt=jnp.asarray(np.asarray(count_nt, np.int64)),
            nreq=jnp.asarray(np.asarray(nreq, np.int64)),
        )
        with self._state_mu:
            self.state, res = quota_take_batch_jit(
                self.state, req, self.node_slot
            )
            self._state_gen += 1
        return res

    def snapshot_planes(self) -> Tuple[np.ndarray, np.ndarray]:
        """Host copies of the device planes with every host-resident
        bucket's lanes max-joined in — the checkpoint-save view. Atomic
        against promotions: copy and join run under ``_host_mu``, and a
        bucket mid-promotion lives in exactly one of three places we all
        read — ``_hosted`` (not popped yet), ``_promoting`` (popped, device
        join in flight), or the device planes (join landed; the staged
        entry may linger until the drain's clear, which is harmless — the
        join is a max, so joining it twice is exact). The drain never
        holds both locks across its pop→merge window; ``_promoting`` is
        what makes this read atomic anyway. Residency is untouched — a
        save must not demote every cold bucket it snapshots. Host serving
        stalls for the copy; checkpoint cadence is operator-controlled and
        rare."""
        with self._host_mu:
            with self._state_mu:
                pn = np.array(self.state.pn)
                elapsed = np.array(self.state.elapsed)
            for row, lanes in itertools.chain(
                self._hosted.items(), self._promoting.items()
            ):
                np.maximum(pn[row, :, 0], lanes.added, out=pn[row, :, 0])
                np.maximum(pn[row, :, 1], lanes.taken, out=pn[row, :, 1])
                if elapsed[row] < lanes.elapsed_ns:
                    elapsed[row] = lanes.elapsed_ns
        return pn, elapsed

    def drain_native_promotions(self) -> None:
        """Promotions-only drain of the native store: no broadcast
        building, no dirty-row pops. The front's pump calls this when a
        poll wake finds the store's promotion-event counter moved but the
        broadcast cadence gate is still closed, so a take-pressure-hot
        bucket joins the device path promptly instead of waiting out
        ``max(poll tick, 4x last drain cost)`` (ADVICE r5). Dirty rows
        keep their queue entries and flags for the cadence-gated drain."""
        st = self._native_store
        if st is None:
            return
        with self._host_mu:
            for row in st.drain_promotes_locked():
                if row in self._hosted:
                    self._promote_locked(row)

    def drain_native_broadcasts(self) -> None:
        """Turn the C++ front's coalesced take effects into replication:
        emit each dirty row's LATEST full state once (CvRDT: a later state
        subsumes all earlier ones — lossless coalescing of the reference's
        per-take broadcast, repo.go:123-127) and mark take-pressure
        promotions. Called by the native front's pump each cycle; the C++
        side wakes the pump promptly via the poll predicate."""
        st = self._native_store
        if st is None:
            return
        # The C++ front serves takes without entering Python at all —
        # the pump's drain cycle is the one periodic seam that can keep
        # the GC cadence alive under pure in-front load.
        self._kick_gc_if_due(self.clock())
        if self.on_broadcast is None:
            # Standalone node: drain the queues (promotion marks still
            # matter; dirty flags must clear) without building states.
            with self._host_mu:
                while True:
                    dirty, _snap, promotes = st.drain_locked()
                    for row in promotes:
                        if row in self._hosted:
                            self._promote_locked(row)
                    if not dirty and not promotes:
                        return
        n = self.config.nodes
        while True:
            # The lock-held work is MINIMAL: the C++ drain copies each
            # dirty row's lanes into a flat buffer inside the call, and
            # Python only captures (membership, name, cap) per row —
            # _host_mu is the very mutex the epoll thread's in-front
            # takes block on, and a per-row Python pass under it (the
            # first r5 shape) held it for ~ms per drain at ~1k dirty
            # rows, surfacing as the front's p99 tail. Wire construction
            # runs OUTSIDE against the copies. Loop until both queues
            # drain: the C++ side pops a buffer's worth per call.
            meta: List[Tuple[int, str, int]] = []  # (snap idx, name, cap)
            with self._host_mu:
                dirty, lanes_snap, promotes = st.drain_locked()
                for row in promotes:
                    if row in self._hosted:
                        self._promote_locked(row)
                for i, row in enumerate(dirty):
                    if not self._hosted_flag[row]:
                        continue  # promoted/evicted since marked: its
                        # state rides the device completion broadcasts
                    name = self.directory.name_of(row)
                    if name is None:
                        continue
                    meta.append((i, name, int(self.directory.cap_base_nt[row])))
            states: List[wire.WireState] = []
            for i, name, cap in meta:
                row_snap = lanes_snap[i]
                own_a = int(row_snap[self.node_slot])
                own_t = int(row_snap[n + self.node_slot])
                elapsed = int(row_snap[2 * n])
                if not (own_a or own_t or elapsed or cap):
                    continue  # zero state is the incast marker
                states.append(
                    wire.from_nanotokens(
                        name, cap + int(row_snap[:n].sum()),
                        int(row_snap[n : 2 * n].sum()), elapsed,
                        origin_slot=self.node_slot, cap_nt=cap,
                        lane_added_nt=own_a, lane_taken_nt=own_t,
                    )
                )
            if states:
                self._emit_broadcasts(states)
            if not dirty and not promotes:
                return

    def take(
        self, name: str, rate: Rate, count: int, now_ns: Optional[int] = None
    ) -> Tuple[int, bool, bool]:
        """Blocking take: returns (remaining, ok, created)."""
        ticket, created = self.submit_take(name, rate, count, now_ns)
        ticket.wait()
        return ticket.remaining, ticket.ok, created

    def submit_takes_batch(
        self,
        names: Sequence[str],
        rates: Sequence[Rate],
        counts: Sequence[int],
        now_ns: Optional[int] = None,
    ) -> Optional[List[Tuple[TakeTicket, bool]]]:
        """Batched :meth:`submit_take` for the native HTTP pump: ONE
        directory pass (assign_many), one capacity init, one queue
        append + wake-up, instead of per-request lock/notify churn.
        Returns [(ticket, created), ...] in request order, or None when
        the pool is spent with every row pinned (the caller falls back or
        fails the batch). Under the memory budget's hard watermark,
        requests for NEW names come back as already-completed shed
        tickets (ok=False) — per-request 429s, never a failed batch."""
        now = self.clock() if now_ns is None else now_ns
        if self._max_buckets or self._bytes_budget:
            unknown = [
                i for i, n in enumerate(names)
                if self.directory.lookup(n) is None
            ]
            if unknown and self._shed_new_names(now, len(unknown)):
                shed = set(unknown)
                out: List = [None] * len(names)
                for i in unknown:
                    t = TakeTicket(names[i], 0, rates[i], int(counts[i]), now)
                    t.shed = True  # overload shed, not a rate deny
                    t.complete(0, False)  # never pinned, never queued
                    out[i] = (t, False)
                keep = [i for i in range(len(names)) if i not in shed]
                if keep:
                    sub = self._submit_takes_batch_inner(
                        [names[i] for i in keep],
                        [rates[i] for i in keep],
                        [counts[i] for i in keep],
                        now,
                    )
                    if sub is None:
                        return None
                    for i, r in zip(keep, sub):
                        out[i] = r
                return out
        return self._submit_takes_batch_inner(
            list(names), list(rates), list(counts), now
        )

    def _submit_takes_batch_inner(
        self,
        names: Sequence[str],
        rates: Sequence[Rate],
        counts: Sequence[int],
        now: int,
    ) -> Optional[List[Tuple[TakeTicket, bool]]]:
        res = self._assign_many_pinned(list(names), now, with_fresh=True)
        if res is None:
            return None
        rows, bind_fresh = res
        created_arr = self.directory.cap_base_nt[rows] == 0
        # Sequential-parity: only the FIRST occurrence of a row in the
        # batch counts as the creating miss (submit_take called twice
        # returns created=(True, False)).
        first = np.zeros(len(rows), dtype=bool)
        first[np.unique(rows, return_index=True)[1]] = True
        created = (created_arr & first).tolist()
        self.directory.init_cap_base_many(
            rows, np.asarray([r.freq for r in rates], np.int64) * NANO
        )
        self.directory.note_rate_many(
            rows, np.asarray([r.per_ns for r in rates], np.int64)
        )
        # Tombstone re-seeds for rows bound fresh by this batch (one per
        # first occurrence): applied into the fresh host lanes below, or
        # queued into the tick's merge phase for the device path.
        fresh_first_all = bind_fresh & first
        seeds: Dict[int, Tuple[int, int, int]] = {}
        if fresh_first_all.any() and self.directory.has_tombstones():
            for i in np.flatnonzero(fresh_first_all):
                s = self._pop_tombstone_seed(names[i], int(rows[i]))
                if s is not None:
                    seeds[int(rows[i])] = s
        # Host fast path: serve host-resident (and fresh) rows in-process,
        # in batch order; only the device-resident remainder rides a tick.
        # The flag is re-read per request (not precomputed): a fresh row
        # hosted by its first occurrence must catch the row's LATER
        # occurrences in this same batch, or they would run against the
        # row's empty device state. Residency eligibility is the
        # DIRECTORY's bind-fresh signal — a cap==0 proxy would mis-host
        # rows that already carry replicated device lanes (cap-less raw
        # lane deltas never set the cap).
        host_served: Dict[int, TakeTicket] = {}
        if HOST_FASTPATH:
            fresh_first = fresh_first_all
            # Candidates only — the device-only common case stays one
            # vectorized probe. Every later occurrence of a row hosted by
            # its own first occurrence has bind_fresh True, so it is in
            # the candidate set and its live flag re-read routes it host;
            # rows hosted by a CONCURRENT thread mid-batch are caught by
            # the tick's residency re-route, like submit_take.
            bc: List[wire.WireState] = []
            for i in np.flatnonzero(self._hosted_flag[rows] | bind_fresh):
                if self._hosted_flag[rows[i]] or fresh_first[i]:
                    t = self._try_host_take(
                        names[i], int(rows[i]), rates[i], int(counts[i]),
                        now, bool(fresh_first[i]), out_broadcasts=bc,
                        seed=seeds.get(int(rows[i])),
                    )
                    if t is not None:
                        host_served[int(i)] = t
                        if fresh_first[i]:
                            # Seed landed in the fresh host lanes.
                            seeds.pop(int(rows[i]), None)
            self._emit_broadcasts(bc)
        tickets = [
            host_served.get(i)
            or TakeTicket(names[i], int(rows[i]), rates[i], int(counts[i]), now)
            for i in range(len(names))
        ]
        queued = [t for i, t in enumerate(tickets) if i not in host_served]
        if host_served and not queued:
            # Fully host-served batch: no feeder work queued — kick the
            # GC cadence like the scalar fast path does.
            self._kick_gc_if_due(now)
        if queued or seeds:
            with self._cond:
                for srow, s in seeds.items():
                    # Un-hosted fresh binds: the seed rides the same
                    # tick's merge phase, ahead of the queued takes.
                    self._deltas.append(_Delta(srow, self.node_slot, *s))
                for t in queued:
                    self._enqueue_take_locked(t)
                self._cond.notify()
        return list(zip(tickets, created))

    def ingest_delta(self, state: wire.WireState, slot: int, scalar: bool = False) -> bool:
        """Queue one replication delta for merge; returns created flag.
        Dropped (not an error) if the pool is spent with everything pinned —
        replication is loss-tolerant by CRDT design (README.md:41-43).

        Wire semantics (the mixed-cluster interop contract; see ops/wire.py):

        * lane trailer present — a patrol_tpu peer's exact PN lane values:
          merge them directly (the float header is its aggregate view, for
          reference peers only); adopt ``cap_nt`` as this row's cap_base
          when still unset.
        * ``cap_nt`` only (with-cap trailer) — the header is the sender's
          capacity-included AGGREGATE but the exact lane is absent: subtract
          the wire cap and route through the deficit-attribution kernel
          (attributing the aggregate to the sender's lane directly would
          double-count every other lane's echoed grants).
        * ``scalar=True`` (v1 packet, no trailer) — a reference peer's
          scalar-max aggregates: subtract OUR cap_base and route through
          the deficit-attribution kernel. Unknowable before the first local
          take reveals the capacity ⇒ dropped until then (the reference
          rebroadcasts full state on every take, so nothing is lost).
        * none of the above — the header carries raw own-lane values: a
          base-trailer peer (grants-only lane header) or an internal
          raw-lane join (upsert seam). Plain lane max-merge.
        """
        now = self.clock()
        if not 0 <= slot < self.config.nodes:
            log.warning("delta slot %d out of range, dropped", slot)
            return False
        try:
            row, created = self._assign_pinned(state.name, now)
        except DirectoryFullError:
            log.warning("pool spent (all pinned); delta for %r dropped", state.name)
            return False
        if created and self.directory.has_tombstones():
            seed = self._pop_tombstone_seed(state.name, row)
            if seed is not None:
                with self._cond:
                    self._deltas.append(_Delta(row, self.node_slot, *seed))
                    self._cond.notify()
        # patrol-audit staleness stamp (remote absorb; racy by design).
        self.directory.last_remote_ns[row] = now
        added_nt = state.added_nt
        taken_nt = state.taken_nt
        if state.cap_nt is not None:
            if state.cap_nt > 0:
                self.directory.init_cap_base(row, state.cap_nt)
            if state.lane_added_nt is not None and state.lane_taken_nt is not None:
                added_nt = state.lane_added_nt
                taken_nt = state.lane_taken_nt
                scalar = False
            else:
                added_nt = max(added_nt - state.cap_nt, 0)
                scalar = True
        elif scalar:
            base = int(self.directory.cap_base_nt[row])
            if base == 0:
                # Capacity unknown on this row: can't separate the reference
                # peer's lazy-init cap from its grants yet. Drop; its next
                # full-state broadcast (every take) re-delivers.
                self.directory.unpin_rows([row])
                self._scalar_dropped += 1
                return created
            added_nt = max(added_nt - base, 0)
        if HOST_FASTPATH and self._hosted_flag[row]:
            # Scalar-fold twin of _host_absorb_ingest for the per-packet
            # path: same join, zero array allocations.
            absorbed = False
            with self._host_mu:
                lanes = self._hosted.get(row)
                if lanes is not None:
                    if scalar:
                        self._promote_locked(row)  # delta rides the tick
                    else:
                        if lanes.added[slot] < added_nt:
                            lanes.added[slot] = added_nt
                        if lanes.taken[slot] < taken_nt:
                            lanes.taken[slot] = taken_nt
                        if lanes.elapsed_ns < state.elapsed_ns:
                            lanes.elapsed_ns = state.elapsed_ns
                        lanes.roll_window(now)
                        lanes.win_rx += 1
                        if lanes.win_rx > HOST_PROMOTE_TAKES:
                            self._promote_locked(row)
                        absorbed = True
            if absorbed:
                self.directory.unpin_rows([row])
                if state.trace_id:
                    # Host-absorbed remote delta: the merge span completes
                    # here, joined to the sender's take span by the id.
                    trace_mod.SPANS.add(
                        state.trace_id, self.node_slot, "merge", state.name,
                        time.perf_counter_ns(), 0,
                    )
                return created
        delta = _Delta(row, slot, added_nt, taken_nt, state.elapsed_ns, scalar)
        if state.trace_id:
            delta.trace_id = state.trace_id
            delta.trace_name = state.name
        with self._cond:
            self._deltas.append(delta)
            self._cond.notify()
        return created

    def ingest_deltas_batch(
        self,
        names: Sequence[str],
        slots: Sequence[int],
        added_nt: Sequence[int],
        taken_nt: Sequence[int],
        elapsed_ns: Sequence[int],
        caps_nt: Optional[Sequence[int]] = None,
        lane_added_nt: Optional[Sequence[int]] = None,
        lane_taken_nt: Optional[Sequence[int]] = None,
        scalar: Optional[Sequence[bool]] = None,
    ) -> int:
        """Bulk ingest from the native receive path: one vectorized
        directory pass, one queue append, one wake-up — the feeder loop the
        Go reference runs one packet per iteration (repo.go:54-92).
        Returns deltas accepted (the whole batch is dropped only when the
        pool is spent with every row pinned).

        Per-delta wire semantics (−1 = field absent; see ingest_delta):
        lane values ≥0 ⇒ exact PN lane merge; cap ≥0 only ⇒ header minus
        wire cap, deficit-attribution merge; neither ⇒ ``scalar[i]`` picks
        between v1 scalar state (no trailer: deficit-attribution merge
        against OUR cap_base, dropped while that capacity is unknown) and a
        base-trailer peer's raw own-lane header (plain lane merge;
        the default when ``scalar`` is omitted, matching prior-version
        senders). ``caps_nt=None`` entirely ⇒ raw lane values (internal
        feeders: bench replay)."""
        now = self.clock()
        slots_a = np.asarray(slots, dtype=np.int64)
        keep = (slots_a >= 0) & (slots_a < self.config.nodes)
        caps_a = None if caps_nt is None else np.asarray(caps_nt, dtype=np.int64)
        lane_a = None if lane_added_nt is None else np.asarray(lane_added_nt, np.int64)
        lane_t = None if lane_taken_nt is None else np.asarray(lane_taken_nt, np.int64)
        scalar_a = None if scalar is None else np.asarray(scalar, dtype=bool)
        if caps_a is None and scalar_a is not None:
            # Honor the scalar flags even without a caps array (parity with
            # ingest_delta(..., scalar=True)): all caps absent.
            caps_a = np.full(len(slots_a), -1, dtype=np.int64)
        if not keep.all():
            idx = np.flatnonzero(keep)
            names = [names[i] for i in idx]
            slots_a = slots_a[idx]
            added_nt = np.asarray(added_nt, dtype=np.int64)[idx]
            taken_nt = np.asarray(taken_nt, dtype=np.int64)[idx]
            elapsed_ns = np.asarray(elapsed_ns, dtype=np.int64)[idx]
            if caps_a is not None:
                caps_a = caps_a[idx]
            if lane_a is not None:
                lane_a, lane_t = lane_a[idx], lane_t[idx]
            if scalar_a is not None:
                scalar_a = scalar_a[idx]
        if not len(names):
            return 0
        accepted = 0
        # Split oversize batches so one chunk never exceeds a tick's budget.
        for lo in range(0, len(names), MAX_MERGE_ROWS):
            hi = lo + MAX_MERGE_ROWS
            chunk_names = names[lo:hi]
            res = self._assign_many_pinned(chunk_names, now, with_fresh=True)
            if res is None:
                log.warning(
                    "pool spent (all pinned); %d deltas dropped", len(chunk_names)
                )
                continue
            rows, fresh = res
            if fresh.any():
                self._reseed_fresh_rows(chunk_names, rows, fresh)
            accepted += self._classify_queue_chunk(
                rows,
                slots_a[lo:hi],
                np.asarray(added_nt[lo:hi], dtype=np.int64),
                np.asarray(taken_nt[lo:hi], dtype=np.int64),
                np.asarray(elapsed_ns[lo:hi], dtype=np.int64),
                None if caps_a is None else caps_a[lo:hi],
                None if lane_a is None else lane_a[lo:hi],
                None if lane_t is None else lane_t[lo:hi],
                None if scalar_a is None else scalar_a[lo:hi],
            )
        return accepted

    def ingest_interval(
        self,
        names: Sequence[str],
        slots: Sequence[int],
        caps_nt: Sequence[int],
        added_nt: Sequence[int],
        taken_nt: Sequence[int],
        elapsed_ns: Sequence[int],
    ) -> int:
        """Bulk ingest of ONE decoded delta-interval datagram (wire v2,
        net/delta.py): exact absolute PN-lane values only — the delta
        plane never ships scalar aggregates, so there is no deficit
        attribution and no capacity gating here. One vectorized directory
        pass, host-lane absorption for host-resident rows (same join as
        the classic rx path), then a SINGLE sentinel-padded scatter-max
        dispatch (ops/delta.delta_fold) for the device remainder — a
        whole interval lands as one batched plane commit instead of
        hundreds of queued per-delta objects. Returns deltas accepted;
        drops are loss-tolerant by CRDT design, like every ingest path."""
        if not self._interval_fold_capable:
            # Sharded planes (_interval_fold_capable=False): the entries
            # are exact PN lane values with caps, which is precisely the
            # lane-trailer case of the classify path — queue them for the
            # feeder's fused step instead of folding here on rx.
            return self.ingest_deltas_batch(
                names,
                slots,
                added_nt,
                taken_nt,
                elapsed_ns,
                caps_nt=caps_nt,
                lane_added_nt=added_nt,
                lane_taken_nt=taken_nt,
            )
        now = self.clock()
        slots_a = np.asarray(slots, dtype=np.int64)
        keep = (slots_a >= 0) & (slots_a < self.config.nodes)
        caps_a = np.asarray(caps_nt, dtype=np.int64)
        added_a = np.asarray(added_nt, dtype=np.int64)
        taken_a = np.asarray(taken_nt, dtype=np.int64)
        elapsed_a = np.asarray(elapsed_ns, dtype=np.int64)
        if not keep.all():
            idx = np.flatnonzero(keep)
            names = [names[i] for i in idx]
            slots_a, caps_a = slots_a[idx], caps_a[idx]
            added_a, taken_a, elapsed_a = added_a[idx], taken_a[idx], elapsed_a[idx]
        if not len(names):
            return 0
        accepted = 0
        for lo in range(0, len(names), MAX_MERGE_ROWS):
            hi = lo + MAX_MERGE_ROWS
            chunk_names = names[lo:hi]
            res = self._assign_many_pinned(chunk_names, now, with_fresh=True)
            if res is None:
                log.warning(
                    "pool spent (all pinned); %d interval deltas dropped",
                    len(chunk_names),
                )
                continue
            rows, fresh_c = res
            # patrol-audit staleness stamp: these rows just absorbed
            # remote-lane state (racy int64 write, sampler-only reader).
            self.directory.last_remote_ns[rows] = now
            if fresh_c.any():
                self._reseed_fresh_rows(chunk_names, rows, fresh_c)
            slots_c = slots_a[lo:hi]
            caps_c = np.maximum(caps_a[lo:hi], 0)
            added_c = np.maximum(added_a[lo:hi], 0)
            taken_c = np.maximum(taken_a[lo:hi], 0)
            elapsed_c = np.maximum(elapsed_a[lo:hi], 0)
            pos = caps_c > 0
            if pos.any():
                self.directory.init_cap_base_many(rows[pos], caps_c[pos])
            live = np.ones(len(rows), dtype=bool)
            if HOST_FASTPATH:
                keep_h = self._host_absorb_ingest(
                    rows, slots_c, added_c, taken_c, elapsed_c, None
                )
                if keep_h is not None:
                    absorbed = ~keep_h
                    if absorbed.any():
                        self.directory.unpin_rows(rows[absorbed])
                        accepted += int(absorbed.sum())
                        live = keep_h
            n = int(live.sum())
            if n == 0:
                continue
            k = _pad_size(n)
            rows_p = np.full(k, merge_mod.FOLD_PAD_ROW, np.int32)
            slots_p = np.zeros(k, np.int32)
            added_p = np.zeros(k, np.int64)
            taken_p = np.zeros(k, np.int64)
            elapsed_p = np.zeros(k, np.int64)
            rows_p[:n] = rows[live]
            slots_p[:n] = slots_c[live]
            added_p[:n] = added_c[live]
            taken_p[:n] = taken_c[live]
            elapsed_p[:n] = elapsed_c[live]
            batch = delta_ops.DeltaBatch(
                rows=jnp.asarray(rows_p),
                slots=jnp.asarray(slots_p),
                added_nt=jnp.asarray(added_p),
                taken_nt=jnp.asarray(taken_p),
                elapsed_ns=jnp.asarray(elapsed_p),
            )
            t0 = time.perf_counter_ns()
            with self._state_mu, _annotate("delta_fold"):
                self.state = delta_ops.delta_fold_jit(self.state, batch)
            self._observe_device_commit("delta_fold", t0, n)
            self._ticks += 1
            self.directory.unpin_rows(rows[live])
            accepted += n
        return accepted

    def ingest_raw_planes(
        self,
        planes: np.ndarray,
        lengths: np.ndarray,
        walk=None,
        release: Optional[Callable[[], None]] = None,
    ) -> int:
        """Device-resident ingest (ops/ingest.py; ROADMAP item 1): raw
        dv2 datagram byte planes → joined state in ONE decode+fold
        dispatch. The wire→state path ships BYTES — framing walk, entry
        extraction, checksum/validation verdicts, sentinel-padding of
        invalid packets, and the scatter-max fold all run inside the
        kernel; the host contributes only what a device cannot: the
        directory pass resolving entry names to rows (vectorized, via
        the walk's name offsets/hashes — Python strings materialize only
        for first-seen buckets) and the host-lane split, which absorbs
        the kernel's ``hosted_mask`` output through the existing
        host-lane join.

        ``planes`` is uint8[P, ROW] (rows straight out of the rx ring —
        non-dv2 rows simply fail the in-kernel verdict via a zeroed
        length); ``walk`` is the caller's :func:`ops.ingest.host_walk`
        result when it already ran one (the delta plane's ack
        bookkeeping shares it); ``release`` is invoked on the completion
        pipeline once the shipped planes operand is READY (the
        StagingPool contract: device_put copies, so readiness means the
        ring plane is refillable) — or inline if the dispatch never
        happens. Returns deltas accepted (folded + host-absorbed)."""
        from patrol_tpu.ops import ingest as ingest_ops

        released = release is None

        def _release_inline() -> None:
            nonlocal released
            if not released:
                released = True
                release()

        try:
            planes = np.asarray(planes)
            lengths = np.ascontiguousarray(lengths, np.int32)
            if walk is None:
                walk = ingest_ops.host_walk(planes, lengths)
            if not walk.ok.any():
                # Nothing dispatch-worthy: every row failed the framing
                # walk, so the kernel would sentinel-pad the whole batch
                # and fold nothing. Skip the dispatch (a garbage flood
                # must not burn device programs) — the finally releases
                # the planes inline, honoring the ring contract.
                return 0
            P, row_w = planes.shape
            E = walk.name_len.shape[1]
            now = self.clock()
            live = walk.ok[:, None] & (
                np.arange(E)[None, :] < walk.count[:, None]
            )
            pi, ei = np.nonzero(live)
            rows_pe = np.full((P, E), _FOLD_PAD_ROW, np.int32)
            hosted_pe = np.zeros((P, E), dtype=bool)
            accepted = 0
            pinned: Optional[np.ndarray] = None
            keep_chunk_rows: Optional[np.ndarray] = None
            if pi.size:
                # Entry filter the python rx path applies per entry:
                # out-of-range slots and control-channel names never
                # reach the directory (nor the fold — their rows stay
                # sentinels).
                slots_f = walk.slot[pi, ei]
                off_f = walk.name_off[pi, ei].astype(np.int64)
                len_f = walk.name_len[pi, ei].astype(np.int32)
                first = planes[pi, np.clip(off_f, 0, row_w - 1)]
                ctrl = (len_f > 0) & (first == 0)
                keep = (slots_f >= 0) & (slots_f < self.config.nodes) & ~ctrl
                pi, ei = pi[keep], ei[keep]
                off_f, len_f = off_f[keep], len_f[keep]
            if pi.size:
                # The existing directory pass, raw form: vectorized
                # hashed lookup (pins hits), misses bound once per
                # bucket lifetime with tombstone re-seed.
                hashes_f = walk.name_hash[pi, ei]
                name_buf = ingest_ops.gather_name_rows(
                    planes, pi, off_f, len_f
                )
                rows_f = self.directory.lookup_hashed_pinned(
                    hashes_f, name_buf, len_f, now
                )
                miss = np.flatnonzero(rows_f < 0)
                for lo in range(0, miss.size, MAX_MERGE_ROWS):
                    mi = miss[lo : lo + MAX_MERGE_ROWS]
                    got = self._bind_wire_misses_pinned(
                        name_buf, len_f, hashes_f, mi, now
                    )
                    if got is not None:
                        rows_f[mi] = got
                bound = rows_f >= 0
                if bound.any():
                    b_rows = rows_f[bound].astype(np.int64)
                    pinned = b_rows
                    # patrol-audit staleness stamp (remote absorb; racy
                    # by design, sampler-only reader).
                    self.directory.last_remote_ns[b_rows] = now
                    caps_b = np.maximum(walk.cap[pi, ei][bound], 0)
                    pos = caps_b > 0
                    if pos.any():
                        self.directory.init_cap_base_many(
                            b_rows[pos], caps_b[pos]
                        )
                    if HOST_FASTPATH and self._hosted:
                        hosted_b = self._hosted_flag[b_rows]
                    else:
                        hosted_b = np.zeros(len(b_rows), dtype=bool)
                    rows_pe[pi[bound], ei[bound]] = b_rows
                    hosted_pe[pi[bound], ei[bound]] = hosted_b

            # ONE dispatch for the whole batch. The planes ship as-is
            # (rx-ring rows, no intermediate numpy repack); entry_off is
            # the walk's framing proposal the kernel RE-VALIDATES,
            # rows/hosted are the host plan; everything else — framing
            # chain, checksums, verdicts, sentinel padding, fold —
            # happens in-kernel.
            entry_off = np.maximum(walk.name_off - 1, 0)
            t0 = time.perf_counter_ns()
            planes_dev = jax.device_put(np.ascontiguousarray(planes))
            _obs_stage(hist.STAGE_H2D, t0, trace_mod.EV_H2D_PUT, int(pi.size))
            t0 = time.perf_counter_ns()
            with self._state_mu, _annotate("decode_fold_raw"):
                (
                    self.state, _ok_d, _entry_ok, hosted_mask,
                    d_slot, _d_cap, d_added, d_taken, d_elapsed,
                ) = ingest_ops.decode_fold_raw_jit(
                    self.state, planes_dev, jnp.asarray(lengths),
                    jnp.asarray(entry_off), jnp.asarray(rows_pe),
                    jnp.asarray(hosted_pe),
                )
            _obs_stage(
                hist.STAGE_DISPATCH, t0, trace_mod.EV_COMMIT_DISPATCH,
                int(pi.size),
            )
            self._observe_device_commit("decode_fold_raw", t0, max(int(pi.size), 1))
            self._ticks += 1
            profiling.COUNTERS.inc("ingest_raw_device_dispatches")
            profiling.COUNTERS.inc(
                "ingest_raw_bytes_on_device",
                int(lengths[walk.ok].sum()) if walk.ok.any() else 0,
            )
            if release is not None:
                released = True

                def _commit_plane() -> None:
                    # Plane-recycle gate ON the completion pipeline: the
                    # rx buffer may not be reused before the kernel has
                    # consumed it. Runs on the completer, not the rx path.
                    jax.block_until_ready(planes_dev)  # patrol-lint: disable=PTD003
                    release()

                self._enqueue_completion(_commit_plane, (), {})

            folded = int(((rows_pe != _FOLD_PAD_ROW) & ~hosted_pe).sum())
            accepted += folded
            if hosted_pe.any():
                # Host-lane split, driven by the KERNEL's hosted-mask
                # output (valid ∩ hosted) and decoded entry values: the
                # readback joins them into the host lanes; entries whose
                # row promoted mid-flight ride the feeder tick instead.
                # Kernel-verdict readback: one batched D2H per rx ring,
                # only on the (rare) host-resident branch — the price of
                # letting the kernel, not the host, decide residency.
                hm = np.asarray(hosted_mask)  # patrol-lint: disable=PTD003
                hpi, hei = np.nonzero(hm)
                if hpi.size:
                    h_rows = rows_pe[hpi, hei].astype(np.int64)
                    h_slots = np.asarray(d_slot)[hpi, hei]  # patrol-lint: disable=PTD003
                    h_added = np.asarray(d_added)[hpi, hei]  # patrol-lint: disable=PTD003
                    h_taken = np.asarray(d_taken)[hpi, hei]  # patrol-lint: disable=PTD003
                    h_elapsed = np.maximum(
                        np.asarray(d_elapsed)[hpi, hei], 0  # patrol-lint: disable=PTD003
                    )
                    keep_h = self._host_absorb_ingest(
                        h_rows, h_slots, h_added, h_taken, h_elapsed, None
                    )
                    if keep_h is None:
                        keep_h = np.ones(len(h_rows), dtype=bool)
                    accepted += int((~keep_h).sum())
                    if keep_h.any():
                        keep_chunk_rows = h_rows[keep_h]
                        chunk = _DeltaChunk(
                            keep_chunk_rows, h_slots[keep_h],
                            h_added[keep_h], h_taken[keep_h],
                            h_elapsed[keep_h],
                        )
                        with self._cond:
                            self._deltas.append(chunk)
                            self._cond.notify()
                        accepted += chunk.n
            # Release this call's pins — except rows re-queued as a
            # feeder chunk, whose pins the tick's finally releases.
            if pinned is not None:
                if keep_chunk_rows is not None and keep_chunk_rows.size:
                    unpin = pinned.copy()
                    # One pin per entry was taken; the chunk keeps one
                    # per re-queued entry.
                    drop = np.zeros(len(unpin), dtype=bool)
                    remaining = {}
                    for r in keep_chunk_rows:
                        remaining[int(r)] = remaining.get(int(r), 0) + 1
                    for i, r in enumerate(unpin):
                        c = remaining.get(int(r), 0)
                        if c:
                            remaining[int(r)] = c - 1
                            drop[i] = True
                    self.directory.unpin_rows(unpin[~drop])
                else:
                    self.directory.unpin_rows(pinned)
            return accepted
        finally:
            _release_inline()

    def _classify_queue_chunk(
        self,
        rows: np.ndarray,
        slots_c: np.ndarray,
        added_c: np.ndarray,
        taken_c: np.ndarray,
        elapsed_c: np.ndarray,
        caps_c: Optional[np.ndarray],
        lane_ac: Optional[np.ndarray],
        lane_tc: Optional[np.ndarray],
        scalar_c_in: Optional[np.ndarray],
    ) -> int:
        """Shared tail of the bulk-ingest paths: wire-semantics
        classification (see ingest_deltas_batch) over a chunk whose rows
        are already assigned+pinned, then one queue append + wake-up.
        Returns deltas queued; unpins any it drops."""
        added_c = np.maximum(added_c, 0)
        taken_c = np.maximum(taken_c, 0)
        elapsed_c = np.maximum(elapsed_c, 0)
        # patrol-audit staleness stamp (remote absorb; racy by design).
        self.directory.last_remote_ns[rows] = self.clock()
        scalar_c = None
        if caps_c is not None:
            has_cap = caps_c >= 0
            # Adopt peer capacities first, so same-batch v1 deltas for
            # rows initialized here already see the base.
            self.directory.init_cap_base_many(
                rows[has_cap & (caps_c > 0)], caps_c[has_cap & (caps_c > 0)]
            )
            # v1 (no trailer) ⇒ capacity-included scalar aggregates; a
            # cap-less base trailer ⇒ raw own-lane header (no subtract).
            v1 = (
                ~has_cap & scalar_c_in
                if scalar_c_in is not None
                else np.zeros_like(has_cap)
            )
            base = self.directory.cap_base_nt[rows]
            sub = np.where(has_cap, np.maximum(caps_c, 0), np.where(v1, base, 0))
            added_c = np.maximum(added_c - sub, 0)
            lane_ok = np.zeros_like(has_cap)
            if lane_ac is not None:
                # Lane-trailer packets: the exact PN lane values replace
                # the header-derived approximation.
                lane_ok = has_cap & (lane_ac >= 0) & (lane_tc >= 0)
                added_c = np.where(lane_ok, lane_ac, added_c)
                taken_c = np.where(lane_ok, lane_tc, taken_c)
            # Deficit attribution for every aggregate-header delta: v1
            # packets and cap-without-lane trailers alike.
            scalar_c = v1 | (has_cap & ~lane_ok)
            # v1 deltas on rows with unknown capacity: drop (the peer's
            # next full-state broadcast re-delivers).
            unknown = v1 & (base == 0)
            if unknown.any():
                self._scalar_dropped += int(unknown.sum())
                self.directory.unpin_rows(rows[unknown])
                keep_c = ~unknown
                rows, slots_c = rows[keep_c], slots_c[keep_c]
                added_c, taken_c = added_c[keep_c], taken_c[keep_c]
                elapsed_c, scalar_c = elapsed_c[keep_c], scalar_c[keep_c]
                if not len(rows):
                    return 0
        absorbed_n = 0
        if HOST_FASTPATH:
            keep_h = self._host_absorb_ingest(
                rows, slots_c, added_c, taken_c, elapsed_c, scalar_c
            )
            if keep_h is not None and not keep_h.all():
                self.directory.unpin_rows(rows[~keep_h])
                absorbed_n = int((~keep_h).sum())
                rows, slots_c = rows[keep_h], slots_c[keep_h]
                added_c, taken_c = added_c[keep_h], taken_c[keep_h]
                elapsed_c = elapsed_c[keep_h]
                if scalar_c is not None:
                    scalar_c = scalar_c[keep_h]
                if not len(rows):
                    return absorbed_n
        chunk = _DeltaChunk(rows, slots_c, added_c, taken_c, elapsed_c, scalar_c)
        with self._cond:
            self._deltas.append(chunk)
            self._cond.notify()
        return chunk.n + absorbed_n

    def ingest_deltas_batch_raw(
        self,
        n: int,
        name_buf: np.ndarray,
        name_lens: np.ndarray,
        name_hashes: np.ndarray,
        slots: np.ndarray,
        added_nt: np.ndarray,
        taken_nt: np.ndarray,
        elapsed_ns: np.ndarray,
        caps_nt: np.ndarray,
        lane_added_nt: np.ndarray,
        lane_taken_nt: np.ndarray,
        scalar: np.ndarray,
    ) -> int:
        """Zero-materialization bulk ingest — the native rx loop's fast
        path. Names arrive as raw zero-padded byte rows + FNV hashes
        (native.decode_batch_raw); known buckets resolve through the
        directory's vectorized hash table without creating ONE Python
        string, and only directory misses (new buckets — once per bucket
        lifetime) fall back to string materialization and the evicting
        assign path. Wire-semantics classification is shared with
        :meth:`ingest_deltas_batch`. BENCH_r02 motivation: string
        materialization was 85% of decode cost on the replay bench."""
        now = self.clock()
        keep = (
            (slots[:n] >= 0)
            & (slots[:n] < self.config.nodes)
            & (name_lens[:n] >= 0)
        )
        idx_all = np.flatnonzero(keep)
        # Gather names as u64 words, not bytes: fancy-indexing cost scales
        # with element count (8× cheaper), and the directory verifies on
        # the same word view.
        name_words = np.ascontiguousarray(name_buf).view(np.uint64)
        accepted = 0
        for lo in range(0, len(idx_all), MAX_MERGE_ROWS):
            idx = idx_all[lo : lo + MAX_MERGE_ROWS]
            if not idx.size:
                continue
            rows = self.directory.lookup_hashed_pinned(
                name_hashes[idx], name_words[idx], name_lens[idx], now
            )
            miss = np.flatnonzero(rows < 0)
            if miss.size:
                mi = idx[miss]
                miss_rows = self._bind_wire_misses_pinned(
                    name_buf, name_lens, name_hashes, mi, now
                )
                if miss_rows is None:
                    hit = rows >= 0
                    idx, rows = idx[hit], rows[hit]
                    if not idx.size:
                        continue
                else:
                    rows[miss] = miss_rows
            accepted += self._classify_queue_chunk(
                rows,
                slots[idx].astype(np.int64),
                added_nt[idx],
                taken_nt[idx],
                elapsed_ns[idx],
                caps_nt[idx],
                lane_added_nt[idx],
                lane_taken_nt[idx],
                scalar[idx],
            )
        return accepted

    def _bind_wire_misses_pinned(
        self,
        name_buf: np.ndarray,
        name_lens: np.ndarray,
        hashes: np.ndarray,
        mi: np.ndarray,
        now: int,
    ) -> Optional[np.ndarray]:
        """Shared miss protocol of the wire ingest paths: materialize the
        first-seen names (the one place the rx path creates Python
        strings), bind + pin via the wire bind path. None ⇒ pool spent
        (logged); callers drop those deltas."""
        miss_names = [
            bytes(name_buf[i, : name_lens[i]]).decode("utf-8", "surrogateescape")
            for i in mi
        ]
        rows = self._assign_many_pinned_wire(
            miss_names, name_buf[mi], name_lens[mi], hashes[mi], now
        )
        if rows is None:
            log.warning("pool spent (all pinned); %d deltas dropped", mi.size)
        elif self.directory.has_tombstones():
            # Wire misses are creations by definition: re-seed any
            # reclaimed bucket's own lane from its tombstone.
            self._reseed_fresh_rows(
                miss_names, rows, np.ones(len(rows), dtype=bool)
            )
        return rows

    def ingest_wire_batch(
        self,
        dbuf,
        n: int,
        slots: np.ndarray,
        no_trailer: np.ndarray,
    ) -> int:
        """The native rx loop's fused fast path: raw decode buffers
        (native.DecodeBuffers — float64 wire headers, zero-padded name
        rows, FNV hashes) → classified device queue in ONE native call
        (pt_rx_classify: resolve + sanitize + wire-semantics classify).
        Python touches only the leftovers: directory misses (bound via the
        wire bind path, classified by the numpy tail) and v1 deltas whose
        row capacity was unknown at native classify time. Falls back to
        :meth:`ingest_deltas_batch_raw` when the native table is absent.
        BENCH r2/r3 motivation: the numpy classify tail cost ~500 ns/delta
        and capped host ingest around 1M deltas/s (VERDICT r2 item 2)."""
        now = self.clock()
        slots = np.ascontiguousarray(slots[:n], np.int64)
        res = self.directory.rx_classify(
            n, dbuf.hashes, dbuf.names, dbuf.name_lens, dbuf.added,
            dbuf.taken, dbuf.elapsed, slots, self.config.nodes,
            dbuf.caps, dbuf.lane_a, dbuf.lane_t, no_trailer, now,
        )
        if res is None:
            return self.ingest_deltas_batch_raw(
                n, dbuf.names, dbuf.name_lens, dbuf.hashes, slots,
                wire.sanitize_nt_array(dbuf.added[:n]),
                wire.sanitize_nt_array(dbuf.taken[:n]),
                np.maximum(dbuf.elapsed[:n].astype(np.int64), 0),
                dbuf.caps[:n], dbuf.lane_a[:n], dbuf.lane_t[:n],
                no_trailer[:n].astype(bool),
            )
        rows, out_a, out_t, out_e, out_s = res
        accepted = 0
        miss = rows == -1
        if miss.any():
            # First sight of these buckets (once per bucket lifetime):
            # bind, then classify through the numpy tail.
            mi = np.flatnonzero(miss)
            miss_rows = self._bind_wire_misses_pinned(
                dbuf.names, dbuf.name_lens, dbuf.hashes, mi, now
            )
            if miss_rows is not None:
                accepted += self._classify_queue_chunk(
                    miss_rows,
                    slots[mi],
                    wire.sanitize_nt_array(dbuf.added[mi]),
                    wire.sanitize_nt_array(dbuf.taken[mi]),
                    np.maximum(dbuf.elapsed[mi].astype(np.int64), 0),
                    dbuf.caps[mi],
                    dbuf.lane_a[mi],
                    dbuf.lane_t[mi],
                    no_trailer[mi].astype(bool),
                )
        live = rows >= 0
        recheck = live & (out_s == 2)
        if recheck.any():
            # v1 deltas on rows whose capacity was 0 during the native
            # pass; the miss binds above may have adopted caps since.
            idx2 = np.flatnonzero(recheck)
            base = self.directory.cap_base_nt[rows[idx2]]
            known = base > 0
            ki = idx2[known]
            out_a[ki] = np.maximum(out_a[ki] - base[known], 0)
            out_s[ki] = 1
            drop = idx2[~known]
            if drop.size:
                self._scalar_dropped += int(drop.size)
                self.directory.unpin_rows(rows[drop])
                live[drop] = False
        idx = np.flatnonzero(live)
        for lo in range(0, len(idx), MAX_MERGE_ROWS):
            sl = idx[lo : lo + MAX_MERGE_ROWS]
            if HOST_FASTPATH:
                keep_h = self._host_absorb_ingest(
                    rows[sl], slots[sl], out_a[sl], out_t[sl], out_e[sl],
                    out_s[sl] == 1,
                )
                if keep_h is not None and not keep_h.all():
                    self.directory.unpin_rows(rows[sl][~keep_h])
                    accepted += int((~keep_h).sum())
                    sl = sl[keep_h]
                    if not sl.size:
                        continue
            chunk = _DeltaChunk(
                rows[sl], slots[sl], out_a[sl], out_t[sl], out_e[sl],
                out_s[sl] == 1,
            )
            with self._cond:
                self._deltas.append(chunk)
                self._cond.notify()
            accepted += chunk.n
        return accepted

    def read_rows(self, rows) -> tuple:
        """Donation-safe gather of per-bucket state: returns (pn[K,N,2],
        elapsed[K]) as host numpy arrays. The gather is padded to a
        power-of-two so arbitrary row counts don't each JIT a new variant."""
        rows = np.asarray(rows, dtype=np.int32)
        n = len(rows)
        k = _pad_size(n, lo=1, hi=1 << 20)
        padded = np.zeros(k, dtype=np.int32)
        padded[:n] = rows
        idx = jnp.asarray(padded)
        with self._state_mu:
            rs = read_rows(self.state, idx)
            # THE sanctioned gather seam: one batched D2H per call. The
            # scrape surfaces (snapshot/row_view/debug vars) answer from
            # the epoch-validated host mirror and only land here on a
            # mirror miss.
            return (
                np.asarray(rs.pn)[:n],  # patrol-lint: disable=PTD003
                np.asarray(rs.elapsed)[:n],  # patrol-lint: disable=PTD003
            )

    def _scrape_epoch(self) -> Tuple[int, int]:
        """The device-state version a mirror snapshot is stamped with.
        Plain int reads (GIL-atomic): a bump landing mid-read only makes
        the mirror LOOK stale — never lets stale data serve as fresh."""
        return (self._ticks, self._state_gen)

    def _refresh_scrape_mirror(self) -> None:
        """One batched window gather re-stamping the scrape mirror. The
        epoch is captured BEFORE the gather: a mutation racing the
        gather leaves the mirror stamped older than its data, which only
        costs an extra refresh — stamping after could mark pre-mutation
        data as current."""
        k = self._mirror_window
        if k <= 0:
            return
        epoch = self._scrape_epoch()
        pn, elapsed = self.read_rows(np.arange(k, dtype=np.int32))
        self._scrape_mirror = (epoch, pn, elapsed)
        profiling.COUNTERS.inc("scrape_mirror_refreshes")

    def _scrape_rows(self, rows) -> Tuple[np.ndarray, np.ndarray]:
        """(pn[len,N,2], elapsed[len]) for device rows on the STATS path
        (snapshot/tokens//debug/vars): answered from the host mirror
        whenever its epoch still matches — exact, zero device transfers
        — else one window gather re-arms it. Rows beyond the mirror
        window (or with the mirror disabled) fall back to a targeted
        gather. The serve path never calls this; ticket completion reads
        ride the completer's batched readback."""
        rows = np.asarray(rows, dtype=np.int32)
        if SCRAPE_MIRROR and rows.size and int(rows.max()) < self._mirror_window:
            mir = self._scrape_mirror
            if mir is None or mir[0] != self._scrape_epoch():
                # Stale: flag interest so the completion pipeline keeps
                # the mirror hot while load is flowing, and re-arm it
                # here so an IDLE engine converges to zero-gather
                # scrapes immediately.
                self._mirror_want = True
                self._refresh_scrape_mirror()
                mir = self._scrape_mirror
            if mir is not None:
                profiling.COUNTERS.inc("scrape_mirror_hits")
                epoch, pn, elapsed = mir
                return pn[rows], elapsed[rows]
        profiling.COUNTERS.inc("scrape_device_gathers")
        return self.read_rows(rows)

    def _hosted_view(self, row: int):
        """(pn[N,2] copy, elapsed_ns) if the row is host-resident, else
        None. Snapshot-consistent: copied under the host lock."""
        if not (HOST_FASTPATH and self._hosted_flag[row]):
            return None
        with self._host_mu:
            lanes = self._hosted.get(row)
            if lanes is None:
                return None
            return (
                np.stack([lanes.added, lanes.taken], axis=-1),
                lanes.elapsed_ns,
            )

    def row_view(self, row: int) -> Tuple[np.ndarray, int]:
        """One bucket row's full PN state, wherever it lives: host lanes
        for host-resident rows, a device gather otherwise."""
        hv = self._hosted_view(row)
        if hv is not None:
            return hv
        pn_rows, elapsed_rows = self._scrape_rows([row])
        return pn_rows[0], int(elapsed_rows[0])

    def snapshot(self, name: str) -> List[wire.WireState]:
        """Read one bucket's full PN state as per-slot wire states — the
        incast reply payload (repo.go:86-90): one packet per non-zero node
        lane, each tagged with its origin slot."""
        row = self.directory.lookup(name)
        if row is None:
            return []
        hv = self._hosted_view(row)
        if hv is not None:
            # Same re-lookup the device branch does: the row could have
            # been evicted and re-bound (and re-HOSTED by another name's
            # take) between the lookup and the view.
            if self.directory.lookup(name) != row:
                return []
            pn, elapsed = hv
        else:
            pn_rows, elapsed_rows = self._scrape_rows([row])
            if self.directory.lookup(name) != row:
                return []  # evicted mid-read
            pn = pn_rows[0]  # [N, 2]
            elapsed = int(elapsed_rows[0])
        cap = int(self.directory.cap_base_nt[row])
        sum_a = int(pn[:, 0].sum())
        sum_t = int(pn[:, 1].sum())
        out = []
        for slot in range(pn.shape[0]):
            a, t = int(pn[slot, 0]), int(pn[slot, 1])
            if a or t:
                # Dual payload (ops/wire.py): aggregate scalars in the
                # header (what reference peers max-merge, idempotent across
                # the per-lane packets), exact lane values in the trailer.
                out.append(
                    wire.from_nanotokens(
                        name, cap + sum_a, sum_t, elapsed,
                        origin_slot=slot, cap_nt=cap,
                        lane_added_nt=a, lane_taken_nt=t,
                    )
                )
        if not out and (elapsed or cap):
            out.append(
                wire.from_nanotokens(
                    name, cap, 0, elapsed, origin_slot=self.node_slot,
                    cap_nt=cap, lane_added_nt=0, lane_taken_nt=0,
                )
            )
        return out

    def release_bucket(self, name: str, timeout: float = 5.0) -> bool:
        """Evict one bucket by name: unbind, zero its device row, recycle.
        The bucket's state survives on peers and re-hydrates via incast on
        next use — the same soft-state story as a node restart (SURVEY §5).
        Unbind-before-zero ordering (the eviction protocol's limbo phase)
        keeps a concurrently re-created bucket from seeing stale state, and
        a pinned row (in-flight take/delta) is waited out, never yanked."""
        deadline = time.monotonic() + timeout
        with self._evict_mu:
            while True:
                row, bound = self.directory.unbind_if_unpinned(name)
                if row is not None:
                    break
                if not bound:
                    return False
                self.flush(timeout=max(0.0, deadline - time.monotonic()))
                if time.monotonic() >= deadline:
                    return False
            self._drop_hosted_rows([row])
            with self._state_mu:
                self.state = zero_rows_jit(
                    self.state, jnp.array([row], jnp.int32)
                )
                self._state_gen += 1
            self.directory.recycle([row])
        return True

    def snapshot_many(self, names: Sequence[str]) -> Dict[str, List[wire.WireState]]:
        """Batched :meth:`snapshot`: one device gather for many buckets
        (the incast-reply fan-in under cold-key storms); host-resident rows
        answer from their lanes without touching the device."""
        known = [(n, self.directory.lookup(n)) for n in names]
        known = [(n, r) for n, r in known if r is not None]
        if not known:
            return {}
        hosted_views = {
            r: hv for _, r in known if (hv := self._hosted_view(r)) is not None
        }
        device_rows = [r for _, r in known if r not in hosted_views]
        if device_rows:
            pn_dev, el_dev = self._scrape_rows(device_rows)
            dev_at = {r: i for i, r in enumerate(device_rows)}
        out: Dict[str, List[wire.WireState]] = {}
        for name, row in known:
            if self.directory.lookup(name) != row:
                continue  # evicted mid-read: don't leak another bucket's state
            hv = hosted_views.get(row)
            if hv is not None:
                pn, elapsed = hv
            else:
                pn = pn_dev[dev_at[row]]
                elapsed = int(el_dev[dev_at[row]])
            cap = int(self.directory.cap_base_nt[row])
            sum_a = int(pn[:, 0].sum())
            sum_t = int(pn[:, 1].sum())
            states = [
                wire.from_nanotokens(
                    name, cap + sum_a, sum_t, elapsed,
                    origin_slot=s, cap_nt=cap,
                    lane_added_nt=int(pn[s, 0]), lane_taken_nt=int(pn[s, 1]),
                )
                for s in range(pn.shape[0])
                if pn[s, 0] or pn[s, 1]
            ]
            if not states and (elapsed or cap):
                states = [
                    wire.from_nanotokens(
                        name, cap, 0, elapsed, origin_slot=self.node_slot,
                        cap_nt=cap, lane_added_nt=0, lane_taken_nt=0,
                    )
                ]
            if states:
                out[name] = states
        return out

    def tokens(self, name: str) -> int:
        """Whole tokens currently in a bucket (introspection; bucket.go:156)."""
        return self.tokens_if_known(name) or 0

    def tokens_if_known(self, name: str) -> Optional[int]:
        """Balance with existence: ``None`` for an unknown bucket, else the
        whole-token balance. The post-read re-lookup closes the eviction
        race (same pattern as :meth:`snapshot`): without it, a concurrent
        evict-and-rebind between lookup and the device gather could
        return another bucket's balance under this name."""
        row = self.directory.lookup(name)
        if row is None:
            return None
        hv = self._hosted_view(row)
        if hv is not None:
            if self.directory.lookup(name) != row:
                return None  # evicted and re-bound (possibly re-hosted)
            pn = hv[0]
        else:
            pn_rows, _ = self._scrape_rows([row])
            if self.directory.lookup(name) != row:
                return None  # evicted (and possibly rebound) mid-read
            pn = pn_rows[0]
        base = int(self.directory.cap_base_nt[row])
        nt = base + int(pn[:, 0].sum()) - int(pn[:, 1].sum())
        return max(nt, 0) // NANO

    def warmup(self) -> None:
        """Pre-compile every padded kernel variant (take and merge at each
        power-of-two batch size) so production traffic never pays a JIT
        compile: without this, the first request that widens the batch
        stalls its whole tick (seen as multi-100ms p99.9 spikes)."""
        size = 8
        while size <= MAX_TAKE_ROWS:
            with self._state_mu:
                self.state, _ = _jit_take_packed(self.node_slot)(
                    self.state, jnp.zeros((8, size), jnp.int64)
                )
            size <<= 1
        size = 8
        while size <= MAX_MERGE_ROWS:
            with self._state_mu:
                self.state = _jit_merge_packed()(
                    self.state, jnp.zeros((5, size), jnp.int64)
                )
            size <<= 1
        if jax.default_backend() != "cpu":
            # The accelerator tick path commits through the FOLDED kernel
            # (flags asserted) — warm its variants too, or the first real
            # tick compiles mid-serve.
            size = 8
            while size <= MAX_MERGE_ROWS:
                packed = np.zeros((6, size), np.int64)
                packed[0] = _FOLD_PAD_ROW
                packed[1] = np.arange(size)
                packed[4] = _FOLD_PAD_ROW + np.arange(size)
                with self._state_mu:
                    self.state = _jit_merge_packed_folded()(
                        self.state, jnp.asarray(packed)
                    )
                size <<= 1
            # Fold-to-dense row-window commits ride the same accel-only
            # fold path — CPU ticks never reach either kernel.
            size = 8
            while size <= MAX_ROW_DENSE:
                with self._state_mu:
                    self.state = _jit_merge_rows_dense()(
                        self.state,
                        jnp.full((size,), _FOLD_PAD_ROW, jnp.int64)
                        + jnp.arange(size, dtype=jnp.int64),
                        jnp.zeros((size, self.config.nodes, 2), jnp.int64),
                        jnp.zeros((size,), jnp.int64),
                    )
                size <<= 1
            # Coalesced commit ring (device-commit pipeline): one variant
            # per power-of-two block count the drain can coalesce, so the
            # first multi-block burst doesn't compile mid-serve.
            j = 2
            while j <= self._commit_blocks:
                warm = commit_mod.pack_commit_blocks(
                    np.empty(0, np.int64), np.empty(0, np.int64),
                    np.empty(0, np.int64), np.empty(0, np.int64),
                    np.empty(0, np.int64), np.empty(0, np.int64),
                    MAX_MERGE_ROWS,
                    out=np.empty((6, j, MAX_MERGE_ROWS), np.int64),
                )
                with self._state_mu:
                    self.state = _jit_commit_packed()(
                        self.state, jnp.asarray(warm)
                    )
                j <<= 1
        size = 1
        while size <= 1024:  # snapshot/introspection gathers
            self.read_rows(np.zeros(size, np.int32))
            size <<= 1
        # Lifecycle sweep probe diagonal: the GC cadence must never JIT a
        # fresh variant mid-serve while holding _state_mu (cap 0 padding
        # means the all-zero warm probe can never report full).
        size = 8
        hi = _pad_size(self._gc_sweep_max, lo=8, hi=1 << 20)
        while size <= hi:
            with self._state_mu:
                lifecycle_ops.lifecycle_probe_jit(
                    self.state,
                    lifecycle_ops.LifecycleProbe(
                        rows=jnp.zeros(size, jnp.int32),
                        now_ns=jnp.zeros(size, jnp.int64),
                        per_ns=jnp.zeros(size, jnp.int64),
                        cap_base_nt=jnp.zeros(size, jnp.int64),
                        created_ns=jnp.zeros(size, jnp.int64),
                    ),
                    self.node_slot,
                )
            size <<= 1
        jax.block_until_ready(self.state.pn)

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until all currently queued work has been applied to device
        state AND every completion has fanned out. Test/introspection
        helper, not a hot-path call."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._cond:
                idle = (
                    not self._takes
                    and not self._deltas
                    and not self._promote_pending
                    and not self._busy
                )
            if idle:
                with self._pcond:
                    if not self._pending and not self._completing:
                        return True
            time.sleep(0.0005)
        return False

    def stop(self) -> None:
        from patrol_tpu.utils import slo as slo_mod

        slo_mod.SENTINEL.unwatch_budget(self._budget_snapshot)
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        with self._pcond:
            # Wake a feeder parked in _enqueue_completion back-pressure NOW
            # (not after its 5s join) so the graceful drain can finish.
            self._pcond.notify_all()
        # _feeder_done is set by the feeder's own exit path (_run), never
        # here: a timed-out join must not let the completer quit while the
        # drain is still producing ticks (stranded tickets, leaked pins).
        self._thread.join(timeout=5)
        self._completer.join(timeout=5)
        if self._native_store is not None:
            # The HTTP front must already be detached (command.py closes
            # the front before engine.stop). Frees every lane block — so
            # drop every proxy first; host-lane views are invalid from
            # here and post-stop introspection sees device planes only.
            with self._host_mu:
                self._hosted.clear()
                self._promoting.clear()
                self._hosted_flag[:] = False
            store, self._native_store = self._native_store, None
            if getattr(self, "_leak_native_store", False):
                # A wedged front pump may still be inside the store
                # (native_http.close's leaked-server path): leak the
                # blocks rather than free them under a live thread.
                log.error("leaking native host store (wedged http pump)")
            else:
                store.destroy()
        self.directory.close()  # releases the native resolve table

    # -- completion pipeline ------------------------------------------------

    def _enqueue_completion(self, thunk, keys, groups) -> None:
        """Hand a tick's completion to the completer thread. Only the
        grouped (non-deferred) tickets belong to the tick — deferred ones
        are already re-queued and must never be failed here — so the
        flatten lives in this one place. Bounded: a slow completer
        back-pressures dispatch rather than buffering device results
        without limit."""
        tickets = [t for key in keys for t in groups[key]]
        with self._pcond:
            while len(self._pending) >= self._dispatch_ahead and not self._stopped:
                self._pcond.wait()
            self._pending.append((thunk, tickets))
            depth = len(self._pending) + (1 if self._completing else 0)
            self._pcond.notify_all()
        profiling.COUNTERS.set_max("dispatch_ahead_depth", depth)

    def _complete_loop(self) -> None:
        while True:
            with self._pcond:
                # Exit only when the FEEDER is done dispatching AND every
                # pending completion ran: the feeder's graceful drain keeps
                # producing ticks after _stopped is set, and abandoning one
                # would hang its callers with their row pins leaked.
                while not self._pending and not self._feeder_done:
                    self._pcond.wait()
                if not self._pending:
                    return  # feeder exited and the queue is drained
                thunk, tickets = self._pending.popleft()
                self._completing = True
                self._pcond.notify_all()  # wake a back-pressured feeder
            try:
                t0 = time.perf_counter_ns()
                thunk()
                _obs_stage(
                    hist.STAGE_COMPLETION, t0, trace_mod.EV_COMMIT_COMPLETE,
                    len(tickets),
                )
            except Exception:  # pragma: no cover - completer must not die
                log.exception("tick completion failed")
                try:
                    self._fail_tickets(tickets)
                except Exception:
                    log.exception("ticket failure fan-out failed")
            finally:
                with self._pcond:
                    self._completing = False
                    self._pcond.notify_all()
            if SCRAPE_MIRROR and self._mirror_want:
                # Scrapes are active and went stale under load: re-arm
                # the mirror HERE, off the scrape threads, so the next
                # stats read costs zero transfers. One window gather per
                # completion batch, and only while scrape interest is
                # flagged.
                try:
                    self._refresh_scrape_mirror()
                    self._mirror_want = False
                except Exception:  # pragma: no cover - gauge refresh
                    log.exception("scrape-mirror refresh failed")

    @property
    def ticks(self) -> int:
        return self._ticks

    @property
    def evictions(self) -> int:
        return self._evictions

    @property
    def scalar_dropped(self) -> int:
        """v1 (reference-peer) deltas dropped while the row's capacity was
        unknown — re-delivered by the peer's next full-state broadcast."""
        return self._scalar_dropped

    @property
    def hosted_buckets(self) -> int:
        """Buckets currently served by the host fast path."""
        return len(self._hosted)

    @property
    def host_takes(self) -> int:
        """Takes answered in-process by the host fast path (µs-class):
        Python-served plus C++-in-front-served."""
        n = self._host_takes
        if self._native_store is not None:
            n += self._native_store.native_takes
        return n

    @property
    def promotions(self) -> int:
        """Host→device residency transitions (QPS threshold or rx traffic)."""
        return self._promotions

    @property
    def demotions(self) -> int:
        """Device→host residency transitions (idle window under crossover)."""
        return self._demotions

    @property
    def audit_ledger(self) -> AuditLedger:
        """patrol-audit admitted-token window ledger (net/audit.py reads
        it on the audit plane's pace)."""
        return self._audit

    def audit_staleness_samples(self, limit: int = 64) -> List[int]:
        """Per-bucket staleness sample for the audit plane: ns the last
        local emission ran ahead of the last remote absorb, over up to
        ``limit`` buckets that have seen both."""
        return [int(v) for v in self.directory.staleness_sample(limit)]

    @property
    def pending_completions(self) -> int:
        """Dispatched ticks whose results haven't fanned out yet — the
        completion pipeline's depth (backpressure signal)."""
        with self._pcond:
            return len(self._pending) + (1 if self._completing else 0)

    def backlog(self) -> int:
        """Queued-but-unapplied work rows (takes + deltas, counting each
        delta inside a bulk chunk): the public backpressure signal for bulk
        feeders (bench replay, heal ingest)."""
        with self._cond:
            return sum(
                len(t.tickets) if isinstance(t, _TakeFold) else 1
                for t in self._takes
            ) + sum(
                d.n if isinstance(d, _DeltaChunk) else 1 for d in self._deltas
            )

    # -- engine loop --------------------------------------------------------

    def _run(self) -> None:
        try:
            self._run_loop()
        finally:
            with self._pcond:
                # The feeder itself declares dispatch over — stop() cannot,
                # because its 5s join may time out while the drain is still
                # producing ticks, and a flag set too early (or never) either
                # strands enqueued completions or parks the completer forever.
                self._feeder_done = True
                self._pcond.notify_all()

    def _run_loop(self) -> None:
        while True:
            with self._cond:
                # Single predicate — pause and work-availability re-check
                # together on every wake, so a pause raised while this
                # thread waits for work can never be skipped (two
                # sequential loops would have that race).
                while (self._tick_paused and not self._stopped) or not (
                    self._takes
                    or self._deltas
                    or self._promote_pending
                    or self._gc_due
                    or self._stopped
                ):
                    self._cond.wait()
                if self._stopped and not (self._takes or self._deltas):
                    return
                self._gc_due = False  # this tick runs _maybe_gc below
                # Drain up to _commit_blocks blocks per tick: everything
                # past one block's budget coalesces into a single commit
                # dispatch (_commit_coalesced) instead of riding extra
                # ticks — one transfer + one dispatch either way. In
                # auto mode the block count tracks the backlog, capped
                # by the measured per-row device-commit cost.
                if self._commit_blocks_auto:
                    self._auto_size_commit_blocks_locked()
                deltas = self._drain_deltas(
                    MAX_MERGE_ROWS * self._commit_blocks
                )
                tickets = self._drain_takes(MAX_TAKE_ROWS)
                # Clear the re-queue marker at drain time, not in
                # _group_tickets: if the tick dies before grouping runs, a
                # stale True from a prior tick would make _fail_tickets skip
                # the ticket and hang its caller while leaking the row pin.
                for t in tickets:
                    t.deferred = False
                self._busy = True
            # Idle demotion: count device-path takes on promoted rows and,
            # at window rollover, move quiet promoted rows back to host
            # residency BEFORE the re-route — so the very take that ends an
            # idle window is already host-served (sub-ms again, VERDICT r4
            # item 3's config #1-after-a-burst scenario).
            if HOST_FASTPATH and self._demotion_capable and self._promoted_rows:
                for t in tickets:
                    if t.row in self._promoted_rows:
                        self._dev_window[t.row] = (
                            self._dev_window.get(t.row, 0) + 1
                        )
                self._maybe_demote(tickets, deltas)
            # Bucket lifecycle: sweep full idle buckets at the GC window
            # cadence (pressure ramps it 8x). In-hand work is safe by
            # construction — this tick's deltas and tickets hold pins.
            self._maybe_gc()
            # Residency re-route: a ticket that raced into the device queue
            # while its row was (or became) host-resident is served from
            # the host model here — the one point every queued take passes
            # through, so a row is never served by both paths at once.
            if HOST_FASTPATH and self._hosted and tickets:
                bc: List[wire.WireState] = []
                tickets = [
                    t
                    for t in tickets
                    if not (
                        self._hosted_flag[t.row]
                        and self._host_serve_ticket(t, False, bc)
                    )
                ]
                self._emit_broadcasts(bc)
            t_tick0 = time.perf_counter_ns()
            try:
                # Pending promotions join BEFORE the tick's device work,
                # so a take routed device-ward this tick (its row's flag
                # flipped in the drain above or earlier) always runs
                # against the already-joined planes.
                if HOST_FASTPATH and self._promote_pending:
                    self._drain_promotions()
                # The re-route may have served everything: don't dispatch
                # an all-padding device step (a wasted full round trip —
                # and on MeshEngine a whole fused no-op step).
                if deltas is not None or tickets:
                    self._apply(deltas, tickets)
                    tick_dur = time.perf_counter_ns() - t_tick0
                    tr = trace_mod.TRACE
                    if tr.enabled:
                        tr.record(
                            trace_mod.EV_TICK, tick_dur,
                            (len(deltas) if deltas is not None else 0)
                            + len(tickets),
                        )
                    for tid, tname in self._tick_traced:
                        # Remote deltas merged this tick: their merge
                        # spans close here, joined by the propagated id.
                        trace_mod.SPANS.add(
                            tid, self.node_slot, "merge", tname,
                            t_tick0, tick_dur,
                        )
            except Exception:  # pragma: no cover - engine must never die
                log.exception("engine tick failed")
                trace_mod.anomaly("engine-tick-failed")
                self._fail_tickets(tickets)
            finally:
                self._tick_traced = []
                if deltas is not None:
                    # Deltas are done (applied or lost with the tick): their
                    # in-flight row pins release here, success or not.
                    self.directory.unpin_rows(deltas.rows)
                with self._cond:
                    self._busy = False

    @staticmethod
    def _drain(q: deque, limit: int) -> list:
        out = []
        while q and len(out) < limit:
            out.append(q.popleft())
        return out

    def _drain_takes(self, limit: int) -> List[TakeTicket]:
        """Pop up to ``limit`` take-queue ENTRIES (caller holds
        ``_cond``) and return the FLAT ticket list in arrival order. A
        folded hot-key entry counts ONCE against the limit — it becomes
        one packed row — so a coalesced tick can serve far more tickets
        than the row budget; popping an entry closes its fold, and later
        arrivals for the key open a fresh one."""
        out: List[TakeTicket] = []
        q = self._takes
        n = 0
        while q and n < limit:
            item = q.popleft()
            n += 1
            if isinstance(item, _TakeFold):
                if self._open_folds.get(item.key) is item:
                    del self._open_folds[item.key]
                out.extend(item.tickets)
            else:
                out.append(item)
        return out

    def _auto_size_commit_blocks_locked(self) -> None:
        """Adaptive commit-block sizing (PATROL_COMMIT_BLOCKS=auto;
        caller holds ``_cond``). The drain width tracks the queue
        backlog — light load drains one block per tick (lowest latency),
        a flood coalesces toward COMMIT_BLOCKS_MAX — and the completion
        pipeline's measured per-row device-commit cost caps the width so
        one dispatch's completion never exceeds PATROL_COMMIT_BUDGET_MS.
        Tascade's lesson (arXiv:2311.15810) with a governor: coalescing
        beats per-update commits, but only up to the latency budget."""
        backlog = sum(
            d.n if isinstance(d, _DeltaChunk) else 1 for d in self._deltas
        )
        want = max(1, -(-backlog // MAX_MERGE_ROWS)) if backlog else 1
        want = min(want, COMMIT_BLOCKS_MAX)
        ewma = self._commit_row_ns_ewma
        if ewma > 0.0:
            budget_blocks = max(
                1, int(COMMIT_BUDGET_NS / (ewma * MAX_MERGE_ROWS))
            )
            want = min(want, budget_blocks)
        if want != self._commit_blocks:
            self._commit_blocks = want
            profiling.COUNTERS.inc("commit_blocks_auto_resized")

    def _drain_deltas(self, limit: int) -> Optional[DeltaArrays]:
        """Pop queued deltas (singles and pre-vectorized chunks) up to a row
        budget, concatenated into flat arrays in arrival order. Called under
        ``_cond``. A chunk is never split; one oversized-first chunk may
        exceed the budget alone."""
        q = self._deltas
        items: list = []
        total = 0
        while q:
            n = q[0].n if isinstance(q[0], _DeltaChunk) else 1
            if total and total + n > limit:
                break
            items.append(q.popleft())
            total += n
        if not items:
            return None
        rows = np.empty(total, np.int64)
        slots = np.empty(total, np.int64)
        added = np.empty(total, np.int64)
        taken = np.empty(total, np.int64)
        elapsed = np.empty(total, np.int64)
        scalar = np.zeros(total, bool)
        traced = self._tick_traced = []
        at = 0
        for it in items:
            if isinstance(it, _DeltaChunk):
                rows[at : at + it.n] = it.rows
                slots[at : at + it.n] = it.slots
                added[at : at + it.n] = it.added_nt
                taken[at : at + it.n] = it.taken_nt
                elapsed[at : at + it.n] = it.elapsed_ns
                scalar[at : at + it.n] = it.scalar
                at += it.n
            else:
                rows[at] = it.row
                slots[at] = it.slot
                added[at] = it.added_nt
                taken[at] = it.taken_nt
                elapsed[at] = it.elapsed_ns
                scalar[at] = it.scalar
                if it.trace_id:
                    traced.append((it.trace_id, it.trace_name))
                at += 1
        return DeltaArrays(rows, slots, added, taken, elapsed, scalar)

    def _fail_tickets(self, tickets: Sequence[TakeTicket]) -> None:
        unpin = [
            t.row for t in tickets if not t.deferred and t.complete(0, False)
        ]
        if unpin:
            self.directory.unpin_rows(unpin)

    def _apply(self, deltas: Optional[DeltaArrays], tickets: Sequence[TakeTicket]) -> None:
        """One tick's work. Subclasses may fuse both phases into a single
        device call (MeshEngine)."""
        if deltas is not None:
            self._apply_merges(deltas)
        if tickets:
            self._apply_takes(tickets)

    def _group_tickets(self, tickets: Sequence[TakeTicket]):
        """Coalesce by (row, rate, count) preserving arrival order; defer
        rows seen with a second key to the next tick (kernel invariant:
        unique rows per batch). → (keys, groups).

        Starvation bound (rate-diversity adversary): deferred tickets are
        re-queued at the FRONT in arrival order, so a ticket can only wait
        behind same-row tickets that arrived BEFORE it — one tick per
        distinct earlier key, and never behind later arrivals. A client
        hammering one bucket with N distinct rates therefore delays only
        that bucket, by exactly its own queue depth (the same cost any
        FIFO service gives N requests), and cannot push an
        already-queued victim back (pinned by
        tests/test_engine.py::TestRateDiversity)."""
        # PATROL_TAKE_FOLD=0 — the per-ticket replay reference: every
        # ticket rides its own nreq=1 row, so a row's second ticket
        # defers to the next tick (the kernel invariant of unique rows
        # per batch stands either way). This is the pre-coalescing
        # serving discipline the bench's hot-key leg replays against.
        per_ticket = not _take_fold_enabled()
        groups: Dict[tuple, List[TakeTicket]] = {}
        row_key: Dict[int, tuple] = {}
        deferred: List[TakeTicket] = []
        for t in tickets:
            key = (t.row, t.rate.freq, t.rate.per_ns, t.count)
            held = row_key.get(t.row)
            if held is None:
                row_key[t.row] = key
                groups[key] = [t]
            elif held == key and not per_ticket:
                groups[key].append(t)
            else:
                deferred.append(t)
        if deferred:
            for t in deferred:
                t.deferred = True
            with self._cond:
                self._takes.extendleft(reversed(deferred))
                self._cond.notify()
        return list(groups.keys()), groups

    def _complete_groups(
        self, keys, groups, have, admitted, own_a, own_t, elapsed, sum_a, sum_t
    ) -> None:
        """Fan per-group kernel results out to tickets + broadcast hook.
        Completion releases each ticket's directory pin."""
        broadcasts: List[wire.WireState] = []
        unpin: List[int] = []
        done_ns = time.perf_counter_ns()
        now_clock = self.clock()
        take_hist = hist.TAKE_SERVICE
        for i, key in enumerate(keys):
            ts = groups[key]
            c_nt = ts[0].count * NANO
            admitted_nt = 0
            adm = int(admitted[i])
            if 0 < adm < len(ts):
                # A coalesced row whose grant covered only a prefix:
                # the earliest tickets are admitted, the rest get clean
                # denies (split_grant's FIFO discipline).
                profiling.COUNTERS.inc("take_partial_grants")
            outcomes = split_grant(int(have[i]), adm, c_nt, len(ts))
            for t, (remaining, ok) in zip(ts, outcomes):
                if ok:
                    admitted_nt += c_nt
                if t.complete(remaining, ok):
                    unpin.append(t.row)
                    take_hist.record(done_ns - t.t0_ns)
                    if t.trace_id:
                        trace_mod.SPANS.add(
                            t.trace_id, self.node_slot, "take", t.name,
                            t.t0_ns, done_ns - t.t0_ns,
                        )
            # Replicate. The reference broadcasts full state on every take,
            # success or not (api.go:74, README.md:41-43) — even a failed
            # first take commits the lazy capacity init (bucket.go:194-196),
            # which we mirror. Dual payload (ops/wire.py): the float header
            # carries the aggregate scalar view (cap + Σadded, Σtaken) that
            # reference peers max-merge; the trailer carries this node's
            # exact PN lane for patrol_tpu peers. We skip only when state is
            # still all-zero — a zero state on the wire is the incast
            # *request* marker (repo.go:78-90).
            cap = int(self.directory.cap_base_nt[ts[0].row])
            if admitted_nt:
                # patrol-audit: the device path's admitted-token booking
                # (the host fast path books in _host_serve_ticket).
                self._audit.note(
                    ts[0].name, admitted_nt, cap, ts[0].rate.per_ns, now_clock
                )
            if own_a[i] or own_t[i] or elapsed[i] or cap:
                broadcasts.append(
                    wire.from_nanotokens(
                        ts[0].name,
                        cap + int(sum_a[i]),
                        int(sum_t[i]),
                        int(elapsed[i]),
                        origin_slot=self.node_slot,
                        cap_nt=cap,
                        lane_added_nt=int(own_a[i]),
                        lane_taken_nt=int(own_t[i]),
                        # A sampled take in the group propagates its trace
                        # id on the state broadcast (the group shares one
                        # packet, so one id rides it).
                        trace_id=next(
                            (t.trace_id for t in ts if t.trace_id), None
                        ),
                    )
                )
        if unpin:
            self.directory.unpin_rows(unpin)
        self._emit_broadcasts(broadcasts)

    def _apply_merges(self, deltas: DeltaArrays) -> None:
        # Scalar-semantics (reference-peer) deltas go through the
        # deficit-attribution kernel; the common case is none of them.
        # Lane merges apply FIRST: a scalar echo's aggregate already
        # includes peer lanes broadcast before it, so attributing the
        # deficit before those lane deltas land would double-count their
        # grants into the sender's lane — permanently (lanes are monotone).
        # Deficit attribution is monotone-decreasing in other-lane values,
        # so lane-first is always the conservative order.
        scalar_subset = None
        if deltas.scalar.any():
            sc = deltas.scalar
            scalar_subset = DeltaArrays(*(a[sc] for a in deltas))
            if sc.all():
                self._apply_scalar_merges(scalar_subset)
                return
            deltas = DeltaArrays(*(a[~sc] for a in deltas))
        self._apply_lane_merges(deltas)
        if scalar_subset is not None:
            self._apply_scalar_merges(scalar_subset)

    def _apply_lane_merges(self, deltas: DeltaArrays) -> None:
        if not len(deltas):  # a zero-length chunk is a no-op tick
            return
        # Device-commit pipeline: a drain wider than one block's budget
        # (the feeder pulls up to _commit_blocks blocks per tick) folds
        # across ALL its blocks and commits in ONE donated dispatch —
        # every per-block kernel below is shape-capped at MAX_MERGE_ROWS.
        if len(deltas) > MAX_MERGE_ROWS:
            self._commit_coalesced(deltas)
            return
        # Merge-kernel selection: "scatter" (XLA, default), "pallas" (the
        # block-sparse TPU kernel whenever it can run natively), or "auto"
        # (per-batch heuristic: pallas iff the batch is block-sparse,
        # ops/pallas_merge.py auto_pick).
        mode = os.environ.get("PATROL_MERGE_KERNEL", "scatter")
        if mode in ("pallas", "auto"):
            from patrol_tpu.ops import pallas_merge

            use_pallas = (
                pallas_merge.native_available()
                if mode == "pallas"
                else pallas_merge.auto_pick(deltas.rows, self.config.buckets)
            )
            if use_pallas:
                t0 = time.perf_counter_ns()
                with self._state_mu, _annotate("merge_pallas"):
                    self.state = pallas_merge.merge_batch_pallas(
                        self.state,
                        deltas.rows,
                        deltas.slots,
                        deltas.added_nt,
                        deltas.taken_nt,
                        deltas.elapsed_ns,
                    )
                self._observe_device_commit("merge_pallas", t0, len(deltas))
                self._ticks += 1
                return
        # Tick-level fold default: ON for accelerator backends, where the
        # scatter serializes per update and asserted-unique/sorted indices
        # measured +28% (scripts/probe_scatter.py); OFF for CPU, where the
        # scatter is already cheap and the fold's host work + extra jit
        # variants measured as a straight loss on the 1-vCPU cluster bench
        # (2,999 rps / p99 60 ms unfolded vs 2,675 rps / p99 337 ms
        # folded, benchmarks/cluster_bench.py, r3). Scope: this is the
        # single-device engine's merge tick only — MeshEngine overrides
        # _apply with a fused shard_map step whose per-block routing
        # (topology.route_requests) does not fold, so PATROL_TICK_FOLD has
        # no effect there.
        fold_default = "0" if jax.default_backend() == "cpu" else "1"
        if os.environ.get("PATROL_TICK_FOLD", fold_default) != "0":
            t0 = time.perf_counter_ns()
            packed, dense = self._fold_hybrid(deltas)
            _obs_stage(hist.STAGE_FOLD, t0, trace_mod.EV_FOLD, len(deltas))
            # Stage the operands on device BEFORE the state lock: the
            # H2D transfer then overlaps the previous tick's compute
            # instead of serializing inside the jit call (device-commit
            # pipeline; the fold buffers are freshly allocated per tick,
            # so jax owns them until the async transfer completes).
            t0 = time.perf_counter_ns()
            dense_dev = (
                tuple(jax.device_put(x) for x in dense)
                if dense is not None
                else None
            )
            packed_dev = (
                jax.device_put(packed) if packed is not None else None
            )
            _obs_stage(hist.STAGE_H2D, t0, trace_mod.EV_H2D_PUT, len(deltas))
            t0 = time.perf_counter_ns()
            with self._state_mu, _annotate("merge_folded"):
                if dense_dev is not None:
                    self.state = _jit_merge_rows_dense()(
                        self.state, *dense_dev
                    )
                if packed_dev is not None:
                    self.state = _jit_merge_packed_folded()(
                        self.state, packed_dev
                    )
            _obs_stage(
                hist.STAGE_DISPATCH, t0, trace_mod.EV_COMMIT_DISPATCH,
                len(deltas),
            )
            self._observe_device_commit("merge_folded", t0, len(deltas))
            self._ticks += 1
            return
        n = len(deltas)
        k = _pad_size(n)
        packed = np.zeros((5, k), dtype=np.int64)
        packed[0, :n] = deltas.rows
        packed[1, :n] = deltas.slots
        packed[2, :n] = deltas.added_nt
        packed[3, :n] = deltas.taken_nt
        packed[4, :n] = deltas.elapsed_ns
        t0 = time.perf_counter_ns()
        packed_dev = jax.device_put(packed)  # staged ahead of the lock
        _obs_stage(hist.STAGE_H2D, t0, trace_mod.EV_H2D_PUT, n)
        t0 = time.perf_counter_ns()
        with self._state_mu, _annotate("merge_packed"):
            self.state = _jit_merge_packed()(self.state, packed_dev)
        _obs_stage(hist.STAGE_DISPATCH, t0, trace_mod.EV_COMMIT_DISPATCH, n)
        self._observe_device_commit("merge_packed", t0, n)
        self._ticks += 1

    def _commit_coalesced(self, deltas: DeltaArrays) -> None:
        """Device-commit pipeline: fold a multi-block drain ONCE across
        all its blocks and commit it as a single donated fixed-shape
        dispatch (ops/commit.py) instead of one dispatch per block —
        exact because the join is commutative/idempotent (patrol-prove
        PTP002/PTP003 on the registered commit root), so cross-block
        fold order cannot matter. The packed matrix fills a reusable
        staging buffer and ships via ``jax.device_put`` before the state
        lock (transfer overlaps the previous tick's compute); the buffer
        returns to the pool on the completer thread once the transfer is
        ready, which also keeps pipeline depth bounded."""
        blocks_in = -(-len(deltas) // MAX_MERGE_ROWS)  # ceil
        t0 = time.perf_counter_ns()
        ur, us, ua, ut, er, e = self._fold_core(deltas)
        _obs_stage(hist.STAGE_FOLD, t0, trace_mod.EV_FOLD, len(deltas))
        if len(ur) <= MAX_MERGE_ROWS:
            # The fold collapsed the drain into one block (hot keys /
            # cross-block duplicates): the single-block folded kernel is
            # the cheaper dispatch, and the coalescing already happened
            # on host.
            packed = self._pack_folded(ur, us, ua, ut, er, e)
            t0 = time.perf_counter_ns()
            packed_dev = jax.device_put(packed)
            _obs_stage(hist.STAGE_H2D, t0, trace_mod.EV_H2D_PUT, len(ur))
            t0 = time.perf_counter_ns()
            with self._state_mu, _annotate("merge_folded"):
                self.state = _jit_merge_packed_folded()(
                    self.state, packed_dev
                )
            _obs_stage(
                hist.STAGE_DISPATCH, t0, trace_mod.EV_COMMIT_DISPATCH,
                len(ur),
            )
            self._observe_device_commit("merge_folded", t0, len(ur))
        else:
            shape = commit_mod.commit_shape(len(ur), MAX_MERGE_ROWS)
            buf = self._staging.lease(shape)
            commit_mod.pack_commit_blocks(
                ur, us, ua, ut, er, e, MAX_MERGE_ROWS, out=buf
            )
            t0 = time.perf_counter_ns()
            dev = jax.device_put(buf)
            _obs_stage(hist.STAGE_H2D, t0, trace_mod.EV_H2D_PUT, len(ur))
            t0 = time.perf_counter_ns()
            with self._state_mu, _annotate("commit_blocks"):
                self.state = _jit_commit_packed()(self.state, dev)
            _obs_stage(
                hist.STAGE_DISPATCH, t0, trace_mod.EV_COMMIT_DISPATCH,
                len(ur),
            )
            self._observe_device_commit("commit_blocks", t0, len(ur))
            self._release_when_shipped(dev, buf)
        self._ticks += 1
        profiling.COUNTERS.inc("commit_blocks_coalesced", blocks_in)
        profiling.COUNTERS.inc("commit_dispatches")

    def _device_marker(self):
        """A tiny device value depending on the just-dispatched state —
        ``block_until_ready`` on it observes the kernel's completion
        without touching the (donation-chained) state buffers themselves:
        the marker is a fresh output, so later ticks donating the state
        away can never invalidate it."""
        try:
            return self.state.elapsed[:1]
        except Exception:  # pragma: no cover - observability only
            return None

    def _observe_device_commit(
        self, kernel: str, t_dispatch_ns: int, n: int, marker=None
    ) -> None:
        """patrol-fleet device-dispatch timing: ride the completion
        pipeline to record this commit dispatch's device-side
        dispatch→ready duration into the ``device_commit_ns`` stage
        histogram and the per-kernel histogram. The wait runs on the
        completer thread (which blocks on device results anyway);
        dispatch-ahead keeps the feeder unblocked.

        ``marker`` lets a caller supply a fresh output of the observed
        program itself. MeshEngine must: the default ``_device_marker``
        slice is a NEW program over the sharded state, and launching it
        outside the state mutex races whatever collective another thread
        dispatches under it (host-platform device pools interleave the
        two rendezvous and deadlock)."""
        if not DEVICE_TIMING:
            return
        if marker is None:
            marker = self._device_marker()
        if marker is None:
            return
        kh = hist.kernel_histogram(kernel)

        def done() -> None:
            # Device-commit latency gauge: awaiting the marker IS the
            # measurement. Runs on the completer, never the feeder.
            jax.block_until_ready(marker)  # patrol-lint: disable=PTD003
            dur = time.perf_counter_ns() - t_dispatch_ns
            hist.STAGE_DEVICE_COMMIT.record(dur)
            kh.record(dur)
            # Adaptive commit sizing input: per-row device-commit cost
            # EWMA (completer writes, feeder reads — a racy float gauge
            # by design; a stale read only mis-sizes one tick's drain).
            per_row = dur / max(n, 1)
            prev = self._commit_row_ns_ewma
            self._commit_row_ns_ewma = (
                per_row if prev == 0.0 else 0.8 * prev + 0.2 * per_row
            )
            tr = trace_mod.TRACE
            if tr.enabled:
                tr.record(trace_mod.EV_DEVICE_READY, dur, n)

        self._enqueue_completion(done, (), {})

    def _release_when_shipped(self, dev, buf: np.ndarray) -> None:
        """Queue a transfer completion: return the staging buffer to the
        pool once the shipped operand is READY on device (device_put
        copies — it never aliases the host source — so readiness means
        the host bytes are free to refill). Rides the completion
        pipeline, so the feeder keeps dispatching ahead while the
        completer waits out the transfer."""

        def done() -> None:
            # Staging-recycle gate on the completion pipeline (see
            # docstring): the wait rides the completer by construction.
            jax.block_until_ready(dev)  # patrol-lint: disable=PTD003
            self._staging.release(buf)

        self._enqueue_completion(done, (), {})

    @staticmethod
    def _fold_lane_merges(deltas: DeltaArrays) -> np.ndarray:
        """Tick-level CRDT fold: sort by (row, slot), max-join duplicate
        keys, and fold the elapsed updates per ROW — the preparation that
        lets the device scatter assert unique+sorted indices (measured
        +28% on v5e, where scatter serializes per update; and a hot-key
        tick shrinks to its unique-key count before padding). Folding is
        exactly the join the kernel computes, so order never matters.

        Padding appends out-of-bounds SENTINEL keys (row ``_FOLD_PAD_ROW``
        far above any bucket row, distinct slot/row per entry) that the
        scatter's ``mode="drop"`` discards — every index the kernel sees
        is genuinely unique and sorted, so the asserted scatter flags are
        literally true rather than resting on duplicate-index behavior.
        A zero-length tick folds to an all-sentinel (no-op) matrix —
        reachable only by direct callers (tests): the engine's tick loop
        early-returns on empty chunks before folding.
        Returns the packed int64[6, k] tick matrix:
        rows, slots, added, taken, erows, elapsed."""
        if not len(deltas):
            k = _pad_size(0)
            packed = np.zeros((6, k), dtype=np.int64)
            packed[0] = _FOLD_PAD_ROW
            packed[1] = np.arange(k)
            packed[4] = _FOLD_PAD_ROW + np.arange(k)
            return packed
        return DeviceEngine._pack_folded(*DeviceEngine._fold_core(deltas))

    @staticmethod
    def _fold_core(deltas: DeltaArrays):
        """The fold computation: → (unique-pair rows, slots, added, taken,
        per-unique-row rows, elapsed), all sorted, duplicates max-joined."""
        order = np.lexsort((deltas.slots, deltas.rows))
        r = deltas.rows[order]
        s = deltas.slots[order]
        new_key = np.empty(len(r), bool)
        new_key[0] = True
        np.logical_or(r[1:] != r[:-1], s[1:] != s[:-1], out=new_key[1:])
        starts = np.flatnonzero(new_key)
        a = np.maximum.reduceat(deltas.added_nt[order], starts)
        t = np.maximum.reduceat(deltas.taken_nt[order], starts)
        el_sorted = deltas.elapsed_ns[order]
        new_row = np.empty(len(r), bool)
        new_row[0] = True
        np.not_equal(r[1:], r[:-1], out=new_row[1:])
        row_starts = np.flatnonzero(new_row)
        er = r[row_starts]
        e = np.maximum.reduceat(el_sorted, row_starts)
        return r[starts], s[starts], a, t, er, e

    @staticmethod
    def _pack_folded(ur, us, ua, ut, er, e) -> Optional[np.ndarray]:
        """Sentinel-padded int64[6, k] tick matrix from folded arrays
        (None when empty). Sentinel tail: rows above every live row keep
        the keys sorted; distinct slots keep them unique; mode="drop"
        discards them."""
        n = len(ur)
        if n == 0:
            return None
        ne = len(er)
        k = _pad_size(n)
        packed = np.empty((6, k), dtype=np.int64)
        packed[0, :n] = ur
        packed[1, :n] = us
        packed[2, :n] = ua
        packed[3, :n] = ut
        packed[0, n:] = _FOLD_PAD_ROW
        packed[1, n:] = np.arange(k - n)
        packed[2, n:] = 0
        packed[3, n:] = 0
        packed[4, :ne] = er
        packed[5, :ne] = e
        packed[4, ne:] = _FOLD_PAD_ROW + np.arange(k - ne)
        packed[5, ne:] = 0
        return packed

    def _fold_hybrid(self, deltas: DeltaArrays):
        return fold_hybrid(deltas, self.config.nodes, self._row_dense_min)

    def _apply_scalar_merges(self, deltas: DeltaArrays) -> None:
        """Deficit-attribution merge of reference-peer deltas (interop).
        Chunks batches past the padded-shape cap — _pad_size clamps at
        MAX_MERGE_ROWS, so a bigger batch would otherwise overflow its
        packed matrix and fail the whole tick."""
        t0 = time.perf_counter_ns()
        for lo in range(0, len(deltas), MAX_MERGE_ROWS):
            chunk = DeltaArrays(*(a[lo : lo + MAX_MERGE_ROWS] for a in deltas))
            n = len(chunk)
            k = _pad_size(n)
            packed = np.zeros((5, k), dtype=np.int64)
            packed[0, :n] = chunk.rows
            packed[1, :n] = chunk.slots
            packed[2, :n] = chunk.added_nt
            packed[3, :n] = chunk.taken_nt
            packed[4, :n] = chunk.elapsed_ns
            with self._state_mu, _annotate("merge_scalar"):
                self.state = _jit_merge_scalar_packed()(
                    self.state, jnp.asarray(packed)
                )
            self._ticks += 1
        self._observe_device_commit("merge_scalar", t0, len(deltas))

    @staticmethod
    def _note_take_coalesce(keys, groups) -> None:
        """Hot-key coalescing receipt for one tick's take pack (shared
        with the mesh fused path): rows dispatched as take-n (nreq > 1),
        with the flight-recorder arg carrying how many tickets rode
        beyond one-per-row."""
        multi = sum(1 for key in keys if len(groups[key]) > 1)
        if multi:
            profiling.COUNTERS.inc("take_rows_coalesced", multi)
            tr = trace_mod.TRACE
            if tr.enabled:
                tr.record(
                    trace_mod.EV_TAKE_COALESCE,
                    0,
                    sum(len(groups[key]) for key in keys) - len(keys),
                )

    def _apply_takes(self, tickets: Sequence[TakeTicket]) -> None:
        keys, groups = self._group_tickets(tickets)
        self._note_take_coalesce(keys, groups)
        k = _pad_size(len(keys), hi=MAX_TAKE_ROWS)
        packed = self._staging.lease((TAKE_PACK_ROWS, k))
        packed[:] = 0  # padding rows must stay nreq=0 no-ops
        for i, key in enumerate(keys):
            ts = groups[key]
            first = ts[0]
            packed[0, i] = first.row
            # Earliest arrival clock for the group: conservative (refills
            # least); exact when callers share an injected clock tick.
            packed[1, i] = min(t.now_ns for t in ts)
            packed[2, i] = first.rate.freq
            packed[3, i] = first.rate.per_ns
            packed[4, i] = first.count * NANO
            packed[5, i] = len(ts)
            packed[6, i] = self.directory.cap_base_nt[first.row]
            packed[7, i] = self.directory.created_ns[first.row]

        t0 = time.perf_counter_ns()
        packed_dev = jax.device_put(packed)  # staged ahead of the lock
        _obs_stage(hist.STAGE_H2D, t0, trace_mod.EV_H2D_PUT, len(keys))
        t0 = time.perf_counter_ns()
        with self._state_mu, _annotate("take_packed"):
            self.state, out = _jit_take_packed(self.node_slot)(
                self.state, packed_dev
            )
        _obs_stage(
            hist.STAGE_DISPATCH, t0, trace_mod.EV_COMMIT_DISPATCH, len(keys)
        )
        self._ticks += 1
        t_dispatch = t0
        n_keys = len(keys)

        def complete() -> None:
            # THE sanctioned completer readback: one batched D2H per
            # take tick, on the completion pipeline by construction.
            res = np.asarray(out)  # patrol-lint: disable=PTD003
            if DEVICE_TIMING:
                # Device-side take duration: dispatch → results readable
                # (the completion-pipeline readback delta, patrol-fleet).
                dur = time.perf_counter_ns() - t_dispatch
                hist.STAGE_DEVICE_TAKE.record(dur)
                hist.kernel_histogram("take_packed").record(dur)
                tr = trace_mod.TRACE
                if tr.enabled:
                    tr.record(trace_mod.EV_DEVICE_READY, dur, n_keys)
            # Device done ⇒ the staged request matrix is consumed on any
            # backend: recycle it.
            self._staging.release(packed)
            have, admitted, own_a, own_t, elapsed, sum_a, sum_t = res
            self._complete_groups(
                keys, groups, have, admitted, own_a, own_t, elapsed, sum_a, sum_t
            )

        self._enqueue_completion(complete, keys, groups)
