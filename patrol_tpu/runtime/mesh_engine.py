"""MeshEngine: the device engine over a multi-device mesh.

Same public surface and host protocol behavior as
:class:`patrol_tpu.runtime.engine.DeviceEngine`, but state lives sharded
over a ``(replicas × shards)`` ``jax.sharding.Mesh``
(:mod:`patrol_tpu.parallel.topology`): bucket rows partition across the
``"b"`` axis, full replicas along ``"r"`` ingest disjoint slices of each
tick's work and converge with a hierarchical tree max-reduce — the
intra-slice analogue of the reference's UDP broadcast (repo.go:123-158),
riding ICI as log2(R) ppermute rounds instead of a flat all-gather
(topology._tree_allreduce_max; Tascade's coalescing-reduction shape).

Each tick fuses merge + take + converge into ONE shard_map'd device call;
the host router places every take in its row's home (replica, shard) block
(single-writer lanes ⇒ exact convergence) and spreads merges round-robin.

Pod-scale serving pipeline (this file's PR): the tick plumbing is the
single-device device-commit pipeline inherited intact —

* the feeder drains up to ``_commit_blocks`` × MAX_MERGE_ROWS deltas per
  tick (no more opting down to one block) and FOLDS the whole drain once
  on host (``DeviceEngine._fold_core``), so cross-block duplicate
  (row, slot) keys coalesce before any routing;
* the routed take/merge matrices fill reusable :class:`StagingPool`
  buffers and ship via ``jax.device_put`` with the mesh sharding BEFORE
  the state lock, so the H2D transfer overlaps the previous tick's
  compute; buffers recycle on the completer once the transfer is ready;
* completions ride the inherited dispatch-ahead pipeline
  (``DISPATCH_AHEAD`` deep), so result readback + ticket fanout overlap
  the next tick's device compute;
* a drain whose densest (replica, shard) block would pad past the warmed
  ``MESH_WARM_MAX`` diagonal splits into sub-dispatches on the ACTUAL
  per-block fill (not the total count): all merge chunks dispatch first,
  then take chunks (the last merge chunk shares a dispatch with the
  first take chunk) — bit-exact versus an unsplit tick, because merges
  are idempotent joins, every take key rides exactly one chunk after
  every merge landed, and take rows are unique per tick.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from patrol_tpu.models.limiter import NANO, LimiterConfig
from patrol_tpu.parallel import topology as topo
from patrol_tpu.runtime.bucket import ClockFn, system_clock
from patrol_tpu.runtime import engine as engine_mod
from patrol_tpu.runtime.engine import (
    MAX_MERGE_ROWS,
    BroadcastFn,
    DeltaArrays,
    DeviceEngine,
    TakeTicket,
    _annotate,
    _jit_merge_packed,
    _jit_merge_scalar_packed,
    _obs_stage,
    _pad_size,
)
from patrol_tpu.utils import histogram as hist
from patrol_tpu.utils import profiling
from patrol_tpu.utils import trace as trace_mod

log = logging.getLogger("patrol.mesh")


# The largest (diagonal) block size warmup() pre-compiles AND the hard cap
# on any runtime dispatch's padded block size. _apply splits a tick whose
# densest (replica, shard) block would exceed this into sequential
# sub-dispatches instead of padding past the warmed set — merges are
# idempotent CRDT joins applied before every take chunk and each take key
# rides exactly one chunk, so the split is bit-exact versus an unsplit
# tick, and no reachable tick shape can JIT a fresh variant mid-serve (a
# multi-second p99 spike on a remote-compile TPU). Scope: the fused
# merge+take+converge step AND (since this PR) the scalar-interop kernel
# — warmup() pre-compiles _jit_merge_scalar_packed's pad diagonal too, so
# a first reference-peer batch no longer compiles lazily mid-serve.
MESH_WARM_MAX = 1 << 12


class _HostSyncStateLock(profiling.ProfiledLock):
    """State mutex for HOST-PLATFORM meshes: materializes the in-flight
    device program before every release. XLA's forced host "devices"
    (``--xla_force_host_platform_device_count``) execute on one shared
    thread pool with no per-device stream FIFO, so two concurrently
    in-flight collective programs can interleave their rendezvous across
    the pool and deadlock — endless ``participant ... may be stuck``
    spins, first hit by the churn gate's incast snapshot gathers racing
    the fused step on an 8-device mesh. Holding every dispatch to
    completion inside the state lock keeps at most ONE collective
    program in flight; real accelerators have proper per-device streams
    and keep the plain lock (async dispatch-ahead intact)."""

    __slots__ = ("_engine",)

    def __init__(self, name: str, engine: "MeshEngine"):
        super().__init__(name)
        self._engine = engine

    def release(self) -> None:
        st = getattr(self._engine, "state", None)
        if st is not None:
            try:
                jax.block_until_ready(st.pn)
            except Exception:  # a poisoned dispatch must still unlock
                pass
        super().release()

    def __exit__(self, *exc) -> None:
        self.release()


class MeshEngine(DeviceEngine):
    # Idle demotion stays off here — DOCUMENTED AND GATED, not silent:
    # the per-row gather/zero pair would run against SHARDED planes,
    # where each demotion's resharding (gather across "b", zero scatter
    # back) costs a cross-device round per window — unmeasured, and the
    # sharded zero_rows would reshard the gathered block through host
    # memory on the tunnel transport. stats()/bench receipts carry
    # ``mesh_demotion: unsupported`` so the Zipf-lifecycle work (ROADMAP
    # item 4) sees the constraint machine-readably instead of finding a
    # silently-disabled flag.
    _demotion_capable = False

    # NOTE: _commit_blocks is INHERITED (PATROL_COMMIT_BLOCKS, default 4)
    # since the pod-scale PR — the fused step's host router folds and
    # splits per block itself, so a multi-block drain coalesces into the
    # fewest dispatches the warmed diagonal allows (previously this class
    # opted down to 1 block per tick and left the device idle between
    # short ticks). The r15 ``auto`` sizing stays OFF here: the fused
    # step's per-block routing economics are unmeasured under a moving
    # drain width, so this class pins the static default.
    _commit_blocks_auto = False
    # Raw-plane device ingest (ops/ingest.py) opts out too: a
    # decode_fold_raw dispatch against the SHARDED planes would reshard
    # the scatter through host memory on the tunnel transport —
    # unmeasured; the delta plane falls back to the python decode path.
    _raw_ingest_capable = False
    # The rx-thread interval fold opts out for the same reason the raw
    # ingest does, plus a liveness one: delta_fold against SHARDED
    # planes is a collective program, and dispatching it from the rx
    # context holds the state mutex across a mesh rendezvous — racing
    # the feeder's own collective step (a deadlock on host-platform
    # device pools, which have no per-device stream FIFO). Decoded
    # intervals route through the queued classify path and merge inside
    # the fused tick instead.
    _interval_fold_capable = False

    def __init__(
        self,
        config: LimiterConfig,
        replicas: int = 1,
        node_slot: int = 0,
        clock: ClockFn = system_clock,
        on_broadcast: Optional[BroadcastFn] = None,
        devices=None,
    ):
        self.mesh = topo.make_mesh(replicas=replicas, devices=devices)
        shards = self.mesh.shape[topo.BUCKET_AXIS]
        if config.buckets % shards:
            raise ValueError(
                f"buckets ({config.buckets}) must divide over {shards} shards"
            )
        super().__init__(config, node_slot=node_slot, clock=clock, on_broadcast=on_broadcast)
        # Host-platform collective safety (_HostSyncStateLock): swap the
        # state mutex BEFORE the first sharded dispatch (place_state
        # below). Nothing touches state concurrently this early — no
        # bucket exists for the feeder/lifecycle threads to reach.
        if next(iter(self.mesh.devices.flat)).platform == "cpu":
            self._state_mu = _HostSyncStateLock("engine.state", self)
        # Host-side mesh tick accounting, read by stats() from API
        # threads while the feeder mutates it — its own lock (leaf-only:
        # never held together with the engine's shared locks), registered
        # in analysis/race.py::GUARDS like every other shared attribute.
        self._mesh_mu = threading.Lock()
        # Serializes resize() calls (admin-driven, rare); never held
        # together with _cond/_state_mu acquisition ordering conflicts —
        # resize takes _resize_mu → _cond → _state_mu, and no other path
        # takes _resize_mu at all.
        self._resize_mu = threading.Lock()
        self._mesh_metrics: Dict[str, int] = {
            "mesh_fused_dispatches": 0,
            "mesh_split_ticks": 0,
            "mesh_sub_dispatches": 0,
            "mesh_routed_takes": 0,
            "mesh_routed_deltas": 0,
            "mesh_folded_dupes": 0,
        }
        try:
            self.plan = topo.plan_for(self.mesh, config)
            self._step = topo.build_cluster_step_packed(self.mesh, node_slot)
            self._mat_sharding = topo.batch_sharding(self.mesh)
            with self._state_mu:
                self.state = topo.place_state(self.state, self.mesh)
        except BaseException:
            # The base engine is live (threads + native directory handle);
            # a half-built MeshEngine must release them or every later
            # engine in the process inherits a shrunken handle registry.
            self.stop()
            raise

    # -- elasticity ---------------------------------------------------------

    def resize(
        self,
        replicas: int = 1,
        devices=None,
        timeout: float = 30.0,
    ) -> dict:
        """Live mesh resharding (patrol-membership, ROADMAP 3c): grow or
        shrink the device mesh WITHOUT restarting the engine or losing a
        single queued take.

        Protocol — quiesce, swap, resume:

        1. **Pause** the feeder between ticks (``_tick_paused`` under
           ``_cond``): work queues keep absorbing submissions — /take
           callers just see one tick's extra latency — but nothing new
           dispatches.
        2. **Wait** for the in-flight tick (``_busy``) to clear. Pending
           completions need no wait: their device results are already
           materialized arrays, indifferent to where state lives next.
        3. **Swap** under ``_state_mu``: build the new mesh's plan, fused
           step, and matrix sharding, then ``device_put`` the state under
           the new :class:`~jax.sharding.NamedSharding` — a straight
           cross-sharding transfer, no recompile dance and no host
           round-trip of the planes. State is a join-semilattice, and the
           transfer is a bit-exact relayout: per-bucket digests before
           and after are identical (the churn bench gates on this).
        4. **Resume** the feeder; the next tick routes against the new
           plan and JITs the new step's first shapes (call
           :meth:`warmup` after, if p99 matters more than the pause).

        Validates ``buckets %% shards == 0`` BEFORE pausing, so an
        invalid target never stalls serving. Returns a receipt dict.
        """
        new_mesh = topo.make_mesh(replicas=replicas, devices=devices)
        shards = new_mesh.shape[topo.BUCKET_AXIS]
        if self.config.buckets % shards:
            raise ValueError(
                f"buckets ({self.config.buckets}) must divide over "
                f"{shards} shards"
            )
        with self._resize_mu:
            old_shape = (self.plan.replicas, self.plan.shards)
            with self._cond:
                self._tick_paused = True
            try:
                # In-flight tick drains; _busy flips under _cond, and with
                # the pause already visible the feeder cannot start another.
                deadline = time.monotonic() + timeout
                while True:
                    with self._cond:
                        if not self._busy:
                            break
                    if time.monotonic() >= deadline:
                        raise TimeoutError(
                            "resize quiesce timed out waiting for the "
                            "in-flight tick"
                        )
                    time.sleep(0.0005)
                plan = topo.plan_for(new_mesh, self.config)
                step = topo.build_cluster_step_packed(new_mesh, self.node_slot)
                sharding = topo.batch_sharding(new_mesh)
                with self._state_mu:
                    self.state = topo.place_state(self.state, new_mesh)
                    self._state_gen += 1  # scrape-mirror epoch: new placement
                    self.mesh = new_mesh
                    self.plan = plan
                    self._step = step
                    self._mat_sharding = sharding
            finally:
                with self._cond:
                    self._tick_paused = False
                    self._cond.notify_all()
        from patrol_tpu.utils import profiling

        profiling.COUNTERS.inc("mesh_resizes")
        receipt = {
            "from": {"replicas": old_shape[0], "shards": old_shape[1]},
            "to": {"replicas": plan.replicas, "shards": plan.shards},
            "devices": len(new_mesh.devices.flatten()),
        }
        log.info("mesh resized", extra=receipt)
        return receipt

    # -- tick ---------------------------------------------------------------

    def _apply(
        self, deltas: Optional[DeltaArrays], tickets: Sequence[TakeTicket]
    ) -> None:
        # Scalar-semantics (reference-peer) deltas can't ride the fused lane
        # merge: they need deficit attribution against the whole row. Rare
        # interop path — peel them into the base kernel (GSPMD shards it),
        # applied AFTER the fused step: lane merges land first so a scalar
        # echo's aggregate (which already includes peer lanes broadcast
        # before it) isn't double-attributed to the sender's lane.
        scalar_subset = None
        if deltas is not None and deltas.scalar.any():
            sc = deltas.scalar
            scalar_subset = DeltaArrays(*(a[sc] for a in deltas))
            deltas = DeltaArrays(*(a[~sc] for a in deltas)) if not sc.all() else None

        keys, groups = self._group_tickets(tickets) if tickets else ([], {})
        if keys:
            self._note_take_coalesce(keys, groups)
        try:
            self._apply_fused(deltas, keys, groups)
        finally:
            if scalar_subset is not None:
                self._apply_scalar_merges(scalar_subset)

    def _device_marker(self):
        # Never slice the sharded state into a fresh marker program: any
        # caller without an explicit marker would dispatch it OUTSIDE the
        # state mutex and interleave its collective rendezvous with a
        # concurrently-locked gather (see _observe_device_commit). Mesh
        # dispatch sites pass their own program output as the marker.
        return None

    def _apply_fused(
        self,
        deltas: Optional[DeltaArrays],
        keys: List,
        groups: Dict,
    ) -> None:
        """The fused mesh tick: fold the whole (multi-block) drain once,
        route per (replica, shard) block, dispatch the fewest
        ≤MESH_WARM_MAX-padded fused steps that cover it — merge chunks
        strictly before take chunks (sharing the boundary dispatch), so
        the result is bit-exact versus one unsplit dispatch."""
        plan = self.plan
        W = MESH_WARM_MAX

        # -- fold: the coalesced-commit analogue (device-commit pipeline).
        # Cross-block duplicate (row, slot) keys max-join on host; the
        # per-row elapsed fold rides the row's FIRST pair (zeros
        # elsewhere join as no-ops).
        folded = None
        blk_m = msub = None
        m = 0
        raw_n = len(deltas) if deltas is not None else 0
        if raw_n:
            t0 = time.perf_counter_ns()
            ur, us, ua, ut, er, e = DeviceEngine._fold_core(deltas)
            first = np.flatnonzero(
                np.concatenate(([True], ur[1:] != ur[:-1]))
            )
            el = np.zeros(len(ur), np.int64)
            el[first] = e
            folded = (ur, us, ua, ut, el)
            _obs_stage(hist.STAGE_FOLD, t0, trace_mod.EV_FOLD, raw_n)
            # Block assignment + within-block rank → sub-dispatch index.
            blk_m = topo.delta_block_assignment(plan, ur)
            counts = np.bincount(blk_m, minlength=plan.blocks)
            order = np.argsort(blk_m, kind="stable")
            run_start = np.concatenate(([0], np.cumsum(counts)))[blk_m[order]]
            rank = np.empty(len(ur), np.int64)
            rank[order] = np.arange(len(ur), dtype=np.int64) - run_start
            msub = rank // W
            m = int(msub.max()) + 1

        # -- take placement: per-block arrival rank → (chunk, slot).
        key_sub: List[int] = []
        fill_t = [0] * plan.blocks
        for key in keys:
            replica, shard, _local = plan.locate(key[0])
            blk = plan.block_index(replica, shard)
            key_sub.append(fill_t[blk] // W)
            fill_t[blk] += 1
        t = (max(key_sub) + 1) if keys else 0

        n_dispatch = m + t - (1 if m and t else 0)
        if n_dispatch == 0:
            return
        if n_dispatch > 1:
            log.debug(
                "mesh tick split into %d sub-dispatches (%d merge chunks, "
                "%d take chunks)",
                n_dispatch, m, t,
            )

        take_base = (m - 1) if m else 0  # dispatch index of take chunk 0
        failed = False
        for d in range(n_dispatch):
            mi = d if d < m else None
            ti = d - take_base if (t and d >= take_base) else None
            keys_d = (
                [k for j, k in enumerate(keys) if key_sub[j] == ti]
                if ti is not None
                else []
            )
            try:
                self._dispatch_fused(folded, blk_m, msub, mi, keys_d, groups)
            except Exception:
                # Partial-failure discipline: earlier sub-dispatches
                # already admitted takes and debited tokens on device —
                # their queued completions must stand. Fail ONLY the
                # tickets of this and later chunks, and swallow
                # (re-raising would make the tick loop's catch-all race
                # those live completions with blanket failures).
                log.exception(
                    "mesh sub-dispatch %d/%d failed; failing undispatched "
                    "takes only",
                    d + 1,
                    n_dispatch,
                )
                later = [
                    tk
                    for j, key in enumerate(keys)
                    if ti is None or key_sub[j] >= ti
                    for tk in groups[key]
                ]
                self._fail_tickets(later)
                failed = True
                break

        n_pairs = len(folded[0]) if folded is not None else 0
        with self._mesh_mu:
            mm = self._mesh_metrics
            mm["mesh_fused_dispatches"] += n_dispatch
            if n_dispatch > 1 and not failed:
                mm["mesh_split_ticks"] += 1
                mm["mesh_sub_dispatches"] += n_dispatch
            mm["mesh_routed_takes"] += len(keys)
            mm["mesh_routed_deltas"] += n_pairs
            mm["mesh_folded_dupes"] += raw_n - n_pairs

    def _dispatch_fused(
        self,
        folded,
        blk_m: Optional[np.ndarray],
        msub: Optional[np.ndarray],
        mi: Optional[int],
        keys_d: List,
        groups: Dict,
    ) -> None:
        """One fused device dispatch: the selected merge chunk + take
        chunk, square-padded to the warmed diagonal, staged through the
        pool and shipped sharded before the state lock."""
        plan = self.plan

        deltas_d = None
        blk_d = None
        max_fill_m = 0
        if mi is not None:
            sel = msub == mi
            deltas_d = tuple(a[sel] for a in folded)
            blk_d = blk_m[sel]
            max_fill_m = int(
                np.bincount(blk_d, minlength=plan.blocks).max(initial=0)
            )

        takes_d = []
        max_fill_t = 0
        if keys_d:
            fill = [0] * plan.blocks
            for key in keys_d:
                ts = groups[key]
                first = ts[0]
                replica, shard, _local = plan.locate(first.row)
                blk = plan.block_index(replica, shard)
                fill[blk] += 1
                takes_d.append(
                    (
                        first.row,
                        min(tk.now_ns for tk in ts),
                        first.rate.freq,
                        first.rate.per_ns,
                        first.count * NANO,
                        len(ts),
                        int(self.directory.cap_base_nt[first.row]),
                        int(self.directory.created_ns[first.row]),
                    )
                )
            max_fill_t = max(fill)

        # Square the paddings: only DIAGONAL (k, k) shapes ever compile,
        # so warmup's size sweep covers every runtime dispatch — an
        # off-diagonal pair would JIT a fresh variant mid-serve (a
        # multi-second p99 spike on a remote-compile TPU). Padded entries
        # are no-ops, so the cost is a slightly wider batch.
        k = _pad_size(max(max_fill_m, max_fill_t, 1), lo=8, hi=MESH_WARM_MAX)

        take_buf = self._staging.lease((topo.TAKE_MAT_ROWS, plan.blocks * k))
        merge_buf = self._staging.lease((topo.MERGE_MAT_ROWS, plan.blocks * k))
        _tm, _mm, placed = topo.route_packed(
            plan, takes_d, deltas_d, k, k,
            take_out=take_buf, merge_out=merge_buf, delta_blocks=blk_d,
        )
        # Stage both matrices on device (sharded) BEFORE the state lock:
        # the H2D transfer overlaps the previous dispatch's compute, and
        # device_put copies — the staging buffers recycle once the
        # transfer is ready, on the completer.
        t0 = time.perf_counter_ns()
        take_dev = jax.device_put(take_buf, self._mat_sharding)
        merge_dev = jax.device_put(merge_buf, self._mat_sharding)
        _obs_stage(
            hist.STAGE_H2D, t0, trace_mod.EV_H2D_PUT,
            len(takes_d) + (len(deltas_d[0]) if deltas_d else 0),
        )
        t0 = time.perf_counter_ns()
        with self._state_mu, _annotate("mesh_step"):
            self.state, out = self._step(self.state, take_dev, merge_dev)
        _obs_stage(
            hist.STAGE_DISPATCH, t0, trace_mod.EV_COMMIT_DISPATCH,
            len(takes_d),
        )
        self._ticks += 1
        t_dispatch = t0
        self._release_when_shipped(take_dev, take_buf)
        self._release_when_shipped(merge_dev, merge_buf)

        if not keys_d:
            # Merge-only dispatch: device timing rides the completion
            # pipeline. The marker is the step's OWN fresh output — never
            # the default _device_marker slice, which would launch a new
            # collective over the sharded state outside the state mutex
            # and interleave with a concurrently-locked gather.
            self._observe_device_commit(
                "mesh_step", t_dispatch,
                len(deltas_d[0]) if deltas_d else 0,
                marker=out,
            )
            return

        groups_d = {key: groups[key] for key in keys_d}
        n_keys = len(keys_d)

        def complete() -> None:
            # THE sanctioned mesh completer readback: one batched D2H
            # per fused step, on the completion pipeline by construction.
            res = np.asarray(out)  # patrol-lint: disable=PTD003
            if engine_mod.DEVICE_TIMING:
                dur = time.perf_counter_ns() - t_dispatch
                hist.STAGE_DEVICE_TAKE.record(dur)
                hist.kernel_histogram("mesh_step").record(dur)
                tr = trace_mod.TRACE
                if tr.enabled:
                    tr.record(trace_mod.EV_DEVICE_READY, dur, n_keys)
            at = [blk * k + slot for blk, slot in placed]
            self._complete_groups(
                keys_d,
                groups_d,
                res[0][at],
                res[1][at],
                res[2][at],
                res[3][at],
                res[4][at],
                res[5][at],
                res[6][at],
            )

        self._enqueue_completion(complete, keys_d, groups_d)

    def warmup(self) -> None:
        """Pre-compile the fused step at each padded block size — the full
        diagonal through MESH_WARM_MAX, which _apply never exceeds (denser
        ticks split into sub-dispatches) — plus the promotion-drain merge
        diagonal, the SCALAR-INTEROP diagonal (the deficit-attribution
        kernel previously compiled lazily on its first reference-peer
        batch per pad size: a multi-second p99 spike on a remote-compile
        TPU), and the introspection gathers. After this, no reachable
        serve-path shape compiles mid-serve."""
        blocks = self.plan.blocks
        size = 8
        while size <= MESH_WARM_MAX:
            tb = np.zeros((topo.TAKE_MAT_ROWS, blocks * size), np.int64)
            mb = np.zeros((topo.MERGE_MAT_ROWS, blocks * size), np.int64)
            take_dev = jax.device_put(tb, self._mat_sharding)
            merge_dev = jax.device_put(mb, self._mat_sharding)
            with self._state_mu:
                self.state, _ = self._step(self.state, take_dev, merge_dev)
            size <<= 1
        # The host-fast-path promotion drain (engine._drain_promotions)
        # batches ALL pending rows' lanes into _jit_merge_packed chunks of
        # up to MAX_MERGE_ROWS entries; a mass promotion (rx storm,
        # checkpoint-restore flush_hosted) can reach any power-of-two pad
        # size, and a first GSPMD compile mid-serve is the multi-second
        # stall this warmup exists to prevent — warm the full diagonal.
        size = 8
        hi = _pad_size(MAX_MERGE_ROWS)
        while size <= hi:
            with self._state_mu:
                self.state = _jit_merge_packed()(
                    self.state, jnp.zeros((5, size), jnp.int64)
                )
            size <<= 1
        # Scalar-interop (reference-peer) kernel: _apply_scalar_merges
        # chunks at MAX_MERGE_ROWS and pads each chunk — warm the same
        # diagonal with all-zero batches (row 0 / slot 0 / zero values:
        # deficit attribution of zero against non-negative lanes is a
        # no-op scatter-max, so warmed state is untouched).
        size = 8
        while size <= hi:
            with self._state_mu:
                self.state = _jit_merge_scalar_packed()(
                    self.state, jnp.zeros((5, size), jnp.int64)
                )
            size <<= 1
        size = 1
        while size <= 1024:
            self.read_rows(np.zeros(size, np.int32))
            size <<= 1
        jax.block_until_ready(self.state.pn)

    def stats(self) -> Dict[str, object]:
        with self._mesh_mu:
            out: Dict[str, object] = dict(self._mesh_metrics)
        out.update(
            mesh_replicas=self.plan.replicas,
            mesh_shards=self.plan.shards,
            mesh_commit_blocks=self._commit_blocks,
            mesh_warm_max=MESH_WARM_MAX,
            # Machine-readable residency constraint (see _demotion_capable
            # note): consumed by bench --mesh receipts and the ROADMAP
            # item-4 lifecycle work.
            mesh_demotion="unsupported",
            # Bucket lifecycle on the mesh: sharded-plane idle DEMOTION
            # stays unsupported (above), but the lifecycle GC path is
            # fully inherited — the IsZero probe and zero_rows both run
            # as GSPMD programs over the sharded planes, so the mesh
            # sheds cold state via host-directory GC like the
            # single-device engine. Measured cost rides the shared
            # ``gc_sweep_ns`` histogram; reclaim counts ride
            # ``engine_gc_reclaimed`` / ``gc_buckets_reclaimed``.
            mesh_gc="host-directory",
            mesh_converge_kernel=(
                "tree"
                if self.plan.replicas > 1
                and self.plan.replicas & (self.plan.replicas - 1) == 0
                else "flat"
            ),
        )
        return out
