"""MeshEngine: the device engine over a multi-device mesh.

Same public surface and host protocol behavior as
:class:`patrol_tpu.runtime.engine.DeviceEngine`, but state lives sharded
over a ``(replicas × shards)`` ``jax.sharding.Mesh``
(:mod:`patrol_tpu.parallel.topology`): bucket rows partition across the
``"b"`` axis, full replicas along ``"r"`` ingest disjoint slices of each
tick's work and converge with a max all-reduce — the intra-slice analogue of
the reference's UDP broadcast (repo.go:123-158), riding ICI.

Each tick fuses merge + take + converge into ONE shard_map'd device call;
the host router places every take in its row's home (replica, shard) block
(single-writer lanes ⇒ exact convergence) and spreads merges round-robin.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from patrol_tpu.models.limiter import NANO, LimiterConfig
from patrol_tpu.parallel import topology as topo
from patrol_tpu.runtime.bucket import ClockFn, system_clock
from patrol_tpu.runtime import engine as engine_mod
from patrol_tpu.runtime.engine import (
    BroadcastFn,
    DeltaArrays,
    DeviceEngine,
    TakeTicket,
    _jit_merge_packed,
    _pad_size,
)
from patrol_tpu.utils import histogram as hist

log = logging.getLogger("patrol.mesh")


# The largest (diagonal) block size warmup() pre-compiles AND the hard cap
# on any runtime tick's padded block size. _apply splits a bigger tick into
# sequential ≤MESH_WARM_MAX sub-ticks instead of padding past the warmed
# set — merges are idempotent CRDT joins and each take key rides exactly
# one sub-tick, so the split is semantically just several smaller ticks,
# and no reachable FUSED-step tick shape can JIT a fresh variant mid-serve
# (a multi-second p99 spike on a remote-compile TPU). Scope: this covers
# the fused merge+take+converge step only — the rare scalar-interop kernel
# (_jit_merge_scalar_packed) still compiles lazily on its first
# reference-peer batch per pad size.
MESH_WARM_MAX = 1 << 12


class MeshEngine(DeviceEngine):
    # Idle demotion stays off here: the per-row gather/zero pair runs
    # against SHARDED planes, whose resharding cost/shape is unmeasured —
    # promoted rows remain device-resident as in r4.
    _demotion_capable = False

    # The coalesced commit ring is a single-device kernel; the fused
    # shard_map step routes per block itself, so one tick drains exactly
    # one block's budget here (the r5 behavior).
    _commit_blocks = 1

    def __init__(
        self,
        config: LimiterConfig,
        replicas: int = 1,
        node_slot: int = 0,
        clock: ClockFn = system_clock,
        on_broadcast: Optional[BroadcastFn] = None,
        devices=None,
    ):
        self.mesh = topo.make_mesh(replicas=replicas, devices=devices)
        shards = self.mesh.shape[topo.BUCKET_AXIS]
        if config.buckets % shards:
            raise ValueError(
                f"buckets ({config.buckets}) must divide over {shards} shards"
            )
        super().__init__(config, node_slot=node_slot, clock=clock, on_broadcast=on_broadcast)
        try:
            self.plan = topo.plan_for(self.mesh, config)
            self._step = topo.build_cluster_step(self.mesh, node_slot)
            with self._state_mu:
                self.state = topo.place_state(self.state, self.mesh)
        except BaseException:
            # The base engine is live (threads + native directory handle);
            # a half-built MeshEngine must release them or every later
            # engine in the process inherits a shrunken handle registry.
            self.stop()
            raise

    # -- tick ---------------------------------------------------------------

    def _apply(
        self, deltas: Optional[DeltaArrays], tickets: Sequence[TakeTicket]
    ) -> None:
        # Scalar-semantics (reference-peer) deltas can't ride the fused lane
        # merge: they need deficit attribution against the whole row. Rare
        # interop path — peel them into the base kernel (GSPMD shards it),
        # applied AFTER the fused step: lane merges land first so a scalar
        # echo's aggregate (which already includes peer lanes broadcast
        # before it) isn't double-attributed to the sender's lane.
        scalar_subset = None
        if deltas is not None and deltas.scalar.any():
            sc = deltas.scalar
            scalar_subset = DeltaArrays(*(a[sc] for a in deltas))
            deltas = DeltaArrays(*(a[~sc] for a in deltas)) if not sc.all() else None

        keys, groups = self._group_tickets(tickets) if tickets else ([], {})

        # Split a tick that could pad past the warmed shape set into
        # sequential sub-ticks: a chunk of ≤MESH_WARM_MAX total keys or
        # deltas can't fill any (replica, shard) block past MESH_WARM_MAX.
        W = MESH_WARM_MAX
        nd = len(deltas) if deltas is not None else 0
        n_sub = max(
            -(-len(keys) // W) if keys else 1, -(-nd // W) if nd else 1
        )
        if n_sub > 1:
            for i in range(n_sub):
                kchunk = keys[i * W : (i + 1) * W]
                dchunk = (
                    DeltaArrays(*(a[i * W : (i + 1) * W] for a in deltas))
                    if nd > i * W
                    else None
                )
                try:
                    self._apply_block(
                        dchunk,
                        kchunk,
                        {k: groups[k] for k in kchunk},
                    )
                except Exception:
                    # Partial-failure discipline: earlier sub-ticks already
                    # admitted takes and debited tokens on device — their
                    # queued completions must stand. Fail ONLY the tickets
                    # of this and later sub-ticks, and swallow (re-raising
                    # would make the tick loop's catch-all race those live
                    # completions with blanket failures). Scalar deltas are
                    # independent of the fused step; break to apply them.
                    log.exception(
                        "mesh sub-tick %d/%d failed; failing undispatched "
                        "takes only",
                        i + 1,
                        n_sub,
                    )
                    self._fail_tickets(
                        [t for k in keys[i * W :] for t in groups[k]]
                    )
                    break
        else:
            self._apply_block(deltas if nd else None, keys, groups)
        if scalar_subset is not None:
            self._apply_scalar_merges(scalar_subset)

    def _apply_block(
        self,
        deltas: Optional[DeltaArrays],
        keys: List,
        groups: Dict,
    ) -> None:
        """One fused sub-tick whose per-block fill is ≤ MESH_WARM_MAX."""
        plan = self.plan
        B = plan.blocks

        # Per-block occupancy → padded block capacity. Take keys are
        # pre-coalesced (few), deltas are bulk → vectorized bincount.
        fill_t = [0] * B
        placed: List[Tuple[int, int]] = []  # (block, slot-in-block) per key
        for key in keys:
            row = key[0]
            replica, shard, _local = plan.locate(row)
            blk = plan.block_index(replica, shard)
            placed.append((blk, fill_t[blk]))
            fill_t[blk] += 1
        k_take = _pad_size(max(fill_t) if fill_t else 1, lo=8, hi=MESH_WARM_MAX)

        if deltas is not None and len(deltas):
            d_rows = np.asarray(deltas.rows, dtype=np.int64)
            blk = (
                np.arange(len(d_rows), dtype=np.int64) % plan.replicas
            ) * plan.shards + d_rows // plan.rows_per_shard
            max_fill = int(np.bincount(blk, minlength=B).max(initial=0))
        else:
            max_fill = 0
        k_merge = _pad_size(max(max_fill, 1), lo=8, hi=MESH_WARM_MAX)
        # Square the paddings: only DIAGONAL (k, k) shapes ever compile, so
        # warmup's size sweep covers every runtime tick — an off-diagonal
        # (k_take, k_merge) pair would JIT a fresh variant mid-serve (a
        # multi-second p99 spike on a remote-compile TPU). Padded rows are
        # no-ops, so the cost is a slightly wider batch, not extra steps.
        k_take = k_merge = max(k_take, k_merge)

        takes = []
        for key in keys:
            ts = groups[key]
            first = ts[0]
            takes.append(
                (
                    first.row,
                    min(t.now_ns for t in ts),
                    first.rate.freq,
                    first.rate.per_ns,
                    first.count * NANO,
                    len(ts),
                    int(self.directory.cap_base_nt[first.row]),
                    int(self.directory.created_ns[first.row]),
                )
            )
        delta_arrays = (
            (
                np.asarray(deltas.rows, np.int64),
                np.asarray(deltas.slots, np.int64),
                np.asarray(deltas.added_nt, np.int64),
                np.asarray(deltas.taken_nt, np.int64),
                np.asarray(deltas.elapsed_ns, np.int64),
            )
            if deltas is not None and len(deltas)
            else None
        )

        req, mb = topo.route_requests(plan, takes, delta_arrays, k_take, k_merge)
        t_dispatch = time.perf_counter_ns()
        with self._state_mu:
            self.state, res = self._step(self.state, mb, req)
        self._ticks += 1

        if not keys:
            jax.block_until_ready(self.state.pn)
            if engine_mod.DEVICE_TIMING:
                # Fused mesh step (merge-only tick): dispatch→ready delta
                # (patrol-fleet device-dispatch timing).
                dur = time.perf_counter_ns() - t_dispatch
                hist.STAGE_DEVICE_COMMIT.record(dur)
                hist.kernel_histogram("mesh_step").record(dur)
            return

        def complete() -> None:
            have_all = np.asarray(res.have_nt)
            adm_all = np.asarray(res.admitted)
            own_a_all = np.asarray(res.own_added_nt)
            own_t_all = np.asarray(res.own_taken_nt)
            el_all = np.asarray(res.elapsed_ns)
            sum_a_all = np.asarray(res.sum_added_nt)
            sum_t_all = np.asarray(res.sum_taken_nt)

            at = [blk * k_take + slot for blk, slot in placed]
            self._complete_groups(
                keys,
                groups,
                have_all[at],
                adm_all[at],
                own_a_all[at],
                own_t_all[at],
                el_all[at],
                sum_a_all[at],
                sum_t_all[at],
            )

        self._enqueue_completion(complete, keys, groups)

    def warmup(self) -> None:
        """Pre-compile the fused step at each padded block size — the full
        diagonal through MESH_WARM_MAX, which _apply never exceeds (bigger
        ticks split into sub-ticks), so the fused serve path never
        compiles mid-serve (scalar-interop batches still compile lazily;
        see MESH_WARM_MAX note)."""
        size = 8
        while size <= MESH_WARM_MAX:
            req, mb = topo.route_requests(self.plan, [], [], size, size)
            with self._state_mu:
                self.state, _ = self._step(self.state, mb, req)
            size <<= 1
        # The host-fast-path promotion drain (engine._drain_promotions)
        # batches ALL pending rows' lanes into _jit_merge_packed chunks of
        # up to MAX_MERGE_ROWS entries; a mass promotion (rx storm,
        # checkpoint-restore flush_hosted) can reach any power-of-two pad
        # size, and a first GSPMD compile mid-serve is the multi-second
        # stall this warmup exists to prevent — warm the full diagonal.
        import jax.numpy as jnp

        from patrol_tpu.runtime.engine import MAX_MERGE_ROWS

        size = 8
        hi = _pad_size(MAX_MERGE_ROWS)
        while size <= hi:
            with self._state_mu:
                self.state = _jit_merge_packed()(
                    self.state, jnp.zeros((5, size), jnp.int64)
                )
            size <<= 1
        size = 1
        while size <= 1024:
            self.read_rows(np.zeros(size, np.int32))
            size <<= 1
        jax.block_until_ready(self.state.pn)

    def stats(self) -> Dict[str, int]:
        return {
            "mesh_replicas": self.plan.replicas,
            "mesh_shards": self.plan.shards,
        }
